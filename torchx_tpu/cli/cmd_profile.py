"""``tpx profile`` — render a run's step-time phase attribution.

Reads the ``profile.jsonl`` journals the step profiler
(:mod:`torchx_tpu.obs.profile`) appends under the obs session dirs
(``$TPX_OBS_DIR`` or ``~/.torchx_tpu/obs``) — no scheduler round-trips,
so it works long after the job is gone::

    tpx profile                      # newest session with a profile
    tpx profile tpx_ab12cd34         # a specific session dir
    tpx profile path/to/profile.jsonl --json
    tpx profile --diff run_a run_b   # before/after phase comparison

The default view is the phase timeline (per-phase seconds/fractions with
bars) plus the roofline/MFU and collective-overlap lines; ``--json``
emits the stable v1 summary schema; ``--diff`` compares two sessions
per-phase (tolerating disjoint phase sets — absent phases read as zero).
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Optional

from torchx_tpu.cli.cmd_base import SubCommand


def _resolve(target: Optional[str], obs_dir: Optional[str]) -> str:
    """Resolve a CLI target to a profile-journal path.

    ``None`` -> the newest session dir under the obs root that contains a
    ``profile.jsonl``; an existing file -> itself; an existing dir -> its
    journal; anything else -> ``<obs root>/<target>/profile.jsonl``.
    Exits with a diagnostic when nothing resolves.
    """
    from torchx_tpu.obs import sinks
    from torchx_tpu.obs.profile import PROFILE_FILE

    root = obs_dir or sinks.obs_root()
    if target is None:
        candidates: list[tuple[float, str]] = []
        try:
            for name in os.listdir(root):
                path = os.path.join(root, name, PROFILE_FILE)
                if os.path.isfile(path):
                    candidates.append((os.path.getmtime(path), path))
        except OSError:
            pass
        if not candidates:
            print(f"no profiles recorded under {root}", file=sys.stderr)
            sys.exit(1)
        return max(candidates)[1]
    if os.path.isfile(target):
        return target
    if os.path.isdir(target):
        path = os.path.join(target, PROFILE_FILE)
    else:
        path = os.path.join(root, target, PROFILE_FILE)
    if not os.path.isfile(path):
        print(f"no profile found for: {target} ({path})", file=sys.stderr)
        sys.exit(1)
    return path


def _load_summary(target: Optional[str], obs_dir: Optional[str]) -> dict:
    from torchx_tpu.obs import profile

    records = profile.load_profile(_resolve(target, obs_dir))
    return profile.summarize(records)


class CmdProfile(SubCommand):
    """Render step-profile journals (see module docstring)."""

    def add_arguments(self, subparser: argparse.ArgumentParser) -> None:
        subparser.add_argument(
            "session",
            nargs="?",
            default=None,
            help="session dir name, session path, or profile.jsonl path"
            " (default: the newest profiled session)",
        )
        subparser.add_argument(
            "--json",
            dest="json_out",
            action="store_true",
            help="emit the stable v1 summary schema instead of text",
        )
        subparser.add_argument(
            "--diff",
            nargs=2,
            metavar=("A", "B"),
            default=None,
            help="compare two sessions/journals per-phase (B - A)",
        )
        subparser.add_argument(
            "--obs-dir",
            default=None,
            help="obs root to search (default: $TPX_OBS_DIR or"
            " ~/.torchx_tpu/obs)",
        )

    def run(self, args: argparse.Namespace) -> None:
        import json

        from torchx_tpu.obs import profile

        if args.diff is not None:
            a = _load_summary(args.diff[0], args.obs_dir)
            b = _load_summary(args.diff[1], args.obs_dir)
            d = profile.diff_summaries(a, b)
            if args.json_out:
                print(json.dumps(d, indent=2, sort_keys=True))
            else:
                print(profile.render_diff(d))
            return
        summary = _load_summary(args.session, args.obs_dir)
        if summary.get("steps", 0) == 0:
            print("profile journal has no step records", file=sys.stderr)
            sys.exit(1)
        if args.json_out:
            print(json.dumps(summary, indent=2, sort_keys=True))
        else:
            print(profile.render_summary(summary))
