"""compute_mesh_size — the canonical end-to-end probe app.

The TPU analog of the reference's ``compute_world_size`` example
(torchx/examples/apps/compute_world_size/main.py:10-28): a single psum over
every device in the gang validates specs → runner → scheduler → rendezvous
→ jax.distributed init → global collective, with zero cloud dependencies
(runs on simulated CPU devices under the local scheduler).

Run via the launcher:

    tpx run -s local dist.spmd -j 1x4 --script torchx_tpu/examples/compute_mesh_size.py
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp


def compute_mesh_size() -> int:
    n_global = jax.device_count()
    n_local = jax.local_device_count()
    # one psum across every device in the (possibly multi-process) mesh
    ones = jnp.ones((n_local,))
    total = jax.pmap(lambda x: jax.lax.psum(x, "i"), axis_name="i")(ones)
    mesh_size = int(total[0])
    print(
        f"process={jax.process_index()}/{jax.process_count()}"
        f" local_devices={n_local} global_devices={n_global}"
        f" computed_mesh_size={mesh_size}",
        flush=True,
    )
    assert mesh_size == n_global, (mesh_size, n_global)
    return mesh_size


def maybe_inject_fault() -> None:
    """Fault-injection hook (reference analog: compute_world_size
    main.py:38-40): ``TPX_EXAMPLE_THROWS=1`` always throws;
    ``TPX_EXAMPLE_THROWS=once:/path/marker`` throws only on the first
    attempt (creates the marker), which lets retry/elastic-restart e2e
    tests prove a gang recovers. ``TPX_EXAMPLE_THROWS_REPLICA=N`` scopes
    the fault to one replica of the gang."""
    from torchx_tpu.settings import (
        ENV_TPX_EXAMPLE_THROWS,
        ENV_TPX_EXAMPLE_THROWS_REPLICA,
        ENV_TPX_REPLICA_ID,
    )

    spec = os.environ.get(ENV_TPX_EXAMPLE_THROWS)
    if not spec:
        return
    want = os.environ.get(ENV_TPX_EXAMPLE_THROWS_REPLICA)
    if want is not None and os.environ.get(ENV_TPX_REPLICA_ID, "0") != want:
        return
    if spec.startswith("once:"):
        marker = spec[len("once:"):]
        if os.path.exists(marker):
            return
        with open(marker, "w"):
            pass
    raise RuntimeError(f"injected failure (TPX_EXAMPLE_THROWS={spec})")


def main() -> None:
    maybe_inject_fault()
    size = compute_mesh_size()
    print(f"mesh size: {size}", flush=True)


if __name__ == "__main__":
    main()
