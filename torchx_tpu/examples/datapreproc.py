"""Data preprocessing app: text files -> packed token memmap.

Reference analog: the datapreproc example (torchx/examples/apps/
datapreproc) — a runnable data-prep stage for pipelines (see
pipeline_data_train_eval.py). Tokenizes input text (byte-level by default;
plugs into a HF tokenizer when --tokenizer is given) and writes one packed
uint32 binary the trainer memory-maps.

    tpx run -s local utils.python -m torchx_tpu.examples.datapreproc -- \
        --input /data/corpus/*.txt --output /data/tokens.bin
"""

from __future__ import annotations

import argparse
import glob
import sys

import numpy as np


def tokenize_bytes(text: str) -> list[int]:
    """Byte-level tokenization (vocab 256 + BOS=256): zero-dependency
    default so the pipeline runs anywhere."""
    return [256] + list(text.encode("utf-8"))


def main(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--input", required=True, nargs="+", help="text file globs")
    parser.add_argument("--output", required=True, help="output .bin (uint32)")
    parser.add_argument(
        "--tokenizer",
        default=None,
        help="HF tokenizer name (default: byte-level)",
    )
    args = parser.parse_args(argv)

    tokenizer = None
    if args.tokenizer:
        from transformers import AutoTokenizer

        tokenizer = AutoTokenizer.from_pretrained(args.tokenizer)

    paths = sorted(p for pattern in args.input for p in glob.glob(pattern))
    if not paths:
        print(f"no input files match {args.input}", file=sys.stderr)
        sys.exit(1)

    # stream file-by-file: memory stays bounded by the largest single file,
    # not the corpus (the output format exists for corpora bigger than RAM)
    total = 0
    with open(args.output, "wb") as out:
        for path in paths:
            with open(path, errors="replace") as f:
                text = f.read()
            if tokenizer is not None:
                arr = np.asarray(tokenizer.encode(text), dtype=np.uint32)
            else:
                arr = np.concatenate(
                    [
                        np.asarray([256], dtype=np.uint32),  # BOS per document
                        np.frombuffer(text.encode("utf-8"), dtype=np.uint8).astype(
                            np.uint32
                        ),
                    ]
                )
            arr.tofile(out)
            total += len(arr)
    print(f"wrote {total:,} tokens from {len(paths)} files -> {args.output}")


if __name__ == "__main__":
    main()
