"""Token-memmap input pipeline for the trainer.

Loads the packed uint32 binary that :mod:`datapreproc` writes, slices it
into per-process shards (each JAX process reads only its contiguous range
and materializes only its own rows of the global batch), and yields
device-resident batches with one host->device copy in flight (simple
double-buffer prefetch; XLA overlaps the copy with the previous step).

Batch sampling is seeded per (seed, process, step), so a job resumed from
checkpoint step N continues the stream at step N instead of replaying
steps 1..N (pass ``start_step``).
"""

from __future__ import annotations

import itertools
from typing import Iterator, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

from torchx_tpu.parallel.mesh import BATCH_SPEC


class TokenDataset:
    """Random-crop batches of ``seq+1`` tokens from a memmapped corpus.

    ``batch`` is the GLOBAL batch size; each process yields its
    ``batch / process_count`` local rows.
    """

    def __init__(
        self,
        path: str,
        seq: int,
        batch: int,
        seed: int = 0,
        start_step: int = 0,
        process_index: Optional[int] = None,
        process_count: Optional[int] = None,
    ) -> None:
        data = np.memmap(path, dtype=np.uint32, mode="r")
        pi = process_index if process_index is not None else jax.process_index()
        pc = process_count if process_count is not None else jax.process_count()
        if batch % pc:
            raise ValueError(f"global batch {batch} not divisible by {pc} processes")
        shard_len = len(data) // pc
        if shard_len < seq + 1:
            raise ValueError(
                f"corpus shard ({shard_len} tokens) smaller than seq+1={seq + 1}"
            )
        self._data = data[pi * shard_len : (pi + 1) * shard_len]
        self._seq = seq
        self._local_batch = batch // pc
        self._seed = seed
        self._start_step = start_step
        self._pi = pi

    def __iter__(self) -> Iterator[np.ndarray]:
        # valid crop starts are [0, len - (seq+1)]; integers() high is
        # exclusive, so the bound is len - seq
        n = len(self._data) - self._seq
        for step in itertools.count(self._start_step):
            rng = np.random.default_rng((self._seed, self._pi, step))
            starts = rng.integers(0, n, size=self._local_batch)
            yield np.stack(
                [self._data[s : s + self._seq + 1] for s in starts]
            ).astype(np.int32)


def device_batches(
    dataset: TokenDataset, mesh: Mesh, prefetch: int = 2
) -> Iterator[dict[str, jax.Array]]:
    """Yield sharded device batches with host production AND the
    host->device transfer running ahead of the consumer.

    A daemon thread assembles up to ``prefetch`` host batches (memmap
    reads + crop stacking) while the device runs the current step; the
    consumer side additionally keeps one async device transfer in flight.
    Each process contributes only its local rows
    (``jax.make_array_from_process_local_data``) — no duplicated host IO
    across the slice. Ordering (and therefore the seeded, resumable
    stream) is preserved: one producer, FIFO queue.
    """
    import queue
    import threading

    sharding = NamedSharding(mesh, BATCH_SPEC)

    def put(local_rows: np.ndarray) -> jax.Array:
        return jax.make_array_from_process_local_data(sharding, local_rows)

    q: "queue.Queue[object]" = queue.Queue(maxsize=max(1, prefetch))
    stop = threading.Event()
    done = object()  # exhaustion sentinel (TokenDataset is infinite, but
    # the helper accepts any iterable — ending must not hang the consumer)

    def _offer(item: object) -> None:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.5)
                return
            except queue.Full:
                continue

    def producer() -> None:
        try:
            for rows in dataset:
                _offer(rows)
                if stop.is_set():
                    return
            _offer(done)
        except BaseException as e:  # noqa: BLE001 - re-raised on the consumer side
            _offer(e)

    threading.Thread(target=producer, daemon=True, name="tpx-data-prefetch").start()

    def take() -> Optional[np.ndarray]:
        item = q.get()
        if item is done:
            return None
        if isinstance(item, BaseException):
            # a data error must fail the job loudly, not hang the loop
            raise item
        return item  # type: ignore[return-value]

    try:
        first = take()
        if first is None:
            return
        pending = put(first)
        while True:
            # dispatch batch N+1's host->device copy BEFORE yielding batch
            # N, so the transfer overlaps the consumer's running step
            nxt = take()  # host batch; None = dataset exhausted
            nxt_dev = put(nxt) if nxt is not None else None
            yield {"tokens": pending}
            if nxt_dev is None:
                return
            pending = nxt_dev
    finally:
        stop.set()  # generator closed/GC'd: release the producer thread
