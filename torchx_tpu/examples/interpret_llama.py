"""Model interpretability example: token attribution for the Llama family.

Reference analog: torchx/examples/apps/lightning/interpret.py — a captum
integrated-gradients app over the trained CNN. The TPU-native counterpart
computes **input-embedding attributions** for a trained (or fresh) Llama
checkpoint with pure jax transforms — no interpretability library needed,
because ``jax.grad`` over the embedding lookup IS the attribution
primitive:

* saliency: d loss(target token) / d embed(input token), L2 per token;
* integrated gradients: the same gradient accumulated along the
  zero-embedding -> input-embedding path (Sundararajan et al., 2017),
  which satisfies completeness (attributions sum to the score delta).

Launch it like every other analysis app (reference usage shape)::

    tpx run -s local utils.python -m torchx_tpu.examples.interpret_llama -- \\
        --config tiny --text "the quick brown fox"
    tpx run -s local utils.python -m torchx_tpu.examples.interpret_llama -- \\
        --config llama3_1b --ckpt-dir /ckpts/run1 --text "..."
"""

from __future__ import annotations

import argparse
from typing import Optional

import jax
import jax.numpy as jnp

from torchx_tpu.models import llama


def token_attributions(
    params: llama.Params,
    tokens: jnp.ndarray,  # [1, t] int32
    cfg: llama.LlamaConfig,
    steps: int = 16,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """-> (saliency [t], integrated_gradients [t]) for the next-token
    prediction at the final position.

    Gradients are taken w.r.t. the input EMBEDDINGS (the continuous relax-
    ation of the discrete tokens), then reduced per token position.
    """
    embeds = params["embed"][tokens[0]].astype(jnp.float32)[None]  # [1, t, d]
    target = jnp.argmax(
        llama.forward(params, tokens, cfg)[0, -1]
    )  # the model's own next-token prediction

    def score(e: jnp.ndarray) -> jnp.ndarray:
        # forward from embeddings: reuse the model stack minus the lookup
        x = e.astype(cfg.dtype)
        h = llama.forward_from_embeddings(params, x, cfg)
        return h[0, -1, target].astype(jnp.float32)

    grad_fn = jax.jit(jax.grad(score))

    # saliency: one gradient at the input
    sal = jnp.linalg.norm(grad_fn(embeds)[0], axis=-1)  # [t]

    # integrated gradients: average gradients along alpha * embeds
    def ig_step(acc: jnp.ndarray, alpha: jnp.ndarray) -> tuple[jnp.ndarray, None]:
        return acc + grad_fn(embeds * alpha)[0], None

    alphas = (jnp.arange(steps, dtype=jnp.float32) + 0.5) / steps
    total, _ = jax.lax.scan(ig_step, jnp.zeros_like(embeds[0]), alphas)
    ig = jnp.einsum("td,td->t", embeds[0], total / steps)  # completeness form
    return sal, ig


def main(argv: Optional[list[str]] = None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--config", default="tiny")
    parser.add_argument("--ckpt-dir", default=None)
    parser.add_argument("--text", default="the quick brown fox jumps over")
    parser.add_argument("--ig-steps", type=int, default=16)
    args = parser.parse_args(argv)

    from torchx_tpu.examples.train_llama import all_configs

    cfg = all_configs()[args.config]()
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    if args.ckpt_dir:
        from torchx_tpu.parallel.checkpoint import Checkpointer

        ckpt = Checkpointer(args.ckpt_dir)
        step, restored = ckpt.restore_latest(params)
        ckpt.close()
        if restored is not None:
            params = restored
            print(f"loaded checkpoint step {step}")

    token_ids = [b % cfg.vocab_size for b in args.text.encode("utf-8")]
    tokens = jnp.asarray([token_ids], dtype=jnp.int32)
    sal, ig = token_attributions(params, tokens, cfg, steps=args.ig_steps)

    print(f"{'pos':>4} {'byte':>6} {'saliency':>10} {'integrated_grad':>16}")
    for i, (tid, s, g) in enumerate(zip(token_ids, sal, ig)):
        ch = chr(tid) if 32 <= tid < 127 else "?"
        print(f"{i:>4} {ch!r:>6} {float(s):>10.4f} {float(g):>16.4f}")


if __name__ == "__main__":
    main()
