"""3-stage data -> train -> eval pipeline example (BASELINE config 5).

Builds the canonical DAG with a TPU training role in the middle and runs
it locally (or emits the Argo workflow with --emit-kfp)::

    python -m torchx_tpu.examples.pipeline_data_train_eval --workdir /tmp/pipe
"""

from __future__ import annotations

import argparse
import json

from torchx_tpu.components import dist, utils
from torchx_tpu.pipelines import Pipeline
from torchx_tpu.specs.builders import materialize_appdef


def build_pipeline(workdir: str, tpu: str | None = None) -> Pipeline:
    data = materialize_appdef(
        utils.sh,
        ["--", "sh", "-c", f"mkdir -p {workdir} && echo dataset > {workdir}/data.txt"],
    )
    train_args = [
        "-m",
        "torchx_tpu.examples.train_llama",
        "--",
        "--config",
        "tiny",
        "--steps",
        "2",
        "--mesh",
        "fsdp=-1",
    ]
    if tpu:
        train_args = ["--tpu", tpu, *train_args]
    else:
        train_args = ["-j", "1x2", *train_args]
    train = materialize_appdef(dist.spmd, train_args)
    evaluate = materialize_appdef(
        utils.sh,
        ["--", "sh", "-c", f"test -f {workdir}/data.txt && echo eval-ok"],
    )
    return (
        Pipeline(name="data-train-eval")
        .stage("data", data)
        .stage("train", train, depends_on=["data"])
        .stage("eval", evaluate, depends_on=["train"])
    )


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--workdir", default="/tmp/tpx_pipeline")
    parser.add_argument("--scheduler", default="local")
    parser.add_argument("--tpu", default=None, help="e.g. v5litepod-8")
    parser.add_argument(
        "--emit-kfp", action="store_true", help="print the Argo workflow and exit"
    )
    args = parser.parse_args()
    pipeline = build_pipeline(args.workdir, args.tpu)
    if args.emit_kfp:
        from torchx_tpu.pipelines.kfp import pipeline_to_workflow

        print(json.dumps(pipeline_to_workflow(pipeline), indent=2))
        return
    from torchx_tpu.pipelines.local_runner import run_pipeline
    from torchx_tpu.runner.api import get_runner

    with get_runner("pipeline") as runner:
        run = run_pipeline(runner, pipeline, args.scheduler)
        print(f"pipeline state: {run.state}")
        for stage, status in run.statuses.items():
            print(f"  {stage}: {status.state}")


if __name__ == "__main__":
    main()
