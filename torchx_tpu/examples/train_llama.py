"""Llama pretraining example/benchmark — the flagship launched job.

The analog of the reference's ``lightning`` example trainer
(torchx/examples/apps/lightning) re-imagined for TPU SPMD: a pjit-style
training step (AdamW, remat, bf16) over the 4-axis dp/fsdp/tp/sp mesh,
launched via::

    tpx run -s gke dist.spmd --tpu v5p-32 -m torchx_tpu.examples.train_llama -- \
        --config llama3_8b --mesh fsdp=-1 --batch 16 --seq 8192

Prints per-step tokens/sec and model FLOPs utilization (MFU); the
launch-to-first-step latency (the BASELINE.md north-star metric) is
reported as the time from process start to the end of step 1.
"""

from __future__ import annotations

import argparse
import contextvars
import dataclasses
import functools
import threading
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torchx_tpu.models import llama
from torchx_tpu.parallel.mesh import (
    BATCH_SPEC,
    MeshConfig,
    enable_shardy_if_supported,
    make_mesh,
)
from torchx_tpu.parallel.prefetch import Prefetcher, device_prefetch

_PROCESS_START = time.monotonic()

# The FIRST train() call in a process anchors launch-to-first-step to
# process start (the BASELINE north-star definition: import time counts);
# later calls in the same process (bench variant legs, sweeps) time only
# themselves — otherwise leg N reports the cumulative process age.
_FIRST_TRAIN_PENDING = True

# peak bf16 FLOPs/s per chip by generation (for MFU)
PEAK_FLOPS = {
    "tpu v2": 23e12,
    "tpu v3": 61.5e12,  # per chip (2 cores)
    "tpu v4": 275e12,
    "tpu v5": 197e12,  # v5e (v5 lite)
    "tpu v5p": 459e12,
    "tpu v6": 918e12,
    "cpu": 1e12,  # nominal, keeps MFU finite in simulation
}


def device_peak_flops() -> float:
    d = jax.devices()[0]
    kind = getattr(d, "device_kind", "cpu").lower()
    for prefix, flops in sorted(PEAK_FLOPS.items(), key=lambda kv: -len(kv[0])):
        if kind.startswith(prefix):
            return flops
    return PEAK_FLOPS["cpu"]


def make_optimizer(
    lr: float = 3e-4, weight_decay: float = 0.1, warmup: int = 100
) -> optax.GradientTransformation:
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=lr,
        warmup_steps=warmup,
        decay_steps=100_000,
        end_value=lr * 0.1,
    )
    return optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.adamw(schedule, b1=0.9, b2=0.95, weight_decay=weight_decay),
    )


@dataclasses.dataclass
class TrainState:
    params: llama.Params
    opt_state: Any
    step: jnp.ndarray


jax.tree_util.register_dataclass(
    TrainState, data_fields=["params", "opt_state", "step"], meta_fields=[]
)


def _model_fns(cfg: llama.LlamaConfig):
    """Dense vs MoE dispatch (see :func:`llama.model_fns`)."""
    return llama.model_fns(cfg)


def init_state(
    cfg: llama.LlamaConfig,
    mesh: Mesh,
    optimizer: optax.GradientTransformation,
    seed: int = 0,
) -> TrainState:
    """Initialize params *sharded* (jit with out_shardings so the full
    fp32 model never materializes on one device)."""
    init_fn, specs_fn = _model_fns(cfg)
    specs = specs_fn(cfg, pp=mesh.shape.get("pp", 1) > 1)
    out_shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), specs)

    @functools.partial(jax.jit, out_shardings=out_shardings)
    def _init(key):  # noqa: ANN001
        return init_fn(cfg, key)

    params = _init(jax.random.PRNGKey(seed))
    opt_state = jax.jit(
        optimizer.init,
        out_shardings=None,  # let XLA choose opt-state shardings from params
    )(params)
    state = TrainState(
        params=params, opt_state=opt_state, step=jnp.zeros((), jnp.int32)
    )
    return normalize_state_shardings(state, mesh)


def normalize_state_shardings(state: TrainState, mesh: Mesh) -> TrainState:
    """Re-place any leaf committed to a single device (XLA puts optimizer
    scalars there; orbax restores them there) as mesh-replicated, so every
    leaf of the state lives on one consistent device set."""
    replicated = NamedSharding(mesh, P())

    def fix(x):  # noqa: ANN001
        sharding = getattr(x, "sharding", None)
        if sharding is not None and len(sharding.device_set) < mesh.devices.size:
            return jax.device_put(x, replicated)
        return x

    return jax.tree.map(fix, state)


def make_train_step(
    cfg: llama.LlamaConfig,
    mesh: Mesh,
    optimizer: optax.GradientTransformation,
    state_shardings: Optional[Any] = None,
    grad_bucket_plan: Optional[Any] = None,
):
    """The jitted SPMD training step: grads + AdamW update, donated state.

    All mesh configs — including ring attention inside a pipeline stage
    (the pipeline manualizes pp and sp in one shard_map) — compile under
    the default Shardy partitioner; no GSPMD fallback remains.

    ``state_shardings`` (a TrainState of NamedShardings) pins the output
    state to the input's shardings. Without it the compiler may pick
    different shardings for the returned opt state than the donated input
    had — then feeding step N's state into step N+1 through an AOT
    executable trips the strict input-sharding check.

    ``grad_bucket_plan`` (a :class:`~torchx_tpu.parallel.overlap.BucketPlan`)
    buckets the gradient sync: value-identity barriers at bucket
    boundaries let XLA issue per-bucket reduces while backward is still
    running, instead of one fused post-backward collective. Gradients are
    bitwise identical to the unbucketed step."""

    def step(state: TrainState, batch: dict[str, jnp.ndarray]):
        (loss, aux), grads = jax.value_and_grad(llama.loss_and_aux, has_aux=True)(
            state.params, batch, cfg, mesh
        )
        if grad_bucket_plan is not None:
            from torchx_tpu.parallel import overlap

            grads, _ = overlap.bucketed_sync(
                grads,
                bucket_mb=max(1, grad_bucket_plan.bucket_bytes // (1024 * 1024)),
                mode="auto",
                plan=grad_bucket_plan,
            )
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params
        )
        params = optax.apply_updates(state.params, updates)
        return (
            TrainState(params=params, opt_state=opt_state, step=state.step + 1),
            loss,
            aux,  # raw MoE balancing aux (router health; 0 for dense)
        )

    out_shardings = None
    if state_shardings is not None:
        scalar = NamedSharding(mesh, P())
        out_shardings = (state_shardings, scalar, scalar)
    return jax.jit(step, donate_argnums=(0,), out_shardings=out_shardings)


def synthetic_batch(
    cfg: llama.LlamaConfig, mesh: Mesh, batch: int, seq: int, seed: int = 0
) -> dict[str, jnp.ndarray]:
    tokens = jax.random.randint(
        jax.random.PRNGKey(seed), (batch, seq + 1), 0, cfg.vocab_size, dtype=jnp.int32
    )
    return {"tokens": jax.device_put(tokens, NamedSharding(mesh, BATCH_SPEC))}


def parse_mesh_arg(spec: str) -> MeshConfig:
    """``dp=2,fsdp=-1,tp=4`` -> MeshConfig."""
    from torchx_tpu.parallel.mesh_config import parse_mesh_spec

    return parse_mesh_spec(spec)


def _replica_id() -> int:
    """This process's global replica id in the gang — the launcher-injected
    ``TPX_REPLICA_ID`` when present (the id the gang monitor expects),
    falling back to the jax process index."""
    import os

    from torchx_tpu import settings

    raw = os.environ.get(settings.ENV_TPX_REPLICA_ID, "")
    try:
        return int(raw)
    except ValueError:
        return jax.process_index()


def _renew_liveness_lease(step: Optional[int]) -> None:
    """Best-effort per-replica liveness lease alongside each heartbeat, so
    the supervisor's gang monitor can tell 'this replica is alive' apart
    from 'the whole gang stopped' even if the shared trace stream stalls.
    Never lets lease I/O take down training."""
    try:
        from torchx_tpu.supervisor.gang import renew_lease

        # step is advisory; None (no step known yet) must not turn into a
        # swallowed TypeError that silently skips the first-step lease
        renew_lease(_replica_id(), step=-1 if step is None else int(step))
    except Exception:  # noqa: BLE001 - liveness is advisory
        pass


def _launch_span(name: str, **attrs: Any):
    """A ``launch.*`` breakdown span when running under tracing, else a
    no-op (same gating as apps/spmd_main: spans only exist when the
    launcher injected ``TPX_TRACE_ID``)."""
    import os
    from contextlib import nullcontext

    from torchx_tpu import settings

    if not os.environ.get(settings.ENV_TPX_TRACE_ID):
        return nullcontext()
    from torchx_tpu.obs import trace as obs_trace

    return obs_trace.span(name, **attrs)


def _report_first_step(
    first_step_s: float, resumed_step: int, breakdown: dict[str, float]
) -> None:
    """Join the launcher's trace with a ``job.first_step`` heartbeat and
    feed the launch-to-first-step histogram (the BASELINE.md north-star
    metric). No-op when this process was not launched under tracing."""
    import os

    from torchx_tpu import settings

    if not os.environ.get(settings.ENV_TPX_TRACE_ID):
        return
    from torchx_tpu.obs import metrics as obs_metrics
    from torchx_tpu.obs import trace as obs_trace

    obs_metrics.LAUNCH_TO_FIRST_STEP.observe(first_step_s)
    obs_trace.heartbeat(
        "job.first_step",
        launch_to_first_step_s=round(first_step_s, 3),
        resumed_step=resumed_step or None,
        replica=_replica_id(),
        **{f"stage_{k}_s": round(v, 3) for k, v in breakdown.items()},
    )
    _renew_liveness_lease(resumed_step)


def _step_heartbeat(**attrs: Any) -> None:
    """A ``step.window`` trace event per log window — the steady-state
    counterpart of the ``launch.*`` spans (same TPX_TRACE_ID gating)."""
    import os

    from torchx_tpu import settings

    if not os.environ.get(settings.ENV_TPX_TRACE_ID):
        return
    from torchx_tpu.obs import trace as obs_trace

    obs_trace.heartbeat("step.window", replica=_replica_id(), **attrs)
    _renew_liveness_lease(int(attrs.get("step", -1)))


def _profile_enabled(flag: bool) -> bool:
    """True when per-step phase profiling is on: the trainer's
    ``--profile`` flag or the launcher-injected ``TPX_PROFILE`` switch
    (so a submitted role enables it via env without editing args)."""
    if flag:
        return True
    import os

    from torchx_tpu import settings

    return os.environ.get(settings.ENV_TPX_PROFILE, "").lower() in (
        "1",
        "true",
        "yes",
        "on",
    )


def _make_profiler(
    cfg: llama.LlamaConfig,
    mesh: Mesh,
    batch: int,
    seq: int,
    tokens_per_step: int,
    flops_per_token: float,
    peak_flops: float,
) -> Optional[Any]:
    """Best-effort :class:`~torchx_tpu.obs.profile.StepProfiler` wired to
    this run's arithmetic.

    Mirrors the live config and mesh into the jax-free
    ``ModelShape``/``ParallelPlan`` IR so the attribution model's
    collective terms come from the same calibrated cost model as
    ``tpx explain``. Returns None when anything is off — profiling must
    never fail the job.
    """
    try:
        from torchx_tpu.analyze.plan import ModelShape, ParallelPlan
        from torchx_tpu.obs.profile import StepProfiler, attribution_model

        kind = getattr(jax.devices()[0], "device_kind", "cpu")
        shape = ModelShape(
            name="train",
            vocab_size=cfg.vocab_size,
            dim=cfg.dim,
            n_layers=cfg.n_layers,
            n_heads=cfg.n_heads,
            n_kv_heads=cfg.n_kv_heads,
            ffn_dim=cfg.ffn_dim,
            max_seq=cfg.max_seq,
            dtype_bytes=jnp.dtype(cfg.dtype).itemsize,
            tie_embeddings=cfg.tie_embeddings,
            loss_chunk=cfg.loss_chunk,
            n_experts=getattr(cfg, "n_experts", 0),
            top_k=getattr(cfg, "top_k", 0),
        )
        plan = ParallelPlan(
            role="train",
            model=shape,
            mesh_spec="",
            sizes={a: int(s) for a, s in mesh.shape.items()},
            batch=batch,
            seq=seq,
            devices=jax.device_count(),
            accelerator=kind,
        )
        return StepProfiler(
            attribution_model(
                flops_per_token=flops_per_token,
                tokens_per_step=tokens_per_step,
                peak_flops=peak_flops,
                param_count=shape.param_count(),
                plan=plan,
                generation=kind,
            )
        )
    except Exception as e:  # noqa: BLE001 - profiling is best-effort
        if jax.process_index() == 0:
            print(f"step profiler unavailable: {e}", flush=True)
        return None


def _install_preempt_handler() -> tuple[Optional[threading.Event], Any]:
    """Arm a SIGTERM preemption-grace handler (main thread only).

    TPU preemptions deliver SIGTERM with a short notice window before the
    hard kill; the default handler would drop the process mid-step and
    waste everything since the last periodic checkpoint. Instead the
    handler just sets an event the train loop polls at each step — the
    loop then forces a final save, *waits for it to be durable*, and exits
    cleanly inside the window. Returns ``(event, restore)`` where
    ``restore()`` reinstates the previous handler; ``(None, noop)`` when
    the handler cannot be installed (non-main thread, e.g. under pytest
    workers or a nested launcher)."""
    import signal

    if threading.current_thread() is not threading.main_thread():
        return None, lambda: None
    evt = threading.Event()

    def _on_sigterm(signum, frame):  # noqa: ANN001
        evt.set()

    try:
        prev = signal.signal(signal.SIGTERM, _on_sigterm)
    except (ValueError, OSError):  # no signal support here
        return None, lambda: None

    def _restore() -> None:
        try:
            signal.signal(signal.SIGTERM, prev)
        except (ValueError, OSError):
            pass

    return evt, _restore


def train(
    cfg: llama.LlamaConfig,
    mesh_config: MeshConfig,
    batch: int,
    seq: int,
    steps: int,
    log_every: int = 1,
    lr: float = 3e-4,
    warmup: int = 100,
    ckpt_dir: Optional[str] = None,
    ckpt_every: int = 0,
    data_path: Optional[str] = None,
    profile_dir: Optional[str] = None,
    prefetch: int = 2,
    profile: bool = False,
    grad_bucket_mb: Any = 0,
    kernels: str = "reference",
    launch_anchor: Optional[float] = None,
) -> dict[str, float]:
    global _FIRST_TRAIN_PENDING
    t_call = time.monotonic()
    # ``launch_anchor`` re-anchors launch-to-first-step for in-process
    # callers (the bench legs): without it, every leg after the first
    # would either inherit process age or measure only its own call —
    # the caller says explicitly which clock this run starts on.
    if launch_anchor is not None:
        launch_ref = launch_anchor
    else:
        launch_ref = _PROCESS_START if _FIRST_TRAIN_PENDING else t_call
    _FIRST_TRAIN_PENDING = False

    from torchx_tpu.obs import metrics as obs_metrics
    from torchx_tpu.parallel.xla_cache import setup_compilation_cache

    breakdown: dict[str, float] = {}

    def _stage(stage: str, seconds: float) -> None:
        breakdown[stage] = seconds
        obs_metrics.LAUNCH_STAGE_SECONDS.observe(seconds, stage=stage)

    _stage("import", t_call - launch_ref)

    cfg = dataclasses.replace(cfg, max_seq=seq)

    kernels_used = "reference"
    if kernels and kernels != "reference":
        # "pallas" silently degrades to "reference" off-TPU (the Mosaic
        # kernels need real TPU cores); "interpret" runs the same kernels
        # through the Pallas interpreter anywhere (tests, CPU sim)
        from torchx_tpu.ops.fused import resolve_kernels

        kernels_used = resolve_kernels(kernels)
        cfg = dataclasses.replace(cfg, kernels=kernels_used)
        if kernels_used != kernels and jax.process_index() == 0:
            print(
                f"kernels: {kernels!r} unavailable on this backend;"
                " using reference ops",
                flush=True,
            )

    t0 = time.monotonic()
    with _launch_span("launch.backend_init"):
        setup_compilation_cache()  # relaunches compile in seconds, not minutes
        # the whole sharding stack (partial-auto shard_map, the embedding
        # gather constraints) targets Shardy; compiling through legacy
        # GSPMD instead logs a deprecation warning per compile and its
        # gather heuristics force involuntary full rematerialization
        enable_shardy_if_supported()
        mesh = make_mesh(mesh_config)  # first device query: backend init
        n_devices = jax.device_count()
        peak = device_peak_flops() * n_devices
    _stage("backend_init", time.monotonic() - t0)

    optimizer = make_optimizer(lr=lr, warmup=warmup)

    if cfg.remat_policy == "auto":
        if cfg.remat:
            # resolve "auto" -> the cheapest-recompute policy whose
            # compiled step fits HBM (trial compiles land in the
            # persistent XLA cache, so the winner's real compile below is
            # a cache hit)
            from torchx_tpu.parallel.remat_auto import choose_remat_policy

            t0 = time.monotonic()
            with _launch_span("launch.remat_select"):
                policy, trials = choose_remat_policy(cfg, mesh, batch, seq)
            cfg = dataclasses.replace(cfg, remat_policy=policy)
            _stage("remat_select", time.monotonic() - t0)
            if jax.process_index() == 0:
                verdicts = ", ".join(
                    f"{t.policy}={'fits' if t.fits else 'no'}" for t in trials
                )
                print(f"remat auto -> {policy} ({verdicts})", flush=True)
        else:
            # remat disabled: the policy is never consulted, but "auto"
            # must not leak into traces/results as if it were concrete
            cfg = dataclasses.replace(cfg, remat_policy="full")
    # what the step actually does — "none" when remat is off entirely
    remat_policy_used = cfg.remat_policy if cfg.remat else "none"

    ckpt = None
    latest = None
    if ckpt_dir:
        from torchx_tpu.parallel.checkpoint import Checkpointer

        ckpt_every = ckpt_every or 100  # ckpt_dir alone must still checkpoint
        ckpt = Checkpointer(ckpt_dir, save_interval_steps=ckpt_every)
        latest = ckpt.latest_step()  # cheap step listing, no tensor IO
    resumed_step = latest or 0

    # -- overlapped bootstrap ----------------------------------------------
    # Corpus setup (memmap open + first host batch + its device transfer)
    # and the heavy checkpoint restore run on threads while the main thread
    # AOT-compiles the train step; both join before the first step. Spans
    # started on the threads keep their parent via the copied context.
    ctx = contextvars.copy_context()

    data_box: dict[str, Any] = {}

    def _data_setup() -> None:
        t_d = time.monotonic()
        try:
            from torchx_tpu.examples.data import TokenDataset

            with _launch_span("launch.data_setup"):
                gen = device_prefetch(
                    ({"tokens": rows} for rows in
                     TokenDataset(data_path, seq, batch, start_step=resumed_step)),
                    mesh,
                    depth=prefetch,
                )
                # pull batch 1 now so its host->device transfer overlaps
                # the compile instead of the first step
                data_box["first"] = next(gen)
            data_box["batches"] = gen
        except BaseException as e:  # noqa: BLE001 - re-raised on join
            data_box["error"] = e
        data_box["seconds"] = time.monotonic() - t_d

    data_thread = None
    if data_path:
        data_thread = threading.Thread(
            target=lambda: ctx.run(_data_setup), name="tpx-data-setup", daemon=True
        )
        data_thread.start()

    restore_box: dict[str, Any] = {}
    restore_thread = None
    if latest is not None:
        # resuming: restore onto the ABSTRACT train state (skipping the
        # init compile entirely) concurrently with the AOT compile below
        from torchx_tpu.parallel.aot_fit import abstract_train_state

        lower_state = abstract_train_state(cfg, mesh, optimizer)

        def _restore() -> None:
            t_r = time.monotonic()
            try:
                with _launch_span("launch.restore", step=latest):
                    step_r, restored = ckpt.restore_latest(lower_state)
                restore_box["step"] = step_r
                restore_box["state"] = restored
            except BaseException as e:  # noqa: BLE001 - re-raised on join
                restore_box["error"] = e
            restore_box["seconds"] = time.monotonic() - t_r

        restore_thread = threading.Thread(
            target=lambda: ctx.run(_restore), name="tpx-ckpt-restore", daemon=True
        )
        restore_thread.start()
    else:
        t0 = time.monotonic()
        with _launch_span("launch.init_state"):
            state = init_state(cfg, mesh, optimizer)
        _stage("init_state", time.monotonic() - t0)
        lower_state = state

    # AOT compile while restore/data IO is in flight. The loop then calls
    # the Compiled executable directly — no per-step jit cache lookup — and
    # variant configs (e.g. the int8 bench leg) lower to distinct programs
    # that each land in (and relaunch from) the persistent XLA cache.
    t0 = time.monotonic()
    state_shardings = jax.tree.map(lambda x: x.sharding, lower_state)

    # resolve --grad-bucket-mb against the (possibly abstract) param tree:
    # bucket layout only needs shapes/dtypes, so the plan is fixed before
    # the compile and never perturbs the compilation cache between runs
    grad_plan = None
    grad_bucket_mb_used = 0
    bucket_trials: tuple = ()
    if grad_bucket_mb not in (0, "0", None, ""):
        from torchx_tpu.parallel import overlap

        grad_bucket_mb_used, bucket_trials = overlap.resolve_bucket_mb(
            lower_state.params, grad_bucket_mb
        )
        grad_plan = overlap.plan_buckets(
            lower_state.params, grad_bucket_mb_used * 1024 * 1024
        )
        if jax.process_index() == 0:
            print(f"grad buckets -> {grad_plan.describe()}", flush=True)

    train_step = make_train_step(
        cfg, mesh, optimizer, state_shardings=state_shardings,
        grad_bucket_plan=grad_plan,
    )
    batch_sds = {
        "tokens": jax.ShapeDtypeStruct(
            (batch, seq + 1),
            jnp.int32,
            sharding=NamedSharding(mesh, BATCH_SPEC),
        )
    }
    step_fn = train_step
    with _launch_span("launch.compile"):
        try:
            step_fn = train_step.lower(lower_state, batch_sds).compile()
        except Exception as e:  # noqa: BLE001 - AOT is an optimization only
            if jax.process_index() == 0:
                print(f"AOT compile unavailable ({e}); using jit path", flush=True)
    _stage("compile", time.monotonic() - t0)

    if restore_thread is not None:
        restore_thread.join()
        if "error" in restore_box:
            raise restore_box["error"]
        if restore_box.get("state") is None:
            # every candidate step failed verification and was quarantined
            # (restore_latest returned (None, None)): train from scratch
            # instead of dying on the missing state
            t0 = time.monotonic()
            with _launch_span("launch.init_state"):
                state = init_state(cfg, mesh, optimizer)
            _stage("init_state", time.monotonic() - t0)
            resumed_step = 0
            if jax.process_index() == 0:
                print(
                    "no restorable checkpoint step (all quarantined);"
                    " starting fresh",
                    flush=True,
                )
        else:
            state = restore_box["state"]
            resumed_step = int(restore_box["step"])
            _stage("restore", restore_box["seconds"])
            if jax.process_index() == 0:
                print(
                    f"resumed from checkpoint step {resumed_step}", flush=True
                )

    if data_thread is not None:
        data_thread.join()
        if "error" in data_box:
            raise data_box["error"]
        if resumed_step != (latest or 0):
            # restore fell back past a corrupt newest step: rebuild the
            # stream so data and params resume from the same step
            from torchx_tpu.examples.data import TokenDataset

            data_box["batches"].close()
            gen = device_prefetch(
                ({"tokens": rows} for rows in
                 TokenDataset(data_path, seq, batch, start_step=resumed_step)),
                mesh,
                depth=prefetch,
            )
            data_box["first"] = next(gen)
            data_box["batches"] = gen
        _stage("data_setup", data_box["seconds"])
        _first_batch = [data_box["first"]]
        _batches = data_box["batches"]

        def next_batch() -> dict[str, jnp.ndarray]:
            if _first_batch:
                return _first_batch.pop()
            return next(_batches)

    else:
        import itertools

        # constant device batch: passthrough prefetcher (depth 0) keeps one
        # code path and an honest (≈0) data-wait account
        data = synthetic_batch(cfg, mesh, batch, seq)
        _batches = Prefetcher(itertools.repeat(data), depth=0)
        next_batch = lambda: next(_batches)  # noqa: E731

    tokens_per_step = batch * seq
    flops_per_token = cfg.flops_per_token()  # cfg.max_seq already == seq

    # step 1 (already AOT-compiled above) = launch-to-first-step
    t0 = time.monotonic()
    with _launch_span("launch.first_step"):
        first = next_batch()
        try:
            state, loss, aux = step_fn(state, first)
        except Exception:
            if step_fn is train_step:
                raise
            # the AOT executable rejected the concrete args (layout or
            # sharding drift): fall back to the jit path, not fail the job
            step_fn = train_step
            state, loss, aux = step_fn(state, first)
        jax.block_until_ready(loss)
    first_step_s = time.monotonic() - launch_ref
    _stage("first_step", time.monotonic() - t0)
    if jax.process_index() == 0:
        print(
            f"step 1 loss={float(loss):.4f}"
            f" launch-to-first-step={first_step_s:.1f}s",
            flush=True,
        )
        _report_first_step(first_step_s, resumed_step, breakdown)

    if steps <= 1:
        # single-step smoke: the compile-including step is the only timing
        _batches.close()
        return {
            "loss": float(loss),
            "tokens_per_sec": tokens_per_step / first_step_s,
            "tokens_per_sec_per_chip": tokens_per_step / first_step_s / n_devices,
            "mfu": tokens_per_step / first_step_s * flops_per_token / peak,
            "launch_to_first_step_s": first_step_s,
            "launch_breakdown": dict(breakdown),
            "remat_policy": remat_policy_used,
            "kernels": kernels_used,
            "grad_bucket_mb": grad_bucket_mb_used,
            "grad_buckets": grad_plan.n_buckets if grad_plan else 0,
        }

    # a few untimed warmup steps: dispatch pipelining + allocator settling
    warmup_steps = min(3, max(steps - 2, 0))
    for _ in range(warmup_steps):
        state, loss, aux = step_fn(state, next_batch())
    jax.block_until_ready(loss)

    import contextlib

    profiler = None
    if _profile_enabled(profile):
        profiler = _make_profiler(
            cfg, mesh, batch, seq, tokens_per_step, flops_per_token, peak
        )
    if profiler is not None:
        # per-next() wait intervals credit the current step's data_wait
        _batches.set_wait_observer(profiler.observe_wait)

    def _prof_phase(name: str):
        return profiler.phase(name) if profiler is not None else (
            contextlib.nullcontext()
        )

    if profile_dir and jax.process_index() == 0:
        # xprof trace of the steady-state steps (view with tensorboard or
        # xprofiler; the TPU observability hook from SURVEY §5)
        jax.profiler.start_trace(profile_dir)

    is_moe = bool(getattr(cfg, "n_experts", 0))

    def _emit_log(entry: dict) -> None:
        # the async copies issued at the log boundary are long since done;
        # float() here is a host-memory read, not a device round-trip
        aux_vec = entry["aux"]
        moe_note = (
            f" router_aux={float(aux_vec[llama.AUX_BALANCE]):.3f}"
            f" router_entropy={float(aux_vec[llama.AUX_ENTROPY]):.2f}"
            f" router_overflow={float(aux_vec[llama.AUX_OVERFLOW]):.1%}"
            if is_moe
            else ""
        )
        print(
            f"step {entry['step']} loss={float(entry['loss']):.4f}"
            f" tokens/sec={entry['tps']:,.0f}"
            f" tokens/sec/chip={entry['tps'] / n_devices:,.0f}"
            f" MFU={entry['mfu']:.1%}"
            f" window_mfu={entry['window_mfu']:.1%}{moe_note}",
            flush=True,
        )

    t0 = time.monotonic()
    timed_steps = max(steps - 1 - warmup_steps, 1)
    # host-side global step counter: int(state.step) would force a
    # device sync every iteration, breaking dispatch pipelining
    global_step = resumed_step + 1 + warmup_steps
    pending = None  # deferred log entry: printed one window late
    window_t0, window_steps = t0, 0
    # data-wait accounting anchors: the prefetcher's cumulative wait at
    # loop entry, and at the last log fence (for per-window splits)
    wait_anchor = window_wait = _batches.data_wait_s
    # preemption grace: SIGTERM sets the event; the loop fences, forces a
    # final durable save, and exits cleanly inside the notice window
    preempt_evt, _restore_sigterm = _install_preempt_handler()
    preempted = False
    try:
        for i in range(timed_steps):
            if profiler is not None:
                # the phase boundary is host-visible only behind a
                # completion fence, so profiled steps serialize dispatch
                # (a measured, documented perturbation — the headline
                # bench legs run unprofiled)
                profiler.begin_step()
                b = next_batch()
                with profiler.phase("forward_backward"):
                    state, loss, aux = step_fn(state, b)
                    jax.block_until_ready(loss)
            else:
                state, loss, aux = step_fn(state, next_batch())
            global_step += 1
            window_steps += 1
            if ckpt is not None and global_step % ckpt_every == 0:
                with _prof_phase("checkpoint"):
                    ckpt.save(global_step, state)
            if preempt_evt is not None and preempt_evt.is_set():
                preempted = True
                jax.block_until_ready(state.params)
                if ckpt is not None:
                    ckpt.save(global_step, state, force=True)
                    ckpt.wait()  # durable BEFORE the hard kill lands
                if jax.process_index() == 0:
                    print(
                        f"preemption notice: checkpointed step {global_step},"
                        " exiting",
                        flush=True,
                    )
                break
            if (i + 1) % log_every == 0 or i + 1 == timed_steps:
                with _prof_phase("host"):
                    jax.block_until_ready(loss)  # completion fence: timing only
                    now = time.monotonic()
                    dt = (now - t0) / (i + 1)
                    tps = tokens_per_step / dt
                    window_dt = (now - window_t0) / window_steps
                    window_mfu = (
                        tokens_per_step / window_dt * flops_per_token / peak
                    )
                    wait_now = _batches.data_wait_s
                    wait_per_step = (wait_now - window_wait) / window_steps
                    window_wait = wait_now
                    obs_metrics.STEP_SECONDS.observe(window_dt, phase="total")
                    obs_metrics.STEP_SECONDS.observe(
                        wait_per_step, phase="data_wait"
                    )
                    _step_heartbeat(
                        step=global_step,
                        avg_step_s=round(window_dt, 6),
                        data_wait_s=round(wait_per_step, 6),
                        mfu=round(window_mfu, 4),
                        remat_policy=remat_policy_used,
                    )
                    # Logging must not stall the device: a synchronous
                    # float(loss) here is a full device->host round trip
                    # (~100ms over a TPU tunnel) that lands INSIDE the next
                    # timed window — measured as a fake 52.8%->48.9% "MFU
                    # decay" in round 2. Instead start an async copy and
                    # print the PREVIOUS window's entry, so the transfer
                    # overlaps the next window's compute.
                    for arr in (loss, aux):
                        copy_async = getattr(arr, "copy_to_host_async", None)
                        if copy_async is not None:
                            copy_async()
                    if pending is not None and jax.process_index() == 0:
                        _emit_log(pending)
                    pending = {
                        "step": global_step,
                        "loss": loss,
                        "aux": aux,
                        "tps": tps,
                        "mfu": tps * flops_per_token / peak,
                        "window_mfu": window_mfu,
                    }
                    window_t0, window_steps = time.monotonic(), 0
            if profiler is not None:
                profiler.end_step(global_step)
        jax.block_until_ready(state.params)
        total = time.monotonic() - t0
        data_wait_s = _batches.data_wait_s - wait_anchor
    finally:
        _restore_sigterm()
        # graceful drain: release the prefetch producer even when the loop
        # exits early (error, interrupt) — never leave a thread blocked on
        # a full queue
        _batches.close()
    if pending is not None and jax.process_index() == 0:
        _emit_log(pending)  # after timing: the flush is off the clock
    if profile_dir and jax.process_index() == 0:
        jax.profiler.stop_trace()
        print(f"profile trace written to {profile_dir}", flush=True)
    tps = tokens_per_step * timed_steps / total
    if ckpt is not None:
        if ckpt.latest_step() != global_step:  # final state, any interval
            ckpt.save(global_step, state, force=True)
        ckpt.close()
    profile_summary = None
    if profiler is not None:
        _batches.set_wait_observer(None)
        try:
            # summarize + tpx_profile_* gauges + the observe_collectives
            # calibration fold (when the mesh moved collective bytes)
            profile_summary = profiler.close()
        except Exception as e:  # noqa: BLE001 - profiling is best-effort
            if jax.process_index() == 0:
                print(f"profile summary failed: {e}", flush=True)
    results = {
        "loss": float(loss),
        "tokens_per_sec": tps,
        "tokens_per_sec_per_chip": tps / n_devices,
        "mfu": tps * flops_per_token / peak,
        "launch_to_first_step_s": first_step_s,
        "launch_breakdown": dict(breakdown),
        "final_step": int(state.step),
        "resumed_from_step": resumed_step,
        # steady-state step-time split: how much of each timed step the
        # host spent blocked on input vs the device computing
        "step_time_s": total / timed_steps,
        "data_wait_s": data_wait_s,
        "data_wait_frac": data_wait_s / total if total > 0 else 0.0,
        "remat_policy": remat_policy_used,
        "prefetch_depth": prefetch,
        # step-time optimization knobs actually in effect for this run
        "kernels": kernels_used,
        "grad_bucket_mb": grad_bucket_mb_used,
        "grad_buckets": grad_plan.n_buckets if grad_plan else 0,
        # True when a SIGTERM preemption notice cut the run short (the
        # final checkpoint is durable; the supervisor resubmits from it)
        "preempted": preempted,
    }
    if bucket_trials:
        results["grad_bucket_trials"] = [t.to_dict() for t in bucket_trials]
    if profile_summary is not None:
        results["profile"] = profile_summary
    return results


def all_configs() -> dict:
    """Dense llama presets plus the MoE family (models/moe.py)."""
    from torchx_tpu.models import moe

    return {**llama.CONFIGS, **moe.CONFIGS}


def main(argv: Optional[list[str]] = None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--config", default="tiny", choices=sorted(all_configs()))
    parser.add_argument(
        "--mesh",
        default="fsdp=-1",
        help="axis sizes pp/dp/fsdp/ep/tp/sp, e.g. dp=2,fsdp=-1,tp=4"
        " (ep shards MoE experts independently of tp, e.g. ep=8,tp=1)",
    )
    parser.add_argument("--batch", type=int, default=8)
    parser.add_argument("--seq", type=int, default=128)
    parser.add_argument("--steps", type=int, default=10)
    parser.add_argument("--ring-attention", action="store_true")
    parser.add_argument(
        "--remat-policy",
        default=None,
        choices=["full", "dots", "dots_attn", "auto"],
        help="rematerialization policy (default: the config's own);"
        " 'auto' AOT-compiles candidates and picks the cheapest-recompute"
        " policy that fits device HBM",
    )
    parser.add_argument(
        "--prefetch",
        type=int,
        default=2,
        help="device input prefetch depth (batches staged ahead of the"
        " step; 0 = synchronous)",
    )
    parser.add_argument(
        "--int8",
        action="store_true",
        help="AQT int8 training matmuls (see docs/performance.md for the"
        " measured v5e guidance before enabling)",
    )
    parser.add_argument(
        "--int8-scope",
        default=None,
        choices=["all", "ffn"],
        help="which projections to quantize (implies --int8)",
    )
    parser.add_argument("--lr", type=float, default=None)
    parser.add_argument(
        "--grad-bucket-mb",
        default="0",
        help="bucket the gradient sync so per-bucket reduces overlap the"
        " backward pass: a size cap in MiB, 'auto' (remat_auto-style"
        " candidate ladder), or 0 to keep the single fused sync."
        " Gradients are bitwise identical either way",
    )
    parser.add_argument(
        "--kernels",
        default=None,
        choices=["reference", "pallas", "interpret"],
        help="attention/norm kernel implementation: 'pallas' selects the"
        " fused Mosaic kernels on TPU (reference fallback elsewhere);"
        " 'interpret' runs the same kernels in the Pallas interpreter"
        " (parity testing); default reference XLA ops",
    )
    parser.add_argument(
        "--log-every", type=int, default=None,
        help="steps between log lines, >= 1 (each is a device fence;"
        " 8+ on TPU)",
    )
    parser.add_argument(
        "--data", default=None, help="packed uint32 token file (see datapreproc); synthetic data when unset"
    )
    parser.add_argument(
        "--profile-dir", default=None, help="write an xprof trace of the timed steps here"
    )
    parser.add_argument(
        "--profile",
        action="store_true",
        help="per-step phase attribution (data_wait / forward_backward /"
        " grad_sync / optimizer / checkpoint / host) appended to the obs"
        " session's profile.jsonl — view with `tpx profile`; also"
        " enabled by TPX_PROFILE=1. Fences every step: use for"
        " attribution runs, not headline numbers",
    )
    parser.add_argument(
        "--ckpt-dir", default=None, help="checkpoint directory (enables save+resume)"
    )
    parser.add_argument(
        "--ckpt-every", type=int, default=0, help="save every N steps (default 100 when --ckpt-dir is set)"
    )
    args = parser.parse_args(argv)

    cfg = all_configs()[args.config]()
    if args.ring_attention:
        cfg = dataclasses.replace(cfg, use_ring_attention=True)
    if args.remat_policy:
        cfg = dataclasses.replace(cfg, remat_policy=args.remat_policy)
    if args.int8 or args.int8_scope:
        cfg = dataclasses.replace(
            cfg, int8_matmuls=True, int8_scope=args.int8_scope or "all"
        )
    if args.log_every is not None and args.log_every < 1:
        parser.error("--log-every must be >= 1")
    # None = keep train()'s own defaults (single source of truth)
    overrides = {
        k: v
        for k, v in {"log_every": args.log_every, "lr": args.lr}.items()
        if v is not None
    }
    import os

    from torchx_tpu import settings

    # an elastic reshape overrides --mesh: the supervisor injects the
    # degraded shape for resubmitted attempts as $TPX_MESH, so the job
    # comes up on the surviving capacity without anyone editing flags
    mesh_spec = os.environ.get(settings.ENV_TPX_MESH) or args.mesh
    metrics = train(
        cfg,
        parse_mesh_arg(mesh_spec),
        args.batch,
        args.seq,
        args.steps,
        **overrides,
        ckpt_dir=args.ckpt_dir,
        ckpt_every=args.ckpt_every,
        data_path=args.data,
        profile_dir=args.profile_dir,
        prefetch=args.prefetch,
        profile=args.profile,
        grad_bucket_mb=args.grad_bucket_mb,
        kernels=args.kernels or "reference",
    )
    if jax.process_index() == 0:
        print("final:", metrics, flush=True)


if __name__ == "__main__":
    main()
