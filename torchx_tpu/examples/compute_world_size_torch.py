"""Torch-side e2e probe: allreduce world size under dist.ddp.

The exact analog of the reference's compute_world_size example
(torchx/examples/apps/compute_world_size/main.py:10-28), for the compat
``dist.ddp`` component: torchrun launches N workers, each allreduces 1
over gloo and asserts the sum equals the world size.

    tpx run -s local dist.ddp -j 1x2 --script torchx_tpu/examples/compute_world_size_torch.py
"""

from __future__ import annotations

import torch
import torch.distributed as dist


def main() -> None:
    backend = "gloo"  # CPU-safe; torchrun provides the rendezvous env
    dist.init_process_group(backend=backend)
    t = torch.ones(1)
    dist.all_reduce(t)
    world_size = int(t.item())
    print(
        f"rank={dist.get_rank()}/{dist.get_world_size()}"
        f" computed_world_size={world_size}",
        flush=True,
    )
    assert world_size == dist.get_world_size(), (world_size, dist.get_world_size())
    dist.destroy_process_group()


if __name__ == "__main__":
    main()
