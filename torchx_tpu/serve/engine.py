"""Continuous-batching decode engine over a paged KV cache.

The batch-to-completion server (`apps/generate_server.py`'s coalescing
batcher) decodes every admitted batch to its full ``max_new_tokens``
before the next batch starts: a request arriving mid-decode waits out the
whole window, and slots whose sequences finish early idle until the
stragglers do. Decode on TPU is HBM-bandwidth-bound, so throughput is
(occupied slots) x (step rate) — idle slots are thrown-away bandwidth.

This engine keeps a **fixed slot array** decoding continuously:

* one jitted :func:`torchx_tpu.models.generate.paged_decode_step` per
  engine — static ``[max_slots]`` shapes, XLA compiles once regardless of
  which requests occupy the slots;
* **admission** between steps: waiting requests are prefilled in
  width-bucketed groups (a handful of compiles total) and dropped into
  free slots, with KV blocks allocated from the shared paged pool
  (:mod:`torchx_tpu.serve.kv_pool`);
* **eviction** per step: a slot that hits EOS or its token budget
  completes immediately — its caller unblocks, its blocks return to the
  pool, and the slot is free for the next admission *that same step*;
* **preemption** under pool pressure: if a mid-decode slot can't get its
  next block, the youngest slot is evicted back to the wait queue (its
  finished tokens kept; decode resumes exactly — sampling keys are a pure
  function of (seed, position));
* **prefix reuse**: admission consults the refcounted radix
  :class:`~torchx_tpu.serve.prefix_cache.PrefixCache` and prefills only
  the *uncached suffix* of each prompt (width-bucketed on suffix length,
  via :func:`~torchx_tpu.models.generate.paged_prefill_chunk`); newly
  computed full blocks are inserted back on prefill and on completion.
  Cached blocks are shared by refcount — a shared tail block about to be
  written is copy-on-write copied first, and under pool pressure the
  engine evicts cache-only blocks before preempting live slots;
* **disaggregation seams**: a request marked ``prefill_only`` completes
  at prefill with its KV blocks exported as a
  :class:`~torchx_tpu.serve.kv_transfer.KvPayload` (the prefill-replica
  role), and :meth:`ServeEngine.submit_prefilled` admits a transferred
  payload straight into a decode slot — scattering the received blocks
  into the pool with no prefill pass (the decode-replica role). A
  draining engine rejects handoffs with :class:`EngineStopped` so the
  sender requeues to another decode target.

Requests carry per-sequence temperature, seed, and EOS, so unrelated
requests share every device step. The engine emits ``serve.*`` spans /
heartbeats and ``tpx_serve_*`` metrics through the obs registry.
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import math
import threading
import time
from collections import deque
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from torchx_tpu.models import generate as gen
from torchx_tpu.models import llama
from torchx_tpu.obs import metrics as obs_metrics
from torchx_tpu.obs import trace as obs_trace
from torchx_tpu.ops.paged_attention import TRASH_BLOCK
from torchx_tpu.serve.kv_pool import BlockAllocator, PoolPlan, SlotTables
from torchx_tpu.serve.kv_transfer import KvPayload, new_request_id
from torchx_tpu.serve.prefix_cache import PrefixCache

logger = logging.getLogger(__name__)

__all__ = [
    "ServeRequest",
    "ServeEngine",
    "EngineStopped",
    "serve_kv_payload",
]


class EngineStopped(RuntimeError):
    """Raised by :meth:`ServeEngine.submit` once the engine is draining or
    stopped — the SIGTERM drain path returns 503s off this."""


@dataclasses.dataclass
class ServeRequest:
    """One generation request moving through the engine.

    Callers fill the first block and :meth:`wait`; the engine appends to
    ``generated`` as tokens decode and sets ``done`` at completion.
    Timing: ``ttft_s`` is enqueue -> first token, ``tpot_s`` the mean gap
    between subsequent tokens — the two serving-latency axes.
    """

    prompt: Sequence[int]
    max_new_tokens: int
    temperature: float = 0.0
    seed: int = 0
    eos_id: Optional[int] = None
    #: disaggregated mode: complete at prefill and export the computed
    #: KV blocks as ``handoff`` instead of occupying a decode slot.
    prefill_only: bool = False
    handoff: Optional[KvPayload] = None

    generated: list[int] = dataclasses.field(default_factory=list)
    error: Optional[str] = None
    done: threading.Event = dataclasses.field(default_factory=threading.Event)
    t_enqueue: float = 0.0
    t_first: float = 0.0
    t_done: float = 0.0

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until the request completes (True) or ``timeout`` (False)."""
        return self.done.wait(timeout)

    @property
    def tokens(self) -> list[int]:
        """prompt + generated, the full sequence."""
        return list(self.prompt) + self.generated

    @property
    def ttft_s(self) -> float:
        """Seconds from enqueue to first generated token."""
        return max(0.0, self.t_first - self.t_enqueue)

    @property
    def tpot_s(self) -> float:
        """Mean seconds per generated token after the first."""
        n = len(self.generated)
        if n <= 1:
            return 0.0
        return max(0.0, self.t_done - self.t_first) / (n - 1)


@dataclasses.dataclass
class _SlotState:
    req: ServeRequest
    cache_len: int  # tokens currently in the KV cache for this sequence
    last_tok: int  # most recent sampled token (next step's input)
    admit_seq: int  # admission order; highest = youngest = preemption victim


@dataclasses.dataclass
class _Admit:
    """One request through admission: its cached prefix + fresh blocks."""

    req: ServeRequest
    toks: list[int]  # prompt + already-generated (resume) tokens
    cached_blocks: list[int]  # retained from the prefix cache
    cached_tokens: int  # block-aligned prefix length served from cache
    new_blocks: list[int]  # freshly allocated for the suffix


@dataclasses.dataclass
class _Handoff:
    """A transferred prefill (KV blocks + continuation state) waiting for
    a decode slot."""

    req: ServeRequest
    k: np.ndarray  # [L, n_blocks, bs, kvh, hd]
    v: np.ndarray
    cache_len: int
    last_tok: int


def _next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1)).bit_length()


def _fold_keys(seeds: jnp.ndarray, sample_pos: jnp.ndarray) -> jnp.ndarray:
    # per-row sampling key = f(seed, position of the last token read):
    # pure, so decode resumed after preemption draws the same tokens
    base = jax.vmap(jax.random.PRNGKey)(seeds)
    return jax.vmap(jax.random.fold_in)(base, sample_pos)


class ServeEngine:
    """The continuous-batching serving engine (see module docstring).

    ``max_slots``/``block_size``/``num_blocks`` fix the compiled geometry;
    pass a :class:`~torchx_tpu.serve.kv_pool.PoolPlan` (from
    :func:`~torchx_tpu.serve.kv_pool.plan_pool`) via :meth:`from_plan` to
    size them against real HBM. The default ``num_blocks`` gives every
    slot a half-``max_seq`` budget — mild oversubscription; the preemption
    path covers the tail.
    """

    def __init__(
        self,
        params: llama.Params,
        cfg: llama.LlamaConfig,
        *,
        max_slots: int = 8,
        block_size: int = 16,
        num_blocks: Optional[int] = None,
        max_prefill_batch: int = 4,
        enable_prefix_cache: bool = True,
        prefix_cache_reserve: float = 0.0,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ) -> None:
        if block_size & (block_size - 1):
            raise ValueError(f"block_size must be a power of 2, got {block_size}")
        self._params = params
        self._cfg = cfg
        self.max_slots = max_slots
        self.block_size = block_size
        self.blocks_per_slot = math.ceil(cfg.max_seq / block_size)
        if num_blocks is None:
            num_blocks = 1 + max_slots * max(1, self.blocks_per_slot // 2)
        if num_blocks < self.blocks_per_slot + 1:
            raise ValueError(
                f"num_blocks={num_blocks} cannot hold one max_seq sequence"
                f" ({self.blocks_per_slot} blocks + trash)"
            )
        self.num_blocks = num_blocks
        self.max_prefill_batch = max(1, max_prefill_batch)
        self._clock = clock
        self._sleep = sleep

        self.pools = gen.init_kv_pools(cfg, num_blocks, block_size)
        self.alloc = BlockAllocator(num_blocks)
        self.tables = SlotTables(max_slots, self.blocks_per_slot)
        self._slots: list[Optional[_SlotState]] = [None] * max_slots
        self._admit_counter = itertools.count()
        self.prefix_cache: Optional[PrefixCache] = None
        if enable_prefix_cache:
            cap = (
                max(1, int(prefix_cache_reserve * num_blocks))
                if prefix_cache_reserve > 0
                else None
            )
            self.prefix_cache = PrefixCache(
                self.alloc, block_size, max_blocks=cap
            )

        self._lock = threading.Lock()
        self._waiting: deque[ServeRequest] = deque()
        self._handoffs: deque[_Handoff] = deque()
        self._prefilling = 0  # popped from _waiting, not yet slotted/done
        self._work = threading.Event()
        self._stop = threading.Event()
        self._draining = False
        self._thread: Optional[threading.Thread] = None
        self.requests_done = 0
        self.tokens_out = 0
        self.steps = 0
        self._steps_since_beat = 0

        # one compiled decode step for the engine's lifetime; donation lets
        # XLA update the pools in place (no-op on CPU, where jax warns —
        # so only donate off-CPU)
        donate = (3,) if jax.default_backend() != "cpu" else ()
        params_c, cfg_c = self._params, self._cfg

        def _decode(tokens, positions, tables, pools, seeds, temps):  # noqa: ANN001
            keys = _fold_keys(seeds, positions)
            return gen.paged_decode_step(
                params_c, tokens, positions, tables, pools, cfg_c, keys, temps
            )

        self._decode = jax.jit(_decode, donate_argnums=donate)
        self._prefill_fns: dict[tuple[int, int], Callable] = {}

    @classmethod
    def from_plan(
        cls,
        params: llama.Params,
        cfg: llama.LlamaConfig,
        plan: PoolPlan,
        **kwargs,
    ) -> "ServeEngine":
        """Build an engine with the geometry a :func:`plan_pool` sizing
        chose for the HBM budget."""
        return cls(
            params,
            cfg,
            max_slots=plan.max_slots,
            block_size=plan.block_size,
            num_blocks=plan.num_blocks,
            **kwargs,
        )

    # -- public API --------------------------------------------------------

    def start(self) -> "ServeEngine":
        """Spawn the engine loop thread (idempotent)."""
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="serve-engine", daemon=True
            )
            self._thread.start()
        return self

    def submit(self, req: ServeRequest) -> ServeRequest:
        """Enqueue a request for admission; raises :class:`EngineStopped`
        when draining/stopped, ValueError when it can never fit."""
        total = len(req.prompt) + req.max_new_tokens
        if total > self._cfg.max_seq:
            raise ValueError(
                f"prompt + new tokens ({total}) exceeds max_seq"
                f" {self._cfg.max_seq}"
            )
        if req.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        with self._lock:
            if self._draining or self._stop.is_set():
                raise EngineStopped("engine is draining; not admitting requests")
            req.t_enqueue = self._clock()
            self._waiting.append(req)
            obs_metrics.SERVE_QUEUE_DEPTH.set(len(self._waiting))
        self._work.set()
        return req

    def submit_prefilled(
        self,
        req: ServeRequest,
        k: np.ndarray,
        v: np.ndarray,
        cache_len: int,
        last_tok: int,
    ) -> ServeRequest:
        """Admit a sequence whose KV was prefilled on another replica.

        ``k``/``v`` are block-granular ``[L, n, bs, kvh, hd]`` arrays
        covering ``cache_len`` tokens; decode continues from ``last_tok``
        with no prefill pass. Raises :class:`EngineStopped` while
        draining — the transfer sender requeues to another decode
        target (the disaggregated drain-race contract)."""
        n_need = math.ceil(cache_len / self.block_size)
        if k.shape[1] != n_need or v.shape[1] != n_need:
            raise ValueError(
                f"payload has {k.shape[1]} blocks; cache_len={cache_len} "
                f"needs {n_need} at block_size={self.block_size}"
            )
        remaining = req.max_new_tokens - len(req.generated)
        if cache_len + remaining > self._cfg.max_seq:
            raise ValueError(
                f"cached tokens + remaining new tokens "
                f"({cache_len}+{remaining}) exceeds max_seq {self._cfg.max_seq}"
            )
        with self._lock:
            if self._draining or self._stop.is_set():
                raise EngineStopped("engine is draining; not accepting handoffs")
            if req.t_enqueue == 0.0:
                req.t_enqueue = self._clock()
            self._handoffs.append(_Handoff(req, k, v, cache_len, last_tok))
        self._work.set()
        return req

    def _admit_handoffs(self) -> bool:
        """Place transferred prefills into free slots: scatter the
        received blocks into the pool, no device prefill needed."""
        worked = False
        while True:
            free = [i for i, s in enumerate(self._slots) if s is None]
            with self._lock:
                if not self._handoffs or not free:
                    return worked
                h = self._handoffs[0]
                blocks = self._alloc_pressure(
                    math.ceil(h.cache_len / self.block_size)
                )
                if blocks is None:
                    return worked  # pool pressure; retry next loop pass
                self._handoffs.popleft()
                self._prefilling += 1  # visible to drain() until slotted
            with obs_trace.span(
                "serve.kv_import", blocks=len(blocks), cache_len=h.cache_len
            ):
                idx = jnp.asarray(np.asarray(blocks, np.int32))
                self.pools = {
                    "k": self.pools["k"].at[:, idx].set(
                        jnp.asarray(h.k, dtype=self.pools["k"].dtype)
                    ),
                    "v": self.pools["v"].at[:, idx].set(
                        jnp.asarray(h.v, dtype=self.pools["v"].dtype)
                    ),
                }
            seq = list(h.req.prompt) + h.req.generated
            if self.prefix_cache is not None:
                self.prefix_cache.insert(seq[: h.cache_len], blocks)
            slot = free[0]
            self.tables.assign(slot, blocks)
            self.tables.lengths[slot] = h.cache_len
            self._slots[slot] = _SlotState(
                req=h.req,
                cache_len=h.cache_len,
                last_tok=h.last_tok,
                admit_seq=next(self._admit_counter),
            )
            with self._lock:
                self._prefilling -= 1
            self._update_gauges()
            worked = True

    def generate(
        self,
        prompt: Sequence[int],
        max_new_tokens: int,
        temperature: float = 0.0,
        seed: int = 0,
        eos_id: Optional[int] = None,
        timeout: Optional[float] = None,
    ) -> ServeRequest:
        """Submit and block until done — the one-call convenience path."""
        req = ServeRequest(
            prompt=list(prompt),
            max_new_tokens=max_new_tokens,
            temperature=temperature,
            seed=seed,
            eos_id=eos_id,
        )
        self.submit(req)
        if not req.wait(timeout):
            raise TimeoutError(f"generation did not finish in {timeout}s")
        if req.error:
            raise RuntimeError(req.error)
        return req

    def stats(self) -> dict:
        """Engine occupancy/queue snapshot (feeds ``/healthz`` and the
        serve pool's load probe)."""
        with self._lock:
            active = sum(1 for s in self._slots if s is not None)
            out = {
                "active_slots": active,
                "max_slots": self.max_slots,
                "occupancy": active / self.max_slots,
                "queue_depth": len(self._waiting),
                "handoffs_pending": len(self._handoffs),
                "kv_blocks_used": self.alloc.used_blocks,
                "kv_blocks_free": self.alloc.free_blocks,
                "requests_done": self.requests_done,
                "tokens_out": self.tokens_out,
                "steps": self.steps,
                "draining": self._draining,
            }
        if self.prefix_cache is not None:
            out["prefix_cache"] = self.prefix_cache.stats()
        return out

    def prefix_summary(self, max_entries: int = 128) -> list[str]:
        """Digests of this engine's hottest cached prefixes — published
        on ``/healthz`` for the cache-aware router."""
        if self.prefix_cache is None:
            return []
        return self.prefix_cache.summary(max_entries)

    @property
    def queue_depth(self) -> int:
        """Requests waiting for a slot (the autoscaler's primary signal)."""
        with self._lock:
            return len(self._waiting)

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Stop admitting, finish everything in flight, return True when
        empty (False on timeout). The SIGTERM grace path."""
        with self._lock:
            self._draining = True
        deadline = None if timeout is None else self._clock() + timeout
        while True:
            with self._lock:
                empty = (
                    not self._waiting
                    and not self._handoffs
                    and self._prefilling == 0
                    and all(s is None for s in self._slots)
                )
            if empty:
                return True
            if deadline is not None and self._clock() > deadline:
                return False
            self._sleep(0.005)

    def stop(self, timeout: float = 5.0) -> None:
        """Kill the loop thread; in-flight requests get ``error`` set."""
        self._stop.set()
        self._work.set()
        if self._thread is not None:
            self._thread.join(timeout)
        self._fail_all("engine stopped")

    # -- engine loop -------------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                worked = self._admit_handoffs()
                worked = self._admit() or worked
                worked = self._decode_once() or worked
            except Exception as e:  # noqa: BLE001 — a step bug must not hang callers
                logger.exception("serve engine step failed")
                self._fail_all(f"engine step failed: {e}")
                return
            if not worked:
                self._work.wait(0.002)
                self._work.clear()

    def _fail_all(self, msg: str) -> None:
        with self._lock:
            pending = list(self._waiting)
            pending.extend(h.req for h in self._handoffs)
            self._waiting.clear()
            self._handoffs.clear()
            self._prefilling = 0
        for i, st in enumerate(self._slots):
            if st is not None:
                self._slots[i] = None
                pending.append(st.req)
        for req in pending:
            if not req.done.is_set():
                req.error = msg
                req.t_done = self._clock()
                req.done.set()
                obs_metrics.SERVE_REQUESTS.inc(status="error")

    # -- admission / prefill ----------------------------------------------

    def _prefill_fn(self, rows: int, width: int) -> Callable:
        fn = self._prefill_fns.get((rows, width))
        if fn is None:
            donate = (4,) if jax.default_backend() != "cpu" else ()
            params_c, cfg_c = self._params, self._cfg

            def _prefill(tokens, prefix_lens, suffix_lens, tables, pools, seeds, temps):  # noqa: ANN001
                # sampling key is a function of the *absolute* position of
                # the last prompt token, so a cache-hit suffix prefill
                # draws the same first token a cold prefill would
                keys = _fold_keys(seeds, prefix_lens + suffix_lens - 1)
                return gen.paged_prefill_chunk(
                    params_c,
                    tokens,
                    prefix_lens,
                    suffix_lens,
                    tables,
                    pools,
                    cfg_c,
                    keys,
                    temps,
                )

            fn = jax.jit(_prefill, donate_argnums=donate)
            self._prefill_fns[(rows, width)] = fn
        return fn

    def _bucket_width(self, plen: int) -> int:
        return min(
            max(self.block_size, _next_pow2(plen)),
            _next_pow2(self._cfg.max_seq),
        )

    def _alloc_pressure(self, n: int) -> Optional[list[int]]:
        """:meth:`BlockAllocator.alloc` that spills cache-only blocks
        first: under pool pressure, LRU prefix-cache entries are cheaper
        to reclaim than preempting a live slot."""
        blocks = self.alloc.alloc(n)
        if blocks is None and self.prefix_cache is not None:
            self.prefix_cache.evict(n - self.alloc.free_blocks)
            blocks = self.alloc.alloc(n)
        return blocks

    def _admit(self) -> bool:
        free_slots = [i for i, s in enumerate(self._slots) if s is None]
        if not free_slots:
            return False
        admitted: list[_Admit] = []
        with self._lock:
            if not self._waiting:
                return False
            width: Optional[int] = None
            limit = min(len(free_slots), self.max_prefill_batch)
            for req in list(self._waiting):
                if len(admitted) >= limit:
                    break
                toks = list(req.prompt) + req.generated
                cached_blocks: list[int] = []
                cached_tokens = 0
                if self.prefix_cache is not None:
                    # retains the matched blocks on our behalf; never
                    # covers the last token, so suffix_len >= 1
                    cached_blocks, cached_tokens = self.prefix_cache.match(toks)
                suffix_len = len(toks) - cached_tokens
                w = self._bucket_width(suffix_len)
                if width is None:
                    width = w  # head of queue picks this round's bucket
                if w != width:
                    if cached_blocks:
                        self.alloc.release(cached_blocks)
                    continue
                need = math.ceil(len(toks) / self.block_size) - len(cached_blocks)
                new_blocks = self._alloc_pressure(need)
                if new_blocks is None:
                    if cached_blocks:
                        self.alloc.release(cached_blocks)
                    break  # pool pressure: admit what fits, retry later
                admitted.append(
                    _Admit(req, toks, cached_blocks, cached_tokens, new_blocks)
                )
            for a in admitted:
                self._waiting.remove(a.req)
            # visible to drain(): popped but not yet in a slot/completed
            self._prefilling += len(admitted)
            obs_metrics.SERVE_QUEUE_DEPTH.set(len(self._waiting))
        if not admitted:
            return False

        rows = _next_pow2(len(admitted))
        tokens = np.zeros((rows, width), np.int32)
        prefix_lens = np.zeros((rows,), np.int32)
        suffix_lens = np.ones((rows,), np.int32)
        tables_rows = np.full((rows, self.blocks_per_slot), TRASH_BLOCK, np.int32)
        seeds = np.zeros((rows,), np.int32)
        temps = np.zeros((rows,), np.float32)
        cached_total = 0
        for r, a in enumerate(admitted):
            blocks = a.cached_blocks + a.new_blocks
            sfx = a.toks[a.cached_tokens :]
            tokens[r, : len(sfx)] = sfx
            prefix_lens[r] = a.cached_tokens
            suffix_lens[r] = len(sfx)
            tables_rows[r, : len(blocks)] = blocks
            seeds[r] = np.int32(np.uint32(a.req.seed & 0xFFFFFFFF))
            temps[r] = a.req.temperature
            cached_total += a.cached_tokens

        with obs_trace.span(
            "serve.prefill",
            rows=len(admitted),
            width=width,
            cached_tokens=cached_total,
        ):
            fn = self._prefill_fn(rows, width)
            first, self.pools = fn(
                jnp.asarray(tokens),
                jnp.asarray(prefix_lens),
                jnp.asarray(suffix_lens),
                jnp.asarray(tables_rows),
                self.pools,
                jnp.asarray(seeds),
                jnp.asarray(temps),
            )
            first = np.asarray(first)

        now = self._clock()
        for r, a in enumerate(admitted):
            req = a.req
            blocks = a.cached_blocks + a.new_blocks
            resumed = bool(req.generated)  # preempted earlier; TTFT already set
            tok = int(first[r])
            req.generated.append(tok)
            if not resumed:
                req.t_first = now
                obs_metrics.SERVE_TTFT_SECONDS.observe(req.ttft_s)
            obs_metrics.SERVE_TOKENS.inc(phase="prefill")
            self.tokens_out += 1
            # index the freshly computed full blocks while they're valid —
            # the next same-prefix request prefills only its tail
            if self.prefix_cache is not None:
                self.prefix_cache.insert(a.toks, blocks)
            if req.prefill_only:
                # a request its first token already finishes never needs
                # the decode side: no handoff, the caller reads .tokens
                if not self._finished(req, tok):
                    req.handoff = self._export_handoff(req, a.toks, blocks)
                self.alloc.release(blocks)
                self._complete(req, now)
                continue
            if self._finished(req, tok):
                self.alloc.release(blocks)
                self._complete(req, now)
                continue
            slot = free_slots.pop(0)
            self.tables.assign(slot, blocks)
            self.tables.lengths[slot] = len(a.toks)
            self._slots[slot] = _SlotState(
                req=req,
                cache_len=len(a.toks),
                last_tok=tok,
                admit_seq=next(self._admit_counter),
            )
        with self._lock:
            self._prefilling -= len(admitted)
        self._update_gauges()
        return True

    def _export_handoff(
        self, req: ServeRequest, toks: list[int], blocks: list[int]
    ) -> KvPayload:
        """Snapshot the prefilled K/V blocks for transfer to a decode
        replica (the ``prefill_only`` completion path)."""
        idx = np.asarray(blocks, np.int32)
        return KvPayload(
            request_id=new_request_id(),
            tokens=list(toks),
            generated=list(req.generated),
            cache_len=len(toks),
            max_new_tokens=req.max_new_tokens,
            temperature=req.temperature,
            seed=req.seed,
            eos_id=req.eos_id,
            block_size=self.block_size,
            k=np.asarray(self.pools["k"][:, idx]),
            v=np.asarray(self.pools["v"][:, idx]),
        )

    # -- decode ------------------------------------------------------------

    def _finished(self, req: ServeRequest, tok: int) -> bool:
        return len(req.generated) >= req.max_new_tokens or (
            req.eos_id is not None and tok == req.eos_id
        )

    def _complete(self, req: ServeRequest, now: float) -> None:
        req.t_done = now
        req.done.set()
        self.requests_done += 1
        obs_metrics.SERVE_REQUESTS.inc(status="ok")
        if len(req.generated) > 1:
            obs_metrics.SERVE_TPOT_SECONDS.observe(req.tpot_s)

    def _preempt_youngest(self) -> bool:
        victims = [
            (st.admit_seq, i) for i, st in enumerate(self._slots) if st is not None
        ]
        if not victims:
            return False
        _, slot = max(victims)
        st = self._slots[slot]
        self._slots[slot] = None
        self.alloc.free(self.tables.release(slot))
        with self._lock:
            self._waiting.appendleft(st.req)  # resumes via re-prefill
            obs_metrics.SERVE_QUEUE_DEPTH.set(len(self._waiting))
        obs_metrics.SERVE_PREEMPTIONS.inc()
        return True

    def _copy_block(self, src: int, dst: int) -> None:
        """Device-side copy of one physical block across all layers."""
        self.pools = {
            "k": self.pools["k"].at[:, dst].set(self.pools["k"][:, src]),
            "v": self.pools["v"].at[:, dst].set(self.pools["v"][:, src]),
        }

    def _ensure_capacity(self, slot: int, write_pos: int) -> bool:
        """Make sure ``slot`` holds a *writable* block for ``write_pos``:
        grows the table lazily, copy-on-writes a shared tail block
        (another holder — cache or sibling slot — still reads it), and
        preempts the youngest slot under pool pressure. False if ``slot``
        itself was preempted away."""
        idx = write_pos // self.block_size
        while True:
            have = len(self.tables.blocks_of(slot))
            if have >= idx + 1:
                tail = self.tables.blocks_of(slot)[idx]
                if not self.alloc.is_shared(tail):
                    return True
                fresh = self._alloc_pressure(1)
                if fresh is not None:
                    self._copy_block(tail, fresh[0])
                    self.tables.replace_block(slot, idx, fresh[0])
                    self.alloc.release([tail])
                    obs_metrics.SERVE_COW_COPIES.inc()
                    return True
            else:
                blocks = self._alloc_pressure(idx + 1 - have)
                if blocks is not None:
                    self.tables.assign(slot, blocks)
                    continue  # re-check the (fresh, unshared) tail
            self._preempt_youngest()
            if self._slots[slot] is None:
                return False  # preempted ourselves: nothing else to evict

    def _decode_once(self) -> bool:
        active = [(i, st) for i, st in enumerate(self._slots) if st is not None]
        if not active:
            return False
        for slot, st in active:
            if self._slots[slot] is None:
                continue  # preempted by an earlier slot's capacity grab
            self._ensure_capacity(slot, st.cache_len)

        tokens = np.zeros((self.max_slots,), np.int32)
        positions = np.zeros((self.max_slots,), np.int32)
        seeds = np.zeros((self.max_slots,), np.int32)
        temps = np.zeros((self.max_slots,), np.float32)
        stepping: list[tuple[int, _SlotState]] = []
        for slot, st in enumerate(self._slots):
            if st is None:
                continue
            tokens[slot] = st.last_tok
            positions[slot] = st.cache_len
            seeds[slot] = np.int32(np.uint32(st.req.seed & 0xFFFFFFFF))
            temps[slot] = st.req.temperature
            stepping.append((slot, st))
        if not stepping:
            return False

        nxt, self.pools = self._decode(
            jnp.asarray(tokens),
            jnp.asarray(positions),
            jnp.asarray(self.tables.tables),
            self.pools,
            jnp.asarray(seeds),
            jnp.asarray(temps),
        )
        nxt = np.asarray(nxt)
        self.steps += 1

        now = self._clock()
        for slot, st in stepping:
            st.cache_len += 1
            self.tables.lengths[slot] = st.cache_len
            tok = int(nxt[slot])
            st.last_tok = tok
            st.req.generated.append(tok)
            self.tokens_out += 1
            obs_metrics.SERVE_TOKENS.inc(phase="decode")
            if self._finished(st.req, tok):
                self._slots[slot] = None
                blocks = self.tables.release(slot)
                if self.prefix_cache is not None:
                    # index the completed sequence's full blocks (cache
                    # holds cache_len tokens: everything but the final
                    # sampled token) before dropping the slot's refs
                    seq = list(st.req.prompt) + st.req.generated
                    self.prefix_cache.insert(seq[: st.cache_len], blocks)
                self.alloc.release(blocks)
                self._complete(st.req, now)
        self._update_gauges()
        self._steps_since_beat += 1
        if self._steps_since_beat >= 64:
            self._steps_since_beat = 0
            obs_trace.heartbeat(
                "serve.window",
                steps=self.steps,
                tokens=self.tokens_out,
                requests=self.requests_done,
            )
        return True

    def _update_gauges(self) -> None:
        active = sum(1 for s in self._slots if s is not None)
        obs_metrics.SERVE_SLOTS_ACTIVE.set(active)
        obs_metrics.SERVE_OCCUPANCY.set(active / self.max_slots)
        obs_metrics.SERVE_KV_BLOCKS_USED.set(self.alloc.used_blocks)


def serve_kv_payload(
    engine: ServeEngine,
    payload: KvPayload,
    timeout: Optional[float] = None,
) -> dict:
    """Decode-replica handler for one transferred prefill: admit the
    payload via :meth:`ServeEngine.submit_prefilled`, wait for
    completion, and return the transport reply. The ``/v1/kv`` endpoint
    and the file-spool pump both route here; :class:`EngineStopped`
    (draining) propagates as
    :class:`~torchx_tpu.serve.kv_transfer.TransferRejected` so the
    prefill side requeues."""
    from torchx_tpu.serve.kv_transfer import TransferRejected

    if payload.block_size != engine.block_size:
        raise ValueError(
            f"payload block_size {payload.block_size} != engine "
            f"block_size {engine.block_size}"
        )
    req = ServeRequest(
        prompt=list(payload.tokens),
        max_new_tokens=payload.max_new_tokens,
        temperature=payload.temperature,
        seed=payload.seed,
        eos_id=payload.eos_id,
        generated=list(payload.generated),
    )
    try:
        engine.submit_prefilled(
            req,
            payload.k,
            payload.v,
            payload.cache_len,
            last_tok=payload.generated[-1],
        )
    except EngineStopped as e:
        raise TransferRejected(str(e)) from e
    if not req.wait(timeout):
        raise TimeoutError(
            f"transferred request {payload.request_id} did not finish"
        )
    if req.error:
        raise RuntimeError(req.error)
    return {"request_id": payload.request_id, "tokens": req.generated}
