"""Continuous-batching decode engine over a paged KV cache.

The batch-to-completion server (`apps/generate_server.py`'s coalescing
batcher) decodes every admitted batch to its full ``max_new_tokens``
before the next batch starts: a request arriving mid-decode waits out the
whole window, and slots whose sequences finish early idle until the
stragglers do. Decode on TPU is HBM-bandwidth-bound, so throughput is
(occupied slots) x (step rate) — idle slots are thrown-away bandwidth.

This engine keeps a **fixed slot array** decoding continuously:

* one jitted :func:`torchx_tpu.models.generate.paged_decode_step` per
  engine — static ``[max_slots]`` shapes, XLA compiles once regardless of
  which requests occupy the slots;
* **admission** between steps: waiting requests are prefilled in
  width-bucketed groups (a handful of compiles total) and dropped into
  free slots, with KV blocks allocated from the shared paged pool
  (:mod:`torchx_tpu.serve.kv_pool`);
* **eviction** per step: a slot that hits EOS or its token budget
  completes immediately — its caller unblocks, its blocks return to the
  pool, and the slot is free for the next admission *that same step*;
* **preemption** under pool pressure: if a mid-decode slot can't get its
  next block, the youngest slot is evicted back to the wait queue (its
  finished tokens kept; decode resumes exactly — sampling keys are a pure
  function of (seed, position)).

Requests carry per-sequence temperature, seed, and EOS, so unrelated
requests share every device step. The engine emits ``serve.*`` spans /
heartbeats and ``tpx_serve_*`` metrics through the obs registry.
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import math
import threading
import time
from collections import deque
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from torchx_tpu.models import generate as gen
from torchx_tpu.models import llama
from torchx_tpu.obs import metrics as obs_metrics
from torchx_tpu.obs import trace as obs_trace
from torchx_tpu.ops.paged_attention import TRASH_BLOCK
from torchx_tpu.serve.kv_pool import BlockAllocator, PoolPlan, SlotTables

logger = logging.getLogger(__name__)

__all__ = ["ServeRequest", "ServeEngine", "EngineStopped"]


class EngineStopped(RuntimeError):
    """Raised by :meth:`ServeEngine.submit` once the engine is draining or
    stopped — the SIGTERM drain path returns 503s off this."""


@dataclasses.dataclass
class ServeRequest:
    """One generation request moving through the engine.

    Callers fill the first block and :meth:`wait`; the engine appends to
    ``generated`` as tokens decode and sets ``done`` at completion.
    Timing: ``ttft_s`` is enqueue -> first token, ``tpot_s`` the mean gap
    between subsequent tokens — the two serving-latency axes.
    """

    prompt: Sequence[int]
    max_new_tokens: int
    temperature: float = 0.0
    seed: int = 0
    eos_id: Optional[int] = None

    generated: list[int] = dataclasses.field(default_factory=list)
    error: Optional[str] = None
    done: threading.Event = dataclasses.field(default_factory=threading.Event)
    t_enqueue: float = 0.0
    t_first: float = 0.0
    t_done: float = 0.0

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until the request completes (True) or ``timeout`` (False)."""
        return self.done.wait(timeout)

    @property
    def tokens(self) -> list[int]:
        """prompt + generated, the full sequence."""
        return list(self.prompt) + self.generated

    @property
    def ttft_s(self) -> float:
        """Seconds from enqueue to first generated token."""
        return max(0.0, self.t_first - self.t_enqueue)

    @property
    def tpot_s(self) -> float:
        """Mean seconds per generated token after the first."""
        n = len(self.generated)
        if n <= 1:
            return 0.0
        return max(0.0, self.t_done - self.t_first) / (n - 1)


@dataclasses.dataclass
class _SlotState:
    req: ServeRequest
    cache_len: int  # tokens currently in the KV cache for this sequence
    last_tok: int  # most recent sampled token (next step's input)
    admit_seq: int  # admission order; highest = youngest = preemption victim


def _next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1)).bit_length()


def _fold_keys(seeds: jnp.ndarray, sample_pos: jnp.ndarray) -> jnp.ndarray:
    # per-row sampling key = f(seed, position of the last token read):
    # pure, so decode resumed after preemption draws the same tokens
    base = jax.vmap(jax.random.PRNGKey)(seeds)
    return jax.vmap(jax.random.fold_in)(base, sample_pos)


class ServeEngine:
    """The continuous-batching serving engine (see module docstring).

    ``max_slots``/``block_size``/``num_blocks`` fix the compiled geometry;
    pass a :class:`~torchx_tpu.serve.kv_pool.PoolPlan` (from
    :func:`~torchx_tpu.serve.kv_pool.plan_pool`) via :meth:`from_plan` to
    size them against real HBM. The default ``num_blocks`` gives every
    slot a half-``max_seq`` budget — mild oversubscription; the preemption
    path covers the tail.
    """

    def __init__(
        self,
        params: llama.Params,
        cfg: llama.LlamaConfig,
        *,
        max_slots: int = 8,
        block_size: int = 16,
        num_blocks: Optional[int] = None,
        max_prefill_batch: int = 4,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if block_size & (block_size - 1):
            raise ValueError(f"block_size must be a power of 2, got {block_size}")
        self._params = params
        self._cfg = cfg
        self.max_slots = max_slots
        self.block_size = block_size
        self.blocks_per_slot = math.ceil(cfg.max_seq / block_size)
        if num_blocks is None:
            num_blocks = 1 + max_slots * max(1, self.blocks_per_slot // 2)
        if num_blocks < self.blocks_per_slot + 1:
            raise ValueError(
                f"num_blocks={num_blocks} cannot hold one max_seq sequence"
                f" ({self.blocks_per_slot} blocks + trash)"
            )
        self.num_blocks = num_blocks
        self.max_prefill_batch = max(1, max_prefill_batch)
        self._clock = clock

        self.pools = gen.init_kv_pools(cfg, num_blocks, block_size)
        self.alloc = BlockAllocator(num_blocks)
        self.tables = SlotTables(max_slots, self.blocks_per_slot)
        self._slots: list[Optional[_SlotState]] = [None] * max_slots
        self._admit_counter = itertools.count()

        self._lock = threading.Lock()
        self._waiting: deque[ServeRequest] = deque()
        self._prefilling = 0  # popped from _waiting, not yet slotted/done
        self._work = threading.Event()
        self._stop = threading.Event()
        self._draining = False
        self._thread: Optional[threading.Thread] = None
        self.requests_done = 0
        self.tokens_out = 0
        self.steps = 0
        self._steps_since_beat = 0

        # one compiled decode step for the engine's lifetime; donation lets
        # XLA update the pools in place (no-op on CPU, where jax warns —
        # so only donate off-CPU)
        donate = (3,) if jax.default_backend() != "cpu" else ()
        params_c, cfg_c = self._params, self._cfg

        def _decode(tokens, positions, tables, pools, seeds, temps):  # noqa: ANN001
            keys = _fold_keys(seeds, positions)
            return gen.paged_decode_step(
                params_c, tokens, positions, tables, pools, cfg_c, keys, temps
            )

        self._decode = jax.jit(_decode, donate_argnums=donate)
        self._prefill_fns: dict[tuple[int, int], Callable] = {}

    @classmethod
    def from_plan(
        cls,
        params: llama.Params,
        cfg: llama.LlamaConfig,
        plan: PoolPlan,
        **kwargs,
    ) -> "ServeEngine":
        """Build an engine with the geometry a :func:`plan_pool` sizing
        chose for the HBM budget."""
        return cls(
            params,
            cfg,
            max_slots=plan.max_slots,
            block_size=plan.block_size,
            num_blocks=plan.num_blocks,
            **kwargs,
        )

    # -- public API --------------------------------------------------------

    def start(self) -> "ServeEngine":
        """Spawn the engine loop thread (idempotent)."""
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="serve-engine", daemon=True
            )
            self._thread.start()
        return self

    def submit(self, req: ServeRequest) -> ServeRequest:
        """Enqueue a request for admission; raises :class:`EngineStopped`
        when draining/stopped, ValueError when it can never fit."""
        total = len(req.prompt) + req.max_new_tokens
        if total > self._cfg.max_seq:
            raise ValueError(
                f"prompt + new tokens ({total}) exceeds max_seq"
                f" {self._cfg.max_seq}"
            )
        if req.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        with self._lock:
            if self._draining or self._stop.is_set():
                raise EngineStopped("engine is draining; not admitting requests")
            req.t_enqueue = self._clock()
            self._waiting.append(req)
            obs_metrics.SERVE_QUEUE_DEPTH.set(len(self._waiting))
        self._work.set()
        return req

    def generate(
        self,
        prompt: Sequence[int],
        max_new_tokens: int,
        temperature: float = 0.0,
        seed: int = 0,
        eos_id: Optional[int] = None,
        timeout: Optional[float] = None,
    ) -> ServeRequest:
        """Submit and block until done — the one-call convenience path."""
        req = ServeRequest(
            prompt=list(prompt),
            max_new_tokens=max_new_tokens,
            temperature=temperature,
            seed=seed,
            eos_id=eos_id,
        )
        self.submit(req)
        if not req.wait(timeout):
            raise TimeoutError(f"generation did not finish in {timeout}s")
        if req.error:
            raise RuntimeError(req.error)
        return req

    def stats(self) -> dict:
        """Engine occupancy/queue snapshot (feeds ``/healthz`` and the
        serve pool's load probe)."""
        with self._lock:
            active = sum(1 for s in self._slots if s is not None)
            return {
                "active_slots": active,
                "max_slots": self.max_slots,
                "occupancy": active / self.max_slots,
                "queue_depth": len(self._waiting),
                "kv_blocks_used": self.alloc.used_blocks,
                "kv_blocks_free": self.alloc.free_blocks,
                "requests_done": self.requests_done,
                "tokens_out": self.tokens_out,
                "steps": self.steps,
                "draining": self._draining,
            }

    @property
    def queue_depth(self) -> int:
        """Requests waiting for a slot (the autoscaler's primary signal)."""
        with self._lock:
            return len(self._waiting)

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Stop admitting, finish everything in flight, return True when
        empty (False on timeout). The SIGTERM grace path."""
        with self._lock:
            self._draining = True
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._lock:
                empty = (
                    not self._waiting
                    and self._prefilling == 0
                    and all(s is None for s in self._slots)
                )
            if empty:
                return True
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(0.005)

    def stop(self, timeout: float = 5.0) -> None:
        """Kill the loop thread; in-flight requests get ``error`` set."""
        self._stop.set()
        self._work.set()
        if self._thread is not None:
            self._thread.join(timeout)
        self._fail_all("engine stopped")

    # -- engine loop -------------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                worked = self._admit()
                worked = self._decode_once() or worked
            except Exception as e:  # noqa: BLE001 — a step bug must not hang callers
                logger.exception("serve engine step failed")
                self._fail_all(f"engine step failed: {e}")
                return
            if not worked:
                self._work.wait(0.002)
                self._work.clear()

    def _fail_all(self, msg: str) -> None:
        with self._lock:
            pending = list(self._waiting)
            self._waiting.clear()
            self._prefilling = 0
        for i, st in enumerate(self._slots):
            if st is not None:
                self._slots[i] = None
                pending.append(st.req)
        for req in pending:
            if not req.done.is_set():
                req.error = msg
                req.t_done = self._clock()
                req.done.set()
                obs_metrics.SERVE_REQUESTS.inc(status="error")

    # -- admission / prefill ----------------------------------------------

    def _prefill_fn(self, rows: int, width: int) -> Callable:
        fn = self._prefill_fns.get((rows, width))
        if fn is None:
            donate = (3,) if jax.default_backend() != "cpu" else ()
            params_c, cfg_c = self._params, self._cfg

            def _prefill(prompts, true_lens, block_ids, pools, seeds, temps):  # noqa: ANN001
                keys = _fold_keys(seeds, true_lens - 1)
                return gen.paged_prefill(
                    params_c, prompts, true_lens, block_ids, pools, cfg_c, keys, temps
                )

            fn = jax.jit(_prefill, donate_argnums=donate)
            self._prefill_fns[(rows, width)] = fn
        return fn

    def _bucket_width(self, plen: int) -> int:
        return min(
            max(self.block_size, _next_pow2(plen)),
            _next_pow2(self._cfg.max_seq),
        )

    def _admit(self) -> bool:
        free_slots = [i for i, s in enumerate(self._slots) if s is None]
        if not free_slots:
            return False
        with self._lock:
            if not self._waiting:
                return False
            head = self._waiting[0]
            width = self._bucket_width(len(head.prompt) + len(head.generated))
            group: list[ServeRequest] = []
            limit = min(len(free_slots), self.max_prefill_batch)
            for req in list(self._waiting):
                if len(group) >= limit:
                    break
                plen = len(req.prompt) + len(req.generated)
                if self._bucket_width(plen) != width:
                    continue
                group.append(req)
            # blocks to hold each prompt now (+1-token headroom comes
            # lazily during decode)
            admitted: list[tuple[ServeRequest, list[int]]] = []
            for req in group:
                plen = len(req.prompt) + len(req.generated)
                blocks = self.alloc.alloc(math.ceil(plen / self.block_size))
                if blocks is None:
                    break  # pool pressure: admit what fits, retry later
                admitted.append((req, blocks))
            for req, _ in admitted:
                self._waiting.remove(req)
            # visible to drain(): popped but not yet in a slot/completed
            self._prefilling += len(admitted)
            obs_metrics.SERVE_QUEUE_DEPTH.set(len(self._waiting))
        if not admitted:
            return False

        rows = _next_pow2(len(admitted))
        nb_bucket = width // self.block_size
        prompts = np.zeros((rows, width), np.int32)
        true_lens = np.ones((rows,), np.int32)
        block_ids = np.full((rows, nb_bucket), TRASH_BLOCK, np.int32)
        seeds = np.zeros((rows,), np.int32)
        temps = np.zeros((rows,), np.float32)
        for r, (req, blocks) in enumerate(admitted):
            toks = list(req.prompt) + req.generated
            prompts[r, : len(toks)] = toks
            true_lens[r] = len(toks)
            block_ids[r, : len(blocks)] = blocks
            seeds[r] = np.int32(np.uint32(req.seed & 0xFFFFFFFF))
            temps[r] = req.temperature

        with obs_trace.span("serve.prefill", rows=len(admitted), width=width):
            fn = self._prefill_fn(rows, width)
            first, self.pools = fn(
                jnp.asarray(prompts),
                jnp.asarray(true_lens),
                jnp.asarray(block_ids),
                self.pools,
                jnp.asarray(seeds),
                jnp.asarray(temps),
            )
            first = np.asarray(first)

        now = self._clock()
        for r, (req, blocks) in enumerate(admitted):
            resumed = bool(req.generated)  # preempted earlier; TTFT already set
            tok = int(first[r])
            req.generated.append(tok)
            if not resumed:
                req.t_first = now
                obs_metrics.SERVE_TTFT_SECONDS.observe(req.ttft_s)
            obs_metrics.SERVE_TOKENS.inc(phase="prefill")
            self.tokens_out += 1
            if self._finished(req, tok):
                self.alloc.free(blocks)
                self._complete(req, now)
                continue
            slot = free_slots.pop(0)
            self.tables.assign(slot, blocks)
            self.tables.lengths[slot] = true_lens[r]
            self._slots[slot] = _SlotState(
                req=req,
                cache_len=int(true_lens[r]),
                last_tok=tok,
                admit_seq=next(self._admit_counter),
            )
        with self._lock:
            self._prefilling -= len(admitted)
        self._update_gauges()
        return True

    # -- decode ------------------------------------------------------------

    def _finished(self, req: ServeRequest, tok: int) -> bool:
        return len(req.generated) >= req.max_new_tokens or (
            req.eos_id is not None and tok == req.eos_id
        )

    def _complete(self, req: ServeRequest, now: float) -> None:
        req.t_done = now
        req.done.set()
        self.requests_done += 1
        obs_metrics.SERVE_REQUESTS.inc(status="ok")
        if len(req.generated) > 1:
            obs_metrics.SERVE_TPOT_SECONDS.observe(req.tpot_s)

    def _preempt_youngest(self) -> bool:
        victims = [
            (st.admit_seq, i) for i, st in enumerate(self._slots) if st is not None
        ]
        if not victims:
            return False
        _, slot = max(victims)
        st = self._slots[slot]
        self._slots[slot] = None
        self.alloc.free(self.tables.release(slot))
        with self._lock:
            self._waiting.appendleft(st.req)  # resumes via re-prefill
            obs_metrics.SERVE_QUEUE_DEPTH.set(len(self._waiting))
        obs_metrics.SERVE_PREEMPTIONS.inc()
        return True

    def _ensure_capacity(self, slot: int, write_pos: int) -> bool:
        """Make sure ``slot`` holds a block for ``write_pos``; preempts the
        youngest slot under pool pressure. False if ``slot`` itself was
        preempted away."""
        while True:
            need = write_pos // self.block_size + 1
            have = len(self.tables.blocks_of(slot))
            if have >= need:
                return True
            blocks = self.alloc.alloc(need - have)
            if blocks is not None:
                self.tables.assign(slot, blocks)
                return True
            self._preempt_youngest()
            if self._slots[slot] is None:
                return False  # preempted ourselves: nothing else to evict

    def _decode_once(self) -> bool:
        active = [(i, st) for i, st in enumerate(self._slots) if st is not None]
        if not active:
            return False
        for slot, st in active:
            if self._slots[slot] is None:
                continue  # preempted by an earlier slot's capacity grab
            self._ensure_capacity(slot, st.cache_len)

        tokens = np.zeros((self.max_slots,), np.int32)
        positions = np.zeros((self.max_slots,), np.int32)
        seeds = np.zeros((self.max_slots,), np.int32)
        temps = np.zeros((self.max_slots,), np.float32)
        stepping: list[tuple[int, _SlotState]] = []
        for slot, st in enumerate(self._slots):
            if st is None:
                continue
            tokens[slot] = st.last_tok
            positions[slot] = st.cache_len
            seeds[slot] = np.int32(np.uint32(st.req.seed & 0xFFFFFFFF))
            temps[slot] = st.req.temperature
            stepping.append((slot, st))
        if not stepping:
            return False

        nxt, self.pools = self._decode(
            jnp.asarray(tokens),
            jnp.asarray(positions),
            jnp.asarray(self.tables.tables),
            self.pools,
            jnp.asarray(seeds),
            jnp.asarray(temps),
        )
        nxt = np.asarray(nxt)
        self.steps += 1

        now = self._clock()
        for slot, st in stepping:
            st.cache_len += 1
            self.tables.lengths[slot] = st.cache_len
            tok = int(nxt[slot])
            st.last_tok = tok
            st.req.generated.append(tok)
            self.tokens_out += 1
            obs_metrics.SERVE_TOKENS.inc(phase="decode")
            if self._finished(st.req, tok):
                self._slots[slot] = None
                self.alloc.free(self.tables.release(slot))
                self._complete(st.req, now)
        self._update_gauges()
        self._steps_since_beat += 1
        if self._steps_since_beat >= 64:
            self._steps_since_beat = 0
            obs_trace.heartbeat(
                "serve.window",
                steps=self.steps,
                tokens=self.tokens_out,
                requests=self.requests_done,
            )
        return True

    def _update_gauges(self) -> None:
        active = sum(1 for s in self._slots if s is not None)
        obs_metrics.SERVE_SLOTS_ACTIVE.set(active)
        obs_metrics.SERVE_OCCUPANCY.set(active / self.max_slots)
        obs_metrics.SERVE_KV_BLOCKS_USED.set(self.alloc.used_blocks)
