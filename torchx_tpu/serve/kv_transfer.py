"""KV-block transfer seam between prefill and decode replicas.

Disaggregated serving splits the two phases of generation onto dedicated
replica gangs: prefill replicas (compute-bound, prefix-cache-warm) build
the KV state for a prompt, then *stream the computed blocks* to a decode
replica (HBM-bandwidth-bound) that carries the sequence to completion.
This module is the transport seam: a :class:`KvPayload` (tokens + the
``[L, n_blocks, bs, kvh, hd]`` K/V arrays the prefill engine exported)
moves through a :class:`KvTransfer` and the decode side's generated
tokens come back as the reply.

Three transports cover the current deployment shapes:

* :class:`LocalTransfer` — in-process handler dispatch (tests, the
  serving bench's equal-chip comparison);
* :class:`HttpTransfer` — POST the serialized payload to a decode
  replica's ``/v1/kv`` endpoint (the `generate_server` decode role);
* :class:`FileTransfer` — spool-directory handoff for co-located
  processes without a network path (write ``<id>.req.npz``, poll for
  ``<id>.resp.json``; :func:`serve_spool` is the decode-side pump).

A decode replica that is draining answers 503 / ``rejected`` — the
sender raises :class:`TransferRejected` and the prefill side **requeues
the handoff to the next decode target instead of dropping it** (the
disaggregated twin of the engine's ``_prefilling`` drain accounting).

The transfer *configuration* — ``TransferConfig``, serialized as a spec
string in role args (``--kv-transfer``) and AppDef role metadata
(:data:`ROLE_METADATA_KEY`) — is the reusable launcher-managed
inter-role machinery: the MPMD pipeline work reuses the same shape for
inter-stage activation transfer. The TPX213 submit rule enforces that a
prefill/decode role pair declares it.

Everything here is jax-free (numpy only) so the analyze/CLI layers can
import the config types.
"""

from __future__ import annotations

import contextlib
import dataclasses
import io
import json
import os
import threading
import time
import urllib.error
import urllib.request
import uuid
from typing import Callable, Optional, Sequence

import numpy as np

from torchx_tpu.obs import metrics as obs_metrics
from torchx_tpu.obs import trace as obs_trace

__all__ = [
    "ROLE_METADATA_KEY",
    "TransferConfig",
    "TransferRejected",
    "TransferError",
    "KvPayload",
    "stamp_trace",
    "payload_span",
    "KvTransfer",
    "LocalTransfer",
    "HttpTransfer",
    "FileTransfer",
    "serve_spool",
    "make_transfer",
    "new_request_id",
]

#: AppDef role-metadata key carrying the transfer spec — the launcher's
#: declaration that this role participates in inter-role KV streaming.
ROLE_METADATA_KEY = "tpx/kv_transfer"


class TransferRejected(RuntimeError):
    """The decode target refused the handoff (draining/stopping): the
    sender must requeue to another target, not drop the request."""


class TransferError(RuntimeError):
    """Transport-level failure (unreachable target, bad payload)."""


@dataclasses.dataclass
class KvPayload:
    """One prefilled sequence in flight from a prefill to a decode replica.

    ``tokens`` are the ``cache_len`` prompt tokens whose K/V fill
    ``k``/``v`` (``[L, n_blocks, block_size, kvh, hd]``, block-granular);
    ``generated`` holds what prefill already sampled (the first token),
    and the sampling parameters let decode continue the exact PRNG
    stream — per-position fold-in keys make the handoff seamless.
    """

    request_id: str
    tokens: list[int]
    generated: list[int]
    cache_len: int
    max_new_tokens: int
    temperature: float
    seed: int
    eos_id: Optional[int]
    block_size: int
    k: np.ndarray
    v: np.ndarray
    # originating trace context: the decode side opens its spans inside
    # this trace, so router -> prefill -> transfer -> decode stitches
    # into ONE timeline. Defaults keep pre-trace payloads deserializable.
    trace_id: str = ""
    parent_span_id: str = ""

    def meta(self) -> dict:
        """The JSON-scalar side of the payload (everything but K/V)."""
        return {
            "request_id": self.request_id,
            "tokens": self.tokens,
            "generated": self.generated,
            "cache_len": self.cache_len,
            "max_new_tokens": self.max_new_tokens,
            "temperature": self.temperature,
            "seed": self.seed,
            "eos_id": self.eos_id,
            "block_size": self.block_size,
            "trace_id": self.trace_id,
            "parent_span_id": self.parent_span_id,
        }

    def to_bytes(self) -> bytes:
        """npz-serialize (meta as a JSON scalar array + the K/V blocks)."""
        buf = io.BytesIO()
        np.savez(
            buf,
            meta=np.frombuffer(
                json.dumps(self.meta()).encode(), dtype=np.uint8
            ),
            k=self.k,
            v=self.v,
        )
        return buf.getvalue()

    @classmethod
    def from_bytes(cls, raw: bytes) -> "KvPayload":
        """Inverse of :meth:`to_bytes` (pickle-free npz load)."""
        with np.load(io.BytesIO(raw), allow_pickle=False) as z:
            meta = json.loads(z["meta"].tobytes().decode())
            k, v = z["k"], z["v"]
        return cls(k=k, v=v, **meta)


def stamp_trace(payload: KvPayload) -> KvPayload:
    """Fill the payload's trace context from the ambient one (no-op on
    already-stamped payloads): the prefill side calls this right before
    :meth:`KvTransfer.send` so the decode replica joins the request's
    trace. Returns the payload for chaining."""
    if not payload.trace_id:
        payload.trace_id = obs_trace.current_trace_id() or ""
    if not payload.parent_span_id:
        payload.parent_span_id = obs_trace.current_span_id() or ""
    return payload


@contextlib.contextmanager
def payload_span(payload: KvPayload, name: str, **attrs):
    """Open span ``name`` inside the payload's originating trace context
    — the decode-side (and transfer-side) hook that makes a cross-process
    handoff one stitched trace. Yields the open span (or None)."""
    with obs_trace.trace_context(
        payload.trace_id or None, payload.parent_span_id or None
    ):
        with obs_trace.span(
            name, request_id=payload.request_id, **attrs
        ) as sp:
            yield sp


@dataclasses.dataclass(frozen=True)
class TransferConfig:
    """Declared shape of a prefill->decode transfer path.

    Spec grammar (role args / metadata):

    * ``local`` — in-process (tests/bench);
    * ``file:/var/spool/tpx-kv`` — spool directory;
    * ``http:http://127.0.0.1:8100,http://127.0.0.1:8101`` — decode
      replica base URLs, tried in order on rejection.
    """

    mode: str = "local"
    endpoints: tuple[str, ...] = ()

    @classmethod
    def from_spec(cls, spec: str) -> "TransferConfig":
        """Parse a spec string (see the class grammar); raises
        ``ValueError`` on an unknown mode or empty endpoint list."""
        spec = (spec or "").strip()
        if not spec or spec == "local":
            return cls(mode="local")
        if spec.startswith("file:"):
            return cls(mode="file", endpoints=(spec[len("file:") :],))
        if spec.startswith("http:"):
            urls = tuple(
                u if "://" in u else f"http://{u}"
                for u in spec[len("http:") :].split(",")
                if u
            )
            if not urls:
                raise ValueError(f"http transfer spec has no endpoints: {spec!r}")
            return cls(mode="http", endpoints=urls)
        raise ValueError(
            f"unknown kv-transfer spec {spec!r} (expected local | "
            f"file:<dir> | http:<url>[,<url>...])"
        )

    def to_spec(self) -> str:
        """Serialize back to the spec grammar (``from_spec`` inverse)."""
        if self.mode == "local":
            return "local"
        if self.mode == "file":
            return f"file:{self.endpoints[0]}"
        return "http:" + ",".join(self.endpoints)


class KvTransfer:
    """Transport seam: targets + synchronous transfer with reply."""

    def targets(self) -> list[str]:
        """Decode targets, in preference order."""
        raise NotImplementedError

    def transfer(self, payload: KvPayload, target: str, timeout: float = 60.0) -> dict:
        """Deliver ``payload`` to ``target`` and return the decode
        result (``{"tokens": [...], ...}``). Raises
        :class:`TransferRejected` when the target is draining."""
        raise NotImplementedError

    def send(self, payload: KvPayload, timeout: float = 60.0) -> dict:
        """Transfer to the first accepting target, requeueing past
        draining/unreachable ones. The drain-race contract: a target
        that rejects mid-transfer costs a retry, never the request.
        Timed as a ``serve.kv_transfer`` span in the payload's
        originating trace."""
        stamp_trace(payload)
        with payload_span(payload, "serve.kv_transfer") as sp:
            last: Optional[Exception] = None
            for target in self.targets():
                try:
                    out = self.transfer(payload, target, timeout=timeout)
                    obs_metrics.SERVE_KV_TRANSFERS.inc(status="ok")
                    if sp is not None:
                        sp.attrs["target"] = str(target)
                    return out
                except TransferRejected as e:
                    obs_metrics.SERVE_KV_TRANSFERS.inc(status="rejected")
                    last = e
                except TransferError as e:
                    obs_metrics.SERVE_KV_TRANSFERS.inc(status="error")
                    last = e
            raise TransferError(
                f"no decode target accepted request"
                f" {payload.request_id}: {last}"
            )


class LocalTransfer(KvTransfer):
    """In-process transport: targets are named handler callables
    (``payload -> result dict``) that raise :class:`TransferRejected`
    themselves when draining."""

    def __init__(
        self, handlers: dict[str, Callable[[KvPayload], dict]]
    ) -> None:
        self._handlers = dict(handlers)

    def targets(self) -> list[str]:
        return list(self._handlers)

    def transfer(self, payload: KvPayload, target: str, timeout: float = 60.0) -> dict:
        obs_metrics.SERVE_KV_TRANSFER_BYTES.inc(
            payload.k.nbytes + payload.v.nbytes
        )
        return self._handlers[target](payload)


class HttpTransfer(KvTransfer):
    """POST the serialized payload to each decode replica's ``/v1/kv``."""

    def __init__(self, endpoints: Sequence[str]) -> None:
        self._endpoints = list(endpoints)

    def targets(self) -> list[str]:
        return list(self._endpoints)

    def transfer(self, payload: KvPayload, target: str, timeout: float = 60.0) -> dict:
        raw = payload.to_bytes()
        req = urllib.request.Request(
            f"{target.rstrip('/')}/v1/kv",
            data=raw,
            headers={"Content-Type": "application/octet-stream"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                obs_metrics.SERVE_KV_TRANSFER_BYTES.inc(len(raw))
                return json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            if e.code == 503:
                raise TransferRejected(f"{target} draining") from e
            raise TransferError(f"{target}: HTTP {e.code}") from e
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            raise TransferError(f"{target}: {e}") from e


class FileTransfer(KvTransfer):
    """Spool-directory transport: atomic ``<id>.req.npz`` writes, reply
    polled from ``<id>.resp.json`` (written by :func:`serve_spool`)."""

    def __init__(
        self,
        spool_dir: str,
        poll_s: float = 0.01,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ) -> None:
        self.spool_dir = spool_dir
        self.poll_s = poll_s
        self._clock = clock
        self._sleep = sleep
        os.makedirs(spool_dir, exist_ok=True)

    def targets(self) -> list[str]:
        return [self.spool_dir]

    def transfer(self, payload: KvPayload, target: str, timeout: float = 60.0) -> dict:
        raw = payload.to_bytes()
        base = os.path.join(target, payload.request_id)
        tmp = f"{base}.tmp"
        with open(tmp, "wb") as f:
            f.write(raw)
        os.replace(tmp, f"{base}.req.npz")  # atomic: readers never see partials
        obs_metrics.SERVE_KV_TRANSFER_BYTES.inc(len(raw))
        resp_path = f"{base}.resp.json"
        deadline = self._clock() + timeout
        while self._clock() < deadline:
            if os.path.exists(resp_path):
                with open(resp_path) as f:
                    out = json.load(f)
                os.unlink(resp_path)
                if out.get("rejected"):
                    raise TransferRejected(f"spool target draining: {target}")
                return out
            self._sleep(self.poll_s)
        raise TransferError(f"no spool reply for {payload.request_id} in {timeout}s")


def serve_spool(
    spool_dir: str,
    handler: Callable[[KvPayload], dict],
    stop: threading.Event,
    poll_s: float = 0.01,
) -> None:
    """Decode-side pump for :class:`FileTransfer`: consume ``*.req.npz``
    oldest-first, run ``handler``, write the ``.resp.json`` reply (a
    :class:`TransferRejected` from the handler becomes a ``rejected``
    reply so the sender requeues)."""
    os.makedirs(spool_dir, exist_ok=True)
    while not stop.is_set():
        reqs = sorted(
            f for f in os.listdir(spool_dir) if f.endswith(".req.npz")
        )
        if not reqs:
            stop.wait(poll_s)
            continue
        path = os.path.join(spool_dir, reqs[0])
        try:
            with open(path, "rb") as f:
                payload = KvPayload.from_bytes(f.read())
        finally:
            os.unlink(path)
        try:
            out = handler(payload)
        except TransferRejected:
            out = {"rejected": True}
        base = path[: -len(".req.npz")]
        tmp = f"{base}.resp.tmp"
        with open(tmp, "w") as f:
            json.dump(out, f)
        os.replace(tmp, f"{base}.resp.json")


def make_transfer(
    cfg: TransferConfig,
    handlers: Optional[dict[str, Callable[[KvPayload], dict]]] = None,
) -> KvTransfer:
    """Instantiate the transport a :class:`TransferConfig` declares
    (``handlers`` backs the ``local`` mode)."""
    if cfg.mode == "local":
        return LocalTransfer(handlers or {})
    if cfg.mode == "file":
        return FileTransfer(cfg.endpoints[0])
    if cfg.mode == "http":
        return HttpTransfer(cfg.endpoints)
    raise ValueError(f"unknown transfer mode {cfg.mode!r}")


def new_request_id() -> str:
    """Collision-free id for one handoff (spool filenames, tracing)."""
    return uuid.uuid4().hex
