"""Production serving runtime: continuous batching over a paged KV cache.

Three layers, bottom-up:

* :mod:`torchx_tpu.serve.kv_pool` — host-side paged KV-cache planning and
  block allocation (the device-side gather/scatter lives in
  :mod:`torchx_tpu.ops.paged_attention`);
* :mod:`torchx_tpu.serve.engine` — the continuous-batching decode engine:
  a fixed slot array XLA compiles once, per-step admission and eviction,
  bucketed prefill interleaved with decode;
* :mod:`torchx_tpu.serve.prefix_cache` — refcounted radix prefix cache
  over the pool: shared prompt prefixes resolve to shared physical
  blocks instead of recomputing (LRU-evicted under pool pressure);
* :mod:`torchx_tpu.serve.kv_transfer` — the prefill->decode KV-block
  transfer seam for disaggregated serving (local/HTTP/file transports;
  the ``TransferConfig`` shape AppDef roles carry);
* :mod:`torchx_tpu.serve.pool` — the launcher-driven serve pool:
  ``tpx serve-pool`` submits N ``generate_server`` replicas through the
  Runner, routes requests least-loaded (with a longest-cached-prefix
  bonus), and autoscales via ``Runner.resize`` on queue-depth/p99
  targets — one gang, or disaggregated prefill + decode gangs with
  independent policies.
"""

from torchx_tpu.serve.kv_pool import BlockAllocator, PoolPlan, plan_pool
from torchx_tpu.serve.kv_transfer import TransferConfig
from torchx_tpu.serve.prefix_cache import PrefixCache, prefix_chain

__all__ = [
    "BlockAllocator",
    "PoolPlan",
    "plan_pool",
    "PrefixCache",
    "prefix_chain",
    "TransferConfig",
]
