"""Production serving runtime: continuous batching over a paged KV cache.

Three layers, bottom-up:

* :mod:`torchx_tpu.serve.kv_pool` — host-side paged KV-cache planning and
  block allocation (the device-side gather/scatter lives in
  :mod:`torchx_tpu.ops.paged_attention`);
* :mod:`torchx_tpu.serve.engine` — the continuous-batching decode engine:
  a fixed slot array XLA compiles once, per-step admission and eviction,
  bucketed prefill interleaved with decode;
* :mod:`torchx_tpu.serve.prefix_cache` — refcounted radix prefix cache
  over the pool: shared prompt prefixes resolve to shared physical
  blocks instead of recomputing (LRU-evicted under pool pressure);
* :mod:`torchx_tpu.serve.kv_transfer` — the prefill->decode KV-block
  transfer seam for disaggregated serving (local/HTTP/file transports;
  the ``TransferConfig`` shape AppDef roles carry);
* :mod:`torchx_tpu.serve.pool` — the launcher-driven serve pool:
  ``tpx serve-pool`` submits N ``generate_server`` replicas through the
  Runner, routes requests least-loaded (with a longest-cached-prefix
  bonus), and autoscales via ``Runner.resize`` on queue-depth/p99
  targets — one gang, or disaggregated prefill + decode gangs with
  independent policies.
"""

# Lazy re-exports (PEP 562): kv_pool pulls in the jax-backed paged
# attention op, but jax-free consumers (the fleet simulator runs the
# production Autoscaler from serve.pool) must be able to import their
# submodule without paying for — or even having — jax.
_EXPORTS = {
    "BlockAllocator": "torchx_tpu.serve.kv_pool",
    "PoolPlan": "torchx_tpu.serve.kv_pool",
    "plan_pool": "torchx_tpu.serve.kv_pool",
    "PrefixCache": "torchx_tpu.serve.prefix_cache",
    "prefix_chain": "torchx_tpu.serve.prefix_cache",
    "TransferConfig": "torchx_tpu.serve.kv_transfer",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(mod), name)


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
