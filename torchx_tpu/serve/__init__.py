"""Production serving runtime: continuous batching over a paged KV cache.

Three layers, bottom-up:

* :mod:`torchx_tpu.serve.kv_pool` — host-side paged KV-cache planning and
  block allocation (the device-side gather/scatter lives in
  :mod:`torchx_tpu.ops.paged_attention`);
* :mod:`torchx_tpu.serve.engine` — the continuous-batching decode engine:
  a fixed slot array XLA compiles once, per-step admission and eviction,
  bucketed prefill interleaved with decode;
* :mod:`torchx_tpu.serve.pool` — the launcher-driven serve pool:
  ``tpx serve-pool`` submits N ``generate_server`` replicas through the
  Runner, routes requests least-loaded, and autoscales via
  ``Runner.resize`` on queue-depth/p99 targets.
"""

from torchx_tpu.serve.kv_pool import BlockAllocator, PoolPlan, plan_pool

__all__ = ["BlockAllocator", "PoolPlan", "plan_pool"]
