"""Launcher-driven autoscaling serve pool (``tpx serve-pool``).

The controller half of the serving runtime: submit N ``generate_server``
replicas as ONE role through the :class:`~torchx_tpu.runner.api.Runner`,
probe each replica's ``/healthz`` for queue depth, and autoscale the role
via :meth:`Runner.resize` — so every scale event rides the same ledger
(``log_event("resize", ...)``), describe-cache invalidation, and gang
restart semantics every other ``tpx`` verb uses. Serving is just another
job to the launcher; there is no second control plane.

Three pieces, smallest surface first:

* :class:`Autoscaler` — the pure decision function. ``observe(replicas,
  queue_depth, p99_s) -> desired`` with hysteresis (consecutive-breach
  streaks) and a post-scale cooldown on an injectable clock, so tests
  drive it deterministically with a fake clock and synthetic load.
* :class:`LeastLoadedRouter` — client-side routing state: pick the
  replica with the lowest cache-aware score (in-flight + last probed
  queue depth, minus a longest-cached-prefix bonus computed from the
  replica's probed prefix summary), record request latencies for the
  p99 the autoscaler consumes. The HTTP proxy front-end
  (:func:`serve_router`) is a thin wrapper over it.
* :class:`ServePool` — mechanism. Owns the app handle, runs the
  probe -> autoscale -> resize loop, exports ``tpx_serve_replicas`` /
  ``tpx_serve_scale_events_total`` and ``serve.pool.*`` spans.
* :class:`DisaggServePool` — disaggregated mechanism: ONE app whose
  AppDef carries a prefill role and a decode role, each driven by its
  own :class:`ServePool` controller (independent
  :class:`AutoscalePolicy`s, one ``Runner.resize`` per role) over a
  shared handle; :meth:`DisaggServePool.transfer_config` derives the
  prefill->decode :class:`~torchx_tpu.serve.kv_transfer.TransferConfig`
  from the decode gang's current replica URLs.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Optional, Sequence

from torchx_tpu import settings
from torchx_tpu.obs import metrics as obs_metrics
from torchx_tpu.obs import trace as obs_trace
from torchx_tpu.serve.kv_transfer import TransferConfig
from torchx_tpu.serve.prefix_cache import prefix_chain

logger = logging.getLogger(__name__)

__all__ = [
    "AutoscalePolicy",
    "Autoscaler",
    "ReplicaStatus",
    "LeastLoadedRouter",
    "ServePool",
    "DisaggServePool",
    "serve_router",
    "http_probe",
]


# =========================================================================
# Policy: the pure scaling decision
# =========================================================================


@dataclasses.dataclass(frozen=True)
class AutoscalePolicy:
    """Targets and damping for :class:`Autoscaler`.

    ``target_queue_depth`` is *per replica*: scale up when the mean probed
    queue depth breaches it (or TTFT p99 breaches ``target_p99_s``) for
    ``up_streak`` consecutive observations; scale down when depth falls
    under ``down_fraction`` of target AND no p99 breach for
    ``down_streak`` observations. ``cooldown_s`` gates both directions
    after any resize so a gang restart can't trigger a flapping loop.
    """

    min_replicas: int = 1
    max_replicas: int = 4
    target_queue_depth: float = 4.0
    target_p99_s: Optional[float] = None
    up_streak: int = 2
    down_streak: int = 6
    down_fraction: float = 0.25
    cooldown_s: float = 60.0

    def __post_init__(self) -> None:
        if not (0 < self.min_replicas <= self.max_replicas):
            raise ValueError(
                f"0 < min_replicas <= max_replicas violated: "
                f"{self.min_replicas}..{self.max_replicas}"
            )
        if self.target_queue_depth <= 0:
            raise ValueError("target_queue_depth must be > 0")
        if self.up_streak < 1 or self.down_streak < 1:
            raise ValueError("streaks must be >= 1")


class Autoscaler:
    """Hysteresis + cooldown around :class:`AutoscalePolicy`.

    Pure apart from the injected ``clock``: call :meth:`observe` once per
    control interval with what the probes saw; it returns the desired
    replica count (== current means hold). The caller performs the actual
    resize and MUST call :meth:`notify_scaled` when it does, which starts
    the cooldown and resets both streaks.
    """

    def __init__(
        self,
        policy: AutoscalePolicy,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.policy = policy
        self._clock = clock
        self._up = 0
        self._down = 0
        self._last_scale_t: Optional[float] = None

    def _in_cooldown(self) -> bool:
        return (
            self._last_scale_t is not None
            and self._clock() - self._last_scale_t < self.policy.cooldown_s
        )

    def observe(
        self,
        replicas: int,
        queue_depth: float,
        p99_s: Optional[float] = None,
        burn_rate: Optional[float] = None,
    ) -> int:
        """One control observation -> desired replica count.

        ``queue_depth`` is the mean per-replica depth across healthy
        replicas; ``p99_s`` the recent TTFT p99 (None = no latency signal,
        depth alone decides). ``burn_rate`` is the optional SLO signal
        (the engine's worst long-window burn): at >= 1.0 the error budget
        is burning faster than sustainable, which counts as hot and
        vetoes scale-down — the pool must not shrink its way deeper into
        a burning SLO even when the queue looks calm.
        """
        p = self.policy
        burning = burn_rate is not None and burn_rate >= 1.0
        hot = (
            burning
            or queue_depth > p.target_queue_depth
            or (
                p.target_p99_s is not None
                and p99_s is not None
                and p99_s > p.target_p99_s
            )
        )
        cold = (
            not burning
            and queue_depth < p.target_queue_depth * p.down_fraction
            and not (
                p.target_p99_s is not None
                and p99_s is not None
                and p99_s > p.target_p99_s
            )
        )
        self._up = self._up + 1 if hot else 0
        self._down = self._down + 1 if cold else 0
        if self._in_cooldown():
            return replicas
        if hot and self._up >= p.up_streak and replicas < p.max_replicas:
            return replicas + 1
        if cold and self._down >= p.down_streak and replicas > p.min_replicas:
            return replicas - 1
        return replicas

    def notify_scaled(self) -> None:
        """The caller resized: start cooldown, reset hysteresis."""
        self._last_scale_t = self._clock()
        self._up = 0
        self._down = 0


# =========================================================================
# Router: least-loaded pick + latency accounting
# =========================================================================


@dataclasses.dataclass
class ReplicaStatus:
    """What one probe observed about one replica.

    ``prefix_summary`` is the replica engine's published set of cached
    prefix-chain digests (hex, recency-ordered; see
    :func:`torchx_tpu.serve.prefix_cache.prefix_chain`) and
    ``block_size`` the paged-cache granularity those digests were chained
    at — together they let the router score a prompt's
    longest-cached-prefix without shipping token ids in probes."""

    replica_id: int
    url: str
    healthy: bool
    queue_depth: float = 0.0
    prefix_summary: tuple[str, ...] = ()
    block_size: int = 16


def http_probe(url: str, timeout: float = 2.0) -> ReplicaStatus:
    """Default probe: GET ``<url>/healthz`` and read the engine's queue
    depth (the continuous engine merges ``queue_depth`` into healthz; a
    draining or unreachable replica probes unhealthy and takes no new
    traffic) plus its prefix-cache summary for cache-aware routing."""
    rid = -1
    try:
        with urllib.request.urlopen(f"{url}/healthz", timeout=timeout) as r:
            body = json.loads(r.read().decode())
        return ReplicaStatus(
            replica_id=rid,
            url=url,
            healthy=body.get("status") == "ok",
            queue_depth=float(body.get("queue_depth", 0.0)),
            prefix_summary=tuple(body.get("prefix_summary", ())),
            block_size=int(body.get("block_size", 16) or 16),
        )
    except (urllib.error.URLError, OSError, ValueError, json.JSONDecodeError):
        return ReplicaStatus(replica_id=rid, url=url, healthy=False)


class LeastLoadedRouter:
    """Cache-aware routing state over the pool's current replica set.

    :meth:`pick` returns the healthy replica with the lowest score:
    load (in-flight requests this router has outstanding + the last
    probed queue depth — the probe sees load from *other* clients, the
    in-flight count sees our own before the probe catches up) minus
    ``cache_bonus`` per prompt block the replica already holds in its
    prefix cache. The match is computed entirely from probe data: the
    prompt's positional chain digests (:func:`prefix_chain`) intersected
    against each replica's published summary — the deepest digest both
    sides share IS the longest cached prefix, because chain digests
    commit to the whole path. :meth:`record` feeds a bounded latency
    window from which :meth:`p99_s` serves the autoscaler's SLO signal.
    """

    def __init__(self, window: int = 512, cache_bonus: float = 1.0) -> None:
        self._lock = threading.Lock()
        self._replicas: dict[int, ReplicaStatus] = {}
        self._inflight: dict[int, int] = {}
        self._latencies: list[float] = []
        self._window = window
        self.cache_bonus = cache_bonus
        # rollout state lives OUTSIDE the probe-replaced table: a drain
        # mark or canary weight must take effect on the very next pick(),
        # not after the next probe sweep rebuilds _replicas
        self._draining: set[int] = set()
        self._weights: dict[int, float] = {}

    def update(self, statuses: list[ReplicaStatus]) -> None:
        """Replace the routing table with the latest probe sweep (drain
        marks and canary weights survive — they are rollout state, not
        probe state)."""
        with self._lock:
            self._replicas = {s.replica_id: s for s in statuses}
            self._inflight = {
                rid: self._inflight.get(rid, 0) for rid in self._replicas
            }

    def mark_draining(self, replica_id: int) -> None:
        """Exclude ``replica_id`` from :meth:`pick` immediately — the
        first step of a checkpoint rollout, effective before any probe
        notices the replica going away."""
        with self._lock:
            self._draining.add(replica_id)

    def clear_draining(self, replica_id: int) -> None:
        """Readmit ``replica_id`` to the pick set (rollout finished)."""
        with self._lock:
            self._draining.discard(replica_id)

    def set_weight(self, replica_id: int, weight: float) -> None:
        """Traffic weight for ``replica_id`` (default 1.0). The promotion
        controller weights the canary cohort's share of the split; higher
        weight attracts proportionally more traffic."""
        with self._lock:
            if weight == 1.0:
                self._weights.pop(replica_id, None)
            else:
                self._weights[replica_id] = max(1e-6, float(weight))

    def inflight(self, replica_id: int) -> int:
        """Requests this router routed to ``replica_id`` that have not
        :meth:`record`-ed back yet — the rollout seam drains on it."""
        with self._lock:
            return self._inflight.get(replica_id, 0)

    def prefix_blocks(
        self, status: ReplicaStatus, tokens: Sequence[int]
    ) -> int:
        """How many leading blocks of ``tokens`` replica ``status`` has
        cached: the deepest chain digest present in its summary."""
        if not tokens or not status.prefix_summary:
            return 0
        chain = prefix_chain(tokens, status.block_size)
        have = set(status.prefix_summary)
        matched = 0
        for depth, digest in enumerate(chain, start=1):
            if digest in have:
                matched = depth
        return matched

    def pick(
        self, tokens: Optional[Sequence[int]] = None
    ) -> Optional[ReplicaStatus]:
        """Best healthy replica for this prompt (None when none are
        healthy); bumps its in-flight count — pair with :meth:`record`.
        With ``tokens`` the score subtracts the longest-cached-prefix
        bonus; without, it degrades to plain least-loaded. Draining
        replicas are excluded outright; weights divide the load score
        (weight 2 looks half as loaded, weight 0.5 twice as loaded)."""
        with self._lock:
            healthy = [
                s
                for s in self._replicas.values()
                if s.healthy and s.replica_id not in self._draining
            ]
            if not healthy:
                return None
            def _score(s: ReplicaStatus) -> tuple[float, int]:
                load = (
                    self._inflight.get(s.replica_id, 0)
                    + s.queue_depth
                    - (
                        self.cache_bonus * self.prefix_blocks(s, tokens)
                        if tokens is not None
                        else 0.0
                    )
                )
                w = self._weights.get(s.replica_id, 1.0)
                # weight scales attractiveness on both sides of zero: a
                # heavier replica looks less loaded (or more cache-ahead)
                return (load / w if load >= 0 else load * w, s.replica_id)

            best = min(healthy, key=_score)
            self._inflight[best.replica_id] = (
                self._inflight.get(best.replica_id, 0) + 1
            )
            return best

    def record(self, replica_id: int, latency_s: float) -> None:
        """Request to ``replica_id`` finished after ``latency_s``."""
        with self._lock:
            if self._inflight.get(replica_id, 0) > 0:
                self._inflight[replica_id] -= 1
            self._latencies.append(latency_s)
            if len(self._latencies) > self._window:
                del self._latencies[: -self._window]

    def p99_s(self) -> Optional[float]:
        """p99 of the recent latency window (None until any data)."""
        with self._lock:
            if not self._latencies:
                return None
            xs = sorted(self._latencies)
            return xs[min(len(xs) - 1, int(0.99 * len(xs)))]

    def queue_depth(self) -> float:
        """Mean probed depth across healthy replicas (0 when none)."""
        with self._lock:
            healthy = [s for s in self._replicas.values() if s.healthy]
            if not healthy:
                return 0.0
            return sum(s.queue_depth for s in healthy) / len(healthy)

    def prefix_digests(self) -> list[str]:
        """Union of every healthy replica's published prefix-chain
        digests, sorted — the cell's cache-affinity summary that
        :meth:`ServePool.federation_summary` exports cross-cell (the
        federation router matches incoming prompts' chains against it)."""
        with self._lock:
            out: set[str] = set()
            for s in self._replicas.values():
                if s.healthy:
                    out.update(s.prefix_summary)
            return sorted(out)


# =========================================================================
# Pool: runner-backed mechanism
# =========================================================================


class ServePool:
    """Probe -> autoscale -> ``Runner.resize`` control loop over one app.

    The pool owns nothing the launcher doesn't already model: replicas are
    the role's gang, scaling is :meth:`Runner.resize` (ledgered, cache
    invalidating, gang-coherent), teardown is :meth:`Runner.cancel`.
    ``probe`` and ``sleep`` are injectable so the e2e test drives the loop
    deterministically against a synthetic workload.
    """

    def __init__(
        self,
        runner: Any,
        app: Any,
        *,
        scheduler: str = "local",
        cfg: Optional[dict] = None,
        role_name: str = "server",
        base_port: int = 8000,
        port_stride: int = 1,
        policy: Optional[AutoscalePolicy] = None,
        probe: Optional[Callable[[int, str], ReplicaStatus]] = None,
        router: Optional[LeastLoadedRouter] = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        reconciler: Optional[Any] = None,
        slo_signal: Optional[Callable[[], Optional[float]]] = None,
        restart: Optional[Callable[[int, str], None]] = None,
        cell: str = "",
    ) -> None:
        self._runner = runner
        # which federation cell this pool serves in; the summary below is
        # what a CellHandle feeds the cross-cell router's affinity score
        self.cell = (
            cell
            or os.environ.get(settings.ENV_TPX_CELL, "").strip()
            or settings.DEFAULT_CELL_NAME
        )
        self._app = app
        self._scheduler = scheduler
        self._cfg = cfg or {}
        self._role_name = role_name
        self._base_port = base_port
        self._port_stride = port_stride
        self.policy = policy or AutoscalePolicy()
        self._probe = probe or self._http_probe
        self.router = router or LeastLoadedRouter()
        self._clock = clock
        self._sleep = sleep
        # optional control-plane reconciler: the run() loop then consumes
        # watch events (terminal detection at event latency, zero describe
        # calls) instead of polling Runner.status every interval
        self._reconciler = reconciler
        # optional SLO burn-rate feed (a callable so the engine's latest
        # evaluation is read per step, e.g. daemon.slo_engine.max_burn)
        self._slo_signal = slo_signal
        # per-replica restart actuator for checkpoint rollouts: called as
        # restart(replica_id, ckpt) after the replica drained; backends
        # that restart replicas out-of-band (local process respawn, k8s
        # pod delete) inject their mechanism here
        self._restart = restart
        self.autoscaler = Autoscaler(self.policy, clock=clock)
        self.handle: Optional[str] = None
        self._replicas = next(
            (r.num_replicas for r in app.roles if r.name == role_name),
            1,
        )
        self.scale_events: list[tuple[int, int]] = []  # (from, to)

    # -- lifecycle --------------------------------------------------------

    def start(self) -> str:
        """Submit the app; returns (and retains) its handle."""
        with obs_trace.span(
            "serve.pool.start", app=self._app.name, scheduler=self._scheduler
        ):
            self.handle = self._runner.run(
                self._app, self._scheduler, self._cfg
            )
        if self._reconciler is not None:
            from torchx_tpu.specs.api import parse_app_handle

            sched_name, _, app_id = parse_app_handle(self.handle)
            self._reconciler.track(
                sched_name, self._runner._scheduler(sched_name), app_id
            )
        obs_metrics.SERVE_REPLICAS.set(self._replicas)
        logger.info(
            "serve pool up: %s with %d replica(s)", self.handle, self._replicas
        )
        return self.handle

    def stop(self) -> None:
        """Cancel the app (replicas drain via their SIGTERM handlers)."""
        if self.handle is not None:
            self._runner.cancel(self.handle)

    @property
    def replicas(self) -> int:
        """Current target replica count."""
        return self._replicas

    def replica_url(self, replica_id: int) -> str:
        """Where replica ``replica_id`` listens (port-stride convention
        shared with ``components.serve.generate_server``)."""
        return f"http://127.0.0.1:{self._base_port + self._port_stride * replica_id}"

    def federation_summary(self) -> dict:
        """This cell's serve-plane export for the federation layer.

        ``prefix_digests`` (union of replica prefix-cache summaries) is
        the affinity signal :class:`torchx_tpu.federation.router.
        FederationRouter` scores against; ``p99_s``/``queue_depth`` are
        the health context a cross-cell dashboard shows next to burn."""
        return {
            "cell": self.cell,
            "prefix_digests": self.router.prefix_digests(),
            "p99_s": self.router.p99_s(),
            "queue_depth": self.router.queue_depth(),
            "replicas": self._replicas,
        }

    # -- checkpoint rollout ------------------------------------------------

    def rollout_replica(
        self,
        replica_id: int,
        ckpt: str,
        *,
        drain_timeout_s: float = 30.0,
        health_timeout_s: float = 30.0,
        poll_s: float = 0.05,
    ) -> bool:
        """Roll ONE replica onto a new checkpoint: drain → restart →
        health-confirm. This is the promotion controller's only seam into
        the pool — it never touches replica handles directly.

        The replica is marked draining on the router first, so it leaves
        the traffic split on the very next ``pick()``; the restart only
        fires once every request the router had in flight to it has
        :meth:`LeastLoadedRouter.record`-ed back (zero dropped requests).
        Returns True once the restarted replica probes healthy again (and
        rejoins the split), False on drain/health timeout or a restart
        error — the caller treats False as a failed rollout and rolls
        back.
        """
        with obs_trace.span(
            "serve.rollout", replica=str(replica_id), ckpt=ckpt
        ):
            self.router.mark_draining(replica_id)
            try:
                deadline = self._clock() + drain_timeout_s
                while self.router.inflight(replica_id) > 0:
                    if self._clock() >= deadline:
                        logger.warning(
                            "replica %d did not drain within %.1fs",
                            replica_id,
                            drain_timeout_s,
                        )
                        return False
                    self._sleep(poll_s)
                if self._restart is not None:
                    try:
                        self._restart(replica_id, ckpt)
                    except Exception as e:  # noqa: BLE001 - a dead restart fails the rollout
                        logger.warning(
                            "restart of replica %d failed: %s", replica_id, e
                        )
                        return False
                deadline = self._clock() + health_timeout_s
                while True:
                    st = self._probe(replica_id, self.replica_url(replica_id))
                    if st.healthy:
                        return True
                    if self._clock() >= deadline:
                        logger.warning(
                            "replica %d not healthy %.1fs after rollout",
                            replica_id,
                            health_timeout_s,
                        )
                        return False
                    self._sleep(poll_s)
            finally:
                self.router.clear_draining(replica_id)

    # -- control loop -----------------------------------------------------

    def _http_probe(self, replica_id: int, url: str) -> ReplicaStatus:
        st = http_probe(url)
        st.replica_id = replica_id
        return st

    def probe_all(self) -> list[ReplicaStatus]:
        """Probe every replica in the current target set."""
        out = []
        for rid in range(self._replicas):
            st = self._probe(rid, self.replica_url(rid))
            st.replica_id = rid
            out.append(st)
        return out

    def step(self) -> Optional[int]:
        """One control iteration: probe, decide, maybe resize.

        Returns the new replica count when a resize happened, else None.
        A resize that the backend refuses (e.g. terminal app) surfaces —
        the loop in :meth:`run` stops on it, the driver decides.
        """
        with obs_trace.span("serve.pool.step", handle=self.handle or ""):
            statuses = self.probe_all()
            self.router.update(statuses)
            depth = self.router.queue_depth()
            p99 = self.router.p99_s()
            obs_metrics.SERVE_QUEUE_DEPTH.set(depth)
            burn: Optional[float] = None
            if self._slo_signal is not None:
                try:
                    burn = self._slo_signal()
                except Exception as e:  # noqa: BLE001 - probes still decide
                    logger.debug("slo signal failed: %s", e)
            desired = self.autoscaler.observe(
                self._replicas, depth, p99, burn_rate=burn
            )
            if desired == self._replicas:
                return None
            return self._resize(desired)

    def _resize(self, desired: int) -> int:
        direction = "up" if desired > self._replicas else "down"
        with obs_trace.span(
            "serve.scale",
            handle=self.handle or "",
            direction=direction,
            to=str(desired),
        ):
            if self.handle is not None:
                self._runner.resize(self.handle, self._role_name, desired)
            self.scale_events.append((self._replicas, desired))
            logger.warning(
                "serve pool scaled %s: %d -> %d replicas",
                direction,
                self._replicas,
                desired,
            )
            self._replicas = desired
            self.autoscaler.notify_scaled()
            obs_metrics.SERVE_REPLICAS.set(desired)
            obs_metrics.SERVE_SCALE_EVENTS.inc(direction=direction)
        return desired

    def run(
        self,
        interval_s: float = 10.0,
        iterations: Optional[int] = None,
        stop_event: Optional[threading.Event] = None,
    ) -> None:
        """The controller loop: step every ``interval_s`` until the app
        terminates, ``iterations`` are spent, or ``stop_event`` fires."""
        done = 0
        while iterations is None or done < iterations:
            if stop_event is not None and stop_event.is_set():
                return
            if self._app_terminal():
                return
            self.step()
            done += 1
            self._pause(interval_s)

    def _app_terminal(self) -> bool:
        """True when the pool's app reached a terminal state. With a
        reconciler the answer comes from the watch stream's last event
        (no describe call); otherwise from a status poll."""
        if self.handle is None:
            return False
        if self._reconciler is not None:
            from torchx_tpu.specs.api import parse_app_handle

            sched_name, _, app_id = parse_app_handle(self.handle)
            event = self._reconciler.latest(sched_name, app_id)
            if event is not None and event.terminal:
                logger.warning(
                    "serve pool app reached %s (watch); controller exiting",
                    event.state.name,
                )
                return True
            if event is not None:
                return False  # watch confirms it live: skip the poll
        status = self._runner.status(self.handle)
        if status is not None and status.state is not None:
            from torchx_tpu.specs.api import is_terminal

            if is_terminal(status.state):
                logger.warning(
                    "serve pool app reached %s; controller exiting",
                    status.state.name,
                )
                return True
        return False

    def _pause(self, interval_s: float) -> None:
        """Between steps: ride the reconciler's wake path when attached
        (a terminal event cuts the sleep short; the next loop iteration
        then exits immediately), else plain sleep."""
        if self._reconciler is not None and self.handle is not None:
            from torchx_tpu.specs.api import parse_app_handle

            sched_name, _, app_id = parse_app_handle(self.handle)
            # blocks up to interval_s either way; an event ends the pause
            # early and the next iteration acts on it
            self._reconciler.wait_event(sched_name, app_id, timeout=interval_s)
            return
        self._sleep(interval_s)


# =========================================================================
# Disaggregated pool: prefill gang + decode gang, one app
# =========================================================================


class DisaggServePool:
    """Two-gang controller for disaggregated serving.

    ONE app (the :func:`torchx_tpu.components.serve.generate_server_disagg`
    AppDef) carries a prefill role and a decode role; each gets its own
    :class:`ServePool` controller — independent
    :class:`AutoscalePolicy`s, separate probe sweeps and routers, one
    ``Runner.resize`` per role — sharing a single submitted handle, so
    every scale event on either gang still rides the launcher's ledger.

    The prefill gang is compute-bound (chunked prefill, prefix-cache
    warm) and scales on queue depth / TTFT p99; the decode gang is
    HBM-bandwidth-bound and scales on its own occupancy signal. Client
    traffic routes to the *prefill* gang (cache-aware);
    :meth:`transfer_config` hands prefill replicas the decode gang's
    current URLs as an ``http:`` transfer spec for the KV handoff.
    """

    def __init__(
        self,
        runner: Any,
        app: Any,
        *,
        scheduler: str = "local",
        cfg: Optional[dict] = None,
        prefill_role: str = "prefill",
        decode_role: str = "decode",
        prefill_policy: Optional[AutoscalePolicy] = None,
        decode_policy: Optional[AutoscalePolicy] = None,
        prefill_base_port: int = 8000,
        decode_base_port: int = 8100,
        port_stride: int = 1,
        probe: Optional[Callable[[int, str], ReplicaStatus]] = None,
        router: Optional[LeastLoadedRouter] = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        reconciler: Optional[Any] = None,
        cell: str = "",
    ) -> None:
        self._runner = runner
        self._app = app
        self._scheduler = scheduler
        self._cfg = cfg or {}
        self.prefill = ServePool(
            runner,
            app,
            scheduler=scheduler,
            cfg=cfg,
            role_name=prefill_role,
            base_port=prefill_base_port,
            port_stride=port_stride,
            policy=prefill_policy or AutoscalePolicy(),
            probe=probe,
            router=router or LeastLoadedRouter(),
            clock=clock,
            sleep=sleep,
            reconciler=reconciler,
            cell=cell,
        )
        self.decode = ServePool(
            runner,
            app,
            scheduler=scheduler,
            cfg=cfg,
            role_name=decode_role,
            base_port=decode_base_port,
            port_stride=port_stride,
            policy=decode_policy or AutoscalePolicy(),
            probe=probe,
            router=LeastLoadedRouter(),
            clock=clock,
            sleep=sleep,
        )
        self.handle: Optional[str] = None

    # client traffic enters through the prefill gang: serve_router() and
    # callers treat a DisaggServePool like a ServePool via these two
    @property
    def router(self) -> LeastLoadedRouter:
        """The prefill gang's router — where client traffic enters."""
        return self.prefill.router

    @property
    def replicas(self) -> int:
        """Total replicas across both gangs (the SERVE_REPLICAS gauge)."""
        return self.prefill.replicas + self.decode.replicas

    @property
    def cell(self) -> str:
        """The federation cell this pool serves in (both gangs share it)."""
        return self.prefill.cell

    def federation_summary(self) -> dict:
        """Cross-cell export: the prefill gang's cache-affinity summary
        (client traffic and the prefix cache live there) with the total
        replica count across both gangs."""
        summary = self.prefill.federation_summary()
        summary["replicas"] = self.replicas
        return summary

    def start(self) -> str:
        """Submit the two-role app ONCE; both controllers share the
        handle (their resizes address their own role by name)."""
        self.handle = self.prefill.start()
        self.decode.handle = self.handle
        obs_metrics.SERVE_REPLICAS.set(self.replicas)
        return self.handle

    def stop(self) -> None:
        """Cancel the shared two-role app (both gangs go down together)."""
        if self.handle is not None:
            self._runner.cancel(self.handle)

    def transfer_config(self) -> TransferConfig:
        """The prefill->decode transfer path as of the current decode
        gang size — refresh after decode-side scale events."""
        return TransferConfig(
            mode="http",
            endpoints=tuple(
                self.decode.replica_url(rid)
                for rid in range(self.decode.replicas)
            ),
        )

    def step(self) -> tuple[Optional[int], Optional[int]]:
        """One control iteration per gang; returns (prefill, decode) new
        replica counts (None where that gang held)."""
        out = (self.prefill.step(), self.decode.step())
        obs_metrics.SERVE_REPLICAS.set(self.replicas)
        return out

    def run(
        self,
        interval_s: float = 10.0,
        iterations: Optional[int] = None,
        stop_event: Optional[threading.Event] = None,
    ) -> None:
        """Interleaved controller loop over both gangs (same exit
        conditions as :meth:`ServePool.run`)."""
        done = 0
        while iterations is None or done < iterations:
            if stop_event is not None and stop_event.is_set():
                return
            if self.prefill._app_terminal():
                return
            self.step()
            done += 1
            self.prefill._pause(interval_s)


# =========================================================================
# HTTP router front-end
# =========================================================================


def _make_router_handler(pool: ServePool) -> type:
    router = pool.router

    class Handler(BaseHTTPRequestHandler):
        # one pool-level entry point; replicas keep their own /healthz
        def log_message(self, fmt: str, *args: Any) -> None:
            logger.debug("router: " + fmt, *args)

        def _reply(self, code: int, body: dict) -> None:
            data = json.dumps(body).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self) -> None:
            if self.path == "/healthz":
                statuses = pool.router._replicas  # snapshot for status page
                self._reply(
                    200,
                    {
                        "status": "ok",
                        "cell": pool.cell,
                        "replicas": pool.replicas,
                        "healthy": sum(
                            1 for s in statuses.values() if s.healthy
                        ),
                        "queue_depth": router.queue_depth(),
                        "p99_s": router.p99_s(),
                    },
                )
            elif self.path == "/v1/federation":
                # the cross-cell export: cell identity + prefix-cache
                # digest union, probed by CellHandle for affinity routing
                self._reply(200, pool.federation_summary())
            elif self.path == "/metricz":
                # the router process's registry (routing counters, pool
                # gauges) in proper exposition format — a scrape target
                # for the control daemon's telemetry collector
                text = obs_metrics.REGISTRY.render().encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(text)))
                self.end_headers()
                self.wfile.write(text)
            else:
                self._reply(404, {"error": f"no route {self.path}"})

        def do_POST(self) -> None:
            if self.path != "/v1/generate":
                self._reply(404, {"error": f"no route {self.path}"})
                return
            length = int(self.headers.get("Content-Length", 0))
            payload = self.rfile.read(length)
            # best-effort prompt extraction for the cache-aware score: an
            # unparseable body still routes (least-loaded) and the replica
            # produces the authoritative 400
            tokens = None
            try:
                req = json.loads(payload or b"{}")
                if "tokens" in req and req["tokens"]:
                    tokens = list(req["tokens"][0])
                elif isinstance(req.get("text"), str):
                    tokens = list(req["text"].encode("utf-8"))
                elif isinstance(req.get("text"), list) and req["text"]:
                    tokens = list(req["text"][0].encode("utf-8"))
            except (ValueError, TypeError, KeyError, IndexError):
                tokens = None
            # adopt the caller's trace (or start one) and forward the
            # context to the replica, so router + replica + KV transfer
            # + decode stitch into one timeline per request
            in_tid, in_sid = obs_trace.extract_headers(self.headers)
            with obs_trace.trace_context(in_tid, in_sid):
                with obs_trace.span("serve.route") as sp:
                    trace_id = sp.trace_id if sp is not None else in_tid
                    target = router.pick(tokens)
                    if target is None:
                        self._reply(503, {"error": "no healthy replicas"})
                        return
                    if sp is not None:
                        sp.attrs["replica"] = target.replica_id
                    t0 = time.perf_counter()
                    try:
                        req = urllib.request.Request(
                            f"{target.url}{self.path}",
                            data=payload,
                            headers=obs_trace.inject_headers(
                                {"Content-Type": "application/json"}
                            ),
                        )
                        with urllib.request.urlopen(req, timeout=600) as r:
                            body = r.read()
                            code = r.status
                    except urllib.error.HTTPError as e:
                        body = e.read()
                        code = e.code
                    except (urllib.error.URLError, OSError) as e:
                        self._reply(
                            502,
                            {"error": f"replica {target.replica_id}: {e}"},
                        )
                        router.record(
                            target.replica_id, time.perf_counter() - t0
                        )
                        return
                    router.record(target.replica_id, time.perf_counter() - t0)
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            if trace_id:
                # callers (and tests) learn which trace to stitch
                self.send_header(obs_trace.HDR_TRACE_ID, trace_id)
            self.end_headers()
            self.wfile.write(body)

    return Handler


def serve_router(pool: ServePool, port: int = 0) -> ThreadingHTTPServer:
    """Start the least-loaded HTTP proxy for ``pool`` (port 0 = ephemeral;
    read the bound port off ``server.server_address``). Caller runs
    ``serve_forever`` (typically on a daemon thread next to the control
    loop)."""
    server = ThreadingHTTPServer(("", port), _make_router_handler(pool))
    return server
