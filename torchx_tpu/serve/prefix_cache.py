"""Refcounted radix prefix cache over the paged KV pool.

Serving traffic is dominated by shared prefixes — the same system prompt
in front of every request, few-shot preambles, multi-turn histories. The
paged pool already stores KV block-wise, so a prefix that two sequences
share can be *one* set of physical blocks with two references instead of
being recomputed per request (SGLang's RadixAttention observation).

:class:`PrefixCache` is the host-side index: a radix tree keyed on token
ids at **block granularity** — each node owns exactly one physical block
holding ``block_size`` tokens, and a root-to-node path spells out a
block-aligned prefix. The cache holds its own reference on every adopted
block through :class:`~torchx_tpu.serve.kv_pool.BlockAllocator`, so
blocks survive the completing slot and are shared into later slots via
:meth:`match` (which retains them for the new holder).

Only *full* blocks are ever cached, and :meth:`match` never covers the
final prompt token (the engine must compute at least one position to
produce logits), so a matched block is never written by its sharers —
the engine's copy-on-write tail guard is the backstop, not the hot path.

Eviction is LRU over nodes whose block has refcount 1 (cache-only, no
live slot): :meth:`evict` frees the least-recently-touched such leaves
under pool pressure, and an optional ``max_blocks`` cap bounds how much
of the pool the cache may pin (the ``--prefix-cache-reserve`` fraction
the cost model accounts for).

Hit/miss accounting feeds ``tpx_serve_prefix_*`` metrics and the serving
bench's prefix-hit-rate scorecard. Routers use :func:`prefix_chain` /
:meth:`summary` — positionally-chained digests of block keys — to score
replicas by longest cached prefix without shipping token ids around.
"""

from __future__ import annotations

import hashlib
import itertools
import threading
from typing import TYPE_CHECKING, Optional, Sequence

from torchx_tpu.obs import metrics as obs_metrics

if TYPE_CHECKING:  # annotation-only: kv_pool pulls the jax-backed op stack
    from torchx_tpu.serve.kv_pool import BlockAllocator

__all__ = ["PrefixCache", "prefix_chain"]


def _chain_digest(parent: bytes, chunk: tuple[int, ...]) -> bytes:
    h = hashlib.blake2b(digest_size=8)
    h.update(parent)
    h.update(b"|".join(str(t).encode() for t in chunk))
    return h.digest()


def prefix_chain(
    tokens: Sequence[int], block_size: int, max_blocks: int = 64
) -> list[str]:
    """Chained per-block digests of ``tokens``: entry ``i`` identifies the
    whole prefix ``tokens[: (i+1) * block_size]``. Routers compare these
    against replica summaries to find the longest cached prefix without
    exchanging raw token ids."""
    out: list[str] = []
    parent = b""
    n_full = min(len(tokens) // block_size, max_blocks)
    for i in range(n_full):
        chunk = tuple(tokens[i * block_size : (i + 1) * block_size])
        parent = _chain_digest(parent, chunk)
        out.append(parent.hex())
    return out


class _Node:
    __slots__ = ("chunk", "block", "children", "parent", "last_used", "digest")

    def __init__(
        self,
        chunk: tuple[int, ...],
        block: int,
        parent: Optional["_Node"],
        stamp: int,
    ) -> None:
        self.chunk = chunk
        self.block = block
        self.parent = parent
        self.children: dict[tuple[int, ...], _Node] = {}
        self.last_used = stamp
        self.digest = _chain_digest(
            parent.digest if parent is not None else b"", chunk
        )


class PrefixCache:
    """Radix tree of cached full KV blocks (see module docstring).

    Thread-safe: the engine loop matches/inserts/evicts while HTTP
    threads read :meth:`stats` and :meth:`summary`.
    """

    def __init__(
        self,
        alloc: BlockAllocator,
        block_size: int,
        *,
        max_blocks: Optional[int] = None,
    ) -> None:
        self.alloc = alloc
        self.block_size = block_size
        self.max_blocks = max_blocks  # None: bounded only by pool pressure
        self._root: dict[tuple[int, ...], _Node] = {}
        self._nodes = 0
        self._stamp = itertools.count()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.hit_tokens = 0
        self.lookup_tokens = 0
        self.evictions = 0

    @property
    def cached_blocks(self) -> int:
        """Blocks currently pinned by the cache."""
        return self._nodes

    # -- lookup ------------------------------------------------------------

    def match(self, tokens: Sequence[int]) -> tuple[list[int], int]:
        """Longest cached block-aligned prefix of ``tokens``.

        Returns ``(blocks, n_tokens)`` with one reference **retained per
        returned block on behalf of the caller** (release them through
        the normal slot-release path). Never covers the final token:
        the engine always has at least one position left to prefill, so
        the sampled "first" token has logits to come from.
        """
        bs = self.block_size
        # at least one token must remain uncached
        limit = max(0, (len(tokens) - 1) // bs)
        blocks: list[int] = []
        with self._lock:
            stamp = next(self._stamp)
            node: Optional[_Node] = None
            children = self._root
            for i in range(limit):
                chunk = tuple(tokens[i * bs : (i + 1) * bs])
                child = children.get(chunk)
                if child is None:
                    break
                child.last_used = stamp
                blocks.append(child.block)
                node = child
                children = child.children
            # touch the whole path so LRU evicts leaves before their parents
            while node is not None:
                node.last_used = stamp
                node = node.parent
            if blocks:
                self.alloc.retain(blocks)
                self.hits += 1
                obs_metrics.SERVE_PREFIX_HITS.inc()
            else:
                self.misses += 1
                obs_metrics.SERVE_PREFIX_MISSES.inc()
            matched = len(blocks) * bs
            self.hit_tokens += matched
            self.lookup_tokens += len(tokens)
            if matched:
                obs_metrics.SERVE_PREFIX_HIT_TOKENS.inc(matched)
        return blocks, matched

    # -- insertion ---------------------------------------------------------

    def insert(self, tokens: Sequence[int], blocks: Sequence[int]) -> int:
        """Index the full blocks of a prefilled/completed sequence.

        ``blocks[i]`` must hold tokens ``tokens[i*bs : (i+1)*bs]``; only
        ``len(tokens) // block_size`` full blocks are considered. New
        nodes adopt the caller's block with a cache-owned reference
        (:meth:`BlockAllocator.retain`); chunks already present keep the
        existing node's block — the caller's duplicate stays the
        caller's to release. Returns the number of newly adopted blocks.
        """
        bs = self.block_size
        n_full = min(len(tokens) // bs, len(blocks))
        adopted = 0
        with self._lock:
            stamp = next(self._stamp)
            parent: Optional[_Node] = None
            children = self._root
            for i in range(n_full):
                chunk = tuple(tokens[i * bs : (i + 1) * bs])
                node = children.get(chunk)
                if node is None:
                    if (
                        self.max_blocks is not None
                        and self._nodes >= self.max_blocks
                        and not self._evict_locked(1)
                    ):
                        break  # cap reached, nothing evictable
                    block = int(blocks[i])
                    self.alloc.retain([block])
                    node = _Node(chunk, block, parent, stamp)
                    children[chunk] = node
                    self._nodes += 1
                    adopted += 1
                node.last_used = stamp
                parent = node
                children = node.children
            obs_metrics.SERVE_PREFIX_CACHED_BLOCKS.set(self._nodes)
        return adopted

    # -- eviction ----------------------------------------------------------

    def evict(self, n_blocks: int) -> int:
        """Free up to ``n_blocks`` cache-only blocks (refcount 1), least
        recently used leaves first. Returns how many were freed — the
        engine calls this under pool pressure before preempting slots."""
        with self._lock:
            freed = self._evict_locked(n_blocks)
            obs_metrics.SERVE_PREFIX_CACHED_BLOCKS.set(self._nodes)
            return freed

    def _evict_locked(self, n_blocks: int) -> int:
        freed = 0
        while freed < n_blocks:
            victim = self._lru_evictable_leaf()
            if victim is None:
                break
            siblings = (
                victim.parent.children if victim.parent is not None else self._root
            )
            del siblings[victim.chunk]
            self._nodes -= 1
            self.alloc.release([victim.block])
            self.evictions += 1
            obs_metrics.SERVE_PREFIX_EVICTIONS.inc()
            freed += 1
        return freed

    def _lru_evictable_leaf(self) -> Optional[_Node]:
        best: Optional[_Node] = None
        stack = list(self._root.values())
        while stack:
            node = stack.pop()
            if node.children:
                stack.extend(node.children.values())
            elif self.alloc.refcount(node.block) == 1 and (
                best is None or node.last_used < best.last_used
            ):
                best = node
        return best

    # -- introspection -----------------------------------------------------

    def stats(self) -> dict:
        """Hit/miss accounting for ``/healthz`` and the bench scorecard."""
        with self._lock:
            lookups = self.hits + self.misses
            return {
                "cached_blocks": self._nodes,
                "hits": self.hits,
                "misses": self.misses,
                "hit_rate": self.hits / lookups if lookups else 0.0,
                "hit_tokens": self.hit_tokens,
                "lookup_tokens": self.lookup_tokens,
                "token_hit_rate": (
                    self.hit_tokens / self.lookup_tokens
                    if self.lookup_tokens
                    else 0.0
                ),
                "evictions": self.evictions,
            }

    def summary(self, max_entries: int = 128) -> list[str]:
        """Digests of the most-recently-used cached prefixes, for the
        cache-aware router (compare against :func:`prefix_chain`)."""
        with self._lock:
            nodes: list[_Node] = []
            stack = list(self._root.values())
            while stack:
                node = stack.pop()
                nodes.append(node)
                stack.extend(node.children.values())
            nodes.sort(key=lambda n: n.last_used, reverse=True)
            return [n.digest.hex() for n in nodes[:max_entries]]
