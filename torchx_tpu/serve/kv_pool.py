"""Paged KV-cache planning and block allocation (host side).

The dense decode path costs ``L * 2 * max_seq * kvh * hd`` bytes per
sequence regardless of how many tokens the request actually produces, so
concurrency is bounded by worst-case ``max_seq``. Here KV memory is one
fixed pool of ``num_blocks`` blocks of ``block_size`` tokens shared by
every active slot; a slot holds only the blocks its tokens occupy, so the
same HBM budget admits far more concurrent sequences (vLLM's central
observation, applied to the TPU serving path).

Nothing here runs on device: :func:`plan_pool` does the analytic HBM
sizing — same style as ``parallel/aot_fit.model_state_bytes_per_device``,
whose budget constants it reuses — and :class:`BlockAllocator` +
:class:`SlotTables` manage physical blocks and per-slot block tables as
plain numpy, feeding the jitted step functions in
:mod:`torchx_tpu.serve.engine` as ordinary array arguments.

Block 0 is the *trash block* (``ops.paged_attention.TRASH_BLOCK``): never
allocated, the target of every unassigned table entry, so inactive slots
in the fixed-shape step harmlessly read/write it under the length mask.
"""

from __future__ import annotations

import dataclasses
import math
from collections import deque

import numpy as np

from torchx_tpu.ops.paged_attention import TRASH_BLOCK
from torchx_tpu.parallel.aot_fit import DEFAULT_HEADROOM, GIB, V5P_HBM_BYTES

__all__ = [
    "PoolPlan",
    "plan_pool",
    "BlockAllocator",
    "SlotTables",
]


@dataclasses.dataclass(frozen=True)
class PoolPlan:
    """Resolved geometry of a paged KV pool for one model config.

    ``kv_budget_bytes`` is HBM after headroom and parameters;
    ``dense_slots`` is how many sequences the *dense* ``[max_seq]`` cache
    would fit in the same budget — the bench's occupancy comparison.
    """

    num_blocks: int
    block_size: int
    blocks_per_slot: int
    max_slots: int
    kv_bytes: int
    kv_budget_bytes: int
    dense_slots: int

    @property
    def pool_tokens(self) -> int:
        """Total KV token capacity (excluding the trash block)."""
        return (self.num_blocks - 1) * self.block_size

    def occupancy_report(self) -> dict:
        """Paged-vs-dense concurrency at the same HBM budget, as a dict
        (serialised into the serving bench's JSON output).

        ``kv_bytes_gib`` is the actual pool footprint
        (``num_blocks * block_bytes``); the block grid rarely tiles the
        budget exactly, so the unusable remainder is reported separately
        as ``kv_slack_gib`` rather than rounded into equality with
        ``kv_budget_gib``.
        """
        slack = self.kv_budget_bytes - self.kv_bytes
        return {
            "num_blocks": self.num_blocks,
            "block_size": self.block_size,
            "blocks_per_slot": self.blocks_per_slot,
            "paged_slots": self.max_slots,
            "dense_slots": self.dense_slots,
            "kv_budget_gib": round(self.kv_budget_bytes / GIB, 3),
            "kv_bytes_gib": round(self.kv_bytes / GIB, 6),
            "kv_slack_gib": round(slack / GIB, 6),
            "pool_tokens": self.pool_tokens,
        }


def _kv_itemsize(cfg) -> int:
    # serving caches are stored in the model compute dtype; np.dtype
    # resolves jnp dtypes too (ml_dtypes registers bfloat16)
    return np.dtype(cfg.dtype).itemsize


def plan_pool(
    cfg,
    *,
    hbm_bytes: int = V5P_HBM_BYTES,
    headroom: float = DEFAULT_HEADROOM,
    block_size: int = 16,
    max_slots: int | None = None,
    mean_tokens_per_seq: int | None = None,
) -> PoolPlan:
    """Size a paged KV pool against an HBM budget for ``cfg``.

    Budget = ``hbm_bytes * headroom`` minus parameter bytes (serving holds
    no optimizer state, so params are ``param_count * itemsize`` — compare
    ``aot_fit.model_state_bytes_per_device`` which charges 3x for Adam).
    ``num_blocks`` fills the remainder; ``max_slots`` (the engine's fixed
    slot-array size) defaults to oversubscribing the pool assuming
    sequences average ``mean_tokens_per_seq`` tokens (default
    ``max_seq / 4`` — serving traffic rarely decodes to the cap), capped
    so a single full-length sequence always fits.
    """
    itemsize = _kv_itemsize(cfg)
    param_bytes = cfg.param_count() * itemsize
    budget = int(hbm_bytes * headroom) - param_bytes
    if budget <= 0:
        raise ValueError(
            f"params ({param_bytes / GIB:.1f} GiB) exceed HBM budget "
            f"({hbm_bytes * headroom / GIB:.1f} GiB); no room for KV pool"
        )
    # one block, all layers, K and V
    block_bytes = cfg.n_layers * 2 * block_size * cfg.n_kv_heads * cfg.head_dim * itemsize
    num_blocks = budget // block_bytes
    blocks_per_slot = math.ceil(cfg.max_seq / block_size)
    if num_blocks < blocks_per_slot + 1:  # +1: trash block
        raise ValueError(
            f"KV budget ({budget / GIB:.2f} GiB) fits only {num_blocks} "
            f"blocks; one {cfg.max_seq}-token sequence needs "
            f"{blocks_per_slot}"
        )
    dense_seq_bytes = (
        cfg.n_layers * 2 * cfg.max_seq * cfg.n_kv_heads * cfg.head_dim * itemsize
    )
    dense_slots = budget // dense_seq_bytes
    if max_slots is None:
        mean_tokens = mean_tokens_per_seq or max(block_size, cfg.max_seq // 4)
        mean_blocks = math.ceil(mean_tokens / block_size)
        max_slots = max(1, (num_blocks - 1) // mean_blocks)
    kv_bytes = num_blocks * block_bytes
    return PoolPlan(
        num_blocks=int(num_blocks),
        block_size=block_size,
        blocks_per_slot=blocks_per_slot,
        max_slots=int(max_slots),
        kv_bytes=int(kv_bytes),
        kv_budget_bytes=int(budget),
        dense_slots=int(dense_slots),
    )


class BlockAllocator:
    """Refcounting free-list allocator over the physical blocks of one
    KV pool.

    Allocation is all-or-nothing: :meth:`alloc` returns ``None`` rather
    than a partial grant, so the engine can atomically decide to admit,
    wait, or preempt. Block ``TRASH_BLOCK`` is never handed out.

    Every allocated block carries a reference count (1 on :meth:`alloc`):
    the prefix cache and any slot sharing a cached prefix each hold one
    reference via :meth:`retain`, and :meth:`release` (or its legacy
    alias :meth:`free`) returns the block to the free list only when the
    count reaches zero. A shared block (refcount > 1) must never be
    written in place — the engine copy-on-writes the partial tail block
    through :meth:`is_shared` before appending to it.

    Freeing a block that is already free (double-free) or freeing the
    trash block raises ``ValueError`` instead of silently corrupting the
    free list.
    """

    def __init__(self, num_blocks: int) -> None:
        if num_blocks < 2:
            raise ValueError(f"need >= 2 blocks (1 is trash), got {num_blocks}")
        self.num_blocks = num_blocks
        self._free: deque[int] = deque(
            b for b in range(num_blocks) if b != TRASH_BLOCK
        )
        # refcount per physical block; 0 == free (trash stays pinned at 0
        # and is rejected everywhere by the explicit guards)
        self._refs = np.zeros((num_blocks,), np.int32)

    @property
    def free_blocks(self) -> int:
        """Blocks currently available to allocate."""
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        """Blocks currently held by slots (excludes the trash block)."""
        return self.num_blocks - 1 - len(self._free)

    def _check(self, b: int) -> None:
        if b == TRASH_BLOCK:
            raise ValueError("trash block is never allocated/retained/freed")
        if not 0 < b < self.num_blocks:
            raise ValueError(f"block {b} outside pool of {self.num_blocks}")

    def alloc(self, n: int) -> list[int] | None:
        """Take ``n`` blocks (each with refcount 1), or ``None`` (and take
        nothing) if fewer are free."""
        if n < 0:
            raise ValueError(f"negative allocation: {n}")
        if n > len(self._free):
            return None
        out = [self._free.popleft() for _ in range(n)]
        self._refs[out] += 1
        return out

    def refcount(self, block: int) -> int:
        """Current reference count of ``block`` (0 == free)."""
        self._check(block)
        return int(self._refs[block])

    def is_shared(self, block: int) -> bool:
        """True when more than one holder references ``block`` — writing
        it in place would corrupt another holder's prefix (COW trigger)."""
        return self.refcount(block) > 1

    def retain(self, blocks: list[int]) -> None:
        """Add one reference to each allocated block (prefix sharing)."""
        for b in blocks:
            self._check(b)
            if self._refs[b] == 0:
                raise ValueError(f"retaining free block {b}")
        for b in blocks:
            self._refs[b] += 1

    def release(self, blocks: list[int]) -> list[int]:
        """Drop one reference per block; blocks reaching refcount 0 go
        back to the free list. Returns the blocks actually freed.

        Raises ``ValueError`` on the trash block or a block that is
        already free (double-free) — validated for the whole batch before
        any count moves, so a raise leaves the allocator unchanged.
        """
        for b in blocks:
            self._check(b)
        counts: dict[int, int] = {}
        for b in blocks:
            counts[b] = counts.get(b, 0) + 1
            if counts[b] > self._refs[b]:
                raise ValueError(
                    f"double-free of block {b} "
                    f"(refcount {int(self._refs[b])})"
                )
        freed: list[int] = []
        for b in blocks:
            self._refs[b] -= 1
            if self._refs[b] == 0:
                self._free.append(b)
                freed.append(b)
        return freed

    def free(self, blocks: list[int]) -> None:
        """Drop one reference per block (see :meth:`release`); the
        historical name for the owner's release path."""
        self.release(blocks)


class SlotTables:
    """Per-slot block tables + valid lengths, host side (numpy).

    The engine passes :attr:`tables` / :attr:`lengths` into the jitted
    decode step every iteration; unassigned entries stay ``TRASH_BLOCK``
    so inactive slots are inert under the mask. One instance is shared by
    all layers — every layer of a sequence uses the same physical block
    ids into its own layer-indexed pool.
    """

    def __init__(self, max_slots: int, blocks_per_slot: int) -> None:
        self.max_slots = max_slots
        self.blocks_per_slot = blocks_per_slot
        self.tables = np.full((max_slots, blocks_per_slot), TRASH_BLOCK, np.int32)
        self.lengths = np.zeros((max_slots,), np.int32)
        self._blocks: list[list[int]] = [[] for _ in range(max_slots)]

    def assign(self, slot: int, blocks: list[int]) -> None:
        """Append physical ``blocks`` to ``slot``'s table."""
        held = self._blocks[slot]
        if len(held) + len(blocks) > self.blocks_per_slot:
            raise ValueError(
                f"slot {slot}: {len(held)}+{len(blocks)} blocks exceeds "
                f"blocks_per_slot={self.blocks_per_slot}"
            )
        self.tables[slot, len(held) : len(held) + len(blocks)] = blocks
        held.extend(blocks)

    def blocks_of(self, slot: int) -> list[int]:
        """Physical blocks currently held by ``slot``."""
        return list(self._blocks[slot])

    def replace_block(self, slot: int, index: int, block: int) -> None:
        """Swap the physical block at table ``index`` — the engine's
        copy-on-write path after duplicating a shared tail block."""
        if index >= len(self._blocks[slot]):
            raise ValueError(
                f"slot {slot} holds {len(self._blocks[slot])} blocks; "
                f"cannot replace index {index}"
            )
        self._blocks[slot][index] = block
        self.tables[slot, index] = block

    def token_capacity(self, slot: int, block_size: int) -> int:
        """Token capacity of ``slot``'s currently-assigned blocks."""
        return len(self._blocks[slot]) * block_size

    def release(self, slot: int) -> list[int]:
        """Clear ``slot`` back to trash and return its blocks for
        :meth:`BlockAllocator.free`."""
        blocks = self._blocks[slot]
        self._blocks[slot] = []
        self.tables[slot, :] = TRASH_BLOCK
        self.lengths[slot] = 0
        return blocks
