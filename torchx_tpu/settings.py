"""Central registry of every environment variable the framework reads/writes.

Reference analog: torchx/settings.py:1-37 (all ``TORCHX_*`` env constants
centralized in one module). We use the ``TPX_`` prefix.

Variables fall into three groups:

* client-side knobs read by the Runner / CLI,
* in-job variables injected by schedulers into every replica,
* TPU runtime variables owned by the platform (GKE / libtpu) that the
  launcher must cooperate with rather than own.
"""

# ---------------------------------------------------------------------------
# Client side
# ---------------------------------------------------------------------------

# Points at an explicit config file, overriding the lookup chain
# (CLI > $TPXCONFIG > $HOME/.tpxconfig > CWD). See runner/config.py.
ENV_TPXCONFIG = "TPXCONFIG"

# Comma list of extra named-resource modules to load (module[:fn] specs).
ENV_TPX_CUSTOM_NAMED_RESOURCES = "TPX_CUSTOM_NAMED_RESOURCES"

# Bitmask controlling which plugin sources are consulted (see plugins/).
ENV_TPX_PLUGINS_SOURCE = "TPX_PLUGINS_SOURCE"

# Propagates the client session id into subprocesses for event correlation.
ENV_TPX_INTERNAL_SESSION_ID = "TPX_INTERNAL_SESSION_ID"

# Scheduler params harvested by the Runner from the environment, e.g.
# TPX_PARAMS_LOG_DIR=... (analog of TORCHX_* param harvesting,
# reference torchx/runner/api.py:128-134).
ENV_TPX_PARAMS_PREFIX = "TPX_PARAMS_"

# Telemetry destination for the events logger ("null"/"console"/"log"/
# "jsonl"/... — see runner/events/handlers.py).
ENV_TPX_EVENT_DESTINATION = "TPX_EVENT_DESTINATION"

# Tracing master switch: "0"/"false"/"off" disables span emission and the
# durable JSONL/metrics sinks (default: on — the launch path is low-rate).
ENV_TPX_TRACE = "TPX_TRACE"

# Root directory for durable observability output; defaults to
# ~/.torchx_tpu/obs (one subdir per client session). See obs/sinks.py.
ENV_TPX_OBS_DIR = "TPX_OBS_DIR"

# Step-profiler master switch: "1"/"true"/"yes"/"on" enables the trainer's
# per-step phase attribution (equivalent to its ``--profile`` flag),
# appending profile.jsonl under the obs session dir. See obs/profile.py.
ENV_TPX_PROFILE = "TPX_PROFILE"

# Escape hatch for the preflight analyzer gate in Runner.dryrun/run:
# "1"/"true"/"yes"/"on" skips linting entirely (same effect as the
# ``--no-lint`` CLI flag / ``no_lint=True`` Runner argument). Diagnostics
# are documented in docs/api/analyze.md; see torchx_tpu/analyze/.
ENV_TPX_NO_LINT = "TPX_NO_LINT"

# Per-call deadline (seconds) for control-plane subprocesses (gcloud /
# kubectl / sbatch / squeue ...) issued through the resilient seam
# (torchx_tpu/resilience/call.py). Unset = DEFAULT_CONTROL_PLANE_TIMEOUT;
# "0"/"off"/"none" disables the deadline entirely.
ENV_TPX_CONTROL_PLANE_TIMEOUT = "TPX_CONTROL_PLANE_TIMEOUT"

# Default for ENV_TPX_CONTROL_PLANE_TIMEOUT: generous enough for a slow
# gcloud auth refresh, small enough that a hung CLI degrades into one
# classified TIMEOUT failure instead of wedging a supervise loop.
DEFAULT_CONTROL_PLANE_TIMEOUT = 60.0

# Deterministic control-plane fault plan (inline JSON or a path to a JSON
# file) consumed by torchx_tpu/resilience/faults.py: inject transient /
# permanent / timeout / garbage-stdout failures on the Nth matching call,
# per backend+op. The control-plane counterpart of the local scheduler's
# TPX_SIMULATE_PREEMPTION_EXIT job-failure drill. The preflight analyzer
# errors (TPX502) when a plan is armed for a non-local submit.
ENV_TPX_FAULT_PLAN = "TPX_FAULT_PLAN"

# Root directory for durable supervisor session state (attempt ledger +
# resume metadata); defaults to ~/.torchx_tpu/supervisor — one subdir per
# supervise session, the ~/.torchx_tpu/obs/<session>/ convention. See
# torchx_tpu/supervisor/ledger.py and `tpx supervise --resume`.
ENV_TPX_SUPERVISOR_DIR = "TPX_SUPERVISOR_DIR"

# TTL (seconds) for the Runner's describe cache: passive readers
# (status/describe, supervision double-polls) within the TTL share one
# backend call; wait() polls always refresh (cache writer) and terminal
# states are pinned forever (immutable, so never stale). "0" disables
# caching for non-terminal states. Default DEFAULT_DESCRIBE_CACHE_TTL.
ENV_TPX_DESCRIBE_CACHE_TTL = "TPX_DESCRIBE_CACHE_TTL"

# Default for ENV_TPX_DESCRIBE_CACHE_TTL: shorter than any poll interval
# the Runner uses, so back-to-back polls from stacked layers coalesce but
# successive wait ticks always observe fresh state.
DEFAULT_DESCRIBE_CACHE_TTL = 1.0

# State root for the config autotuner (`tpx tune`): per-run trial
# journals + the persisted per-generation cost-model calibration table
# (torchx_tpu/tune/). Default ~/.torchx_tpu/tune.
ENV_TPX_TUNE_DIR = "TPX_TUNE_DIR"

# Device count assumed by `tpx tune` when --devices is not passed
# (defaults to 8, one v5p host).
ENV_TPX_TUNE_DEVICES = "TPX_TUNE_DEVICES"

# Path to a tune plan artifact (torchx_tpu/tune/artifact.py) pinned for
# submission: the submit gate (rules.check_plan_artifact) diffs every
# plan-shaped role against it and errors on divergence (TPX706) or an
# unreadable/digest-mismatched artifact (TPX707). Unset = no pinning.
ENV_TPX_PLAN_ARTIFACT = "TPX_PLAN_ARTIFACT"

# Address ("host:port") of a running `tpx control` daemon. When set, the
# CLI transparently proxies submit/status/list/cancel/log through the
# daemon's HTTP API instead of driving schedulers directly — thousands of
# callers then share ONE reconciler and ONE describe path per backend.
# Unset = direct-runner mode (the pre-daemon behavior, unchanged).
ENV_TPX_CONTROL_ADDR = "TPX_CONTROL_ADDR"

# Bearer token presented to the control daemon. Falls back to the token
# recorded in the daemon's discovery file ($TPX_CONTROL_DIR/control.json).
ENV_TPX_CONTROL_TOKEN = "TPX_CONTROL_TOKEN"

# State root for the control plane: the daemon's discovery file and the
# sharded job-state store live here. Default ~/.torchx_tpu/control.
ENV_TPX_CONTROL_DIR = "TPX_CONTROL_DIR"

# Minimum interval (seconds) between full-registry metrics textfile
# re-renders by the prom event handler (obs/sinks.py); events arriving
# inside the window mark the registry dirty and a final flush on handler
# close writes them. "0" restores flush-on-every-event.
ENV_TPX_METRICS_MIN_INTERVAL = "TPX_METRICS_MIN_INTERVAL"
DEFAULT_METRICS_MIN_INTERVAL = 2.0

# Scrape/ingest interval (seconds) of the control daemon's telemetry
# collector (obs/telemetry.py): replica /metricz scrapes + obs-session
# textfile ingestion each cycle, followed by one SLO evaluation.
ENV_TPX_TELEMETRY_INTERVAL = "TPX_TELEMETRY_INTERVAL"
DEFAULT_TELEMETRY_INTERVAL = 5.0

# Bounded per-series ring-buffer capacity (samples) of the telemetry
# collector's metric store. At the default 5s interval, 720 samples is
# one hour of history per series.
DEFAULT_TELEMETRY_CAPACITY = 720

# Poll interval (seconds) for watch adapters that fall back to polling
# (generic backends) and for the local scheduler's sidecar mtime watcher.
# Watch streams coalesce N callers into one scan, so this can be much
# tighter than Runner.wait's per-caller interval without amplifying
# control-plane calls.
ENV_TPX_WATCH_INTERVAL = "TPX_WATCH_INTERVAL"
DEFAULT_WATCH_INTERVAL = 1.0

# Default per-tenant cap on concurrently active (non-terminal) jobs
# submitted through the control daemon; submits past the cap get HTTP 429
# (daemon-only mode; with the fleet scheduler enabled submits queue instead).
DEFAULT_CONTROL_TENANT_CAP = 64

# Seconds a 429'd client should wait before resubmitting (the daemon's
# Retry-After header and the retry_after_seconds field of the error body).
CONTROL_RETRY_AFTER_SECONDS = 5

# Bounded 429 retry budget of ControlClient: how many times a throttled
# request sleeps out the daemon's Retry-After hint and retries before the
# 429 surfaces to the caller. A 429'd request never executed, so the
# retry is replay-safe (unlike transport errors on submits).
CONTROL_429_MAX_RETRIES = 3

# Ceiling (seconds) on a single Retry-After sleep honored by the client —
# a daemon bug (or a hostile proxy) must not park a CLI for an hour.
CONTROL_429_RETRY_CAP_SECONDS = 30.0

# This control daemon's cell name within a federation. Every journal
# record, /healthz reply and metric the daemon emits carries it, so a
# federation router (torchx_tpu/federation/) can address N regional
# daemons as cells. Unset = "default" (single-cell, pre-federation
# behavior unchanged).
ENV_TPX_CELL = "TPX_CELL"
DEFAULT_CELL_NAME = "default"

# State root of the federation layer: the durable cell registry
# (cells.jsonl) lives here. Default ~/.torchx_tpu/federation.
ENV_TPX_FEDERATION_DIR = "TPX_FEDERATION_DIR"

# Long-window SLO burn rate at/above which the federation router stops
# preferring a cell and spills new traffic to the next-best cell (the
# cell stays admissible as a last resort — never a hard fail while any
# cell answers).
DEFAULT_FEDERATION_BURN_BUDGET = 1.0

# Per-cell circuit breaker of the federation router: consecutive
# transport failures before the cell is skipped without a dial, and how
# long it sits out before a half-open probe.
FEDERATION_BREAKER_TRIP_AFTER = 3
FEDERATION_BREAKER_COOLDOWN_SECONDS = 5.0

# ---------------------------------------------------------------------------
# In-job (injected by schedulers into every replica)
# ---------------------------------------------------------------------------

# App handle / id of the surrounding job.
ENV_TPX_APP_ID = "TPX_APP_ID"
ENV_TPX_JOB_ID = "TPX_JOB_ID"  # full handle scheme://session/app_id

# Replica identity within the role's gang. TPX_REPLICA_ID, when present, is
# the GLOBAL process id across all slices of the role (0..TPX_NUM_REPLICAS-1).
ENV_TPX_REPLICA_ID = "TPX_REPLICA_ID"
ENV_TPX_ROLE_NAME = "TPX_ROLE_NAME"
ENV_TPX_NUM_REPLICAS = "TPX_NUM_REPLICAS"

# Multi-slice decomposition of the global id. Backends that cannot compute
# arithmetic at pod start (kubelet env expansion is substitution-only) inject
# these three instead of TPX_REPLICA_ID and the bootstrap derives
# ``replica_id = slice_id * hosts_per_slice + host_id``.
ENV_TPX_SLICE_ID = "TPX_SLICE_ID"

# Fault-injection hook for the example apps (examples/compute_mesh_size):
# "1" always throws, "once:/path/marker" throws only on the first attempt.
# _REPLICA scopes the fault to one replica of the gang. Used by
# retry/elastic-restart e2e tests to prove a gang recovers.
ENV_TPX_EXAMPLE_THROWS = "TPX_EXAMPLE_THROWS"
ENV_TPX_EXAMPLE_THROWS_REPLICA = "TPX_EXAMPLE_THROWS_REPLICA"
ENV_TPX_HOST_ID = "TPX_HOST_ID"  # host index within the slice
ENV_TPX_HOSTS_PER_SLICE = "TPX_HOSTS_PER_SLICE"

# Elastic lower bound of the gang (replicas may legally shrink to this on
# restart after host loss; see local_scheduler._try_elastic_restart).
ENV_TPX_MIN_REPLICAS = "TPX_MIN_REPLICAS"

# Host that replica 0 of role 0 runs on -- the SPMD coordinator. The *name*
# of the env var holding it is what ``macros.coordinator_env`` substitutes
# (reference analog: rank0_env, torchx/specs/api.py:216-222).
ENV_TPX_COORDINATOR_HOST = "TPX_COORDINATOR_HOST"

# Default port for jax.distributed coordinator service (analog of c10d 29500).
TPX_COORDINATOR_PORT = 8476

# File each replica writes a structured error JSON into on failure
# (reference analog: TORCHELASTIC_ERROR_FILE, local_scheduler.py:996-1001).
ENV_TPX_ERROR_FILE = "TPX_ERROR_FILE"

# Per-replica log directory.
ENV_TPX_LOG_DIR = "TPX_LOG_DIR"

# Trace correlation: the client injects these at submit so in-job spans
# (spmd_main bootstrap, train_llama heartbeats) join the client-side trace
# instead of starting orphan traces. See obs/trace.py.
ENV_TPX_TRACE_ID = "TPX_TRACE_ID"
ENV_TPX_PARENT_SPAN = "TPX_PARENT_SPAN"

# Checkpoint step a resubmitted (supervised) run should resume from. The
# supervisor injects it from the checkpoint manifest before every
# resubmission; Checkpointer.resume_step_from_env() is the in-job reader.
ENV_TPX_RESUME_STEP = "TPX_RESUME_STEP"

# Mesh spec override (--mesh syntax, e.g. "pp=1,dp=1,fsdp=4,ep=1,tp=1,sp=1")
# the supervisor injects when an elastic reshape degrades the mesh after a
# preemption/hang; trainers honor it over their --mesh flag so a resubmitted
# attempt comes up on the surviving capacity.
ENV_TPX_MESH = "TPX_MESH"

# Injected by the fleet scheduler into every replica it places: the fleet
# job id (stable across shrink/grow reshapes) and the gang's priority
# class, so in-job tooling and log lines can be joined back to the
# scheduling decision that produced them.
ENV_TPX_FLEET_JOB = "TPX_FLEET_JOB"
ENV_TPX_FLEET_CLASS = "TPX_FLEET_CLASS"

# Preemption drill knob for the LOCAL scheduler only: when a role env sets
# this to an integer exit code, a replica exiting with that code marks the
# attempt PREEMPTED (classified FailureClass.PREEMPTION) instead of FAILED,
# so `tpx supervise` retry/backoff/resume handling can be exercised end to
# end without spot capacity. Unset = no behavior change.
ENV_TPX_SIMULATE_PREEMPTION_EXIT = "TPX_SIMULATE_PREEMPTION_EXIT"

# Manifest file the Checkpointer maintains next to its step dirs: a small
# JSON record of the latest finalized step, readable by the client-side
# supervisor WITHOUT importing jax/orbax (see supervisor/api.py).
CHECKPOINT_MANIFEST = "MANIFEST.json"

# Experiment tracking (reference analog: TORCHX_TRACKERS family,
# torchx/tracker/api.py:209-239).
ENV_TPX_TRACKERS = "TPX_TRACKERS"
ENV_TPX_TRACKER_PREFIX = "TPX_TRACKER_"  # TPX_TRACKER_<NAME>_CONFIG
ENV_TPX_PARENT_RUN_ID = "TPX_PARENT_RUN_ID"

# ---------------------------------------------------------------------------
# TPU platform variables (owned by GKE / libtpu / JAX; the launcher reads or
# forwards these but does not invent them)
# ---------------------------------------------------------------------------

# Injected by GKE on TPU node pools; authoritative host list for a slice.
ENV_TPU_WORKER_ID = "TPU_WORKER_ID"
ENV_TPU_WORKER_HOSTNAMES = "TPU_WORKER_HOSTNAMES"
ENV_TPU_SKIP_MDS_QUERY = "TPU_SKIP_MDS_QUERY"

# Host-local chip partitioning (used by the local scheduler to split one
# host's chips between replicas -- analog of auto_set_CUDA_VISIBLE_DEVICES,
# reference local_scheduler.py:855-945).
ENV_TPU_VISIBLE_CHIPS = "TPU_VISIBLE_CHIPS"
ENV_TPU_PROCESS_BOUNDS = "TPU_PROCESS_BOUNDS"
ENV_TPU_CHIPS_PER_PROCESS_BOUNDS = "TPU_CHIPS_PER_PROCESS_BOUNDS"

# Simulation: run "TPU" jobs on CPU with N virtual devices.
ENV_JAX_PLATFORMS = "JAX_PLATFORMS"
ENV_XLA_FLAGS = "XLA_FLAGS"

# Multi-slice (DCN) wiring -- analog of the EFA device plumbing in the
# reference (named_resources_aws.py:40, kubernetes_scheduler.py:346-358).
ENV_MEGASCALE_COORDINATOR_ADDRESS = "MEGASCALE_COORDINATOR_ADDRESS"
ENV_MEGASCALE_NUM_SLICES = "MEGASCALE_NUM_SLICES"
ENV_MEGASCALE_SLICE_ID = "MEGASCALE_SLICE_ID"

# RMSNorm backward selection when the call site says "auto": "never"
# (default — plain XLA backward, measured fastest on v5e at batch 2),
# "pallas" (the fused dx+dw kernel; re-evaluate at batch >= 8), or
# "interpret" (Pallas interpreter — CPU tests). See ops/norms.py.
ENV_TPX_FUSED_NORM = "TPX_FUSED_NORM"
