from torchx_tpu.models.llama import (  # noqa: F401
    LlamaConfig,
    forward,
    init_params,
    loss_fn,
    param_specs,
    shard_params,
)
