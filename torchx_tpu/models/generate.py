"""KV-cache autoregressive generation for the Llama family.

The inference half of the model stack: prefill runs the stacked-layer scan
once over the prompt while collecting per-layer K/V; decode steps then
attend a single query token against the cache (O(seq) per token instead of
O(seq²) re-forwarding). Everything is ``lax.scan``/``dynamic_update_slice``
— static shapes, one compile for any prompt length up to ``max_seq``.

Greedy decoding is exactly argmax-teacher-forcing (tested against the full
forward), temperature>0 samples from the softmax.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from torchx_tpu.models import llama
from torchx_tpu.ops.norms import rms_norm
from torchx_tpu.ops.paged_attention import (
    append_kv,
    paged_attention,
    paged_attention_chunk,
    scatter_kv_chunk,
)
from torchx_tpu.ops.quant import maybe_matmul as mm
from torchx_tpu.ops.rope import apply_rope, rope_frequencies

KVCache = dict[str, jnp.ndarray]  # {"k": [L,b,S,kvh,hd], "v": ...}
KVPools = dict[str, jnp.ndarray]  # {"k": [L,num_blocks,block_size,kvh,hd]}


def init_kv_cache(
    cfg: llama.LlamaConfig, batch: int, max_seq: int
) -> KVCache:
    """Zeroed [layers, batch, max_seq, kv_heads, head_dim] K/V buffers."""
    shape = (cfg.n_layers, batch, max_seq, cfg.n_kv_heads, cfg.head_dim)
    return {
        "k": jnp.zeros(shape, dtype=cfg.dtype),
        "v": jnp.zeros(shape, dtype=cfg.dtype),
    }


def _cached_attention(
    q: jnp.ndarray,  # [b, t, h, d] (t = tokens this call)
    k_cache: jnp.ndarray,  # [b, S, kvh, d] — positions >= valid_len are zeros
    v_cache: jnp.ndarray,
    q_pos: jnp.ndarray,  # [t] absolute positions of the query tokens
) -> jnp.ndarray:
    b, t, h, d = q.shape
    S = k_cache.shape[1]
    n_rep = h // k_cache.shape[2]
    k = jnp.repeat(k_cache, n_rep, axis=2) if n_rep > 1 else k_cache
    v = jnp.repeat(v_cache, n_rep, axis=2) if n_rep > 1 else v_cache
    logits = (
        jnp.einsum("bthd,bshd->bhts", q, k, preferred_element_type=jnp.float32)
        * d**-0.5
    )
    # causal vs absolute cache positions: key position s visible to query at
    # absolute position p iff s <= p
    mask = jnp.arange(S)[None, :] <= q_pos[:, None]  # [t, S]
    logits = jnp.where(mask[None, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhts,bshd->bthd", probs, v)


def _layer_step(
    cfg: llama.LlamaConfig,
    cos: jnp.ndarray,  # [t, hd/2] rope slices for these positions
    sin: jnp.ndarray,
    q_pos: jnp.ndarray,  # [t]
    x: jnp.ndarray,  # [b, t, d]
    layer: llama.Params,
    k_cache: jnp.ndarray,  # [b, S, kvh, hd] this layer's cache
    v_cache: jnp.ndarray,
    start: jnp.ndarray,  # scalar: where these t tokens go in the cache
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    b, t, d = x.shape
    h, kvh, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    attn_in = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
    q = apply_rope(mm(attn_in, layer["wq"]).reshape(b, t, h, hd), cos, sin)
    k = apply_rope(mm(attn_in, layer["wk"]).reshape(b, t, kvh, hd), cos, sin)
    v = mm(attn_in, layer["wv"]).reshape(b, t, kvh, hd)
    k_cache = jax.lax.dynamic_update_slice(k_cache, k, (0, start, 0, 0))
    v_cache = jax.lax.dynamic_update_slice(v_cache, v, (0, start, 0, 0))
    attn = _cached_attention(q, k_cache, v_cache, q_pos)
    x = x + mm(attn.reshape(b, t, h * hd), layer["wo"])
    mlp_in = rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
    # the SAME dispatch as the training forward (dense SwiGLU or GShard
    # MoE — static shapes hold at t=1); the balancing aux is training-only
    down, _aux = llama.ffn(cfg, layer, mlp_in)
    x = x + down
    return x, k_cache, v_cache


def forward_with_cache(
    params: llama.Params,
    tokens: jnp.ndarray,  # [b, t]
    cache: KVCache,
    start: jnp.ndarray,  # scalar int: absolute position of tokens[:, 0]
    cfg: llama.LlamaConfig,
) -> tuple[jnp.ndarray, KVCache]:
    """-> (logits [b, t, vocab] f32, updated cache). Used for both prefill
    (t = prompt length) and decode (t = 1)."""
    b, t = tokens.shape
    S = cache["k"].shape[2]
    x = params["embed"][tokens].astype(cfg.dtype)
    q_pos = start + jnp.arange(t)
    cos_full, sin_full = rope_frequencies(cfg.head_dim, S, cfg.rope_theta)
    cos = jax.lax.dynamic_slice_in_dim(cos_full, start, t, axis=0)
    sin = jax.lax.dynamic_slice_in_dim(sin_full, start, t, axis=0)

    def scan_step(carry, layer_and_cache):  # noqa: ANN001
        x = carry
        layer, k_c, v_c = layer_and_cache
        x, k_c, v_c = _layer_step(cfg, cos, sin, q_pos, x, layer, k_c, v_c, start)
        return x, (k_c, v_c)

    x, (k_new, v_new) = jax.lax.scan(
        scan_step, x, (params["layers"], cache["k"], cache["v"])
    )
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    head = llama.lm_head(params, cfg)
    if isinstance(head, dict):  # int8-quantized lm_head: keep f32 accum
        logits = mm(x, head, out_dtype=jnp.float32)
    else:
        logits = jnp.einsum(
            "btd,dv->btv", x, head, preferred_element_type=jnp.float32
        )
    return logits, {"k": k_new, "v": v_new}


def _sample(logits_t: jnp.ndarray, key: jax.Array, temperature: float) -> jnp.ndarray:
    """Greedy at temperature 0, else categorical — the ONE sampling rule
    both the batch and streaming paths use (parity depends on it).

    ``key`` may be a single key (one sampling stream for the whole batch —
    the original behavior) or a ``[b, 2]`` stack of per-row keys, which
    draws each row from its own stream so requests with different seeds
    can share one device batch."""
    if temperature <= 0:
        return jnp.argmax(logits_t, axis=-1).astype(jnp.int32)
    if key.ndim == 2:  # per-row keys
        draw = jax.vmap(lambda l, k: jax.random.categorical(k, l / temperature))
        return draw(logits_t, key).astype(jnp.int32)
    return jax.random.categorical(key, logits_t / temperature, axis=-1).astype(
        jnp.int32
    )


def _split_keys(key: jax.Array) -> tuple[jax.Array, jax.Array]:
    """``jax.random.split`` that also accepts a ``[b, 2]`` stack of per-row
    keys (vmapped split, preserving one independent stream per row)."""
    if key.ndim == 2:
        ks = jax.vmap(jax.random.split)(key)  # [b, 2, 2]
        return ks[:, 0], ks[:, 1]
    k0, k1 = jax.random.split(key)
    return k0, k1


def _prefill(
    params: llama.Params,
    prompt: jnp.ndarray,
    cfg: llama.LlamaConfig,
    total: int,
    rng: jax.Array,
    temperature: float,
) -> tuple[KVCache, jnp.ndarray, jax.Array]:
    """Shared prompt pass: -> (cache, first sampled token, carried rng).
    Consumes a fresh subkey for token 0 and carries the unconsumed key, so
    step 0's draw is independent of step 1's."""
    cache = init_kv_cache(cfg, prompt.shape[0], total)
    logits, cache = forward_with_cache(params, prompt, cache, jnp.int32(0), cfg)
    rng, first_key = _split_keys(rng)
    return cache, _sample(logits[:, -1], first_key, temperature), rng


def generate(
    params: llama.Params,
    prompt: jnp.ndarray,  # [b, t0] int32
    cfg: llama.LlamaConfig,
    max_new_tokens: int,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
) -> jnp.ndarray:
    """-> [b, t0 + max_new_tokens]; greedy when temperature == 0.

    Works for dense and MoE configs alike (the cached layer dispatches to
    the GShard expert FFN when the config carries experts). Note MoE
    capacity is computed per call width, so aggressive ``capacity_factor``
    settings can drop different tokens at prefill vs full forward.

    ``rng`` may be a single PRNG key (one sampling stream shared by the
    batch) or a ``[b, 2]`` stack of per-row keys, giving every row its own
    stream — this is how requests with different seeds coalesce into one
    device batch. Row ``i`` of a stacked call draws the same tokens as a
    single-row call seeded with row ``i``'s key."""
    b, t0 = prompt.shape
    total = t0 + max_new_tokens
    if total > cfg.max_seq:
        raise ValueError(
            f"prompt + new tokens ({total}) exceeds max_seq {cfg.max_seq}"
        )
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    cache, next_tok, rng = _prefill(params, prompt, cfg, total, rng, temperature)

    def sample(logits_t, key):  # noqa: ANN001
        return _sample(logits_t, key, temperature)
    out = jnp.zeros((b, max_new_tokens), dtype=jnp.int32)
    out = out.at[:, 0].set(next_tok)

    def step(carry, i):  # noqa: ANN001
        cache, tok, out, key = carry
        key, sub = _split_keys(key)
        logits, cache = forward_with_cache(
            params, tok[:, None], cache, t0 + i, cfg
        )
        nxt = sample(logits[:, -1], sub)
        # scan runs i in [0, max_new_tokens-2], so i+1 is always in range
        out = out.at[:, i + 1].set(nxt)
        return (cache, nxt, out, key), None

    if max_new_tokens > 1:
        (cache, _, out, _), _ = jax.lax.scan(
            step, (cache, next_tok, out, rng), jnp.arange(max_new_tokens - 1)
        )
    return jnp.concatenate([prompt, out], axis=1)


@functools.lru_cache(maxsize=64)
def _stream_fns(cfg: llama.LlamaConfig, total: int, temperature: float, chunk: int):
    """Jitted (prefill, decode_chunk) pair for one streaming shape — cached
    at module level so repeated streaming requests reuse the compiled
    programs instead of re-tracing per call (jax's own jit cache then
    handles distinct batch sizes under each entry)."""

    @jax.jit
    def prefill(params, prompt, rng):  # noqa: ANN001
        return _prefill(params, prompt, cfg, total, rng, temperature)

    @jax.jit
    def decode_chunk(params, cache, tok, rng, start):  # noqa: ANN001
        # always runs `chunk` steps (static shapes under jit); on the final
        # partial chunk the caller slices off the surplus tokens, whose
        # cache writes are never read again
        def step(carry, i):  # noqa: ANN001
            cache, tok, key = carry
            key, sub = _split_keys(key)
            logits, cache = forward_with_cache(params, tok[:, None], cache, start + i, cfg)
            nxt = _sample(logits[:, -1], sub, temperature)
            return (cache, nxt, key), nxt

        (cache, tok, rng), toks = jax.lax.scan(
            step, (cache, tok, rng), jnp.arange(chunk)
        )
        return cache, tok, rng, toks.swapaxes(0, 1)  # [b, chunk]

    return prefill, decode_chunk


def generate_stream(
    params: llama.Params,
    prompt: jnp.ndarray,  # [b, t0] int32
    cfg: llama.LlamaConfig,
    max_new_tokens: int,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
    chunk: int = 8,
):
    """Streaming :func:`generate`: yields ``[b, t]`` int32 arrays of NEW
    tokens as they decode (t <= ``chunk``), token-identical to the batch
    path at the same seed (shared ``_sample``/``_prefill``).

    Decode runs in jitted ``chunk``-step segments — one device dispatch +
    one host transfer per chunk; the compiled programs are cached across
    calls (:func:`_stream_fns`). Arguments are validated eagerly (this is
    a generator; callers see errors before any output is produced)."""
    if chunk < 1:
        raise ValueError(f"chunk must be >= 1, got {chunk}")
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    b, t0 = prompt.shape
    total = t0 + max_new_tokens
    if total > cfg.max_seq:
        raise ValueError(
            f"prompt + new tokens ({total}) exceeds max_seq {cfg.max_seq}"
        )
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    prefill, decode_chunk = _stream_fns(cfg, total, float(temperature), chunk)

    def run():
        cache, tok, carried = prefill(params, prompt, rng)
        yield jax.device_get(tok)[:, None]
        produced = 1
        state = (cache, tok, carried)
        while produced < max_new_tokens:
            n = min(chunk, max_new_tokens - produced)
            cache, tok, carried, toks = decode_chunk(
                params, *state, jnp.int32(t0 + produced - 1)
            )
            state = (cache, tok, carried)
            yield jax.device_get(toks)[:, :n]
            produced += n

    return run()


# ---------------------------------------------------------------------------
# Paged-KV serving path (continuous batching; see torchx_tpu/serve/)
# ---------------------------------------------------------------------------
#
# Same layer math as the dense path above — rms_norm / rope / ffn / lm_head
# are shared, and the attention softmax masks exactly the positions the
# dense mask admits — but K/V live in a block-table pool
# ([L, num_blocks, block_size, kvh, hd]) instead of per-request
# [L, b, max_seq, ...] buffers, and every slot carries its own position,
# RNG stream, and temperature so unrelated requests share one jitted step.


def init_kv_pools(
    cfg: llama.LlamaConfig, num_blocks: int, block_size: int
) -> KVPools:
    """Zeroed paged K/V pools, ``[layers, num_blocks, block_size, kvh, hd]``
    (block 0 is the trash block — see :mod:`torchx_tpu.ops.paged_attention`)."""
    shape = (cfg.n_layers, num_blocks, block_size, cfg.n_kv_heads, cfg.head_dim)
    return {
        "k": jnp.zeros(shape, dtype=cfg.dtype),
        "v": jnp.zeros(shape, dtype=cfg.dtype),
    }


def _rope_rows(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray) -> jnp.ndarray:
    """:func:`apply_rope` for one token per row at per-row positions:
    ``x`` [rows, heads, hd], ``cos``/``sin`` [rows, hd/2] (same float32
    rotation, so paged decode matches the dense path bit-for-bit)."""
    dtype = x.dtype
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    c = cos[:, None, :]
    s = sin[:, None, :]
    return jnp.concatenate((x1 * c - x2 * s, x2 * c + x1 * s), axis=-1).astype(dtype)


def _sample_rows(
    logits: jnp.ndarray,  # [rows, vocab]
    keys: jnp.ndarray,  # [rows, 2] per-row PRNG keys
    temps: jnp.ndarray,  # [rows] — <= 0 means greedy for that row
) -> jnp.ndarray:
    """Per-row :func:`_sample` where temperature is data, not static: each
    row greedy-decodes or draws from its own stream at its own temperature
    (a continuous batch mixes requests with different sampling params)."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    safe_t = jnp.where(temps > 0, temps, 1.0)[:, None]
    draw = jax.vmap(lambda l, k: jax.random.categorical(k, l))
    sampled = draw(logits / safe_t, keys).astype(jnp.int32)
    return jnp.where(temps > 0, sampled, greedy)


def _paged_layer_step(
    cfg: llama.LlamaConfig,
    cos: jnp.ndarray,  # [slots, hd/2] rope rows at each slot's position
    sin: jnp.ndarray,
    positions: jnp.ndarray,  # [slots] — cache index the new token writes to
    tables: jnp.ndarray,  # [slots, blocks_per_slot] int32
    x: jnp.ndarray,  # [slots, 1, d]
    layer: llama.Params,
    k_pool: jnp.ndarray,  # [num_blocks, bs, kvh, hd] this layer's pool
    v_pool: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    slots = x.shape[0]
    h, kvh, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    attn_in = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
    q = _rope_rows(mm(attn_in, layer["wq"]).reshape(slots, h, hd), cos, sin)
    k = _rope_rows(mm(attn_in, layer["wk"]).reshape(slots, kvh, hd), cos, sin)
    v = mm(attn_in, layer["wv"]).reshape(slots, kvh, hd)
    k_pool = append_kv(k_pool, tables, positions, k)
    v_pool = append_kv(v_pool, tables, positions, v)
    attn = paged_attention(q, k_pool, v_pool, tables, positions + 1)
    x = x + mm(attn.reshape(slots, 1, h * hd), layer["wo"])
    mlp_in = rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
    down, _aux = llama.ffn(cfg, layer, mlp_in)
    x = x + down
    return x, k_pool, v_pool


def _lm_head_rows(params: llama.Params, x: jnp.ndarray, cfg: llama.LlamaConfig):
    # [rows, d] -> [rows, vocab] f32, same head dispatch as forward_with_cache
    head = llama.lm_head(params, cfg)
    if isinstance(head, dict):  # int8-quantized lm_head: keep f32 accum
        return mm(x, head, out_dtype=jnp.float32)
    return jnp.einsum("rd,dv->rv", x, head, preferred_element_type=jnp.float32)


def paged_decode_step(
    params: llama.Params,
    tokens: jnp.ndarray,  # [slots] int32 — last sampled token per slot
    positions: jnp.ndarray,  # [slots] int32 — where each token's K/V goes
    tables: jnp.ndarray,  # [slots, blocks_per_slot] int32 block tables
    pools: KVPools,
    cfg: llama.LlamaConfig,
    keys: jnp.ndarray,  # [slots, 2] per-slot PRNG keys for THIS position
    temps: jnp.ndarray,  # [slots] f32 — <= 0 greedy
) -> tuple[jnp.ndarray, KVPools]:
    """One continuous-batching decode step over the whole slot array.

    -> (next token [slots], updated pools). Every slot advances one token
    against its own block table at its own position; inactive slots
    (table all trash, position 0) compute garbage that lands in the trash
    block and is never read. Static shapes: one XLA compile per
    (slots, pool geometry), regardless of which requests occupy the slots.
    Jit with ``donate_argnums`` on ``pools`` so the pool updates in place.
    """
    slots = tokens.shape[0]
    x = params["embed"][tokens].astype(cfg.dtype)[:, None, :]  # [slots, 1, d]
    cos_full, sin_full = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
    cos, sin = cos_full[positions], sin_full[positions]  # [slots, hd/2]

    def scan_step(carry, layer_and_pools):  # noqa: ANN001
        x = carry
        layer, k_p, v_p = layer_and_pools
        x, k_p, v_p = _paged_layer_step(
            cfg, cos, sin, positions, tables, x, layer, k_p, v_p
        )
        return x, (k_p, v_p)

    x, (k_new, v_new) = jax.lax.scan(
        scan_step, x, (params["layers"], pools["k"], pools["v"])
    )
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)[:, 0, :]  # [slots, d]
    logits = _lm_head_rows(params, x, cfg)
    nxt = _sample_rows(logits, keys, temps)
    return nxt, {"k": k_new, "v": v_new}


def paged_prefill(
    params: llama.Params,
    prompts: jnp.ndarray,  # [b, t] int32, right-padded to the bucket width
    true_lens: jnp.ndarray,  # [b] int32 — real prompt lengths
    block_ids: jnp.ndarray,  # [b, t // block_size] physical blocks per row
    pools: KVPools,
    cfg: llama.LlamaConfig,
    keys: jnp.ndarray,  # [b, 2] per-row PRNG keys for the first token
    temps: jnp.ndarray,  # [b] f32
) -> tuple[jnp.ndarray, KVPools]:
    """Prefill a bucket of prompts straight into the paged pools.

    Runs the dense stacked-layer prefill over the right-padded bucket
    (causal masking keeps every position < ``true_lens[i]`` exact despite
    the padding), scatters the bucket's K/V into each row's assigned
    blocks, and samples the first output token from the logits at
    ``true_lens[i] - 1``. ``t`` must be a multiple of the pool block size;
    rows that need fewer blocks pad ``block_ids`` with the trash block.
    -> (first token [b], updated pools).
    """
    b, t = prompts.shape
    cache = init_kv_cache(cfg, b, t)
    logits, cache = forward_with_cache(params, prompts, cache, jnp.int32(0), cfg)
    bs = pools["k"].shape[2]
    nb = t // bs
    kvh, hd = cfg.n_kv_heads, cfg.head_dim
    k = cache["k"].reshape(cfg.n_layers, b, nb, bs, kvh, hd)
    v = cache["v"].reshape(cfg.n_layers, b, nb, bs, kvh, hd)
    pools = {
        "k": pools["k"].at[:, block_ids].set(k, mode="drop"),
        "v": pools["v"].at[:, block_ids].set(v, mode="drop"),
    }
    last = logits[jnp.arange(b), true_lens - 1]  # [b, vocab]
    return _sample_rows(last, keys, temps), pools


def _rope_chunk(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray) -> jnp.ndarray:
    """:func:`apply_rope` for a chunk of tokens at per-(row, token)
    positions: ``x`` [b, t, heads, hd], ``cos``/``sin`` [b, t, hd/2] —
    the same float32 rotation as :func:`_rope_rows`."""
    dtype = x.dtype
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    c = cos[:, :, None, :]
    s = sin[:, :, None, :]
    return jnp.concatenate((x1 * c - x2 * s, x2 * c + x1 * s), axis=-1).astype(dtype)


def _paged_chunk_layer_step(
    cfg: llama.LlamaConfig,
    cos: jnp.ndarray,  # [b, t, hd/2] rope rows at each token's position
    sin: jnp.ndarray,
    positions: jnp.ndarray,  # [b, t] absolute cache positions
    valid: jnp.ndarray,  # [b, t] bool — real suffix tokens
    tables: jnp.ndarray,  # [b, blocks_per_slot] int32
    x: jnp.ndarray,  # [b, t, d]
    layer: llama.Params,
    k_pool: jnp.ndarray,
    v_pool: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    b, t, _ = x.shape
    h, kvh, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    attn_in = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
    q = _rope_chunk(mm(attn_in, layer["wq"]).reshape(b, t, h, hd), cos, sin)
    k = _rope_chunk(mm(attn_in, layer["wk"]).reshape(b, t, kvh, hd), cos, sin)
    v = mm(attn_in, layer["wv"]).reshape(b, t, kvh, hd)
    k_pool = scatter_kv_chunk(k_pool, tables, positions, k, valid)
    v_pool = scatter_kv_chunk(v_pool, tables, positions, v, valid)
    attn = paged_attention_chunk(q, k_pool, v_pool, tables, positions)
    x = x + mm(attn.reshape(b, t, h * hd), layer["wo"])
    mlp_in = rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
    down, _aux = llama.ffn(cfg, layer, mlp_in)
    x = x + down
    return x, k_pool, v_pool


def paged_prefill_chunk(
    params: llama.Params,
    tokens: jnp.ndarray,  # [b, t] int32 suffix tokens, right-padded
    prefix_lens: jnp.ndarray,  # [b] int32 — cached tokens already in the pool
    suffix_lens: jnp.ndarray,  # [b] int32 — real suffix lengths (>= 1)
    tables: jnp.ndarray,  # [b, blocks_per_slot] full per-row block tables
    pools: KVPools,
    cfg: llama.LlamaConfig,
    keys: jnp.ndarray,  # [b, 2] per-row PRNG keys for the first token
    temps: jnp.ndarray,  # [b] f32
) -> tuple[jnp.ndarray, KVPools]:
    """Prefill only the *uncached suffix* of each prompt against the pool.

    The prefix-cache fast path: row ``i``'s first ``prefix_lens[i]``
    tokens already sit in cached blocks referenced by ``tables[i]``; this
    computes K/V for the suffix chunk, scatters it into the row's freshly
    allocated blocks, and attends each suffix token causally over cached
    prefix + chunk through the same block tables. With ``prefix_lens = 0``
    it is a cold paged prefill, so cached and cold requests run the exact
    same program — reused prefix blocks hold bit-identical K/V to what
    the cold path would recompute, keeping decode parity exact.

    ``t`` is the suffix bucket width; samples the first output token from
    the logits at each row's last real suffix position.
    -> (first token [b], updated pools).
    """
    b, t = tokens.shape
    x = params["embed"][tokens].astype(cfg.dtype)  # [b, t, d]
    cos_full, sin_full = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
    positions = prefix_lens[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]
    pos_safe = jnp.clip(positions, 0, cfg.max_seq - 1)
    cos, sin = cos_full[pos_safe], sin_full[pos_safe]  # [b, t, hd/2]
    valid = jnp.arange(t)[None, :] < suffix_lens[:, None]

    def scan_step(carry, layer_and_pools):  # noqa: ANN001
        x = carry
        layer, k_p, v_p = layer_and_pools
        x, k_p, v_p = _paged_chunk_layer_step(
            cfg, cos, sin, positions, valid, tables, x, layer, k_p, v_p
        )
        return x, (k_p, v_p)

    x, (k_new, v_new) = jax.lax.scan(
        scan_step, x, (params["layers"], pools["k"], pools["v"])
    )
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)  # [b, t, d]
    last = x[jnp.arange(b), suffix_lens - 1]  # [b, d]
    logits = _lm_head_rows(params, last, cfg)
    return _sample_rows(logits, keys, temps), {"k": k_new, "v": v_new}
