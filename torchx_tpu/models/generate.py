"""KV-cache autoregressive generation for the Llama family.

The inference half of the model stack: prefill runs the stacked-layer scan
once over the prompt while collecting per-layer K/V; decode steps then
attend a single query token against the cache (O(seq) per token instead of
O(seq²) re-forwarding). Everything is ``lax.scan``/``dynamic_update_slice``
— static shapes, one compile for any prompt length up to ``max_seq``.

Greedy decoding is exactly argmax-teacher-forcing (tested against the full
forward), temperature>0 samples from the softmax.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from torchx_tpu.models import llama
from torchx_tpu.ops.norms import rms_norm
from torchx_tpu.ops.quant import maybe_matmul as mm
from torchx_tpu.ops.rope import apply_rope, rope_frequencies

KVCache = dict[str, jnp.ndarray]  # {"k": [L,b,S,kvh,hd], "v": ...}


def init_kv_cache(
    cfg: llama.LlamaConfig, batch: int, max_seq: int
) -> KVCache:
    """Zeroed [layers, batch, max_seq, kv_heads, head_dim] K/V buffers."""
    shape = (cfg.n_layers, batch, max_seq, cfg.n_kv_heads, cfg.head_dim)
    return {
        "k": jnp.zeros(shape, dtype=cfg.dtype),
        "v": jnp.zeros(shape, dtype=cfg.dtype),
    }


def _cached_attention(
    q: jnp.ndarray,  # [b, t, h, d] (t = tokens this call)
    k_cache: jnp.ndarray,  # [b, S, kvh, d] — positions >= valid_len are zeros
    v_cache: jnp.ndarray,
    q_pos: jnp.ndarray,  # [t] absolute positions of the query tokens
) -> jnp.ndarray:
    b, t, h, d = q.shape
    S = k_cache.shape[1]
    n_rep = h // k_cache.shape[2]
    k = jnp.repeat(k_cache, n_rep, axis=2) if n_rep > 1 else k_cache
    v = jnp.repeat(v_cache, n_rep, axis=2) if n_rep > 1 else v_cache
    logits = (
        jnp.einsum("bthd,bshd->bhts", q, k, preferred_element_type=jnp.float32)
        * d**-0.5
    )
    # causal vs absolute cache positions: key position s visible to query at
    # absolute position p iff s <= p
    mask = jnp.arange(S)[None, :] <= q_pos[:, None]  # [t, S]
    logits = jnp.where(mask[None, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhts,bshd->bthd", probs, v)


def _layer_step(
    cfg: llama.LlamaConfig,
    cos: jnp.ndarray,  # [t, hd/2] rope slices for these positions
    sin: jnp.ndarray,
    q_pos: jnp.ndarray,  # [t]
    x: jnp.ndarray,  # [b, t, d]
    layer: llama.Params,
    k_cache: jnp.ndarray,  # [b, S, kvh, hd] this layer's cache
    v_cache: jnp.ndarray,
    start: jnp.ndarray,  # scalar: where these t tokens go in the cache
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    b, t, d = x.shape
    h, kvh, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    attn_in = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
    q = apply_rope(mm(attn_in, layer["wq"]).reshape(b, t, h, hd), cos, sin)
    k = apply_rope(mm(attn_in, layer["wk"]).reshape(b, t, kvh, hd), cos, sin)
    v = mm(attn_in, layer["wv"]).reshape(b, t, kvh, hd)
    k_cache = jax.lax.dynamic_update_slice(k_cache, k, (0, start, 0, 0))
    v_cache = jax.lax.dynamic_update_slice(v_cache, v, (0, start, 0, 0))
    attn = _cached_attention(q, k_cache, v_cache, q_pos)
    x = x + mm(attn.reshape(b, t, h * hd), layer["wo"])
    mlp_in = rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
    # the SAME dispatch as the training forward (dense SwiGLU or GShard
    # MoE — static shapes hold at t=1); the balancing aux is training-only
    down, _aux = llama.ffn(cfg, layer, mlp_in)
    x = x + down
    return x, k_cache, v_cache


def forward_with_cache(
    params: llama.Params,
    tokens: jnp.ndarray,  # [b, t]
    cache: KVCache,
    start: jnp.ndarray,  # scalar int: absolute position of tokens[:, 0]
    cfg: llama.LlamaConfig,
) -> tuple[jnp.ndarray, KVCache]:
    """-> (logits [b, t, vocab] f32, updated cache). Used for both prefill
    (t = prompt length) and decode (t = 1)."""
    b, t = tokens.shape
    S = cache["k"].shape[2]
    x = params["embed"][tokens].astype(cfg.dtype)
    q_pos = start + jnp.arange(t)
    cos_full, sin_full = rope_frequencies(cfg.head_dim, S, cfg.rope_theta)
    cos = jax.lax.dynamic_slice_in_dim(cos_full, start, t, axis=0)
    sin = jax.lax.dynamic_slice_in_dim(sin_full, start, t, axis=0)

    def scan_step(carry, layer_and_cache):  # noqa: ANN001
        x = carry
        layer, k_c, v_c = layer_and_cache
        x, k_c, v_c = _layer_step(cfg, cos, sin, q_pos, x, layer, k_c, v_c, start)
        return x, (k_c, v_c)

    x, (k_new, v_new) = jax.lax.scan(
        scan_step, x, (params["layers"], cache["k"], cache["v"])
    )
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    head = llama.lm_head(params, cfg)
    if isinstance(head, dict):  # int8-quantized lm_head: keep f32 accum
        logits = mm(x, head, out_dtype=jnp.float32)
    else:
        logits = jnp.einsum(
            "btd,dv->btv", x, head, preferred_element_type=jnp.float32
        )
    return logits, {"k": k_new, "v": v_new}


def _sample(logits_t: jnp.ndarray, key: jax.Array, temperature: float) -> jnp.ndarray:
    """Greedy at temperature 0, else categorical — the ONE sampling rule
    both the batch and streaming paths use (parity depends on it)."""
    if temperature <= 0:
        return jnp.argmax(logits_t, axis=-1).astype(jnp.int32)
    return jax.random.categorical(key, logits_t / temperature, axis=-1).astype(
        jnp.int32
    )


def _prefill(
    params: llama.Params,
    prompt: jnp.ndarray,
    cfg: llama.LlamaConfig,
    total: int,
    rng: jax.Array,
    temperature: float,
) -> tuple[KVCache, jnp.ndarray, jax.Array]:
    """Shared prompt pass: -> (cache, first sampled token, carried rng).
    Consumes a fresh subkey for token 0 and carries the unconsumed key, so
    step 0's draw is independent of step 1's."""
    cache = init_kv_cache(cfg, prompt.shape[0], total)
    logits, cache = forward_with_cache(params, prompt, cache, jnp.int32(0), cfg)
    rng, first_key = jax.random.split(rng)
    return cache, _sample(logits[:, -1], first_key, temperature), rng


def generate(
    params: llama.Params,
    prompt: jnp.ndarray,  # [b, t0] int32
    cfg: llama.LlamaConfig,
    max_new_tokens: int,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
) -> jnp.ndarray:
    """-> [b, t0 + max_new_tokens]; greedy when temperature == 0.

    Works for dense and MoE configs alike (the cached layer dispatches to
    the GShard expert FFN when the config carries experts). Note MoE
    capacity is computed per call width, so aggressive ``capacity_factor``
    settings can drop different tokens at prefill vs full forward."""
    b, t0 = prompt.shape
    total = t0 + max_new_tokens
    if total > cfg.max_seq:
        raise ValueError(
            f"prompt + new tokens ({total}) exceeds max_seq {cfg.max_seq}"
        )
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    cache, next_tok, rng = _prefill(params, prompt, cfg, total, rng, temperature)

    def sample(logits_t, key):  # noqa: ANN001
        return _sample(logits_t, key, temperature)
    out = jnp.zeros((b, max_new_tokens), dtype=jnp.int32)
    out = out.at[:, 0].set(next_tok)

    def step(carry, i):  # noqa: ANN001
        cache, tok, out, key = carry
        key, sub = jax.random.split(key)
        logits, cache = forward_with_cache(
            params, tok[:, None], cache, t0 + i, cfg
        )
        nxt = sample(logits[:, -1], sub)
        # scan runs i in [0, max_new_tokens-2], so i+1 is always in range
        out = out.at[:, i + 1].set(nxt)
        return (cache, nxt, out, key), None

    if max_new_tokens > 1:
        (cache, _, out, _), _ = jax.lax.scan(
            step, (cache, next_tok, out, rng), jnp.arange(max_new_tokens - 1)
        )
    return jnp.concatenate([prompt, out], axis=1)


@functools.lru_cache(maxsize=64)
def _stream_fns(cfg: llama.LlamaConfig, total: int, temperature: float, chunk: int):
    """Jitted (prefill, decode_chunk) pair for one streaming shape — cached
    at module level so repeated streaming requests reuse the compiled
    programs instead of re-tracing per call (jax's own jit cache then
    handles distinct batch sizes under each entry)."""

    @jax.jit
    def prefill(params, prompt, rng):  # noqa: ANN001
        return _prefill(params, prompt, cfg, total, rng, temperature)

    @jax.jit
    def decode_chunk(params, cache, tok, rng, start):  # noqa: ANN001
        # always runs `chunk` steps (static shapes under jit); on the final
        # partial chunk the caller slices off the surplus tokens, whose
        # cache writes are never read again
        def step(carry, i):  # noqa: ANN001
            cache, tok, key = carry
            key, sub = jax.random.split(key)
            logits, cache = forward_with_cache(params, tok[:, None], cache, start + i, cfg)
            nxt = _sample(logits[:, -1], sub, temperature)
            return (cache, nxt, key), nxt

        (cache, tok, rng), toks = jax.lax.scan(
            step, (cache, tok, rng), jnp.arange(chunk)
        )
        return cache, tok, rng, toks.swapaxes(0, 1)  # [b, chunk]

    return prefill, decode_chunk


def generate_stream(
    params: llama.Params,
    prompt: jnp.ndarray,  # [b, t0] int32
    cfg: llama.LlamaConfig,
    max_new_tokens: int,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
    chunk: int = 8,
):
    """Streaming :func:`generate`: yields ``[b, t]`` int32 arrays of NEW
    tokens as they decode (t <= ``chunk``), token-identical to the batch
    path at the same seed (shared ``_sample``/``_prefill``).

    Decode runs in jitted ``chunk``-step segments — one device dispatch +
    one host transfer per chunk; the compiled programs are cached across
    calls (:func:`_stream_fns`). Arguments are validated eagerly (this is
    a generator; callers see errors before any output is produced)."""
    if chunk < 1:
        raise ValueError(f"chunk must be >= 1, got {chunk}")
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    b, t0 = prompt.shape
    total = t0 + max_new_tokens
    if total > cfg.max_seq:
        raise ValueError(
            f"prompt + new tokens ({total}) exceeds max_seq {cfg.max_seq}"
        )
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    prefill, decode_chunk = _stream_fns(cfg, total, float(temperature), chunk)

    def run():
        cache, tok, carried = prefill(params, prompt, rng)
        yield jax.device_get(tok)[:, None]
        produced = 1
        state = (cache, tok, carried)
        while produced < max_new_tokens:
            n = min(chunk, max_new_tokens - produced)
            cache, tok, carried, toks = decode_chunk(
                params, *state, jnp.int32(t0 + produced - 1)
            )
            state = (cache, tok, carried)
            yield jax.device_get(toks)[:, :n]
            produced += n

    return run()
