"""Llama-3-family decoder, pure-functional JAX, TPU-first.

Design choices for the TPU compilation model:

* **Stacked layer params + ``lax.scan``** over layers — one compiled layer
  body instead of n_layers unrolled copies: seconds-not-minutes compiles at
  8B scale, and XLA pipelines the scan cleanly.
* **``jax.checkpoint`` on the scan body** (``remat=True``) — recompute
  activations in backward, trading MXU FLOPs (abundant) for HBM (scarce).
* **bfloat16 params/activations, float32 softmax/norms/logits** — the
  standard TPU numerics recipe.
* **GSPMD sharding via PartitionSpec trees** — :func:`param_specs` maps
  every param to the canonical 5-axis mesh (pp/dp/fsdp/tp/sp);
  :func:`forward` drops ``with_sharding_constraint`` hints on the residual
  stream so XLA places the collectives (all-gather for fsdp params,
  all-reduce for tp partials) on ICI.
* **Ring attention** over the ``sp`` axis for long-context training
  (config.use_ring_attention), falling back to full (flash) attention when
  the sequence is unsharded.

The flagship model config matches Llama-3-8B (meta-llama/Meta-Llama-3-8B
architecture: 32 layers, 4096 dim, 32 heads / 8 KV heads, 14336 FFN,
128256 vocab, rope theta 500k).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torchx_tpu.parallel import mesh as mesh_lib
from torchx_tpu.ops.attention import attention
from torchx_tpu.ops.norms import rms_norm
from torchx_tpu.ops.quant import maybe_matmul
from torchx_tpu.ops.ring_attention import ring_attention
from torchx_tpu.ops.rope import apply_rope, rope_frequencies

Params = dict[str, Any]

# Layout of the router-health aux vector threaded through every forward:
# [Switch balance loss, normalized router entropy, capacity-overflow
# fraction]. Dense layers contribute zeros. Shared by moe.moe_ffn (the
# producer), the trainer's log line, and the dryrun gate — index through
# these names, never bare integers.
AUX_BALANCE, AUX_ENTROPY, AUX_OVERFLOW = 0, 1, 2
AUX_LEN = 3


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_dim: int = 14336
    max_seq: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    tie_embeddings: bool = False
    remat: bool = True
    # remat policy: "full" recomputes everything (min memory);
    # "dots" saves matmul outputs (fewer recomputes, more memory)
    remat_policy: str = "full"
    attn_impl: str = "auto"  # auto | xla | pallas | splash
    # flash-attention tile sizes (0 = kernel defaults); tune for head_dim
    # (profiling: defaults underfill the MXU at head_dim 64 — see
    # docs/performance.md)
    attn_block_q: int = 0
    attn_block_kv: int = 0
    use_ring_attention: bool = False
    # cross-entropy is computed in sequence chunks of this size so the
    # [batch, seq, vocab] float32 logits never materialize (the dominant
    # activation at 128k vocab); 0 disables chunking
    loss_chunk: int = 512
    # microbatches for pipeline parallelism (meshes with pp > 1);
    # 0 = auto (2x the pp degree — a 2(S-1)/(2S) bubble)
    pp_microbatches: int = 0
    # AQT int8 training matmuls for the layer projections (wq/wk/wv/wo +
    # FFN): int8 runs ~1.94x faster than bf16 on v5e MXUs (measured, see
    # docs/performance.md); master weights stay bf16, quantization is
    # dynamic per step with a straight-through estimator in the backward
    int8_matmuls: bool = False
    # which projections int8_matmuls quantizes: "all" (attention + FFN)
    # or "ffn" (gate/up/down only — the largest, most int8-friendly dots;
    # attention projections at head_dim granularity amortize the dynamic
    # quant/dequant overhead worst, so selective mode trims overhead at
    # small batch; measured crossover in docs/performance.md)
    int8_scope: str = "all"
    # store CE logits in f32 instead of bf16 (exact-f32 cross entropy at
    # 2x the logits HBM traffic; see _token_nll for the measured tradeoff)
    ce_f32_logits: bool = False
    # fused-kernel selection for the layer hot path (ops/fused.py):
    # "reference" keeps the stock ops; "pallas" swaps in the fused
    # flash-attention and residual+RMSNorm Mosaic kernels on TPU (each
    # call site falls back per-shape when gating fails — TPX112 warns at
    # launch time); "interpret" runs the same kernels in the Pallas
    # interpreter (CPU parity tests only — slow)
    kernels: str = "reference"

    def __post_init__(self) -> None:
        if self.int8_scope not in ("all", "ffn"):
            raise ValueError(
                f"int8_scope must be 'all' or 'ffn', got {self.int8_scope!r}"
            )
        if self.kernels not in ("reference", "pallas", "interpret"):
            raise ValueError(
                "kernels must be 'reference', 'pallas' or 'interpret',"
                f" got {self.kernels!r}"
            )

    @property
    def head_dim(self) -> int:
        """Per-head projection width (dim / n_heads)."""
        return self.dim // self.n_heads

    def flops_per_token(self) -> float:
        """Training FLOPs/token (fwd+bwd), 6N + attention quadratic term."""
        n_params = self.param_count()
        attn = (
            12
            * self.n_layers
            * self.dim
            * self.max_seq  # per-token causal avg is seq/2; 2*seq/2*... -> seq
        )
        return 6 * n_params + attn

    def param_count(self) -> int:
        """Exact parameter count for this shape (layers + embeddings)."""
        d, f, v = self.dim, self.ffn_dim, self.vocab_size
        hd = self.head_dim
        per_layer = (
            d * self.n_heads * hd  # wq
            + 2 * d * self.n_kv_heads * hd  # wk, wv
            + self.n_heads * hd * d  # wo
            + 3 * d * f  # gate, up, down
            + 2 * d  # norms
        )
        total = self.n_layers * per_layer + v * d + d  # embed + final norm
        if not self.tie_embeddings:
            total += d * v
        return total


# -- presets ---------------------------------------------------------------


def llama3_8b(**overrides: Any) -> LlamaConfig:
    """Llama-3-8B (the config defaults: 32L/4096d/32h/8kv/128k vocab)."""
    return LlamaConfig(**overrides)


def llama3_1b(**overrides: Any) -> LlamaConfig:
    """Llama-3.2-1B shape (tied embeddings).

    attn_block_q/kv defaults come from the hardware sweeps
    (``scripts/tune_attention_blocks.py`` on v5e-1, seq 2048): with the
    GQA-native splash kernel that ``attn_impl="auto"`` now picks on TPU,
    512/512 tiles measure 46.9% MFU (50.2% steady-state) vs 39.6% for the
    best flash tiling (256/512) and 23.9% at kernel-default 128 tiles —
    head_dim 64 underfills the MXU, larger tiles amortize it; full tables
    in docs/performance.md.
    """
    defaults = dict(
        dim=2048,
        n_layers=16,
        n_heads=32,
        n_kv_heads=8,
        ffn_dim=8192,
        tie_embeddings=True,
        attn_block_q=512,
        attn_block_kv=512,
    )
    defaults.update(overrides)
    return LlamaConfig(**defaults)


def llama_tiny(**overrides: Any) -> LlamaConfig:
    """Test/debug config: runs on anything in milliseconds."""
    defaults = dict(
        vocab_size=512,
        dim=64,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        ffn_dim=128,
        max_seq=128,
        dtype=jnp.float32,
        remat=False,
    )
    defaults.update(overrides)
    return LlamaConfig(**defaults)


CONFIGS = {
    "llama3_8b": llama3_8b,
    "llama3_1b": llama3_1b,
    "tiny": llama_tiny,
}


# -- parameters ------------------------------------------------------------


def init_params(cfg: LlamaConfig, key: jax.Array) -> Params:
    """Scaled-normal init; layer params stacked on a leading axis."""
    k_embed, k_layers, k_head = jax.random.split(key, 3)
    d, f = cfg.dim, cfg.ffn_dim
    hd, h, kvh, L = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads, cfg.n_layers

    def norm_init(key, shape, in_dim):  # noqa: ANN001
        return (
            jax.random.normal(key, shape, dtype=jnp.float32) * (in_dim**-0.5)
        ).astype(cfg.dtype)

    ks = jax.random.split(k_layers, 7)
    params: Params = {
        "embed": norm_init(k_embed, (cfg.vocab_size, d), d),
        "layers": {
            "attn_norm": jnp.ones((L, d), dtype=cfg.dtype),
            "wq": norm_init(ks[0], (L, d, h * hd), d),
            "wk": norm_init(ks[1], (L, d, kvh * hd), d),
            "wv": norm_init(ks[2], (L, d, kvh * hd), d),
            "wo": norm_init(ks[3], (L, h * hd, d), h * hd),
            "mlp_norm": jnp.ones((L, d), dtype=cfg.dtype),
            "w_gate": norm_init(ks[4], (L, d, f), d),
            "w_up": norm_init(ks[5], (L, d, f), d),
            "w_down": norm_init(ks[6], (L, f, d), f),
        },
        "final_norm": jnp.ones((d,), dtype=cfg.dtype),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = norm_init(k_head, (d, cfg.vocab_size), d)
    return params


def param_specs(cfg: LlamaConfig, pp: bool = False) -> Params:
    """PartitionSpec tree matching init_params, on the pp/dp/fsdp/tp/sp mesh.

    2D sharding: the "fsdp" axis shards the model dimension (ZeRO-3-style
    weight gather per layer under the scan), "tp" shards heads/ffn
    (Megatron-style, all-reduce after wo/w_down). The stacked layer axis
    shards over "pp" when pipeline parallelism is on (each stage owns a
    contiguous run of layers), else stays unsharded.
    """
    layer_axis = "pp" if pp else None
    specs: Params = {
        # vocab axis unsharded: a gather over a vocab-sharded table forces
        # the SPMD partitioner into full rematerialization; dim shards fine
        "embed": P(None, "fsdp"),
        "layers": {
            "attn_norm": P(layer_axis, None),
            "wq": P(layer_axis, "fsdp", "tp"),
            "wk": P(layer_axis, "fsdp", "tp"),
            "wv": P(layer_axis, "fsdp", "tp"),
            "wo": P(layer_axis, "tp", "fsdp"),
            "mlp_norm": P(layer_axis, None),
            "w_gate": P(layer_axis, "fsdp", "tp"),
            "w_up": P(layer_axis, "fsdp", "tp"),
            "w_down": P(layer_axis, "tp", "fsdp"),
        },
        "final_norm": P(None),
    }
    if not cfg.tie_embeddings:
        specs["lm_head"] = P("fsdp", "tp")
    return specs


def model_fns(cfg: LlamaConfig):
    """(init_params, param_specs) for the config's model family — dense,
    or MoE when the config carries experts. The single dispatch point the
    trainer and the AOT-fit machinery share."""
    if getattr(cfg, "n_experts", 0):
        from torchx_tpu.models import moe

        return moe.init_params, moe.param_specs
    return init_params, param_specs


def shard_params(params: Params, cfg: LlamaConfig, mesh: Mesh) -> Params:
    """Device-put params onto the mesh per param_specs."""
    specs = param_specs(cfg, pp=mesh.shape.get("pp", 1) > 1)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs
    )


# -- forward ---------------------------------------------------------------


def _constraint(x: jnp.ndarray, mesh: Optional[Mesh], *spec) -> jnp.ndarray:
    if mesh is None:
        return x
    manual = mesh_lib.manual_axes()
    if manual:
        # inside a shard_map manual region (pp stage, possibly with sp
        # manual too for in-stage ring attention): constraints may only
        # name the still-automatic axes — manual ones are per-shard here
        def strip(entry):  # noqa: ANN001
            if entry is None or isinstance(entry, str):
                return None if entry in manual else entry
            kept = tuple(a for a in entry if a not in manual)
            return kept if kept else None

        spec = tuple(strip(e) for e in spec)
        if all(e is None for e in spec):
            return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(*spec)))


def ffn(
    cfg: LlamaConfig, layer: Params, mlp_in: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """The FFN half of a layer: dense SwiGLU, or the GShard MoE dispatch
    when the config carries experts. -> (down, aux). Shared by the training
    forward and the KV-cache decode path so the two can never diverge."""
    if getattr(cfg, "n_experts", 0):
        if cfg.int8_matmuls:
            import warnings

            scope = cfg.int8_scope
            warnings.warn(
                "int8_matmuls does not cover the MoE expert einsums"
                " (expert-stacked weights need a grouped AQT einsum); "
                + (
                    "only the attention projections quantize"
                    if scope == "all"
                    else "with int8_scope='ffn' NOTHING quantizes on a MoE"
                    " config — the flag is a no-op here"
                ),
                stacklevel=2,
            )
        from torchx_tpu.models.moe import moe_ffn

        return moe_ffn(cfg, layer, mlp_in)

    i8 = cfg.int8_matmuls
    gate = jax.nn.silu(maybe_matmul(mlp_in, layer["w_gate"], int8_training=i8))
    up = maybe_matmul(mlp_in, layer["w_up"], int8_training=i8)
    return (
        maybe_matmul(gate * up, layer["w_down"], int8_training=i8),
        jnp.zeros((AUX_LEN,), jnp.float32),  # aux vector: dense = zeros
    )


def _layer(
    cfg: LlamaConfig,
    mesh: Optional[Mesh],
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    x: jnp.ndarray,  # [b, s, d]
    layer: Params,  # one layer's slice
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """-> (x, aux): aux is the MoE load-balancing loss contribution of this
    layer (0 for dense layers).

    ``cos``/``sin`` of None means the sequence axis is manual here (ring
    attention inside a pipeline stage): x holds only this device's shard of
    positions, so the RoPE frequencies are computed locally from the
    shard's global offset."""
    b, s, d = x.shape
    h, kvh, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    if cos is None:
        start = jax.lax.axis_index("sp") * s
        cos, sin = rope_frequencies(hd, s, cfg.rope_theta, start=start)

    # attention block
    i8 = cfg.int8_matmuls
    i8_attn = i8 and cfg.int8_scope == "all"
    attn_in = rms_norm(x, layer["attn_norm"], cfg.norm_eps, mesh=mesh)
    q = maybe_matmul(attn_in, layer["wq"], int8_training=i8_attn).reshape(b, s, h, hd)
    k = maybe_matmul(attn_in, layer["wk"], int8_training=i8_attn).reshape(b, s, kvh, hd)
    v = maybe_matmul(attn_in, layer["wv"], int8_training=i8_attn).reshape(b, s, kvh, hd)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    if cfg.use_ring_attention and mesh is not None and mesh.shape.get("sp", 1) > 1:
        attn_out = ring_attention(q, k, v, mesh)
    else:
        attn_out = None
        if cfg.kernels != "reference":
            from torchx_tpu.ops.fused import flash_attention as fused_flash

            # None when gating fails (shape/platform/mesh): stock path below
            attn_out = fused_flash(
                q,
                k,
                v,
                causal=True,
                kernels=cfg.kernels,
                block_q=cfg.attn_block_q,
                block_kv=cfg.attn_block_kv,
                mesh=mesh,
            )
        if attn_out is None:
            attn_out = attention(
                q,
                k,
                v,
                causal=True,
                impl=cfg.attn_impl,
                block_q=cfg.attn_block_q,
                block_kv=cfg.attn_block_kv,
                mesh=mesh,
            )
    # named so remat policies can SAVE the kernel output: the attention
    # kernels are not dot_generals, so "dots" alone recomputes the whole
    # flash/splash forward in the backward pass (see "dots_attn")
    attn_out = checkpoint_name(attn_out, "attn_out")
    attn_out = maybe_matmul(
        attn_out.reshape(b, s, h * hd), layer["wo"], int8_training=i8_attn
    )
    if cfg.kernels != "reference":
        from torchx_tpu.ops.fused import rms_norm_residual

        # fused residual-add + RMSNorm: one VMEM pass yields both the mlp
        # input and the continued stream (degrades internally to the
        # reference op sequence when gating fails — identical values)
        mlp_in, x = rms_norm_residual(
            x,
            attn_out,
            layer["mlp_norm"],
            cfg.norm_eps,
            kernels=cfg.kernels,
            mesh=mesh,
        )
        x = _constraint(x, mesh, ("dp", "fsdp"), "sp", None)
    else:
        x = x + attn_out
        x = _constraint(x, mesh, ("dp", "fsdp"), "sp", None)
        # mlp block: dense SwiGLU, or MoE when the config carries experts
        mlp_in = rms_norm(x, layer["mlp_norm"], cfg.norm_eps, mesh=mesh)
    down, aux = ffn(cfg, layer, mlp_in)
    x = x + down
    return _constraint(x, mesh, ("dp", "fsdp"), "sp", None), aux


def _remat(body, cfg: LlamaConfig):  # noqa: ANN001
    if not cfg.remat:
        return body
    if cfg.remat_policy == "auto":
        # "auto" is a launch-time directive, not a policy: the trainer
        # resolves it to a concrete policy via memory analysis before the
        # forward ever traces (parallel/remat_auto.choose_remat_policy)
        raise ValueError(
            "remat_policy='auto' must be resolved before tracing — "
            "call torchx_tpu.parallel.remat_auto.choose_remat_policy"
            " (the trainer does this at launch)"
        )
    if cfg.remat_policy == "dots":
        return jax.checkpoint(
            body, policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        )
    if cfg.remat_policy == "dots_attn":
        # dots + the named attention-kernel outputs: flash/splash are pallas
        # calls, not dot_generals, so plain "dots" recomputes the whole
        # attention forward in the backward; saving [b, s, h, d] bf16 per
        # layer (~17 MB/layer at 1B shapes) skips that recompute
        return jax.checkpoint(
            body,
            policy=jax.checkpoint_policies.save_from_both_policies(
                jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
                jax.checkpoint_policies.save_only_these_names("attn_out"),
            ),
        )
    return jax.checkpoint(body)


def forward_features(
    params: Params,
    tokens: jnp.ndarray,  # [b, s] int32
    cfg: LlamaConfig,
    mesh: Optional[Mesh] = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """-> (final-norm hidden states [b, s, dim], router-health aux).

    aux is the [AUX_LEN] vector [balance, entropy, overflow] (all zeros
    for dense models; see moe.moe_ffn). Under pipeline parallelism the
    per-layer aux threads through the pipeline (summed over stages,
    averaged over microbatches). The MoE balancing term is nonlinear in
    token statistics, so the microbatch-averaged value differs slightly
    from the full-batch pp=1 value when routing varies across microbatches
    — the standard group-wise aux (GShard computes it per dispatch group
    the same way); router balancing pressure is preserved, exact loss
    parity is not."""
    # The table lookup follows the ZeRO-3 pattern of every other fsdp
    # weight: all-gather the (dim-sharded) table at use and gather with
    # batch/seq-sharded indices, so the output is BORN in the activation
    # sharding. Replicating the operand alone is not enough: GSPMD's
    # gather heuristic may still pick operand-passthrough (output
    # dim-sharded, indices all-gathered) and then reshard to the
    # batch/seq layout — an axis-moving reshard it can only do by
    # involuntary full rematerialization (replicate + reslice), warned on
    # every compile. Constraining the gather OUTPUT pins the
    # index-passthrough partitioning, so the reshard (and the indices
    # all-gather feeding it) never exists.
    sp = mesh.shape.get("sp", 1) if mesh is not None else 1
    seq_spec = "sp" if sp > 1 and tokens.shape[1] % sp == 0 else None
    tokens = _constraint(tokens, mesh, ("dp", "fsdp"), seq_spec)
    table = _constraint(params["embed"], mesh, None, None)
    x = _constraint(table[tokens], mesh, ("dp", "fsdp"), seq_spec, None)
    return features_from_embeddings(params, x.astype(cfg.dtype), cfg, mesh)


def features_from_embeddings(
    params: Params,
    x: jnp.ndarray,  # [b, s, d] input embeddings
    cfg: LlamaConfig,
    mesh: Optional[Mesh] = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """:func:`forward_features` starting AFTER the embedding lookup — the
    continuous-input entry point interpretability needs (gradients w.r.t.
    embeddings, e.g. saliency / integrated gradients over tokens)."""
    s = x.shape[1]
    x = x.astype(cfg.dtype)
    x = _constraint(x, mesh, ("dp", "fsdp"), "sp", None)

    pp = mesh.shape.get("pp", 1) if mesh is not None else 1
    # ring attention under pp runs inside the pipeline's manual region, so
    # the sequence axis manualizes at the pipeline shard_map (Shardy rejects
    # a nested shard_map rebinding pp) and RoPE is computed per-shard from
    # the sp position offset (cos/sin of None -> _layer computes locally)
    ring_in_pp = (
        pp > 1
        and cfg.use_ring_attention
        and mesh is not None
        and mesh.shape.get("sp", 1) > 1
    )
    if ring_in_pp:
        cos = sin = None
    else:
        cos, sin = rope_frequencies(cfg.head_dim, s, cfg.rope_theta)

    body = _remat(functools.partial(_layer, cfg, mesh, cos, sin), cfg)

    if pp > 1:
        # pipeline the layer stack over the pp axis (embedding/head stay
        # outside the pipeline, replicated over pp)
        import math as _math

        from torchx_tpu.parallel.pipeline import pipeline_apply

        # auto mode picks the largest divisor of the batch <= 2*pp so the
        # schedule always validates; an EXPLICIT pp_microbatches passes
        # through untouched — pipeline_apply raises a clear error on a
        # non-divisor rather than silently degrading the pipeline. When the
        # batch also splits over dp*fsdp, keep each microbatch divisible by
        # that product so in-stage batch sharding (ring attention) holds.
        data_div = max(mesh.shape.get("dp", 1) * mesh.shape.get("fsdp", 1), 1)
        div = x.shape[0] // data_div if x.shape[0] % data_div == 0 else x.shape[0]
        n_micro = cfg.pp_microbatches or _math.gcd(2 * pp, div)
        x, aux_total = pipeline_apply(
            body,
            params["layers"],
            x,
            mesh,
            n_microbatches=n_micro,
            with_aux=True,
            manual_axes=frozenset({"sp"}) if ring_in_pp else frozenset(),
            x_spec=P(None, "sp", None) if ring_in_pp else None,
        )
        # stages SUM aux over their layers; balance keeps the sum (Switch
        # semantics) but the monitoring stats (entropy/overflow) are
        # per-layer means, so divide the layer count back out
        aux_total = jnp.stack(
            [
                aux_total[AUX_BALANCE],
                aux_total[AUX_ENTROPY] / cfg.n_layers,
                aux_total[AUX_OVERFLOW] / cfg.n_layers,
            ]
        )
    else:
        def scan_step(x, layer_slice):  # noqa: ANN001
            x, aux = body(x, layer_slice)
            return x, aux

        x, aux_per_layer = jax.lax.scan(scan_step, x, params["layers"])
        # [L, AUX_LEN] per-layer aux: balance sums over layers (matches
        # the Switch loss), the monitoring stats average
        aux_total = jnp.stack(
            [
                aux_per_layer[:, AUX_BALANCE].sum(),
                aux_per_layer[:, AUX_ENTROPY].mean(),
                aux_per_layer[:, AUX_OVERFLOW].mean(),
            ]
        )
    return rms_norm(x, params["final_norm"], cfg.norm_eps, mesh=mesh), aux_total


def lm_head(params: Params, cfg: LlamaConfig) -> jnp.ndarray:
    """[dim, vocab] output projection (the embedding transposed when
    tied)."""
    return params["embed"].T if cfg.tie_embeddings else params["lm_head"]


def forward_from_embeddings(
    params: Params,
    embeds: jnp.ndarray,  # [b, s, d]
    cfg: LlamaConfig,
    mesh: Optional[Mesh] = None,
) -> jnp.ndarray:
    """-> logits [b, s, vocab] f32 from input embeddings (see
    :func:`features_from_embeddings`)."""
    x, _ = features_from_embeddings(params, embeds, cfg, mesh)
    logits = jnp.einsum(
        "bsd,dv->bsv", x, lm_head(params, cfg), preferred_element_type=jnp.float32
    )
    return _constraint(logits, mesh, ("dp", "fsdp"), "sp", "tp")


def forward(
    params: Params,
    tokens: jnp.ndarray,  # [b, s] int32
    cfg: LlamaConfig,
    mesh: Optional[Mesh] = None,
) -> jnp.ndarray:
    """-> logits [b, s, vocab] float32 (full materialization — use
    :func:`loss_fn` for training, which never builds this tensor)."""
    x, _ = forward_features(params, tokens, cfg, mesh)
    logits = jnp.einsum(
        "bsd,dv->bsv", x, lm_head(params, cfg), preferred_element_type=jnp.float32
    )
    # keep the vocab axis tp-sharded: the lm_head einsum produces it that
    # way, and all-gathering [b, s, vocab] f32 logits would cost ~GBs of
    # HBM + ICI per step at 128k vocab (log_softmax is fine sharded)
    return _constraint(logits, mesh, ("dp", "fsdp"), "sp", "tp")


def _token_nll(
    x: jnp.ndarray,  # [b, c, d] hidden states
    head: jnp.ndarray,  # [d, v]
    targets: jnp.ndarray,  # [b, c]
    mesh: Optional[Mesh] = None,
    f32_logits: bool = False,
) -> jnp.ndarray:
    """-> per-token negative log-likelihood [b, c] float32.

    Two deliberate choices, both measured on v5e (docs/performance.md):

    * ``logsumexp(logits) - logits[target]`` instead of
      ``log_softmax + take``: log_softmax materializes a SECOND
      [b, c, vocab] tensor (2.1 GB f32 at 1B shapes) purely as an
      intermediate — avoiding it was worth +1.1pp MFU.
    * logits stored bf16 by default (``f32_logits=False``): the MXU
      accumulates the matmul in f32 either way, storage rounding halves
      the HBM traffic of every later pass (+0.3pp MFU, 53.5→53.8);
      reductions and
      the CE gradient (softmax - onehot) run in f32 from the bf16 tensor.
      Loss trajectories match f32 to 3 decimals at 1B scale; flip
      ``LlamaConfig.ce_f32_logits`` for exact-f32 CE.
    """
    logits = jnp.einsum(
        "bcd,dv->bcv",
        x,
        head,
        preferred_element_type=jnp.float32 if f32_logits else None,
    )
    # keep the vocab axis tp-sharded (same guard as forward(): never
    # all-gather [b, *, vocab] logits on a tensor-parallel mesh)
    logits = _constraint(logits, mesh, ("dp", "fsdp"), None, "tp")
    lf = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(lf, axis=-1)
    tgt = jnp.take_along_axis(lf, targets[..., None], axis=-1)[..., 0]
    return lse - tgt


def loss_fn(
    params: Params,
    batch: dict[str, jnp.ndarray],  # {"tokens": [b, s]} next-token LM
    cfg: LlamaConfig,
    mesh: Optional[Mesh] = None,
) -> jnp.ndarray:
    """Next-token cross-entropy loss (see :func:`loss_and_aux`)."""
    return loss_and_aux(params, batch, cfg, mesh)[0]


def loss_and_aux(
    params: Params,
    batch: dict[str, jnp.ndarray],
    cfg: LlamaConfig,
    mesh: Optional[Mesh] = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """-> (total loss, router-health aux vector).

    aux is [balance, entropy, overflow] (index via AUX_*): the raw
    pre-coefficient Switch balance term (≈1 when experts are balanced,
    grows as routing collapses), the normalized router entropy, and the
    capacity-overflow fraction — all zeros for dense models. Only
    aux[AUX_BALANCE] is scaled into the loss."""
    tokens = batch["tokens"]
    x, aux = forward_features(params, tokens[:, :-1], cfg, mesh)
    aux_term = getattr(cfg, "router_aux_coef", 0.0) * aux[AUX_BALANCE]
    targets = tokens[:, 1:]
    head = lm_head(params, cfg)
    mask = batch.get("loss_mask")
    m = mask[:, 1:].astype(jnp.float32) if mask is not None else None
    f32 = cfg.ce_f32_logits

    s = targets.shape[1]
    chunk = cfg.loss_chunk
    if chunk and s % chunk == 0 and s > chunk:
        # scan over sequence chunks with remat: only [b, chunk, vocab]
        # logits ever exist (fwd and bwd) instead of [b, s, vocab]
        b = targets.shape[0]
        n = s // chunk
        xs = x.reshape(b, n, chunk, -1).swapaxes(0, 1)  # [n, b, c, d]
        ts = targets.reshape(b, n, chunk).swapaxes(0, 1)

        def body(acc, xt):  # noqa: ANN001
            x_c, t_c = xt
            return acc + _token_nll(x_c, head, t_c, mesh, f32).sum(), None

        if m is None:
            total, _ = jax.lax.scan(jax.checkpoint(body), jnp.float32(0), (xs, ts))
            return total / (b * s) + aux_term, aux
        ms = m.reshape(b, n, chunk).swapaxes(0, 1)

        def body_masked(acc, xt):  # noqa: ANN001
            x_c, t_c, m_c = xt
            return acc + (_token_nll(x_c, head, t_c, mesh, f32) * m_c).sum(), None

        total, _ = jax.lax.scan(
            jax.checkpoint(body_masked), jnp.float32(0), (xs, ts, ms)
        )
        return total / jnp.maximum(m.sum(), 1.0) + aux_term, aux

    nll = _token_nll(x, head, targets, mesh, f32)
    if m is not None:
        return (nll * m).sum() / jnp.maximum(m.sum(), 1.0) + aux_term, aux
    return nll.mean() + aux_term, aux
