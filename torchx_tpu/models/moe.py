"""Sparse Mixture-of-Experts Llama variant with expert parallelism.

Extends the dense Llama family (models/llama.py) with a Mixtral-style MoE
FFN using the canonical GShard/Switch **einsum dispatch** formulation —
top-k routing materialized as one-hot dispatch/combine tensors with a
fixed per-expert capacity, so every shape is static and XLA lays the whole
thing on the MXU (no dynamic gathers, the TPU-idiomatic MoE).

Expert parallelism (EP): the expert axis of the expert weights shards over
the combined ``("ep", "tp")`` mesh axes (see :func:`param_specs`); the
dispatch einsum then becomes the token all-to-all over ICI, placed by XLA.
A dedicated ``ep`` axis means ep and tp size independently — tp=1, ep=8
runs a small MoE expert-parallel without tensor parallelism; at ep=1 the
layout degenerates to experts-over-tp. Capacity overflow tokens are
dropped (standard GShard semantics) — size capacity_factor accordingly.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from torchx_tpu.models import llama


@dataclasses.dataclass(frozen=True)
class MoEConfig(llama.LlamaConfig):
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 2.0
    # Switch/GShard load-balancing auxiliary loss coefficient: pushes the
    # router toward uniform expert utilization (0 disables)
    router_aux_coef: float = 0.01

    def param_count(self) -> int:
        """Exact parameter count (dense shapes + per-expert FFNs)."""
        dense = super().param_count()
        # replace the dense FFN with E experts + router
        ffn = 3 * self.dim * self.ffn_dim
        return dense + self.n_layers * (
            (self.n_experts - 1) * ffn + self.dim * self.n_experts
        )

    def flops_per_token(self) -> float:
        """MoE FLOPs count only the top_k ACTIVE experts per token."""
        attn = 12 * self.n_layers * self.dim * self.max_seq
        return 6 * self.active_param_count() + attn

    def active_param_count(self) -> int:
        """Params touched per token (top_k experts) — the MFU-relevant N."""
        ffn = 3 * self.dim * self.ffn_dim
        dense = super().param_count()
        return dense + self.n_layers * (
            (self.top_k - 1) * ffn + self.dim * self.n_experts
        )


def moe_tiny(**overrides: Any) -> MoEConfig:
    """Test/debug MoE config: runs anywhere in milliseconds."""
    defaults = dict(
        vocab_size=512,
        dim=64,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        ffn_dim=128,
        max_seq=128,
        dtype=jnp.float32,
        remat=False,
        n_experts=4,
        top_k=2,
    )
    defaults.update(overrides)
    return MoEConfig(**defaults)


def mixtral_8x7b_shape(**overrides: Any) -> MoEConfig:
    """Mixtral-8x7B architecture shape (for parity/scaling experiments)."""
    defaults = dict(
        vocab_size=32000,
        dim=4096,
        n_layers=32,
        n_heads=32,
        n_kv_heads=8,
        ffn_dim=14336,
        n_experts=8,
        top_k=2,
        rope_theta=1e6,
    )
    defaults.update(overrides)
    return MoEConfig(**defaults)


CONFIGS = {"moe_tiny": moe_tiny, "mixtral_8x7b": mixtral_8x7b_shape}


# -- parameters ------------------------------------------------------------


def init_params(cfg: MoEConfig, key: jax.Array) -> llama.Params:
    """Dense-llama params with the FFN weights expanded to [L, E, ...] and a
    router added."""
    params = llama.init_params(cfg, key)
    L, E, d, f = cfg.n_layers, cfg.n_experts, cfg.dim, cfg.ffn_dim
    k_router, k_g, k_u, k_d = jax.random.split(jax.random.fold_in(key, 17), 4)

    def init(key, shape, in_dim):  # noqa: ANN001
        return (
            jax.random.normal(key, shape, dtype=jnp.float32) * (in_dim**-0.5)
        ).astype(cfg.dtype)

    layers = params["layers"]
    layers["w_router"] = init(k_router, (L, d, E), d)
    layers["w_gate"] = init(k_g, (L, E, d, f), d)
    layers["w_up"] = init(k_u, (L, E, d, f), d)
    layers["w_down"] = init(k_d, (L, E, f, d), f)
    return params


def param_specs(cfg: MoEConfig, pp: bool = False) -> llama.Params:
    """Expert axis shards over ``("ep", "tp")`` combined (expert
    parallelism, independent of tensor-parallel size); within-expert dims
    shard over ``fsdp`` like the dense model; the stacked layer axis shards
    over ``pp`` when pipeline parallelism is on."""
    layer_axis = "pp" if pp else None
    expert_axes = ("ep", "tp")
    specs = llama.param_specs(cfg, pp=pp)
    specs["layers"]["w_router"] = P(layer_axis, "fsdp", None)
    specs["layers"]["w_gate"] = P(layer_axis, expert_axes, "fsdp", None)
    specs["layers"]["w_up"] = P(layer_axis, expert_axes, "fsdp", None)
    specs["layers"]["w_down"] = P(layer_axis, expert_axes, None, "fsdp")
    return specs


def shard_params(params: llama.Params, cfg: MoEConfig, mesh) -> llama.Params:  # noqa: ANN001
    """Device-put params onto the mesh per :func:`param_specs` (experts
    over the ep axis)."""
    from jax.sharding import NamedSharding

    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params,
        param_specs(cfg, pp=mesh.shape.get("pp", 1) > 1),
    )


# -- MoE FFN ----------------------------------------------------------------


def moe_ffn(
    cfg: MoEConfig,
    layer: llama.Params,  # one layer's slice (with w_router/w_gate/w_up/w_down)
    x: jnp.ndarray,  # [b, s, d]
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """GShard einsum dispatch: route -> dispatch to capacity slots ->
    per-expert SwiGLU -> combine. Static shapes throughout.

    Returns (output, aux): aux is the router-health vector
    ``[balance, entropy, overflow]`` —

    * balance: the Switch-style load-balancing loss
      ``E * Σ_e fraction_routed_e * mean_router_prob_e`` (≈1 when
      balanced; this component, and only this, is scaled into the loss
      by cfg.router_aux_coef),
    * entropy: mean router-distribution entropy normalized by log(E)
      (1 = uniform routing, →0 as the router collapses onto experts),
    * overflow: fraction of (token, choice) routings dropped because
      their expert's capacity buffer was full.

    The trainer surfaces all three at log points (docs/ROADMAP.md #12)."""
    b, s, d = x.shape
    E, k = cfg.n_experts, cfg.top_k
    capacity = max(1, int(cfg.capacity_factor * s * k / E))

    router_logits = jnp.einsum(
        "bsd,de->bse", x, layer["w_router"], preferred_element_type=jnp.float32
    )
    probs = jax.nn.softmax(router_logits, axis=-1)  # [b, s, E] f32
    gate_vals, gate_idx = jax.lax.top_k(probs, k)  # [b, s, k]
    gate_vals = gate_vals / jnp.maximum(
        gate_vals.sum(axis=-1, keepdims=True), 1e-9
    )

    # expert one-hot per choice: [b, s, k, E]
    choice_oh = jax.nn.one_hot(gate_idx, E, dtype=jnp.float32)
    # position of each (token, choice) in its expert's capacity buffer:
    # cumsum over the flattened (s, k) token-choice axis, per (b, E)
    flat = choice_oh.reshape(b, s * k, E)
    pos = jnp.cumsum(flat, axis=1) - flat  # [b, s*k, E]
    pos = (pos * flat).sum(-1).reshape(b, s, k).astype(jnp.int32)  # [b, s, k]
    within = pos < capacity
    pos_oh = jax.nn.one_hot(pos, capacity, dtype=jnp.float32) * within[..., None]

    # dispatch [b, s, E, C] (0/1) and combine (gate-weighted)
    dispatch = jnp.einsum("bske,bskc->bsec", choice_oh, pos_oh)
    combine = jnp.einsum("bske,bskc,bsk->bsec", choice_oh, pos_oh, gate_vals)

    # tokens -> expert capacity slots: [b, E, C, d]
    expert_in = jnp.einsum("bsec,bsd->becd", dispatch.astype(x.dtype), x)
    # per-expert SwiGLU, expert axis stays leading (sharded over tp)
    gate = jax.nn.silu(jnp.einsum("becd,edf->becf", expert_in, layer["w_gate"]))
    up = jnp.einsum("becd,edf->becf", expert_in, layer["w_up"])
    expert_out = jnp.einsum("becf,efd->becd", gate * up, layer["w_down"])
    # load-balancing aux: fraction of top-1 routings per expert x mean
    # router probability per expert (Switch Transformer eq. 4-6)
    top1_oh = choice_oh[:, :, 0, :]  # [b, s, E]
    frac_routed = top1_oh.mean(axis=(0, 1))  # [E]
    mean_prob = probs.mean(axis=(0, 1))  # [E]
    balance = E * jnp.sum(frac_routed * mean_prob)
    # router health metrics (monitoring only; stop_gradient keeps them
    # out of the backward pass)
    p_safe = jnp.maximum(probs, 1e-9)
    entropy = jax.lax.stop_gradient(
        (-(p_safe * jnp.log(p_safe)).sum(-1).mean()) / jnp.log(float(E))
    )
    overflow = jax.lax.stop_gradient(1.0 - within.astype(jnp.float32).mean())
    # order fixed by llama.AUX_BALANCE / AUX_ENTROPY / AUX_OVERFLOW
    aux = jnp.stack([balance, entropy, overflow])

    # back to tokens, gate-weighted
    out = jnp.einsum("bsec,becd->bsd", combine.astype(x.dtype), expert_out)
    return out, aux


# -- model glue -------------------------------------------------------------
# llama._layer dispatches to moe_ffn when the config carries n_experts
# (duck-typed on the config, imported lazily there); forward/loss_fn are
# re-exported so MoE callers depend only on this module.


def forward(
    params: llama.Params,
    tokens: jnp.ndarray,
    cfg: MoEConfig,
    mesh=None,  # noqa: ANN001
) -> jnp.ndarray:
    """Logits for a MoE config (the shared llama forward dispatches to
    the expert FFN when the config carries experts)."""
    return llama.forward(params, tokens, cfg, mesh)


def loss_fn(
    params: llama.Params,
    batch: dict[str, jnp.ndarray],
    cfg: MoEConfig,
    mesh=None,  # noqa: ANN001
) -> jnp.ndarray:
    """Next-token CE + router balancing aux term."""
    return llama.loss_fn(params, batch, cfg, mesh)
