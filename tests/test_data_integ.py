"""Data pipeline + integration-test-harness tests."""

import subprocess
import sys

import numpy as np
import pytest

from torchx_tpu.components.integration_tests import (
    BoothProvider,
    EchoProvider,
    IntegComponentTest,
)
from torchx_tpu.examples.data import TokenDataset, device_batches
from torchx_tpu.parallel.mesh import MeshConfig, make_mesh


@pytest.fixture
def token_file(tmp_path):
    arr = np.arange(10_000, dtype=np.uint32) % 257
    path = tmp_path / "tokens.bin"
    arr.tofile(path)
    return str(path)


class TestDatapreproc:
    def test_byte_tokenization_roundtrip(self, tmp_path):
        (tmp_path / "a.txt").write_text("hello")
        out = tmp_path / "tokens.bin"
        subprocess.run(
            [
                sys.executable,
                "-m",
                "torchx_tpu.examples.datapreproc",
                "--input",
                str(tmp_path / "*.txt"),
                "--output",
                str(out),
            ],
            check=True,
        )
        arr = np.fromfile(out, dtype=np.uint32)
        assert arr[0] == 256  # BOS
        assert bytes(arr[1:].astype(np.uint8)).decode() == "hello"

    def test_no_inputs_fails(self, tmp_path):
        rc = subprocess.run(
            [
                sys.executable,
                "-m",
                "torchx_tpu.examples.datapreproc",
                "--input",
                str(tmp_path / "nope*.txt"),
                "--output",
                str(tmp_path / "o.bin"),
            ],
        ).returncode
        assert rc == 1


class TestTokenDataset:
    def test_batch_shapes(self, token_file):
        ds = TokenDataset(token_file, seq=32, batch=4)
        batch = next(iter(ds))
        assert batch.shape == (4, 33)
        assert batch.dtype == np.int32

    def test_process_sharding_disjoint(self, token_file):
        a = TokenDataset(token_file, seq=8, batch=2, process_index=0, process_count=2)
        b = TokenDataset(token_file, seq=8, batch=2, process_index=1, process_count=2)
        # different halves of the (distinct-valued) corpus, local batch split
        assert not np.array_equal(a._data[:10], b._data[:10])
        assert a._local_batch == 1 and b._local_batch == 1

    def test_exact_min_corpus_no_crash(self, tmp_path):
        # shard exactly seq+1 tokens: constructor allows it; sampling must too
        arr = np.arange(33, dtype=np.uint32)
        path = tmp_path / "t.bin"
        arr.tofile(path)
        ds = TokenDataset(str(path), seq=32, batch=1)
        batch = next(iter(ds))
        assert batch.shape == (1, 33)

    def test_resume_continues_stream(self, token_file):
        fresh = iter(TokenDataset(token_file, seq=8, batch=2, seed=7))
        b0, b1, b2 = next(fresh), next(fresh), next(fresh)
        resumed = iter(TokenDataset(token_file, seq=8, batch=2, seed=7, start_step=2))
        np.testing.assert_array_equal(next(resumed), b2)
        assert not np.array_equal(b0, b2)

    def test_too_small_corpus(self, token_file):
        with pytest.raises(ValueError, match="smaller than"):
            TokenDataset(token_file, seq=100_000, batch=1)

    def test_device_batches_sharded(self, token_file):
        mesh = make_mesh(MeshConfig(dp=2, fsdp=4, tp=1, sp=1))
        ds = TokenDataset(token_file, seq=16, batch=8)
        it = device_batches(ds, mesh)
        b1 = next(it)["tokens"]
        b2 = next(it)["tokens"]
        assert b1.shape == (8, 17)
        assert not np.array_equal(np.asarray(b1), np.asarray(b2))


class TestIntegHarness:
    def test_local_suite_passes(self, tmp_path):
        suite = IntegComponentTest(
            scheduler="local",
            cfg={"log_dir": str(tmp_path)},
            wait_interval=0.2,
        )
        suite.assert_all_succeeded([EchoProvider, BoothProvider])

    def test_failure_reported(self, tmp_path):
        from torchx_tpu.components.integration_tests import ComponentProvider
        from torchx_tpu.specs.api import AppDef, Role

        class FailingProvider(ComponentProvider):
            def get_app_def(self):
                return AppDef(
                    name="f",
                    roles=[Role(name="f", image="", entrypoint="false")],
                )

        suite = IntegComponentTest(
            scheduler="local", cfg={"log_dir": str(tmp_path)}, wait_interval=0.2
        )
        with pytest.raises(AssertionError, match="FailingProvider"):
            suite.assert_all_succeeded([FailingProvider])


def test_prefetch_preserves_seeded_order(tmp_path):
    """The threaded multi-buffer prefetch must yield exactly the batches
    direct iteration yields (determinism + checkpoint-resume stream)."""
    import numpy as np

    import jax
    from torchx_tpu.examples.data import TokenDataset, device_batches
    from torchx_tpu.parallel.mesh import MeshConfig, make_mesh

    path = tmp_path / "corpus.bin"
    np.arange(4096, dtype=np.uint32).tofile(path)
    mesh = make_mesh(MeshConfig(dp=1, fsdp=-1, tp=1, sp=1))

    def make():
        return TokenDataset(
            str(path), seq=16, batch=8, seed=7, process_index=0, process_count=1
        )

    it_direct = iter(make())
    want = [next(it_direct) for _ in range(6)]
    got = []
    stream = device_batches(make(), mesh, prefetch=3)
    for _ in range(6):
        got.append(np.asarray(next(stream)["tokens"]))
    for w, g in zip(want, got):
        np.testing.assert_array_equal(w, g)


def test_prefetch_finite_iterable_ends_cleanly(tmp_path):
    """A finite dataset must END the stream, not hang the consumer."""
    import numpy as np

    from torchx_tpu.examples.data import device_batches
    from torchx_tpu.parallel.mesh import MeshConfig, make_mesh

    mesh = make_mesh(MeshConfig(dp=1, fsdp=-1, tp=1, sp=1))
    finite = [np.zeros((8, 17), dtype=np.int32) for _ in range(3)]
    got = list(device_batches(finite, mesh, prefetch=2))
    assert len(got) == 3


def test_prefetch_propagates_producer_errors(tmp_path):
    import numpy as np
    import pytest

    from torchx_tpu.examples.data import device_batches
    from torchx_tpu.parallel.mesh import MeshConfig, make_mesh

    mesh = make_mesh(MeshConfig(dp=1, fsdp=-1, tp=1, sp=1))

    def bad():
        yield np.zeros((8, 17), dtype=np.int32)
        raise OSError("disk went away")

    stream = device_batches(bad(), mesh, prefetch=2)
    # the producer may race ahead, so the error can surface on any pull
    with pytest.raises(OSError, match="disk went away"):
        for _ in stream:
            pass
