"""Bucketed gradient-sync tests (parallel/overlap.py).

The load-bearing property: bucket boundaries are pure scheduling. At ANY
bucket size the per-leaf gradients must be bitwise identical to the
single-sync step — psum is leafwise, barriers are value-identities.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from torchx_tpu.parallel import overlap
from torchx_tpu.parallel.mesh import MeshConfig, make_mesh
from torchx_tpu.parallel.mesh import shard_map as tpx_shard_map

MIB = 1024 * 1024


def _grad_tree(key=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(key), 4)
    return {
        "wq": jax.random.normal(ks[0], (8, 64, 32), dtype=dtype),
        "wo": jax.random.normal(ks[1], (8, 32, 64), dtype=dtype),
        "norm": jax.random.normal(ks[2], (8, 64), dtype=dtype),
        "emb": jax.random.normal(ks[3], (8, 128, 64), dtype=dtype),
    }


class TestPlanBuckets:
    def test_reverse_order_and_cap(self):
        tree = {"a": jnp.zeros((256,)), "b": jnp.zeros((256,)), "c": jnp.zeros((256,))}
        plan = overlap.plan_buckets(tree, 2 * 256 * 4)
        # leaves flatten a, b, c -> reverse issue order starts at c
        assert plan.buckets[0] == (2, 1)
        assert plan.buckets[1] == (0,)
        assert plan.n_buckets == 2
        assert plan.total_bytes == 3 * 256 * 4

    def test_oversize_leaf_gets_own_bucket(self):
        tree = [jnp.zeros((1024,)), jnp.zeros((8,)), jnp.zeros((8,))]
        plan = overlap.plan_buckets(tree, 64)
        assert (0,) in plan.buckets
        assert all(len(b) >= 1 for b in plan.buckets)

    def test_single_bucket_when_cap_huge(self):
        plan = overlap.plan_buckets(_grad_tree(), 10 * MIB)
        assert plan.n_buckets == 1
        assert set(plan.buckets[0]) == set(range(4))

    def test_deterministic(self):
        a = overlap.plan_buckets(_grad_tree(), MIB)
        b = overlap.plan_buckets(_grad_tree(1), MIB)  # same structure
        assert a.buckets == b.buckets

    def test_describe(self):
        d = overlap.plan_buckets(_grad_tree(), MIB).describe()
        assert set(d) == {"bucket_mb", "n_buckets", "total_mb", "largest_bucket_mb"}


class TestResolveBucketMb:
    def test_explicit_passthrough(self):
        mb, trials = overlap.resolve_bucket_mb(_grad_tree(), 16)
        assert mb == 16
        assert len(trials) == 1 and trials[0].chosen
        assert trials[0].to_dict()["reason"] == "explicit --grad-bucket-mb"

    def test_explicit_invalid(self):
        with pytest.raises(ValueError):
            overlap.resolve_bucket_mb(_grad_tree(), -4)

    def test_auto_picks_smallest_acceptable(self):
        mb, trials = overlap.resolve_bucket_mb(_grad_tree(), "auto")
        assert mb in overlap.BUCKET_MB_CANDIDATES
        chosen = [t for t in trials if t.chosen]
        assert len(chosen) == 1 and chosen[0].bucket_mb == mb
        plan = overlap.plan_buckets(_grad_tree(), mb * MIB)
        assert plan.n_buckets <= overlap.TARGET_BUCKETS

    def test_auto_records_all_candidates(self):
        _, trials = overlap.resolve_bucket_mb(_grad_tree(), "auto")
        assert [t.bucket_mb for t in trials] == list(overlap.BUCKET_MB_CANDIDATES)


class TestBitwiseEquality:
    """Gradients bitwise-equal to single-sync at any bucket size."""

    @pytest.mark.parametrize("cap_bytes", [1, 4096, MIB, 64 * MIB])
    def test_bucketed_psum_matches_single_psum(self, cap_bytes):
        mesh = make_mesh(MeshConfig(dp=8, fsdp=1, tp=1, sp=1))
        tree = _grad_tree()
        plan = overlap.plan_buckets(tree, cap_bytes)
        spec = P("dp")

        def bucketed(g):
            return overlap.bucketed_psum(g, "dp", plan)

        def single(g):
            return jax.tree_util.tree_map(lambda x: jax.lax.psum(x, "dp"), g)

        specs = jax.tree_util.tree_map(lambda _: spec, tree)
        run = lambda fn: tpx_shard_map(  # noqa: E731
            fn,
            mesh=mesh,
            in_specs=(specs,),
            out_specs=specs,
            axis_names=frozenset(dict(mesh.shape)),
            check_vma=False,
        )(tree)
        got = run(bucketed)
        want = run(single)
        for a, b in zip(jax.tree_util.tree_leaves(got), jax.tree_util.tree_leaves(want)):
            assert np.array_equal(np.asarray(a), np.asarray(b))

    @pytest.mark.parametrize("cap_bytes", [1, 4096, 64 * MIB])
    def test_gspmd_barriers_are_value_identity(self, cap_bytes):
        tree = _grad_tree(dtype=jnp.bfloat16)
        plan = overlap.plan_buckets(tree, cap_bytes)
        out = jax.jit(lambda g: overlap.apply_bucketed_barriers(g, plan))(tree)
        for a, b in zip(jax.tree_util.tree_leaves(out), jax.tree_util.tree_leaves(tree)):
            assert np.array_equal(
                np.asarray(a, dtype=np.float32), np.asarray(b, dtype=np.float32)
            )


class TestBucketedSync:
    def test_off_switch(self):
        tree = _grad_tree()
        out, plan = overlap.bucketed_sync(tree, bucket_mb=0)
        assert plan is None
        assert out is tree

    def test_gspmd_mode_outside_manual_region(self):
        tree = _grad_tree()
        out, plan = overlap.bucketed_sync(tree, bucket_mb=1, mode="auto")
        assert plan is not None and plan.n_buckets >= 1
        for a, b in zip(jax.tree_util.tree_leaves(out), jax.tree_util.tree_leaves(tree)):
            assert np.array_equal(np.asarray(a), np.asarray(b))

    def test_manual_mode_inside_shard_map(self):
        mesh = make_mesh(MeshConfig(dp=8, fsdp=1, tp=1, sp=1))
        tree = {"w": jnp.arange(16, dtype=jnp.float32).reshape(8, 2)}
        spec = {"w": P("dp")}

        def fn(g):
            out, plan = overlap.bucketed_sync(g, bucket_mb=1, mode="auto")
            assert plan is not None
            return out

        got = tpx_shard_map(
            fn,
            mesh=mesh,
            in_specs=(spec,),
            out_specs=spec,
            axis_names=frozenset(dict(mesh.shape)),
            check_vma=False,
        )(tree)
        col_sum = np.asarray(tree["w"]).sum(axis=0)
        want = np.tile(col_sum, (8, 1))
        np.testing.assert_array_equal(np.asarray(got["w"]), want)

    def test_unknown_mode_raises(self):
        with pytest.raises(ValueError):
            overlap.bucketed_sync(_grad_tree(), bucket_mb=1, mode="nope")
