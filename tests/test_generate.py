"""KV-cache generation tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchx_tpu.models import generate as gen
from torchx_tpu.models import llama


@pytest.fixture(scope="module")
def setup():
    cfg = llama.llama_tiny(max_seq=64)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, 512)
    return cfg, params, prompt


class TestGenerate:
    def test_prefill_logits_match_full_forward(self, setup):
        cfg, params, prompt = setup
        cache = gen.init_kv_cache(cfg, 2, 16)
        logits_c, cache = gen.forward_with_cache(
            params, prompt, cache, jnp.int32(0), cfg
        )
        logits_f = llama.forward(params, prompt, cfg)
        np.testing.assert_allclose(logits_c, logits_f, atol=1e-5)
        # cache filled only at prompt positions
        assert not np.allclose(np.asarray(cache["k"][:, :, :8]), 0)
        np.testing.assert_array_equal(np.asarray(cache["k"][:, :, 8:]), 0)

    def test_greedy_matches_teacher_forcing(self, setup):
        cfg, params, prompt = setup
        seq = prompt
        for _ in range(6):
            logits = llama.forward(params, seq, cfg)
            nxt = jnp.argmax(logits[:, -1], -1).astype(jnp.int32)
            seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
        out = gen.generate(params, prompt, cfg, max_new_tokens=6)
        np.testing.assert_array_equal(out, seq)

    def test_generate_jits(self, setup):
        cfg, params, prompt = setup
        fn = jax.jit(
            lambda p, t: gen.generate(p, t, cfg, max_new_tokens=4),
        )
        out = fn(params, prompt)
        assert out.shape == (2, 12)

    def test_sampling_temperature(self, setup):
        cfg, params, prompt = setup
        a = gen.generate(
            params, prompt, cfg, 8, temperature=1.5, rng=jax.random.PRNGKey(7)
        )
        b = gen.generate(
            params, prompt, cfg, 8, temperature=1.5, rng=jax.random.PRNGKey(8)
        )
        assert a.shape == b.shape == (2, 16)
        assert not np.array_equal(a, b)  # different keys -> different samples
        # deterministic under the same key
        c = gen.generate(
            params, prompt, cfg, 8, temperature=1.5, rng=jax.random.PRNGKey(7)
        )
        np.testing.assert_array_equal(a, c)

    def test_exceeds_max_seq_raises(self, setup):
        cfg, params, prompt = setup
        with pytest.raises(ValueError, match="max_seq"):
            gen.generate(params, prompt, cfg, max_new_tokens=100)

    def test_single_new_token(self, setup):
        cfg, params, prompt = setup
        out = gen.generate(params, prompt, cfg, max_new_tokens=1)
        assert out.shape == (2, 9)


class TestMoEGenerate:
    """KV-cache decode for MoE configs: the cached layer dispatches to the
    GShard expert FFN (dense-only NotImplementedError removed)."""

    @pytest.fixture(scope="class")
    def moe_setup(self):
        from torchx_tpu.models import moe

        # generous capacity so no token drops -> decode matches forward
        cfg = moe.moe_tiny(capacity_factor=4.0)
        params = moe.init_params(cfg, jax.random.PRNGKey(0))
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, 512)
        return cfg, params, prompt

    def test_moe_prefill_matches_full_forward(self, moe_setup):
        from torchx_tpu.models import moe

        cfg, params, prompt = moe_setup
        cache = gen.init_kv_cache(cfg, 2, 16)
        logits_c, _ = gen.forward_with_cache(
            params, prompt, cache, jnp.int32(0), cfg
        )
        logits_f = moe.forward(params, prompt, cfg)
        np.testing.assert_allclose(logits_c, logits_f, atol=2e-4)

    def test_moe_greedy_matches_teacher_forcing(self, moe_setup):
        from torchx_tpu.models import moe

        cfg, params, prompt = moe_setup
        seq = prompt
        for _ in range(4):
            logits = moe.forward(params, seq, cfg)
            nxt = jnp.argmax(logits[:, -1], -1).astype(jnp.int32)
            seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
        out = gen.generate(params, prompt, cfg, max_new_tokens=4)
        np.testing.assert_array_equal(out, seq)


class TestGenerateStream:
    def test_stream_token_identical_to_batch(self):
        import jax
        import jax.numpy as jnp
        from torchx_tpu.models import generate as gen, llama

        cfg = llama.llama_tiny()
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        prompt = jnp.array([[1, 2, 3, 4], [5, 6, 7, 8]], dtype=jnp.int32)
        for temp, seed in [(0.0, 0), (0.8, 7)]:
            full = gen.generate(
                params, prompt, cfg, max_new_tokens=11,
                temperature=temp, rng=jax.random.PRNGKey(seed),
            )
            chunks = list(gen.generate_stream(
                params, prompt, cfg, max_new_tokens=11,
                temperature=temp, rng=jax.random.PRNGKey(seed), chunk=4,
            ))
            streamed = jnp.concatenate([jnp.asarray(c) for c in chunks], axis=1)
            assert (streamed == full[:, 4:]).all(), temp
            # chunk sizes: prefill token, then 4/4/2
            assert [c.shape[1] for c in chunks] == [1, 4, 4, 2]

    def test_stream_rejects_overflow(self):
        import jax
        import jax.numpy as jnp
        from torchx_tpu.models import generate as gen, llama

        cfg = llama.llama_tiny()
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        prompt = jnp.ones((1, 4), dtype=jnp.int32)
        import pytest as _pytest

        with _pytest.raises(ValueError, match="max_seq"):
            list(gen.generate_stream(
                params, prompt, cfg, max_new_tokens=cfg.max_seq,
            ))


@pytest.mark.integ
def test_bench_serving_script_smoke():
    """scripts/bench_serving.py runs on CPU (tiny config) and emits valid
    JSON lines — keeps the serving bench from rotting."""
    import json
    import subprocess
    import sys
    from pathlib import Path

    script = Path(__file__).resolve().parent.parent / "scripts" / "bench_serving.py"
    proc = subprocess.run(
        [sys.executable, str(script), "--steps", "4", "--batches", "1"],
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 0, proc.stderr
    lines = [ln for ln in proc.stdout.splitlines() if ln.startswith("{")]
    assert len(lines) == 2  # bf16 + int8
    for ln in lines:
        d = json.loads(ln)
        assert "error" not in d, d
        assert d["value"] > 0
