"""Step-profiler tests: attribution arithmetic, the fsync'd journal's
crash safety (torn final line held back on read), summarize/diff schema
stability, the ``observe_collectives`` calibration fold, the
``tpx_profile_*`` gauge export, the ``tpx profile`` CLI, and the
prefetcher's wait-observer seam."""

from __future__ import annotations

import json
import os
import sys
import subprocess
from pathlib import Path

import pytest

from torchx_tpu.obs.profile import (
    AttributionModel,
    CORE_PHASES,
    PROFILE_FILE,
    StepProfiler,
    diff_summaries,
    export_metrics,
    feed_calibration,
    load_profile,
    render_diff,
    render_summary,
    summarize,
)

REPO = Path(__file__).resolve().parent.parent


def model(**overrides) -> AttributionModel:
    defaults = dict(
        flops_per_token=1000.0,
        tokens_per_step=100,
        peak_flops=1e6,
        param_count=1000,
        comm_axis_s={"fsdp": 0.02, "dp": 0.01},
        generation="cpu",
    )
    defaults.update(overrides)
    return AttributionModel(**defaults)


def profiler(tmp_path, **overrides) -> StepProfiler:
    return StepProfiler(
        model(**overrides),
        path=str(tmp_path / PROFILE_FILE),
        clock=lambda: 123.0,
    )


MEASURED = {
    "data_wait": 0.05,
    "forward_backward": 0.2,
    "checkpoint": 0.0,
    "host": 0.01,
}


# ---------------------------------------------------------------------------
# attribution arithmetic
# ---------------------------------------------------------------------------


def test_attribution_model_arithmetic():
    m = model()
    assert m.ideal_compute_s == pytest.approx(0.1)  # 100 * 1000 / 1e6
    assert m.optimizer_s == pytest.approx(0.012)  # 12 * 1000 / 1e6
    assert m.total_comm_s == pytest.approx(0.03)
    # ASSUMED_MFU = 0.5 -> slack equals the ideal floor
    assert m.compute_slack_s == pytest.approx(0.1)


def test_record_step_splits_device_time(tmp_path):
    p = profiler(tmp_path)
    rec = p.record_step(1, wall_s=0.27, measured=dict(MEASURED))
    phases = rec["phases"]
    # the device slice is conserved: fb + optimizer + exposed == measured
    device = (
        phases["forward_backward"]
        + phases["optimizer"]
        + rec["comm_exposed_s"]
    )
    assert device == pytest.approx(0.2)
    # residual above the floor split by modeled shares:
    # residual = 0.2 - 0.1 - 0.012; comm share = 0.03 / (0.03 + 0.1)
    assert rec["comm_exposed_s"] == pytest.approx(0.088 * 0.03 / 0.13)
    # grad_sync distributes exposed by the per-axis model (2:1)
    gs = rec["grad_sync"]
    assert gs["fsdp"] == pytest.approx(2 * gs["dp"])
    assert sum(gs.values()) == pytest.approx(rec["comm_exposed_s"])
    # measured slices pass through untouched
    assert phases["data_wait"] == pytest.approx(0.05)
    assert phases["host"] == pytest.approx(0.01)
    assert rec["mfu"] == pytest.approx(100 * 1000 / (0.27 * 1e6))
    assert rec["overlap_frac"] == pytest.approx(
        1.0 - rec["comm_exposed_s"] / 0.03
    )


def test_phase_seconds_sum_to_measured_slices(tmp_path):
    # the 5%-of-wall acceptance bound holds by construction: phases +
    # grad_sync sum exactly to the measured slices
    p = profiler(tmp_path)
    rec = p.record_step(1, wall_s=0.27, measured=dict(MEASURED))
    total = sum(rec["phases"].values()) + sum(rec["grad_sync"].values())
    assert total == pytest.approx(sum(MEASURED.values()))


def test_no_comm_model_means_no_grad_sync(tmp_path):
    p = profiler(tmp_path, comm_axis_s={})
    rec = p.record_step(1, wall_s=0.27, measured=dict(MEASURED))
    assert rec["grad_sync"] == {}
    assert rec["comm_exposed_s"] == 0.0
    assert rec["overlap_frac"] is None
    # the whole device slice minus the optimizer stays forward_backward
    assert rec["phases"]["forward_backward"] == pytest.approx(0.2 - 0.012)


def test_device_faster_than_floor_exposes_nothing(tmp_path):
    # device time below the roofline floor: no residual to attribute
    p = profiler(tmp_path)
    rec = p.record_step(1, wall_s=0.1, measured={"forward_backward": 0.05})
    assert rec["comm_exposed_s"] == 0.0
    assert rec["overlap_frac"] == pytest.approx(1.0)


def test_end_step_without_begin_is_none(tmp_path):
    p = profiler(tmp_path)
    assert p.end_step(1) is None


def test_hooks_accumulate_and_record(tmp_path):
    p = profiler(tmp_path)
    p.begin_step()
    p.observe_wait(0.004)
    p.observe_wait(0.001)
    with p.phase("forward_backward"):
        pass
    rec = p.end_step(7)
    assert rec is not None and rec["step"] == 7
    assert rec["phases"]["data_wait"] == pytest.approx(0.005)
    assert rec["wall_s"] > 0
    # waits arriving outside a window are discarded by the next begin
    p.observe_wait(9.0)
    p.begin_step()
    rec2 = p.end_step(8)
    assert rec2["phases"]["data_wait"] == 0.0


# ---------------------------------------------------------------------------
# journal crash safety
# ---------------------------------------------------------------------------


def test_journal_meta_first_then_steps(tmp_path):
    p = profiler(tmp_path)
    p.record_step(1, wall_s=0.27, measured=dict(MEASURED))
    p.record_step(2, wall_s=0.28, measured=dict(MEASURED))
    lines = (tmp_path / PROFILE_FILE).read_text().splitlines()
    assert len(lines) == 3
    meta = json.loads(lines[0])
    assert meta["kind"] == "meta"
    assert meta["ts"] == 123.0  # the injected clock seam stamps records
    assert meta["model"]["tokens_per_step"] == 100
    assert [json.loads(ln)["step"] for ln in lines[1:]] == [1, 2]


def test_torn_final_line_held_back(tmp_path):
    # a kill mid-append leaves a torn final line; readers must skip it
    p = profiler(tmp_path)
    p.record_step(1, wall_s=0.27, measured=dict(MEASURED))
    p.record_step(2, wall_s=0.28, measured=dict(MEASURED))
    path = tmp_path / PROFILE_FILE
    with open(path, "ab") as f:
        f.write(b'{"v": 1, "kind": "step", "step": 3, "wall')
    records = load_profile(str(path))
    steps = [r["step"] for r in records if r.get("kind") == "step"]
    assert steps == [1, 2]
    # a directory target resolves to its profile.jsonl
    assert load_profile(str(tmp_path)) == records


def test_journal_failure_never_raises(tmp_path):
    bad = StepProfiler(
        model(), path=str(tmp_path / "no" / "such" / "x.jsonl"), clock=lambda: 0.0
    )
    # make the parent un-creatable by shadowing it with a file
    (tmp_path / "no").write_text("a file, not a dir")
    rec = bad.record_step(1, wall_s=0.1, measured=dict(MEASURED))
    assert rec["step"] == 1  # in-memory record still produced


# ---------------------------------------------------------------------------
# summarize / diff / render
# ---------------------------------------------------------------------------


def summary_of(tmp_path, n=3) -> dict:
    p = profiler(tmp_path)
    for i in range(n):
        p.record_step(i + 1, wall_s=0.27, measured=dict(MEASURED))
    return summarize(load_profile(str(tmp_path)))


def test_summarize_schema(tmp_path):
    s = summary_of(tmp_path)
    assert s["v"] == 1 and s["steps"] == 3
    assert s["wall_s"] == pytest.approx(0.81)
    assert s["step_s"] == pytest.approx(0.27)
    for ph in ("data_wait", "forward_backward", "optimizer", "host"):
        assert ph in s["phase_seconds"]
    assert s["phase_fracs"]["data_wait"] == pytest.approx(0.05 / 0.27)
    assert s["data_wait_frac"] == pytest.approx(0.05 / 0.27)
    assert set(s["grad_sync_seconds"]) == {"fsdp", "dp"}
    assert 0 < s["mfu"] <= 1
    assert s["overlap_frac"] == pytest.approx(
        1.0 - s["comm_exposed_s"] / s["comm_modeled_s"]
    )
    assert s["meta"]["peak_flops"] == 1e6  # meta record rides the summary


def test_summarize_empty():
    s = summarize([])
    assert s["steps"] == 0 and s["overlap_frac"] is None


def test_diff_tolerates_disjoint_phase_sets(tmp_path):
    a = summary_of(tmp_path / "a")
    # run b checkpoints; run a never did — the diff must still line up
    pb = profiler(tmp_path / "b")
    mb = dict(MEASURED, checkpoint=0.03)
    pb.record_step(1, wall_s=0.30, measured=mb)
    b = summarize(load_profile(str(tmp_path / "b")))
    d = diff_summaries(a, b)
    row = d["phase_step_s"]["checkpoint"]
    assert row["a"] == pytest.approx(0.0)
    assert row["b"] == pytest.approx(0.03)
    assert row["delta"] == pytest.approx(0.03)
    # fully disjoint dict inputs also survive
    d2 = diff_summaries(
        {"steps": 1, "phase_seconds": {"x": 1.0}},
        {"steps": 1, "phase_seconds": {"y": 2.0}},
    )
    assert d2["phase_step_s"]["x"]["b"] == 0.0
    assert d2["phase_step_s"]["y"]["a"] == 0.0


def test_render_summary_and_diff_are_strings(tmp_path):
    s = summary_of(tmp_path)
    out = render_summary(s)
    assert "forward_backward" in out and "roofline" in out and "overlap" in out
    assert "grad_sync[fsdp]" in out
    d = render_diff(diff_summaries(s, s))
    assert "profile diff" in d and "mfu" in d


# ---------------------------------------------------------------------------
# calibration feedback
# ---------------------------------------------------------------------------


def test_observe_collectives_fold(tmp_path):
    from torchx_tpu.tune.calibrate import CalibrationTable

    table = CalibrationTable(str(tmp_path / "calibration.json"))
    out = table.observe_collectives(
        "cpu", predicted_collective_s=0.001, measured_collective_s=0.004
    )
    assert out["generation"] == "cpu-sim"
    # EMA gain 0.5: scale moves halfway to the 4x measured ratio
    assert out["scales"]["collective_scale"] == pytest.approx(2.5)
    assert out["collectives"]["err_before"] == pytest.approx(0.75)
    assert out["collectives"]["err_after"] == pytest.approx(0.375)
    assert out["scales"]["samples"] == 1
    # other scales untouched
    assert out["scales"]["activation_scale"] == 1.0
    assert out["scales"]["step_time_scale"] == 1.0
    # roundtrip
    table.save()
    loaded = CalibrationTable.load(table.path)
    assert loaded.scales_for("cpu").collective_scale == pytest.approx(2.5)


def test_observe_collectives_rejects_bad_inputs(tmp_path):
    from torchx_tpu.tune.calibrate import CalibrationTable

    table = CalibrationTable(str(tmp_path / "c.json"))
    with pytest.raises(ValueError, match="alpha"):
        table.observe_collectives(
            "v5e", predicted_collective_s=1.0, measured_collective_s=1.0, alpha=1.0
        )
    with pytest.raises(ValueError, match="> 0"):
        table.observe_collectives(
            "v5e", predicted_collective_s=0.0, measured_collective_s=1.0
        )


def test_feed_calibration_writes_default_table(tmp_path, monkeypatch):
    from torchx_tpu.tune.calibrate import CalibrationTable

    monkeypatch.setenv("TPX_TUNE_DIR", str(tmp_path))
    s = {"steps": 2, "comm_modeled_s": 0.002, "comm_exposed_s": 0.008}
    out = feed_calibration(s, generation="cpu")
    assert out is not None
    # per-step: predicted 0.001 vs measured 0.004 -> scale 2.5
    assert CalibrationTable.load_default().scales_for(
        "cpu"
    ).collective_scale == pytest.approx(2.5)
    # nothing to fold on a single-device run
    assert (
        feed_calibration(
            {"steps": 2, "comm_modeled_s": 0.0, "comm_exposed_s": 0.0},
            generation="cpu",
        )
        is None
    )


def test_profiler_close_feeds_calibration(tmp_path, monkeypatch):
    from torchx_tpu.tune.calibrate import CalibrationTable

    monkeypatch.setenv("TPX_TUNE_DIR", str(tmp_path / "tune"))
    p = profiler(tmp_path)
    p.record_step(1, wall_s=0.27, measured=dict(MEASURED))
    s = p.close()
    assert s["steps"] == 1
    assert "calibration" in s
    assert CalibrationTable.load_default().scales_for(
        "cpu"
    ).collective_scale != 1.0


def test_profiler_close_calibrate_false(tmp_path, monkeypatch):
    monkeypatch.setenv("TPX_TUNE_DIR", str(tmp_path / "tune"))
    p = profiler(tmp_path)
    p.record_step(1, wall_s=0.27, measured=dict(MEASURED))
    s = p.close(calibrate=False)
    assert "calibration" not in s
    assert not (tmp_path / "tune").exists()


# ---------------------------------------------------------------------------
# metrics export
# ---------------------------------------------------------------------------


def test_export_metrics_sets_gauges(tmp_path):
    from torchx_tpu.obs import metrics as obs_metrics

    s = summary_of(tmp_path)
    export_metrics(s)
    assert obs_metrics.PROFILE_MFU.value() == pytest.approx(s["mfu"])
    assert obs_metrics.PROFILE_DATA_WAIT_FRAC.value() == pytest.approx(
        s["data_wait_frac"]
    )
    assert obs_metrics.PROFILE_OVERLAP_FRAC.value() == pytest.approx(
        s["overlap_frac"]
    )
    assert obs_metrics.PROFILE_PHASE_SECONDS.value(
        phase="data_wait"
    ) == pytest.approx(0.05)
    assert obs_metrics.PROFILE_PHASE_SECONDS.value(
        phase="grad_sync[fsdp]"
    ) == pytest.approx(s["grad_sync_seconds"]["fsdp"] / s["steps"])


# ---------------------------------------------------------------------------
# tpx profile CLI
# ---------------------------------------------------------------------------


def make_session(root: Path, name: str, n=2, wall=0.27) -> Path:
    d = root / name
    d.mkdir(parents=True)
    p = StepProfiler(model(), path=str(d / PROFILE_FILE), clock=lambda: 1.0)
    for i in range(n):
        p.record_step(i + 1, wall_s=wall, measured=dict(MEASURED))
    return d


def test_cli_json_explicit_path(tmp_path, capsys):
    from torchx_tpu.cli.main import main

    d = make_session(tmp_path, "tpx_aa")
    main(["profile", str(d / PROFILE_FILE), "--json"])
    out = json.loads(capsys.readouterr().out)
    assert out["steps"] == 2
    for ph in CORE_PHASES:
        assert ph in out["phase_seconds"]


def test_cli_picks_newest_session(tmp_path, capsys, monkeypatch):
    from torchx_tpu.cli.main import main

    monkeypatch.setenv("TPX_OBS_DIR", str(tmp_path))
    make_session(tmp_path, "tpx_old")
    new = make_session(tmp_path, "tpx_new", n=3)
    old_j, new_j = tmp_path / "tpx_old" / PROFILE_FILE, new / PROFILE_FILE
    os.utime(old_j, (1_000, 1_000))
    os.utime(new_j, (2_000, 2_000))
    main(["profile", "--json"])
    assert json.loads(capsys.readouterr().out)["steps"] == 3
    # session NAME resolution against the obs root
    main(["profile", "tpx_old", "--json"])
    assert json.loads(capsys.readouterr().out)["steps"] == 2


def test_cli_text_render(tmp_path, capsys):
    from torchx_tpu.cli.main import main

    d = make_session(tmp_path, "tpx_bb")
    main(["profile", str(d)])
    out = capsys.readouterr().out
    assert "roofline" in out and "forward_backward" in out


def test_cli_diff(tmp_path, capsys):
    from torchx_tpu.cli.main import main

    a = make_session(tmp_path, "a", wall=0.27)
    b = make_session(tmp_path, "b", wall=0.30)
    main(["profile", "--diff", str(a), str(b), "--json"])
    d = json.loads(capsys.readouterr().out)
    assert d["step_s"]["delta"] == pytest.approx(0.03)
    main(["profile", "--diff", str(a), str(b)])
    assert "profile diff" in capsys.readouterr().out


def test_cli_missing_profile_errors(tmp_path, capsys, monkeypatch):
    from torchx_tpu.cli.main import main

    monkeypatch.setenv("TPX_OBS_DIR", str(tmp_path))
    with pytest.raises(SystemExit):
        main(["profile", "--json"])
    assert "no profiles recorded" in capsys.readouterr().err
    with pytest.raises(SystemExit):
        main(["profile", "nope"])
    assert "no profile found" in capsys.readouterr().err


def test_cli_help_is_jax_free():
    # the lazy-dispatch contract: `tpx profile --help` must not pay for
    # jax (also enforced repo-wide by lint_internal JAX_FREE)
    code = (
        "import sys\n"
        "from torchx_tpu.cli.main import main\n"
        "try:\n"
        "    main(['profile', '--help'])\n"
        "except SystemExit:\n"
        "    pass\n"
        "assert 'jax' not in sys.modules, 'tpx profile --help imported jax'\n"
    )
    subprocess.run(
        [sys.executable, "-c", code], check=True, cwd=REPO, timeout=120
    )


# ---------------------------------------------------------------------------
# prefetcher wait-observer seam
# ---------------------------------------------------------------------------


def test_prefetcher_wait_observer():
    from torchx_tpu.parallel.prefetch import Prefetcher

    waits: list[float] = []
    with Prefetcher(iter([1, 2, 3]), depth=0) as pf:
        pf.set_wait_observer(waits.append)
        assert next(pf) == 1
        assert next(pf) == 2
        assert len(waits) == 2 and all(w >= 0 for w in waits)
        # cumulative account and the per-next observer agree
        assert sum(waits) == pytest.approx(pf.data_wait_s, abs=1e-6)
        pf.set_wait_observer(None)
        assert next(pf) == 3
        assert len(waits) == 2


def test_prefetcher_observer_errors_are_swallowed():
    from torchx_tpu.parallel.prefetch import Prefetcher

    def boom(dt: float) -> None:
        raise RuntimeError("observer bug")

    with Prefetcher(iter([1, 2]), depth=0) as pf:
        pf.set_wait_observer(boom)
        assert next(pf) == 1  # the loop must survive a broken observer
