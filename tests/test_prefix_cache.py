"""Prefix-cache + KV-transfer unit tests, host side only: allocator
refcounting/guards, the radix cache (match/insert/LRU eviction/summary),
pool occupancy accounting, transfer configs and transports, and the
cache-aware router scoring — no model, no device step."""

import threading

import pytest

from torchx_tpu.models import llama
from torchx_tpu.ops.paged_attention import TRASH_BLOCK
from torchx_tpu.serve.kv_pool import BlockAllocator, plan_pool
from torchx_tpu.serve.kv_transfer import (
    FileTransfer,
    KvPayload,
    LocalTransfer,
    TransferConfig,
    TransferError,
    TransferRejected,
    make_transfer,
    new_request_id,
    serve_spool,
)
from torchx_tpu.serve.pool import LeastLoadedRouter, ReplicaStatus
from torchx_tpu.serve.prefix_cache import PrefixCache, prefix_chain

import numpy as np

GIB = 1024**3


# -- allocator refcounting -------------------------------------------------


class TestAllocatorRefcount:
    def test_alloc_starts_at_one_reference(self):
        a = BlockAllocator(8)
        (b,) = a.alloc(1)
        assert a.refcount(b) == 1 and not a.is_shared(b)

    def test_retain_release_roundtrip(self):
        a = BlockAllocator(8)
        (b,) = a.alloc(1)
        a.retain([b])
        assert a.refcount(b) == 2 and a.is_shared(b)
        assert a.release([b]) == []  # still held by the other reference
        assert a.refcount(b) == 1 and a.free_blocks == 6
        assert a.release([b]) == [b]  # last reference frees it
        assert a.refcount(b) == 0 and a.free_blocks == 7

    def test_double_free_raises(self):
        a = BlockAllocator(8)
        (b,) = a.alloc(1)
        a.free([b])
        with pytest.raises(ValueError, match="double-free"):
            a.free([b])

    def test_batch_double_free_validated_before_any_count_moves(self):
        a = BlockAllocator(8)
        b1, b2 = a.alloc(2)
        with pytest.raises(ValueError, match="double-free"):
            a.release([b1, b2, b1])  # b1 twice against refcount 1
        # the raise left the allocator unchanged: both still allocated
        assert a.refcount(b1) == 1 and a.refcount(b2) == 1
        assert a.free_blocks == 5

    def test_trash_block_guards(self):
        a = BlockAllocator(8)
        with pytest.raises(ValueError, match="trash"):
            a.release([TRASH_BLOCK])
        with pytest.raises(ValueError, match="trash"):
            a.retain([TRASH_BLOCK])
        with pytest.raises(ValueError, match="trash"):
            a.refcount(TRASH_BLOCK)

    def test_retain_free_block_raises(self):
        a = BlockAllocator(8)
        (b,) = a.alloc(1)
        a.free([b])
        with pytest.raises(ValueError, match="retaining free"):
            a.retain([b])

    def test_out_of_pool_block_raises(self):
        a = BlockAllocator(8)
        with pytest.raises(ValueError, match="outside pool"):
            a.release([99])


# -- occupancy accounting --------------------------------------------------


class TestOccupancyReport:
    def test_kv_bytes_and_slack_sum_to_budget(self):
        cfg = llama.CONFIGS["tiny"]()
        plan = plan_pool(cfg, hbm_bytes=1 * GIB, headroom=0.9, block_size=16)
        report = plan.occupancy_report()
        # the block grid rarely tiles the budget exactly: the actual pool
        # footprint plus the unusable remainder is the whole budget
        assert plan.kv_bytes + (plan.kv_budget_bytes - plan.kv_bytes) == (
            plan.kv_budget_bytes
        )
        itemsize = np.dtype(cfg.dtype).itemsize
        block_bytes = (
            cfg.n_layers * 2 * 16 * cfg.n_kv_heads * cfg.head_dim * itemsize
        )
        assert plan.kv_bytes == plan.num_blocks * block_bytes
        assert report["kv_bytes_gib"] == round(plan.kv_bytes / GIB, 6)
        assert report["kv_slack_gib"] == round(
            (plan.kv_budget_bytes - plan.kv_bytes) / GIB, 6
        )
        assert 0 <= report["kv_slack_gib"] * GIB < block_bytes + 1


# -- prefix_chain ----------------------------------------------------------


class TestPrefixChain:
    def test_full_blocks_only_and_cap(self):
        toks = list(range(50))
        assert len(prefix_chain(toks, 16)) == 3  # 50 // 16
        assert len(prefix_chain(toks, 16, max_blocks=2)) == 2
        assert prefix_chain([1, 2], 16) == []

    def test_chain_commits_to_the_whole_path(self):
        toks = list(range(48))
        chain = prefix_chain(toks, 16)
        # the chain of a shorter prefix is a prefix of the longer chain
        assert prefix_chain(toks[:32], 16) == chain[:2]
        # changing an *early* token changes every later digest
        other = [99] + toks[1:]
        assert prefix_chain(other, 16)[2] != chain[2]

    def test_same_block_different_position_differs(self):
        # positional chaining: identical 16 tokens at depth 0 vs depth 1
        # must not collide (a plain per-block hash would)
        block = list(range(16))
        assert prefix_chain(block * 2, 16)[1] != prefix_chain(block, 16)[0]


# -- PrefixCache -----------------------------------------------------------


def _cache(num_blocks=32, bs=4, **kw):
    alloc = BlockAllocator(num_blocks)
    return alloc, PrefixCache(alloc, bs, **kw)


class TestPrefixCache:
    def test_match_miss_then_insert_then_hit(self):
        alloc, pc = _cache()
        toks = list(range(12))  # 3 full blocks at bs=4
        blocks = alloc.alloc(3)
        assert pc.match(toks) == ([], 0)
        assert pc.insert(toks, blocks) == 3
        assert pc.cached_blocks == 3
        # the cache holds its own reference on every adopted block
        assert all(alloc.refcount(b) == 2 for b in blocks)
        alloc.release(blocks)  # the prefilling slot completes
        got, n = pc.match(toks)
        # never covers the final token: 2 of the 3 cached blocks match
        assert got == blocks[:2] and n == 8
        # match retained the matched blocks on behalf of the caller
        assert [alloc.refcount(b) for b in blocks] == [2, 2, 1]
        st = pc.stats()
        assert st["hits"] == 1 and st["misses"] == 1
        assert st["hit_tokens"] == 8 and st["lookup_tokens"] == 24

    def test_match_never_covers_the_final_token(self):
        alloc, pc = _cache()
        toks = list(range(8))  # exactly 2 blocks
        pc.insert(toks, alloc.alloc(2))
        got, n = pc.match(toks)
        # the last token must stay uncached so prefill has logits to
        # sample from: only the first block matches
        assert len(got) == 1 and n == 4
        got, n = pc.match(toks + [42])
        assert len(got) == 2 and n == 8

    def test_insert_keeps_existing_node_on_duplicate(self):
        alloc, pc = _cache()
        toks = list(range(8))
        first = alloc.alloc(2)
        dup = alloc.alloc(2)
        assert pc.insert(toks, first) == 2
        assert pc.insert(toks, dup) == 0  # chunks present: caller keeps dup
        assert all(alloc.refcount(b) == 2 for b in first)
        assert all(alloc.refcount(b) == 1 for b in dup)

    def test_evict_lru_frees_only_unreferenced(self):
        alloc, pc = _cache()
        cold = list(range(100, 104))
        hot = list(range(200, 204))
        for toks in (cold, hot):
            blocks = alloc.alloc(1)
            pc.insert(toks, blocks)
            alloc.release(blocks)  # cache-only: refcount 1, evictable
        held, _ = pc.match(hot + [1])  # touch hot + hold a live reference
        free0 = alloc.free_blocks
        assert pc.evict(2) == 1  # cold goes; hot is refcount 2 (cache+us)
        assert alloc.free_blocks == free0 + 1
        assert pc.match(cold + [1]) == ([], 0)
        assert pc.stats()["evictions"] == 1
        alloc.release(held)

    def test_evict_leaves_before_parents(self):
        alloc, pc = _cache()
        toks = list(range(8))
        blocks = alloc.alloc(2)
        pc.insert(toks, blocks)
        alloc.release(blocks)
        assert pc.evict(1) == 1
        # the leaf (depth 2) went first; the depth-1 prefix still matches
        got, n = pc.match(toks + [9])
        assert n == 4
        alloc.release(got)

    def test_max_blocks_cap_evicts_then_stops(self):
        alloc, pc = _cache(max_blocks=2)
        a, b = list(range(4)), list(range(10, 14))
        for toks in (a, b):
            blocks = alloc.alloc(1)
            pc.insert(toks, blocks)
            alloc.release(blocks)
        assert pc.cached_blocks == 2
        # a third distinct prefix evicts the LRU entry to stay under cap
        c_blocks = alloc.alloc(1)
        assert pc.insert(list(range(20, 24)), c_blocks) == 1
        assert pc.cached_blocks == 2
        assert pc.match(a + [0]) == ([], 0)  # a was LRU: gone

    def test_summary_matches_prefix_chain_digests(self):
        alloc, pc = _cache()
        toks = list(range(12))
        pc.insert(toks, alloc.alloc(3))
        digests = pc.summary()
        assert set(prefix_chain(toks, 4)) <= set(digests)


# -- TransferConfig --------------------------------------------------------


class TestTransferConfig:
    def test_spec_grammar_roundtrip(self):
        assert TransferConfig.from_spec("local").mode == "local"
        assert TransferConfig.from_spec("").mode == "local"
        fc = TransferConfig.from_spec("file:/var/spool/kv")
        assert fc.mode == "file" and fc.endpoints == ("/var/spool/kv",)
        hc = TransferConfig.from_spec("http:http://a:1,b:2")
        assert hc.mode == "http"
        assert hc.endpoints == ("http://a:1", "http://b:2")  # scheme added
        for spec in ("local", "file:/spool", "http:http://a:1,http://b:2"):
            assert TransferConfig.from_spec(spec).to_spec() == spec

    def test_bad_specs_raise(self):
        with pytest.raises(ValueError, match="no endpoints"):
            TransferConfig.from_spec("http:")
        with pytest.raises(ValueError, match="unknown kv-transfer"):
            TransferConfig.from_spec("carrier-pigeon:coop")

    def test_make_transfer_dispatch(self, tmp_path):
        assert isinstance(
            make_transfer(TransferConfig.from_spec("local")), LocalTransfer
        )
        ft = make_transfer(TransferConfig.from_spec(f"file:{tmp_path}/sp"))
        assert isinstance(ft, FileTransfer)


# -- payload + transports --------------------------------------------------


def _payload(**kw):
    defaults = dict(
        request_id=new_request_id(),
        tokens=[1, 2, 3, 4, 5],
        generated=[7],
        cache_len=5,
        max_new_tokens=4,
        temperature=0.5,
        seed=11,
        eos_id=None,
        block_size=4,
        k=np.arange(2 * 2 * 4 * 2 * 3, dtype=np.float32).reshape(2, 2, 4, 2, 3),
        v=np.zeros((2, 2, 4, 2, 3), np.float32),
    )
    defaults.update(kw)
    return KvPayload(**defaults)


class TestTransports:
    def test_payload_bytes_roundtrip(self):
        p = _payload()
        q = KvPayload.from_bytes(p.to_bytes())
        assert q.meta() == p.meta()
        assert (q.k == p.k).all() and (q.v == p.v).all()
        assert q.k.dtype == p.k.dtype

    def test_send_requeues_past_rejecting_target(self):
        served = []

        def draining(payload):
            raise TransferRejected("draining")

        def healthy(payload):
            served.append(payload.request_id)
            return {"tokens": [9, 9]}

        t = LocalTransfer({"a": draining, "b": healthy})
        p = _payload()
        out = t.send(p)
        # the drain-race contract: the rejection cost a retry, not the
        # request — the second target served it
        assert out == {"tokens": [9, 9]} and served == [p.request_id]

    def test_send_raises_when_all_targets_reject(self):
        t = LocalTransfer(
            {"a": lambda p: (_ for _ in ()).throw(TransferRejected("x"))}
        )
        with pytest.raises(TransferError, match="no decode target"):
            t.send(_payload())

    def test_file_spool_roundtrip_and_rejection(self, tmp_path):
        spool = str(tmp_path / "spool")
        calls = []

        def handler(payload):
            calls.append(payload.request_id)
            if len(calls) == 1:
                raise TransferRejected("draining")
            return {"tokens": [int(t) + 1 for t in payload.generated]}

        stop = threading.Event()
        pump = threading.Thread(
            target=serve_spool, args=(spool, handler, stop), daemon=True
        )
        pump.start()
        try:
            ft = FileTransfer(spool)
            with pytest.raises(TransferRejected, match="draining"):
                ft.transfer(_payload(), spool, timeout=30)
            out = ft.transfer(_payload(generated=[5]), spool, timeout=30)
            assert out == {"tokens": [6]}
        finally:
            stop.set()
            pump.join(timeout=10)


# -- cache-aware router ----------------------------------------------------


def _status(rid, summary=(), bs=4, queue=0.0):
    return ReplicaStatus(
        replica_id=rid,
        url=f"http://r{rid}",
        healthy=True,
        queue_depth=queue,
        prefix_summary=tuple(summary),
        block_size=bs,
    )


class TestCacheAwareRouter:
    def test_prefix_blocks_is_deepest_shared_digest(self):
        toks = list(range(12))
        chain = prefix_chain(toks, 4)
        r = LeastLoadedRouter()
        assert r.prefix_blocks(_status(0, chain[:2]), toks) == 2
        assert r.prefix_blocks(_status(0, chain), toks) == 3
        assert r.prefix_blocks(_status(0), toks) == 0
        # a foreign digest set shares nothing
        other = prefix_chain([9] * 12, 4)
        assert r.prefix_blocks(_status(0, other), toks) == 0

    def test_pick_prefers_cache_warm_replica(self):
        toks = list(range(12))
        chain = prefix_chain(toks, 4)
        r = LeastLoadedRouter(cache_bonus=1.0)
        # replica 1 is busier but holds the whole prefix: 2 - 3 < 0
        r.update([_status(0, queue=0.0), _status(1, chain, queue=2.0)])
        assert r.pick(toks).replica_id == 1
        # without tokens the same table degrades to plain least-loaded
        r.update([_status(0, queue=0.0), _status(1, chain, queue=2.0)])
        assert r.pick().replica_id == 0

    def test_pick_bumps_inflight(self):
        toks = list(range(8))
        chain = prefix_chain(toks, 4)
        r = LeastLoadedRouter(cache_bonus=1.0)
        r.update([_status(0, chain), _status(1, chain)])
        first = r.pick(toks).replica_id
        # the bonus ties; in-flight from the first pick breaks the tie
        assert r.pick(toks).replica_id != first
