"""Generation server tests: real HTTP round-trips against a tiny model."""

import json
import threading
import time
import urllib.request

import pytest

from torchx_tpu.apps.generate_server import GenerateService, serve


@pytest.fixture(scope="module")
def server_url():
    srv = serve("tiny", port=0)  # OS-assigned port
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()


def post(url, payload):
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=120) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


class TestGenerateServer:
    def test_healthz(self, server_url):
        with urllib.request.urlopen(f"{server_url}/healthz", timeout=30) as r:
            body = json.loads(r.read())
        assert body["status"] == "ok"
        assert body["model"] == "tiny"

    def test_token_generation(self, server_url):
        code, body = post(
            f"{server_url}/v1/generate",
            {"tokens": [[1, 2, 3, 4]], "max_new_tokens": 4},
        )
        assert code == 200
        (seq,) = body["tokens"]
        assert len(seq) == 8 and seq[:4] == [1, 2, 3, 4]

    def test_mixed_lengths_batch(self, server_url):
        code, body = post(
            f"{server_url}/v1/generate",
            {"tokens": [[1, 2, 3], [4, 5, 6, 7, 8]], "max_new_tokens": 2},
        )
        assert code == 200
        a, b = body["tokens"]
        assert len(a) == 5 and a[:3] == [1, 2, 3]
        assert len(b) == 7 and b[:5] == [4, 5, 6, 7, 8]

    def test_text_mode_byte_codec(self, server_url):
        code, body = post(
            f"{server_url}/v1/generate",
            {"text": "hi", "max_new_tokens": 3},
        )
        assert code == 200
        (text,) = body["text"]
        assert text.startswith("hi")

    def test_errors_are_4xx(self, server_url):
        code, body = post(f"{server_url}/v1/generate", {"tokens": [[]]})
        assert code == 400 and "error" in body
        code, body = post(
            f"{server_url}/v1/generate",
            {"tokens": [[1]], "max_new_tokens": 10_000},
        )
        assert code == 400 and "max_seq" in body["error"]
        code, _ = post(f"{server_url}/nope", {})
        assert code == 404

    def test_unknown_config_rejected(self):
        with pytest.raises(ValueError, match="unknown config"):
            GenerateService("not-a-model")

    def test_component_materializes(self):
        from torchx_tpu.components.serve import generate_server

        app = generate_server(
            "llama3_1b", port=9000, int8=True, tpu="v5litepod-8"
        )
        (role,) = app.roles
        assert "--int8" in role.args
        assert role.port_map == {"http": 9000}
        assert role.resource.tpu is not None

    def test_disagg_component_materializes(self):
        from torchx_tpu.components.serve import generate_server_disagg
        from torchx_tpu.serve.kv_transfer import ROLE_METADATA_KEY

        app = generate_server_disagg(
            "llama3_1b", prefill_replicas=2, decode_replicas=2
        )
        pre, dec = app.roles
        assert pre.name == "prefill" and dec.name == "decode"
        assert pre.num_replicas == 2 and dec.num_replicas == 2
        i = list(pre.args).index("--serve-role")
        assert pre.args[i + 1] == "prefill"
        # default transfer spec spans the decode gang's port range and is
        # mirrored into both roles' metadata for the TPX213 submit rule
        spec = pre.metadata[ROLE_METADATA_KEY]
        assert spec == "http:http://127.0.0.1:8100,http://127.0.0.1:8101"
        assert dec.metadata[ROLE_METADATA_KEY] == spec
        assert spec in pre.args and spec in dec.args

    def test_disagg_component_rejects_bad_transfer_spec(self):
        from torchx_tpu.components.serve import generate_server_disagg

        with pytest.raises(ValueError, match="kv-transfer"):
            generate_server_disagg("llama3_1b", kv_transfer="smoke-signal:x")


class TestBatcher:
    """Cross-request coalescing: concurrent compatible requests merge into
    one device batch (JetStream-style); incompatible ones don't."""

    def test_concurrent_requests_coalesce(self):
        svc = GenerateService("tiny", batch_window_ms=200, max_batch=8, engine="coalesce")
        try:
            # warm the jit cache so the batch window isn't spent compiling
            svc.generate([[9, 9]], max_new_tokens=2)
            base_batches = svc.batches
            results = {}
            def hit(i):
                results[i] = svc.generate([[i, i + 1]], max_new_tokens=2)[0]
            threads = [
                threading.Thread(target=hit, args=(i,)) for i in range(1, 5)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert len(results) == 4
            for i, seq in results.items():
                assert seq[:2] == [i, i + 1] and len(seq) == 4
            # 4 compatible sequences arrived within one 200ms window ->
            # strictly fewer device dispatches than sequences
            assert svc.batches - base_batches < 4
        finally:
            svc.close()

    def test_incompatible_keys_do_not_merge(self):
        svc = GenerateService("tiny", batch_window_ms=50, max_batch=8, engine="coalesce")
        try:
            svc.generate([[1, 2]], max_new_tokens=2)
            svc.generate([[1, 2, 3]], max_new_tokens=2)  # different length
            base = svc.batches
            out = svc.generate(
                [[1, 2], [1, 2, 3]], max_new_tokens=2
            )  # mixed lengths in ONE request: two dispatches
            assert svc.batches - base == 2
            assert len(out[0]) == 4 and len(out[1]) == 5
        finally:
            svc.close()

    def test_decode_errors_surface_to_caller(self):
        svc = GenerateService("tiny", batch_window_ms=1, engine="coalesce")
        try:
            with pytest.raises(ValueError, match="max_seq"):
                svc.generate([[1] * 100], max_new_tokens=100)
        finally:
            svc.close()

    def test_close_is_idempotent(self):
        svc = GenerateService("tiny", batch_window_ms=1, engine="coalesce")
        svc.close()
        svc.close()

    def test_generate_after_close_raises(self):
        svc = GenerateService("tiny", batch_window_ms=1, engine="coalesce")
        svc.close()
        with pytest.raises(RuntimeError, match="closed"):
            svc.generate([[1, 2]], max_new_tokens=2)

    def test_close_drains_mixed_length_work(self):
        # a mixed-length request enqueues two incompatible pendings; a
        # close() racing the first dispatch must still let BOTH complete
        # (the shutdown sentinel re-arms after the incompatible re-queue)
        svc = GenerateService("tiny", batch_window_ms=100, max_batch=8, engine="coalesce")
        svc.generate([[5, 6]], max_new_tokens=2)  # warm compile
        svc.generate([[5, 6, 7]], max_new_tokens=2)
        results = []
        t = threading.Thread(
            target=lambda: results.append(
                svc.generate([[1, 2], [1, 2, 3]], max_new_tokens=2)
            )
        )
        t.start()
        time.sleep(0.01)  # let the pendings enqueue
        svc.close()
        t.join(timeout=60)
        assert not t.is_alive(), "caller stranded by shutdown"
        # either both sequences completed, or the race landed on the
        # closed error — never a hang
        if results:
            a, b = results[0]
            assert len(a) == 4 and len(b) == 5


class TestStreaming:
    def test_stream_matches_batch(self, server_url):
        # streaming yields the same tokens the batch path returns, in
        # incrementally delivered JSONL chunks
        code, body = post(
            f"{server_url}/v1/generate",
            {"tokens": [[1, 2, 3, 4]], "max_new_tokens": 6},
        )
        assert code == 200
        (expect,) = body["tokens"]
        req = urllib.request.Request(
            f"{server_url}/v1/generate",
            data=json.dumps(
                {
                    "tokens": [[1, 2, 3, 4]],
                    "max_new_tokens": 6,
                    "stream": True,
                    "stream_chunk": 2,
                }
            ).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        got = []
        lines = []
        with urllib.request.urlopen(req, timeout=120) as resp:
            assert resp.headers["Content-Type"] == "application/jsonl"
            for raw in resp:
                line = json.loads(raw)
                lines.append(line)
                got.extend(line.get("tokens", []))
        assert lines[-1] == {"done": True}
        assert got == expect[4:]
        # delivered in >1 chunk (chunk=2 over 6 tokens: 1 + 2 + 2 + 1)
        assert len(lines) >= 3

    def test_stream_rejects_multi_sequence(self, server_url):
        code, body = post(
            f"{server_url}/v1/generate",
            {"tokens": [[1, 2], [3, 4]], "max_new_tokens": 2, "stream": True},
        )
        assert code == 400
        assert "one sequence" in body["error"]

    def test_stream_text_mode(self, server_url):
        req = urllib.request.Request(
            f"{server_url}/v1/generate",
            data=json.dumps(
                {"text": "hi", "max_new_tokens": 3, "stream": True}
            ).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        deltas = []
        with urllib.request.urlopen(req, timeout=120) as resp:
            for raw in resp:
                line = json.loads(raw)
                if "text_delta" in line:
                    deltas.append(line["text_delta"])
        assert deltas  # decoded something, byte-codec round-trips


class TestStreamValidation:
    def test_stream_overflow_is_clean_400(self, server_url):
        # validation happens BEFORE the 200 goes out: the client sees a
        # clean 400 JSON error, not a half-started stream
        code, body = post(
            f"{server_url}/v1/generate",
            {
                "tokens": [[1, 2, 3]],
                "max_new_tokens": 10**6,
                "stream": True,
            },
        )
        assert code == 400
        assert "max_seq" in body["error"]

    def test_stream_chunk_zero_clamped(self, server_url):
        # stream_chunk=0 would loop forever if passed through; the handler
        # clamps it to >= 1
        req = urllib.request.Request(
            f"{server_url}/v1/generate",
            data=json.dumps(
                {
                    "tokens": [[1, 2]],
                    "max_new_tokens": 3,
                    "stream": True,
                    "stream_chunk": 0,
                }
            ).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        got = []
        with urllib.request.urlopen(req, timeout=120) as resp:
            for raw in resp:
                got.append(json.loads(raw))
        assert got[-1] == {"done": True}
        assert sum(len(x.get("tokens", [])) for x in got) == 3


class TestContinuousEngineServer:
    """The default engine is the continuous-batching ServeEngine; its
    stats surface on /healthz and its drain path returns 503s."""

    def test_healthz_reports_engine_stats(self, server_url):
        with urllib.request.urlopen(f"{server_url}/healthz", timeout=30) as r:
            body = json.loads(r.read())
        assert body["engine"] == "continuous"
        for k in ("occupancy", "queue_depth", "active_slots", "kv_blocks_free"):
            assert k in body, body

    def test_metricz_exports_serving_gauges(self, server_url):
        post(  # make sure at least one request has decoded
            f"{server_url}/v1/generate",
            {"tokens": [[2, 3]], "max_new_tokens": 2},
        )
        with urllib.request.urlopen(f"{server_url}/metricz", timeout=30) as r:
            text = r.read().decode()
        assert "tpx_serve_slot_occupancy" in text
        assert "tpx_serve_tokens_total" in text

    def test_engine_matches_coalesce_greedy(self):
        cont = GenerateService("tiny", engine="continuous", max_batch=4)
        coal = GenerateService(
            "tiny", engine="coalesce", batch_window_ms=1, max_batch=4
        )
        try:
            for prompt in ([1, 2, 3], [9, 8, 7, 6]):
                a = cont.generate([prompt], max_new_tokens=4)[0]
                b = coal.generate([prompt], max_new_tokens=4)[0]
                assert a == b, (prompt, a, b)
        finally:
            cont.close()
            coal.close()

    def test_seeded_sampling_is_deterministic_over_http(self, server_url):
        payload = {
            "tokens": [[4, 5]],
            "max_new_tokens": 4,
            "temperature": 0.8,
            "seed": 7,
        }
        _, a = post(f"{server_url}/v1/generate", payload)
        _, b = post(f"{server_url}/v1/generate", payload)
        assert a["tokens"] == b["tokens"]

    def test_eos_id_field_respected(self, server_url):
        _, full = post(
            f"{server_url}/v1/generate",
            {"tokens": [[1, 2, 3]], "max_new_tokens": 6},
        )
        (seq,) = full["tokens"]
        eos = seq[4]  # second generated token
        _, cut = post(
            f"{server_url}/v1/generate",
            {"tokens": [[1, 2, 3]], "max_new_tokens": 6, "eos_id": eos},
        )
        (short,) = cut["tokens"]
        assert short == seq[:5] and short[-1] == eos

    def test_bad_engine_name_rejected(self):
        with pytest.raises(ValueError, match="engine"):
            GenerateService("tiny", engine="warp-drive")


class TestDrain:
    """SIGTERM drain: stop admission, finish in-flight work, fail
    /healthz so the pool's router stops sending traffic, exit cleanly."""

    def test_drain_finishes_inflight_then_rejects(self):
        svc = GenerateService("tiny", engine="continuous", max_batch=4)
        try:
            results = []
            t = threading.Thread(
                target=lambda: results.append(
                    svc.generate([[1, 2]], max_new_tokens=4)
                )
            )
            t.start()
            time.sleep(0.05)  # let it enter the engine
            assert svc.drain(grace_s=120) is True
            t.join(timeout=60)
            assert results and len(results[0][0]) == 6
            from torchx_tpu.apps.generate_server import ServiceDraining

            with pytest.raises(ServiceDraining):
                svc.generate([[1]], max_new_tokens=1)
        finally:
            svc.close()

    def test_draining_healthz_is_503(self):
        import urllib.error

        srv = serve("tiny", port=0, engine="continuous")
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{srv.server_address[1]}"
        try:
            srv.service.drain(grace_s=60)
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(f"{base}/healthz", timeout=30)
            assert e.value.code == 503
            assert json.loads(e.value.read())["status"] == "draining"
            code, body = post(
                f"{base}/v1/generate", {"tokens": [[1]], "max_new_tokens": 1}
            )
            assert code == 503 and "drain" in body["error"]
        finally:
            srv.shutdown()
            srv.service.close()

    def test_make_drain_sequence(self):
        # the SIGTERM callable: drain the service, then stop serve_forever
        from torchx_tpu.apps.generate_server import make_drain

        calls = []

        class FakeServer:
            def shutdown(self):
                calls.append("shutdown")

        class FakeService:
            def drain(self, grace_s):
                calls.append(("drain", grace_s))
                return True

        make_drain(FakeServer(), FakeService(), grace_s=7.5)()
        assert calls == [("drain", 7.5), "shutdown"]

    def test_coalesce_drain_also_stops_admission(self):
        from torchx_tpu.apps.generate_server import ServiceDraining

        svc = GenerateService("tiny", engine="coalesce", batch_window_ms=1)
        try:
            svc.generate([[1, 2]], max_new_tokens=2)  # warm
            assert svc.drain(grace_s=60) is True
            with pytest.raises(ServiceDraining):
                svc.generate([[1]], max_new_tokens=1)
        finally:
            svc.close()


class TestDisaggHttp:
    """Prefill/decode split over real HTTP: the prefill service streams
    KV payloads to the decode replica's /v1/kv and returns the full
    sequence to the client, matching the unified engine exactly."""

    @pytest.fixture(scope="class")
    def decode_url(self):
        srv = serve("tiny", port=0, engine="continuous", serve_role="decode")
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        yield f"http://127.0.0.1:{srv.server_address[1]}"
        srv.shutdown()
        srv.service.close()

    def test_round_trip_matches_unified(self, decode_url):
        pre = GenerateService(
            "tiny",
            engine="continuous",
            serve_role="prefill",
            kv_transfer=f"http:{decode_url}",
        )
        uni = GenerateService("tiny", engine="continuous")
        try:
            prompts = [[1, 2, 3], list(range(4, 21))]
            for prompt in prompts:
                split = pre.generate([prompt], max_new_tokens=5)[0]
                whole = uni.generate([prompt], max_new_tokens=5)[0]
                assert split == whole, (prompt, split, whole)
        finally:
            pre.close()
            uni.close()

    def test_decode_healthz_publishes_role_and_block_size(self, decode_url):
        with urllib.request.urlopen(f"{decode_url}/healthz", timeout=30) as r:
            body = json.loads(r.read())
        assert body["serve_role"] == "decode"
        assert body["block_size"] > 0
        assert "prefix_summary" in body

    def test_kv_endpoint_rejects_garbage(self, decode_url):
        req = urllib.request.Request(
            f"{decode_url}/v1/kv",
            data=b"not an npz payload",
            headers={"Content-Type": "application/octet-stream"},
            method="POST",
        )
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=30)
        assert e.value.code == 400

    def test_unified_role_rejects_kv_handoffs(self, server_url):
        # a valid payload at a non-decode replica is rejected (503) so
        # the sender requeues it to a real decode target
        import numpy as np

        from torchx_tpu.serve.kv_transfer import KvPayload, new_request_id

        payload = KvPayload(
            request_id=new_request_id(),
            tokens=[1, 2, 3, 4],
            generated=[5],
            cache_len=4,
            max_new_tokens=4,
            temperature=0.0,
            seed=0,
            eos_id=None,
            block_size=16,
            k=np.zeros((2, 1, 16, 2, 32), np.float32),
            v=np.zeros((2, 1, 16, 2, 32), np.float32),
        )
        req = urllib.request.Request(
            f"{server_url}/v1/kv",
            data=payload.to_bytes(),
            headers={"Content-Type": "application/octet-stream"},
            method="POST",
        )
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=30)
        assert e.value.code == 503

    def test_role_validation(self):
        with pytest.raises(ValueError, match="serve role"):
            GenerateService("tiny", serve_role="sideways")
        with pytest.raises(ValueError, match="continuous"):
            GenerateService("tiny", engine="coalesce", serve_role="decode")
        with pytest.raises(ValueError, match="kv.transfer|transfer"):
            GenerateService(
                "tiny", engine="continuous", serve_role="prefill"
            )
