"""Generation server tests: real HTTP round-trips against a tiny model."""

import json
import threading
import urllib.request

import pytest

from torchx_tpu.apps.generate_server import GenerateService, serve


@pytest.fixture(scope="module")
def server_url():
    srv = serve("tiny", port=0)  # OS-assigned port
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()


def post(url, payload):
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=120) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


class TestGenerateServer:
    def test_healthz(self, server_url):
        with urllib.request.urlopen(f"{server_url}/healthz", timeout=30) as r:
            body = json.loads(r.read())
        assert body["status"] == "ok"
        assert body["model"] == "tiny"

    def test_token_generation(self, server_url):
        code, body = post(
            f"{server_url}/v1/generate",
            {"tokens": [[1, 2, 3, 4]], "max_new_tokens": 4},
        )
        assert code == 200
        (seq,) = body["tokens"]
        assert len(seq) == 8 and seq[:4] == [1, 2, 3, 4]

    def test_mixed_lengths_batch(self, server_url):
        code, body = post(
            f"{server_url}/v1/generate",
            {"tokens": [[1, 2, 3], [4, 5, 6, 7, 8]], "max_new_tokens": 2},
        )
        assert code == 200
        a, b = body["tokens"]
        assert len(a) == 5 and a[:3] == [1, 2, 3]
        assert len(b) == 7 and b[:5] == [4, 5, 6, 7, 8]

    def test_text_mode_byte_codec(self, server_url):
        code, body = post(
            f"{server_url}/v1/generate",
            {"text": "hi", "max_new_tokens": 3},
        )
        assert code == 200
        (text,) = body["text"]
        assert text.startswith("hi")

    def test_errors_are_4xx(self, server_url):
        code, body = post(f"{server_url}/v1/generate", {"tokens": [[]]})
        assert code == 400 and "error" in body
        code, body = post(
            f"{server_url}/v1/generate",
            {"tokens": [[1]], "max_new_tokens": 10_000},
        )
        assert code == 400 and "max_seq" in body["error"]
        code, _ = post(f"{server_url}/nope", {})
        assert code == 404

    def test_unknown_config_rejected(self):
        with pytest.raises(ValueError, match="unknown config"):
            GenerateService("not-a-model")

    def test_component_materializes(self):
        from torchx_tpu.components.serve import generate_server

        app = generate_server(
            "llama3_1b", port=9000, int8=True, tpu="v5litepod-8"
        )
        (role,) = app.roles
        assert "--int8" in role.args
        assert role.port_map == {"http": 9000}
        assert role.resource.tpu is not None
