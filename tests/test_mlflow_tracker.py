"""MLflow tracker backend tests against an in-memory fake client (the
reference pattern: mlflow_test.py runs against a throwaway tracking store;
mlflow itself is not a baked dependency here, so the client surface the
tracker touches is faked instead)."""

from __future__ import annotations

import re
import sys
import types
from dataclasses import dataclass, field
from typing import Any, Optional

import pytest


@dataclass
class _Info:
    run_id: str
    artifact_uri: str


@dataclass
class _Data:
    tags: dict = field(default_factory=dict)
    params: dict = field(default_factory=dict)
    metrics: dict = field(default_factory=dict)


@dataclass
class _Run:
    info: _Info
    data: _Data = field(default_factory=_Data)


@dataclass
class _FileInfo:
    path: str
    is_dir: bool


class _PagedList(list):
    """Mimics mlflow's PagedList: a list with a ``token`` attribute."""

    token: Optional[str] = None


class FakeMlflowClient:
    def __init__(self, tracking_uri: Optional[str] = None) -> None:
        self.tracking_uri = tracking_uri
        self.experiments: dict[str, str] = {}
        self.runs: dict[str, _Run] = {}
        self.artifact_store: dict[str, list[str]] = {}  # run -> file paths
        self._n = 0

    def get_experiment_by_name(self, name):
        if name in self.experiments:
            return types.SimpleNamespace(experiment_id=self.experiments[name])
        return None

    def create_experiment(self, name):
        self.experiments[name] = f"exp-{len(self.experiments)}"
        return self.experiments[name]

    def create_run(self, experiment_id, tags=None, run_name=None):
        self._n += 1
        rid = f"mlrun-{self._n}"
        run = _Run(
            info=_Info(run_id=rid, artifact_uri=f"mlflow-artifacts:/{rid}"),
            data=_Data(tags=dict(tags or {})),
        )
        self.runs[rid] = run
        self.artifact_store[rid] = []
        return run

    def get_run(self, run_id):
        return self.runs[run_id]

    # paginate with tiny pages so _all_runs' page_token loop is exercised
    PAGE_SIZE = 2

    def search_runs(
        self,
        experiment_ids,
        filter_string: Optional[str] = None,
        page_token: Optional[str] = None,
    ):
        out = list(self.runs.values())
        if filter_string:
            m = re.search(r"= '([^']*)'", filter_string)
            want = m.group(1) if m else ""
            out = [r for r in out if r.data.tags.get("tpx.run_id") == want]

        start = int(page_token) if page_token else 0
        page = _PagedList(out[start : start + self.PAGE_SIZE])
        page.token = (
            str(start + self.PAGE_SIZE)
            if start + self.PAGE_SIZE < len(out)
            else None
        )
        return page

    def set_tag(self, run_id, key, value):
        self.runs[run_id].data.tags[key] = value

    def log_param(self, run_id, key, value):
        self.runs[run_id].data.params[key] = str(value)

    def log_metric(self, run_id, key, value):
        self.runs[run_id].data.metrics[key] = value

    def log_artifact(self, run_id, local_path, artifact_path=None):
        import os

        name = os.path.basename(local_path)
        dest = f"{artifact_path}/{name}" if artifact_path else name
        self.artifact_store[run_id].append(dest)

    def log_artifacts(self, run_id, local_dir, artifact_path=None):
        import os

        for root, _dirs, files in os.walk(local_dir):
            for f in files:
                rel = os.path.relpath(os.path.join(root, f), local_dir)
                dest = f"{artifact_path}/{rel}" if artifact_path else rel
                self.artifact_store[run_id].append(dest)

    def list_artifacts(self, run_id, path=None):
        # one flat level per call, emulating the real API
        seen: dict[str, _FileInfo] = {}
        prefix = f"{path}/" if path else ""
        for p in self.artifact_store[run_id]:
            if not p.startswith(prefix):
                continue
            rest = p[len(prefix) :]
            head = rest.split("/", 1)[0]
            full = prefix + head
            seen[full] = _FileInfo(path=full, is_dir="/" in rest)
        return list(seen.values())


@pytest.fixture
def tracker(monkeypatch):
    """MLflowTracker wired to the fake client via a stub mlflow module."""
    fake_clients = []

    def client_factory(tracking_uri=None):
        c = FakeMlflowClient(tracking_uri)
        fake_clients.append(c)
        return c

    mlflow_mod = types.ModuleType("mlflow")
    tracking_mod = types.ModuleType("mlflow.tracking")
    tracking_mod.MlflowClient = client_factory
    mlflow_mod.tracking = tracking_mod
    monkeypatch.setitem(sys.modules, "mlflow", mlflow_mod)
    monkeypatch.setitem(sys.modules, "mlflow.tracking", tracking_mod)

    from torchx_tpu.tracker.mlflow import MLflowTracker

    t = MLflowTracker(tracking_uri="fake://x", experiment_name="tpx-test")
    t._fake = fake_clients[0]
    return t


class TestMLflowTracker:
    def test_run_mapping_is_stable(self, tracker):
        a = tracker._mlflow_run("app-1")
        b = tracker._mlflow_run("app-1")
        assert a == b
        run = tracker._fake.runs[a]
        assert run.data.tags["tpx.run_id"] == "app-1"

    def test_metadata_params_vs_metrics(self, tracker):
        tracker.add_metadata("app-1", lr=3e-4, steps=100, name="llama", flag=True)
        md = tracker.metadata("app-1")
        assert md["name"] == "llama" and md["flag"] == "True"  # params
        assert md["lr"] == 3e-4 and md["steps"] == 100.0  # metrics

    def test_local_file_artifact_logged_to_store(self, tracker, tmp_path):
        f = tmp_path / "model.ckpt"
        f.write_text("weights")
        tracker.add_artifact("app-1", "ckpt", str(f), metadata={"step": 42})
        arts = tracker.artifacts("app-1")
        assert set(arts) == {"ckpt"}
        # resolved to the artifact-store URI, not the local path
        assert arts["ckpt"].path.startswith("mlflow-artifacts:/")
        assert arts["ckpt"].metadata == {"step": 42}
        mlrun = tracker._mlflow_run("app-1")
        assert "ckpt/model.ckpt" in tracker._fake.artifact_store[mlrun]

    def test_dir_artifact_logged_recursively(self, tracker, tmp_path):
        d = tmp_path / "ckpt_dir"
        (d / "sub").mkdir(parents=True)
        (d / "a.txt").write_text("1")
        (d / "sub" / "b.txt").write_text("2")
        tracker.add_artifact("app-1", "ckpt", str(d))
        mlrun = tracker._mlflow_run("app-1")
        assert sorted(tracker._fake.artifact_store[mlrun]) == [
            "ckpt/a.txt",
            "ckpt/sub/b.txt",
        ]

    def test_remote_artifact_becomes_pointer(self, tracker):
        tracker.add_artifact("app-1", "data", "gs://bucket/data")
        arts = tracker.artifacts("app-1")
        assert arts["data"].path == "gs://bucket/data"

    def test_store_only_artifacts_surface(self, tracker):
        # logged via raw mlflow, outside add_artifact
        mlrun = tracker._mlflow_run("app-1")
        tracker._fake.artifact_store[mlrun].append("profile/trace.json")
        arts = tracker.artifacts("app-1")
        assert "profile" in arts

    def test_lineage_upstream_and_downstream(self, tracker):
        tracker.add_source("train-1", "data-prep-1", artifact_name="tokens")
        tracker.add_source("eval-1", "train-1")
        lineage = tracker.lineage("train-1")
        assert [s.source_run_id for s in lineage.sources] == ["data-prep-1"]
        assert lineage.sources[0].artifact_name == "tokens"
        assert lineage.descendants == ["eval-1"]

    def test_source_order_stable_past_ten(self, tracker):
        # tag suffixes sort numerically: "source.10" after "source.2"
        for i in range(12):
            tracker.add_source("train-1", f"shard-{i}")
        order = [s.source_run_id for s in tracker.sources("train-1")]
        assert order == [f"shard-{i}" for i in range(12)]

    def test_descendants_paginated(self, tracker):
        # FakeMlflowClient pages at 2 runs; 4 tracked runs + sources forces
        # descendants() through multiple page tokens
        for name in ("eval-1", "eval-2", "eval-3"):
            tracker.add_source(name, "train-1")
        tracker.add_metadata("train-1", x=1)
        assert set(tracker.descendants("train-1")) == {
            "eval-1",
            "eval-2",
            "eval-3",
        }
        assert set(tracker.run_ids()) == {
            "train-1",
            "eval-1",
            "eval-2",
            "eval-3",
        }

    def test_run_ids_and_source_filter(self, tracker):
        tracker.add_source("eval-1", "train-1")
        tracker.add_metadata("train-1", x=1)
        assert set(tracker.run_ids()) == {"eval-1", "train-1"}
        assert list(tracker.run_ids(source_run_id="train-1")) == ["eval-1"]

    def test_log_params_flat(self, tracker):
        from dataclasses import dataclass as dc

        @dc
        class Opt:
            lr: float = 3e-4
            warmup: int = 100

        cfg = {"model": "llama3_1b", "opt": Opt(), "layers": [1, 2]}
        tracker.log_params_flat("app-1", cfg)
        md = tracker.metadata("app-1")
        assert md["model"] == "llama3_1b"
        assert md["opt.lr"] == 3e-4
        assert md["opt.warmup"] == 100.0
        assert md["layers"] == "[1, 2]"

    def test_factory_config_parse(self, tracker, monkeypatch):
        from torchx_tpu.tracker.mlflow import create

        t = create("fake://host:5000;experiment=myexp")
        assert t._fake if hasattr(t, "_fake") else True
        assert "myexp" in t._client.experiments
