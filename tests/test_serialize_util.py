"""AppDef JSON serialization + util module tests."""

import pytest

from torchx_tpu.specs.api import (
    AppDef,
    BindMount,
    Resource,
    RetryPolicy,
    Role,
    TpuSlice,
)
from torchx_tpu.specs.serialize import appdef_from_dict, appdef_to_dict
from torchx_tpu.util.colors import colored, state_color
from torchx_tpu.util.strings import normalize_str, truncate_middle


class TestSerialize:
    def make_app(self):
        return AppDef(
            name="train",
            metadata={"team": "ml"},
            roles=[
                Role(
                    name="trainer",
                    image="img:1",
                    entrypoint="python",
                    args=["-m", "t"],
                    env={"A": "1"},
                    num_replicas=2,
                    min_replicas=1,
                    max_retries=3,
                    retry_policy=RetryPolicy.APPLICATION,
                    port_map={"coordinator": 8476},
                    resource=Resource(
                        cpu=8, memMB=1024, tpu=TpuSlice("v5p", 16, "2x2x4")
                    ),
                    mounts=[BindMount(src_path="/a", dst_path="/b", read_only=True)],
                )
            ],
        )

    def test_roundtrip(self):
        app = self.make_app()
        restored = appdef_from_dict(appdef_to_dict(app))
        assert restored == app

    def test_workspace_roundtrip(self):
        from torchx_tpu.specs.api import Workspace

        app = self.make_app()
        app.roles[0].workspace = Workspace(projects={"./src": "app/src"})
        restored = appdef_from_dict(appdef_to_dict(app))
        assert restored == app
        assert restored.roles[0].workspace.projects == {"./src": "app/src"}

    def test_from_dict_minimal(self):
        app = appdef_from_dict(
            {"roles": [{"name": "r", "entrypoint": "echo", "args": ["hi"]}]}
        )
        assert app.roles[0].entrypoint == "echo"
        assert app.roles[0].resource.tpu is None

    def test_from_dict_no_roles(self):
        with pytest.raises(ValueError):
            appdef_from_dict({"name": "x"})


class TestUtilStrings:
    def test_normalize(self):
        assert normalize_str("My Job!x") == "my-job-x"
        assert len(normalize_str("x" * 100)) <= 63

    def test_truncate_middle(self):
        assert truncate_middle("abcdef", 10) == "abcdef"
        out = truncate_middle("abcdefghijklmno", 9)
        assert len(out) == 9 and "..." in out
        assert out.startswith("abc") and out.endswith("o")


class TestUtilColors:
    def test_colored(self):
        assert colored("x", "red") == "\x1b[31mx\x1b[0m"
        assert colored("x", "red", enabled=False) == "x"
        assert colored("x", "nope") == "x"

    def test_state_color(self):
        assert state_color("FAILED") == "red"
        assert state_color("RUNNING") == "green"
        assert state_color("???") == "gray"


class TestUtilModules:
    def test_load_module_plain_and_attr(self):
        from torchx_tpu.util.modules import load_module

        mod = load_module("torchx_tpu.util.strings")
        assert mod is not None
        fn = load_module("torchx_tpu.util.modules:load_module")
        assert fn is load_module
        assert load_module("no.such.module") is None
        assert load_module("torchx_tpu.util.modules:nope") is None

    def test_import_attr_optional_dependency(self):
        from torchx_tpu.util.modules import import_attr

        assert import_attr("not_installed_pkg", "X", default=42) == 42
        got = import_attr("torchx_tpu.util.modules", "import_attr", default=None)
        assert got is import_attr
        # module exists but attr missing: a bug, not an absent dep
        import pytest

        with pytest.raises(AttributeError):
            import_attr("torchx_tpu.util.modules", "nope", default=1)


class TestUtilIO:
    def test_copy_and_read(self, tmp_path):
        from torchx_tpu.util.io import copy_path, exists, read_text

        src = tmp_path / "a.txt"
        src.write_text("payload")
        dst = tmp_path / "sub" / "b.txt"
        copy_path(str(src), str(dst))
        assert read_text(str(dst)) == "payload"
        assert exists(str(dst)) and not exists(str(tmp_path / "nope"))


class TestUtilTimes:
    def test_parse_when_forms(self):
        from torchx_tpu.util.times import parse_when

        assert parse_when(None) is None
        assert parse_when("") is None
        assert parse_when("1722333444.5") == 1722333444.5
        assert parse_when("2h", now=10_000.0) == 10_000.0 - 7200
        assert parse_when("30m", now=10_000.0) == 10_000.0 - 1800
        assert parse_when("1w", now=700_000.0) == 700_000.0 - 604800
        from datetime import datetime

        iso = "2026-07-29T10:00:00"
        assert parse_when(iso) == datetime.fromisoformat(iso).timestamp()
        import pytest

        with pytest.raises(ValueError, match="cannot parse"):
            parse_when("yesterdayish")
