"""AppDef JSON serialization + util module tests."""

import pytest

from torchx_tpu.specs.api import (
    AppDef,
    BindMount,
    Resource,
    RetryPolicy,
    Role,
    TpuSlice,
)
from torchx_tpu.specs.serialize import appdef_from_dict, appdef_to_dict
from torchx_tpu.util.colors import colored, state_color
from torchx_tpu.util.strings import normalize_str, truncate_middle


class TestSerialize:
    def make_app(self):
        return AppDef(
            name="train",
            metadata={"team": "ml"},
            roles=[
                Role(
                    name="trainer",
                    image="img:1",
                    entrypoint="python",
                    args=["-m", "t"],
                    env={"A": "1"},
                    num_replicas=2,
                    min_replicas=1,
                    max_retries=3,
                    retry_policy=RetryPolicy.APPLICATION,
                    port_map={"coordinator": 8476},
                    resource=Resource(
                        cpu=8, memMB=1024, tpu=TpuSlice("v5p", 16, "2x2x4")
                    ),
                    mounts=[BindMount(src_path="/a", dst_path="/b", read_only=True)],
                )
            ],
        )

    def test_roundtrip(self):
        app = self.make_app()
        restored = appdef_from_dict(appdef_to_dict(app))
        assert restored == app

    def test_workspace_roundtrip(self):
        from torchx_tpu.specs.api import Workspace

        app = self.make_app()
        app.roles[0].workspace = Workspace(projects={"./src": "app/src"})
        restored = appdef_from_dict(appdef_to_dict(app))
        assert restored == app
        assert restored.roles[0].workspace.projects == {"./src": "app/src"}

    def test_from_dict_minimal(self):
        app = appdef_from_dict(
            {"roles": [{"name": "r", "entrypoint": "echo", "args": ["hi"]}]}
        )
        assert app.roles[0].entrypoint == "echo"
        assert app.roles[0].resource.tpu is None

    def test_from_dict_no_roles(self):
        with pytest.raises(ValueError):
            appdef_from_dict({"name": "x"})


class TestUtilStrings:
    def test_normalize(self):
        assert normalize_str("My Job!x") == "my-job-x"
        assert len(normalize_str("x" * 100)) <= 63

    def test_truncate_middle(self):
        assert truncate_middle("abcdef", 10) == "abcdef"
        out = truncate_middle("abcdefghijklmno", 9)
        assert len(out) == 9 and "..." in out
        assert out.startswith("abc") and out.endswith("o")


class TestUtilColors:
    def test_colored(self):
        assert colored("x", "red") == "\x1b[31mx\x1b[0m"
        assert colored("x", "red", enabled=False) == "x"
        assert colored("x", "nope") == "x"

    def test_state_color(self):
        assert state_color("FAILED") == "red"
        assert state_color("RUNNING") == "green"
        assert state_color("???") == "gray"
