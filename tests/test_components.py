"""Component materialization + finder + linter tests (reference analog:
torchx/components/test/, specs/test/builders_test, finder_test)."""

import pytest

from torchx_tpu.components.dist import parse_j
from torchx_tpu.specs.api import AppDef
from torchx_tpu.specs.builders import (
    ComponentArgumentError,
    component_args_from_str,
    materialize_appdef,
)
from torchx_tpu.specs.file_linter import parse_docstring, validate_source
from torchx_tpu.specs.finder import (
    ComponentNotFoundException,
    get_component,
    get_components,
)


class TestParseJ:
    def test_forms(self):
        assert parse_j("2x4") == (None, 2, 4)
        assert parse_j("4") == (None, 4, None)
        assert parse_j("1:4") == (1, 4, None)
        assert parse_j("1:4x8") == (1, 4, 8)

    def test_invalid(self):
        for bad in ("", "x4", "ax2", "1:2:3"):
            with pytest.raises(ValueError):
                parse_j(bad)


class TestFinder:
    def test_builtins_discovered(self):
        components = get_components()
        for expected in ("dist.spmd", "dist.ddp", "utils.echo", "utils.sh", "utils.python"):
            assert expected in components, expected

    def test_get_component_unknown(self):
        with pytest.raises(ComponentNotFoundException):
            get_component("nope.nothing")

    def test_custom_file_component(self, tmp_path):
        f = tmp_path / "comp.py"
        f.write_text(
            "from torchx_tpu.specs import AppDef, Role\n"
            "def my_comp(msg: str = 'hi') -> AppDef:\n"
            "    '''My component.\n\n    Args:\n        msg: the message\n    '''\n"
            "    return AppDef(name='x', roles=[Role(name='r', image='i', entrypoint='echo', args=[msg])])\n"
        )
        c = get_component(f"{f}:my_comp")
        app = materialize_appdef(c.fn, ["--msg", "yo"])
        assert app.roles[0].args == ["yo"]

    def test_custom_file_component_missing_fn(self, tmp_path):
        f = tmp_path / "comp.py"
        f.write_text("x = 1\n")
        with pytest.raises(ComponentNotFoundException):
            get_component(f"{f}:nope")


class TestMaterialize:
    def test_spmd_materialize(self):
        c = get_component("dist.spmd")
        app = materialize_appdef(
            c.fn,
            ["-j", "2x4", "--script", "train.py", "--", "--lr", "0.1"],
        )
        role = app.roles[0]
        assert role.num_replicas == 2
        assert "--script" in role.args and "train.py" in role.args
        assert role.args[-2:] == ["--lr", "0.1"]
        assert role.env["XLA_FLAGS"].endswith("device_count=4")

    def test_spmd_tpu_slice(self):
        c = get_component("dist.spmd")
        app = materialize_appdef(c.fn, ["--tpu", "v5p-32", "-m", "train"])
        role = app.roles[0]
        assert role.resource.tpu.chips == 16
        assert role.num_replicas == 1  # one slice; hosts derived by scheduler

    def test_spmd_elastic(self):
        c = get_component("dist.spmd")
        app = materialize_appdef(c.fn, ["-j", "1:4", "-m", "train"])
        assert app.roles[0].min_replicas == 1
        assert app.roles[0].num_replicas == 4

    def test_spmd_requires_script_or_m(self):
        c = get_component("dist.spmd")
        with pytest.raises(ValueError):
            materialize_appdef(c.fn, ["-j", "1"])

    def test_ddp_single_node_endpoint(self):
        c = get_component("dist.ddp")
        app = materialize_appdef(c.fn, ["-j", "1x2", "--script", "t.py"])
        args = " ".join(app.roles[0].args)
        assert "localhost:0" in args

    def test_ddp_multi_node_defers_endpoint(self):
        c = get_component("dist.ddp")
        app = materialize_appdef(c.fn, ["-j", "2x2", "--script", "t.py"])
        role = app.roles[0]
        assert role.entrypoint == "sh"
        joined = " ".join(role.args)
        # macro still unsubstituted at materialize time
        assert "${coordinator_env}" in joined

    def test_echo_defaults(self):
        c = get_component("utils.echo")
        app = materialize_appdef(c.fn, [])
        assert app.roles[0].args == ["hello world"]

    def test_component_defaults_from_config(self):
        c = get_component("utils.echo")
        app = materialize_appdef(c.fn, [], defaults={"msg": "from-config"})
        assert app.roles[0].args == ["from-config"]

    def test_cli_overrides_config_defaults(self):
        c = get_component("utils.echo")
        app = materialize_appdef(
            c.fn, ["--msg", "from-cli"], defaults={"msg": "from-config"}
        )
        assert app.roles[0].args == ["from-cli"]

    def test_required_arg_missing(self):
        c = get_component("utils.touch")
        with pytest.raises(ComponentArgumentError):
            materialize_appdef(c.fn, [])

    def test_dict_and_bool_decoding(self):
        c = get_component("dist.spmd")
        app = materialize_appdef(
            c.fn,
            ["-m", "t", "--env", "A=1,B=2", "--debug", "true"],
        )
        role = app.roles[0]
        assert role.env["A"] == "1" and role.env["B"] == "2"
        assert role.env["JAX_LOG_COMPILES"] == "1"  # debug preset applied

    def test_args_from_str(self):
        assert component_args_from_str("-j 1x2 --msg 'a b'") == ["-j", "1x2", "--msg", "a b"]


class TestLinter:
    def test_valid_component(self):
        src = (
            "def c(x: int, y: str = 'a') -> AppDef:\n"
            "    '''doc'''\n"
            "    return AppDef(name='x')\n"
        )
        assert validate_source(src, "c") == []

    def test_missing_annotation(self):
        src = "def c(x) -> AppDef:\n    '''d'''\n    return None\n"
        errors = validate_source(src, "c")
        assert any("missing a type annotation" in e.description for e in errors)

    def test_unsupported_type(self):
        src = "def c(x: object) -> AppDef:\n    '''d'''\n    return None\n"
        errors = validate_source(src, "c")
        assert any("unsupported type" in e.description for e in errors)

    def test_missing_return(self):
        src = "def c(x: int):\n    '''d'''\n    return None\n"
        errors = validate_source(src, "c")
        assert any("return annotation" in e.description for e in errors)

    def test_kwargs_rejected(self):
        src = "def c(**kw: str) -> AppDef:\n    '''d'''\n    return None\n"
        errors = validate_source(src, "c")
        assert any("kwargs" in e.description for e in errors)

    def test_fn_not_found(self):
        errors = validate_source("x = 1", "c")
        assert errors and "not found" in errors[0].description

    def test_all_builtins_lint_clean(self):
        for name, c in get_components().items():
            assert c.validation_errors == [], f"{name}: {c.validation_errors}"

    def test_parse_docstring(self):
        summary, args = parse_docstring(
            "Does a thing.\n\n"
            "    Args:\n"
            "        alpha: first arg\n"
            "            continued help\n"
            "        beta: second arg\n"
        )
        assert summary == "Does a thing."
        assert args["alpha"] == "first arg continued help"
        assert args["beta"] == "second arg"
