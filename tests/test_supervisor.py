"""Supervisor state-machine tests against a scripted fake scheduler.

The scheduler's "cluster" is a script of terminal outcomes, one per
submission — so the whole preempt/classify/backoff/resubmit loop runs
deterministically in-process with injected sleep and rng."""

import json
import logging
import os
import random
from typing import Mapping, Optional

import pytest

from torchx_tpu.runner.api import Runner
from torchx_tpu.runner.events import get_events_logger
from torchx_tpu.runner.events.api import TpxEvent
from torchx_tpu.schedulers.api import DescribeAppResponse, Scheduler
from torchx_tpu.specs.api import (
    AppDef,
    AppDryRunInfo,
    AppState,
    AppStatus,
    CfgVal,
    FailureClass,
    Role,
    runopts,
)
from torchx_tpu.specs.serialize import (
    supervisor_policy_from_dict,
    supervisor_policy_to_dict,
)
from torchx_tpu.supervisor import (
    Supervisor,
    SupervisorPolicy,
    latest_checkpoint_step,
)
from torchx_tpu.settings import CHECKPOINT_MANIFEST, ENV_TPX_RESUME_STEP


class ScriptedScheduler(Scheduler[dict]):
    """Each ``schedule()`` consumes the next scripted terminal outcome;
    ``describe()`` then reports that attempt as immediately terminal."""

    def __init__(self, session_name: str, script=None, **kwargs):
        super().__init__("scripted", session_name)
        self.script = list(script or [])
        self.apps: dict[str, tuple[AppState, Optional[FailureClass]]] = {}
        self.submitted_envs: list[dict[str, str]] = []
        self._counter = 0

    def run_opts(self) -> runopts:
        return runopts()

    def _submit_dryrun(self, app: AppDef, cfg: Mapping[str, CfgVal]):
        return AppDryRunInfo({"app": app})

    def schedule(self, dryrun_info) -> str:
        self._counter += 1
        app_id = f"job_{self._counter}"
        outcome = (
            self.script.pop(0) if self.script else (AppState.SUCCEEDED, None)
        )
        self.apps[app_id] = outcome
        self.submitted_envs.append(dict(dryrun_info._app.roles[0].env))
        return app_id

    def describe(self, app_id: str) -> Optional[DescribeAppResponse]:
        if app_id not in self.apps:
            return None
        state, fclass = self.apps[app_id]
        return DescribeAppResponse(
            app_id=app_id, state=state, failure_class=fclass
        )

    def _cancel_existing(self, app_id: str) -> None:
        self.apps[app_id] = (AppState.CANCELLED, None)


class _CaptureEvents(logging.Handler):
    def __init__(self):
        super().__init__()
        self.events: list[TpxEvent] = []

    def emit(self, record: logging.LogRecord) -> None:
        msg = record.getMessage()
        if json.loads(msg).get("kind") == "span":
            return  # spans share the pipeline; these tests assert events only
        self.events.append(TpxEvent.deserialize(msg))


@pytest.fixture
def capture_events():
    handler = _CaptureEvents()
    logger = get_events_logger()
    logger.addHandler(handler)
    yield handler.events
    logger.removeHandler(handler)


def make_runner(script):
    sched = ScriptedScheduler("sup", script=script)
    runner = Runner("sup", {"scripted": lambda session_name, **kw: sched})
    return runner, sched


def dryrun(runner):
    app = AppDef(
        name="train",
        roles=[Role(name="trainer", image="i", entrypoint="python")],
    )
    return runner.dryrun(app, "scripted")


def fast_policy(**kwargs) -> SupervisorPolicy:
    defaults = dict(
        backoff_seconds=1.0,
        backoff_factor=2.0,
        jitter=0.0,
        poll_interval=0.01,
    )
    defaults.update(kwargs)
    return SupervisorPolicy(**defaults)


def run_supervised(script, policy):
    runner, sched = make_runner(script)
    sleeps: list[float] = []
    with runner:
        sup = Supervisor(
            runner,
            dryrun(runner),
            policy,
            sleep=sleeps.append,
            rng=random.Random(0),
        )
        result = sup.run()
    return result, sched, sleeps


PREEMPT = (AppState.PREEMPTED, FailureClass.PREEMPTION)
APP_FAIL = (AppState.FAILED, FailureClass.APP)
INFRA_FAIL = (AppState.FAILED, FailureClass.INFRA)
OK = (AppState.SUCCEEDED, None)


class TestSupervisorLoop:
    def test_preempted_twice_then_succeeds(self, tmp_path, capture_events):
        """The acceptance scenario: two spot reclaims, each resubmitted
        with backoff and checkpoint resume, then success within budget."""
        ckpt = tmp_path / "ckpt"
        ckpt.mkdir()
        (ckpt / CHECKPOINT_MANIFEST).write_text(json.dumps({"latest_step": 120}))

        result, sched, sleeps = run_supervised(
            [PREEMPT, PREEMPT, OK],
            fast_policy(max_preemptions=3, checkpoint_dir=str(ckpt)),
        )

        assert result.succeeded
        assert result.attempts == 3
        assert result.budget_exhausted is None
        assert result.retries[FailureClass.PREEMPTION] == 2
        assert result.retries[FailureClass.APP] == 0
        assert result.handles == [
            "scripted://sup/job_1",
            "scripted://sup/job_2",
            "scripted://sup/job_3",
        ]
        # first attempt starts fresh; every resubmit resumes from step 120
        assert ENV_TPX_RESUME_STEP not in sched.submitted_envs[0]
        assert sched.submitted_envs[1][ENV_TPX_RESUME_STEP] == "120"
        assert sched.submitted_envs[2][ENV_TPX_RESUME_STEP] == "120"
        assert result.resume_steps == [None, 120, 120]
        # capped exponential backoff: 1s then 2s (jitter=0)
        assert sleeps == [1.0, 2.0]

    def test_each_transition_emits_event(self, tmp_path, capture_events):
        result, _, _ = run_supervised(
            [PREEMPT, OK], fast_policy(max_preemptions=1)
        )
        assert result.succeeded
        sup_events = [e for e in capture_events if e.api == "supervise"]
        transitions = [e.app_metadata["transition"] for e in sup_events]
        assert transitions == ["submitted", "resubmitting", "submitted", "finished"]
        resub = sup_events[1]
        assert resub.app_metadata["failure_class"] == "PREEMPTION"
        assert resub.app_metadata["retry"] == 1
        assert resub.scheduler == "scripted"
        assert resub.app_id == "job_1"

    def test_preemption_budget_exhaustion(self):
        result, _, sleeps = run_supervised(
            [PREEMPT, PREEMPT, PREEMPT], fast_policy(max_preemptions=2)
        )
        assert not result.succeeded
        assert result.attempts == 3
        assert result.budget_exhausted == FailureClass.PREEMPTION
        assert result.retries[FailureClass.PREEMPTION] == 2
        assert result.status.state == AppState.PREEMPTED
        assert len(sleeps) == 2  # no backoff after the budget is spent

    def test_fatal_app_error_stays_failed(self):
        """Default policy: app bugs are deterministic; zero resubmits."""
        result, sched, sleeps = run_supervised([APP_FAIL], fast_policy())
        assert not result.succeeded
        assert result.attempts == 1
        assert result.budget_exhausted == FailureClass.APP
        assert result.status.state == AppState.FAILED
        assert result.status.failure_class == FailureClass.APP
        assert sleeps == []
        assert len(sched.submitted_envs) == 1

    def test_budgets_are_independent(self):
        """Preemptions must not eat the infra budget and vice versa."""
        result, _, _ = run_supervised(
            [PREEMPT, INFRA_FAIL, PREEMPT, INFRA_FAIL, OK],
            fast_policy(max_preemptions=2, max_infra_retries=2),
        )
        assert result.succeeded
        assert result.attempts == 5
        assert result.retries[FailureClass.PREEMPTION] == 2
        assert result.retries[FailureClass.INFRA] == 2

    def test_unclassified_failure_defaults_to_app(self):
        result, _, _ = run_supervised(
            [(AppState.FAILED, None)], fast_policy(max_app_retries=0)
        )
        assert result.budget_exhausted == FailureClass.APP

    def test_cancelled_app_is_not_retried(self):
        result, sched, _ = run_supervised(
            [(AppState.CANCELLED, None)], fast_policy(max_preemptions=5)
        )
        assert not result.succeeded
        assert result.attempts == 1
        assert result.status.state == AppState.CANCELLED

    def test_vanished_app_stops_the_loop(self):
        """A scheduler that forgot the app (expired/deleted) must halt the
        supervisor — resubmitting blind could double-run the job."""
        runner, sched = make_runner([PREEMPT])
        sched.describe = lambda app_id: None  # type: ignore[method-assign]
        with runner:
            result = Supervisor(
                runner, dryrun(runner), fast_policy(), sleep=lambda s: None
            ).run()
        assert result.status is None
        assert result.attempts == 1
        assert not result.succeeded

    def test_runner_supervise_wrapper(self, capture_events):
        runner, sched = make_runner([PREEMPT, OK])
        with runner:
            result = runner.supervise(
                dryrun(runner),
                fast_policy(max_preemptions=1, backoff_seconds=0.01),
            )
        assert result.succeeded
        top = [
            e
            for e in capture_events
            if e.api == "supervise"
            and e.app_metadata
            and "attempts" in e.app_metadata
        ]
        assert top and top[-1].app_metadata["attempts"] == 2

    def test_rejects_raw_dryrun_info(self):
        runner, _ = make_runner([])
        with runner, pytest.raises(ValueError, match="cannot resubmit"):
            Supervisor(runner, AppDryRunInfo({"raw": True}))


class TestPolicy:
    def test_budget_for(self):
        p = SupervisorPolicy(
            max_preemptions=7, max_infra_retries=2, max_app_retries=1
        )
        assert p.budget_for(FailureClass.PREEMPTION) == 7
        assert p.budget_for(FailureClass.INFRA) == 2
        assert p.budget_for(FailureClass.APP) == 1

    def test_backoff_caps_and_grows(self):
        p = SupervisorPolicy(
            backoff_seconds=5, backoff_factor=2, backoff_max_seconds=30, jitter=0
        )
        assert [p.backoff_delay(n) for n in range(1, 6)] == [5, 10, 20, 30, 30]

    def test_jitter_bounds(self):
        p = SupervisorPolicy(backoff_seconds=10, jitter=0.1)
        rng = random.Random(7)
        for n in range(1, 5):
            base = min(10 * 2 ** (n - 1), p.backoff_max_seconds)
            assert base * 0.9 <= p.backoff_delay(n, rng) <= base * 1.1

    def test_validation(self):
        with pytest.raises(ValueError):
            SupervisorPolicy(max_preemptions=-1)
        with pytest.raises(ValueError):
            SupervisorPolicy(backoff_factor=0.5)
        with pytest.raises(ValueError):
            SupervisorPolicy(jitter=1.5)
        with pytest.raises(ValueError):
            SupervisorPolicy(poll_interval=0)

    def test_serialization_round_trip(self):
        p = SupervisorPolicy(
            max_preemptions=5, checkpoint_dir="/ckpt", elastic=True
        )
        d = json.loads(json.dumps(supervisor_policy_to_dict(p)))
        assert supervisor_policy_from_dict(d) == p

    def test_unknown_policy_key_raises(self):
        with pytest.raises(ValueError, match="unknown supervisor policy keys"):
            supervisor_policy_from_dict({"max_preemption": 3})


class TestCheckpointManifest:
    def test_manifest_wins(self, tmp_path):
        (tmp_path / "40").mkdir()
        (tmp_path / CHECKPOINT_MANIFEST).write_text(
            json.dumps({"latest_step": 55})
        )
        assert latest_checkpoint_step(str(tmp_path)) == 55

    def test_fallback_scans_orbax_and_pickle_layouts(self, tmp_path):
        assert latest_checkpoint_step(str(tmp_path)) is None
        (tmp_path / "40").mkdir()
        (tmp_path / "step_30.pkl").write_bytes(b"")
        (tmp_path / "50.corrupt").mkdir()  # quarantined: never a candidate
        assert latest_checkpoint_step(str(tmp_path)) == 40

    def test_corrupt_manifest_falls_back(self, tmp_path):
        (tmp_path / CHECKPOINT_MANIFEST).write_text("{not json")
        (tmp_path / "step_7.pkl").write_bytes(b"")
        assert latest_checkpoint_step(str(tmp_path)) == 7

    def test_missing_directory(self, tmp_path):
        assert latest_checkpoint_step(str(tmp_path / "nope")) is None


class TestWaitTimeout:
    def test_wait_times_out(self):
        runner, sched = make_runner([])
        with runner:
            app_id = sched.schedule(dryrun(runner))
            sched.apps[app_id] = (AppState.RUNNING, None)
            handle = f"scripted://sup/{app_id}"
            with pytest.raises(TimeoutError, match="still"):
                runner.wait(handle, wait_interval=0.01, timeout=0.05)

    def test_wait_returns_before_timeout(self):
        runner, sched = make_runner([OK])
        with runner:
            handle = runner.schedule(dryrun(runner))
            status = runner.wait(handle, wait_interval=0.01, timeout=5)
        assert status.state == AppState.SUCCEEDED


class TestStatusShowsFailureClass:
    def test_status_format_names_the_class(self):
        runner, sched = make_runner([PREEMPT])
        with runner:
            handle = runner.schedule(dryrun(runner))
            status = runner.status(handle)
        assert status.failure_class == FailureClass.PREEMPTION
        assert "PREEMPTED (preemption)" in status.format()
        assert "PREEMPTED (preemption)" in str(status)

    def test_plain_states_unchanged(self):
        assert "SUCCEEDED (" not in AppStatus(state=AppState.SUCCEEDED).format()


class TestLedgerCrashSafety:
    """The client can die at ANY byte of a ledger write; resume must see
    exactly the transitions that completed — never a torn line, never a
    half-replaced meta.json."""

    def test_torn_final_line_skipped_and_restore_replays_complete_lines(self):
        from torchx_tpu.supervisor.ledger import LEDGER_FILE, AttemptLedger

        ledger = AttemptLedger("torn")
        ledger.append(
            "submitted", "job_1", attempt=1,
            handle="scripted://sup/job_1", resume_step=None,
        )
        ledger.append(
            "resubmitting", "job_1", attempt=1,
            failure_class="FailureClass.PREEMPTION",
        )
        ledger.append(
            "submitted", "job_2", attempt=2,
            handle="scripted://sup/job_2", resume_step=7,
            mesh="pp=1,dp=1,fsdp=4,ep=1,tp=1,sp=1",
        )
        # the client is SIGKILLed mid-append: a torn, non-JSON final line
        with open(os.path.join(ledger.path, LEDGER_FILE), "a") as f:
            f.write('{"transition": "resubmitting", "app_id": "job_2", "fail')
        assert [e["transition"] for e in ledger.entries()] == [
            "submitted", "resubmitting", "submitted",
        ]
        # a fresh supervisor restores exactly the completed transitions
        runner, _ = make_runner([])
        with runner:
            sup = Supervisor(
                runner, dryrun(runner), fast_policy(), session="torn-resumer"
            )
            sup._restore(ledger)
        assert sup._resume_handle == "scripted://sup/job_2"
        assert sup._resume_attempts == 2
        assert sup._resume_retries[FailureClass.PREEMPTION] == 1
        assert sup._resume_steps == [None, 7]
        assert sup._mesh_spec == "pp=1,dp=1,fsdp=4,ep=1,tp=1,sp=1"

    def test_meta_replace_is_atomic_past_a_dead_writer_tmp(self):
        from torchx_tpu.supervisor.ledger import META_FILE, AttemptLedger

        ledger = AttemptLedger("meta-atomic")
        ledger.write_meta({"v": 1})
        # a previous writer died between tmp-write and rename, leaving a
        # torn tmp; it must never shadow the committed doc, and the next
        # write_meta must clean it up (same tmp name, atomic replace)
        tmp = os.path.join(ledger.path, META_FILE + ".tmp")
        with open(tmp, "w") as f:
            f.write('{"v": ')
        assert ledger.read_meta() == {"v": 1}
        ledger.write_meta({"v": 2})
        assert ledger.read_meta() == {"v": 2}
        assert not os.path.exists(tmp)
