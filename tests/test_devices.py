"""Named-device mount mapping tests."""

class TestDeviceMounts:
    def test_gpu_mapping(self):
        from torchx_tpu.schedulers.devices import get_device_mounts

        gpu = get_device_mounts({"nvidia.com/gpu": 1})
        assert gpu[0].src_path == "/dev/nvidia0"
        assert any("nvidiactl" in m.src_path for m in gpu)

    def test_docker_scheduler_maps_named_devices(self):
        from unittest import mock

        from torchx_tpu.schedulers.docker_scheduler import DockerScheduler
        from torchx_tpu.specs.api import AppDef, Resource, Role

        sched = DockerScheduler("t", docker_client=mock.MagicMock())
        app = AppDef(
            name="g",
            roles=[
                Role(
                    name="g",
                    image="i",
                    entrypoint="e",
                    resource=Resource(cpu=1, memMB=1, devices={"nvidia.com/gpu": 1}),
                )
            ],
        )
        info = sched.submit_dryrun(app, {})
        devs = info.request.containers[0].kwargs["devices"]
        assert "/dev/nvidia0:/dev/nvidia0:rwm" in devs

    def test_unknown_device_skipped(self):
        from torchx_tpu.schedulers.devices import get_device_mounts

        assert get_device_mounts({"vendor.com/thing": 1}) == []


