"""Docs are executable: every quickstart block marked ``bash verify`` runs
verbatim (the local path of the user journey), ``python verify-write:<f>``
blocks are materialized as the files the commands expect, and the docs
build check (generated tables + links) passes.

Reference analog: torchx gates its docs with doctest + sphinx CI; here the
quickstart IS the test fixture, so the first page a user reads cannot rot.
"""

from __future__ import annotations

import re
import shlex
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
QUICKSTART = REPO / "docs" / "quickstart.md"

FENCE_RE = re.compile(
    r"^```(\w+) (verify[^\n`]*)\n(.*?)^```", re.M | re.S
)


def quickstart_blocks() -> list[tuple[str, str, str]]:
    """[(lang, marker, body)] in document order."""
    return [
        (m.group(1), m.group(2), m.group(3))
        for m in FENCE_RE.finditer(QUICKSTART.read_text())
    ]


def test_quickstart_has_verified_blocks():
    blocks = quickstart_blocks()
    langs = [lang for lang, _, _ in blocks]
    assert langs.count("bash") >= 2, blocks
    assert any(marker.startswith("verify-write:") for _, marker, _ in blocks)


@pytest.mark.integ
def test_quickstart_local_path_executes(tmp_path):
    """Run the quickstart's CI-verified journey end to end in a scratch
    dir: write train.py exactly as documented, then execute every
    documented command and require success (the spmd run must actually
    form the 2x2 mesh)."""
    import os

    # redirect HOME so subprocesses' per-user registries (~/.tpx_local_apps
    # etc.) land in the scratch dir, not the developer's real home
    env = {**os.environ, "HOME": str(tmp_path)}
    outputs: dict[str, str] = {}
    for lang, marker, body in quickstart_blocks():
        if lang == "python" and marker.startswith("verify-write:"):
            (tmp_path / marker.split(":", 1)[1]).write_text(body)
            continue
        assert lang == "bash" and marker == "verify", (lang, marker)
        for line in body.splitlines():
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            assert line.startswith("tpx "), f"unexpected quickstart cmd: {line}"
            argv = [sys.executable, "-m", "torchx_tpu.cli.main"] + shlex.split(
                line
            )[1:]
            proc = subprocess.run(
                argv,
                cwd=tmp_path,
                env=env,
                capture_output=True,
                text=True,
                timeout=300,
            )
            assert proc.returncode == 0, (
                f"quickstart cmd failed: {line}\n"
                f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
            )
            outputs[line] = proc.stdout + proc.stderr

    mesh_runs = [
        out for cmd, out in outputs.items() if "-j 2x2" in cmd
    ]
    assert mesh_runs and "SUCCEEDED" in mesh_runs[0]


def test_docs_build_check_passes():
    proc = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "check_docs.py")],
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_every_diagnostic_code_documented():
    """Every TPX diagnostic code the analyzers can emit has a row in the
    torchx_tpu/analyze docstring table (the one gen_api_docs renders into
    docs/api/analyze.md), and the table carries no dead rows."""
    import torchx_tpu.analyze as analyze_pkg

    code_re = re.compile(r"TPX\d{3}")
    emitted: set[str] = set()
    for src in (
        REPO / "torchx_tpu" / "analyze" / "rules.py",
        REPO / "torchx_tpu" / "analyze" / "explain.py",
        REPO / "torchx_tpu" / "specs" / "file_linter.py",
        REPO / "torchx_tpu" / "cli" / "cmd_lint.py",
        # the selfcheck pass engine emits the TPX9xx whole-program codes
        *sorted((REPO / "torchx_tpu" / "analyze" / "selfcheck").glob("*.py")),
    ):
        emitted |= set(code_re.findall(src.read_text()))
    documented = {
        m.group(0)
        for line in (analyze_pkg.__doc__ or "").splitlines()
        if line.startswith("| TPX")
        for m in [code_re.search(line)]
        if m
    }
    assert emitted - documented == set(), (
        f"codes emitted but missing from the analyze docstring table:"
        f" {sorted(emitted - documented)}"
    )
    assert documented - emitted == set(), (
        f"documented codes nothing emits: {sorted(documented - emitted)}"
    )
