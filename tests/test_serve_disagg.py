"""Disaggregated serving tests: prefill-only handoff export, transferred
decode parity against the unified engine and the dense reference, the
drain-race requeue contract, cached-vs-cold prefill parity (bit-identical
greedy and sampled outputs), and the copy-on-write tail guard."""

import numpy as np
import pytest

import jax

from torchx_tpu.models import generate as gen, llama
from torchx_tpu.serve.engine import (
    EngineStopped,
    ServeEngine,
    ServeRequest,
    serve_kv_payload,
)
from torchx_tpu.serve.kv_transfer import (
    LocalTransfer,
    TransferError,
    TransferRejected,
)


@pytest.fixture(scope="module")
def tiny():
    cfg = llama.CONFIGS["tiny"]()
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def dense_generate(params, cfg, prompt, max_new, temperature=0.0, seed=0):
    out = gen.generate(
        params,
        np.array([prompt], np.int32),
        cfg,
        max_new_tokens=max_new,
        temperature=temperature,
        rng=jax.random.PRNGKey(seed) if temperature > 0 else None,
    )
    return [int(t) for t in np.asarray(out)[0]]


def make_engine(tiny, **kw):
    cfg, params = tiny
    kw.setdefault("max_slots", 2)
    kw.setdefault("block_size", 8)
    return ServeEngine(params, cfg, **kw).start()


# -- cached-vs-cold prefill parity -----------------------------------------


class TestPrefixCacheParity:
    def test_repeat_prompt_hits_cache_and_stays_bit_identical(self, tiny):
        cfg, params = tiny
        eng = make_engine(tiny, enable_prefix_cache=True)
        try:
            prompt = list(range(1, 20))  # spans 2 full blocks at bs=8
            cold = eng.generate(prompt, 6, timeout=120).tokens
            assert cold == dense_generate(params, cfg, prompt, 6)
            hits0 = eng.prefix_cache.stats()["hits"]
            warm = eng.generate(prompt, 6, timeout=120).tokens
            assert eng.prefix_cache.stats()["hits"] > hits0
            # the cache-hit suffix prefill reproduced the cold output
            # exactly — same tokens, not merely similar
            assert warm == cold
        finally:
            eng.stop()

    def test_sampled_parity_and_seed_sensitivity_with_cache(self, tiny):
        eng = make_engine(tiny, enable_prefix_cache=True)
        try:
            prompt = list(range(3, 21))
            a = eng.generate(prompt, 6, temperature=0.9, seed=7, timeout=120)
            b = eng.generate(prompt, 6, temperature=0.9, seed=7, timeout=120)
            c = eng.generate(prompt, 6, temperature=0.9, seed=8, timeout=120)
            # sampling keys are position-absolute, so the warm (cached)
            # run draws the same stream the cold run did
            assert b.tokens == a.tokens
            assert c.tokens != a.tokens
        finally:
            eng.stop()

    def test_extended_prompt_reuses_shared_prefix(self, tiny):
        cfg, params = tiny
        eng = make_engine(tiny, enable_prefix_cache=True)
        try:
            base = list(range(5, 22))
            eng.generate(base, 4, timeout=120)
            longer = base + [40, 41, 42]
            got = eng.generate(longer, 4, timeout=120).tokens
            assert got == dense_generate(params, cfg, longer, 4)
            assert eng.prefix_cache.stats()["hit_tokens"] >= 16
        finally:
            eng.stop()


# -- copy-on-write tail guard ----------------------------------------------


class TestCopyOnWrite:
    def test_shared_tail_is_copied_before_write(self, tiny):
        # drive _ensure_capacity directly: a slot whose tail block another
        # holder references must get a private copy, never write in place
        eng = make_engine(tiny)
        try:
            blocks = eng.alloc.alloc(2)
            eng.tables.assign(0, blocks)
            eng.alloc.retain([blocks[1]])  # e.g. the prefix cache
            assert eng._ensure_capacity(0, 8)  # write pos in block index 1
            tail = eng.tables.blocks_of(0)[1]
            assert tail != blocks[1]
            assert not eng.alloc.is_shared(tail)
            # the other holder keeps its (now sole) reference
            assert eng.alloc.refcount(blocks[1]) == 1
            assert eng.tables.blocks_of(0)[0] == blocks[0]  # untouched
        finally:
            eng.stop()

    def test_unshared_tail_is_left_in_place(self, tiny):
        eng = make_engine(tiny)
        try:
            blocks = eng.alloc.alloc(2)
            eng.tables.assign(0, blocks)
            assert eng._ensure_capacity(0, 8)
            assert eng.tables.blocks_of(0) == blocks
        finally:
            eng.stop()


# -- prefill-only handoff export -------------------------------------------


class TestPrefillOnly:
    def test_handoff_snapshot_shape_and_state(self, tiny):
        cfg, _ = tiny
        eng = make_engine(tiny)
        try:
            prompt = list(range(1, 11))
            req = ServeRequest(
                prompt=prompt, max_new_tokens=5, prefill_only=True
            )
            eng.submit(req)
            assert req.wait(timeout=120) and req.error is None
            assert len(req.generated) == 1  # prefill sampled exactly one
            h = req.handoff
            assert h is not None
            assert h.tokens == prompt and h.cache_len == len(prompt)
            assert h.generated == req.generated
            n_blocks = -(-len(prompt) // eng.block_size)
            assert h.k.shape == (
                cfg.n_layers,
                n_blocks,
                eng.block_size,
                cfg.n_kv_heads,
                cfg.head_dim,
            )
            # the exported blocks were released back to the pool
            assert eng.alloc.used_blocks == eng.prefix_cache.cached_blocks
        finally:
            eng.stop()

    def test_finished_at_prefill_needs_no_handoff(self, tiny):
        cfg, params = tiny
        eng = make_engine(tiny)
        try:
            req = ServeRequest(
                prompt=[1, 2, 3], max_new_tokens=1, prefill_only=True
            )
            eng.submit(req)
            assert req.wait(timeout=120) and req.error is None
            assert req.handoff is None  # nothing left for a decode side
            assert req.tokens == dense_generate(params, cfg, [1, 2, 3], 1)
        finally:
            eng.stop()


# -- prefill -> decode transfer parity -------------------------------------


class TestDisaggParity:
    def _disagg_generate(self, pre, dec, prompt, max_new, **kw):
        req = ServeRequest(
            prompt=list(prompt),
            max_new_tokens=max_new,
            prefill_only=True,
            **kw,
        )
        pre.submit(req)
        assert req.wait(timeout=120) and req.error is None
        if req.handoff is None:
            return req.tokens
        transfer = LocalTransfer(
            {"decode": lambda p: serve_kv_payload(dec, p, timeout=120)}
        )
        out = transfer.send(req.handoff)
        return list(prompt) + [int(t) for t in out["tokens"]]

    def test_greedy_matches_unified_and_dense(self, tiny):
        cfg, params = tiny
        pre = make_engine(tiny)
        dec = make_engine(tiny)
        try:
            for prompt in ([1, 2, 3], list(range(4, 17)), [9]):
                got = self._disagg_generate(pre, dec, prompt, 6)
                assert got == dense_generate(params, cfg, prompt, 6)
        finally:
            pre.stop()
            dec.stop()

    def test_sampled_stream_continues_across_the_handoff(self, tiny):
        # decode must fold the same (seed, position) keys prefill would
        # have: the split sequence equals the unified sampled sequence
        pre = make_engine(tiny)
        dec = make_engine(tiny)
        uni = make_engine(tiny)
        try:
            prompt = list(range(2, 12))
            split = self._disagg_generate(
                pre, dec, prompt, 8, temperature=0.9, seed=7
            )
            whole = uni.generate(
                prompt, 8, temperature=0.9, seed=7, timeout=120
            ).tokens
            assert split == whole
        finally:
            pre.stop()
            dec.stop()
            uni.stop()

    def test_decode_side_respects_eos(self, tiny):
        cfg, params = tiny
        pre = make_engine(tiny)
        dec = make_engine(tiny)
        try:
            full = dense_generate(params, cfg, [1, 2, 3], 8)
            eos = full[3 + 2]  # emitted 3rd: decode side must stop there
            got = self._disagg_generate(pre, dec, [1, 2, 3], 8, eos_id=eos)
            assert got == full[: 3 + 3]
        finally:
            pre.stop()
            dec.stop()


# -- the drain-race requeue contract ---------------------------------------


class TestDrainRace:
    def test_draining_target_rejects_and_next_target_serves(self, tiny):
        cfg, params = tiny
        pre = make_engine(tiny)
        drainer = make_engine(tiny)
        healthy = make_engine(tiny)
        try:
            assert drainer.drain(timeout=30)  # empty: drains immediately
            req = ServeRequest(
                prompt=list(range(1, 8)), max_new_tokens=5, prefill_only=True
            )
            pre.submit(req)
            assert req.wait(timeout=120) and req.handoff is not None
            order = []

            def via(name, eng):
                def handler(payload):
                    order.append(name)
                    return serve_kv_payload(eng, payload, timeout=120)

                return handler

            transfer = LocalTransfer(
                {"a": via("a", drainer), "b": via("b", healthy)}
            )
            out = transfer.send(req.handoff)
            # the draining replica rejected; the request was requeued to
            # the next target and completed — not dropped
            assert order == ["a", "b"]
            got = list(req.prompt) + [int(t) for t in out["tokens"]]
            assert got == dense_generate(params, cfg, list(range(1, 8)), 5)
        finally:
            pre.stop()
            drainer.stop()
            healthy.stop()

    def test_all_targets_draining_surfaces_transfer_error(self, tiny):
        pre = make_engine(tiny)
        drainer = make_engine(tiny)
        try:
            assert drainer.drain(timeout=30)
            req = ServeRequest(
                prompt=[1, 2, 3, 4], max_new_tokens=4, prefill_only=True
            )
            pre.submit(req)
            assert req.wait(timeout=120) and req.handoff is not None
            transfer = LocalTransfer(
                {"a": lambda p: serve_kv_payload(drainer, p, timeout=120)}
            )
            with pytest.raises(TransferError, match="no decode target"):
                transfer.send(req.handoff)
            # the handoff payload is still intact for a later retry
            assert req.handoff.cache_len == 4
        finally:
            pre.stop()
            drainer.stop()

    def test_submit_prefilled_validates_geometry(self, tiny):
        cfg, _ = tiny
        pre = make_engine(tiny)
        dec = make_engine(tiny)
        try:
            req = ServeRequest(
                prompt=list(range(1, 10)), max_new_tokens=4, prefill_only=True
            )
            pre.submit(req)
            assert req.wait(timeout=120) and req.handoff is not None
            h = req.handoff
            bad = ServeRequest(
                prompt=h.tokens,
                max_new_tokens=h.max_new_tokens,
                generated=list(h.generated),
            )
            with pytest.raises(ValueError, match="blocks"):
                dec.submit_prefilled(
                    bad, h.k[:, :1], h.v[:, :1], h.cache_len, h.generated[-1]
                )
            with pytest.raises(ValueError, match="max_seq"):
                big = ServeRequest(
                    prompt=h.tokens,
                    max_new_tokens=cfg.max_seq,
                    generated=list(h.generated),
                )
                dec.submit_prefilled(
                    big, h.k, h.v, h.cache_len, h.generated[-1]
                )
        finally:
            pre.stop()
            dec.stop()

    def test_rejection_propagates_through_serve_kv_payload(self, tiny):
        pre = make_engine(tiny)
        drainer = make_engine(tiny)
        try:
            assert drainer.drain(timeout=30)
            req = ServeRequest(
                prompt=[5, 6, 7], max_new_tokens=3, prefill_only=True
            )
            pre.submit(req)
            assert req.wait(timeout=120) and req.handoff is not None
            with pytest.raises(TransferRejected):
                serve_kv_payload(drainer, req.handoff, timeout=30)
            with pytest.raises(EngineStopped):
                drainer.submit(ServeRequest(prompt=[1], max_new_tokens=1))
        finally:
            pre.stop()
            drainer.stop()
