"""Federation tests: cell registry durability, burn/affinity routing,
spillover + circuit breaking, the daemon's drain lifecycle + rehydration
reporting, 429 Retry-After handling, ``wait`` across a daemon restart,
region-by-region promotion waves, TPX605, and the deterministic two-cell
sim scenario."""

import json
import os
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from types import SimpleNamespace

import pytest

from torchx_tpu import settings
from torchx_tpu.control.client import ControlClient, ControlClientError
from torchx_tpu.control.daemon import ControlDaemon
from torchx_tpu.federation import (
    DRAINED,
    DRAINING,
    HEALTHY,
    UNCORDONED,
    CellHandle,
    CellRegistry,
    CellSpec,
    FederationError,
    FederationPromoter,
    FederationRouter,
)
from torchx_tpu.resilience.breaker import BreakerState
from torchx_tpu.runner.api import get_runner


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


class TestCellRegistry:
    def test_add_get_remove_rehydrate(self, tmp_path):
        root = str(tmp_path / "fed")
        reg = CellRegistry(root=root)
        reg.add("us-east1", "http://127.0.0.1:1001/", token="t1")
        reg.add("eu-west4", "http://127.0.0.1:1002", token="t2")
        # trailing slash normalized; journal is 0600 (it carries tokens)
        assert reg.get("us-east1").addr == "http://127.0.0.1:1001"
        assert os.stat(reg.path).st_mode & 0o777 == 0o600
        # a fresh registry over the same root replays the journal
        reg2 = CellRegistry(root=root)
        assert [s.name for s in reg2.cells()] == ["eu-west4", "us-east1"]
        assert reg2.get("eu-west4").token == "t2"
        # last writer wins: re-address then remove
        reg2.add("us-east1", "http://127.0.0.1:1003")
        assert reg2.remove("eu-west4")
        assert not reg2.remove("never-was")
        reg3 = CellRegistry(root=root)
        assert [s.name for s in reg3.cells()] == ["us-east1"]
        assert reg3.get("us-east1").addr == "http://127.0.0.1:1003"

    def test_add_requires_name_and_addr(self, tmp_path):
        reg = CellRegistry(root=str(tmp_path / "fed"))
        with pytest.raises(ValueError):
            reg.add("", "http://x")
        with pytest.raises(ValueError):
            reg.add("a", "")


# ---------------------------------------------------------------------------
# router scoring + dispatch (fake clients, no daemons)
# ---------------------------------------------------------------------------


class _FakeCellClient:
    """Scriptable stand-in for ControlClient's probe/dispatch surface."""

    def __init__(
        self,
        state=HEALTHY,
        rehydrated=True,
        draining=False,
        burn=0.0,
        dead=False,
    ):
        self.state = state
        self.rehydrated = rehydrated
        self.draining = draining
        self.burn = burn
        self.dead = dead
        self.calls = 0
        #: exception to raise from dispatched fns (None = succeed)
        self.dispatch_error = None

    def cell_status(self):
        if self.dead:
            raise ControlClientError(0, "unreachable")
        return {
            "cell": "x",
            "state": self.state,
            "draining": self.draining,
            "rehydrated": self.rehydrated,
        }

    def alerts(self):
        return {"enabled": True, "burns": {"ttft": {"long": self.burn}}}

    def do(self):
        self.calls += 1
        if self.dispatch_error is not None:
            raise self.dispatch_error
        return {"ok": True}


def _handle(name, client, clock=time.monotonic):
    return CellHandle(CellSpec(name=name, addr=f"http://{name}"), client=client, clock=clock)


def _router(handles, **kw):
    kw.setdefault("sleep", lambda s: None)
    kw.setdefault("probe_ttl_s", 0.0)  # re-probe every candidates() call
    return FederationRouter(handles, **kw)


class TestFederationRouter:
    def test_affinity_prefers_cache_warm_cell(self):
        a = _handle("aaa", _FakeCellClient())
        b = _handle("bbb", _FakeCellClient())
        b.update_prefix_digests(["d0", "d1", "d2"])
        r = _router([a, b])
        chain = ["d0", "d1", "d2", "d3"]
        assert [h.name for h in r.candidates(chain)] == ["bbb", "aaa"]
        # without a chain the name tie-break is deterministic
        assert [h.name for h in r.candidates()] == ["aaa", "bbb"]

    def test_overlap_is_a_prefix_match(self):
        b = _handle("bbb", _FakeCellClient())
        # holds a later block but NOT the chain head: no credit
        b.update_prefix_digests(["d2", "d3"])
        r = _router([b])
        assert r._overlap(b, ["d0", "d1", "d2", "d3"]) == 0.0
        b.update_prefix_digests(["d0", "d1"])
        assert r._overlap(b, ["d0", "d1", "d2", "d3"]) == 0.5

    def test_burn_over_budget_demotes_not_excludes(self):
        hot = _handle("aaa", _FakeCellClient(burn=3.0))
        cool = _handle("bbb", _FakeCellClient(burn=0.1))
        r = _router([hot, cool], burn_budget=1.0)
        assert [h.name for h in r.candidates()] == ["bbb", "aaa"]
        # the hot cell still serves when it is the only one left
        cool.client.dead = True
        name, _ = r.dispatch(lambda c: c.do())
        assert name == "aaa"

    def test_draining_unreachable_unrehydrated_excluded(self):
        ok = _handle("ok", _FakeCellClient())
        drn = _handle("drn", _FakeCellClient(state=DRAINING, draining=True))
        gone = _handle("gone", _FakeCellClient(dead=True))
        boot = _handle("boot", _FakeCellClient(rehydrated=False))
        r = _router([ok, drn, gone, boot])
        assert [h.name for h in r.candidates()] == ["ok"]

    def test_dispatch_spills_on_503_and_marks_draining(self):
        a = _handle("aaa", _FakeCellClient())
        b = _handle("bbb", _FakeCellClient())
        a.client.dispatch_error = ControlClientError(503, "cell draining")
        r = _router([a, b])
        name, result = r.dispatch(lambda c: c.do())
        assert name == "bbb" and result == {"ok": True}
        # the 503 verdict stuck: aaa drops out of the next candidate list
        # via its cached probe, before any TTL-driven re-probe
        assert a.last_probe["draining"] and a.last_probe["state"] == DRAINING

    def test_dispatch_reraises_non_spill_codes(self):
        a = _handle("aaa", _FakeCellClient())
        b = _handle("bbb", _FakeCellClient())
        a.client.dispatch_error = ControlClientError(400, "bad component")
        r = _router([a, b])
        with pytest.raises(ControlClientError) as ei:
            r.dispatch(lambda c: c.do())
        assert ei.value.code == 400
        assert b.client.calls == 0  # a malformed request is not replayed

    def test_transport_failures_trip_breaker_then_federation_error(self):
        clk = [0.0]
        a = _handle("aaa", _FakeCellClient(), clock=lambda: clk[0])
        a.client.dispatch_error = ControlClientError(0, "boom")
        slept = []
        # long probe TTL: the healthy-looking cached probe must not reset
        # the breaker's failure streak between dispatch rounds
        r = _router(
            [a], sleep=slept.append, clock=lambda: clk[0], probe_ttl_s=999.0
        )
        with pytest.raises(FederationError) as ei:
            r.dispatch(lambda c: c.do())
        assert "aaa" in ei.value.errors
        # trip_after transport failures opened the breaker
        assert a.breaker.state is BreakerState.OPEN
        assert a.client.calls == settings.FEDERATION_BREAKER_TRIP_AFTER
        # capped jittered backoff ran between rounds, never a hard spin
        assert len(slept) == r.max_rounds - 1
        assert all(0 < s <= r.policy.backoff_max_seconds * 1.5 for s in slept)

    def test_no_cells_is_federation_error(self):
        r = _router([])
        with pytest.raises(FederationError):
            r.dispatch(lambda c: c.do())

    def test_snapshot_reports_breaker_state(self):
        a = _handle("aaa", _FakeCellClient(burn=0.4))
        r = _router([a])
        snap = r.snapshot()
        assert snap["aaa"]["burn"] == 0.4
        assert snap["aaa"]["breaker"] == BreakerState.CLOSED.value


# ---------------------------------------------------------------------------
# satellite: 429 Retry-After handling in ControlClient
# ---------------------------------------------------------------------------


def _throttle_server(replies):
    """An HTTP server that pops one scripted reply per request:
    ("429", hint_header, hint_body) or ("200", body_dict)."""

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            pass

        def do_GET(self):
            kind = replies.pop(0)
            if kind[0] == "429":
                _, header, body_hint = kind
                body = {"error": "throttled"}
                if body_hint is not None:
                    body["retry_after_seconds"] = body_hint
                data = json.dumps(body).encode()
                self.send_response(429)
                if header is not None:
                    self.send_header("Retry-After", str(header))
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
            else:
                data = json.dumps(kind[1]).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

    srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, f"http://127.0.0.1:{srv.server_address[1]}"


class _Rng:
    def uniform(self, a, b):
        return 0.0  # no jitter: assert exact hints


class TestClient429Retry:
    def test_retry_after_header_honored_then_success(self):
        srv, addr = _throttle_server(
            [("429", 2, None), ("200", {"status": "ok"})]
        )
        try:
            slept = []
            client = ControlClient(
                addr, "t", sleep=slept.append, rng=_Rng(), retry_429=3
            )
            assert client.healthz() == {"status": "ok"}
            assert slept == [2.0]
        finally:
            srv.shutdown()

    def test_body_hint_used_when_header_missing_and_cap_applies(self):
        srv, addr = _throttle_server(
            [("429", None, 1.5), ("429", 10_000, None), ("200", {"status": "ok"})]
        )
        try:
            slept = []
            client = ControlClient(
                addr, "t", sleep=slept.append, rng=_Rng(), retry_429=3
            )
            assert client.healthz() == {"status": "ok"}
            assert slept == [1.5, settings.CONTROL_429_RETRY_CAP_SECONDS]
        finally:
            srv.shutdown()

    def test_attempts_are_bounded(self):
        srv, addr = _throttle_server([("429", 0, None)] * 4)
        try:
            slept = []
            client = ControlClient(
                addr, "t", sleep=slept.append, rng=_Rng(), retry_429=2
            )
            with pytest.raises(ControlClientError) as ei:
                client.healthz()
            assert ei.value.code == 429
            assert len(slept) == 2  # retry_429 sleeps, then surface
        finally:
            srv.shutdown()

    def test_retry_disabled_surfaces_immediately(self):
        srv, addr = _throttle_server([("429", 1, None)])
        try:
            slept = []
            client = ControlClient(
                addr, "t", sleep=slept.append, retry_429=0
            )
            with pytest.raises(ControlClientError) as ei:
                client.healthz()
            assert ei.value.code == 429 and slept == []
        finally:
            srv.shutdown()


# ---------------------------------------------------------------------------
# daemon: cell lifecycle + rehydration reporting
# ---------------------------------------------------------------------------


@pytest.fixture
def cell_daemon(tmp_path, monkeypatch):
    monkeypatch.setenv("TPX_WATCH_INTERVAL", "0.05")
    d = ControlDaemon(
        runner=get_runner("fed-test"),
        state_dir=str(tmp_path / "cell-a"),
        cell="us-east1",
    ).start()
    yield d
    d.close()
    d.runner.close()


class TestDaemonCellLifecycle:
    def test_healthz_reports_cell_and_rehydration(self, cell_daemon):
        client = ControlClient(cell_daemon.addr, cell_daemon.root_token)
        health = client.healthz()
        assert health["cell"] == "us-east1"
        assert health["rehydrated"] is True
        assert health["rehydration"]["journal_jobs"] == 0
        assert health["draining"] is False

    def test_drain_refuses_submits_and_uncordon_reopens(
        self, cell_daemon, tmp_path
    ):
        client = ControlClient(cell_daemon.addr, cell_daemon.root_token)
        assert client.cell_status()["state"] == HEALTHY
        drained = client.cell_drain()
        assert drained["draining"] and drained["state"] == DRAINED
        with pytest.raises(ControlClientError) as ei:
            client.submit(
                "utils.echo",
                ["--msg", "nope"],
                "local",
                cfg={"log_dir": str(tmp_path / "logs")},
            )
        assert ei.value.code == 503
        reopened = client.cell_uncordon()
        assert reopened["state"] == UNCORDONED
        assert client.cell_status()["state"] == HEALTHY
        handle = client.submit(
            "utils.echo",
            ["--msg", "back"],
            "local",
            cfg={"log_dir": str(tmp_path / "logs")},
        )
        assert client.wait(handle, timeout=60)["terminal"]

    def test_drain_survives_restart(self, cell_daemon):
        client = ControlClient(cell_daemon.addr, cell_daemon.root_token)
        client.cell_drain()
        state_dir = cell_daemon.state_dir
        cell_daemon.close()
        runner2 = get_runner("fed-test-2")
        d2 = ControlDaemon(runner=runner2, state_dir=state_dir, cell="us-east1")
        try:
            assert d2.cell_payload()["draining"] is True
            assert d2.cell_payload()["state"] == DRAINED
        finally:
            d2.close()
            runner2.close()

    def test_journal_records_carry_cell(self, cell_daemon, tmp_path):
        client = ControlClient(cell_daemon.addr, cell_daemon.root_token)
        handle = client.submit(
            "utils.echo",
            ["--msg", "stamped"],
            "local",
            cfg={"log_dir": str(tmp_path / "logs")},
        )
        client.wait(handle, timeout=60)
        from torchx_tpu.specs.api import parse_app_handle

        _, _, app_id = parse_app_handle(handle)
        event = cell_daemon.store.latest("local", app_id)
        assert event is not None and event.cell == "us-east1"

    def test_router_treats_unrehydrated_cell_as_drained(self, cell_daemon):
        handle = CellHandle(
            CellSpec(name="us-east1", addr=cell_daemon.addr),
            client=ControlClient(cell_daemon.addr, cell_daemon.root_token),
        )
        router = _router([handle])
        assert [h.name for h in router.candidates()] == ["us-east1"]
        # a daemon mid-rehydration answers /v1/cell but is not routable
        cell_daemon.rehydrated = False
        try:
            snap = handle.probe()
            assert snap["reachable"] and not snap["rehydrated"]
            assert router.candidates() == []
        finally:
            cell_daemon.rehydrated = True

    def test_probe_of_dead_daemon_feeds_breaker(self):
        handle = CellHandle(
            CellSpec(name="ghost", addr="http://127.0.0.1:1"),
            client=ControlClient("http://127.0.0.1:1", "t", timeout=0.2),
        )
        for _ in range(settings.FEDERATION_BREAKER_TRIP_AFTER):
            assert handle.probe()["reachable"] is False
        assert handle.breaker.state is BreakerState.OPEN


# ---------------------------------------------------------------------------
# satellite: wait() survives a daemon restart mid-long-poll
# ---------------------------------------------------------------------------


class TestWaitAcrossRestart:
    def test_wait_reconnects_and_resolves_from_journal(
        self, tmp_path, monkeypatch
    ):
        monkeypatch.setenv("TPX_WATCH_INTERVAL", "0.05")
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        state_dir = str(tmp_path / "control")
        d1 = ControlDaemon(
            runner=get_runner("fed-wait"),
            state_dir=state_dir,
            host="127.0.0.1",
            port=port,
        ).start()
        client = ControlClient(
            d1.addr,
            d1.root_token,
            timeout=5.0,
            # compress the reconnect backoff so the test stays fast
            sleep=lambda s: time.sleep(min(s, 0.05)),
        )
        handle = client.submit(
            "utils.echo",
            ["--msg", "over-the-gap"],
            "local",
            cfg={"log_dir": str(tmp_path / "logs")},
        )
        # let the job reach its (journaled) terminal state, then take the
        # daemon down and start the wait against the dead address
        client.wait(handle, timeout=60)
        d1.close()
        d1.runner.close()
        result, errors = {}, []

        def _wait():
            try:
                result.update(client.wait(handle, timeout=30))
            except Exception as e:  # noqa: BLE001 - asserted below
                errors.append(e)

        t = threading.Thread(target=_wait)
        t.start()
        time.sleep(0.3)  # a few reconnect attempts fail against the gap
        runner2 = get_runner("fed-wait-2")
        d2 = ControlDaemon(
            runner=runner2,
            state_dir=state_dir,
            host="127.0.0.1",
            port=port,
        )
        # tokens die with the daemon: hand the waiting client the new
        # root token BEFORE the restarted daemon starts answering (real
        # callers re-read the 0600 discovery file the restart rewrites)
        client.token = d2.root_token
        d2.start()
        try:
            t.join(timeout=30)
            assert not t.is_alive()
            assert errors == []
            assert result["state"] == "SUCCEEDED" and result["terminal"]
        finally:
            d2.close()
            runner2.close()

    def test_wait_gives_up_after_reconnect_budget(self):
        slept = []
        client = ControlClient(
            "http://127.0.0.1:1",
            "t",
            timeout=0.2,
            sleep=slept.append,
        )
        with pytest.raises(ControlClientError) as ei:
            client.wait("local://fed/ghost", timeout=120)
        assert ei.value.code == 0
        # one capped, growing backoff per failed reconnect
        assert len(slept) == client.WAIT_RECONNECT_ATTEMPTS - 1
        assert all(s <= 5.0 * 1.1 for s in slept)


# ---------------------------------------------------------------------------
# promotion waves
# ---------------------------------------------------------------------------


class _FakePipelineClient(_FakeCellClient):
    def __init__(self, terminal="PROMOTED", submit_error=None, **kw):
        super().__init__(**kw)
        self.terminal = terminal
        self.submit_error = submit_error
        self.submitted = []

    def pipeline_submit(self, spec):
        if self.submit_error is not None:
            raise self.submit_error
        self.submitted.append(spec)
        return {"pipeline": f"p-{len(self.submitted)}"}

    def pipeline_status(self, pid):
        return {"pipeline": pid, "state": self.terminal, "reason": ""}


class TestFederationPromoter:
    def _promoter(self, handles, **kw):
        kw.setdefault("sleep", lambda s: None)
        kw.setdefault("poll_interval_s", 0.0)
        return FederationPromoter(_router(handles), **kw)

    def test_wave_halts_on_rollback_and_skips_rest(self):
        a = _handle("aaa", _FakePipelineClient(terminal="PROMOTED"))
        b = _handle("bbb", _FakePipelineClient(terminal="ROLLED_BACK"))
        c = _handle("ccc", _FakePipelineClient(terminal="PROMOTED"))
        wave = self._promoter([a, b, c]).run_wave(
            {"name": "cand"}, order=["aaa", "bbb", "ccc"]
        )
        assert wave.promoted == ["aaa"]
        assert wave.halted and "bbb" in wave.halt_reason
        assert wave.skipped == ["ccc"]
        assert c.client.submitted == []  # the candidate never reached ccc

    def test_wave_halts_on_burn_after_promote(self):
        a = _handle("aaa", _FakePipelineClient(terminal="PROMOTED", burn=5.0))
        b = _handle("bbb", _FakePipelineClient(terminal="PROMOTED"))
        wave = self._promoter([a, b], burn_threshold=1.0).run_wave(
            {"name": "cand"}, order=["aaa", "bbb"]
        )
        assert wave.promoted == []
        assert wave.halted and "burn" in wave.halt_reason
        assert wave.skipped == ["bbb"]

    def test_drained_cell_is_skipped_without_halting(self):
        a = _handle(
            "aaa",
            _FakePipelineClient(
                submit_error=ControlClientError(503, "cell draining")
            ),
        )
        b = _handle("bbb", _FakePipelineClient(terminal="PROMOTED"))
        wave = self._promoter([a, b]).run_wave(
            {"name": "cand"}, order=["aaa", "bbb"]
        )
        assert wave.cells["aaa"]["state"] == "UNREACHED"
        assert wave.promoted == ["bbb"] and not wave.halted

    def test_default_order_is_healthiest_first(self):
        hot = _handle("aaa", _FakePipelineClient(burn=2.0))
        cool = _handle("bbb", _FakePipelineClient(burn=0.1))
        promoter = self._promoter([hot, cool], burn_threshold=10.0)
        assert promoter._wave_order(None) == ["bbb", "aaa"]


# ---------------------------------------------------------------------------
# TPX605
# ---------------------------------------------------------------------------


class TestTPX605:
    def _codes(self, config):
        from torchx_tpu.analyze.rules import check_federation_config

        return [(d.code, d.field) for d in check_federation_config(config)]

    def test_single_cell_federation_warns(self):
        codes = self._codes({"cells": [{"name": "only", "addr": "http://x"}]})
        assert codes == [("TPX605", "cells")]

    def test_promote_without_rollback_warns(self):
        config = {
            "cells": [{"name": "a"}, {"name": "b"}],
            "promote": {"name": "ship", "rollback": False},
        }
        assert ("TPX605", "promote.ship") in self._codes(config)

    def test_non_positive_burn_threshold_warns(self):
        config = {
            "cells": [{"name": "a"}, {"name": "b"}],
            "pipelines": [
                {
                    "spec": {
                        "stages": [
                            {
                                "name": "promote",
                                "kind": "promote",
                                "burn_threshold": 0,
                            }
                        ]
                    }
                }
            ],
        }
        assert ("TPX605", "promote.promote") in self._codes(config)

    def test_clean_two_cell_config_is_silent(self):
        config = {
            "cells": [{"name": "a"}, {"name": "b"}],
            "promote": {"name": "ship", "burn_threshold": 1.0},
        }
        assert self._codes(config) == []


# ---------------------------------------------------------------------------
# serve-pool federation export
# ---------------------------------------------------------------------------


class TestServePoolFederation:
    def _pool(self, **kw):
        from torchx_tpu.serve.pool import ServePool

        app = SimpleNamespace(
            name="svc",
            roles=[SimpleNamespace(name="server", num_replicas=2)],
        )
        return ServePool(runner=None, app=app, **kw)

    def test_summary_unions_replica_prefix_digests(self):
        from torchx_tpu.serve.pool import ReplicaStatus

        pool = self._pool(cell="us-east1")
        pool.router.update(
            [
                ReplicaStatus(
                    replica_id=0,
                    url="http://r0",
                    healthy=True,
                    prefix_summary=("d0", "d1"),
                ),
                ReplicaStatus(
                    replica_id=1,
                    url="http://r1",
                    healthy=True,
                    prefix_summary=("d1", "d2"),
                ),
                # unhealthy replicas do not advertise their cache
                ReplicaStatus(
                    replica_id=2,
                    url="http://r2",
                    healthy=False,
                    prefix_summary=("dead",),
                ),
            ]
        )
        summary = pool.federation_summary()
        assert summary["cell"] == "us-east1"
        assert summary["prefix_digests"] == ["d0", "d1", "d2"]
        assert summary["replicas"] == 2
        # the summary feeds the router's affinity signal directly
        handle = CellHandle(CellSpec(name="us-east1", addr="http://x"))
        handle.update_prefix_digests(summary["prefix_digests"])
        assert handle.prefix_digests == {"d0", "d1", "d2"}

    def test_cell_defaults_from_environment(self, monkeypatch):
        monkeypatch.delenv(settings.ENV_TPX_CELL, raising=False)
        assert self._pool().cell == settings.DEFAULT_CELL_NAME
        monkeypatch.setenv(settings.ENV_TPX_CELL, "eu-west4")
        assert self._pool().cell == "eu-west4"


# ---------------------------------------------------------------------------
# the deterministic two-cell sim scenario
# ---------------------------------------------------------------------------


class TestFederationSim:
    def _run(self, tmp_path, tag, seed=11):
        from torchx_tpu.federation.sim import FederationSimHarness
        from torchx_tpu.sim.scenarios import get_scenario

        scenario = get_scenario("federation-two-cell")
        harness = FederationSimHarness(
            scenario, seed=seed, state_dir=str(tmp_path / tag)
        )
        return harness.run()

    def test_drain_mid_trace_zero_drops(self, tmp_path):
        report = self._run(tmp_path, "a")
        assert report.stats["requests"] > 0
        assert report.stats["dropped"] == 0
        assert report.stats["spillovers"] > 0
        # both cells served: the drained cell before/after, the survivor
        # throughout
        assert set(report.stats["per_cell"]) == {"eu-west4", "us-east1"}
        assert all(v > 0 for v in report.stats["per_cell"].values())
        # failover p99 is bounded: degraded, not collapsed
        assert report.stats["ttft_p99_during_s"] <= 1.0

    def test_same_seed_is_byte_identical(self, tmp_path):
        r1 = self._run(tmp_path, "a")
        r2 = self._run(tmp_path, "b")
        assert r1.journal_sha256 == r2.journal_sha256
        assert r1.stats == r2.stats

    def test_different_seed_diverges(self, tmp_path):
        r1 = self._run(tmp_path, "a", seed=11)
        r2 = self._run(tmp_path, "b", seed=12)
        assert r1.journal_sha256 != r2.journal_sha256
