"""The ``tpx tune`` autotuner: space enumeration, the prune funnel, the
resumable journal, calibration persistence, the plan artifact, and the
submit-gate pin (TPX706/707).

Measured trials use a stub ``measure_cmd`` (a tiny script speaking the
stdin-spec / ``TUNE_METRICS``-line protocol), so the funnel tests spend
zero device seconds; the real subprocess entrypoints get their own
focused tests (``probe_fits``, ``tpx tune --help`` jax-freeness).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

import pytest

from torchx_tpu import settings
from torchx_tpu.analyze import analyze
from torchx_tpu.analyze.explain import deep_preflight
from torchx_tpu.components import dist
from torchx_tpu.tune.artifact import (
    ArtifactError,
    PlanArtifact,
    load_artifact,
)
from torchx_tpu.tune.calibrate import CalibrationTable, generation_key
from torchx_tpu.tune.driver import (
    TuneError,
    _last_json,
    role_for_candidate,
    run_tune,
)
from torchx_tpu.tune.journal import TuneJournal
from torchx_tpu.tune.space import (
    BUILTIN_SPACES,
    Candidate,
    SearchSpace,
    tiny_smoke_space,
)


@pytest.fixture(autouse=True)
def _isolated_tune_state(tmp_path, monkeypatch):
    """Every test gets its own tune dir (journal + calibration table)
    and no inherited artifact pin."""
    monkeypatch.setenv(settings.ENV_TPX_TUNE_DIR, str(tmp_path / "tunestate"))
    monkeypatch.delenv(settings.ENV_TPX_PLAN_ARTIFACT, raising=False)


def stub_measure(tmp_path) -> tuple[list[str], str]:
    """A measure_cmd stub: logs each call, optionally fails one policy
    (``$STUB_FAIL_POLICY``), reports dots as 2x faster than full."""
    log = str(tmp_path / "stub_calls.log")
    script = tmp_path / "stub_measure.py"
    script.write_text(
        textwrap.dedent(
            """
            import json, os, sys

            spec = json.load(sys.stdin)
            policy = spec["candidate"]["remat_policy"]
            with open(os.environ["STUB_LOG"], "a") as f:
                f.write(policy + "\\n")
            if os.environ.get("STUB_FAIL_POLICY") == policy:
                sys.exit(1)
            tok = 200.0 if policy == "dots" else 100.0
            out = {"step_time_s": 0.5, "tokens_per_sec_per_chip": tok}
            print("TUNE_METRICS " + json.dumps(out))
            """
        )
    )
    return [sys.executable, str(script)], log


def stub_calls(log: str) -> list[str]:
    try:
        with open(log) as f:
            return f.read().split()
    except OSError:
        return []


# ---------------------------------------------------------------------------
# search space
# ---------------------------------------------------------------------------


class TestSearchSpace:
    def test_enumeration_is_deterministic(self):
        a, b = tiny_smoke_space(), tiny_smoke_space()
        assert [c.cid for c in a.candidates()] == [
            c.cid for c in b.candidates()
        ]
        assert a.digest() == b.digest()
        assert len(a.candidates()) == 4

    def test_digest_tracks_content(self):
        base = tiny_smoke_space()
        widened = SearchSpace.from_dict(
            {**base.to_dict(), "batches": [8, 16]}
        )
        assert widened.digest() != base.digest()
        # a faithful round-trip keeps the digest
        assert SearchSpace.from_dict(base.to_dict()).digest() == base.digest()

    def test_candidate_roundtrip(self):
        c = tiny_smoke_space().candidates()[0]
        assert Candidate.from_dict(c.to_dict()) == c
        assert c.cid == "tiny|fsdp=-1|full|b8|s128|pf2|i8=none"

    def test_validation(self):
        with pytest.raises(ValueError, match="int8_scope"):
            SearchSpace(
                config="tiny",
                mesh_specs=("fsdp=-1",),
                remat_policies=("full",),
                batches=(8,),
                seq=128,
                int8_scopes=("int4",),
            )
        with pytest.raises(ValueError, match="empty axis"):
            SearchSpace(
                config="tiny",
                mesh_specs=(),
                remat_policies=("full",),
                batches=(8,),
                seq=128,
            )

    def test_builtin_spaces_enumerate(self):
        for name, factory in BUILTIN_SPACES.items():
            assert factory().candidates(), name


# ---------------------------------------------------------------------------
# the funnel (stubbed measure; aot off = zero subprocesses)
# ---------------------------------------------------------------------------


class TestFunnel:
    def test_static_prune_kills_unresolvable_meshes(self, tmp_path):
        res = run_tune(
            tiny_smoke_space(),
            devices=8,
            out_dir=str(tmp_path / "run"),
            aot=False,
            measure=False,
        )
        pruned = [t for t in res.trials if t.status == "pruned_static"]
        # tp=3 cannot resolve on 8 devices: both its policies die static
        assert len(pruned) == 2
        assert {t.code for t in pruned} == {"TPX703"}
        assert res.report["prune_rate"] == 0.5
        assert res.report["pruned_by_code"] == {"TPX703": 2}
        assert res.report["device_seconds_pruning"] == 0.0
        # measure=False still selects the top-ranked survivor + artifact
        assert res.winner is not None and res.winner.status == "selected"
        art = load_artifact(res.artifact_path)
        assert art.candidate["config"] == "tiny"

    def test_indivisible_batch_pruned_before_any_device_work(self, tmp_path):
        space = SearchSpace(
            config="tiny",
            mesh_specs=("fsdp=-1",),
            remat_policies=("full",),
            batches=(6, 8),  # 6 does not shard over 8 data shards
            seq=128,
        )
        res = run_tune(
            space,
            devices=8,
            out_dir=str(tmp_path / "run"),
            aot=False,
            measure=False,
        )
        by_status = {t.candidate.batch: t for t in res.trials}
        assert by_status[6].status == "pruned_static"
        assert by_status[6].code == "SHARD_INDIVISIBLE"
        assert res.winner.candidate.batch == 8

    def test_everything_pruned_raises(self, tmp_path):
        space = SearchSpace(
            config="tiny",
            mesh_specs=("tp=3",),
            remat_policies=("full",),
            batches=(8,),
            seq=128,
        )
        with pytest.raises(TuneError, match="killed every candidate"):
            run_tune(
                space,
                devices=8,
                out_dir=str(tmp_path / "run"),
                aot=False,
                measure=False,
            )

    def test_measured_winner_and_journal(self, tmp_path):
        cmd, log = stub_measure(tmp_path)
        out_dir = str(tmp_path / "run")
        res = run_tune(
            tiny_smoke_space(),
            devices=8,
            out_dir=out_dir,
            aot=False,
            top_k=2,
            measure_cmd=cmd,
            subprocess_env={"STUB_LOG": log},
        )
        assert res.report["measured"] == 2
        # the stub reports dots 2x faster; the winner must follow
        assert res.winner.candidate.remat_policy == "dots"
        assert res.winner.metrics["tokens_per_sec_per_chip"] == 200.0
        events = TuneJournal(os.path.join(out_dir, "journal.jsonl")).replay()
        kinds = [e["event"] for e in events]
        assert kinds.count("pruned") == 2
        assert kinds.count("measured") == 2
        assert "winner" in kinds
        # every pruned event names the rule that killed the candidate
        assert all(
            e["code"] == "TPX703"
            for e in events
            if e["event"] == "pruned"
        )


# ---------------------------------------------------------------------------
# resume + calibration persistence
# ---------------------------------------------------------------------------


class TestResume:
    def test_killed_run_resumes_replaying_measured_trials(self, tmp_path):
        cmd, log = stub_measure(tmp_path)
        out_dir = str(tmp_path / "run")
        # run 1: "dots" dies mid-trial (simulated kill: no measured event)
        res1 = run_tune(
            tiny_smoke_space(),
            devices=8,
            out_dir=out_dir,
            aot=False,
            top_k=2,
            measure_cmd=cmd,
            subprocess_env={"STUB_LOG": log, "STUB_FAIL_POLICY": "dots"},
        )
        assert {t.status for t in res1.trials if t.candidate.remat_policy == "dots"} & {
            "measure_failed"
        }
        assert res1.winner.candidate.remat_policy == "full"
        assert stub_calls(log) == ["full", "dots"]
        # a kill mid-append leaves at most one torn line: tolerated
        with open(os.path.join(out_dir, "journal.jsonl"), "a") as f:
            f.write('{"event": "measu')
        # run 2: the completed trial replays; only the remainder re-runs
        res2 = run_tune(
            tiny_smoke_space(),
            devices=8,
            out_dir=out_dir,
            aot=False,
            top_k=2,
            measure_cmd=cmd,
            subprocess_env={"STUB_LOG": log},
        )
        assert stub_calls(log) == ["full", "dots", "dots"]  # full NOT re-run
        by_policy = {
            t.candidate.remat_policy: t
            for t in res2.trials
            if t.status == "measured"
        }
        assert by_policy["full"].replayed is True
        assert by_policy["dots"].replayed is False
        assert res2.winner.candidate.remat_policy == "dots"

    def test_journal_of_a_different_space_is_reset(self, tmp_path):
        cmd, log = stub_measure(tmp_path)
        out_dir = str(tmp_path / "run")
        run_tune(
            tiny_smoke_space(),
            devices=8,
            out_dir=out_dir,
            aot=False,
            top_k=1,
            measure_cmd=cmd,
            subprocess_env={"STUB_LOG": log},
        )
        other = SearchSpace.from_dict(
            {**tiny_smoke_space().to_dict(), "batches": [16]}
        )
        run_tune(
            other,
            devices=8,
            out_dir=out_dir,
            aot=False,
            top_k=1,
            measure_cmd=cmd,
            subprocess_env={"STUB_LOG": log},
        )
        journal = TuneJournal(os.path.join(out_dir, "journal.jsonl"))
        assert journal.space_digest() == other.digest()
        # a resumed journal never mixes spaces: 16 re-measured fresh
        assert all(
            e["cid"].startswith("tiny|") and "|b16|" in e["cid"]
            for e in journal.events("measured")
        )

    def test_calibration_survives_restart_and_error_shrinks(self, tmp_path):
        cmd, log = stub_measure(tmp_path)
        res1 = run_tune(
            tiny_smoke_space(),
            devices=8,
            out_dir=str(tmp_path / "r1"),
            aot=False,
            top_k=1,
            measure_cmd=cmd,
            subprocess_env={"STUB_LOG": log},
        )
        obs = res1.calibration["step_time"]
        assert obs["err_after"] < obs["err_before"]
        # the table is persisted under $TPX_TUNE_DIR: a FRESH load (new
        # process restart equivalent) sees the folded observation
        table = CalibrationTable.load_default()
        assert table.scales_for("").samples == 1
        assert table.scales_for("").step_time_scale != 1.0
        # a second run folds on top of the persisted scales
        res2 = run_tune(
            tiny_smoke_space(),
            devices=8,
            out_dir=str(tmp_path / "r2"),
            aot=False,
            top_k=1,
            measure_cmd=cmd,
            subprocess_env={"STUB_LOG": log},
        )
        assert res2.calibration["step_time"]["err_before"] < obs["err_before"]
        assert CalibrationTable.load_default().scales_for("").samples == 2


class TestCalibrationTable:
    def test_observe_halves_the_error(self, tmp_path):
        table = CalibrationTable(str(tmp_path / "cal.json"))
        out = table.observe(
            "v5e", predicted_step_s=1.0, measured_step_s=2.0
        )
        st = out["step_time"]
        assert st["err_before"] == pytest.approx(0.5)
        assert st["err_after"] == pytest.approx(0.25)
        assert table.scales_for("v5e").step_time_scale == pytest.approx(1.5)

    def test_save_load_roundtrip(self, tmp_path):
        path = str(tmp_path / "cal.json")
        table = CalibrationTable(path)
        table.observe("v5e", predicted_step_s=1.0, measured_step_s=2.0)
        table.save()
        again = CalibrationTable.load(path)
        assert again.scales_for("v5e").to_dict() == table.scales_for(
            "v5e"
        ).to_dict()

    def test_generation_key_normalization(self):
        assert generation_key("TPU v5e") == "v5e"
        assert generation_key("V4") == "v4"
        assert generation_key("") == "cpu-sim"
        assert generation_key("some CPU host") == "cpu-sim"

    def test_bad_alpha_rejected(self, tmp_path):
        table = CalibrationTable(str(tmp_path / "cal.json"))
        with pytest.raises(ValueError, match="alpha"):
            table.observe(
                "v5e", predicted_step_s=1.0, measured_step_s=2.0, alpha=1.0
            )

    def test_observe_overlap_ema_and_clamp(self, tmp_path):
        table = CalibrationTable(str(tmp_path / "cal.json"))
        out = table.observe_overlap("v5e", measured_overlap_frac=0.6)
        assert out["overlap"]["after"] == pytest.approx(0.3)  # 0 + 0.5*0.6
        out = table.observe_overlap("v5e", measured_overlap_frac=0.6)
        assert out["overlap"]["after"] == pytest.approx(0.45)
        # runaway 1.0 never makes collectives free
        for _ in range(20):
            out = table.observe_overlap("v5e", measured_overlap_frac=5.0)
        assert table.scales_for("v5e").overlap_frac <= 0.95
        with pytest.raises(ValueError, match="alpha"):
            table.observe_overlap("v5e", measured_overlap_frac=0.5, alpha=0.0)

    def test_overlap_frac_survives_other_observes(self, tmp_path):
        path = str(tmp_path / "cal.json")
        table = CalibrationTable(path)
        table.observe_overlap("v5e", measured_overlap_frac=0.8)
        frac = table.scales_for("v5e").overlap_frac
        assert frac > 0
        table.observe("v5e", predicted_step_s=1.0, measured_step_s=2.0)
        table.observe_collectives(
            "v5e", predicted_collective_s=1.0, measured_collective_s=2.0
        )
        assert table.scales_for("v5e").overlap_frac == pytest.approx(frac)
        table.save()
        assert CalibrationTable.load(path).scales_for(
            "v5e"
        ).overlap_frac == pytest.approx(frac)

    def test_rank_discounts_overlapped_collectives(self, tmp_path):
        from torchx_tpu.analyze.plan import plan_from_role
        from torchx_tpu.components import dist
        from torchx_tpu.tune.rank import predicted_step_cost

        app = dist.spmd(
            "--config", "llama3_1b", "--mesh", "dp=2,fsdp=4",
            m="torchx_tpu.examples.train_llama", j="1x8",
        )
        plan = plan_from_role(app.roles[0])
        assert plan is not None
        base = predicted_step_cost(plan, generation="v5e")
        assert base.collective_s > 0
        table = CalibrationTable(str(tmp_path / "cal.json"))
        table.observe_overlap("v5e", measured_overlap_frac=0.95, alpha=0.9)
        cal = table.scales_for("v5e")
        discounted = predicted_step_cost(
            plan, generation="v5e", calibration=cal
        )
        # the StepCost still reports the full modeled collective time;
        # only the rank key charges the exposed share
        assert discounted.collective_s == pytest.approx(base.collective_s)
        assert discounted.step_s < base.step_s
        # identity calibration (overlap never observed) is bit-identical
        from torchx_tpu.tune.calibrate import CalibrationScales

        assert predicted_step_cost(
            plan, generation="v5e", calibration=CalibrationScales()
        ).step_s == base.step_s


# ---------------------------------------------------------------------------
# artifact: digest, tamper, diff, and the submit-gate pin
# ---------------------------------------------------------------------------


def _plan_for(app):
    plan, _diags = deep_preflight(app.roles[0])
    assert plan is not None
    return plan


def tuned_app(batch: str = "8", policy: str = "full"):
    return dist.spmd(
        "--config",
        "tiny",
        "--mesh",
        "fsdp=-1",
        "--batch",
        batch,
        "--seq",
        "128",
        "--remat-policy",
        policy,
        m="torchx_tpu.examples.train_llama",
        j="1x8",
    )


class TestArtifact:
    def test_digest_roundtrip_and_tamper_detection(self, tmp_path):
        art = PlanArtifact(
            space={}, candidate={"config": "tiny"},
            plan=_plan_for(tuned_app()).to_dict(),
        )
        path = art.save(str(tmp_path / "art.json"))
        assert load_artifact(path).digest == art.digest
        raw = json.load(open(path))
        raw["plan"]["batch"] = 4  # hand-edit: digest no longer matches
        json.dump(raw, open(path, "w"))
        with pytest.raises(ArtifactError, match="digest mismatch"):
            load_artifact(path)

    def test_unreadable_artifact(self, tmp_path):
        with pytest.raises(ArtifactError, match="cannot read"):
            load_artifact(str(tmp_path / "missing.json"))
        bad = tmp_path / "bad.json"
        bad.write_text("not json")
        with pytest.raises(ArtifactError):
            load_artifact(str(bad))

    def test_diff_plan(self):
        plan = _plan_for(tuned_app()).to_dict()
        art = PlanArtifact(space={}, candidate={}, plan=plan)
        assert art.diff_plan(plan) == []
        moved = dict(plan, batch=4, remat_policy="dots")
        diffs = art.diff_plan(moved)
        assert sorted(d.split(":")[0] for d in diffs) == [
            "batch",
            "remat_policy",
        ]
        # trivial (size-1) mesh axes never diff: wildcard resolution noise
        relaxed = dict(plan, mesh={
            k: v for k, v in plan["mesh"].items() if int(v) != 1
        })
        assert art.diff_plan(relaxed) == []


class TestSubmitGatePin:
    def _pin(self, tmp_path, monkeypatch, plan_app=None):
        art = PlanArtifact(
            space={}, candidate={"cid": "test"},
            plan=_plan_for(plan_app or tuned_app()).to_dict(),
        )
        path = art.save(str(tmp_path / "pin.json"))
        monkeypatch.setenv(settings.ENV_TPX_PLAN_ARTIFACT, path)
        return path

    def test_matching_plan_passes(self, tmp_path, monkeypatch):
        self._pin(tmp_path, monkeypatch)
        codes = [d.code for d in analyze(tuned_app()).diagnostics]
        assert "TPX706" not in codes and "TPX707" not in codes

    def test_diverging_plan_is_tpx706_error(self, tmp_path, monkeypatch):
        self._pin(tmp_path, monkeypatch)
        report = analyze(tuned_app(batch="4", policy="dots"))
        tpx706 = [d for d in report.diagnostics if d.code == "TPX706"]
        assert len(tpx706) == 1
        assert tpx706[0].severity.value == "error"
        assert "batch: artifact=8 plan=4" in tpx706[0].message
        assert "remat_policy" in tpx706[0].message

    def test_corrupt_pin_is_tpx707_error(self, tmp_path, monkeypatch):
        path = self._pin(tmp_path, monkeypatch)
        with open(path, "a") as f:
            f.write("garbage")
        report = analyze(tuned_app())
        tpx707 = [d for d in report.diagnostics if d.code == "TPX707"]
        assert len(tpx707) == 1
        assert tpx707[0].severity.value == "error"

    def test_no_pin_no_gate(self):
        codes = [d.code for d in analyze(tuned_app()).diagnostics]
        assert "TPX706" not in codes and "TPX707" not in codes

    def test_tune_emitted_artifact_is_accepted_by_the_gate(
        self, tmp_path, monkeypatch
    ):
        res = run_tune(
            tiny_smoke_space(),
            devices=8,
            out_dir=str(tmp_path / "run"),
            aot=False,
            measure=False,
        )
        monkeypatch.setenv(settings.ENV_TPX_PLAN_ARTIFACT, res.artifact_path)
        win = res.winner.candidate
        app = tuned_app(batch=str(win.batch), policy=win.remat_policy)
        codes = [d.code for d in analyze(app).diagnostics]
        assert "TPX706" not in codes and "TPX707" not in codes


# ---------------------------------------------------------------------------
# subprocess entrypoints
# ---------------------------------------------------------------------------


class TestProbeFits:
    def test_probe_fits_and_refuses(self):
        from torchx_tpu.parallel.aot_fit import probe_fits

        base = {
            "config": "tiny",
            "mesh_spec": "fsdp=-1",
            "batch": 8,
            "seq": 128,
            "remat_policy": "full",
            "int8_scope": "none",
        }
        fits, starved, broken = probe_fits(
            [base, {**base, "hbm_bytes": 1}, {**base, "mesh_spec": "tp=3"}]
        )
        assert fits["fits"] is True and fits["peak_bytes"] > 0
        assert starved["fits"] is False
        assert "error" in broken  # unresolvable mesh: advisory error


class TestDriverPlumbing:
    def test_last_json_prefix_and_noise(self):
        noisy = "warn: blah\nTUNE_METRICS {\"a\": 1}\ntrailing garbage\n"
        assert _last_json(noisy, prefix="TUNE_METRICS ") == {"a": 1}
        assert _last_json(noisy) is None  # without the prefix: no bare JSON
        assert _last_json("x\n{broken\n[1, 2]\n") == [1, 2]

    def test_role_for_candidate_shape(self):
        c = tiny_smoke_space().candidates()[0]
        role = role_for_candidate(c, devices=8)
        assert role.args[:2] == ["-m", "torchx_tpu.examples.train_llama"]
        assert "--int8" not in role.args
        assert "host_platform_device_count=8" in role.env["XLA_FLAGS"]

    def test_devices_validated(self, tmp_path):
        with pytest.raises(TuneError, match="devices"):
            run_tune(
                tiny_smoke_space(),
                devices=0,
                out_dir=str(tmp_path / "run"),
            )


@pytest.mark.integ
class TestCliLayering:
    def test_tune_help_never_imports_jax(self):
        code = (
            "import sys\n"
            "from torchx_tpu.cli.main import main\n"
            "try:\n"
            "    main(['tune', '--help'])\n"
            "except SystemExit:\n"
            "    pass\n"
            "assert 'jax' not in sys.modules, 'tune --help imported jax'\n"
            "print('LAYERING_OK')\n"
        )
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert proc.returncode == 0, proc.stderr
        assert "LAYERING_OK" in proc.stdout
