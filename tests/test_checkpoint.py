"""Checkpoint/resume: sharded save/restore + preemption-recovery loop."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchx_tpu.examples.train_llama import (
    init_state,
    make_optimizer,
    train,
)
from torchx_tpu.models import llama
from torchx_tpu.parallel.checkpoint import Checkpointer
from torchx_tpu.parallel.mesh import MeshConfig, make_mesh


class TestCheckpointer:
    def test_save_restore_sharded_state(self, tmp_path):
        cfg = llama.llama_tiny()
        mesh = make_mesh(MeshConfig(dp=2, fsdp=2, tp=2, sp=1))
        opt = make_optimizer(warmup=1)
        state = init_state(cfg, mesh, opt)
        ckpt = Checkpointer(str(tmp_path))
        assert ckpt.save(5, state)
        assert ckpt.latest_step() == 5
        restored = ckpt.restore(5, state)
        for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # restored arrays carry the same shardings
        assert (
            jax.tree.leaves(restored)[1].sharding.spec
            == jax.tree.leaves(state)[1].sharding.spec
        )
        ckpt.close()

    def test_restore_latest_empty(self, tmp_path):
        ckpt = Checkpointer(str(tmp_path))
        step, state = ckpt.restore_latest({"x": jnp.zeros(3)})
        assert step is None and state is None
        ckpt.close()

    def test_max_to_keep(self, tmp_path):
        ckpt = Checkpointer(str(tmp_path), max_to_keep=2)
        state = {"x": jnp.arange(4.0)}
        for s in (1, 2, 3):
            ckpt.save(s, state)
        assert ckpt.latest_step() == 3
        ckpt.close()


class TestPreemptionRecovery:
    def test_train_resumes_from_checkpoint(self, tmp_path):
        """The BASELINE config-4 loop: run, 'die', relaunch, resume."""
        cfg = llama.llama_tiny()
        mc = MeshConfig(dp=1, fsdp=-1, tp=1, sp=1)
        # first run: 6 steps, checkpoint every 2
        m1 = train(
            cfg, mc, batch=8, seq=32, steps=6,
            ckpt_dir=str(tmp_path), ckpt_every=2, warmup=2, lr=1e-2,
        )
        assert m1["final_step"] == 6
        assert m1["resumed_from_step"] == 0
        # "preempted" relaunch: must resume from the saved step, not 0
        m2 = train(
            cfg, mc, batch=8, seq=32, steps=4,
            ckpt_dir=str(tmp_path), ckpt_every=2, warmup=2, lr=1e-2,
        )
        assert m2["resumed_from_step"] == 6
        assert m2["final_step"] > 6
        # training continued descending from where it left off
        assert m2["loss"] <= m1["loss"] + 0.1


def test_async_save_overlaps_and_restores(tmp_path):
    """Async checkpointing (default): save() returns immediately, wait()
    makes the checkpoint durable, restore round-trips the state."""
    import jax.numpy as jnp

    from torchx_tpu.parallel.checkpoint import Checkpointer

    state = {"w": jnp.arange(16.0).reshape(4, 4), "step": jnp.int32(7)}
    ckpt = Checkpointer(str(tmp_path), async_save=True)
    try:
        assert ckpt.save(1, state)
        # a second save while the first may still be in flight must not
        # corrupt anything (orbax serializes internally)
        state2 = {"w": state["w"] * 2, "step": jnp.int32(8)}
        ckpt.save(2, state2, force=True)
        ckpt.wait()
        assert ckpt.latest_step() == 2
        step, restored = ckpt.restore_latest(state2)
        assert step == 2
        assert float(restored["w"][0, 1]) == 2.0
    finally:
        ckpt.close()


def test_sync_mode_still_supported(tmp_path):
    import jax.numpy as jnp

    from torchx_tpu.parallel.checkpoint import Checkpointer

    ckpt = Checkpointer(str(tmp_path), async_save=False)
    try:
        ckpt.save(1, {"x": jnp.ones(3)})
        assert ckpt.latest_step() == 1
    finally:
        ckpt.close()


class TestRobustness:
    """Edge cases a real preemption leaves behind: partial/corrupt
    checkpoint dirs must not take down the resume path."""

    def _state(self):
        import jax.numpy as jnp

        return {"w": jnp.arange(8, dtype=jnp.float32), "step": jnp.int32(0)}

    def test_restore_falls_back_past_corrupt_latest(self, tmp_path):
        """A preemption mid-write leaves the newest step corrupt; resume
        must fall back to the previous intact step, not die."""
        import jax.numpy as jnp

        from torchx_tpu.parallel.checkpoint import Checkpointer

        ckpt = Checkpointer(str(tmp_path), async_save=False)
        ckpt.save(1, {"w": jnp.full(8, 1.0), "step": jnp.int32(1)})
        ckpt.save(2, {"w": jnp.full(8, 2.0), "step": jnp.int32(2)})
        ckpt.wait()
        ckpt.close()
        # gut step 2's payload (orbax dir "2" or pickle "step_2.pkl")
        corrupted = 0
        for p in tmp_path.iterdir():
            if p.name == "2" or p.name.startswith("step_2"):
                if p.is_file():
                    p.write_bytes(b"truncated")
                    corrupted += 1
                else:
                    for child in p.rglob("*"):
                        if child.is_file():
                            child.write_bytes(b"truncated")
                            corrupted += 1
        assert corrupted, "corruption target not found: layout changed?"
        ckpt2 = Checkpointer(str(tmp_path), async_save=False)
        step, restored = ckpt2.restore_latest(self._state())
        # fell back to the intact step 1 with its REAL data
        assert step == 1
        assert (jax.device_get(restored["w"]) == 1.0).all()
        # the corrupt step was quarantined, so training that resumes from
        # step 1 can SAVE step 2 again (no StepAlreadyExistsError crash
        # loop under gang-restart retries)
        assert ckpt2.save(2, {"w": jnp.full(8, 2.5), "step": jnp.int32(2)})
        ckpt2.wait()
        ckpt2.close()
        ckpt3 = Checkpointer(str(tmp_path))
        step3, restored3 = ckpt3.restore_latest(self._state())
        ckpt3.close()
        assert step3 == 2
        assert (jax.device_get(restored3["w"]) == 2.5).all()
        # the quarantined dir is kept aside as evidence
        assert any(".corrupt" in p.name for p in tmp_path.iterdir())

    def test_all_corrupt_raises_instead_of_reinit(self, tmp_path):
        import pytest as _pytest

        from torchx_tpu.parallel.checkpoint import Checkpointer

        ckpt = Checkpointer(str(tmp_path), async_save=False)
        ckpt.save(1, self._state())
        ckpt.wait()
        ckpt.close()
        for p in tmp_path.rglob("*"):
            if p.is_file():
                p.write_bytes(b"junk")
        ckpt2 = Checkpointer(str(tmp_path), async_save=False)
        with _pytest.raises(RuntimeError, match="failed to restore"):
            ckpt2.restore_latest(self._state())
        ckpt2.close()

    def test_empty_directory_roundtrip(self, tmp_path):
        from torchx_tpu.parallel.checkpoint import Checkpointer

        ckpt = Checkpointer(str(tmp_path / "fresh"))
        step, restored = ckpt.restore_latest(self._state())
        assert restored is None and not step
        ckpt.close()

    def test_save_interval_respected(self, tmp_path):
        from torchx_tpu.parallel.checkpoint import Checkpointer

        state = self._state()
        ckpt = Checkpointer(str(tmp_path), save_interval_steps=5, async_save=False)
        for s in range(1, 12):
            ckpt.save(s, state)
        ckpt.wait()
        ckpt.close()
        ckpt2 = Checkpointer(str(tmp_path))
        step, restored = ckpt2.restore_latest(self._state())
        ckpt2.close()
        # only interval steps persisted; latest is the last multiple of 5
        assert step == 10


def _pickle_ckpt(path, **kw):
    """A Checkpointer forced onto the pickle fallback (the backend that
    owns the snapshot-then-write machinery) even when orbax is present."""
    from torchx_tpu.parallel.checkpoint import Checkpointer

    ckpt = Checkpointer(str(path), **kw)
    if ckpt._mgr is not None:
        ckpt._mgr.close()
        ckpt._mgr = None
        ckpt._ocp = None
    return ckpt


class TestSnapshotThenWrite:
    """Async pickle checkpointing: device→host snapshot fenced in save(),
    serialization/digest/manifest on a background thread."""

    def _state(self, v=1.0):
        import jax.numpy as jnp

        return {"w": jnp.full(8, v), "step": jnp.int32(int(v))}

    def test_background_write_completes_at_wait(self, tmp_path, monkeypatch):
        import threading

        from torchx_tpu.parallel import checkpoint as ckpt_mod

        gate = threading.Event()
        real_write = ckpt_mod.Checkpointer._pickle_write

        def gated_write(self, step, host_state):
            gate.wait(timeout=30)
            real_write(self, step, host_state)

        monkeypatch.setattr(ckpt_mod.Checkpointer, "_pickle_write", gated_write)
        ckpt = _pickle_ckpt(tmp_path, async_save=True)
        assert ckpt.save(1, self._state())
        # save() returned while the writer is gated: nothing on disk yet,
        # which is the point — the step loop is not stalled by the write
        assert not any(p.name.startswith("step_") for p in tmp_path.iterdir())
        gate.set()
        ckpt.wait()
        assert (tmp_path / "step_1.pkl").exists()
        # digest + manifest were finalized by the background thread
        assert ckpt.verify_step(1) is True
        step, restored = ckpt.restore_latest(self._state())
        assert step == 1
        assert (jax.device_get(restored["w"]) == 1.0).all()
        ckpt.close()

    def test_snapshot_is_fenced_before_mutation(self, tmp_path, monkeypatch):
        """The state captured is the state AT save() time, even if the
        caller overwrites its buffers while the write is in flight."""
        import threading

        import numpy as _np

        from torchx_tpu.parallel import checkpoint as ckpt_mod

        gate = threading.Event()
        real_write = ckpt_mod.Checkpointer._pickle_write

        def gated_write(self, step, host_state):
            gate.wait(timeout=30)
            real_write(self, step, host_state)

        monkeypatch.setattr(ckpt_mod.Checkpointer, "_pickle_write", gated_write)
        ckpt = _pickle_ckpt(tmp_path, async_save=True)
        state = {"w": _np.full(8, 3.0)}  # host buffer: mutable in place
        ckpt.save(1, state)
        state["w"][:] = -1.0  # trainer reuses the buffer mid-write
        gate.set()
        ckpt.wait()
        _, restored = ckpt.restore_latest({"w": _np.zeros(8)})
        assert (restored["w"] == 3.0).all()
        ckpt.close()

    def test_crash_mid_background_write_falls_back(self, tmp_path, monkeypatch):
        """Kill mid-background-write: restore_latest falls back to the
        previous verified step and the MANIFEST is never torn."""
        import json as _json

        from torchx_tpu import settings
        from torchx_tpu.parallel import checkpoint as ckpt_mod

        ckpt = _pickle_ckpt(tmp_path, async_save=True)
        ckpt.save(1, self._state(1.0))
        ckpt.wait()

        real_dump = ckpt_mod.pickle.dump

        def dying_dump(obj, f, *a, **kw):
            f.write(b"\x80\x04partial")  # torn bytes land in the .tmp file
            raise OSError("simulated kill mid-write")

        monkeypatch.setattr(ckpt_mod.pickle, "dump", dying_dump)
        ckpt.save(2, self._state(2.0))
        with pytest.raises(RuntimeError, match="background checkpoint write"):
            ckpt.wait()
        monkeypatch.setattr(ckpt_mod.pickle, "dump", real_dump)
        # no torn step file escaped the tmp+rename protocol
        assert not (tmp_path / "step_2.pkl").exists()
        # the manifest is intact JSON and still points at the verified step
        doc = _json.loads(
            (tmp_path / settings.CHECKPOINT_MANIFEST).read_text()
        )
        assert doc["latest_step"] == 1
        ckpt2 = _pickle_ckpt(tmp_path)
        step, restored = ckpt2.restore_latest(self._state())
        assert step == 1
        assert (jax.device_get(restored["w"]) == 1.0).all()
        ckpt2.close()
        ckpt.close()

    def test_writer_error_also_surfaces_at_next_save(self, tmp_path, monkeypatch):
        from torchx_tpu.parallel import checkpoint as ckpt_mod

        ckpt = _pickle_ckpt(tmp_path, async_save=True)

        def dying_dump(obj, f, *a, **kw):
            raise OSError("disk full")

        monkeypatch.setattr(ckpt_mod.pickle, "dump", dying_dump)
        ckpt.save(1, self._state())
        ckpt._writer.join()  # let the failure land before unpatching
        monkeypatch.undo()
        with pytest.raises(RuntimeError, match="background checkpoint write"):
            ckpt.save(2, self._state())
        # latched error cleared: subsequent saves work again
        assert ckpt.save(3, self._state(3.0))
        ckpt.wait()
        assert ckpt.latest_step() == 3
        ckpt.close()

    def test_back_to_back_saves_serialize(self, tmp_path):
        ckpt = _pickle_ckpt(tmp_path, async_save=True, max_to_keep=10)
        for s in range(1, 6):
            assert ckpt.save(s, self._state(float(s)))
        ckpt.wait()
        assert ckpt.latest_step() == 5
        for s in range(1, 6):
            assert ckpt.verify_step(s) is True
        ckpt.close()
