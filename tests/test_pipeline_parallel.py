"""GPipe-style pipeline parallelism tests (pp mesh axis)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchx_tpu.models import llama
from torchx_tpu.ops.rope import rope_frequencies
from torchx_tpu.parallel.pipeline import make_pp_mesh, pipeline_apply


def mlp_body(x, layer):
    return jnp.tanh(x @ layer["w"] + layer["b"])


def mlp_params(L, d, key):
    k1, k2 = jax.random.split(key)
    return {
        "w": jax.random.normal(k1, (L, d, d)) * 0.3,
        "b": jax.random.normal(k2, (L, d)) * 0.1,
    }


def sequential(body, params, x):
    def step(h, layer):
        return body(h, layer), None

    out, _ = jax.lax.scan(step, x, params)
    return out


class TestPipelineApply:
    def test_forward_matches_sequential(self):
        params = mlp_params(8, 16, jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (16, 16))
        mesh = make_pp_mesh(4)
        out = jax.jit(
            lambda p, x: pipeline_apply(mlp_body, p, x, mesh, n_microbatches=4)
        )(params, x)
        np.testing.assert_allclose(out, sequential(mlp_body, params, x), atol=1e-6)

    def test_gradients_match(self):
        params = mlp_params(4, 8, jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (8, 8))
        mesh = make_pp_mesh(2)
        g_pp = jax.grad(
            lambda p: jnp.sum(pipeline_apply(mlp_body, p, x, mesh, 4) ** 2)
        )(params)
        g_ref = jax.grad(lambda p: jnp.sum(sequential(mlp_body, p, x) ** 2))(params)
        for a, b in zip(jax.tree.leaves(g_pp), jax.tree.leaves(g_ref)):
            np.testing.assert_allclose(a, b, atol=1e-5)

    def test_microbatch_count_one(self):
        # degenerate pipeline: 1 microbatch still correct (pure bubble)
        params = mlp_params(4, 8, jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 8))
        mesh = make_pp_mesh(4)
        out = pipeline_apply(mlp_body, params, x, mesh, n_microbatches=1)
        np.testing.assert_allclose(out, sequential(mlp_body, params, x), atol=1e-6)

    def test_aux_threads_through_pipeline(self):
        """A body returning (x, aux) accumulates aux across stages and
        microbatches, matching the sequential scan exactly (per-layer aux
        linear in the microbatch mean -> microbatch average == batch mean)."""

        def aux_body(x, layer):
            return mlp_body(x, layer), jnp.mean(x)

        params = mlp_params(8, 16, jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (16, 16))
        mesh = make_pp_mesh(4)
        out, aux = jax.jit(
            lambda p, x: pipeline_apply(
                aux_body, p, x, mesh, n_microbatches=4, with_aux=True
            )
        )(params, x)
        np.testing.assert_allclose(out, sequential(mlp_body, params, x), atol=1e-6)

        def seq_step(h, layer):
            h2, aux = aux_body(h, layer)
            return h2, aux

        _, aux_per_layer = jax.lax.scan(seq_step, x, params)
        np.testing.assert_allclose(float(aux), float(aux_per_layer.sum()), rtol=1e-5)

    def test_aux_gradients_flow_through_pipeline(self):
        def aux_body(x, layer):
            return mlp_body(x, layer), jnp.mean(x**2)

        params = mlp_params(4, 8, jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (8, 8))
        mesh = make_pp_mesh(2)

        def pp_loss(p):
            out, aux = pipeline_apply(aux_body, p, x, mesh, 4, with_aux=True)
            return jnp.sum(out**2) + aux

        def seq_loss(p):
            def step(h, layer):
                h2, aux = aux_body(h, layer)
                return h2, aux

            out, aux_per_layer = jax.lax.scan(step, x, p)
            return jnp.sum(out**2) + aux_per_layer.sum()

        g_pp = jax.grad(pp_loss)(params)
        g_ref = jax.grad(seq_loss)(params)
        for a, b in zip(jax.tree.leaves(g_pp), jax.tree.leaves(g_ref)):
            np.testing.assert_allclose(a, b, atol=1e-5)

    def test_validation_errors(self):
        params = mlp_params(6, 8, jax.random.PRNGKey(0))
        x = jnp.zeros((8, 8))
        mesh = make_pp_mesh(4)
        with pytest.raises(ValueError, match="not divisible"):
            pipeline_apply(mlp_body, params, x, mesh, 4)  # 6 layers / 4 stages
        params8 = mlp_params(8, 8, jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="microbatches"):
            pipeline_apply(mlp_body, params8, x, mesh, 3)  # 8 % 3

    def test_full_llama_model_with_pp_mesh(self):
        """pp wired through llama.forward + shard_params on a 3D mesh."""
        from torchx_tpu.parallel.mesh import MeshConfig, make_mesh

        cfg = llama.llama_tiny(n_layers=4)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, 100)
        ref = llama.forward(params, tokens, cfg)
        mesh = make_mesh(MeshConfig(pp=2, dp=1, fsdp=2, tp=2, sp=1))
        sharded = llama.shard_params(params, cfg, mesh)
        out = jax.jit(lambda p, t: llama.forward(p, t, cfg, mesh))(sharded, tokens)
        np.testing.assert_allclose(out, ref, atol=1e-4)

    def test_ring_attention_inside_pp(self):
        """Long-context composition: ring attention over sp NESTED inside a
        pp pipeline stage (shard_map within partial-manual shard_map) —
        forward matches the unsharded dense reference."""
        import dataclasses

        from torchx_tpu.parallel.mesh import MeshConfig, make_mesh

        cfg = llama.llama_tiny(n_layers=4)
        cfg = dataclasses.replace(cfg, use_ring_attention=True)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, 100)
        ref = llama.forward(
            params, tokens, dataclasses.replace(cfg, use_ring_attention=False)
        )
        mesh = make_mesh(MeshConfig(pp=2, dp=1, fsdp=2, tp=1, sp=2))
        sharded = llama.shard_params(params, cfg, mesh)
        out = jax.jit(lambda p, t: llama.forward(p, t, cfg, mesh))(sharded, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)

    def test_ring_attention_inside_pp_trains(self):
        """Grads flow through the nested shard_map (GSPMD fallback) and the
        loss decreases."""
        from torchx_tpu.examples.train_llama import train
        from torchx_tpu.parallel.mesh import MeshConfig

        cfg = llama.llama_tiny(use_ring_attention=True)
        m = train(
            cfg,
            MeshConfig(pp=2, dp=1, fsdp=2, tp=1, sp=2),
            batch=4,
            seq=64,
            steps=5,
            lr=1e-2,
            warmup=1,
        )
        assert m["loss"] < 6.2

    def test_pp_train_step_loss_decreases(self):
        from torchx_tpu.examples.train_llama import train
        from torchx_tpu.parallel.mesh import MeshConfig

        m = train(
            llama.llama_tiny(n_layers=4),
            MeshConfig(pp=2, dp=1, fsdp=2, tp=2, sp=1),
            batch=8,
            seq=32,
            steps=6,
            lr=1e-2,
            warmup=1,
        )
        assert m["loss"] < 6.0

    def test_llama_layers_pipelined(self):
        """The real model body (attention + SwiGLU) through the pipeline."""
        cfg = llama.llama_tiny(n_layers=4)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        x = jax.random.normal(
            jax.random.PRNGKey(1), (8, 16, cfg.dim), dtype=cfg.dtype
        )
        cos, sin = rope_frequencies(cfg.head_dim, 16, cfg.rope_theta)
        body = lambda h, layer: llama._layer(cfg, None, cos, sin, h, layer)[0]  # noqa: E731
        ref = sequential(body, params["layers"], x)
        mesh = make_pp_mesh(2)
        out = jax.jit(
            lambda p, x: pipeline_apply(body, p, x, mesh, n_microbatches=4)
        )(params["layers"], x)
        np.testing.assert_allclose(out, ref, atol=1e-4)
