"""Control-plane resilience tests: failure taxonomy, retry policies, circuit
breakers, deterministic fault injection, the resilient call seam, poll-miss
absorption, and the crash-safe supervision ledger.

The two ISSUE acceptance scenarios live at the bottom: a fault-injected
``supervise`` against the real local scheduler that must complete with ZERO
resubmits (in-seam retries absorb the injected faults), and a SIGKILL of the
supervising client followed by ``Supervisor.resume`` reattaching to the same
live attempt and driving it to SUCCEEDED.
"""

import json
import logging
import os
import random
import subprocess
import sys
import textwrap
import time
from pathlib import Path
from typing import Mapping, Optional

import pytest

from torchx_tpu import settings
from torchx_tpu.obs import metrics as obs_metrics
from torchx_tpu.resilience import (
    BreakerOpenError,
    BreakerState,
    CallPolicy,
    CircuitBreaker,
    FailureKind,
    FailureLedger,
    FaultInjector,
    FaultPlan,
    FaultRule,
    PermanentSchedulerError,
    TransientSchedulerError,
    classify_exception,
    classify_proc,
    classify_text,
    is_transient,
)
from torchx_tpu.resilience import faults as resilience_faults
from torchx_tpu.resilience.call import (
    TIMEOUT_RETURNCODE,
    breaker_for,
    control_plane_timeout,
    resilient_call,
    resilient_cmd,
)
from torchx_tpu.resilience.faults import GARBAGE_PAYLOAD, fault_plan_active
from torchx_tpu.resilience.policy import NON_IDEMPOTENT
from torchx_tpu.runner.api import Runner
from torchx_tpu.runner.events import get_events_logger
from torchx_tpu.runner.events.api import TpxEvent
from torchx_tpu.schedulers.api import DescribeAppResponse, Scheduler
from torchx_tpu.specs.api import (
    AppDef,
    AppDryRunInfo,
    AppState,
    CfgVal,
    FailureClass,
    Role,
    runopts,
)
from torchx_tpu.supervisor import (
    AttemptLedger,
    Supervisor,
    SupervisorPolicy,
    list_sessions,
)

REPO_ROOT = Path(__file__).resolve().parents[1]


def fast_call_policy(**kwargs) -> CallPolicy:
    defaults = dict(backoff_seconds=0.0, jitter=0.0)
    defaults.update(kwargs)
    return CallPolicy(**defaults)


def proc(rc: int, stderr: str = "", stdout: str = "") -> subprocess.CompletedProcess:
    return subprocess.CompletedProcess(
        args=["fake"], returncode=rc, stdout=stdout, stderr=stderr
    )


# -- classifier ------------------------------------------------------------


class TestClassifier:
    @pytest.mark.parametrize(
        "text,kind",
        [
            ("HTTP 429: Too Many Requests", FailureKind.RATE_LIMIT),
            ("Quota exceeded for quota metric 'TPU v5e'", FailureKind.QUOTA),
            ("RESOURCE_EXHAUSTED: out of capacity", FailureKind.QUOTA),
            ("DEADLINE_EXCEEDED while polling operation", FailureKind.TIMEOUT),
            ("request timed out", FailureKind.TIMEOUT),
            ("connection reset by peer", FailureKind.CONNECTION),
            ("Temporary failure in name resolution", FailureKind.CONNECTION),
            ("503 Service Unavailable", FailureKind.UNAVAILABLE),
            ("backend error, try again later", FailureKind.UNAVAILABLE),
            ("ERROR: permission denied on project", FailureKind.AUTH),
            ("401 Unauthorized", FailureKind.AUTH),
            ("404: job does not exist", FailureKind.NOT_FOUND),
            ("INVALID_ARGUMENT: bad topology", FailureKind.INVALID),
            ("segfault in the flux capacitor", FailureKind.UNKNOWN),
            ("", FailureKind.UNKNOWN),
        ],
    )
    def test_text_table(self, text, kind):
        assert classify_text(text) is kind

    def test_throttling_with_403_is_transient_not_auth(self):
        # ordered table: RATE_LIMIT is checked before AUTH so gcloud's
        # "403 rate limit exceeded" wording classifies retryable
        assert classify_text("403 rate limit exceeded for project") is (
            FailureKind.RATE_LIMIT
        )
        assert classify_text("403 Forbidden") is FailureKind.AUTH

    def test_proc_success_is_none(self):
        assert classify_proc(proc(0)) is None

    def test_proc_stderr_and_stdout_fallback(self):
        assert classify_proc(proc(1, stderr="quota exceeded")) is FailureKind.QUOTA
        # some gcloud verbs print the error on stdout
        assert classify_proc(proc(1, stdout="503 unavailable")) is (
            FailureKind.UNAVAILABLE
        )
        assert classify_proc(proc(1, stderr="boom")) is FailureKind.UNKNOWN

    def test_exception_taxonomy_kind_wins(self):
        e = TransientSchedulerError("x", kind=FailureKind.QUOTA)
        assert classify_exception(e) is FailureKind.QUOTA

    def test_exception_structural(self):
        assert classify_exception(
            subprocess.TimeoutExpired(cmd="gcloud", timeout=5)
        ) is FailureKind.TIMEOUT
        assert classify_exception(ConnectionResetError()) is FailureKind.CONNECTION
        assert classify_exception(TimeoutError()) is FailureKind.TIMEOUT

    def test_exception_status_attribute(self):
        class ApiException(Exception):
            status = 429

        assert classify_exception(ApiException("throttled")) is (
            FailureKind.RATE_LIMIT
        )

        class CodeError(Exception):
            code = 503

        assert classify_exception(CodeError()) is FailureKind.UNAVAILABLE

    def test_exception_typename_without_sdk_import(self):
        class NotFound(Exception):
            pass

        class ServiceUnavailable(Exception):
            pass

        assert classify_exception(NotFound("job gone")) is FailureKind.NOT_FOUND
        assert classify_exception(ServiceUnavailable()) is FailureKind.UNAVAILABLE

    def test_exception_message_fallback(self):
        assert classify_exception(
            RuntimeError("connection refused by endpoint")
        ) is FailureKind.CONNECTION
        assert classify_exception(RuntimeError("???")) is FailureKind.UNKNOWN

    def test_transient_split(self):
        for kind in (
            FailureKind.TIMEOUT,
            FailureKind.RATE_LIMIT,
            FailureKind.QUOTA,
            FailureKind.UNAVAILABLE,
            FailureKind.CONNECTION,
        ):
            assert is_transient(kind)
        for kind in (
            FailureKind.AUTH,
            FailureKind.NOT_FOUND,
            FailureKind.INVALID,
            FailureKind.UNKNOWN,
        ):
            assert not is_transient(kind)


# -- CallPolicy ------------------------------------------------------------


class TestCallPolicy:
    def test_defaults(self):
        p = CallPolicy()
        assert p.retries_for(FailureKind.UNAVAILABLE) == 2
        assert p.retries_for(FailureKind.RATE_LIMIT) == 3
        assert p.retries_for(FailureKind.TIMEOUT) == 1

    def test_permanent_kinds_never_retried(self):
        # even an explicit budget for a permanent kind is hard-zeroed
        p = CallPolicy(retries={FailureKind.AUTH: 5})
        assert p.retries_for(FailureKind.AUTH) == 0
        assert p.retries_for(FailureKind.UNKNOWN) == 0

    def test_missing_kind_is_zero(self):
        p = CallPolicy(retries={})
        assert p.retries_for(FailureKind.UNAVAILABLE) == 0

    def test_non_idempotent_policy_retries_nothing(self):
        for kind in FailureKind:
            assert NON_IDEMPOTENT.retries_for(kind) == 0

    def test_backoff_grows_and_caps(self):
        p = CallPolicy(
            backoff_seconds=1.0,
            backoff_factor=2.0,
            backoff_max_seconds=4.0,
            jitter=0.0,
        )
        assert [p.backoff_delay(n) for n in (1, 2, 3, 4)] == [1.0, 2.0, 4.0, 4.0]

    def test_jitter_bounds(self):
        p = CallPolicy(backoff_seconds=10.0, jitter=0.5)
        rng = random.Random(7)
        for _ in range(50):
            assert 5.0 <= p.backoff_delay(1, rng=rng) <= 15.0

    @pytest.mark.parametrize(
        "kwargs",
        [
            dict(timeout=0),
            dict(timeout=-1),
            dict(backoff_seconds=-1),
            dict(backoff_factor=0.5),
            dict(jitter=1.0),
            dict(jitter=-0.1),
            dict(retries={FailureKind.QUOTA: -1}),
        ],
    )
    def test_validation(self, kwargs):
        with pytest.raises(ValueError):
            CallPolicy(**kwargs)

    def test_retry_number_is_one_based(self):
        with pytest.raises(ValueError):
            CallPolicy().backoff_delay(0)


# -- CircuitBreaker --------------------------------------------------------


class FakeClock:
    def __init__(self):
        self.now = 0.0

    def __call__(self) -> float:
        return self.now


class TestCircuitBreaker:
    def make(self, **kwargs):
        clock = FakeClock()
        defaults = dict(trip_after=3, cooldown_seconds=10.0, clock=clock)
        defaults.update(kwargs)
        return CircuitBreaker("test", **defaults), clock

    def test_trips_after_consecutive_failures(self):
        b, _ = self.make()
        for _ in range(2):
            b.record_failure()
        assert b.state is BreakerState.CLOSED
        b.record_failure()
        assert b.state is BreakerState.OPEN
        assert not b.allow()

    def test_success_resets_the_streak(self):
        b, _ = self.make()
        b.record_failure()
        b.record_failure()
        b.record_success()
        b.record_failure()
        b.record_failure()
        assert b.state is BreakerState.CLOSED

    def test_cooldown_decays_to_half_open_and_admits_one_probe(self):
        b, clock = self.make()
        for _ in range(3):
            b.record_failure()
        clock.now = 9.9
        assert not b.allow()
        clock.now = 10.0
        assert b.state is BreakerState.HALF_OPEN
        assert b.allow()  # the probe
        assert not b.allow()  # only one probe at a time

    def test_probe_success_closes(self):
        b, clock = self.make()
        for _ in range(3):
            b.record_failure()
        clock.now = 10.0
        assert b.allow()
        b.record_success()
        assert b.state is BreakerState.CLOSED
        assert b.allow()

    def test_probe_failure_reopens_immediately(self):
        b, clock = self.make()
        for _ in range(3):
            b.record_failure()
        clock.now = 10.0
        assert b.allow()
        b.record_failure()  # one probe failure trips, not trip_after
        assert b.state is BreakerState.OPEN
        assert not b.allow()

    def test_abandoned_probe_does_not_wedge(self):
        # the prober dies without reporting; the cool-down restarted at
        # probe admission, so another probe is admitted one cool-down later
        b, clock = self.make()
        for _ in range(3):
            b.record_failure()
        clock.now = 10.0
        assert b.allow()
        clock.now = 20.0
        assert b.allow()

    def test_validation(self):
        with pytest.raises(ValueError):
            CircuitBreaker("x", trip_after=0)
        with pytest.raises(ValueError):
            CircuitBreaker("x", cooldown_seconds=-1)


class TestFailureLedger:
    def test_note_count_clear(self, tmp_path):
        led = FailureLedger(str(tmp_path / "fails"), threshold=2)
        assert led.failures() == {}
        led.note("a|b", ok=False)
        led.note("a|b", ok=False)
        led.note("c|d", ok=False)
        assert led.failures() == {"a|b": 2, "c|d": 1}
        assert led.tripped() == {"a|b"}
        led.note("a|b", ok=True)  # success clears only that key
        assert led.failures() == {"c|d": 1}
        assert led.tripped() == set()

    def test_success_without_failures_is_noop(self, tmp_path):
        path = tmp_path / "fails"
        led = FailureLedger(str(path), threshold=1)
        led.note("k", ok=True)
        assert not path.exists()

    def test_clear_is_an_append_only_tombstone(self, tmp_path):
        path = tmp_path / "fails"
        led = FailureLedger(str(path), threshold=1)
        led.note("k", ok=False)
        led.note("k", ok=True)
        # the success appended a tombstone; nothing was rewritten away
        assert path.read_text() == "k\nk|clear\n"
        assert led.failures() == {}
        # a failure landing AFTER the tombstone survives it (the rewrite
        # implementation could drop such a line racing the replace)
        led.note("k", ok=False)
        assert led.failures() == {"k": 1}
        assert led.tripped() == {"k"}

    def test_tombstone_only_clears_earlier_lines(self, tmp_path):
        path = tmp_path / "fails"
        with open(path, "w") as f:
            f.write("a|b\na|b|clear\na|b\nc|d\n")
        led = FailureLedger(str(path), threshold=1)
        assert led.failures() == {"a|b": 1, "c|d": 1}

    def test_validation(self):
        with pytest.raises(ValueError):
            FailureLedger("x", threshold=0)


# -- fault plans -----------------------------------------------------------


class TestFaultPlan:
    def test_parse_inline_list(self):
        plan = FaultPlan.parse(
            '[{"backend": "local", "op": "describe", "nth": 2, "times": 2}]'
        )
        assert len(plan.rules) == 1
        rule = plan.rules[0]
        assert (rule.backend, rule.op, rule.nth, rule.times) == (
            "local",
            "describe",
            2,
            2,
        )
        assert rule.mode == "transient"

    def test_parse_rules_object(self):
        plan = FaultPlan.parse('{"rules": [{"op": "submit", "mode": "timeout"}]}')
        assert plan.rules[0].mode == "timeout"

    def test_parse_file(self, tmp_path):
        f = tmp_path / "plan.json"
        f.write_text('[{"backend": "gke", "mode": "garbage"}]')
        plan = FaultPlan.parse(str(f))
        assert plan.rules[0].backend == "gke"

    @pytest.mark.parametrize(
        "raw",
        [
            "not json at all {",
            '"just a string"',
            '[{"backend": "x", "typo_key": 1}]',
            '[{"mode": "explode"}]',
            '[{"nth": 0}]',
            '[{"times": 0}]',
            "[42]",
        ],
    )
    def test_malformed_plans_fail_loudly(self, raw):
        with pytest.raises(ValueError):
            FaultPlan.parse(raw)

    def test_rule_matching_is_deterministic(self):
        rule = FaultRule(backend="loc*", op="describe", nth=2, times=2)
        fires = [rule.matches("local", "describe", n) for n in range(1, 6)]
        assert fires == [False, True, True, False, False]
        assert not rule.matches("gke", "describe", 2)
        assert not rule.matches("local", "submit", 2)

    def test_nth_omitted_fires_from_first_call(self):
        rule = FaultRule(times=3)
        assert [rule.matches("b", "o", n) for n in (1, 2, 3, 4)] == [
            True,
            True,
            True,
            False,
        ]

    def test_injector_counts_per_backend_op(self):
        plan = FaultPlan(rules=[FaultRule(backend="local", op="describe", nth=2)])
        inj = FaultInjector(plan)
        assert inj.check("local", "describe") is None  # call 1
        assert inj.check("local", "submit") is None  # independent counter
        assert inj.check("local", "describe") is not None  # call 2 fires
        assert inj.check("local", "describe") is None  # call 3

    def test_fire_modes(self):
        inj = FaultInjector(FaultPlan())
        with pytest.raises(TransientSchedulerError) as ei:
            inj.fire(FaultRule(mode="transient"), "b", "o")
        assert ei.value.kind is FailureKind.UNAVAILABLE
        with pytest.raises(PermanentSchedulerError):
            inj.fire(FaultRule(mode="permanent"), "b", "o")
        with pytest.raises(subprocess.TimeoutExpired):
            inj.fire(FaultRule(mode="timeout"), "b", "o")
        assert inj.fire(FaultRule(mode="garbage"), "b", "o") == GARBAGE_PAYLOAD

    def test_active_injector_cached_while_env_unchanged(self, monkeypatch):
        monkeypatch.setenv(
            settings.ENV_TPX_FAULT_PLAN, '[{"backend": "x", "nth": 1}]'
        )
        first = resilience_faults.active_injector()
        assert first is resilience_faults.active_injector()  # counters persist
        monkeypatch.setenv(settings.ENV_TPX_FAULT_PLAN, '[{"backend": "y"}]')
        assert resilience_faults.active_injector() is not first
        monkeypatch.delenv(settings.ENV_TPX_FAULT_PLAN)
        assert resilience_faults.active_injector() is None

    def test_fault_plan_active(self, monkeypatch):
        monkeypatch.delenv(settings.ENV_TPX_FAULT_PLAN, raising=False)
        assert not fault_plan_active()
        monkeypatch.setenv(settings.ENV_TPX_FAULT_PLAN, "[]")
        assert fault_plan_active()


# -- control-plane timeout knob --------------------------------------------


class TestControlPlaneTimeout:
    def test_default(self, monkeypatch):
        monkeypatch.delenv(settings.ENV_TPX_CONTROL_PLANE_TIMEOUT, raising=False)
        assert control_plane_timeout() == settings.DEFAULT_CONTROL_PLANE_TIMEOUT

    @pytest.mark.parametrize("raw", ["0", "off", "none", "NONE", "false", "-5"])
    def test_disabled(self, monkeypatch, raw):
        monkeypatch.setenv(settings.ENV_TPX_CONTROL_PLANE_TIMEOUT, raw)
        assert control_plane_timeout() is None

    def test_explicit_value(self, monkeypatch):
        monkeypatch.setenv(settings.ENV_TPX_CONTROL_PLANE_TIMEOUT, "12.5")
        assert control_plane_timeout() == 12.5

    def test_unparseable_falls_back_to_default(self, monkeypatch):
        monkeypatch.setenv(settings.ENV_TPX_CONTROL_PLANE_TIMEOUT, "soon")
        assert control_plane_timeout() == settings.DEFAULT_CONTROL_PLANE_TIMEOUT


# -- resilient_call --------------------------------------------------------


class TestResilientCall:
    def test_success_passthrough(self):
        before = obs_metrics.CONTROL_PLANE_CALLS.value(
            backend="tc1", op="describe", status="ok"
        )
        assert (
            resilient_call(lambda: 42, backend="tc1", op="describe") == 42
        )
        after = obs_metrics.CONTROL_PLANE_CALLS.value(
            backend="tc1", op="describe", status="ok"
        )
        assert after == before + 1

    def test_transient_retried_then_succeeds(self):
        calls = {"n": 0}
        sleeps: list[float] = []

        def fn():
            calls["n"] += 1
            if calls["n"] <= 2:
                raise TransientSchedulerError("x", kind=FailureKind.UNAVAILABLE)
            return "ok"

        result = resilient_call(
            fn,
            backend="tc2",
            op="describe",
            policy=fast_call_policy(),
            sleep=sleeps.append,
        )
        assert result == "ok"
        assert calls["n"] == 3
        assert len(sleeps) == 2

    def test_budget_exhausted_reraises_the_original(self):
        original = TransientSchedulerError("x", kind=FailureKind.UNAVAILABLE)

        def fn():
            raise original

        with pytest.raises(TransientSchedulerError) as ei:
            resilient_call(
                fn,
                backend="tc3",
                op="describe",
                policy=fast_call_policy(
                    retries={FailureKind.UNAVAILABLE: 1}
                ),
                sleep=lambda s: None,
            )
        assert ei.value is original  # identity: callers' except clauses work

    def test_permanent_raises_immediately_without_retry(self):
        sleeps: list[float] = []

        class NotFound(Exception):
            pass

        def fn():
            raise NotFound("gone")

        with pytest.raises(NotFound):
            resilient_call(
                fn, backend="tc4", op="describe", sleep=sleeps.append
            )
        assert sleeps == []
        # a permanent answer proves the backend reachable
        assert breaker_for("tc4").state is BreakerState.CLOSED

    def test_breaker_opens_and_rejects(self):
        def fn():
            raise TransientSchedulerError("x", kind=FailureKind.UNAVAILABLE)

        policy = fast_call_policy(retries={})
        for _ in range(5):  # default trip_after
            with pytest.raises(TransientSchedulerError):
                resilient_call(
                    fn, backend="tc5", op="describe", policy=policy,
                    sleep=lambda s: None,
                )
        assert breaker_for("tc5").state is BreakerState.OPEN
        before = obs_metrics.CONTROL_PLANE_CALLS.value(
            backend="tc5", op="describe", status="rejected"
        )
        with pytest.raises(BreakerOpenError):
            resilient_call(lambda: 1, backend="tc5", op="describe")
        after = obs_metrics.CONTROL_PLANE_CALLS.value(
            backend="tc5", op="describe", status="rejected"
        )
        assert after == before + 1
        # BreakerOpenError itself classifies transient (UNAVAILABLE), so
        # poll loops absorb it under their miss budget
        assert is_transient(classify_exception(BreakerOpenError("x")))


# -- resilient_cmd ---------------------------------------------------------


class TestResilientCmd:
    def test_default_deadline_injected(self, monkeypatch):
        monkeypatch.delenv(settings.ENV_TPX_CONTROL_PLANE_TIMEOUT, raising=False)
        seen = {}

        def run(cmd, **kwargs):
            seen.update(kwargs)
            return proc(0)

        resilient_cmd(run, ["x"], backend="cm1", op="describe")
        assert seen["timeout"] == settings.DEFAULT_CONTROL_PLANE_TIMEOUT

    def test_caller_timeout_wins(self):
        seen = {}

        def run(cmd, **kwargs):
            seen.update(kwargs)
            return proc(0)

        resilient_cmd(run, ["x"], backend="cm1", op="describe", timeout=7)
        assert seen["timeout"] == 7

    def test_disabled_deadline_means_no_timeout_kwarg(self, monkeypatch):
        monkeypatch.setenv(settings.ENV_TPX_CONTROL_PLANE_TIMEOUT, "off")
        seen = {"called": False}

        def run(cmd, **kwargs):
            seen["called"] = True
            assert "timeout" not in kwargs
            return proc(0)

        resilient_cmd(run, ["x"], backend="cm1", op="describe")
        assert seen["called"]

    def test_transient_exit_retried_then_succeeds(self):
        procs = [proc(1, stderr="503 unavailable"), proc(0, stdout="done")]
        sleeps: list[float] = []

        result = resilient_cmd(
            lambda cmd, **kw: procs.pop(0),
            ["x"],
            backend="cm2",
            op="describe",
            policy=fast_call_policy(),
            sleep=sleeps.append,
        )
        assert result.returncode == 0
        assert result.stdout == "done"
        assert len(sleeps) == 1

    def test_budget_exhausted_returns_last_failing_proc(self):
        last = proc(1, stderr="too many requests")
        sleeps: list[float] = []

        result = resilient_cmd(
            lambda cmd, **kw: last,
            ["x"],
            backend="cm3",
            op="describe",
            policy=fast_call_policy(retries={FailureKind.RATE_LIMIT: 2}),
            sleep=sleeps.append,
        )
        assert result is last  # returned, never raised: rc semantics hold
        assert len(sleeps) == 2

    def test_permanent_exit_returned_without_retry(self):
        sleeps: list[float] = []
        result = resilient_cmd(
            lambda cmd, **kw: proc(1, stderr="permission denied"),
            ["x"],
            backend="cm4",
            op="describe",
            policy=fast_call_policy(),
            sleep=sleeps.append,
        )
        assert result.returncode == 1
        assert sleeps == []
        assert breaker_for("cm4").state is BreakerState.CLOSED

    def test_hung_call_synthesizes_timeout_proc(self):
        def run(cmd, **kwargs):
            raise subprocess.TimeoutExpired(cmd=cmd, timeout=kwargs["timeout"])

        sleeps: list[float] = []
        result = resilient_cmd(
            run,
            ["x"],
            backend="cm5",
            op="describe",
            policy=fast_call_policy(retries={FailureKind.TIMEOUT: 1}),
            sleep=sleeps.append,
            timeout=0.5,
        )
        assert result.returncode == TIMEOUT_RETURNCODE
        assert settings.ENV_TPX_CONTROL_PLANE_TIMEOUT in result.stderr
        assert len(sleeps) == 1  # retried once, then degraded to a proc

    def test_garbage_fault_returns_unparseable_stdout(self, monkeypatch):
        monkeypatch.setenv(
            settings.ENV_TPX_FAULT_PLAN,
            '[{"backend": "cm6", "op": "list", "mode": "garbage"}]',
        )
        calls = {"n": 0}

        def run(cmd, **kwargs):
            calls["n"] += 1
            return proc(0, stdout="real output")

        result = resilient_cmd(run, ["x"], backend="cm6", op="list")
        assert calls["n"] == 0  # the real call never happened
        assert result.returncode == 0
        assert result.stdout == GARBAGE_PAYLOAD


# -- Runner.wait poll-miss budget ------------------------------------------


class FlakyScheduler(Scheduler[dict]):
    """``describe()`` raises the scripted exceptions first, then reports a
    terminal SUCCEEDED — a control plane that flakes mid-wait."""

    def __init__(self, session_name: str, failures=None, **kwargs):
        super().__init__("flaky", session_name)
        self.failures = list(failures or [])

    def run_opts(self) -> runopts:
        return runopts()

    def _submit_dryrun(self, app: AppDef, cfg: Mapping[str, CfgVal]):
        return AppDryRunInfo({"app": app})

    def schedule(self, dryrun_info) -> str:
        return "job_1"

    def describe(self, app_id: str) -> Optional[DescribeAppResponse]:
        if self.failures:
            raise self.failures.pop(0)
        return DescribeAppResponse(app_id=app_id, state=AppState.SUCCEEDED)

    def _cancel_existing(self, app_id: str) -> None:
        pass


class _CaptureEvents(logging.Handler):
    def __init__(self):
        super().__init__()
        self.events: list[TpxEvent] = []
        self.spans: list[dict] = []

    def emit(self, record: logging.LogRecord) -> None:
        msg = record.getMessage()
        obj = json.loads(msg)
        if obj.get("kind") == "span":
            self.spans.append(obj)
        else:
            self.events.append(TpxEvent.deserialize(msg))


@pytest.fixture
def capture_pipeline():
    handler = _CaptureEvents()
    logger = get_events_logger()
    logger.addHandler(handler)
    yield handler
    logger.removeHandler(handler)


def flaky_wait(failures, budget):
    sched = FlakyScheduler("w", failures=failures)
    runner = Runner("w", {"flaky": lambda session_name, **kw: sched})
    with runner:
        return runner.wait(
            "flaky://w/job_1",
            wait_interval=0.01,
            sleep=lambda s: None,
            poll_miss_budget=budget,
        )


class TestPollMissBudget:
    def test_absorbs_transient_misses_within_budget(self, capture_pipeline):
        failures = [
            TransientSchedulerError("a", kind=FailureKind.UNAVAILABLE),
            TransientSchedulerError("b", kind=FailureKind.CONNECTION),
        ]
        status = flaky_wait(failures, budget=2)
        assert status is not None and status.state == AppState.SUCCEEDED
        degraded = [
            e
            for e in capture_pipeline.events
            if (e.app_metadata or {}).get("transition") == "poll_degraded"
        ]
        assert len(degraded) == 2
        assert degraded[0].app_metadata["miss"] == 1
        assert degraded[0].app_metadata["kind"] == str(FailureKind.UNAVAILABLE)
        assert degraded[1].app_metadata["miss"] == 2

    def test_budget_exceeded_raises(self):
        failures = [
            TransientSchedulerError("x", kind=FailureKind.UNAVAILABLE)
            for _ in range(3)
        ]
        with pytest.raises(TransientSchedulerError):
            flaky_wait(failures, budget=2)

    def test_consecutive_semantics_reset_on_success(self):
        # default budget of 0 absorbs nothing...
        with pytest.raises(TransientSchedulerError):
            flaky_wait(
                [TransientSchedulerError("x", kind=FailureKind.UNAVAILABLE)],
                budget=0,
            )

    def test_permanent_error_always_raises(self):
        failures = [PermanentSchedulerError("auth", kind=FailureKind.AUTH)]
        with pytest.raises(PermanentSchedulerError):
            flaky_wait(failures, budget=5)


# -- analyzer rules TPX501 / TPX502 ----------------------------------------


class TestResilienceRules:
    def run_rule(self, **kwargs):
        from torchx_tpu.analyze.rules import RuleContext, check_resilience

        app = kwargs.pop(
            "app",
            AppDef(
                name="a",
                roles=[
                    Role(
                        name="r",
                        image="i",
                        entrypoint="e",
                        max_retries=kwargs.pop("max_retries", 0),
                    )
                ],
            ),
        )
        return list(check_resilience(RuleContext(app=app, **kwargs)))

    def test_tpx501_multiplicative_budgets(self):
        from torchx_tpu.analyze.diagnostics import Severity
        from torchx_tpu.schedulers.api import SchedulerCapabilities

        diags = self.run_rule(
            max_retries=2,
            scheduler="gke",
            capabilities=SchedulerCapabilities(native_retries=True),
            policy=SupervisorPolicy(),
        )
        assert [d.code for d in diags] == ["TPX501"]
        assert diags[0].severity == Severity.WARNING
        # default policy budget 8+3+0=11, native 2 -> (11+1)*(2+1)-1 = 35
        assert "35 total restarts" in diags[0].message

    def test_tpx501_needs_all_three_layers(self):
        from torchx_tpu.schedulers.api import SchedulerCapabilities

        cap = SchedulerCapabilities(native_retries=True)
        assert self.run_rule(max_retries=0, scheduler="gke",
                             capabilities=cap, policy=SupervisorPolicy()) == []
        assert self.run_rule(max_retries=2, scheduler="gke",
                             capabilities=cap, policy=None) == []
        assert self.run_rule(
            max_retries=2,
            scheduler="tpu_vm",
            capabilities=SchedulerCapabilities(native_retries=False),
            policy=SupervisorPolicy(),
        ) == []
        zero = SupervisorPolicy(
            max_preemptions=0, max_infra_retries=0, max_app_retries=0
        )
        assert self.run_rule(max_retries=2, scheduler="gke",
                             capabilities=cap, policy=zero) == []

    def test_tpx502_fault_plan_on_real_backend(self, monkeypatch):
        from torchx_tpu.analyze.diagnostics import Severity

        monkeypatch.setenv(settings.ENV_TPX_FAULT_PLAN, "[]")
        diags = self.run_rule(scheduler="gke")
        assert [d.code for d in diags] == ["TPX502"]
        assert diags[0].severity == Severity.ERROR

    def test_tpx502_local_drills_allowed(self, monkeypatch):
        monkeypatch.setenv(settings.ENV_TPX_FAULT_PLAN, "[]")
        assert self.run_rule(scheduler="local") == []
        assert self.run_rule(scheduler="local_docker") == []
        monkeypatch.delenv(settings.ENV_TPX_FAULT_PLAN)
        assert self.run_rule(scheduler="gke") == []


# -- supervision ledger ----------------------------------------------------


class ScriptedScheduler(Scheduler[dict]):
    """Each ``schedule()`` consumes the next scripted terminal outcome."""

    def __init__(self, session_name: str, script=None, **kwargs):
        super().__init__("scripted", session_name)
        self.script = list(script or [])
        self.apps: dict[str, tuple[AppState, Optional[FailureClass]]] = {}
        self._counter = 0

    def run_opts(self) -> runopts:
        return runopts()

    def _submit_dryrun(self, app: AppDef, cfg: Mapping[str, CfgVal]):
        return AppDryRunInfo({"app": app})

    def schedule(self, dryrun_info) -> str:
        self._counter += 1
        app_id = f"job_{self._counter}"
        outcome = (
            self.script.pop(0) if self.script else (AppState.SUCCEEDED, None)
        )
        self.apps[app_id] = outcome
        return app_id

    def describe(self, app_id: str) -> Optional[DescribeAppResponse]:
        if app_id not in self.apps:
            return None
        state, fclass = self.apps[app_id]
        return DescribeAppResponse(
            app_id=app_id, state=state, failure_class=fclass
        )

    def _cancel_existing(self, app_id: str) -> None:
        self.apps[app_id] = (AppState.CANCELLED, None)


def make_runner(script=None):
    sched = ScriptedScheduler("sup", script=script)
    runner = Runner("sup", {"scripted": lambda session_name, **kw: sched})
    return runner, sched


def dryrun(runner):
    app = AppDef(
        name="train",
        roles=[Role(name="trainer", image="i", entrypoint="python")],
    )
    return runner.dryrun(app, "scripted")


def fast_policy(**kwargs) -> SupervisorPolicy:
    defaults = dict(
        backoff_seconds=1.0, backoff_factor=2.0, jitter=0.0, poll_interval=0.01
    )
    defaults.update(kwargs)
    return SupervisorPolicy(**defaults)


class TestAttemptLedger:
    @pytest.mark.parametrize("name", ["", "a/b", ".", ".."])
    def test_invalid_session_names(self, name):
        with pytest.raises(ValueError):
            AttemptLedger(name)

    def test_append_and_entries_round_trip(self):
        led = AttemptLedger("s1")
        led.append("submitted", "job_1", attempt=1, handle="x://s/job_1")
        led.append("finished", "job_1", state="SUCCEEDED")
        entries = list(led.entries())
        assert [e["transition"] for e in entries] == ["submitted", "finished"]
        assert entries[0]["handle"] == "x://s/job_1"
        assert entries[0]["time_usec"] > 0

    def test_torn_final_line_is_skipped(self):
        led = AttemptLedger("s2")
        led.append("submitted", "job_1")
        with open(os.path.join(led.path, "ledger.jsonl"), "a") as f:
            f.write('{"transition": "resub')  # writer died mid-append
        assert [e["transition"] for e in led.entries()] == ["submitted"]

    def test_meta_round_trip_and_missing(self):
        led = AttemptLedger("s3")
        assert not led.exists()
        led.write_meta({"scheduler": "local", "app": {}})
        assert led.exists()
        assert led.read_meta()["scheduler"] == "local"
        with pytest.raises(FileNotFoundError) as ei:
            AttemptLedger("nope").read_meta()
        assert "s3" in str(ei.value)  # known sessions listed in the error

    def test_list_sessions_newest_first(self):
        for name in ("old", "new"):
            AttemptLedger(name).write_meta({})
        root = os.environ["TPX_SUPERVISOR_DIR"]
        os.utime(os.path.join(root, "old", "meta.json"), (1, 1))
        os.utime(os.path.join(root, "new", "meta.json"), (2, 2))
        assert list_sessions() == ["new", "old"]


class TestSupervisorResume:
    def test_restore_replays_the_ledger(self):
        led = AttemptLedger("restore1")
        led.append("submitted", "job_1", attempt=1, resume_step=None,
                   handle="scripted://sup/job_1")
        led.append("resubmitting", "job_1",
                   failure_class=str(FailureClass.PREEMPTION))
        led.append("submitted", "job_2", attempt=2, resume_step=120,
                   handle="scripted://sup/job_2")
        runner, _ = make_runner()
        with runner:
            sup = Supervisor(runner, dryrun(runner), fast_policy(),
                             session="restore1")
            sup._restore(led)
        assert sup._resume_attempts == 2
        assert sup._resume_handle == "scripted://sup/job_2"
        assert sup._resume_retries[FailureClass.PREEMPTION] == 1
        assert sup._resume_retries[FailureClass.INFRA] == 0
        assert sup._resume_steps == [None, 120]

    def test_resume_reattaches_without_resubmitting(self, capture_pipeline):
        runner, sched = make_runner(script=[(AppState.SUCCEEDED, None)])
        with runner:
            sup = Supervisor(
                runner, dryrun(runner), fast_policy(), session="reatt",
                sleep=lambda s: None,
            )
            first = sup.run()
            assert first.succeeded and sched._counter == 1

            resumed = Supervisor.resume(runner, "reatt", sleep=lambda s: None)
            assert resumed.session == "reatt"
            result = resumed.run()
        assert result.succeeded
        assert result.attempts == 1
        assert result.handles == ["scripted://sup/job_1"]
        assert sched._counter == 1  # reattached; never submitted again
        reattached = [
            e
            for e in capture_pipeline.events
            if (e.app_metadata or {}).get("transition") == "reattached"
        ]
        assert len(reattached) == 1
        assert [e["transition"] for e in AttemptLedger("reatt").entries()].count(
            "submitted"
        ) == 1

    def test_resume_unknown_session_raises(self):
        runner, _ = make_runner()
        with runner:
            with pytest.raises(FileNotFoundError):
                Supervisor.resume(runner, "ghost")

    def test_resume_before_first_submit_raises(self):
        runner, _ = make_runner()
        with runner:
            sup = Supervisor(runner, dryrun(runner), fast_policy(),
                             session="early")
            sup._write_meta()  # client died between meta and first submit
            with pytest.raises(ValueError, match="no submitted attempt"):
                Supervisor.resume(runner, "early")


# -- ISSUE acceptance ------------------------------------------------------


class TestAcceptance:
    def test_fault_injected_supervise_completes_with_zero_resubmits(
        self, monkeypatch, capture_pipeline
    ):
        """ISSUE acceptance: two transient faults injected into local status
        polls are absorbed by in-seam retries — the supervised run succeeds
        on its FIRST attempt (no resubmits), with ``launcher.retry`` span
        and retry-metric evidence."""
        from torchx_tpu.schedulers.local_scheduler import LocalScheduler

        monkeypatch.setattr(
            "torchx_tpu.resilience.call.DEFAULT_POLICY", fast_call_policy()
        )
        monkeypatch.setenv(
            settings.ENV_TPX_FAULT_PLAN,
            '[{"backend": "local", "op": "describe", "nth": 2, "times": 2,'
            ' "mode": "transient", "message": "injected 503"}]',
        )
        retries_before = obs_metrics.CONTROL_PLANE_RETRIES.value(
            backend="local", op="describe", kind="UNAVAILABLE"
        )

        sched = LocalScheduler(session_name="acc", cache_size=10)
        runner = Runner(
            "acc", {"local": lambda session_name, **kw: sched}
        )
        app = AppDef(
            name="accjob",
            roles=[
                Role(
                    name="t", image="", entrypoint="sh",
                    args=["-c", "sleep 0.4"],
                )
            ],
        )
        with runner:
            info = runner.dryrun(app, "local")
            sup = Supervisor(
                runner, info, fast_policy(poll_interval=0.02),
                session="accsess",
            )
            result = sup.run()
        sched.close()

        assert result.succeeded
        assert result.attempts == 1  # ZERO resubmits
        assert len(result.handles) == 1
        assert all(n == 0 for n in result.retries.values())
        assert [e["transition"] for e in AttemptLedger("accsess").entries()].count(
            "resubmitting"
        ) == 0

        retries_after = obs_metrics.CONTROL_PLANE_RETRIES.value(
            backend="local", op="describe", kind="UNAVAILABLE"
        )
        assert retries_after - retries_before == 2
        retry_spans = [
            s
            for s in capture_pipeline.spans
            if s["name"] == "launcher.retry"
            and s["attrs"].get("backend") == "local"
            and s["attrs"].get("op") == "describe"
        ]
        assert len(retry_spans) == 2

    def test_sigkill_then_resume_reattaches_to_success(
        self, tmp_path, monkeypatch
    ):
        """ISSUE acceptance: SIGKILL the supervising client mid-run, then
        ``Supervisor.resume`` in a fresh process reattaches to the SAME
        handle (no duplicate submission) and drives it to SUCCEEDED."""
        from torchx_tpu.schedulers.local_scheduler import LocalScheduler

        # child + parent must share the local-scheduler app registry: the
        # child resolves it under $HOME, the parent's conftest monkeypatch
        # is re-pointed at the same file
        registry = tmp_path / ".tpx_local_apps"
        monkeypatch.setattr(
            "torchx_tpu.schedulers.local_scheduler._registry_path",
            lambda: str(registry),
        )
        child_src = textwrap.dedent(
            """
            from torchx_tpu.runner.api import Runner
            from torchx_tpu.schedulers.local_scheduler import LocalScheduler
            from torchx_tpu.specs.api import AppDef, Role
            from torchx_tpu.supervisor import Supervisor, SupervisorPolicy

            runner = Runner(
                "crash",
                {"local": lambda session_name, **kw: LocalScheduler(
                    session_name=session_name, cache_size=10)},
            )
            app = AppDef(
                name="crashjob",
                roles=[Role(name="t", image="", entrypoint="sh",
                            args=["-c", "sleep 2"])],
            )
            info = runner.dryrun(app, "local")
            sup = Supervisor(
                runner, info,
                SupervisorPolicy(poll_interval=0.05),
                session="crashsess",
            )
            sup.run()
            """
        )
        script = tmp_path / "crash_child.py"
        script.write_text(child_src)
        env = dict(os.environ, HOME=str(tmp_path))
        child = subprocess.Popen(
            [sys.executable, str(script)],
            cwd=str(REPO_ROOT),
            env=env,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        try:
            ledger_file = (
                Path(os.environ["TPX_SUPERVISOR_DIR"])
                / "crashsess"
                / "ledger.jsonl"
            )
            deadline = time.monotonic() + 30
            submitted = None
            while time.monotonic() < deadline and submitted is None:
                if ledger_file.exists():
                    for line in ledger_file.read_text().splitlines():
                        try:
                            entry = json.loads(line)
                        except ValueError:
                            continue
                        if entry.get("transition") == "submitted":
                            submitted = entry
                            break
                if child.poll() is not None:
                    pytest.fail("supervising child exited before the kill")
                time.sleep(0.02)
            assert submitted is not None, "child never submitted"
        finally:
            child.kill()  # SIGKILL: no cleanup handlers run
            child.wait()

        # the replica (its own session) survives the supervisor's death;
        # a fresh client reattaches to the recorded handle
        sched = LocalScheduler(session_name="crash", cache_size=10)
        runner = Runner("crash", {"local": lambda session_name, **kw: sched})
        with runner:
            sup = Supervisor.resume(runner, "crashsess")
            result = sup.run()
        sched.close()

        assert result.succeeded
        assert result.status is not None
        assert result.status.state == AppState.SUCCEEDED
        assert result.attempts == 1
        assert result.handles == [submitted["handle"]]  # the SAME attempt
        transitions = [
            e["transition"] for e in AttemptLedger("crashsess").entries()
        ]
        assert transitions.count("submitted") == 1  # never resubmitted
        assert "reattached" in transitions
        assert transitions[-1] == "finished"
