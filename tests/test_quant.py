"""Int8 weight-only quantization tests (ops/quant.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchx_tpu.models import generate as gen
from torchx_tpu.models import llama
from torchx_tpu.ops import quant


class TestQuantOps:
    def test_roundtrip_error_small(self):
        w = jax.random.normal(jax.random.PRNGKey(0), (64, 32))
        q, scale = quant.quantize(w)
        back = quant.dequantize(q, scale, dtype=jnp.float32)
        rel = float(jnp.abs(back - w).max() / jnp.abs(w).max())
        assert rel < 0.01  # 127-level symmetric grid

    def test_per_layer_scales_on_stacked_weights(self):
        # two layers with wildly different magnitudes must not share scales
        w = jnp.stack(
            [jnp.ones((8, 4)) * 0.01, jnp.ones((8, 4)) * 100.0]
        )  # [L=2, in, out]
        q, scale = quant.quantize(w)
        assert scale.shape == (2, 1, 4)
        back = quant.dequantize(q, scale, dtype=jnp.float32)
        np.testing.assert_allclose(back, w, rtol=0.01)

    def test_int8_matmul_matches_dequant(self):
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 64), dtype=jnp.float32)
        w = jax.random.normal(jax.random.PRNGKey(2), (64, 32))
        q, scale = quant.quantize(w)
        got = quant.int8_matmul(x, q, scale)
        want = x @ quant.dequantize(q, scale, dtype=jnp.float32)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_maybe_matmul_both_forms(self):
        x = jnp.ones((2, 8))
        w = jax.random.normal(jax.random.PRNGKey(3), (8, 4))
        q, scale = quant.quantize(w)
        plain = quant.maybe_matmul(x, w)
        quantized = quant.maybe_matmul(x, {"q": q, "scale": scale})
        np.testing.assert_allclose(plain, quantized, rtol=0.02, atol=0.02)


class TestQuantizedModel:
    @pytest.fixture(scope="class")
    def setup(self):
        cfg = llama.llama_tiny(max_seq=64)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, 512)
        return cfg, params, prompt

    def test_quantize_params_halves_projection_bytes(self, setup):
        cfg, params, _ = setup
        qparams = quant.quantize_params(params)
        # projections dominate; total must shrink substantially
        assert quant.size_bytes(qparams) < 0.75 * quant.size_bytes(params)
        # embeddings/norms stay exact
        assert qparams["embed"].dtype == params["embed"].dtype

    def test_quantized_decode_close_to_fp(self, setup):
        cfg, params, prompt = setup
        qparams = quant.quantize_params(params)
        cache = gen.init_kv_cache(cfg, 2, 16)
        logits_fp, _ = gen.forward_with_cache(
            params, prompt, cache, jnp.int32(0), cfg
        )
        cache2 = gen.init_kv_cache(cfg, 2, 16)
        logits_q, _ = gen.forward_with_cache(
            qparams, prompt, cache2, jnp.int32(0), cfg
        )
        # int8 weight-only: logits track fp closely at tiny scale
        err = float(
            jnp.abs(logits_q - logits_fp).mean() / jnp.abs(logits_fp).mean()
        )
        assert err < 0.05, err

    def test_quantized_generate_runs(self, setup):
        cfg, params, prompt = setup
        qparams = quant.quantize_params(params)
        out = gen.generate(params, prompt, cfg, max_new_tokens=4)
        qout = gen.generate(qparams, prompt, cfg, max_new_tokens=4)
        assert qout.shape == out.shape
        # greedy decode from near-identical logits: most tokens agree
        agree = float((qout == out).mean())
        assert agree > 0.8, agree


class TestInt8TrainingMatmul:
    """AQT int8 TRAINING matmuls (fwd+bwd quantized, STE backward) —
    the training-side counterpart of weight-only serving quant."""

    def test_close_to_bf16_and_grads_flow(self):
        pytest.importorskip("aqt")
        import jax

        k = jax.random.PRNGKey(0)
        x = jax.random.normal(k, (64, 128), jnp.bfloat16)
        w = jax.random.normal(jax.random.PRNGKey(1), (128, 96), jnp.bfloat16)

        y_fp = quant.maybe_matmul(x, w)
        y_i8 = quant.maybe_matmul(x, w, int8_training=True)
        assert y_i8.dtype == y_fp.dtype
        err = float(
            jnp.abs(y_i8.astype(jnp.float32) - y_fp.astype(jnp.float32)).mean()
            / jnp.abs(y_fp.astype(jnp.float32)).mean()
        )
        assert err < 0.05, err

        def loss(w):
            return quant.maybe_matmul(x, w, int8_training=True).astype(
                jnp.float32
            ).sum()

        g = jax.grad(loss)(w)
        assert g.shape == w.shape
        assert float(jnp.abs(g.astype(jnp.float32)).mean()) > 0

    def test_int8_training_model_matches_bf16(self):
        pytest.importorskip("aqt")
        import jax
        from torchx_tpu.models import llama

        cfg = llama.llama_tiny(remat_policy="full")
        cfg_i8 = llama.llama_tiny(remat_policy="full", int8_matmuls=True)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        batch = {
            "tokens": jax.random.randint(
                jax.random.PRNGKey(1), (2, 32), 0, cfg.vocab_size
            )
        }
        l_fp = float(llama.loss_fn(params, batch, cfg))
        l_i8 = float(llama.loss_fn(params, batch, cfg_i8))
        assert abs(l_fp - l_i8) < 0.2, (l_fp, l_i8)

    def test_int8_scope_ffn_only(self):
        """int8_scope='ffn' quantizes the FFN dots and ONLY those: output
        differs from bf16 (int8 is active) but is at least as close to
        bf16 as full-scope int8 (attention path untouched)."""
        import jax
        import numpy as np
        import pytest

        pytest.importorskip("aqt")
        from torchx_tpu.models import llama

        cfg_bf16 = llama.llama_tiny(remat_policy="full")
        cfg_ffn = llama.llama_tiny(
            remat_policy="full", int8_matmuls=True, int8_scope="ffn"
        )
        cfg_all = llama.llama_tiny(remat_policy="full", int8_matmuls=True)
        params = llama.init_params(cfg_bf16, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 100)
        ref = np.asarray(llama.forward(params, tokens, cfg_bf16))
        out_ffn = np.asarray(llama.forward(params, tokens, cfg_ffn))
        out_all = np.asarray(llama.forward(params, tokens, cfg_all))
        err_ffn = np.abs(out_ffn - ref).mean()
        err_all = np.abs(out_all - ref).mean()
        assert err_ffn > 0, "ffn scope quantized nothing"
        assert err_ffn <= err_all + 1e-6, (
            f"ffn-only scope should not round more than full scope:"
            f" {err_ffn} vs {err_all}"
        )
        np.testing.assert_allclose(out_ffn, ref, atol=0.15, rtol=0.15)

    def test_int8_scope_validated(self):
        import pytest

        from torchx_tpu.models import llama

        with pytest.raises(ValueError, match="int8_scope"):
            llama.llama_tiny(int8_scope="attn")

    def test_int8_training_on_sharded_mesh(self):
        """AQT int8 matmuls must compose with GSPMD sharding: users flip
        int8_matmuls on real dp/fsdp/tp meshes, where AQT's internal
        quantize/dequantize ops get partitioned too."""
        pytest.importorskip("aqt")
        from torchx_tpu.examples.train_llama import train
        from torchx_tpu.models import llama
        from torchx_tpu.parallel.mesh import MeshConfig

        cfg = llama.llama_tiny(remat_policy="full", int8_matmuls=True)
        mesh = MeshConfig(dp=2, fsdp=2, tp=2, sp=1)
        m = train(cfg, mesh, batch=8, seq=64, steps=3, log_every=3)
        assert 0 < m["loss"] < 10
