"""Local scheduler tests against real subprocesses (reference analog:
torchx/schedulers/test/local_scheduler_test.py — real Popen, no mocks)."""

import os
import time
from pathlib import Path

import pytest

from torchx_tpu.schedulers.api import Stream
from torchx_tpu.schedulers.local_scheduler import (
    CWDImageProvider,
    LocalDirectoryImageProvider,
    LocalScheduler,
    tpu_device_env,
)
from torchx_tpu.specs.api import (
    AppDef,
    AppState,
    Resource,
    Role,
    TpuSlice,
    macros,
)


@pytest.fixture
def sched():
    s = LocalScheduler(session_name="test", cache_size=10)
    yield s
    s.close()


def sh_role(name: str, script: str, num_replicas: int = 1, **kwargs) -> Role:
    return Role(
        name=name,
        image="",
        entrypoint="sh",
        args=["-c", script],
        num_replicas=num_replicas,
        **kwargs,
    )


def wait_terminal(sched: LocalScheduler, app_id: str, timeout: float = 30) -> AppState:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        desc = sched.describe(app_id)
        assert desc is not None
        if desc.state in (AppState.SUCCEEDED, AppState.FAILED, AppState.CANCELLED):
            return desc.state
        time.sleep(0.05)
    raise TimeoutError(f"app {app_id} did not finish")


class TestElasticRestart:
    """Elastic gangs (min_replicas) shrink-and-restart on replica death,
    resuming from the app's own checkpoint with a resized world
    (BASELINE config 4: elastic min/max rendezvous under preemption)."""

    def elastic_script(self, ckpt_dir: str) -> str:
        # replica 2 "is preempted" (exit 1) before the checkpoint reaches
        # step 5; after the elastic restart the world is smaller, replica 2
        # no longer exists, and survivors resume from the checkpoint
        return (
            f"CK={ckpt_dir}/progress; start=0; "
            '[ -f "$CK" ] && start=$(cat "$CK"); '
            'if [ "$TPX_REPLICA_ID" = "2" ] && [ "$start" -lt 5 ]; then '
            'echo 5 > "$CK"; exit 1; fi; '
            'echo "world=$TPX_NUM_REPLICAS start=$start"; '
            "sleep 0.5; "
            '[ "$TPX_REPLICA_ID" = "0" ] && echo 10 > "$CK"; exit 0'
        )

    def test_shrink_restart_resumes_from_checkpoint(self, sched, tmp_path):
        ckpt = tmp_path / "ckpt"
        ckpt.mkdir()
        app = AppDef(
            name="elastic",
            roles=[
                sh_role(
                    "w",
                    self.elastic_script(str(ckpt)),
                    num_replicas=3,
                    min_replicas=1,
                    max_retries=2,
                )
            ],
        )
        app_id = sched.submit(app, {"log_dir": str(tmp_path)})
        assert wait_terminal(sched, app_id, timeout=30) == AppState.SUCCEEDED
        desc = sched.describe(app_id)
        assert desc.num_restarts == 1
        # the relaunched gang is 2 wide and resumed from the checkpoint
        out0 = (tmp_path / app_id / "w" / "0" / "stdout.log").read_text()
        assert "world=2 start=5" in out0
        # attempt-0 logs were rotated aside, not clobbered
        assert (tmp_path / app_id / "w" / "0" / "stdout.log.0").exists()
        # only 2 replicas in the final gang
        (rs,) = desc.roles_statuses
        assert len(rs.replicas) == 2
        assert (ckpt / "progress").read_text().strip() == "10"

    def test_no_restart_below_min(self, sched, tmp_path):
        ckpt = tmp_path / "ckpt"
        ckpt.mkdir()
        app = AppDef(
            name="floor",
            roles=[
                sh_role(
                    "w",
                    self.elastic_script(str(ckpt)),
                    num_replicas=3,
                    min_replicas=3,  # can't shrink below the floor
                    max_retries=2,
                )
            ],
        )
        app_id = sched.submit(app, {"log_dir": str(tmp_path)})
        assert wait_terminal(sched, app_id, timeout=30) == AppState.FAILED
        assert sched.describe(app_id).num_restarts == 0

    def test_rigid_gang_restarts_full_size(self, sched, tmp_path):
        """No min_replicas, but max_retries with the default APPLICATION
        retry policy: the gang restarts at FULL size (the local analog of
        JobSet maxRestarts / slurm requeue)."""
        ckpt = tmp_path / "ckpt"
        ckpt.mkdir()
        app = AppDef(
            name="rigid",
            roles=[
                sh_role(
                    "w",
                    self.elastic_script(str(ckpt)),
                    num_replicas=3,
                    max_retries=2,
                )
            ],
        )
        app_id = sched.submit(app, {"log_dir": str(tmp_path)})
        assert wait_terminal(sched, app_id, timeout=30) == AppState.SUCCEEDED
        desc = sched.describe(app_id)
        assert desc.num_restarts == 1
        (rs,) = desc.roles_statuses
        assert len(rs.replicas) == 3  # full size, not shrunk
        out0 = (tmp_path / app_id / "w" / "0" / "stdout.log").read_text()
        assert "world=3 start=5" in out0  # resumed from checkpoint

    def test_rigid_gang_fatal_without_retries(self, sched, tmp_path):
        ckpt = tmp_path / "ckpt"
        ckpt.mkdir()
        app = AppDef(
            name="rigid0",
            roles=[
                sh_role(
                    "w",
                    self.elastic_script(str(ckpt)),
                    num_replicas=3,  # max_retries defaults to 0
                )
            ],
        )
        app_id = sched.submit(app, {"log_dir": str(tmp_path)})
        assert wait_terminal(sched, app_id, timeout=30) == AppState.FAILED
        assert sched.describe(app_id).num_restarts == 0

    def test_replica_retry_policy_is_fatal_for_gang(self, sched, tmp_path):
        from torchx_tpu.specs.api import RetryPolicy

        ckpt = tmp_path / "ckpt"
        ckpt.mkdir()
        app = AppDef(
            name="rep",
            roles=[
                sh_role(
                    "w",
                    self.elastic_script(str(ckpt)),
                    num_replicas=3,
                    max_retries=2,
                    retry_policy=RetryPolicy.REPLICA,
                )
            ],
        )
        app_id = sched.submit(app, {"log_dir": str(tmp_path)})
        assert wait_terminal(sched, app_id, timeout=30) == AppState.FAILED

    def test_tpu_gang_shrinks_whole_slices(self, sched, tmp_path):
        """A TPU gang (2 slices x 2 hosts) losing one host must shrink to
        ONE whole slice (2 hosts), not 3 — and the relaunched world's env
        must be internally consistent (no stale multi-slice megascale env)."""
        script = (
            'if [ "$TPX_REPLICA_ID" = "3" ] && [ ! -f %s/died ]; then '
            "touch %s/died; exit 1; fi; "
            'echo "world=$TPX_NUM_REPLICAS slices=${MEGASCALE_NUM_SLICES:-none}'
            ' slice=${TPX_SLICE_ID:-none}"; sleep 0.5; exit 0'
        ) % (tmp_path, tmp_path)
        role = Role(
            name="w",
            image="",
            entrypoint="sh",
            args=["-c", script],
            num_replicas=2,  # slices
            min_replicas=1,
            max_retries=2,
            resource=Resource(cpu=1, memMB=256, tpu=TpuSlice("v5p", 8)),
        )
        app_id = sched.submit(AppDef(name="tpu-elastic", roles=[role]),
                              {"log_dir": str(tmp_path)})
        assert wait_terminal(sched, app_id, timeout=30) == AppState.SUCCEEDED
        desc = sched.describe(app_id)
        assert desc.num_restarts == 1
        (rs,) = desc.roles_statuses
        assert len(rs.replicas) == 2  # one whole slice, not 3 hosts
        out0 = (tmp_path / app_id / "w" / "0" / "stdout.log").read_text()
        assert "world=2 slices=none slice=none" in out0

    def test_role_scoped_restart_keeps_healthy_roles_running(self, sched, tmp_path):
        """RetryPolicy.ROLE: only the failed role relaunches; the healthy
        role's processes are left untouched (same pid across the restart)."""
        from torchx_tpu.specs.api import RetryPolicy

        flaky = (
            f"if [ ! -f {tmp_path}/fired ]; then touch {tmp_path}/fired;"
            ' exit 1; fi; echo "recovered"; exit 0'
        )
        steady = f'echo "pid=$$" >> {tmp_path}/steady.pids; sleep 3; exit 0'
        app = AppDef(
            name="rolescope",
            roles=[
                sh_role(
                    "flaky", flaky, num_replicas=1, max_retries=1,
                    retry_policy=RetryPolicy.ROLE,
                ),
                sh_role("steady", steady, num_replicas=1),
            ],
        )
        app_id = sched.submit(app, {"log_dir": str(tmp_path)})
        assert wait_terminal(sched, app_id, timeout=30) == AppState.SUCCEEDED
        assert sched.describe(app_id).num_restarts == 1
        # the steady role ran exactly once — it was never killed/relaunched
        pids = (tmp_path / "steady.pids").read_text().strip().splitlines()
        assert len(pids) == 1
        # only the flaky role's logs were rotated
        assert (tmp_path / app_id / "flaky" / "0" / "stdout.log.0").exists()
        assert not (tmp_path / app_id / "steady" / "0" / "stdout.log.0").exists()

    def test_per_role_budget_not_pooled(self, sched, tmp_path):
        """A role's own max_retries bounds ITS restarts even when another
        role in the app carries a bigger budget."""
        always_fails = 'exit 1'
        app = AppDef(
            name="pooled",
            roles=[
                sh_role("a", always_fails, num_replicas=1, max_retries=1),
                sh_role("b", "sleep 5", num_replicas=1, max_retries=3),
            ],
        )
        app_id = sched.submit(app, {"log_dir": str(tmp_path)})
        assert wait_terminal(sched, app_id, timeout=30) == AppState.FAILED
        # role a restarted once (its budget), NOT three times (b's budget)
        assert sched.describe(app_id).num_restarts == 1

    def test_budgets_are_per_role_both_directions(self, sched, tmp_path):
        """Role A's restart must not consume role B's budget: after A
        restarts once (its budget), B's FIRST failure still gets B's own
        retry. Both roles are ROLE-scoped with max_retries=1."""
        from torchx_tpu.specs.api import RetryPolicy

        a = (
            f"if [ ! -f {tmp_path}/a-fired ]; then touch {tmp_path}/a-fired;"
            " exit 1; fi; exit 0"
        )
        # b fails AFTER a recovered (ordering via marker file), once
        b = (
            f"while [ ! -f {tmp_path}/a-fired ]; do sleep 0.1; done; "
            f"if [ ! -f {tmp_path}/b-fired ]; then sleep 0.5;"
            f" touch {tmp_path}/b-fired; exit 1; fi; exit 0"
        )
        app = AppDef(
            name="two-budgets",
            roles=[
                sh_role("a", a, num_replicas=1, max_retries=1,
                        retry_policy=RetryPolicy.ROLE),
                sh_role("b", b, num_replicas=1, max_retries=1,
                        retry_policy=RetryPolicy.ROLE),
            ],
        )
        app_id = sched.submit(app, {"log_dir": str(tmp_path)})
        assert wait_terminal(sched, app_id, timeout=30) == AppState.SUCCEEDED
        # each role consumed exactly its own single retry
        assert sched.describe(app_id).num_restarts == 2

    def test_restart_budget_exhausted(self, sched, tmp_path):
        # every attempt fails (replica 0 always dies) -> FAILED after
        # max_retries restarts
        app = AppDef(
            name="burn",
            roles=[
                sh_role(
                    "w",
                    'if [ "$TPX_REPLICA_ID" = "0" ]; then exit 1; fi; sleep 20',
                    num_replicas=3,
                    min_replicas=1,
                    max_retries=1,
                )
            ],
        )
        app_id = sched.submit(app, {"log_dir": str(tmp_path)})
        assert wait_terminal(sched, app_id, timeout=30) == AppState.FAILED
        assert sched.describe(app_id).num_restarts == 1


class TestLocalScheduler:
    def test_submit_success(self, sched, tmp_path):
        app = AppDef(name="ok", roles=[sh_role("r", "echo hello")])
        app_id = sched.submit(app, {"log_dir": str(tmp_path)})
        assert wait_terminal(sched, app_id) == AppState.SUCCEEDED
        out = tmp_path / app_id / "r" / "0" / "stdout.log"
        assert out.read_text().strip() == "hello"
        # SUCCESS marker written
        assert (tmp_path / app_id / "SUCCESS").exists()

    def test_submit_failure_kills_gang(self, sched, tmp_path):
        app = AppDef(
            name="fail",
            roles=[
                sh_role("bad", "exit 3"),
                sh_role("slow", "sleep 30"),
            ],
        )
        app_id = sched.submit(app, {"log_dir": str(tmp_path)})
        state = wait_terminal(sched, app_id, timeout=20)
        assert state == AppState.FAILED
        # gang fail-fast: the sleeper must not still be running
        desc = sched.describe(app_id)
        slow = [rs for rs in desc.roles_statuses if rs.role == "slow"][0]
        assert all(r.state != AppState.RUNNING for r in slow.replicas)

    def test_macro_substitution(self, sched, tmp_path):
        app = AppDef(
            name="macro",
            roles=[
                sh_role(
                    "m",
                    f"echo replica={macros.replica_id} app={macros.app_id}",
                    num_replicas=2,
                )
            ],
        )
        app_id = sched.submit(app, {"log_dir": str(tmp_path)})
        wait_terminal(sched, app_id)
        out0 = (tmp_path / app_id / "m" / "0" / "stdout.log").read_text()
        out1 = (tmp_path / app_id / "m" / "1" / "stdout.log").read_text()
        assert f"replica=0 app={app_id}" in out0
        assert f"replica=1 app={app_id}" in out1

    def test_gang_env_injection(self, sched, tmp_path):
        app = AppDef(
            name="env",
            roles=[sh_role("e", "echo $TPX_REPLICA_ID/$TPX_NUM_REPLICAS-$TPX_COORDINATOR_HOST", num_replicas=2)],
        )
        app_id = sched.submit(app, {"log_dir": str(tmp_path)})
        wait_terminal(sched, app_id)
        assert (tmp_path / app_id / "e" / "1" / "stdout.log").read_text().strip() == (
            "1/2-localhost"
        )

    def test_tpu_role_expands_to_hosts(self, sched, tmp_path):
        # v5p-32 = 16 chips = 4 hosts -> 4 replicas
        role = sh_role("t", "echo $TPX_NUM_REPLICAS")
        role.resource = Resource(cpu=1, memMB=512, tpu=TpuSlice("v5p", 16))
        app = AppDef(name="tpu", roles=[role])
        info = sched.submit_dryrun(app, {"log_dir": str(tmp_path)})
        assert len(info.request.role_params["t"]) == 4
        env = info.request.role_params["t"][0].env
        assert env["TPX_NUM_REPLICAS"] == "4"
        assert env["TPX_TPU_ACCELERATOR_TYPE"] == "v5p-32"
        # no local chips in CI: simulation env is set
        assert env.get("JAX_PLATFORMS") == "cpu"
        assert "xla_force_host_platform_device_count=4" in env.get("XLA_FLAGS", "")

    def test_multislice_megascale_env(self, sched, tmp_path):
        role = sh_role("t", "true")
        role.resource = Resource(cpu=1, memMB=512, tpu=TpuSlice("v5e", 8))
        role.num_replicas = 2  # 2 slices x 1 host
        app = AppDef(name="ms", roles=[role])
        info = sched.submit_dryrun(app, {"log_dir": str(tmp_path)})
        params = info.request.role_params["t"]
        assert len(params) == 2
        assert params[0].env["MEGASCALE_NUM_SLICES"] == "2"
        assert params[0].env["MEGASCALE_SLICE_ID"] == "0"
        assert params[1].env["MEGASCALE_SLICE_ID"] == "1"

    def test_cancel(self, sched, tmp_path):
        app = AppDef(name="c", roles=[sh_role("s", "sleep 60")])
        app_id = sched.submit(app, {"log_dir": str(tmp_path)})
        time.sleep(0.2)
        sched.cancel(app_id)
        assert wait_terminal(sched, app_id) == AppState.CANCELLED

    def test_error_file_surfaced(self, sched, tmp_path):
        script = (
            'mkdir -p "$(dirname $TPX_ERROR_FILE)"; '
            'echo \'{"message": {"message": "kaboom", "extraInfo": {}}, "exitcode": 5, "hostname": "h"}\' > $TPX_ERROR_FILE; '
            "exit 5"
        )
        app = AppDef(name="err", roles=[sh_role("e", script)])
        app_id = sched.submit(app, {"log_dir": str(tmp_path)})
        assert wait_terminal(sched, app_id) == AppState.FAILED
        desc = sched.describe(app_id)
        assert "kaboom" in desc.structured_error_msg

    def test_log_iter(self, sched, tmp_path):
        app = AppDef(name="logs", roles=[sh_role("l", "echo a; echo b; echo c")])
        app_id = sched.submit(app, {"log_dir": str(tmp_path)})
        wait_terminal(sched, app_id)
        lines = list(sched.log_iter(app_id, "l", 0, streams=Stream.STDOUT))
        assert lines == ["a", "b", "c"]

    def test_log_iter_tail(self, sched, tmp_path):
        app = AppDef(
            name="tail", roles=[sh_role("t", "echo first; sleep 0.8; echo last")]
        )
        app_id = sched.submit(app, {"log_dir": str(tmp_path)})
        lines = list(
            sched.log_iter(app_id, "t", 0, should_tail=True, streams=Stream.STDOUT)
        )
        assert lines == ["first", "last"]

    def test_log_iter_regex(self, sched, tmp_path):
        app = AppDef(name="re", roles=[sh_role("r", "echo keep; echo drop")])
        app_id = sched.submit(app, {"log_dir": str(tmp_path)})
        wait_terminal(sched, app_id)
        lines = list(
            sched.log_iter(app_id, "r", 0, regex="keep", streams=Stream.STDOUT)
        )
        assert lines == ["keep"]

    def test_list(self, sched, tmp_path):
        app = AppDef(name="lst", roles=[sh_role("x", "true")])
        app_id = sched.submit(app, {"log_dir": str(tmp_path)})
        wait_terminal(sched, app_id)
        listing = sched.list()
        assert any(a.app_id == app_id for a in listing)

    def test_lru_eviction(self, tmp_path):
        sched = LocalScheduler(session_name="lru", cache_size=2)
        try:
            ids = []
            for i in range(3):
                app = AppDef(name=f"a{i}", roles=[sh_role("r", "true")])
                app_id = sched.submit(app, {"log_dir": str(tmp_path)})
                wait_terminal(sched, app_id)
                ids.append(app_id)
            # evicted from the in-process cache, but still describable via
            # the on-disk state file (terminal state is authoritative)
            evicted = sched.describe(ids[0])
            assert evicted is not None and evicted.state == AppState.SUCCEEDED
            assert sched.describe(ids[2]) is not None
        finally:
            sched.close()

    def test_combined_stream(self, sched, tmp_path):
        app = AppDef(name="comb", roles=[sh_role("c", "echo out; echo err 1>&2")])
        app_id = sched.submit(app, {"log_dir": str(tmp_path)})
        wait_terminal(sched, app_id)
        time.sleep(0.3)  # allow tee to drain
        combined = (tmp_path / app_id / "c" / "0" / "combined.log").read_text()
        assert "out" in combined and "err" in combined
        # every tee'd line leads with an epoch stamp (what log windows use)
        from torchx_tpu.schedulers.api import parse_epoch_stamp

        for raw in combined.splitlines():
            ts, payload = parse_epoch_stamp(raw)
            assert ts is not None and payload in ("out", "err")

    def test_log_windows_on_combined(self, sched, tmp_path):
        app = AppDef(name="win", roles=[sh_role("w", "echo early; echo late")])
        app_id = sched.submit(app, {"log_dir": str(tmp_path)})
        wait_terminal(sched, app_id)
        time.sleep(0.3)  # allow tee to drain
        now = time.time()
        # stamps are stripped from the default (combined) stream
        lines = list(sched.log_iter(app_id, "w", 0))
        assert lines == ["early", "late"]
        # a window entirely in the past excludes everything
        assert list(sched.log_iter(app_id, "w", 0, until=now - 3600)) == []
        # a window entirely in the future excludes everything
        assert list(sched.log_iter(app_id, "w", 0, since=now + 3600)) == []
        # a window spanning now includes everything
        assert (
            list(sched.log_iter(app_id, "w", 0, since=now - 3600, until=now + 60))
            == ["early", "late"]
        )

    def test_dir_image_provider(self, tmp_path):
        img = tmp_path / "img"
        img.mkdir()
        (img / "hello.sh").write_text("#!/bin/sh\necho from-image\n")
        os.chmod(img / "hello.sh", 0o755)
        sched = LocalScheduler(
            session_name="dir", image_provider=LocalDirectoryImageProvider()
        )
        try:
            app = AppDef(
                name="img",
                roles=[
                    Role(name="r", image=str(img), entrypoint="hello.sh", args=[])
                ],
            )
            app_id = sched.submit(app, {"log_dir": str(tmp_path / "logs")})
            assert wait_terminal(sched, app_id) == AppState.SUCCEEDED
            out = tmp_path / "logs" / app_id / "r" / "0" / "stdout.log"
            assert out.read_text().strip() == "from-image"
        finally:
            sched.close()

    def test_dir_image_provider_rejects_missing(self):
        with pytest.raises(ValueError):
            LocalDirectoryImageProvider().fetch("/definitely/not/a/dir")


class TestCrossProcessState:
    def test_second_scheduler_reads_terminal_state(self, tmp_path, monkeypatch):
        monkeypatch.setattr(
            "torchx_tpu.schedulers.local_scheduler._registry_path",
            lambda: str(tmp_path / "registry"),
        )
        owner = LocalScheduler(session_name="owner")
        try:
            app = AppDef(name="xp", roles=[sh_role("r", "echo cross-process")])
            app_id = owner.submit(app, {"log_dir": str(tmp_path)})
            wait_terminal(owner, app_id)
        finally:
            owner.close()
        # a different scheduler instance (≈ another CLI process)
        other = LocalScheduler(session_name="other")
        try:
            desc = other.describe(app_id)
            assert desc is not None and desc.state == AppState.SUCCEEDED
            lines = list(other.log_iter(app_id, "r", 0, streams=Stream.STDOUT))
            assert lines == ["cross-process"]
        finally:
            other.close()

    def test_orphaned_running_state_reports_unknown(self, tmp_path, monkeypatch):
        import json

        monkeypatch.setattr(
            "torchx_tpu.schedulers.local_scheduler._registry_path",
            lambda: str(tmp_path / "registry"),
        )
        # forge a state file whose owner died mid-run (pid 1 is not ours;
        # use an impossible pid)
        log_dir = tmp_path / "ghost-app"
        log_dir.mkdir()
        (log_dir / ".tpx_state.json").write_text(
            json.dumps(
                {
                    "app_id": "ghost-app",
                    "state": "RUNNING",
                    "log_dir": str(log_dir),
                    "roles": {"r": [{"id": 0, "pid": 2**22 + 12345}]},
                }
            )
        )
        (tmp_path / "registry").write_text(f"ghost-app = {log_dir}\n")
        sched = LocalScheduler(session_name="reader")
        try:
            desc = sched.describe("ghost-app")
            assert desc is not None and desc.state == AppState.UNKNOWN
        finally:
            sched.close()


class TestCrossProcessCancelList:
    def test_cancel_from_other_process(self, tmp_path, monkeypatch):
        monkeypatch.setattr(
            "torchx_tpu.schedulers.local_scheduler._registry_path",
            lambda: str(tmp_path / "registry"),
        )
        owner = LocalScheduler(session_name="owner")
        other = LocalScheduler(session_name="other")
        try:
            app = AppDef(name="xc", roles=[sh_role("r", "sleep 60")])
            app_id = owner.submit(app, {"log_dir": str(tmp_path)})
            time.sleep(0.3)
            # cancel from the NON-owning scheduler
            other.cancel(app_id)
            desc = other.describe(app_id)
            assert desc.state == AppState.CANCELLED
            # the owner honors the on-disk CANCELLED mark rather than
            # recording its SIGTERM'd children as a failure
            assert wait_terminal(owner, app_id, timeout=15) == AppState.CANCELLED
        finally:
            owner.close()
            other.close()

    def test_list_includes_external(self, tmp_path, monkeypatch):
        monkeypatch.setattr(
            "torchx_tpu.schedulers.local_scheduler._registry_path",
            lambda: str(tmp_path / "registry"),
        )
        owner = LocalScheduler(session_name="owner")
        try:
            app = AppDef(name="xl", roles=[sh_role("r", "true")])
            app_id = owner.submit(app, {"log_dir": str(tmp_path)})
            wait_terminal(owner, app_id)
        finally:
            owner.close()
        other = LocalScheduler(session_name="other")
        try:
            listing = other.list()
            assert any(a.app_id == app_id for a in listing)
        finally:
            other.close()


class TestTpuDeviceEnv:
    def test_partitioning(self):
        env = tpu_device_env(4, replica_id=1, replicas_on_host=2, host_chips=8, simulate=True)
        assert env["TPU_VISIBLE_CHIPS"] == "4,5,6,7"

    def test_single_replica_uses_all_chips(self):
        assert tpu_device_env(4, 0, replicas_on_host=1, host_chips=4, simulate=True) == {}

    def test_partition_disabled_on_real_host(self):
        env = tpu_device_env(4, 0, replicas_on_host=2, host_chips=4, simulate=True, partition=False)
        assert env == {}  # no CPU simulation forced on a host with chips

    def test_oversubscription_raises(self):
        import pytest as _pytest

        with _pytest.raises(ValueError):
            tpu_device_env(1, 5, replicas_on_host=8, host_chips=4, simulate=True)

    def test_simulation(self):
        env = tpu_device_env(4, 0, 1, host_chips=0, simulate=True)
        assert env["JAX_PLATFORMS"] == "cpu"
        assert "device_count=4" in env["XLA_FLAGS"]

    def test_no_sim_no_chips(self):
        assert tpu_device_env(4, 0, 1, host_chips=0, simulate=False) == {}


class TestManualResize:
    """Operator-driven `resize` (the manual counterpart of the elastic
    shrink-on-failure path): the role gang restarts with a coherent world
    and resumes from its checkpoint."""

    def resize_script(self, tmp_path) -> str:
        # each attempt logs its world, then waits long enough for the test
        # to resize mid-flight (the resized attempt exits promptly)
        return (
            f'echo "world=$TPX_NUM_REPLICAS id=$TPX_REPLICA_ID"; '
            f'if [ -f {tmp_path}/resized ]; then exit 0; fi; '
            "sleep 30"
        )

    def test_shrink_and_grow(self, sched, tmp_path):
        app = AppDef(
            name="manual",
            roles=[
                sh_role(
                    "w",
                    self.resize_script(tmp_path),
                    num_replicas=4,
                    min_replicas=2,
                )
            ],
        )
        app_id = sched.submit(app, {"log_dir": str(tmp_path)})
        # shrink 4 -> 2
        sched.resize(app_id, "w", 2)
        desc = sched.describe(app_id)
        (rs,) = desc.roles_statuses
        assert len(rs.replicas) == 2
        assert desc.num_restarts == 1
        # grow 2 -> 3 (local gangs can grow: they are just processes)
        (tmp_path / "resized").touch()
        sched.resize(app_id, "w", 3)
        assert wait_terminal(sched, app_id, timeout=30) == AppState.SUCCEEDED
        out0 = (tmp_path / app_id / "w" / "0" / "stdout.log").read_text()
        assert "world=3 id=0" in out0
        # both earlier attempts' logs were rotated aside
        assert (tmp_path / app_id / "w" / "0" / "stdout.log.0").exists()
        assert (tmp_path / app_id / "w" / "0" / "stdout.log.1").exists()

    def test_floor_enforced(self, sched, tmp_path):
        app = AppDef(
            name="floor",
            roles=[
                sh_role(
                    "w",
                    self.resize_script(tmp_path),
                    num_replicas=3,
                    min_replicas=2,
                )
            ],
        )
        app_id = sched.submit(app, {"log_dir": str(tmp_path)})
        with pytest.raises(ValueError, match="below its declared min_replicas"):
            sched.resize(app_id, "w", 1)
        sched.cancel(app_id)

    def test_tpu_role_resizes_in_slice_units(self, sched, tmp_path):
        script = (
            'echo "world=$TPX_NUM_REPLICAS slices=${MEGASCALE_NUM_SLICES:-none}"; '
            f'if [ -f {tmp_path}/resized ]; then exit 0; fi; sleep 30'
        )
        role = Role(
            name="w",
            image="",
            entrypoint="sh",
            args=["-c", script],
            num_replicas=3,  # slices of 2 hosts each
            min_replicas=1,
            resource=Resource(cpu=1, memMB=256, tpu=TpuSlice("v5p", 8)),
        )
        app_id = sched.submit(
            AppDef(name="tpu-resize", roles=[role]), {"log_dir": str(tmp_path)}
        )
        (tmp_path / "resized").touch()
        sched.resize(app_id, "w", 2)  # 3 slices -> 2 slices = 4 hosts
        desc = sched.describe(app_id)
        (rs,) = desc.roles_statuses
        assert len(rs.replicas) == 4
        assert wait_terminal(sched, app_id, timeout=30) == AppState.SUCCEEDED
        out0 = (tmp_path / app_id / "w" / "0" / "stdout.log").read_text()
        assert "world=4 slices=2" in out0

    def test_resize_unknown_app_or_role(self, sched, tmp_path):
        with pytest.raises(ValueError, match="unknown app"):
            sched.resize("ghost", "w", 2)
        app = AppDef(
            name="r", roles=[sh_role("w", "sleep 30", num_replicas=2)]
        )
        app_id = sched.submit(app, {"log_dir": str(tmp_path)})
        with pytest.raises(ValueError, match="has no role"):
            sched.resize(app_id, "ghost", 2)
        sched.cancel(app_id)

    def test_resize_terminal_app_raises(self, sched, tmp_path):
        app = AppDef(name="done", roles=[sh_role("w", "exit 0")])
        app_id = sched.submit(app, {"log_dir": str(tmp_path)})
        assert wait_terminal(sched, app_id, timeout=30) == AppState.SUCCEEDED
        with pytest.raises(ValueError, match="terminal"):
            sched.resize(app_id, "w", 2)

    def test_noop_resize_keeps_gang(self, sched, tmp_path):
        app = AppDef(
            name="noop", roles=[sh_role("w", "sleep 30", num_replicas=2)]
        )
        app_id = sched.submit(app, {"log_dir": str(tmp_path)})
        sched.resize(app_id, "w", 2)  # same size: no restart
        assert sched.describe(app_id).num_restarts == 0
        sched.cancel(app_id)
