"""Warm-launch fast-path tests: lazy CLI dispatch, the describe cache,
concurrent control-plane fan-out (list / logs / workspace builds), the
line-atomic log emitter, and the launch.breakdown span plumbing."""

import io
import json
import os
import subprocess
import sys
import threading
import time
from typing import Mapping, Optional

import pytest

from torchx_tpu.obs import metrics as obs_metrics
from torchx_tpu.runner.api import Runner, UnknownSchedulerError
from torchx_tpu.runner.describe_cache import DescribeCache, cache_ttl
from torchx_tpu.schedulers.api import DescribeAppResponse, ListAppResponse, Scheduler
from torchx_tpu.specs.api import (
    AppDef,
    AppDryRunInfo,
    AppState,
    CfgVal,
    Role,
    Workspace,
    runopts,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# =========================================================================
# Lazy CLI dispatch
# =========================================================================


def _probe_cli(argv: list[str], forbidden: list[str]) -> None:
    """Run ``main(argv)`` in a fresh interpreter and assert none of the
    ``forbidden`` modules were imported (the lazy-dispatch contract)."""
    code = f"""
import json, sys
from torchx_tpu.cli.main import main
try:
    main({argv!r})
except SystemExit:
    pass
print(json.dumps([m for m in {forbidden!r} if m in sys.modules]))
"""
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=120,
        cwd=REPO_ROOT,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr
    leaked = json.loads(proc.stdout.strip().splitlines()[-1])
    assert leaked == [], f"lazily-dispatched CLI imported {leaked}"


class TestLazyCli:
    HEAVY = [
        "jax",
        "numpy",
        "torchx_tpu.cli.cmd_run",
        "torchx_tpu.cli.cmd_lint",
        "torchx_tpu.examples.train_llama",
        "torchx_tpu.parallel.aot_fit",
    ]

    def test_help_imports_no_subcommand_modules(self):
        _probe_cli(["--help"], self.HEAVY)

    def test_list_never_imports_jax(self, tmp_path):
        code = """
import json, sys
from torchx_tpu.cli.main import main
try:
    main(["list", "-s", "local"])
except SystemExit:
    pass
print(json.dumps([m for m in ("jax", "torchx_tpu.cli.cmd_run") if m in sys.modules]))
"""
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=120,
            cwd=REPO_ROOT,
            env={**os.environ, "HOME": str(tmp_path), "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0, proc.stderr
        leaked = json.loads(proc.stdout.strip().splitlines()[-1])
        assert leaked == [], f"`tpx list` imported {leaked}"

    def test_peek_cmd(self):
        from torchx_tpu.cli.main import _peek_cmd

        assert _peek_cmd(["status", "x"]) == "status"
        assert _peek_cmd(["--log_level", "DEBUG", "list"]) == "list"
        assert _peek_cmd(["--log-level", "DEBUG", "list"]) == "list"
        assert _peek_cmd(["--log_level=DEBUG", "list"]) == "list"
        assert _peek_cmd(["--version"]) is None
        assert _peek_cmd([]) is None

    def test_create_parser_only_registers_one(self):
        from torchx_tpu.cli.main import create_parser

        parser = create_parser(only="status")
        args = parser.parse_args(["status", "local://s/app"])
        assert hasattr(args, "func")
        with pytest.raises(SystemExit):
            parser.parse_args(["list", "-s", "local"])

    def test_unknown_command_is_an_error(self):
        from torchx_tpu.cli.main import main

        with pytest.raises(SystemExit) as e:
            main(["definitely-not-a-command"])
        assert e.value.code not in (0, None)


# =========================================================================
# Describe cache
# =========================================================================


def _resp(state: AppState = AppState.RUNNING) -> DescribeAppResponse:
    return DescribeAppResponse(app_id="a1", state=state)


class TestDescribeCache:
    def test_ttl_shares_one_fetch(self):
        cache = DescribeCache(ttl=60.0)
        calls = []
        fetch = lambda: calls.append(1) or _resp()  # noqa: E731
        r1 = cache.get("stub", "a1", fetch)
        r2 = cache.get("stub", "a1", fetch)
        assert len(calls) == 1
        assert r1 is r2

    def test_fresh_bypasses_ttl(self):
        cache = DescribeCache(ttl=60.0)
        calls = []
        fetch = lambda: calls.append(1) or _resp()  # noqa: E731
        cache.get("stub", "a1", fetch)
        cache.get("stub", "a1", fetch, fresh=True)
        assert len(calls) == 2

    def test_terminal_state_pinned_even_for_fresh(self):
        cache = DescribeCache(ttl=0.0)
        calls = []
        fetch = lambda: calls.append(1) or _resp(AppState.SUCCEEDED)  # noqa: E731
        cache.get("stub", "a1", fetch)
        r = cache.get("stub", "a1", fetch, fresh=True)
        assert len(calls) == 1
        assert r.state == AppState.SUCCEEDED

    def test_zero_ttl_never_caches_nonterminal(self):
        cache = DescribeCache(ttl=0.0)
        calls = []
        fetch = lambda: calls.append(1) or _resp()  # noqa: E731
        cache.get("stub", "a1", fetch)
        cache.get("stub", "a1", fetch)
        assert len(calls) == 2

    def test_errors_never_cached(self):
        cache = DescribeCache(ttl=60.0)
        calls = []

        def boom():
            calls.append(1)
            raise RuntimeError("control plane down")

        with pytest.raises(RuntimeError):
            cache.get("stub", "a1", boom)
        ok = lambda: calls.append(1) or _resp()  # noqa: E731
        assert cache.get("stub", "a1", ok) is not None
        assert len(calls) == 2

    def test_none_drops_entry(self):
        cache = DescribeCache(ttl=60.0)
        assert cache.get("stub", "a1", lambda: None) is None
        calls = []
        cache.get("stub", "a1", lambda: calls.append(1) or _resp())
        assert len(calls) == 1  # nothing was cached for the None result

    def test_invalidate(self):
        cache = DescribeCache(ttl=60.0)
        calls = []
        fetch = lambda: calls.append(1) or _resp()  # noqa: E731
        cache.get("stub", "a1", fetch)
        cache.invalidate("stub", "a1")
        cache.get("stub", "a1", fetch)
        assert len(calls) == 2

    def test_concurrent_gets_coalesce_to_one_fetch(self):
        cache = DescribeCache(ttl=0.0)  # TTL off: coalescing does the work
        started = threading.Event()
        release = threading.Event()
        calls = []

        def slow_fetch():
            calls.append(1)
            started.set()
            assert release.wait(10)
            return _resp()

        results = []

        def get():
            results.append(cache.get("stub", "a1", slow_fetch, fresh=True))

        t1 = threading.Thread(target=get)
        t1.start()
        assert started.wait(10)
        t2 = threading.Thread(target=get)
        t2.start()
        time.sleep(0.05)  # let t2 reach the coalescing wait
        release.set()
        t1.join(10)
        t2.join(10)
        assert len(calls) == 1
        assert len(results) == 2
        assert all(r is not None and r.state == AppState.RUNNING for r in results)

    def test_cache_ttl_env_parsing(self, monkeypatch):
        from torchx_tpu import settings

        monkeypatch.delenv(settings.ENV_TPX_DESCRIBE_CACHE_TTL, raising=False)
        assert cache_ttl() == settings.DEFAULT_DESCRIBE_CACHE_TTL
        monkeypatch.setenv(settings.ENV_TPX_DESCRIBE_CACHE_TTL, "2.5")
        assert cache_ttl() == 2.5
        monkeypatch.setenv(settings.ENV_TPX_DESCRIBE_CACHE_TTL, "-1")
        assert cache_ttl() == 0.0
        monkeypatch.setenv(settings.ENV_TPX_DESCRIBE_CACHE_TTL, "nope")
        assert cache_ttl() == settings.DEFAULT_DESCRIBE_CACHE_TTL


# =========================================================================
# Runner integration: cache routing + fan-out
# =========================================================================


class CountingScheduler(Scheduler[dict]):
    """Stub backend that counts describe calls and supports logs."""

    def __init__(self, session_name: str, **kwargs):
        super().__init__("stub", session_name)
        self.apps: dict[str, AppState] = {}
        self.describe_calls = 0
        self.list_delay = 0.0
        self.log_lines_by_replica: dict[tuple[str, int], list[str]] = {}
        self._counter = 0

    def run_opts(self) -> runopts:
        return runopts()

    def _submit_dryrun(self, app: AppDef, cfg: Mapping[str, CfgVal]):
        return AppDryRunInfo({"app": app, "cfg": dict(cfg)})

    def schedule(self, dryrun_info) -> str:
        self._counter += 1
        app_id = f"stub_app_{self._counter}"
        self.apps[app_id] = AppState.RUNNING
        return app_id

    def describe(self, app_id: str) -> Optional[DescribeAppResponse]:
        self.describe_calls += 1
        if app_id not in self.apps:
            return None
        return DescribeAppResponse(app_id=app_id, state=self.apps[app_id])

    def _cancel_existing(self, app_id: str) -> None:
        self.apps[app_id] = AppState.CANCELLED

    def list(self):
        if self.list_delay:
            time.sleep(self.list_delay)
        return [ListAppResponse(app_id=a, state=s) for a, s in self.apps.items()]

    def log_iter(
        self,
        app_id,
        role_name,
        k=0,
        regex=None,
        since=None,
        until=None,
        should_tail=False,
        streams=None,
    ):
        lines = self.log_lines_by_replica.get((role_name, k))
        if lines is None:
            raise RuntimeError(f"no logs for {role_name}/{k}")
        for line in lines:
            time.sleep(0.001)
            yield line


def simple_app() -> AppDef:
    return AppDef(
        name="app",
        roles=[Role(name="r", image="i", entrypoint="echo", args=["hi"])],
    )


@pytest.fixture
def stub():
    return CountingScheduler("test")


@pytest.fixture
def runner(stub):
    r = Runner("test", {"stub": lambda session_name, **kw: stub})
    yield r
    r.close()


class TestRunnerCacheRouting:
    def test_status_polls_share_backend_call(self, runner, stub, monkeypatch):
        from torchx_tpu import settings

        monkeypatch.setenv(settings.ENV_TPX_DESCRIBE_CACHE_TTL, "60")
        handle = runner.run(simple_app(), "stub")
        base = stub.describe_calls
        h0 = obs_metrics.DESCRIBE_CACHE_HITS.value(scheduler="stub")
        m0 = obs_metrics.DESCRIBE_CACHE_MISSES.value(scheduler="stub")
        for _ in range(5):
            assert runner.status(handle).state == AppState.RUNNING
        assert stub.describe_calls == base + 1
        assert obs_metrics.DESCRIBE_CACHE_MISSES.value(scheduler="stub") == m0 + 1
        assert obs_metrics.DESCRIBE_CACHE_HITS.value(scheduler="stub") == h0 + 4

    def test_fresh_status_always_hits_backend(self, runner, stub, monkeypatch):
        from torchx_tpu import settings

        monkeypatch.setenv(settings.ENV_TPX_DESCRIBE_CACHE_TTL, "60")
        handle = runner.run(simple_app(), "stub")
        base = stub.describe_calls
        runner.status(handle, fresh=True)
        runner.status(handle, fresh=True)
        assert stub.describe_calls == base + 2

    def test_cancel_invalidates_cache(self, runner, stub, monkeypatch):
        from torchx_tpu import settings

        monkeypatch.setenv(settings.ENV_TPX_DESCRIBE_CACHE_TTL, "60")
        handle = runner.run(simple_app(), "stub")
        assert runner.status(handle).state == AppState.RUNNING
        runner.cancel(handle)
        # CANCELLED must be visible immediately despite the fat TTL
        assert runner.status(handle).state == AppState.CANCELLED

    def test_terminal_state_needs_no_backend_calls(self, runner, stub, monkeypatch):
        from torchx_tpu import settings

        monkeypatch.setenv(settings.ENV_TPX_DESCRIBE_CACHE_TTL, "0")
        handle = runner.run(simple_app(), "stub")
        app_id = handle.rsplit("/", 1)[-1]
        stub.apps[app_id] = AppState.SUCCEEDED
        runner.status(handle, fresh=True)
        base = stub.describe_calls
        for _ in range(3):
            assert runner.status(handle, fresh=True).state == AppState.SUCCEEDED
        assert stub.describe_calls == base


class TestListFanOut:
    def _runner(self, factories):
        return Runner("test", factories)

    def test_registry_order_regardless_of_completion(self):
        slow = CountingScheduler("test")
        slow.apps["slow_1"] = AppState.RUNNING
        slow.list_delay = 0.2
        fast = CountingScheduler("test")
        fast.apps["fast_1"] = AppState.SUCCEEDED
        r = self._runner(
            {
                "slow": lambda session_name, **kw: slow,
                "fast": lambda session_name, **kw: fast,
            }
        )
        try:
            results, errors = r.list_all()
        finally:
            r.close()
        assert errors == {}
        assert list(results) == ["slow", "fast"]  # registry order
        assert [a.app_id for a in results["slow"]] == ["slow_1"]
        assert [a.app_id for a in results["fast"]] == ["fast_1"]

    def test_one_broken_backend_does_not_hide_others(self):
        ok = CountingScheduler("test")
        ok.apps["ok_1"] = AppState.RUNNING

        class Broken(CountingScheduler):
            def list(self):
                raise RuntimeError("unreachable control plane")

        r = self._runner(
            {
                "broken": lambda session_name, **kw: Broken("test"),
                "ok": lambda session_name, **kw: ok,
            }
        )
        try:
            results, errors = r.list_all()
        finally:
            r.close()
        assert [a.app_id for a in results["ok"]] == ["ok_1"]
        assert "broken" in errors
        assert "unreachable" in str(errors["broken"])

    def test_unknown_scheduler_rejected(self, runner):
        with pytest.raises(UnknownSchedulerError):
            runner.list_all(schedulers=["nope"])

    def test_fanout_is_concurrent(self):
        barrier = threading.Barrier(2, timeout=10)

        class Meeting(CountingScheduler):
            def list(self):
                barrier.wait()  # deadlocks unless both lists run at once
                return super().list()

        r = self._runner(
            {
                "a": lambda session_name, **kw: Meeting("test"),
                "b": lambda session_name, **kw: Meeting("test"),
            }
        )
        try:
            results, errors = r.list_all()
        finally:
            r.close()
        assert errors == {}
        assert list(results) == ["a", "b"]


class TestLogMerge:
    def test_per_replica_order_preserved(self, runner, stub):
        handle = runner.run(simple_app(), "stub")
        stub.log_lines_by_replica = {
            ("r", 0): [f"r0 line {i}\n" for i in range(20)],
            ("r", 1): [f"r1 line {i}\n" for i in range(20)],
        }
        got = list(runner.log_lines_multi(handle, {"r": [0, 1]}))
        by_replica: dict[int, list[str]] = {0: [], 1: []}
        for role, rid, line in got:
            assert role == "r"
            assert not line.endswith("\n")
            by_replica[rid].append(line)
        assert by_replica[0] == [f"r0 line {i}" for i in range(20)]
        assert by_replica[1] == [f"r1 line {i}" for i in range(20)]

    def test_stream_error_is_isolated(self, runner, stub):
        handle = runner.run(simple_app(), "stub")
        stub.log_lines_by_replica = {("r", 0): ["ok\n"]}  # replica 1 missing
        got = list(runner.log_lines_multi(handle, {"r": [0, 1]}))
        lines = {(rid, line) for _, rid, line in got}
        assert (0, "ok") in lines
        assert any(rid == 1 and "log stream error" in line for rid, line in lines)

    def test_empty_replicas(self, runner, stub):
        handle = runner.run(simple_app(), "stub")
        assert list(runner.log_lines_multi(handle, {})) == []


# =========================================================================
# Parallel workspace builds
# =========================================================================


class BarrierWorkspace:
    """Mixin host whose builds must overlap to pass the barrier."""

    from torchx_tpu.workspace.api import WorkspaceMixin

    class Impl(WorkspaceMixin[dict]):
        def __init__(self, barrier=None):
            self.barrier = barrier
            self.builds: list[str] = []

        def build_workspace_and_update_role(self, role, workspace, cfg):
            if self.barrier is not None:
                self.barrier.wait()
            self.builds.append(role.image)
            role.image = f"built-{role.image}"


def _role(name: str, image: str, projects: dict) -> Role:
    return Role(
        name=name,
        image=image,
        entrypoint="echo",
        workspace=Workspace(projects=projects),
    )


class TestParallelWorkspaceBuilds:
    def test_distinct_keys_build_concurrently(self):
        barrier = threading.Barrier(2, timeout=10)
        ws = BarrierWorkspace.Impl(barrier)
        roles = [
            _role("a", "img-a", {"./src": "src"}),
            _role("b", "img-b", {"./src": "src"}),
        ]
        ws.build_workspaces(roles, {})  # serial builds would deadlock here
        assert roles[0].image == "built-img-a"
        assert roles[1].image == "built-img-b"

    def test_shared_key_builds_once(self):
        ws = BarrierWorkspace.Impl()
        roles = [
            _role("a", "img", {"./src": "src"}),
            _role("b", "img", {"./src": "src"}),
            _role("c", "other", {"./src": "src"}),
        ]
        ws.build_workspaces(roles, {})
        assert sorted(ws.builds) == ["img", "other"]  # one build per key
        assert roles[0].image == "built-img"
        assert roles[1].image == "built-img"  # cached result, same key
        assert roles[2].image == "built-other"

    def test_roles_without_workspace_untouched(self):
        ws = BarrierWorkspace.Impl()
        plain = Role(name="p", image="img", entrypoint="echo")
        ws.build_workspaces([plain], {})
        assert plain.image == "img"
        assert ws.builds == []

    def test_build_error_propagates(self):
        class Exploding(BarrierWorkspace.Impl):
            def build_workspace_and_update_role(self, role, workspace, cfg):
                raise RuntimeError("docker build failed")

        ws = Exploding()
        roles = [
            _role("a", "img-a", {"./src": "src"}),
            _role("b", "img-b", {"./src": "src"}),
        ]
        with pytest.raises(RuntimeError, match="docker build failed"):
            ws.build_workspaces(roles, {})


# =========================================================================
# Line-atomic log emitter
# =========================================================================


class TestLineEmitter:
    def test_concurrent_emits_never_tear_lines(self):
        from torchx_tpu.util.log_tee_helpers import LineEmitter

        out = io.StringIO()
        emitter = LineEmitter(out)
        n, writers = 200, 8

        def spam(tag: str):
            for i in range(n):
                emitter.emit(f"[{tag}]", f"line {i}")

        threads = [
            threading.Thread(target=spam, args=(f"w{w}",)) for w in range(writers)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        lines = out.getvalue().splitlines()
        assert len(lines) == n * writers
        for line in lines:
            assert line.startswith("[w") and "] line " in line, line

    def test_strips_trailing_newline(self):
        from torchx_tpu.util.log_tee_helpers import LineEmitter

        out = io.StringIO()
        LineEmitter(out).emit("p", "hello\n")
        assert out.getvalue() == "p hello\n"

    def test_no_prefix(self):
        from torchx_tpu.util.log_tee_helpers import LineEmitter

        out = io.StringIO()
        LineEmitter(out).emit("", "bare")
        assert out.getvalue() == "bare\n"


# =========================================================================
# Launch breakdown plumbing
# =========================================================================


class TestLaunchBreakdown:
    def test_launch_span_noop_without_trace_id(self, monkeypatch):
        from torchx_tpu import settings
        from torchx_tpu.examples.train_llama import _launch_span
        from torchx_tpu.obs import sinks

        monkeypatch.delenv(settings.ENV_TPX_TRACE_ID, raising=False)
        with _launch_span("launch.test_stage"):
            pass
        assert not os.path.exists(sinks.trace_path())

    def test_launch_span_written_under_trace_id(self, monkeypatch):
        from torchx_tpu import settings
        from torchx_tpu.examples.train_llama import _launch_span
        from torchx_tpu.obs import sinks
        from torchx_tpu.obs import trace as obs_trace

        monkeypatch.setenv(settings.ENV_TPX_TRACE_ID, obs_trace.new_trace_id())
        with _launch_span("launch.test_stage", step=7):
            pass
        with open(sinks.trace_path()) as f:
            spans = [json.loads(line) for line in f if line.strip()]
        names = [s.get("name") for s in spans]
        assert "launch.test_stage" in names

    def test_launch_stage_histogram_registered(self):
        before_n = obs_metrics.LAUNCH_STAGE_SECONDS.count(stage="unit_test")
        before_s = obs_metrics.LAUNCH_STAGE_SECONDS.sum(stage="unit_test")
        obs_metrics.LAUNCH_STAGE_SECONDS.observe(1.25, stage="unit_test")
        assert obs_metrics.LAUNCH_STAGE_SECONDS.count(stage="unit_test") == before_n + 1
        assert obs_metrics.LAUNCH_STAGE_SECONDS.sum(stage="unit_test") == pytest.approx(
            before_s + 1.25
        )
