"""Runner tests with a stub scheduler (reference analog:
torchx/runner/test/api_test.py) plus a real local-scheduler e2e."""

import threading
from typing import Mapping, Optional

import pytest

from torchx_tpu.runner.api import Runner, get_runner
from torchx_tpu.schedulers.api import DescribeAppResponse, ListAppResponse, Scheduler
from torchx_tpu.specs.api import (
    AppDef,
    AppDryRunInfo,
    AppState,
    CfgVal,
    Role,
    runopts,
)


class StubScheduler(Scheduler[dict]):
    def __init__(self, session_name: str, **kwargs):
        super().__init__("stub", session_name)
        self.apps: dict[str, AppState] = {}
        self.cancelled: list[str] = []
        self._counter = 0

    def run_opts(self) -> runopts:
        opts = runopts()
        opts.add("knob", type_=str, help="a knob", default="k0")
        return opts

    def _submit_dryrun(self, app: AppDef, cfg: Mapping[str, CfgVal]):
        return AppDryRunInfo({"app": app, "cfg": dict(cfg)})

    def schedule(self, dryrun_info) -> str:
        self._counter += 1
        app_id = f"stub_app_{self._counter}"
        self.apps[app_id] = AppState.RUNNING
        return app_id

    def describe(self, app_id: str) -> Optional[DescribeAppResponse]:
        if app_id not in self.apps:
            return None
        return DescribeAppResponse(app_id=app_id, state=self.apps[app_id])

    def _cancel_existing(self, app_id: str) -> None:
        self.apps[app_id] = AppState.CANCELLED
        self.cancelled.append(app_id)

    def list(self):
        return [ListAppResponse(app_id=a, state=s) for a, s in self.apps.items()]


@pytest.fixture
def runner():
    stub = StubScheduler("test")
    r = Runner("test", {"stub": lambda session_name, **kw: stub})
    yield r
    r.close()


def simple_app(**role_kwargs) -> AppDef:
    defaults = dict(name="r", image="i", entrypoint="echo", args=["hi"])
    defaults.update(role_kwargs)
    return AppDef(name="app", roles=[Role(**defaults)])


class TestRunner:
    def test_run_and_status(self, runner):
        handle = runner.run(simple_app(), "stub")
        assert handle.startswith("stub://test/")
        status = runner.status(handle)
        assert status.state == AppState.RUNNING

    def test_dryrun_resolves_cfg(self, runner):
        info = runner.dryrun(simple_app(), "stub", {"knob": "custom"})
        assert info.request["cfg"]["knob"] == "custom"
        info = runner.dryrun(simple_app(), "stub")
        assert info.request["cfg"]["knob"] == "k0"

    def test_dryrun_validation(self, runner):
        with pytest.raises(ValueError):
            runner.dryrun(AppDef(name="empty"), "stub")
        with pytest.raises(ValueError):
            runner.dryrun(simple_app(entrypoint=""), "stub")
        with pytest.raises(ValueError):
            runner.dryrun(simple_app(num_replicas=0), "stub")
        with pytest.raises(ValueError):
            runner.dryrun(simple_app(min_replicas=5, num_replicas=2), "stub")

    def test_schedule_requires_runner_dryrun(self, runner):
        with pytest.raises(ValueError):
            runner.schedule(AppDryRunInfo({"raw": True}))

    def test_cancel(self, runner):
        handle = runner.run(simple_app(), "stub")
        runner.cancel(handle)
        assert runner.status(handle).state == AppState.CANCELLED

    def test_resize_routes_to_scheduler(self, runner):
        handle = runner.run(simple_app(), "stub")
        # the stub does not implement resize: the optional-capability
        # default must raise a clear NotImplementedError
        with pytest.raises(NotImplementedError, match="does not support resizing"):
            runner.resize(handle, "r", 2)

    def test_status_unknown_app(self, runner):
        assert runner.status("stub://test/ghost") is None

    def test_unknown_scheduler(self, runner):
        with pytest.raises(KeyError):
            runner.run(simple_app(), "nope")

    def test_list(self, runner):
        runner.run(simple_app(), "stub")
        assert len(runner.list("stub")) == 1

    def test_wait_terminal(self, runner):
        handle = runner.run(simple_app(), "stub")
        _, _, app_id = handle.partition("//")[0], None, handle.rsplit("/", 1)[-1]

        def finish():
            sched = runner._scheduler("stub")
            sched.apps[app_id] = AppState.SUCCEEDED

        t = threading.Timer(0.3, finish)
        t.start()
        status = runner.wait(handle, wait_interval=0.05)
        assert status.state == AppState.SUCCEEDED

    def test_run_component_via_stub(self, runner):
        handle = runner.run_component(
            "utils.echo", ["--msg", "yo"], "stub"
        )
        assert handle.startswith("stub://")

    def test_dryrun_does_not_mutate_caller_app(self, runner):
        app = simple_app()
        runner.dryrun(app, "stub", workspace=None)
        assert app.roles[0].env == {}
        before = app.roles[0].image
        runner.dryrun(app, "stub")
        assert app.roles[0].image == before

    def test_component_defaults_applied(self):
        stub = StubScheduler("test")
        r = Runner(
            "test",
            {"stub": lambda session_name, **kw: stub},
            component_defaults={"utils.echo": {"msg": "default-msg"}},
        )
        info = r.dryrun_component("utils.echo", [], "stub")
        assert info.request["app"].roles[0].args == ["default-msg"]


class TestGetRunner:
    def test_get_runner_has_registered_backends(self):
        from torchx_tpu.schedulers import DEFAULT_SCHEDULER_MODULES

        with get_runner() as runner:
            backends = runner.scheduler_backends()
            for expected in DEFAULT_SCHEDULER_MODULES:
                assert expected in backends

    def test_env_param_harvest(self, monkeypatch):
        monkeypatch.setenv("TPX_PARAMS_CACHE_SIZE", "5")
        with get_runner() as runner:
            assert runner._scheduler_params.get("cache_size") == "5"


class TestRunnerLocalE2E:
    def test_echo_end_to_end(self, tmp_path):
        with get_runner("e2e") as runner:
            handle = runner.run_component(
                "utils.echo",
                ["--msg", "runner-e2e"],
                "local",
                {"log_dir": str(tmp_path)},
            )
            status = runner.wait(handle, wait_interval=0.1)
            assert status.state == AppState.SUCCEEDED
            lines = list(runner.log_lines(handle, "echo", 0))
            assert "runner-e2e" in lines


class FlakySequenceScheduler(Scheduler[dict]):
    """``describe()`` follows a script mixing exceptions and states — for
    the consecutive-miss-reset contract of ``Runner.wait``."""

    def __init__(self, session_name: str, script=None, **kwargs):
        super().__init__("flaky", session_name)
        self.script = list(script or [])

    def run_opts(self) -> runopts:
        return runopts()

    def _submit_dryrun(self, app: AppDef, cfg: Mapping[str, CfgVal]):
        return AppDryRunInfo({"app": app})

    def schedule(self, dryrun_info) -> str:
        return "job_1"

    def describe(self, app_id: str) -> Optional[DescribeAppResponse]:
        item = self.script.pop(0) if self.script else AppState.SUCCEEDED
        if isinstance(item, BaseException):
            raise item
        return DescribeAppResponse(app_id=app_id, state=item)

    def _cancel_existing(self, app_id: str) -> None:
        pass


def _flaky_wait(script, budget):
    sched = FlakySequenceScheduler("w", script=script)
    r = Runner("w", {"flaky": lambda session_name, **kw: sched})
    with r:
        status = r.wait(
            "flaky://w/job_1",
            wait_interval=0.01,
            sleep=lambda s: None,
            poll_miss_budget=budget,
        )
    return status, sched


class TestWaitMissReset:
    def test_success_resets_consecutive_miss_counter(self):
        """miss -> success -> miss -> success with budget=1: each miss is
        the FIRST of its streak, so a week-long wait can absorb any number
        of isolated blips (a cumulative counter would raise on blip 2)."""
        status, sched = _flaky_wait(
            [
                ConnectionError("blip 1"),
                AppState.RUNNING,
                ConnectionError("blip 2"),
                AppState.SUCCEEDED,
            ],
            budget=1,
        )
        assert status.state == AppState.SUCCEEDED
        assert not sched.script  # every scripted poll was consumed

    def test_consecutive_misses_still_exhaust_the_budget(self):
        """Control for the reset: two misses in a row DO exceed budget=1."""
        with pytest.raises(ConnectionError, match="back-to-back"):
            _flaky_wait(
                [
                    ConnectionError("blip"),
                    ConnectionError("back-to-back"),
                    AppState.SUCCEEDED,
                ],
                budget=1,
            )


class ResizableStubScheduler(StubScheduler):
    """Stub with resize support, counting backend describes so tests can
    observe the describe cache being (in)validated."""

    def __init__(self, session_name: str, **kwargs):
        super().__init__(session_name, **kwargs)
        self.describe_calls = 0
        self.resized: list[tuple[str, str, int]] = []

    def describe(self, app_id: str):
        self.describe_calls += 1
        return super().describe(app_id)

    def resize(self, app_id: str, role_name: str, num_replicas: int) -> None:
        if self.apps.get(app_id) in (AppState.CANCELLED, AppState.SUCCEEDED):
            raise ValueError(f"cannot resize terminal app {app_id}")
        self.resized.append((app_id, role_name, num_replicas))


class TestRunnerResize:
    """Satellite coverage for Runner.resize: ledger + cache + error path."""

    @pytest.fixture
    def rig(self, monkeypatch):
        monkeypatch.setenv("TPX_DESCRIBE_CACHE_TTL", "300")
        stub = ResizableStubScheduler("test")
        r = Runner("test", {"stub": lambda session_name, **kw: stub})
        yield r, stub
        r.close()

    def test_resize_invalidates_describe_cache(self, rig):
        runner, stub = rig
        handle = runner.run(simple_app(), "stub")
        assert runner.status(handle).state == AppState.RUNNING
        calls = stub.describe_calls
        runner.status(handle)  # within TTL: served from cache
        assert stub.describe_calls == calls
        runner.resize(handle, "r", 3)
        assert stub.resized[-1][1:] == ("r", 3)
        runner.status(handle)  # resize invalidated: backend re-fetched
        assert stub.describe_calls == calls + 1

    def test_resize_terminal_app_raises(self, rig):
        runner, stub = rig
        handle = runner.run(simple_app(), "stub")
        runner.cancel(handle)
        with pytest.raises(ValueError, match="terminal"):
            runner.resize(handle, "r", 2)

    def test_resize_is_ledgered(self, rig):
        runner, stub = rig
        from torchx_tpu.obs import sinks, timeline

        handle = runner.run(simple_app(), "stub")
        runner.resize(handle, "r", 2)
        records = timeline.load_records(sinks.trace_path())
        apis = [rec.get("api") for rec in records if rec.get("api")]
        assert "resize" in apis
