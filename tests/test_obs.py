"""Observability subsystem tests: span model, metrics registry, durable
sinks, destination plumbing, timeline reconstruction, and the acceptance
scenario — a supervised run with an injected preemption producing ONE
trace with nested spans for both attempts, rendered by ``tpx trace``."""

import json
import logging
import os
from typing import Mapping, Optional

import pytest

from torchx_tpu.obs import metrics as obs_metrics
from torchx_tpu.obs import sinks, timeline
from torchx_tpu.obs import trace as obs_trace
from torchx_tpu.obs.metrics import Counter, Gauge, Histogram, MetricsRegistry
from torchx_tpu.obs.trace import Span
from torchx_tpu.runner.api import Runner
from torchx_tpu.runner.events import record
from torchx_tpu.runner.events.api import TpxEvent
from torchx_tpu.schedulers.api import DescribeAppResponse, Scheduler
from torchx_tpu import settings
from torchx_tpu.settings import (
    ENV_TPX_METRICS_MIN_INTERVAL,
    ENV_TPX_PARENT_SPAN,
    ENV_TPX_SIMULATE_PREEMPTION_EXIT,
    ENV_TPX_TRACE,
    ENV_TPX_TRACE_ID,
)
from torchx_tpu.specs.api import (
    AppDef,
    AppState,
    CfgVal,
    FailureClass,
    Role,
    runopts,
)
from torchx_tpu.supervisor import SupervisorPolicy


# -- span model ------------------------------------------------------------


class TestSpans:
    def test_nesting_via_contextvar(self):
        with obs_trace.span("outer") as outer:
            assert obs_trace.current_span() is outer
            with obs_trace.span("inner") as inner:
                assert inner.trace_id == outer.trace_id
                assert inner.parent_span_id == outer.span_id
            assert obs_trace.current_span() is outer
        assert obs_trace.current_span() is None
        assert outer.parent_span_id is None
        assert outer.duration_usec() is not None

    def test_error_status_on_exception(self):
        with pytest.raises(RuntimeError):
            with obs_trace.span("boom") as sp:
                raise RuntimeError("kapow")
        assert sp.status == "ERROR"
        assert "kapow" in sp.attrs["exception"]

    def test_serialize_round_trip_and_unknown_fields_dropped(self):
        with obs_trace.span("op", scheduler="local") as sp:
            pass
        obj = json.loads(sp.serialize())
        assert obj["kind"] == "span"
        obj["fancy_new_field"] = {"from": "the future"}
        restored = Span.deserialize(json.dumps(obj))
        assert restored.span_id == sp.span_id
        assert restored.attrs == {"scheduler": "local"}
        assert not hasattr(restored, "fancy_new_field")

    def test_root_joins_env_trace(self, monkeypatch):
        monkeypatch.setenv(ENV_TPX_TRACE_ID, "f" * 32)
        monkeypatch.setenv(ENV_TPX_PARENT_SPAN, "a" * 16)
        with obs_trace.span("in_job") as sp:
            assert sp.trace_id == "f" * 32
            assert sp.parent_span_id == "a" * 16

    def test_inject_env_setdefault_vs_force(self, monkeypatch):
        with obs_trace.span("client") as sp:
            env = {ENV_TPX_TRACE_ID: "0" * 32, ENV_TPX_PARENT_SPAN: "old"}
            obs_trace.inject_env(env)
            assert env[ENV_TPX_TRACE_ID] == "0" * 32  # inherited id kept
            assert env[ENV_TPX_PARENT_SPAN] == sp.span_id  # parent refreshed
            obs_trace.inject_env(env, force=True)
            assert env[ENV_TPX_TRACE_ID] == sp.trace_id

    def test_disabled_tracing_is_a_noop(self, monkeypatch):
        monkeypatch.setenv(ENV_TPX_TRACE, "0")
        with obs_trace.span("off") as sp:
            assert sp is None
        assert not os.path.exists(sinks.trace_path())
        assert sinks.flush_metrics() is None
        env: dict = {}
        obs_trace.inject_env(env)
        assert env == {}


class TestEventForwardCompat:
    def test_deserialize_drops_unknown_fields(self):
        ev = TpxEvent(session="s", scheduler="local", api="run", app_id="a1")
        obj = json.loads(ev.serialize())
        obj["brand_new_field"] = 42
        restored = TpxEvent.deserialize(json.dumps(obj))
        assert restored == ev


# -- metrics registry ------------------------------------------------------


class TestMetrics:
    def test_counter(self):
        c = Counter("t_c", "h", ("k",))
        c.inc(k="a")
        c.inc(2, k="a")
        assert c.value(k="a") == 3
        assert c.value(k="b") == 0
        with pytest.raises(ValueError, match="cannot decrease"):
            c.inc(-1, k="a")
        with pytest.raises(ValueError, match="takes labels"):
            c.inc(wrong="a")
        assert c.render() == ['t_c{k="a"} 3']

    def test_gauge(self):
        g = Gauge("t_g", "h")
        g.set(1.5)
        assert g.value() == 1.5
        g.set(0.5)
        assert g.render() == ["t_g 0.5"]

    def test_histogram_cumulative_buckets(self):
        h = Histogram("t_h", "h", buckets=(1.0, 5.0))
        for v in (0.5, 0.7, 3.0, 100.0):
            h.observe(v)
        assert h.count() == 4
        assert h.sum() == pytest.approx(104.2)
        assert h.render() == [
            't_h_bucket{le="1"} 2',
            't_h_bucket{le="5"} 3',
            't_h_bucket{le="+Inf"} 4',
            "t_h_sum 104.2",
            "t_h_count 4",
        ]

    def test_registry_get_or_create_and_kind_clash(self):
        reg = MetricsRegistry()
        c1 = reg.counter("x", "h")
        assert reg.counter("x", "h") is c1
        assert reg.get("x") is c1
        with pytest.raises(ValueError, match="already registered"):
            reg.gauge("x", "h")

    def test_render_documents_empty_instruments(self):
        reg = MetricsRegistry()
        reg.histogram("quiet_seconds", "never observed")
        text = reg.render()
        assert "# HELP quiet_seconds never observed" in text
        assert "# TYPE quiet_seconds histogram" in text


# -- destinations ----------------------------------------------------------


@pytest.fixture
def clean_destinations(monkeypatch):
    from torchx_tpu.runner.events import handlers

    monkeypatch.setattr(handlers, "_DESTINATIONS", dict(handlers._DESTINATIONS))
    monkeypatch.setattr(handlers, "_RESOLVED_EP_FACTORIES", {})
    return handlers


class TestDestinations:
    def test_register_destination(self, clean_destinations):
        handlers = clean_destinations
        marker = logging.StreamHandler()
        handlers.register_destination("mine", lambda: marker)
        assert handlers.get_destination_handler("mine") is marker

    def test_builtin_obs_destinations(self, clean_destinations):
        handlers = clean_destinations
        assert isinstance(
            handlers.get_destination_handler("jsonl"), sinks.JsonlTraceHandler
        )
        assert isinstance(
            handlers.get_destination_handler("prom"), sinks.PromMetricsHandler
        )

    def test_unknown_falls_back_to_null(self, clean_destinations):
        handler = clean_destinations.get_destination_handler("nope")
        assert isinstance(handler, logging.NullHandler)

    def test_broken_entrypoint_falls_back_and_is_not_cached(
        self, clean_destinations, monkeypatch
    ):
        handlers = clean_destinations
        calls = []

        def boom():
            calls.append(1)
            raise RuntimeError("broken plugin")

        monkeypatch.setattr(
            "torchx_tpu.util.entrypoints.load_group",
            lambda group: {"broken": boom},
        )
        assert isinstance(
            handlers.get_destination_handler("broken"), logging.NullHandler
        )
        assert isinstance(
            handlers.get_destination_handler("broken"), logging.NullHandler
        )
        assert len(calls) == 2  # failures retried (and re-warned), not cached

    def test_good_entrypoint_is_resolved_once(
        self, clean_destinations, monkeypatch
    ):
        handlers = clean_destinations
        loads = []

        def fake_load_group(group):
            loads.append(group)
            return {"ep_dest": lambda: logging.StreamHandler}

        monkeypatch.setattr(
            "torchx_tpu.util.entrypoints.load_group", fake_load_group
        )
        h1 = handlers.get_destination_handler("ep_dest")
        h2 = handlers.get_destination_handler("ep_dest")
        assert isinstance(h1, logging.StreamHandler)
        assert isinstance(h2, logging.StreamHandler)
        assert loads == ["tpx.event_handlers"]  # second hit served from cache

    def test_factory_constructor_failure_falls_back(self, clean_destinations):
        handlers = clean_destinations

        def bad_factory():
            raise OSError("disk full")

        handlers.register_destination("bad", bad_factory)
        assert isinstance(
            handlers.get_destination_handler("bad"), logging.NullHandler
        )


# -- sinks + timeline ------------------------------------------------------


class TestSinksAndTimeline:
    def test_spans_and_events_share_one_jsonl(self):
        with obs_trace.span("parent") as parent:
            record(
                TpxEvent(session="s", scheduler="local", api="describe")
            )
        records = timeline.load_records(sinks.trace_path())
        spans = [r for r in records if timeline.is_span(r)]
        events = [r for r in records if not timeline.is_span(r)]
        assert [s["name"] for s in spans] == ["parent"]
        assert events[-1]["api"] == "describe"
        # the event is correlated to the enclosing span at emit time
        assert events[-1]["trace_id"] == parent.trace_id
        assert events[-1]["span_id"] == parent.span_id
        # and events get their clocks stamped at emit (satellite: times.py)
        assert events[-1]["start_epoch_time_usec"] is not None
        assert events[-1]["wall_time_usec"] is not None
        assert events[-1]["cpu_time_usec"] is not None

    def test_load_records_skips_torn_lines(self, tmp_path):
        p = tmp_path / "trace.jsonl"
        p.write_text('{"kind": "span", "name": "ok"}\n{"kind": "sp')
        assert [r["name"] for r in timeline.load_records(str(p))] == ["ok"]

    def test_flush_and_load_metrics(self):
        reg_counter = obs_metrics.RETRIES
        before = reg_counter.value(failure_class="TEST_ONLY")
        reg_counter.inc(failure_class="TEST_ONLY")
        path = sinks.flush_metrics()
        assert path is not None and os.path.exists(path)
        rows = timeline.load_metrics(os.path.dirname(path))
        hits = [
            v
            for n, labels, v in rows
            if n == "tpx_supervisor_retries_total" and "TEST_ONLY" in labels
        ]
        assert hits == [before + 1]

    def test_timeline_orphan_parents_become_roots(self):
        tid = "a" * 32
        recs = [
            json.loads(
                Span(
                    name="child",
                    trace_id=tid,
                    span_id="c" * 16,
                    parent_span_id="missing",
                    start_epoch_usec=10,
                    end_epoch_usec=20,
                ).serialize()
            )
        ]
        roots = timeline.build_timeline(recs, tid)
        assert [r.span.name for r in roots] == ["child"]
        assert "child" in timeline.render_timeline(roots)

    def test_render_metrics_table_collapses_buckets(self):
        rows = [
            ("tpx_launch_seconds_bucket", 'le="1"', 3.0),
            ("tpx_launch_seconds_count", "", 3.0),
        ]
        out = timeline.render_metrics_table(rows)
        assert "tpx_launch_seconds_count" in out
        assert "_bucket" not in out
        out_all = timeline.render_metrics_table(rows, include_buckets=True)
        assert "tpx_launch_seconds_bucket" in out_all

    def test_load_metrics_mixed_pid_dir_with_torn_tail(self, tmp_path):
        d = tmp_path / "sess"
        d.mkdir()
        (d / "metrics-100.prom").write_text(
            "# TYPE tpx_runs_total counter\ntpx_runs_total 3\n"
        )
        # a second process's file, its writer killed mid-line
        (d / "metrics-200.prom").write_text(
            "tpx_runs_total 4\ntpx_queue_depth 2\ntorn_met"
        )
        rows = timeline.load_metrics(str(d))
        assert ("tpx_runs_total", "", 7.0) in rows  # per-pid files sum
        assert ("tpx_queue_depth", "", 2.0) in rows
        assert not any(n.startswith("torn") for n, _, _ in rows)


# -- metrics flush debounce -------------------------------------------------


class TestMetricsFlushDebounce:
    def _record(self):
        return logging.LogRecord(
            "tpx", logging.INFO, __file__, 0, "{}", None, None
        )

    def test_burst_collapses_to_one_write(self, monkeypatch):
        writes = []
        monkeypatch.setattr(
            sinks, "flush_metrics", lambda session=None: writes.append(1)
        )
        h = sinks.PromMetricsHandler(min_interval_s=60.0)
        for _ in range(25):
            h.emit(self._record())
        assert len(writes) == 1  # first emit flushes, the burst defers
        h.flush()
        assert len(writes) == 2  # the deferred final state
        h.flush()
        assert len(writes) == 2  # nothing dirty: flush is a no-op

    def test_writes_resume_after_the_interval(self, monkeypatch):
        writes = []
        monkeypatch.setattr(
            sinks, "flush_metrics", lambda session=None: writes.append(1)
        )
        now = [0.0]
        monkeypatch.setattr(sinks.time, "monotonic", lambda: now[0])
        h = sinks.PromMetricsHandler(min_interval_s=2.0)
        h.emit(self._record())
        h.emit(self._record())
        assert len(writes) == 1
        now[0] = 5.0
        h.emit(self._record())
        assert len(writes) == 2

    def test_close_writes_deferred_state(self, monkeypatch):
        writes = []
        monkeypatch.setattr(
            sinks, "flush_metrics", lambda session=None: writes.append(1)
        )
        h = sinks.PromMetricsHandler(min_interval_s=60.0)
        h.emit(self._record())
        h.emit(self._record())
        h.close()  # logging shutdown path
        assert len(writes) == 2

    def test_env_configures_interval(self, monkeypatch):
        monkeypatch.setenv(ENV_TPX_METRICS_MIN_INTERVAL, "7.5")
        assert sinks.PromMetricsHandler().min_interval_s == 7.5
        monkeypatch.setenv(ENV_TPX_METRICS_MIN_INTERVAL, "junk")
        assert (
            sinks.PromMetricsHandler().min_interval_s
            == settings.DEFAULT_METRICS_MIN_INTERVAL
        )

    def test_operator_alias(self):
        assert sinks.MetricsFlushHandler is sinks.PromMetricsHandler


# -- exposition round trip --------------------------------------------------


class TestExpositionRoundTrip:
    def test_registry_render_parses_back_exactly(self):
        from torchx_tpu.obs.telemetry import parse_exposition

        reg = MetricsRegistry()
        c = reg.counter("rt_total", "help", ("path",))
        c.inc(3, path='a"b\\c\nd')  # every escapable character
        h = reg.histogram("rt_seconds", "help", buckets=(0.5,))
        h.observe(0.1)
        h.observe(2.0)
        samples = parse_exposition(reg.render())
        by = {(s.name, s.labels): s for s in samples}
        counter = by[("rt_total", (("path", 'a"b\\c\nd'),))]
        assert counter.value == 3.0 and counter.kind == "counter"
        assert by[("rt_seconds_bucket", (("le", "0.5"),))].value == 1.0
        assert by[("rt_seconds_bucket", (("le", "+Inf"),))].value == 2.0
        assert by[("rt_seconds_count", ())].kind == "histogram"
        assert by[("rt_seconds_sum", ())].value == pytest.approx(2.1)


# -- the acceptance scenario ----------------------------------------------


class ScriptedScheduler(Scheduler[dict]):
    """Each ``schedule()`` consumes the next scripted terminal outcome."""

    def __init__(self, session_name: str, script=None, **kwargs):
        super().__init__("scripted", session_name)
        self.script = list(script or [])
        self.apps: dict[str, tuple[AppState, Optional[FailureClass]]] = {}
        self.submitted_envs: list[dict[str, str]] = []
        self._counter = 0

    def run_opts(self) -> runopts:
        return runopts()

    def _submit_dryrun(self, app: AppDef, cfg: Mapping[str, CfgVal]):
        from torchx_tpu.specs.api import AppDryRunInfo

        return AppDryRunInfo({"app": app})

    def schedule(self, dryrun_info) -> str:
        self._counter += 1
        app_id = f"job_{self._counter}"
        outcome = (
            self.script.pop(0) if self.script else (AppState.SUCCEEDED, None)
        )
        self.apps[app_id] = outcome
        self.submitted_envs.append(dict(dryrun_info._app.roles[0].env))
        return app_id

    def describe(self, app_id: str) -> Optional[DescribeAppResponse]:
        if app_id not in self.apps:
            return None
        state, fclass = self.apps[app_id]
        return DescribeAppResponse(
            app_id=app_id, state=state, failure_class=fclass
        )

    def _cancel_existing(self, app_id: str) -> None:
        self.apps[app_id] = (AppState.CANCELLED, None)


PREEMPT = (AppState.PREEMPTED, FailureClass.PREEMPTION)
OK = (AppState.SUCCEEDED, None)


def supervise_with_preemption():
    """One preemption then success, under Runner.supervise (fast policy)."""
    sched = ScriptedScheduler("obs", script=[PREEMPT, OK])
    runner = Runner("obs", {"scripted": lambda session_name, **kw: sched})
    app = AppDef(
        name="train",
        roles=[Role(name="trainer", image="i", entrypoint="python")],
    )
    with runner:
        info = runner.dryrun(app, "scripted")
        result = runner.supervise(
            info,
            SupervisorPolicy(
                max_preemptions=2,
                backoff_seconds=0.01,
                jitter=0.0,
                poll_interval=0.01,
            ),
        )
    return result, sched


class TestSuperviseTrace:
    def test_one_trace_with_nested_attempt_spans(self):
        result, sched = supervise_with_preemption()
        assert result.succeeded and result.attempts == 2

        records = timeline.load_records(sinks.trace_path())
        spans = [r for r in records if timeline.is_span(r)]
        root = [s for s in spans if s["name"] == "runner.supervise"][-1]
        tid = root["trace_id"]
        in_trace = [s for s in spans if s["trace_id"] == tid]
        names = [s["name"] for s in in_trace]

        # both attempts, the backoff between them, and their submissions
        # all live in ONE trace
        assert names.count("supervisor.attempt") == 2
        assert names.count("supervisor.backoff") == 1
        assert names.count("runner.schedule") == 2
        assert names.count("runner.wait") == 2

        by_id = {s["span_id"]: s for s in in_trace}
        sup_run = next(s for s in in_trace if s["name"] == "supervisor.run")
        assert sup_run["parent_span_id"] == root["span_id"]
        attempts = sorted(
            (s for s in in_trace if s["name"] == "supervisor.attempt"),
            key=lambda s: s["attrs"]["attempt"],
        )
        for s in attempts:
            assert by_id[s["parent_span_id"]]["name"] == "supervisor.run"
        assert attempts[0]["attrs"]["app_id"] == "job_1"
        assert attempts[0]["attrs"]["failure_class"] == "PREEMPTION"
        assert attempts[1]["attrs"]["app_id"] == "job_2"
        assert "resume_step" not in attempts[1]["attrs"]  # no ckpt dir set

        # supervisor transition events carry the same trace id and attach
        # to the attempt spans that emitted them
        sup_events = [
            r
            for r in records
            if not timeline.is_span(r) and r.get("api") == "supervise"
        ]
        transitions = [
            e["app_metadata"]["transition"]
            for e in sup_events
            if e.get("app_metadata", {}).get("transition")
        ]
        assert transitions == ["submitted", "resubmitting", "submitted", "finished"]
        for e in sup_events:
            assert e["trace_id"] == tid

    def test_trace_env_repointed_per_attempt(self):
        result, sched = supervise_with_preemption()
        env1, env2 = sched.submitted_envs
        assert env1[ENV_TPX_TRACE_ID] == env2[ENV_TPX_TRACE_ID]
        # each attempt's in-job spans must hang off THAT attempt's span
        assert env1[ENV_TPX_PARENT_SPAN] != env2[ENV_TPX_PARENT_SPAN]
        records = timeline.load_records(sinks.trace_path())
        spans = {r["span_id"]: r for r in records if timeline.is_span(r)}
        assert spans[env1[ENV_TPX_PARENT_SPAN]]["name"] == "supervisor.attempt"
        assert spans[env2[ENV_TPX_PARENT_SPAN]]["name"] == "supervisor.attempt"
        # and the injected trace is the client's own
        root = [s for s in spans.values() if s["name"] == "runner.supervise"][-1]
        assert env1[ENV_TPX_TRACE_ID] == root["trace_id"]

    def test_metrics_flushed_with_retry_and_launch_series(self):
        supervise_with_preemption()
        path = sinks.metrics_path()
        assert os.path.exists(path)
        text = open(path).read()
        assert 'tpx_supervisor_retries_total{failure_class="PREEMPTION"}' in text
        assert "tpx_launch_seconds_bucket" in text
        assert 'tpx_wait_polls_total{scheduler="scripted"}' in text
        assert "tpx_supervisor_backoff_seconds_total" in text

    def test_tpx_trace_cli_renders_the_timeline(self, capsys):
        result, _ = supervise_with_preemption()
        from torchx_tpu.cli.main import main as cli_main

        cli_main(["trace", result.handle, "--events", "--metrics"])
        out = capsys.readouterr().out
        assert "trace " in out
        assert "runner.supervise" in out
        assert "supervisor.attempt (job_1)" in out
        assert "supervisor.attempt (job_2)" in out
        assert "supervisor.backoff" in out
        assert "· resubmitting" in out  # --events interleaving
        assert "tpx_supervisor_retries_total" in out  # --metrics table

    def test_tpx_trace_cli_unknown_identifier(self, capsys):
        supervise_with_preemption()
        from torchx_tpu.cli.main import main as cli_main

        with pytest.raises(SystemExit):
            cli_main(["trace", "no_such_app"])
        assert "no trace found" in capsys.readouterr().err


class TestLocalPreemptionDrill:
    """The acceptance scenario on the REAL local scheduler: an injected
    preemption (TPX_SIMULATE_PREEMPTION_EXIT drill knob) supervised end to
    end, leaving ONE trace with both attempts and the backoff between."""

    def test_local_supervise_injected_preemption_one_trace(self, tmp_path):
        from torchx_tpu.schedulers.local_scheduler import LocalScheduler

        marker = tmp_path / "preempted-once"
        # first run "loses its capacity" (exits with the drill code);
        # the resubmitted attempt finds the marker and succeeds
        script = (
            f'if [ -e "{marker}" ]; then exit 0; fi;'
            f' touch "{marker}"; exit 67'
        )
        sched = LocalScheduler(session_name="obs-local", cache_size=10)
        runner = Runner("obs-local", {"local": lambda session_name, **kw: sched})
        app = AppDef(
            name="drill",
            roles=[
                Role(
                    name="w",
                    image="",
                    entrypoint="sh",
                    args=["-c", script],
                    env={ENV_TPX_SIMULATE_PREEMPTION_EXIT: "67"},
                )
            ],
        )
        try:
            with runner:
                info = runner.dryrun(
                    app, "local", cfg={"log_dir": str(tmp_path / "logs")}
                )
                result = runner.supervise(
                    info,
                    SupervisorPolicy(
                        max_preemptions=2,
                        backoff_seconds=0.01,
                        jitter=0.0,
                        poll_interval=0.05,
                    ),
                )
        finally:
            sched.close()
        assert result.succeeded and result.attempts == 2

        records = timeline.load_records(sinks.trace_path())
        spans = [r for r in records if timeline.is_span(r)]
        root = [s for s in spans if s["name"] == "runner.supervise"][-1]
        tid = root["trace_id"]
        names = [s["name"] for s in spans if s["trace_id"] == tid]
        assert names.count("supervisor.attempt") == 2
        assert names.count("supervisor.backoff") == 1
        assert names.count("scheduler.spawn") == 2  # real Popen submits
        attempts = sorted(
            (
                s
                for s in spans
                if s["trace_id"] == tid and s["name"] == "supervisor.attempt"
            ),
            key=lambda s: s["attrs"]["attempt"],
        )
        # the drill exit code classified as a real preemption
        assert attempts[0]["attrs"]["failure_class"] == "PREEMPTION"
        assert attempts[0]["attrs"]["state"] == "PREEMPTED"
        assert "failure_class" not in attempts[1]["attrs"]

    def test_drill_knob_absent_keeps_failed_semantics(self, tmp_path):
        from torchx_tpu.schedulers.local_scheduler import LocalScheduler

        sched = LocalScheduler(session_name="obs-nodrill", cache_size=10)
        try:
            app = AppDef(
                name="plain",
                roles=[
                    Role(name="w", image="", entrypoint="sh", args=["-c", "exit 67"])
                ],
            )
            app_id = sched.submit(app, {"log_dir": str(tmp_path / "logs")})
            import time

            from torchx_tpu.specs.api import is_terminal

            for _ in range(200):
                desc = sched.describe(app_id)
                if desc is not None and is_terminal(desc.state):
                    break
                time.sleep(0.05)
            assert desc.state == AppState.FAILED
            assert sched.classify_failure(desc) == FailureClass.APP
        finally:
            sched.close()


# -- in-job helpers --------------------------------------------------------


class TestJobSide:
    def test_spmd_job_span_noop_without_trace_env(self, monkeypatch):
        from torchx_tpu.apps.spmd_main import _job_span

        monkeypatch.delenv(ENV_TPX_TRACE_ID, raising=False)
        with _job_span("job.bootstrap") as sp:
            assert sp is None
        assert not os.path.exists(sinks.trace_path())

    def test_spmd_job_span_joins_client_trace(self, monkeypatch):
        from torchx_tpu.apps.spmd_main import _job_span

        monkeypatch.setenv(ENV_TPX_TRACE_ID, "e" * 32)
        monkeypatch.setenv(ENV_TPX_PARENT_SPAN, "b" * 16)
        with _job_span("job.bootstrap") as sp:
            pass
        assert sp.trace_id == "e" * 32
        assert sp.parent_span_id == "b" * 16

    def test_heartbeat_is_instant_and_flushes_metrics(self):
        sp = obs_trace.heartbeat("job.first_step", launch_to_first_step_s=1.2)
        assert sp.end_epoch_usec is not None
        assert sp.attrs["launch_to_first_step_s"] == 1.2
        assert os.path.exists(sinks.metrics_path())
