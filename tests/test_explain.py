"""Deep-preflight tests: the jax-free plan IR, sharding propagation per
parallelism leg, the HBM/collective cost model, TPX7xx gating in the
submit gate, the ``tpx explain`` CLI (golden-filed ``--json`` schema) and
the ``--aot`` cross-check against the XLA compiler's memory analysis."""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

from torchx_tpu.analyze import analyze
from torchx_tpu.analyze.costmodel import (
    collective_traffic,
    hbm_fit,
)
from torchx_tpu.analyze.explain import ExplainReport, deep_preflight, explain
from torchx_tpu.analyze.plan import (
    MODEL_SHAPES,
    ParallelPlan,
    PlanError,
    plan_from_role,
)
from torchx_tpu.analyze.propagation import propagate
from torchx_tpu.cli.main import main
from torchx_tpu.components import dist
from torchx_tpu.parallel.mesh_config import axis_networks
from torchx_tpu.specs.api import AppDef, Role

REPO = Path(__file__).resolve().parent.parent
GOLDEN = Path(__file__).resolve().parent / "fixtures" / "explain_golden.json"

GIB = 1024**3


def spmd_app(*trainer_args: str, m: str = "my.custom_trainer", j: str = "1x8", **kw) -> AppDef:
    """A dist.spmd AppDef shaped exactly like the CLI would build it."""
    return dist.spmd(*trainer_args, m=m, j=j, **kw)


def plan_of(app: AppDef) -> ParallelPlan:
    plan = plan_from_role(app.roles[0])
    assert plan is not None
    return plan


def kinds(flow) -> dict[str, str]:
    return {b.op: b.kind for b in flow.boundaries}


# ---------------------------------------------------------------------------
# plan IR
# ---------------------------------------------------------------------------


def test_model_shapes_match_jax_configs():
    """The honesty contract from plan.py's docstring: the arithmetic-only
    ModelShape mirror must agree exactly with the real (jax-importing)
    model configs on parameter counts."""
    from torchx_tpu.examples.train_llama import all_configs

    cfgs = all_configs()
    for name, shape in MODEL_SHAPES.items():
        cfg = cfgs[name]()
        assert shape.param_count() == cfg.param_count(), name
        if shape.is_moe:
            assert shape.active_param_count() == cfg.active_param_count(), name
        # the step profiler's MFU denominator reuses this mirror: the
        # FLOP arithmetic must agree exactly too
        assert shape.flops_per_token() == cfg.flops_per_token(), name


def test_plan_from_spmd_role():
    plan = plan_of(
        spmd_app("--config", "tiny", "--mesh", "fsdp=-1", "--batch", "16")
    )
    assert plan.model.name == "tiny"
    assert plan.axis("fsdp") == 8 and plan.devices == 8
    assert plan.batch == 16 and plan.seq == 128
    assert plan.mesh_spec == "fsdp=-1"
    assert not plan.serve and not plan.remat_safe
    assert plan.hbm_source == "assumed"  # CPU-sim role


def test_plan_flags_int8_ring_remat():
    plan = plan_of(
        spmd_app(
            "--config", "tiny", "--mesh", "fsdp=1,sp=-1",
            "--int8", "--ring-attention", "--remat-policy", "dots",
        )
    )
    assert plan.int8 and plan.ring_attention
    assert plan.remat_policy == "dots"
    # "auto" maps to the trainer's push floor
    plan = plan_of(
        spmd_app("--config", "tiny", "--remat-policy", "auto")
    )
    assert plan.remat_policy == "dots"


def test_plan_stock_trainer_is_remat_safe():
    plan = plan_of(
        spmd_app("--config", "moe_tiny", m="torchx_tpu.examples.train_llama")
    )
    assert plan.remat_safe


def test_plan_none_without_config():
    assert plan_from_role(spmd_app("--lr", "3e-4").roles[0]) is None
    assert plan_from_role(spmd_app("--config", "nonesuch").roles[0]) is None
    assert (
        plan_from_role(Role(name="r", image="img", entrypoint="bash")) is None
    )


def test_plan_error_on_unresolvable_mesh():
    with pytest.raises(PlanError):
        plan_of(spmd_app("--config", "tiny", "--mesh", "tp=3"))
    with pytest.raises(PlanError):
        plan_of(spmd_app("--config", "tiny", "--mesh", "bogus=2"))


def test_plan_tpu_topology_and_hbm_table():
    app = spmd_app(
        "--config", "llama3_8b", "--mesh", "fsdp=-1", tpu="v5p-32", j="1"
    )
    plan = plan_of(app)
    assert plan.hbm_source == "tpu_slice"
    assert plan.accelerator.startswith("v5p")
    assert plan.hbm_bytes_per_chip == 95 * GIB
    assert plan.devices == plan.slices * plan.chips_per_slice


def test_plan_tpx_mesh_env_overrides_flag():
    app = spmd_app("--config", "tiny", "--mesh", "fsdp=-1")
    role = dataclasses.replace(
        app.roles[0], env={**app.roles[0].env, "TPX_MESH": "fsdp=1,tp=-1"}
    )
    plan = plan_from_role(role)
    assert plan is not None and plan.axis("tp") == 8 and plan.axis("fsdp") == 1


# ---------------------------------------------------------------------------
# sharding propagation, one test per parallelism leg
# ---------------------------------------------------------------------------


def test_propagate_fsdp_leg():
    flow = propagate(plan_of(spmd_app("--config", "tiny", "--mesh", "fsdp=-1")))
    k = kinds(flow)
    assert k["embed.gather"] == "allgather"
    assert k["layer.qkv"] == "allgather"
    assert k["grad.sync"] == "allreduce"
    assert not flow.full_remat
    assert flow.activation_spec == "P('fsdp', None, None)"


def test_propagate_tp_leg():
    flow = propagate(
        plan_of(spmd_app("--config", "tiny", "--mesh", "fsdp=1,tp=-1"))
    )
    k = kinds(flow)
    assert k["layer.attn_out"] == "allreduce"
    assert k["layer.mlp_out"] == "allreduce"
    assert k["loss.ce"] == "allreduce"
    assert "embed.gather" not in k  # table not dim-sharded without fsdp


def test_propagate_pp_leg():
    flow = propagate(
        plan_of(spmd_app("--config", "tiny", "--mesh", "pp=2,fsdp=-1"))
    )
    assert kinds(flow)["pp.stage"] == "permute"


def test_propagate_ring_vs_allgather_sp_leg():
    ring = propagate(
        plan_of(
            spmd_app(
                "--config", "tiny", "--mesh", "fsdp=1,sp=-1", "--ring-attention"
            )
        )
    )
    assert kinds(ring)["attn.ring"] == "permute"
    full = propagate(
        plan_of(spmd_app("--config", "tiny", "--mesh", "fsdp=1,sp=-1"))
    )
    assert kinds(full)["attn.kv_allgather"] == "allgather"


def test_propagate_moe_full_remat_gated_by_remat_safety():
    """The tentpole boundary: ep x fsdp on a custom trainer makes both the
    embed gather and the MoE dispatch involuntary-full-remat; the stock
    trainer (with_sharding_constraint pins) keeps them benign."""
    custom = propagate(
        plan_of(spmd_app("--config", "moe_tiny", "--mesh", "ep=2,fsdp=-1"))
    )
    k = kinds(custom)
    assert custom.full_remat
    assert k["embed.gather"] == "full_remat"
    assert k["moe.dispatch"] == "full_remat"
    assert k["moe.combine"] == "alltoall"
    # axes reported in canonical mesh order
    dispatch = next(b for b in custom.boundaries if b.op == "moe.dispatch")
    assert dispatch.axes == ("fsdp", "ep")

    stock = propagate(
        plan_of(
            spmd_app(
                "--config", "moe_tiny", "--mesh", "ep=2,fsdp=-1",
                m="torchx_tpu.examples.train_llama",
            )
        )
    )
    assert not stock.full_remat
    assert kinds(stock)["moe.dispatch"] == "alltoall"


def test_propagate_moe_ep_alone_is_benign():
    flow = propagate(
        plan_of(spmd_app("--config", "moe_tiny", "--mesh", "ep=2,fsdp=1,dp=-1"))
    )
    assert not flow.full_remat
    assert kinds(flow)["moe.dispatch"] == "alltoall"


def test_propagate_serve_has_no_loss_or_grad():
    plan = dataclasses.replace(
        plan_of(spmd_app("--config", "tiny", "--mesh", "fsdp=-1")), serve=True
    )
    k = kinds(propagate(plan))
    assert "loss.ce" not in k and "grad.sync" not in k


# ---------------------------------------------------------------------------
# cost model
# ---------------------------------------------------------------------------


def test_hbm_fit_components_and_verdict():
    plan = plan_of(spmd_app("--config", "tiny", "--mesh", "fsdp=-1"))
    fit = hbm_fit(plan)
    assert set(fit.components) == {
        "params", "optimizer", "gradients", "activations", "logits", "batch",
    }
    assert fit.components["optimizer"] == 2 * fit.components["params"]
    assert fit.total_bytes == sum(fit.components.values())
    assert fit.fits and fit.verdict == "fits"
    d = fit.to_dict()
    assert d["usable_bytes"] == int(fit.budget_bytes * fit.headroom)


def test_hbm_fit_shards_params_over_fsdp_tp():
    flat = plan_of(spmd_app("--config", "tiny", "--mesh", "fsdp=1,dp=-1"))
    sharded = plan_of(spmd_app("--config", "tiny", "--mesh", "fsdp=4,tp=2"))
    assert (
        hbm_fit(sharded).components["params"] * 8
        <= hbm_fit(flat).components["params"] + 8
    )


def test_hbm_fit_serve_kv_pool():
    plan = dataclasses.replace(
        plan_of(spmd_app("--config", "tiny", "--mesh", "fsdp=1,tp=-1")),
        serve=True,
        max_batch=4,
    )
    fit = hbm_fit(plan)
    assert set(fit.components) == {"params", "kv_pool", "decode_state"}
    m = plan.model
    dense = 4 * m.n_layers * 2 * m.max_seq * m.n_kv_heads * m.head_dim
    assert fit.components["kv_pool"] == dense * m.dtype_bytes // 8  # /tp


def test_plan_parses_serve_role_and_prefix_reserve():
    app = spmd_app(
        "--config",
        "tiny",
        "--serve-role",
        "prefill",
        "--prefix-cache-reserve",
        "0.25",
    )
    plan = plan_of(app)
    assert plan.serve_role == "prefill" and plan.prefix_reserve == 0.25
    d = plan.to_dict()
    assert d["serve_role"] == "prefill" and d["prefix_reserve"] == 0.25
    # defaults: unified, no reserve
    default = plan_of(spmd_app("--config", "tiny"))
    assert default.serve_role == "unified" and default.prefix_reserve == 0.0


def test_hbm_fit_charges_prefix_cache_reserve():
    base = dataclasses.replace(
        plan_of(spmd_app("--config", "tiny", "--mesh", "fsdp=1,tp=-1")),
        serve=True,
        max_batch=4,
    )
    reserved = dataclasses.replace(base, prefix_reserve=0.25)
    fit0, fit1 = hbm_fit(base), hbm_fit(reserved)
    assert "prefix_cache" not in fit0.components
    # the reserve holds cached prefixes ON TOP of the live-sequence pool
    assert fit1.components["prefix_cache"] == -(
        -fit1.components["kv_pool"] // 4
    )
    assert fit1.total_bytes == fit0.total_bytes + fit1.components["prefix_cache"]


def test_collective_traffic_axes_and_network():
    plan = plan_of(
        spmd_app("--config", "moe_tiny", "--mesh", "ep=2,fsdp=4", j="1x8")
    )
    traffic = {t.axis: t for t in collective_traffic(plan)}
    assert set(traffic) == {"fsdp", "ep"}
    # single slice of 8: everything is ICI
    assert all(t.network == "ici" for t in traffic.values())
    assert traffic["fsdp"].bytes_per_step > 0
    assert "alltoall_dispatch" in traffic["ep"].ops


def test_axis_networks_classification():
    # 2 slices x 4 chips: innermost fsdp stays on ICI, outer dp is DCN
    nets = axis_networks({"dp": 2, "fsdp": 4}, chips_per_slice=4)
    assert nets["fsdp"] == "ici" and nets["dp"] == "dcn"
    assert nets["tp"] == "none"  # size-1 axis
    # an axis straddling the slice edge is mixed
    nets = axis_networks({"fsdp": 8}, chips_per_slice=4)
    assert nets["fsdp"] == "mixed"


# ---------------------------------------------------------------------------
# TPX7xx diagnostics: deep_preflight + the submit gate
# ---------------------------------------------------------------------------


def dcodes(diags) -> list[str]:
    return [d.code for d in diags]


def test_tpx700_moe_boundary_error():
    """The MULTICHIP r03/r04 dryrun scenario, caught statically: custom
    trainer + moe mesh -> TPX700 ERROR naming the exact boundary."""
    app = spmd_app("--config", "moe_tiny", "--mesh", "ep=2,fsdp=-1")
    plan, diags = deep_preflight(app.roles[0])
    assert plan is not None
    assert dcodes(diags).count("TPX700") == 2  # embed.gather + moe.dispatch
    fields = {d.field for d in diags if d.code == "TPX700"}
    assert fields == {"sharding.embed.gather", "sharding.moe.dispatch"}
    assert all(d.severity.value == "error" for d in diags)


def test_tpx701_hbm_exceeded():
    app = spmd_app("--config", "llama3_8b", "--mesh", "fsdp=-1")
    _plan, diags = deep_preflight(app.roles[0], hbm_bytes=1 * GIB)
    assert "TPX701" in dcodes(diags)
    d = next(d for d in diags if d.code == "TPX701")
    assert "params" in d.message and d.severity.value == "error"


def test_tpx702_dcn_axis_warning():
    # 2 slices x 8 chips, fsdp spanning all 16 devices -> mixed network
    app = spmd_app(
        "--config", "llama3_1b", "--mesh", "fsdp=-1", tpu="v5e-8", j="2"
    )
    _plan, diags = deep_preflight(app.roles[0])
    assert "TPX702" in dcodes(diags)
    d = next(d for d in diags if d.code == "TPX702")
    assert d.severity.value == "warning" and "fsdp" in d.message


def test_tpx703_broken_mesh():
    app = spmd_app("--config", "tiny", "--mesh", "tp=3")
    plan, diags = deep_preflight(app.roles[0])
    assert plan is None and dcodes(diags) == ["TPX703"]


def test_tpx704_serve_kv_overflow():
    role = Role(
        name="server",
        image="img",
        entrypoint="python",
        args=[
            "-m", "torchx_tpu.apps.generate_server",
            "--config", "llama3_8b", "--max-batch", "64",
        ],
    )
    _plan, diags = deep_preflight(role, hbm_bytes=8 * GIB)
    assert "TPX704" in dcodes(diags)
    assert next(d for d in diags if d.code == "TPX704").severity.value == "warning"


def test_tpx705_no_plan_info():
    _plan, diags = deep_preflight(spmd_app("--steps", "5").roles[0])
    assert dcodes(diags) == ["TPX705"]
    assert diags[0].severity.value == "info"


def test_gate_runs_deep_preflight_and_supersedes_tpx110():
    """The submit gate on a plan-shaped role reports propagation's TPX700
    and stands the TPX110 heuristic down; TPX705 never reaches the gate."""
    report = analyze(spmd_app("--config", "moe_tiny", "--mesh", "ep=2,fsdp=-1"))
    got = [d.code for d in report.diagnostics]
    assert "TPX700" in got and "TPX110" not in got and "TPX705" not in got


def test_gate_tpx110_heuristic_still_fires_without_plan():
    """Regression for the pre-propagation behavior: a custom trainer with
    no recognizable --config keeps the TPX110 pattern-match warning."""
    report = analyze(spmd_app("--mesh", "ep=2,fsdp=-1"))
    got = [d.code for d in report.diagnostics]
    assert "TPX110" in got and "TPX700" not in got and "TPX705" not in got


def test_gate_tpx110_silent_for_stock_trainer():
    report = analyze(
        spmd_app("--mesh", "ep=2,fsdp=-1", m="torchx_tpu.examples.train_llama")
    )
    assert "TPX110" not in [d.code for d in report.diagnostics]


def test_gate_tpx111_unknown_axis_still_errors():
    report = analyze(spmd_app("--config", "tiny", "--mesh", "fsd=2"))
    assert "TPX111" in [d.code for d in report.diagnostics]


# ---------------------------------------------------------------------------
# the explain report + CLI
# ---------------------------------------------------------------------------


def test_explain_report_schema_golden():
    """``tpx explain --json`` is schema version 1 and byte-stable: the
    full report for a fixed plan must match the committed golden file.
    Regenerate deliberately with scripts/gen_explain_golden.py when the
    schema (or the cost model) changes on purpose."""
    app = spmd_app(
        "--config", "moe_tiny", "--mesh", "ep=2,fsdp=-1",
        "--batch", "8", "--seq", "128",
    )
    report = explain(app, gate="test")
    got = report.to_dict()
    golden = json.loads(GOLDEN.read_text())
    assert got == golden


def test_explain_report_render_and_summary():
    app = spmd_app("--config", "moe_tiny", "--mesh", "ep=2,fsdp=-1")
    report = explain(app, gate="test")
    assert report.has_errors
    assert report.summary()["error"] == 2
    text = report.render()
    assert "INVOLUNTARY FULL REMAT" in text
    assert "FITS" in text and "TPX700" in text


def test_explain_metrics_and_span(tmp_path, monkeypatch):
    monkeypatch.setenv("TPX_OBS_DIR", str(tmp_path / "obs"))
    from torchx_tpu.obs import metrics as obs_metrics

    explain(spmd_app("--config", "tiny"), gate="test", session="s1")
    text = obs_metrics.REGISTRY.render()
    assert "tpx_explain_runs_total" in text
    assert "tpx_explain_hbm_total_bytes" in text


def test_explain_mixed_app_keeps_non_plan_roles():
    app = AppDef(
        name="mixed",
        roles=[
            spmd_app("--config", "tiny", "--mesh", "fsdp=-1").roles[0],
            Role(name="sidecar", image="img", entrypoint="bash"),
        ],
    )
    report = explain(app, gate="test")
    assert len(report.roles) == 2
    assert report.roles[1]["plan"] is None
    assert dcodes(report.roles[1]["_diags"]) == ["TPX705"]
    assert not report.has_errors  # TPX705 is info


def test_cli_explain_json_and_exit_codes(capsys):
    argv = [
        "explain", "--json", "dist.spmd",
        "-j", "1x8", "-m", "my.custom_trainer",
        "--", "--config", "moe_tiny", "--mesh", "ep=2,fsdp=-1",
    ]
    with pytest.raises(SystemExit) as e:
        main(argv)
    assert e.value.code == 1  # TPX700 errors
    doc = json.loads(capsys.readouterr().out)
    assert doc["version"] == 1
    got = [d["code"] for r in doc["roles"] for d in r["diagnostics"]]
    assert "TPX700" in got
    boundary_kinds = {
        b["kind"] for r in doc["roles"] for b in r["sharding"]["boundaries"]
    }
    assert "full_remat" in boundary_kinds


def test_cli_explain_clean_stock_trainer(capsys):
    argv = [
        "explain", "dist.spmd",
        "-j", "1x8", "-m", "torchx_tpu.examples.train_llama",
        "--", "--config", "moe_tiny", "--mesh", "ep=2,fsdp=-1",
    ]
    with pytest.raises(SystemExit) as e:
        main(argv)
    assert e.value.code == 0
    out = capsys.readouterr().out
    assert "FITS" in out and "full_remat" not in out


def test_cli_explain_usage_errors(capsys):
    with pytest.raises(SystemExit) as e:
        main(["explain", "--json"])
    assert e.value.code == 2
    with pytest.raises(SystemExit) as e:
        main(["explain", "-s", "nonesuch", "dist.spmd", "-m", "x"])
    assert e.value.code == 2
    assert "unknown scheduler" in capsys.readouterr().err


def test_cli_explain_hbm_override(capsys):
    argv = [
        "explain", "--hbm-gb", "0.001", "dist.spmd",
        "-j", "1x8", "-m", "torchx_tpu.examples.train_llama",
        "--", "--config", "tiny", "--mesh", "fsdp=-1",
    ]
    with pytest.raises(SystemExit) as e:
        main(argv)
    assert e.value.code == 1
    assert "TPX701" in capsys.readouterr().out


@pytest.mark.integ
def test_explain_path_never_imports_jax():
    """The acceptance bar SELF_LINT enforces statically, proven
    dynamically: a full non---aot explain run leaves jax unimported."""
    code = (
        "import sys\n"
        "from torchx_tpu.cli.main import main\n"
        "try:\n"
        "    main(['explain', '--json', 'dist.spmd', '-j', '1x8',"
        " '-m', 'my.t', '--', '--config', 'moe_tiny',"
        " '--mesh', 'ep=2,fsdp=-1'])\n"
        "except SystemExit:\n"
        "    pass\n"
        "assert 'jax' not in sys.modules, 'explain imported jax'\n"
    )
    env = {**os.environ, "TPX_EVENT_DESTINATION": "null"}
    subprocess.run(
        [sys.executable, "-c", code], check=True, cwd=str(REPO), env=env,
        stdout=subprocess.DEVNULL,
    )


# ---------------------------------------------------------------------------
# --aot cross-check (imports jax)
# ---------------------------------------------------------------------------


def test_aot_cross_check_tiny_agrees():
    import jax

    if jax.device_count() < 8:
        pytest.skip("needs the 8-virtual-device CPU mesh")
    app = spmd_app(
        "--config", "tiny", "--mesh", "fsdp=-1", "--batch", "8",
        m="torchx_tpu.examples.train_llama",
    )
    report = explain(app, aot=True, gate="test")
    aot = report.roles[0]["aot"]
    assert "error" not in aot, aot
    assert aot["fits"] is True
    assert abs(aot["state_agreement_pct"]) <= 15.0


@pytest.mark.slow
def test_aot_cross_check_1b_within_15pct():
    """The acceptance criterion: on the 1B config the static state
    prediction agrees with compile_fit's argument bytes within 15%."""
    import jax

    if jax.device_count() < 8:
        pytest.skip("needs the 8-virtual-device CPU mesh")
    app = spmd_app(
        "--config", "llama3_1b", "--mesh", "fsdp=-1",
        "--batch", "8", "--seq", "512",
        m="torchx_tpu.examples.train_llama",
    )
    report = explain(app, aot=True, gate="test")
    aot = report.roles[0]["aot"]
    assert "error" not in aot, aot
    assert abs(aot["state_agreement_pct"]) <= 15.0


def test_aot_cross_check_device_mismatch_reports_error():
    app = spmd_app("--config", "tiny", "--mesh", "fsdp=-1", j="1x4")
    report = explain(app, aot=True, gate="test")
    aot = report.roles[0]["aot"]
    assert "error" in aot and "4 device" in aot["error"]
