"""Preflight analyzer tests: diagnostics model, rule families, the Runner
lint gate, `tpx lint` CLI, builtin self-lint, and TpuSlice edge cases."""

import json
from typing import Mapping, Optional

import pytest

from torchx_tpu.analyze import (
    Diagnostic,
    LintError,
    LintReport,
    RuleContext,
    Severity,
    all_rules,
    analyze,
    analyze_component,
    capabilities_for,
    register_rule,
)
from torchx_tpu.cli.main import main
from torchx_tpu.runner.api import Runner
from torchx_tpu.schedulers.api import (
    DescribeAppResponse,
    ListAppResponse,
    Scheduler,
    SchedulerCapabilities,
)
from torchx_tpu.specs.api import (
    AppDef,
    AppDryRunInfo,
    AppState,
    BindMount,
    CfgVal,
    Resource,
    RetryPolicy,
    Role,
    TpuSlice,
    parse_mounts,
    runopts,
)
from torchx_tpu.specs.file_linter import validate_source
from torchx_tpu.specs.finder import get_components
from torchx_tpu.specs.serialize import appdef_to_dict
from torchx_tpu.supervisor.policy import SupervisorPolicy


def app_with(**role_kwargs) -> AppDef:
    defaults = dict(name="worker", image="img", entrypoint="python")
    defaults.update(role_kwargs)
    return AppDef(name="app", roles=[Role(**defaults)])


def broken_app() -> AppDef:
    """The canonical deliberately-broken AppDef from the acceptance criteria:
    bad topology dims + launcher-owned env + duplicate mounts; on tpu_vm the
    mounts also hit the capability rule."""
    return AppDef(
        name="bad",
        roles=[
            Role(
                name="trainer",
                image="img",
                entrypoint="python",
                env={"TPX_REPLICA_ID": "0"},
                mounts=[
                    BindMount(src_path="/a", dst_path="/x"),
                    BindMount(src_path="/b", dst_path="/x"),
                ],
                resource=Resource(tpu=TpuSlice("v5e", 16, "2x2x4")),
            )
        ],
    )


def codes(report: LintReport) -> list[str]:
    return [d.code for d in report.diagnostics]


# ---------------------------------------------------------------------------
# Diagnostics model
# ---------------------------------------------------------------------------


class TestDiagnosticsModel:
    def test_location(self):
        assert Diagnostic("X", Severity.ERROR, "m", role="r", field="f").location == "r.f"
        assert Diagnostic("X", Severity.ERROR, "m", role="r").location == "r"
        assert Diagnostic("X", Severity.ERROR, "m", field="f").location == "f"
        assert Diagnostic("X", Severity.ERROR, "m").location == "app"

    def test_report_sorts_errors_first(self):
        r = LintReport(target="t")
        r.extend(
            [
                Diagnostic("TPX203", Severity.INFO, "i"),
                Diagnostic("TPX202", Severity.WARNING, "w"),
                Diagnostic("TPX201", Severity.ERROR, "e"),
            ]
        )
        assert [d.severity for d in r.diagnostics] == [
            Severity.ERROR,
            Severity.WARNING,
            Severity.INFO,
        ]
        assert r.has_errors
        assert len(r.errors) == 1 and len(r.warnings) == 1
        assert r.summary() == {"error": 1, "warning": 1, "info": 1}

    def test_to_dict_is_stable(self):
        r = LintReport(target="t", scheduler="local")
        r.extend([Diagnostic("TPX010", Severity.ERROR, "no roles", field="roles")])
        d = r.to_dict()
        assert d["version"] == 1
        assert d["target"] == "t"
        assert d["scheduler"] == "local"
        assert d["summary"] == {"error": 1, "warning": 0, "info": 0}
        assert d["diagnostics"][0]["code"] == "TPX010"
        # keys must stay stable: external tooling parses this
        assert list(d) == ["version", "target", "scheduler", "diagnostics", "summary"]

    def test_render_clean_and_dirty(self):
        r = LintReport(target="t")
        assert "clean" in r.render()
        r.extend([Diagnostic("TPX011", Severity.ERROR, "no entrypoint", role="r", hint="set it")])
        out = r.render()
        assert "TPX011" in out and "[r]" in out and "fix: set it" in out

    def test_lint_error_mentions_escape_hatch(self):
        r = LintReport(target="t")
        r.extend([Diagnostic("TPX010", Severity.ERROR, "no roles")])
        msg = str(LintError(r))
        assert "--no-lint" in msg and "TPX_NO_LINT" in msg and "TPX010" in msg


# ---------------------------------------------------------------------------
# Rule registry
# ---------------------------------------------------------------------------


class TestRegistry:
    def test_builtin_rules_registered(self):
        names = set(all_rules())
        assert {
            "structure",
            "topology",
            "env",
            "macros",
            "ports",
            "mounts",
            "capabilities",
            "retries",
        } <= names

    def test_custom_rule_runs_and_is_replaceable(self):
        def my_rule(ctx: RuleContext):
            yield Diagnostic("TPX999", Severity.WARNING, "custom")

        register_rule("test-custom", my_rule)
        try:
            report = analyze(app_with())
            assert "TPX999" in codes(report)
        finally:
            from torchx_tpu.analyze import rules as rules_mod

            rules_mod._RULES.pop("test-custom", None)


# ---------------------------------------------------------------------------
# TPX01x structure
# ---------------------------------------------------------------------------


class TestStructureRules:
    def test_clean_app_has_no_findings(self):
        assert analyze(app_with(), scheduler="local").diagnostics == []

    def test_no_roles(self):
        assert codes(analyze(AppDef(name="empty"))) == ["TPX010"]

    def test_missing_entrypoint_and_image(self):
        report = analyze(app_with(entrypoint="", image=""))
        assert "TPX011" in codes(report)
        assert "TPX015" in codes(report)

    def test_bad_replica_counts(self):
        assert "TPX012" in codes(analyze(app_with(num_replicas=0)))
        assert "TPX013" in codes(analyze(app_with(num_replicas=2, min_replicas=3)))

    def test_duplicate_role_names(self):
        app = AppDef(
            name="app",
            roles=[
                Role(name="r", image="i", entrypoint="e"),
                Role(name="r", image="i", entrypoint="e"),
            ],
        )
        assert "TPX014" in codes(analyze(app))


# ---------------------------------------------------------------------------
# TPX1xx topology + TpuSlice edge cases
# ---------------------------------------------------------------------------


class TestTopologyRules:
    def test_impossible_v5e_chip_count(self):
        # 10 > 8 single-host chips and not a multiple of the 4-chip host VM
        report = analyze(app_with(resource=Resource(tpu=TpuSlice("v5e", 10))))
        assert codes(report) == ["TPX101"]

    def test_v5e_pod_cap(self):
        report = analyze(app_with(resource=Resource(tpu=TpuSlice("v5e", 512))))
        assert "TPX101" in codes(report)

    def test_dims_mismatch_both_ways(self):
        r2 = analyze(app_with(resource=Resource(tpu=TpuSlice("v5e", 16, "2x2x4"))))
        assert codes(r2) == ["TPX102"]
        r3 = analyze(app_with(resource=Resource(tpu=TpuSlice("v4", 16, "4x4"))))
        assert codes(r3) == ["TPX102"]

    def test_valid_slices_are_clean(self):
        for tpu in (
            TpuSlice("v5e", 16, "4x4"),
            TpuSlice("v4", 16, "2x2x4"),
            TpuSlice("v5p", 8),
            TpuSlice("v5e", 256),
        ):
            assert analyze(app_with(resource=Resource(tpu=tpu))).diagnostics == []

    def test_tpu_in_devices(self):
        report = analyze(app_with(resource=Resource(devices={"google.com/tpu": 4})))
        assert "TPX103" in codes(report)


class TestMeshRules:
    """TPX110/TPX111 regression: the heuristic mesh rule keeps firing for
    roles deep preflight cannot plan, and stands down when TPX700
    propagation owns the role (tests/test_explain.py covers the TPX7xx
    side)."""

    def heuristic_role(self, *extra, entrypoint="python"):
        return app_with(
            entrypoint=entrypoint,
            args=["-m", "my.custom_trainer", "--mesh", "ep=2,fsdp=-1", *extra],
            env={"XLA_FLAGS": "--xla_force_host_platform_device_count=8"},
        )

    def test_tpx110_fires_without_a_plan(self):
        # no --config: plan_from_role returns None, the heuristic owns it
        report = analyze(self.heuristic_role())
        assert "TPX110" in codes(report)
        assert "TPX700" not in codes(report)

    def test_tpx110_stock_trainer_stays_clean(self):
        report = analyze(
            app_with(
                entrypoint="python",
                args=[
                    "-m", "torchx_tpu.examples.train_llama",
                    "--mesh", "ep=2,fsdp=-1",
                ],
            )
        )
        assert "TPX110" not in codes(report)

    def test_tpx110_superseded_by_propagation(self):
        # a recognizable --config resolves into a ParallelPlan: TPX700
        # carries the exact boundary and the pattern-match stands down
        report = analyze(self.heuristic_role("--config", "moe_tiny"))
        assert "TPX110" not in codes(report)
        assert "TPX700" in codes(report)

    def test_tpx110_stands_down_on_broken_plans(self):
        # plan-shaped but inconsistent: TPX703 owns the role
        report = analyze(
            app_with(
                entrypoint="python",
                args=[
                    "-m", "my.custom_trainer",
                    "--config", "moe_tiny", "--mesh", "ep=3,fsdp=7",
                ],
                env={"XLA_FLAGS": "--xla_force_host_platform_device_count=8"},
            )
        )
        assert "TPX703" in codes(report)
        assert "TPX110" not in codes(report)

    def test_tpx111_unknown_axis_always_errors(self):
        report = analyze(self.heuristic_role("--mesh", "fsd=2"))
        assert "TPX111" in codes(report)
        # ...including on plan-shaped roles (spec hygiene never stands down)
        report = analyze(
            app_with(
                entrypoint="python",
                args=["-m", "t", "--config", "tiny", "--mesh=fsd=2"],
            )
        )
        assert "TPX111" in codes(report)


class TestKernelsRule:
    """TPX112: ``--kernels pallas`` that will silently fall back."""

    def test_pallas_without_tpu_resource_warns(self):
        report = analyze(
            app_with(
                entrypoint="python",
                args=["-m", "t", "--config", "llama3_8b", "--kernels", "pallas"],
            )
        )
        diags = [d for d in report.diagnostics if d.code == "TPX112"]
        assert len(diags) == 1
        assert "non-TPU" in diags[0].message
        assert "fall back" in diags[0].message

    def test_pallas_on_tpu_with_tileable_shapes_is_clean(self):
        # llama3_8b: head_dim 128, dim 4096, seq 256 — all tileable
        report = analyze(
            app_with(
                entrypoint="python",
                args=[
                    "-m", "t", "--config", "llama3_8b",
                    "--kernels", "pallas", "--seq", "256",
                ],
                resource=Resource(tpu=TpuSlice("v5e", 8)),
            )
        )
        assert "TPX112" not in codes(report)

    def test_pallas_untileable_shapes_warn_even_on_tpu(self):
        # tiny: head_dim 16, dim 64 — neither kernel can tile
        report = analyze(
            app_with(
                entrypoint="python",
                args=["-m", "t", "--config", "tiny", "--kernels=pallas"],
                resource=Resource(tpu=TpuSlice("v5e", 8)),
            )
        )
        diags = [d for d in report.diagnostics if d.code == "TPX112"]
        assert len(diags) == 1
        assert "head_dim 16" in diags[0].message
        assert "reference" in diags[0].message

    def test_pallas_ragged_seq_warns(self):
        report = analyze(
            app_with(
                entrypoint="python",
                args=[
                    "-m", "t", "--config", "llama3_8b",
                    "--kernels", "pallas", "--seq", "100",
                ],
                resource=Resource(tpu=TpuSlice("v5e", 8)),
            )
        )
        diags = [d for d in report.diagnostics if d.code == "TPX112"]
        assert len(diags) == 1 and "seq 100" in diags[0].message

    def test_reference_and_interpret_never_fire(self):
        for kernels in ("reference", "interpret"):
            report = analyze(
                app_with(
                    entrypoint="python",
                    args=["-m", "t", "--config", "tiny", "--kernels", kernels],
                )
            )
            assert "TPX112" not in codes(report)


class TestTpuSliceEdgeCases:
    """Satellite: TpuSlice naming/shape edge cases backing the TPX1xx rules."""

    def test_invalid_accelerator_type_strings(self):
        for bad in ("v5litepod", "v5litepod-0", "v9-8", "potato-4"):
            with pytest.raises(ValueError):
                TpuSlice.from_type(bad)

    def test_topology_must_factor_chip_count(self):
        with pytest.raises(ValueError, match="topology"):
            TpuSlice("v5e", 8, "2x3")

    def test_cores_vs_chips_naming(self):
        # v2..v5p count TensorCores in the type suffix; v5e/v6e count chips
        assert TpuSlice.from_type("v5p-32").chips == 16
        assert TpuSlice.from_type("v4-16").chips == 8
        assert TpuSlice.from_type("v5litepod-16").chips == 16
        assert TpuSlice.from_type("v6e-8").chips == 8

    def test_accelerator_type_round_trip(self):
        assert TpuSlice("v5p", 16).accelerator_type == "v5p-32"
        assert TpuSlice("v5e", 8).accelerator_type == "v5litepod-8"
        # aliases normalize on construction
        assert TpuSlice("v5litepod", 8).accelerator == "v5e"
        assert TpuSlice("v5lite", 4).accelerator == "v5e"

    def test_host_layout(self):
        # single-host v5e slice uses the full 8-chip host ...
        assert TpuSlice("v5e", 8).hosts == 1
        # ... but multi-host slices are built from 4-chip VMs
        assert TpuSlice("v5e", 16).hosts == 4
        assert TpuSlice("v5p", 16).hosts == 4


# ---------------------------------------------------------------------------
# TPX2xx env / macros / ports / mounts
# ---------------------------------------------------------------------------


class TestEnvRules:
    def test_launcher_owned_env_is_error(self):
        report = analyze(app_with(env={"TPX_REPLICA_ID": "0"}))
        assert codes(report) == ["TPX201"]

    def test_reserved_prefix_is_warning(self):
        report = analyze(app_with(env={"TPX_MY_THING": "x"}))
        assert codes(report) == ["TPX202"]

    def test_documented_knobs_are_silent(self):
        report = analyze(
            app_with(env={"TPX_RESUME_STEP": "5", "TPU_SKIP_MDS_QUERY": "1"})
        )
        assert report.diagnostics == []

    def test_jax_env_is_info(self):
        report = analyze(app_with(env={"JAX_PLATFORMS": "cpu"}))
        assert codes(report) == ["TPX203"]
        assert not report.has_errors


class TestMacroRules:
    def test_unknown_macro_warns(self):
        report = analyze(app_with(args=["--out", "${output_dir}"]))
        assert codes(report) == ["TPX204"]

    def test_known_macros_and_escapes_are_silent(self):
        report = analyze(
            app_with(args=["--id", "${app_id}", "--replica", "${replica_id}", "$${HOME}"])
        )
        assert report.diagnostics == []


class TestPortAndMountRules:
    def test_duplicate_port(self):
        report = analyze(app_with(port_map={"http": 8080, "grpc": 8080}))
        assert codes(report) == ["TPX210"]

    def test_port_out_of_range(self):
        report = analyze(app_with(port_map={"http": 70000}))
        assert codes(report) == ["TPX211"]

    def test_serve_port_without_port_map_warns(self):
        report = analyze(app_with(args=["--config", "tiny", "--port", "8000"]))
        assert codes(report) == ["TPX212"]
        (d,) = report.diagnostics
        assert d.severity == Severity.WARNING
        assert "port_map" in d.hint

    def test_serve_port_equals_form_detected(self):
        report = analyze(app_with(args=["--port=9000"]))
        assert codes(report) == ["TPX212"]

    def test_mapped_serve_port_is_silent(self):
        report = analyze(
            app_with(args=["--port", "8000"], port_map={"http": 8000})
        )
        assert report.diagnostics == []

    def test_ephemeral_and_non_numeric_ports_are_silent(self):
        # port 0 means "OS picks"; a macro value is not statically checkable
        report = analyze(
            app_with(args=["--port", "0", "--port", "${replica_id}"])
        )
        assert report.diagnostics == []

    def test_disagg_role_without_transfer_path_errors(self):
        report = analyze(app_with(args=["--serve-role", "prefill"]))
        assert codes(report) == ["TPX213"]
        (d,) = report.diagnostics
        assert d.severity == Severity.ERROR
        assert "--kv-transfer" in d.hint

    def test_disagg_decode_equals_form_detected(self):
        report = analyze(app_with(args=["--serve-role=decode"]))
        assert codes(report) == ["TPX213"]

    def test_disagg_role_with_transfer_arg_is_silent(self):
        report = analyze(
            app_with(
                args=[
                    "--serve-role",
                    "prefill",
                    "--kv-transfer",
                    "http:http://127.0.0.1:8100",
                ]
            )
        )
        assert report.diagnostics == []

    def test_disagg_role_with_metadata_is_silent(self):
        report = analyze(
            app_with(
                args=["--serve-role", "decode"],
                metadata={"tpx/kv_transfer": "file:/var/spool/tpx-kv"},
            )
        )
        assert report.diagnostics == []

    def test_unified_serve_role_is_silent(self):
        report = analyze(app_with(args=["--serve-role", "unified"]))
        assert report.diagnostics == []

    def test_disagg_component_wires_both_roles_clean(self):
        from torchx_tpu.components.serve import generate_server_disagg

        report = analyze(generate_server_disagg("llama3_1b"))
        assert "TPX213" not in codes(report)

    def test_slo_on_unscrapable_backend_warns(self):
        report = analyze(
            app_with(args=["--slo", "p99-ttft"]), scheduler="tpu_vm"
        )
        assert "TPX214" in codes(report)
        d = next(d for d in report.diagnostics if d.code == "TPX214")
        assert d.severity == Severity.WARNING
        assert "metricz_scrape" in d.message
        assert "textfile" in d.hint

    def test_slo_equals_form_and_metadata_detected(self):
        report = analyze(app_with(args=["--slo=goodput"]), scheduler="tpu_vm")
        assert "TPX214" in codes(report)
        report = analyze(
            app_with(metadata={"tpx/slo": "p99-ttft"}), scheduler="tpu_vm"
        )
        assert "TPX214" in codes(report)

    def test_slo_on_scrapable_backend_is_silent(self):
        for backend in ("local", "local_docker", "gke", "slurm"):
            report = analyze(
                app_with(args=["--slo", "p99-ttft"]), scheduler=backend
            )
            assert "TPX214" not in codes(report), backend

    def test_no_slo_declared_is_silent(self):
        report = analyze(app_with(), scheduler="tpu_vm")
        assert "TPX214" not in codes(report)

    def test_profile_on_unscrapable_backend_warns(self):
        report = analyze(app_with(args=["--profile"]), scheduler="tpu_vm")
        assert "TPX215" in codes(report)
        d = next(d for d in report.diagnostics if d.code == "TPX215")
        assert d.severity == Severity.WARNING
        assert "metricz_scrape" in d.message
        assert "tpx profile" in d.hint

    def test_profile_env_switch_detected(self):
        report = analyze(
            app_with(env={"TPX_PROFILE": "1"}), scheduler="tpu_vm"
        )
        assert "TPX215" in codes(report)
        # a disabled switch is silent
        report = analyze(
            app_with(env={"TPX_PROFILE": "0"}), scheduler="tpu_vm"
        )
        assert "TPX215" not in codes(report)

    def test_profile_dir_flag_does_not_trigger(self):
        # --profile-dir is the xprof trace flag, a different feature
        report = analyze(
            app_with(args=["--profile-dir", "/tmp/x"]), scheduler="tpu_vm"
        )
        assert "TPX215" not in codes(report)

    def test_profile_on_scrapable_backend_is_silent(self):
        for backend in ("local", "local_docker", "gke", "slurm"):
            report = analyze(
                app_with(args=["--profile"]), scheduler=backend
            )
            assert "TPX215" not in codes(report), backend

    def test_duplicate_mount_dst(self):
        report = analyze(
            app_with(
                mounts=[
                    BindMount(src_path="/a", dst_path="/x"),
                    BindMount(src_path="/b", dst_path="/x"),
                ]
            )
        )
        assert codes(report) == ["TPX220"]

    def test_relative_mount_dst_warns(self):
        report = analyze(
            app_with(mounts=[BindMount(src_path="/a", dst_path="data")])
        )
        assert codes(report) == ["TPX221"]

    def test_parse_mounts_rejects_duplicate_destinations(self):
        with pytest.raises(ValueError, match="duplicate mount destination"):
            parse_mounts(
                ["type=bind", "src=/a", "dst=/x", "type=bind", "src=/b", "dst=/x"]
            )
        # distinct destinations still parse
        mounts = parse_mounts(
            ["type=bind", "src=/a", "dst=/x", "type=bind", "src=/b", "dst=/y"]
        )
        assert [m.dst_path for m in mounts] == ["/x", "/y"]


# ---------------------------------------------------------------------------
# TPX3xx scheduler capabilities
# ---------------------------------------------------------------------------


class TestCapabilityRules:
    def test_capabilities_for_builtin_backends(self):
        local = capabilities_for("local")
        assert local is not None and local.multislice and local.classifies_preemption
        tpu_vm = capabilities_for("tpu_vm")
        assert tpu_vm is not None and tpu_vm.requires_tpu and not tpu_vm.mounts
        gke = capabilities_for("gke")
        assert gke is not None and gke.mounts and gke.multislice
        assert capabilities_for("no_such_backend") is None

    def test_unknown_scheduler_reports_info_only(self):
        report = analyze(app_with(), scheduler="no_such_backend")
        assert codes(report) == ["TPX300"]
        assert not report.has_errors

    def test_mounts_on_backend_without_mounts(self):
        report = analyze(
            app_with(mounts=[BindMount(src_path="/a", dst_path="/x")]),
            scheduler="tpu_vm",
        )
        assert "TPX301" in codes(report)

    def test_multi_role_on_single_role_backend(self):
        app = AppDef(
            name="app",
            roles=[
                Role(name="a", image="i", entrypoint="e"),
                Role(name="b", image="i", entrypoint="e"),
            ],
        )
        report = analyze(app, scheduler="tpu_vm")
        assert "TPX303" in codes(report)

    def test_multislice_on_single_slice_backend(self):
        report = analyze(
            app_with(num_replicas=2, resource=Resource(tpu=TpuSlice("v5e", 4))),
            scheduler="slurm",
        )
        assert "TPX304" in codes(report)

    def test_tpu_only_backend_needs_tpu(self):
        report = analyze(app_with(), scheduler="tpu_vm")
        assert "TPX305" in codes(report)

    def test_retries_without_native_restarts(self):
        report = analyze(app_with(max_retries=3), scheduler="tpu_vm")
        assert "TPX306" in codes(report)
        # docker restarts natively: no warning
        report = analyze(app_with(max_retries=3), scheduler="local_docker")
        assert "TPX306" not in codes(report)

    def test_concrete_resources_unset(self):
        report = analyze(app_with(), scheduler="vertex")
        assert "TPX307" in codes(report)
        report = analyze(
            app_with(resource=Resource(cpu=8, memMB=1024)), scheduler="vertex"
        )
        assert "TPX307" not in codes(report)

    def test_explicit_capabilities_override_registry(self):
        caps = SchedulerCapabilities(mounts=True, delete=True)
        report = analyze(
            app_with(mounts=[BindMount(src_path="/a", dst_path="/x")]),
            scheduler="tpu_vm",
            capabilities=caps,
        )
        assert "TPX301" not in codes(report)


# ---------------------------------------------------------------------------
# TPX4xx supervisor / retry coherence
# ---------------------------------------------------------------------------


class TestRetryRules:
    def test_negative_retries(self):
        assert "TPX402" in codes(analyze(app_with(max_retries=-1)))

    def test_replica_retry_on_tpu_role(self):
        report = analyze(
            app_with(
                retry_policy=RetryPolicy.REPLICA,
                resource=Resource(tpu=TpuSlice("v5e", 4)),
            )
        )
        assert "TPX401" in codes(report)
        # REPLICA on a CPU role is fine
        assert "TPX401" not in codes(analyze(app_with(retry_policy=RetryPolicy.REPLICA)))

    def test_preemption_budget_on_blind_backend(self):
        policy = SupervisorPolicy(max_preemptions=5)
        report = analyze(app_with(), scheduler="vertex", policy=policy)
        assert "TPX403" in codes(report)
        report = analyze(app_with(), scheduler="local", policy=policy)
        assert "TPX403" not in codes(report)

    def test_resume_env_collision(self):
        policy = SupervisorPolicy()
        report = analyze(
            app_with(env={policy.resume_env: "7"}), policy=policy
        )
        assert "TPX404" in codes(report)


# ---------------------------------------------------------------------------
# The acceptance-criteria broken AppDef
# ---------------------------------------------------------------------------


class TestBrokenAppAcceptance:
    def test_reports_at_least_three_distinct_codes(self):
        report = analyze(broken_app(), scheduler="tpu_vm")
        distinct = set(codes(report))
        assert {"TPX102", "TPX201", "TPX220", "TPX301"} <= distinct
        assert len({c for c in distinct if c}) >= 3
        assert report.has_errors


# ---------------------------------------------------------------------------
# Runner gate
# ---------------------------------------------------------------------------


class _StubScheduler(Scheduler[dict]):
    def __init__(self, session_name: str, **kwargs):
        super().__init__("stub", session_name)
        self._counter = 0
        self.apps: dict[str, AppState] = {}

    def run_opts(self) -> runopts:
        return runopts()

    def _submit_dryrun(self, app: AppDef, cfg: Mapping[str, CfgVal]):
        return AppDryRunInfo({"app": app})

    def schedule(self, dryrun_info) -> str:
        self._counter += 1
        app_id = f"stub_app_{self._counter}"
        self.apps[app_id] = AppState.RUNNING
        return app_id

    def describe(self, app_id: str) -> Optional[DescribeAppResponse]:
        if app_id not in self.apps:
            return None
        return DescribeAppResponse(app_id=app_id, state=self.apps[app_id])

    def _cancel_existing(self, app_id: str) -> None:
        self.apps[app_id] = AppState.CANCELLED

    def list(self):
        return [ListAppResponse(app_id=a, state=s) for a, s in self.apps.items()]


@pytest.fixture
def runner():
    stub = _StubScheduler("test")
    r = Runner("test", {"stub": lambda session_name, **kw: stub})
    yield r
    r.close()


class TestRunnerGate:
    def test_submit_refuses_broken_app(self, runner):
        with pytest.raises(LintError) as ei:
            runner.run(broken_app(), "stub")
        report = ei.value.report
        # stub has no capability profile, so TPX301 drops out, but the
        # AppDef-intrinsic errors survive
        assert {"TPX102", "TPX201", "TPX220"} <= set(codes(report))

    def test_dryrun_refuses_broken_app(self, runner):
        with pytest.raises(LintError):
            runner.dryrun(broken_app(), "stub")

    def test_no_lint_flag_bypasses(self, runner):
        handle = runner.run(broken_app(), "stub", no_lint=True)
        assert handle.startswith("stub://")

    def test_env_escape_hatch(self, runner, monkeypatch):
        monkeypatch.setenv("TPX_NO_LINT", "1")
        handle = runner.run(broken_app(), "stub")
        assert handle.startswith("stub://")

    def test_clean_app_passes_gate(self, runner):
        handle = runner.run(app_with(), "stub")
        assert handle.startswith("stub://")

    def test_warnings_do_not_gate(self, runner):
        # reserved-prefix env is only a warning
        handle = runner.run(app_with(env={"TPX_MY_KNOB": "x"}), "stub")
        assert handle.startswith("stub://")


# ---------------------------------------------------------------------------
# Builtin components pass their own linter (satellite)
# ---------------------------------------------------------------------------


class TestBuiltinSelfLint:
    @pytest.mark.parametrize("name", sorted(get_components()))
    def test_builtin_component_is_clean(self, name):
        report = analyze_component(name)
        assert not report.errors, report.render()
        assert not report.warnings, report.render()


# ---------------------------------------------------------------------------
# file_linter: codes, string annotations, PEP 604 unions (satellite)
# ---------------------------------------------------------------------------


class TestFileLinter:
    def test_string_annotations_accepted(self):
        src = (
            "def c(x: 'str', n: \"int\" = 1) -> 'AppDef':\n"
            '    """A component.\n\n    Args:\n        x: x.\n        n: n.\n    """\n'
        )
        assert validate_source(src, "c") == []

    def test_pep604_unions_accepted(self):
        src = (
            "def c(x: str | None = None, ns: list[str] | None = None) -> AppDef:\n"
            '    """A component.\n\n    Args:\n        x: x.\n        ns: ns.\n    """\n'
        )
        assert validate_source(src, "c") == []

    def test_missing_annotation_code(self):
        msgs = validate_source('def c(x) -> AppDef:\n    """D."""\n', "c")
        assert [m.code for m in msgs] == ["TPX002"]

    def test_kwargs_code(self):
        msgs = validate_source('def c(**kw: str) -> AppDef:\n    """D."""\n', "c")
        assert "TPX004" in [m.code for m in msgs]

    def test_bad_return_code(self):
        msgs = validate_source('def c() -> int:\n    """D."""\n', "c")
        assert "TPX005" in [m.code for m in msgs]

    def test_docstring_warning_only_with_include_warnings(self):
        src = "def c() -> AppDef:\n    pass\n"
        assert validate_source(src, "c") == []
        warnings = validate_source(src, "c", include_warnings=True)
        assert [m.code for m in warnings] == ["TPX006"]

    def test_syntax_error_code(self):
        msgs = validate_source("def c(:\n", "c")
        assert [m.code for m in msgs] == ["TPX001"]


# ---------------------------------------------------------------------------
# CLI: tpx lint (flags before the target — REMAINDER swallows the rest)
# ---------------------------------------------------------------------------


class TestCmdLint:
    def _run(self, argv, capsys):
        with pytest.raises(SystemExit) as ei:
            main(argv)
        out = capsys.readouterr()
        return ei.value.code or 0, out.out, out.err

    def test_lint_clean_component(self, capsys):
        rc, out, _ = self._run(["lint", "utils.echo"], capsys)
        assert rc == 0
        assert "clean" in out

    def test_lint_bad_appdef_json_text(self, tmp_path, capsys):
        path = tmp_path / "bad.json"
        path.write_text(json.dumps(appdef_to_dict(broken_app())))
        rc, out, _ = self._run(["lint", "-s", "tpu_vm", str(path)], capsys)
        assert rc == 1
        for code in ("TPX102", "TPX201", "TPX220", "TPX301"):
            assert code in out

    def test_lint_bad_appdef_json_json(self, tmp_path, capsys):
        path = tmp_path / "bad.json"
        path.write_text(json.dumps(appdef_to_dict(broken_app())))
        rc, out, _ = self._run(
            ["lint", "-s", "tpu_vm", "--json", str(path)], capsys
        )
        assert rc == 1
        doc = json.loads(out)
        assert doc["version"] == 1
        assert doc["scheduler"] == "tpu_vm"
        assert doc["summary"]["error"] >= 3
        assert len({d["code"] for d in doc["diagnostics"]}) >= 3

    def test_lint_good_appdef_json(self, tmp_path, capsys):
        path = tmp_path / "good.json"
        path.write_text(json.dumps(appdef_to_dict(app_with())))
        rc, out, _ = self._run(["lint", "-s", "local", str(path)], capsys)
        assert rc == 0
        assert "clean" in out

    def test_lint_unknown_scheduler_is_usage_error(self, capsys):
        rc, _, err = self._run(["lint", "-s", "nope", "utils.echo"], capsys)
        assert rc == 2
        assert "unknown scheduler" in err

    def test_lint_no_target_is_usage_error(self, capsys):
        rc, _, err = self._run(["lint"], capsys)
        assert rc == 2
        assert "target" in err

    def test_lint_unreadable_json_is_usage_error(self, tmp_path, capsys):
        rc, _, err = self._run(["lint", str(tmp_path / "missing.json")], capsys)
        assert rc == 2

    def test_lint_component_with_args_lints_appdef(self, capsys):
        rc, out, _ = self._run(
            ["lint", "-s", "local", "--", "utils.echo", "--msg", "hi"], capsys
        )
        assert rc == 0

    def test_lint_component_without_required_args_is_info(self, capsys):
        # dist.ddp needs --script; materialization fails -> TPX007 info, rc 0
        rc, out, _ = self._run(["lint", "dist.ddp"], capsys)
        assert rc == 0
        assert "TPX007" in out


class TestRunNoLintFlag:
    def test_run_dryrun_refuses_broken_stdin_spec(self, tmp_path, capsys, monkeypatch):
        import io
        import sys as _sys

        spec = json.dumps(appdef_to_dict(broken_app()))
        monkeypatch.setattr(_sys, "stdin", io.StringIO(spec))
        with pytest.raises(SystemExit) as ei:
            main(["run", "-s", "local", "--dryrun", "--stdin"])
        assert ei.value.code == 1
        assert "preflight lint" in capsys.readouterr().err

    def test_run_dryrun_no_lint_bypasses(self, tmp_path, capsys, monkeypatch):
        import io
        import sys as _sys

        spec = json.dumps(appdef_to_dict(broken_app()))
        monkeypatch.setattr(_sys, "stdin", io.StringIO(spec))
        main(["run", "-s", "local", "--dryrun", "--no-lint", "--stdin"])
        assert "=== APPLICATION ===" in capsys.readouterr().out


class TestRecoveryRules:
    def test_checkpoint_resume_without_ckpt_flag_warns(self):
        policy = SupervisorPolicy(checkpoint_dir="/ckpt", max_preemptions=2)
        report = analyze(app_with(), policy=policy)
        assert "TPX503" in codes(report)
        d = next(d for d in report.diagnostics if d.code == "TPX503")
        assert d.severity is Severity.WARNING
        assert "step 0" in d.message
        assert "--ckpt-dir /ckpt" in d.hint

    def test_role_passing_a_ckpt_flag_is_coherent(self):
        policy = SupervisorPolicy(checkpoint_dir="/ckpt", max_preemptions=2)
        report = analyze(
            app_with(args=["--ckpt-dir", "/ckpt"]), policy=policy
        )
        assert "TPX503" not in codes(report)
        # = -joined and snake_case spellings count too
        report = analyze(
            app_with(args=["--checkpoint-dir=/ckpt"]), policy=policy
        )
        assert "TPX503" not in codes(report)
        report = analyze(app_with(args=["--ckpt_dir", "/c"]), policy=policy)
        assert "TPX503" not in codes(report)

    def test_silent_without_checkpoint_dir_or_resume_budgets(self):
        # no checkpoint_dir: nothing to resume from — not this rule's beat
        report = analyze(app_with(), policy=SupervisorPolicy(max_preemptions=5))
        assert "TPX503" not in codes(report)
        # checkpoint_dir but zero resume-relevant budgets: never resubmits
        quiet = SupervisorPolicy(
            checkpoint_dir="/ckpt",
            max_preemptions=0,
            max_infra_retries=0,
            max_hang_retries=0,
        )
        assert "TPX503" not in codes(analyze(app_with(), policy=quiet))
        # no policy at all
        assert "TPX503" not in codes(analyze(app_with()))

    def test_hang_budget_alone_arms_the_rule(self):
        policy = SupervisorPolicy(
            checkpoint_dir="/ckpt",
            max_preemptions=0,
            max_infra_retries=0,
            max_hang_retries=2,
        )
        assert "TPX503" in codes(analyze(app_with(), policy=policy))
