"""Tracker backends, in-job AppRun, plugins registry, result tracking."""

import os

import pytest

from torchx_tpu.plugins import get_registry, register
from torchx_tpu.plugins._registration import clear_registrations
from torchx_tpu.runtime.tracking import FsspecResultTracker
from torchx_tpu.specs.api import Resource, TpuSlice
from torchx_tpu.tracker.api import (
    AppRun,
    tracker_config_env_vars,
    trackers_from_environ,
)
from torchx_tpu.tracker.backend.fsspec import FsspecTracker


class TestFsspecTracker:
    def test_metadata_roundtrip(self, tmp_path):
        t = FsspecTracker(str(tmp_path))
        t.add_metadata("run1", lr=0.1, model="llama")
        t.add_metadata("run1", step=5)
        md = t.metadata("run1")
        assert md == {"lr": 0.1, "model": "llama", "step": 5}

    def test_artifacts_roundtrip(self, tmp_path):
        t = FsspecTracker(str(tmp_path))
        t.add_artifact("run1", "ckpt", "/mnt/ckpt/100", {"step": 100})
        arts = t.artifacts("run1")
        assert arts["ckpt"].path == "/mnt/ckpt/100"
        assert arts["ckpt"].metadata == {"step": 100}

    def test_lineage(self, tmp_path):
        t = FsspecTracker(str(tmp_path))
        t.add_source("child", "parent-run", artifact_name="ckpt")
        (src,) = list(t.sources("child"))
        assert src.source_run_id == "parent-run"
        assert src.artifact_name == "ckpt"
        assert list(t.sources("child", artifact_name="other")) == []

    def test_run_ids_with_handle_chars(self, tmp_path):
        t = FsspecTracker(str(tmp_path))
        run_id = "local://session/app_123"
        t.add_metadata(run_id, a=1)
        assert list(t.run_ids()) == [run_id]

    def test_empty(self, tmp_path):
        t = FsspecTracker(str(tmp_path))
        assert t.metadata("nope") == {}
        assert t.artifacts("nope") == {}


class TestAppRunFromEnv:
    def test_env_roundtrip(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TPX_JOB_ID", "local://s/app1")
        monkeypatch.setenv("TPX_TRACKERS", "fsspec")
        monkeypatch.setenv("TPX_TRACKER_FSSPEC_CONFIG", str(tmp_path))
        AppRun._instance = None
        run = AppRun.run_from_env()
        assert run.id == "local://s/app1"
        run.add_metadata(objective=0.5)
        t = FsspecTracker(str(tmp_path))
        assert t.metadata("local://s/app1")["objective"] == 0.5
        AppRun._instance = None

    def test_no_env_is_noop(self, monkeypatch):
        monkeypatch.delenv("TPX_JOB_ID", raising=False)
        monkeypatch.delenv("TPX_TRACKERS", raising=False)
        AppRun._instance = None
        run = AppRun.run_from_env()
        assert run.id == "<unknown_run_id>"
        run.add_metadata(x=1)  # no backends: must not raise
        AppRun._instance = None

    def test_parent_lineage_autolink(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TPX_JOB_ID", "local://s/child")
        monkeypatch.setenv("TPX_TRACKERS", "fsspec")
        monkeypatch.setenv("TPX_TRACKER_FSSPEC_CONFIG", str(tmp_path))
        monkeypatch.setenv("TPX_PARENT_RUN_ID", "local://s/parent")
        AppRun._instance = None
        AppRun.run_from_env()
        srcs = list(FsspecTracker(str(tmp_path)).sources("local://s/child"))
        assert srcs[0].source_run_id == "local://s/parent"
        AppRun._instance = None

    def test_client_env_injection(self):
        env = tracker_config_env_vars(
            parent_run_id="p1", trackers={"fsspec": "/mnt/exp"}
        )
        assert env["TPX_TRACKERS"] == "fsspec"
        assert env["TPX_TRACKER_FSSPEC_CONFIG"] == "/mnt/exp"
        assert env["TPX_PARENT_RUN_ID"] == "p1"

    def test_client_env_injection_empty(self):
        assert tracker_config_env_vars(trackers={}) == {}


class TestResultTracker:
    def test_roundtrip(self, tmp_path):
        t = FsspecResultTracker(str(tmp_path))
        t["trial/1"] = {"loss": 0.5}
        assert t["trial/1"] == {"loss": 0.5}

    def test_missing_key(self, tmp_path):
        with pytest.raises(KeyError):
            FsspecResultTracker(str(tmp_path))["nope"]


class TestPlugins:
    def teardown_method(self):
        clear_registrations()
        get_registry(invalidate_cache=True)

    def test_register_scheduler(self):
        @register.scheduler("mysched", alias="ms")
        def create(session_name, **kw):  # noqa: ANN001
            return "sched-instance"

        reg = get_registry(invalidate_cache=True)
        assert reg.schedulers["mysched"] is create
        assert reg.schedulers["ms"] is create
        from torchx_tpu.schedulers import get_scheduler_factories

        assert "mysched" in get_scheduler_factories()

    def test_register_named_resource_with_fractions(self):
        @register.named_resource("superpod", fractions=True)
        def superpod():
            return Resource(cpu=208, memMB=1000, tpu=TpuSlice("v5e", 8))

        reg = get_registry(invalidate_cache=True)
        assert set(reg.named_resources) >= {
            "superpod",
            "superpod_half",
            "superpod_quarter",
        }
        half = reg.named_resources["superpod_half"]()
        assert half.tpu.chips == 4
        assert half.cpu == 104
        assert half.tags["tpx.share"] == "half"
        quarter = reg.named_resources["superpod_quarter"]()
        assert quarter.tpu.chips == 2

    def test_named_resource_visible_after_cache_invalidation(self):
        from torchx_tpu.specs import named_resources

        _ = named_resources["cpu_small"]  # populate the specs-level cache

        @register.named_resource("late_resource")
        def late():
            return Resource(cpu=7, memMB=7)

        get_registry(invalidate_cache=True)
        assert named_resources["late_resource"].cpu == 7

    def test_plugin_tracker_with_colon_name(self, tmp_path, monkeypatch):
        from torchx_tpu.tracker.backend.fsspec import FsspecTracker as FT

        @register.tracker("myorg:prod")
        def create(config):  # noqa: ANN001
            return FT(str(tmp_path))

        get_registry(invalidate_cache=True)
        monkeypatch.setenv("TPX_TRACKERS", "myorg:prod")
        assert "myorg:prod" in trackers_from_environ()

    def test_register_tracker_reachable_from_env(self, tmp_path, monkeypatch):
        from torchx_tpu.tracker.backend.fsspec import FsspecTracker as FT

        @register.tracker("custom_t")
        def create(config):  # noqa: ANN001
            return FT(str(tmp_path))

        get_registry(invalidate_cache=True)
        monkeypatch.setenv("TPX_TRACKERS", "custom_t")
        trackers = trackers_from_environ()
        assert "custom_t" in trackers

    def test_namespace_package_discovery(self, tmp_path, monkeypatch):
        ns = tmp_path / "tpx_plugins"
        ns.mkdir()
        (ns / "myplug.py").write_text(
            "def register(registrar):\n"
            "    registrar.scheduler('ns_sched', lambda session_name, **kw: 'x')\n"
        )
        monkeypatch.syspath_prepend(str(tmp_path))
        reg = get_registry(invalidate_cache=True)
        assert "ns_sched" in reg.schedulers

    def test_broken_namespace_plugin_captured(self, tmp_path, monkeypatch):
        ns = tmp_path / "tpx_plugins"
        ns.mkdir()
        (ns / "broken.py").write_text("raise RuntimeError('boom')\n")
        monkeypatch.syspath_prepend(str(tmp_path))
        reg = get_registry(invalidate_cache=True)
        assert any("broken" in e.plugin for e in reg.errors)
        from torchx_tpu.plugins import error_report

        assert "boom" in error_report()

    def test_plugins_disabled_by_env(self, tmp_path, monkeypatch):
        @register.scheduler("always_there")
        def create(session_name, **kw):  # noqa: ANN001
            return "x"

        ns = tmp_path / "tpx_plugins"
        ns.mkdir()
        (ns / "p.py").write_text(
            "def register(r):\n    r.scheduler('ns_only', lambda **kw: 'y')\n"
        )
        monkeypatch.syspath_prepend(str(tmp_path))
        monkeypatch.setenv("TPX_PLUGINS_SOURCE", "0")
        reg = get_registry(invalidate_cache=True)
        assert "ns_only" not in reg.schedulers
        # programmatic registrations always apply
        assert "always_there" in reg.schedulers
