"""Negative coverage for the dryrun remat gate (__graft_entry__).

The dryrun gate exists to fail configs whose shardings force XLA's
involuntary-full-rematerialization fallback. The positive path (a good
config passes) is covered by test_model_stack's dryrun tests; this file
proves the gate actually FIRES: a known-bad resharding compiles with the
"Involuntary full rematerialization" warning, and
``check_partitioner_output`` turns that captured output into an error.
"""

from __future__ import annotations

import importlib.util
import os
import subprocess
import sys
import textwrap

import pytest


def _graft():
    spec = importlib.util.spec_from_file_location(
        "graft_entry_remat", "__graft_entry__.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestCheckPartitionerOutput:
    def test_clean_output_passes(self):
        mod = _graft()
        mod.check_partitioner_output("compiled ok\nno warnings here\n")

    def test_remat_warning_raises(self):
        mod = _graft()
        with pytest.raises(RuntimeError, match="rematerialization"):
            mod.check_partitioner_output(
                f"blah\n{mod.REMAT_WARNING} for op %dot.1\nblah\n"
            )

    def test_gspmd_deprecation_with_shardy_raises(self):
        mod = _graft()
        out = (
            "shardy=on\n"
            "W0000 GSPMD sharding propagation is going to be deprecated\n"
        )
        with pytest.raises(RuntimeError, match="GSPMD"):
            mod.check_partitioner_output(out)

    def test_gspmd_deprecation_without_shardy_passes(self):
        # Old jax without Shardy legitimately compiles through GSPMD.
        mod = _graft()
        mod.check_partitioner_output(
            "W0000 GSPMD sharding propagation is going to be deprecated\n"
        )


# A resharding the partitioner can only honor by replicating the whole
# tensor: dim 0 is laid out on mesh axis "a", then immediately demanded
# on ("a","b") over dim 1 — verified to print the involuntary-full-remat
# warning on jax's CPU backend with 8 forced devices.
_BAD_RESHARD = textwrap.dedent(
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental.shard_map import shard_map  # noqa: F401  (forces SPMD init)
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()).reshape(2, 2, 2), ("a", "b", "c"))

    def f(x):
        x = jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P("a", None, None)))
        x = x * 2
        x = jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P(None, ("a", "b"), None))
        )
        return x

    x = jnp.ones((8, 8, 4), jnp.float32)
    print(jax.jit(f)(x).sum())
    """
)


@pytest.mark.integ
class TestRematGateFires:
    def test_known_bad_sharding_trips_the_gate(self):
        mod = _graft()
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
        ).strip()
        proc = subprocess.run(
            [sys.executable, "-c", _BAD_RESHARD],
            env=env,
            capture_output=True,
            text=True,
            timeout=600,
        )
        output = proc.stdout + proc.stderr
        assert proc.returncode == 0, output  # it compiles — the gate is the catch
        assert mod.REMAT_WARNING in output, output
        with pytest.raises(RuntimeError, match="involuntary full remat"):
            mod.check_partitioner_output(output)
