"""Pipeline subsystem tests: the DAG model, the eval app's digest chain,
the router's rollout seams (drain exclusion, canary weights), the serve
pool's zero-drop per-replica checkpoint rollout, the promotion
controller's gates, the end-to-end engine on the real local scheduler
(happy path, forced eval regression, SLO burn, daemon kill+restart
mid-canary), the deprecation shims, and the TPX603 analyze rule."""

import hashlib
import json
import os
import threading
import time

import pytest

from torchx_tpu.pipelines.dag import (
    Artifact,
    PipelineSpec,
    PipelineStage,
    checkpoint_artifact,
    resolve_args,
    score_artifact,
)
from torchx_tpu.pipelines.promote import PROMOTED, ROLLED_BACK, PromotionController
from torchx_tpu.serve.pool import LeastLoadedRouter, ReplicaStatus


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def write_checkpoint(ckpt_dir: str, payload: bytes = b"weights-v1") -> str:
    """A minimal finalized checkpoint: step-1 payload + MANIFEST.json with
    the writer's sha256 relpath+bytes digest. Returns the digest."""
    step_dir = os.path.join(ckpt_dir, "1")
    os.makedirs(step_dir, exist_ok=True)
    fp = os.path.join(step_dir, "w.bin")
    with open(fp, "wb") as f:
        f.write(payload)
    h = hashlib.sha256()
    h.update(os.path.relpath(fp, step_dir).encode())
    h.update(payload)
    digest = h.hexdigest()
    with open(os.path.join(ckpt_dir, "MANIFEST.json"), "w") as f:
        json.dump({"latest_step": 1, "steps": {"1": {"digest": digest}}}, f)
    return digest


def statuses(n: int, healthy=None) -> list:
    healthy = set(range(n)) if healthy is None else set(healthy)
    return [
        ReplicaStatus(replica_id=i, url=f"http://x:{i}", healthy=i in healthy)
        for i in range(n)
    ]


class FakePool:
    """The promotion controller's pool contract, recording every roll."""

    def __init__(self, replicas: int = 4, fail_on=(), block_on=None):
        self.replicas = replicas
        self.router = LeastLoadedRouter()
        self.router.update(statuses(replicas))
        self.rolls: list = []  # (replica_id, ckpt)
        self._fail_on = set(fail_on)
        self._block_on = block_on  # (replica_id, threading.Event)

    def rollout_replica(self, replica_id: int, ckpt: str, **kw) -> bool:
        if self._block_on and replica_id == self._block_on[0]:
            self._block_on[1].wait()
        self.rolls.append((replica_id, ckpt))
        return replica_id not in self._fail_on


# ---------------------------------------------------------------------------
# DAG model
# ---------------------------------------------------------------------------


class TestDagModel:
    def spec(self):
        return PipelineSpec(
            name="p",
            stages=[
                PipelineStage(name="train", kind="train", component="utils.python"),
                PipelineStage(
                    name="eval",
                    kind="eval",
                    component="utils.python",
                    depends_on=["train"],
                    score_file="/tmp/s.json",
                ),
                PipelineStage(
                    name="promote", kind="promote", depends_on=["eval"]
                ),
            ],
        )

    def test_validate_accepts_well_formed(self):
        self.spec().validate()

    def test_generations_are_topological(self):
        gens = self.spec().generations()
        assert [[s.name for s in g] for g in gens] == [
            ["train"],
            ["eval"],
            ["promote"],
        ]

    def test_default_priorities_by_kind(self):
        spec = self.spec()
        assert spec.stage("train").priority == "batch"
        assert spec.stage("eval").priority == "interactive"
        assert spec.stage("promote").priority == "serve"

    def test_rejects_bad_kind(self):
        with pytest.raises(ValueError, match="kind"):
            PipelineStage(name="x", kind="deploy")

    def test_eval_requires_score_file(self):
        with pytest.raises(ValueError, match="score_file"):
            PipelineStage(name="e", kind="eval")

    def test_rejects_duplicate_names(self):
        spec = self.spec()
        spec.stages.append(PipelineStage(name="train", kind="train"))
        with pytest.raises(ValueError, match="duplicate"):
            spec.validate()

    def test_rejects_unknown_dependency(self):
        spec = self.spec()
        spec.stages[1].depends_on = ["nope"]
        with pytest.raises(ValueError, match="unknown"):
            spec.validate()

    def test_rejects_cycle(self):
        spec = self.spec()
        spec.stages[0].depends_on = ["promote"]
        with pytest.raises(ValueError, match="cycle"):
            spec.validate()

    def test_round_trips_through_dict(self):
        spec = self.spec()
        again = PipelineSpec.from_dict(spec.to_dict())
        assert again.to_dict() == spec.to_dict()

    def test_resolve_args_substitutes_artifact_fields(self):
        arts = {
            "train": Artifact(kind="checkpoint", path="/c", digest="abc", step=7)
        }
        out = resolve_args(
            ["--ckpt", "{train.path}", "--expect", "{train.digest}@{train.step}"],
            arts,
        )
        assert out == ["--ckpt", "/c", "--expect", "abc@7"]

    def test_resolve_args_rejects_dangling_reference(self):
        with pytest.raises(KeyError, match="eval"):
            resolve_args(["{eval.score}"], {})

    def test_checkpoint_artifact_reads_manifest(self, tmp_path):
        digest = write_checkpoint(str(tmp_path))
        art = checkpoint_artifact(str(tmp_path))
        assert (art.kind, art.step, art.digest) == ("checkpoint", 1, digest)

    def test_checkpoint_artifact_requires_manifest(self, tmp_path):
        with pytest.raises(ValueError, match="manifest"):
            checkpoint_artifact(str(tmp_path))

    def test_checkpoint_artifact_requires_finalized_step(self, tmp_path):
        (tmp_path / "MANIFEST.json").write_text('{"steps": {}}')
        with pytest.raises(ValueError, match="finalized"):
            checkpoint_artifact(str(tmp_path))

    def test_score_artifact_requires_score(self, tmp_path):
        f = tmp_path / "s.json"
        f.write_text('{"ckpt": "/c"}')
        with pytest.raises(ValueError, match="score"):
            score_artifact(str(f))
        f.write_text('{"score": 0.25, "step": 3}')
        art = score_artifact(str(f))
        assert (art.kind, art.score, art.step) == ("score", 0.25, 3)


# ---------------------------------------------------------------------------
# eval app: digest re-verification
# ---------------------------------------------------------------------------


class TestEvalMain:
    def test_scores_a_verified_checkpoint(self, tmp_path):
        from torchx_tpu.apps.eval_main import main

        write_checkpoint(str(tmp_path / "ckpt"))
        out = str(tmp_path / "score.json")
        rc = main(["--ckpt", str(tmp_path / "ckpt"), "--out", out, "--score", "0.9"])
        assert rc == 0
        doc = json.load(open(out))
        assert doc["score"] == 0.9
        assert doc["step"] == 1
        assert doc["digest"]

    def test_rejects_tampered_payload(self, tmp_path, capsys):
        from torchx_tpu.apps.eval_main import main

        write_checkpoint(str(tmp_path / "ckpt"))
        # corrupt the payload after the manifest recorded its digest
        with open(tmp_path / "ckpt" / "1" / "w.bin", "wb") as f:
            f.write(b"tampered")
        out = str(tmp_path / "score.json")
        rc = main(["--ckpt", str(tmp_path / "ckpt"), "--out", out])
        assert rc == 1
        assert not os.path.exists(out)
        assert "digest mismatch" in capsys.readouterr().err

    def test_digest_derived_score_is_deterministic(self, tmp_path):
        from torchx_tpu.apps.eval_main import main

        write_checkpoint(str(tmp_path / "ckpt"))
        a, b = str(tmp_path / "a.json"), str(tmp_path / "b.json")
        assert main(["--ckpt", str(tmp_path / "ckpt"), "--out", a]) == 0
        assert main(["--ckpt", str(tmp_path / "ckpt"), "--out", b]) == 0
        assert json.load(open(a))["score"] == json.load(open(b))["score"]


# ---------------------------------------------------------------------------
# router: drain exclusion + canary weights
# ---------------------------------------------------------------------------


class TestRouterRollout:
    def test_draining_replica_leaves_split_immediately(self):
        r = LeastLoadedRouter()
        r.update(statuses(2))
        r.mark_draining(0)
        # no probe sweep between mark and pick: 0 must already be gone
        for _ in range(5):
            assert r.pick().replica_id == 1
        r.clear_draining(0)
        # readmitted and now the least loaded: it takes the next pick
        assert r.pick().replica_id == 0

    def test_drain_mark_survives_probe_update(self):
        r = LeastLoadedRouter()
        r.update(statuses(2))
        r.mark_draining(0)
        r.update(statuses(2))  # probe sweep rebuilds the table
        assert r.pick().replica_id == 1

    def test_weight_attracts_traffic(self):
        r = LeastLoadedRouter()
        r.update(statuses(2))
        r.set_weight(1, 4.0)
        # equal load: ties break toward the lower id unless weighted
        picks = [r.pick().replica_id for _ in range(4)]
        assert picks.count(1) > picks.count(0)

    def test_weight_scales_negative_scores_toward_canary(self):
        # a cache bonus can push load negative; weight must still attract
        r = LeastLoadedRouter(cache_bonus=3.0)
        summary = ("d0",)
        r.update(
            [
                ReplicaStatus(
                    replica_id=i,
                    url=f"http://x:{i}",
                    healthy=True,
                    prefix_summary=summary,
                    block_size=4,
                )
                for i in range(2)
            ]
        )
        r.set_weight(1, 4.0)
        from torchx_tpu.serve.prefix_cache import prefix_chain

        tokens = list(range(4))
        assert prefix_chain(tokens, 4)  # sanity: at least one block
        # patch the summaries to actually match the prompt's first chain digest
        chain = prefix_chain(tokens, 4)
        r.update(
            [
                ReplicaStatus(
                    replica_id=i,
                    url=f"http://x:{i}",
                    healthy=True,
                    prefix_summary=(chain[0],),
                    block_size=4,
                )
                for i in range(2)
            ]
        )
        r.set_weight(1, 4.0)
        assert r.pick(tokens).replica_id == 1

    def test_inflight_counts_route_and_record(self):
        r = LeastLoadedRouter()
        r.update(statuses(1))
        assert r.inflight(0) == 0
        r.pick()
        r.pick()
        assert r.inflight(0) == 2
        r.record(0, 0.01)
        assert r.inflight(0) == 1


# ---------------------------------------------------------------------------
# serve pool: zero-drop per-replica rollout
# ---------------------------------------------------------------------------


class TestServePoolRollout:
    def make_pool(self, restarted, drain_log):
        from torchx_tpu.serve.pool import ServePool
        from torchx_tpu.specs.api import AppDef, Role

        app = AppDef(
            name="srv",
            roles=[
                Role(name="server", image="i", entrypoint="x", num_replicas=2)
            ],
        )
        router = LeastLoadedRouter()
        router.update(statuses(2))
        clock = {"t": 0.0}

        def sleep(dt):
            clock["t"] += dt
            # in-flight requests complete while the rollout waits: this is
            # the drain the seam must observe before restarting
            if router.inflight(0) > 0:
                router.record(0, 0.01)

        def restart(rid, ckpt):
            drain_log.append(router.inflight(rid))
            restarted.append((rid, ckpt))

        pool = ServePool(
            runner=object(),
            app=app,
            router=router,
            probe=lambda rid, url: ReplicaStatus(
                replica_id=rid, url=url, healthy=True
            ),
            clock=lambda: clock["t"],
            sleep=sleep,
            restart=restart,
        )
        return pool, router

    def test_rollout_waits_for_inflight_then_restarts(self):
        restarted, drain_log = [], []
        pool, router = self.make_pool(restarted, drain_log)
        # two requests in flight to replica 0 (replica 1 briefly unhealthy
        # so the least-loaded split can't spread them)
        router.update(statuses(2, healthy=[0]))
        router.pick(), router.pick()
        router.update(statuses(2))
        assert router.inflight(0) == 2
        assert pool.rollout_replica(0, "/new/ckpt") is True
        # the restart fired with ZERO requests still in flight (no drops)
        assert restarted == [(0, "/new/ckpt")]
        assert drain_log == [0]
        # the replica rejoined the split after health-confirm
        assert 0 in {router.pick().replica_id for _ in range(4)}

    def test_rollout_fails_on_drain_timeout(self):
        restarted, drain_log = [], []
        pool, router = self.make_pool(restarted, drain_log)
        # a request that never records back
        router._inflight[0] = 1
        pool._sleep = lambda dt: setattr(
            pool, "_now", getattr(pool, "_now", 0.0) + dt
        )
        pool._clock = lambda: getattr(pool, "_now", 0.0)
        assert pool.rollout_replica(0, "/new", drain_timeout_s=0.2) is False
        assert restarted == []
        # the drain mark was cleared even on failure
        assert 0 in {router.pick().replica_id for _ in range(4)}

    def test_restart_exception_fails_rollout(self):
        restarted, drain_log = [], []
        pool, router = self.make_pool(restarted, drain_log)

        def bad_restart(rid, ckpt):
            raise RuntimeError("boom")

        pool._restart = bad_restart
        assert pool.rollout_replica(0, "/new") is False


# ---------------------------------------------------------------------------
# promotion controller (unit, fake pool)
# ---------------------------------------------------------------------------


class TestPromotionController:
    def candidate(self):
        return Artifact(kind="checkpoint", path="/new", digest="d", step=5)

    def test_promotes_canary_then_rest(self):
        pool = FakePool(replicas=4)
        events = []
        c = PromotionController(
            pool,
            canary_fraction=0.5,
            journal=lambda e, **f: events.append((e, f)),
        )
        assert c.run(self.candidate(), score=0.9, baseline_score=0.5) == PROMOTED
        assert [r for r, _ in pool.rolls] == [0, 1, 2, 3]
        kinds = [e for e, _ in events]
        assert kinds[0] == "canary_start"
        assert ("gate", True) in [
            (e, f.get("passed")) for e, f in events if e == "gate"
        ]
        assert kinds[-1] == "promoted"

    def test_eval_regression_rolls_canary_back(self):
        pool = FakePool(replicas=4)
        events = []
        c = PromotionController(
            pool,
            canary_fraction=0.5,
            journal=lambda e, **f: events.append((e, f)),
        )
        out = c.run(
            self.candidate(), score=0.2, baseline_score=0.9, incumbent_ckpt="/old"
        )
        assert out == ROLLED_BACK
        # canaries 0,1 rolled forward, then restored to the incumbent;
        # replicas 2,3 never touched
        assert pool.rolls == [
            (0, "/new"),
            (1, "/new"),
            (0, "/old"),
            (1, "/old"),
        ]
        rb = next(f for e, f in events if e == "rollback")
        assert rb["reason"] == "eval_regression"
        assert rb["incumbent"] == "/old"

    def test_slo_burn_rolls_canary_back(self):
        pool = FakePool(replicas=2)
        events = []
        c = PromotionController(
            pool,
            slo_signal=lambda: 2.5,
            burn_threshold=1.0,
            observe_s=0.5,
            canary_fraction=0.5,
            journal=lambda e, **f: events.append((e, f)),
            clock=lambda: 0.0,
            sleep=lambda dt: None,
        )
        out = c.run(self.candidate(), score=0.9, incumbent_ckpt="/old")
        assert out == ROLLED_BACK
        rb = next(f for e, f in events if e == "rollback")
        assert rb["reason"] == "slo_burn"

    def test_resume_skips_already_rolled(self):
        pool = FakePool(replicas=4)
        c = PromotionController(
            pool, canary_fraction=0.5, already_rolled=[0]
        )
        assert c.run(self.candidate(), score=0.9) == PROMOTED
        # replica 0 was rolled by the pre-restart attempt: never re-rolled
        assert [r for r, _ in pool.rolls] == [1, 2, 3]

    def test_failed_rollout_rolls_back(self):
        pool = FakePool(replicas=4, fail_on={1})
        c = PromotionController(pool, canary_fraction=0.5)
        out = c.run(self.candidate(), score=0.9, incumbent_ckpt="/old")
        assert out == ROLLED_BACK
        # only replica 0 completed a forward roll; it alone is restored
        assert pool.rolls[-1] == (0, "/old")

    def test_gate_only_mode_without_pool(self):
        c = PromotionController(None)
        assert c.run(self.candidate(), score=0.9, baseline_score=0.5) == PROMOTED
        assert (
            c.run(self.candidate(), score=0.2, baseline_score=0.9)
            == ROLLED_BACK
        )

    def test_weights_restored_after_promotion(self):
        pool = FakePool(replicas=2)
        c = PromotionController(pool, canary_fraction=0.5, canary_weight=3.0)
        assert c.run(self.candidate(), score=0.9) == PROMOTED
        assert pool.router._weights == {}


# ---------------------------------------------------------------------------
# engine end-to-end on the real local scheduler
# ---------------------------------------------------------------------------


def _train_code(ckpt: str) -> str:
    return (
        "import hashlib,json,os\n"
        f"ckpt={ckpt!r}\n"
        "p=os.path.join(ckpt,'1'); os.makedirs(p,exist_ok=True)\n"
        "fp=os.path.join(p,'w.bin')\n"
        "open(fp,'wb').write(b'weights-'+os.path.basename(ckpt).encode())\n"
        "h=hashlib.sha256()\n"
        "h.update(os.path.relpath(fp,p).encode())\n"
        "h.update(open(fp,'rb').read())\n"
        "json.dump({'latest_step':1,'steps':{'1':{'digest':h.hexdigest()}}},"
        "open(os.path.join(ckpt,'MANIFEST.json'),'w'))\n"
    )


def _spec(base: str, tag: str, score: float, **promote_kw) -> dict:
    ckpt = os.path.join(base, f"ckpt-{tag}")
    score_file = os.path.join(base, f"score-{tag}.json")
    logs = os.path.join(base, "logs")
    stages = [
        {
            "name": "train",
            "kind": "train",
            "component": "utils.python",
            "args": ["-c", _train_code(ckpt)],
            "ckpt_dir": ckpt,
            "cfg": {"log_dir": logs},
        },
        {
            "name": "eval",
            "kind": "eval",
            "component": "utils.python",
            "args": [
                "-m",
                "torchx_tpu.apps.eval_main",
                "--",
                "--ckpt",
                "{train.path}",
                "--out",
                score_file,
                "--score",
                str(score),
            ],
            "depends_on": ["train"],
            "score_file": score_file,
            "threshold": 0.1,
            "baseline": "incumbent",
            "cfg": {"log_dir": logs},
        },
        {
            "name": "promote",
            "kind": "promote",
            "depends_on": ["eval"],
            "observe_s": promote_kw.pop("observe_s", 0.0),
            **promote_kw,
        },
    ]
    return {"name": f"pl-{tag}", "stages": stages}


def _wait_terminal(daemon, pid: str, timeout: float = 60.0) -> dict:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        doc = daemon.pipelines.status(pid)
        if doc["state"] in (
            "PROMOTED",
            "SUCCEEDED",
            "FAILED",
            "ROLLED_BACK",
            "CANCELLED",
        ):
            return doc
        time.sleep(0.05)
    raise AssertionError(f"pipeline {pid} never terminal: {doc}")


def _journal_entries(state_dir: str) -> list:
    out = []
    with open(os.path.join(state_dir, "pipelines.jsonl")) as f:
        for line in f:
            out.append(json.loads(line))
    return out


@pytest.fixture
def daemon_factory(tmp_path, monkeypatch):
    """Builds ControlDaemons over one shared state_dir (restart tests
    construct a second one after closing the first)."""
    from torchx_tpu.control.daemon import ControlDaemon
    from torchx_tpu.runner.api import get_runner

    monkeypatch.setenv("TPX_WATCH_INTERVAL", "0.05")
    made = []

    def make(**kw):
        d = ControlDaemon(
            runner=get_runner(f"pl-test-{len(made)}"),
            state_dir=str(tmp_path / "control"),
            tenant_cap=8,
            telemetry=False,
            **kw,
        )
        made.append(d)
        return d

    yield make
    for d in made:
        d.close()


class TestPipelineEndToEnd:
    def test_happy_path_promotes_over_http(self, tmp_path, daemon_factory):
        from torchx_tpu.control.client import ControlClient

        daemon = daemon_factory().start()
        client = ControlClient(daemon.addr, daemon.root_token)
        reply = client.pipeline_submit(_spec(str(tmp_path), "v1", 0.9))
        pid = reply["pipeline"]
        doc = _wait_terminal(daemon, pid)
        assert doc["state"] == "PROMOTED", doc
        states = {s["name"]: s["state"] for s in doc["stages"]}
        assert states == {
            "train": "SUCCEEDED",
            "eval": "SUCCEEDED",
            "promote": "SUCCEEDED",
        }
        # the artifact edge carried the digest train published
        ckpt_art = next(
            s["artifact"] for s in doc["stages"] if s["name"] == "train"
        )
        assert ckpt_art["digest"]
        assert doc["incumbent"]["digest"] == ckpt_art["digest"]
        assert doc["incumbent"]["score"] == 0.9
        # the same record over the HTTP list + status verbs
        listing = client.pipeline_status()
        assert [p["pipeline"] for p in listing["pipelines"]] == [pid]
        # every decision journaled
        kinds = {e["kind"] for e in _journal_entries(daemon.state_dir)}
        assert {
            "submit",
            "stage_submit",
            "stage_done",
            "gate",
            "promote_step",
            "pipeline_state",
            "incumbent",
        } <= kinds

    def test_eval_threshold_gate_fails_pipeline(self, tmp_path, daemon_factory):
        daemon = daemon_factory()
        spec = _spec(str(tmp_path), "bad", 0.05)  # below threshold 0.1
        pid = daemon.pipelines.submit(
            PipelineSpec.from_dict(spec), tenant="root"
        )
        doc = _wait_terminal(daemon, pid)
        assert doc["state"] == "FAILED"
        states = {s["name"]: s["state"] for s in doc["stages"]}
        assert states["eval"] == "FAILED"
        assert states["promote"] == "PENDING"  # never started
        gates = [
            e
            for e in _journal_entries(daemon.state_dir)
            if e["kind"] == "gate"
        ]
        assert gates and gates[-1]["passed"] is False

    def test_eval_regression_rolls_canary_back(self, tmp_path, daemon_factory):
        """The acceptance scenario: an induced eval-score regression on
        the candidate auto-rolls the canary back onto the incumbent
        checkpoint, with the rollback decision journaled."""
        pools = []

        def pool_provider(stage):
            pool = FakePool(replicas=4)
            pools.append(pool)
            return pool

        daemon = daemon_factory(pipeline_pool_provider=pool_provider)
        # pipeline 1 promotes at 0.9 and becomes the incumbent
        pid1 = daemon.pipelines.submit(
            PipelineSpec.from_dict(
                _spec(str(tmp_path), "v1", 0.9, canary_fraction=0.5)
            ),
            tenant="root",
        )
        assert _wait_terminal(daemon, pid1)["state"] == "PROMOTED"
        incumbent_ckpt = daemon.pipelines.incumbent["ckpt"]
        # pipeline 2 regresses to 0.3 < incumbent 0.9 -> auto-rollback
        pid2 = daemon.pipelines.submit(
            PipelineSpec.from_dict(
                _spec(str(tmp_path), "v2", 0.3, canary_fraction=0.5)
            ),
            tenant="root",
        )
        doc = _wait_terminal(daemon, pid2)
        assert doc["state"] == "ROLLED_BACK", doc
        states = {s["name"]: s["state"] for s in doc["stages"]}
        assert states["promote"] == "ROLLED_BACK"
        # the canary cohort (replicas 0,1 of 4 at fraction 0.5) went
        # forward onto v2, then back onto the incumbent's checkpoint
        pool2 = pools[-1]
        v2_ckpt = os.path.join(str(tmp_path), "ckpt-v2")
        assert pool2.rolls == [
            (0, v2_ckpt),
            (1, v2_ckpt),
            (0, incumbent_ckpt),
            (1, incumbent_ckpt),
        ]
        # the rollback decision is durably journaled with its reason
        rollbacks = [
            e
            for e in _journal_entries(daemon.state_dir)
            if e["kind"] == "promote_step" and e.get("event") == "rollback"
        ]
        assert rollbacks and rollbacks[-1]["reason"] == "eval_regression"
        assert rollbacks[-1]["incumbent"] == incumbent_ckpt
        # the incumbent is unchanged: v1 still owns the pool
        assert daemon.pipelines.incumbent["ckpt"] == incumbent_ckpt

    def test_slo_burn_rolls_canary_back(self, tmp_path, daemon_factory):
        """The other acceptance gate: an induced SLO burn at/over the
        threshold during the canary window rolls back."""
        pools = []

        def pool_provider(stage):
            pool = FakePool(replicas=2)
            pools.append(pool)
            return pool

        daemon = daemon_factory(pipeline_pool_provider=pool_provider)
        daemon.pipelines.set_slo_signal(lambda: 2.0)  # burning hard
        pid = daemon.pipelines.submit(
            PipelineSpec.from_dict(
                _spec(
                    str(tmp_path),
                    "v1",
                    0.9,
                    canary_fraction=0.5,
                    burn_threshold=1.0,
                    observe_s=0.2,
                )
            ),
            tenant="root",
        )
        doc = _wait_terminal(daemon, pid)
        assert doc["state"] == "ROLLED_BACK", doc
        rollbacks = [
            e
            for e in _journal_entries(daemon.state_dir)
            if e["kind"] == "promote_step" and e.get("event") == "rollback"
        ]
        assert rollbacks and rollbacks[-1]["reason"] == "slo_burn"
        assert daemon.pipelines.incumbent is None  # nothing ever promoted

    def test_restart_mid_canary_resumes_pipeline(
        self, tmp_path, daemon_factory
    ):
        """Kill the daemon mid-canary: the restarted daemon rehydrates the
        pipeline from its journal and resumes the canary from the exact
        replica it stopped at — completed stages are not re-run, rolled
        replicas are not re-rolled."""
        release = threading.Event()
        pool1 = FakePool(replicas=4, block_on=(1, release))

        daemon1 = daemon_factory(pipeline_pool_provider=lambda s: pool1)
        pid = daemon1.pipelines.submit(
            PipelineSpec.from_dict(
                _spec(str(tmp_path), "v1", 0.9, canary_fraction=0.5)
            ),
            tenant="root",
        )
        # wait until replica 0 is rolled and journaled, replica 1 blocked
        deadline = time.monotonic() + 60
        while not pool1.rolls:
            assert time.monotonic() < deadline, "canary never started"
            time.sleep(0.02)
        assert pool1.rolls[0][0] == 0
        # kill the daemon mid-canary (the promote thread is parked on
        # replica 1; close() gives up on joining it after its timeout)
        daemon1.close()
        mid = _journal_entries(daemon1.state_dir)
        rolled = [
            e
            for e in mid
            if e["kind"] == "promote_step"
            and e.get("event") == "replica_rolled"
        ]
        assert [e["replica"] for e in rolled] == [0]

        pool2 = FakePool(replicas=4)
        daemon2 = daemon_factory(pipeline_pool_provider=lambda s: pool2)
        doc = _wait_terminal(daemon2, pid)
        assert doc["state"] == "PROMOTED", doc
        # replica 0 (already rolled pre-restart) was NOT re-rolled; the
        # resumed canary started at replica 1 and promotion finished 2,3
        assert [r for r, _ in pool2.rolls] == [1, 2, 3]
        # completed train/eval stages were not re-submitted: exactly one
        # stage_submit journal entry per app stage across both daemons
        submits = [
            e
            for e in _journal_entries(daemon2.state_dir)
            if e["kind"] == "stage_submit" and not e.get("promote")
        ]
        assert sorted(e["stage"] for e in submits) == ["eval", "train"]
        # the resumed attempt journaled what it inherited
        starts = [
            e
            for e in _journal_entries(daemon2.state_dir)
            if e["kind"] == "promote_step"
            and e.get("event") == "canary_start"
        ]
        assert starts[-1]["resumed"] == [0]
        release.set()  # unpark the orphaned thread

    def test_cancel_over_http(self, tmp_path, daemon_factory):
        from torchx_tpu.control.client import ControlClient

        daemon = daemon_factory().start()
        client = ControlClient(daemon.addr, daemon.root_token)
        spec = _spec(str(tmp_path), "v1", 0.9)
        # a train stage that runs long enough to cancel
        spec["stages"][0]["args"] = ["-c", "import time; time.sleep(60)"]
        pid = client.pipeline_submit(spec)["pipeline"]
        doc = client.pipeline_cancel(pid)
        assert doc["state"] == "CANCELLED"
        assert daemon.pipelines.status(pid)["state"] == "CANCELLED"

    def test_unknown_pipeline_is_404_over_http(self, daemon_factory):
        from torchx_tpu.control.client import ControlClient, ControlClientError

        daemon = daemon_factory().start()
        client = ControlClient(daemon.addr, daemon.root_token)
        with pytest.raises(ControlClientError) as ei:
            client.pipeline_status("pl_999")
        assert ei.value.code == 404
        with pytest.raises(ControlClientError) as ei:
            client.pipeline_submit({"name": "x", "stages": []})
        assert ei.value.code == 400


# ---------------------------------------------------------------------------
# deprecation shims
# ---------------------------------------------------------------------------


class TestLegacyShims:
    def test_kfp_shim_warns_and_reexports(self):
        import importlib
        import warnings

        import torchx_tpu.pipelines.kfp as kfp

        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            importlib.reload(kfp)
        assert any(
            issubclass(x.category, UserWarning) and "deprecated" in str(x.message)
            for x in w
        ), [x.category for x in w]
        from torchx_tpu.pipelines.legacy import pipeline_to_workflow

        assert kfp.pipeline_to_workflow is pipeline_to_workflow

    def test_local_runner_shim_warns_and_reexports(self):
        import importlib
        import warnings

        import torchx_tpu.pipelines.local_runner as lr

        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            importlib.reload(lr)
        assert any(
            issubclass(x.category, UserWarning) and "deprecated" in str(x.message)
            for x in w
        ), [x.category for x in w]
        from torchx_tpu.pipelines.legacy import run_pipeline

        assert lr.run_pipeline is run_pipeline


# ---------------------------------------------------------------------------
# TPX603: promotion without a scrape path
# ---------------------------------------------------------------------------


class TestPromotionScrapeRule:
    def app(self, kind="promote"):
        from torchx_tpu.specs.api import AppDef, Role

        role = Role(name="p", image="i", entrypoint="x")
        role.metadata["tpx/pipeline"] = kind
        return AppDef(name="app", roles=[role])

    def report(self, app, scrape: bool):
        from torchx_tpu.analyze import analyze
        from torchx_tpu.schedulers.api import SchedulerCapabilities

        return analyze(
            app,
            scheduler="local",
            capabilities=SchedulerCapabilities(metricz_scrape=scrape),
        )

    @staticmethod
    def codes(report):
        return {d.code for d in report.diagnostics}

    def test_warns_on_scrapeless_backend(self):
        report = self.report(self.app(), scrape=False)
        assert "TPX603" in self.codes(report)
        d = next(x for x in report.diagnostics if x.code == "TPX603")
        assert d.severity.name == "WARNING"
        assert "eval-score-only" in d.message

    def test_quiet_with_scrape_path(self):
        assert "TPX603" not in self.codes(self.report(self.app(), scrape=True))

    def test_quiet_for_non_promote_stages(self):
        assert "TPX603" not in self.codes(
            self.report(self.app(kind="eval"), scrape=False)
        )
