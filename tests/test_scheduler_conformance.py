"""Scheduler ABC conformance suite (reference analog:
torchx/schedulers/test/api_test.py — the contract every backend honors)."""

from unittest import mock

import pytest

from torchx_tpu.schedulers import (
    DEFAULT_SCHEDULER_MODULES,
    get_default_scheduler_name,
    get_scheduler_factories,
)
from torchx_tpu.schedulers.api import DescribeAppResponse, Scheduler
from torchx_tpu.specs.api import (
    AppDef,
    AppDryRunInfo,
    AppState,
    FailureClass,
    Resource,
    Role,
    TpuSlice,
    is_terminal,
    runopts,
)


def make_scheduler(name: str) -> Scheduler:
    factory = get_scheduler_factories()[name]
    kwargs = {}
    if name == "gke":
        kwargs["client"] = object()  # never used at dryrun level
    if name == "local_docker":
        kwargs["docker_client"] = mock.MagicMock()
    if name == "vertex":
        kwargs["client"] = mock.MagicMock()
    if name == "gcp_batch":
        kwargs["docker_client"] = mock.MagicMock()
    return factory(session_name="conformance", **kwargs)


def sample_app(name: str) -> AppDef:
    role = Role(
        name="trainer",
        image="img:1"
        if name in ("gke", "local_docker", "vertex", "gcp_batch")
        else "",
        entrypoint="python",
        args=["-m", "train"],
        resource=Resource(cpu=2, memMB=1024, tpu=TpuSlice("v5e", 8)),
    )
    return AppDef(name="conf-test", roles=[role])


MINIMAL_CFG = {
    "local": {},
    "local_docker": {},
    "gke": {},
    "slurm": {},
    "tpu_vm": {"zone": "us-east5-a"},
    "vertex": {"project": "test-proj"},
    "gcp_batch": {"project": "test-proj"},
}

ALL = sorted(DEFAULT_SCHEDULER_MODULES)


class TestSchedulerConformance:
    @pytest.mark.parametrize("name", ALL)
    def test_factory_and_backend_name(self, name):
        sched = make_scheduler(name)
        assert isinstance(sched, Scheduler)
        assert sched.backend == name
        assert sched.session_name == "conformance"
        sched.close()  # idempotent

    @pytest.mark.parametrize("name", ALL)
    def test_run_opts_shape(self, name):
        opts = make_scheduler(name).run_opts()
        assert isinstance(opts, runopts)
        for key, opt in opts:
            assert opt.help, f"{name}.{key} has no help text"
            assert not (opt.is_required and opt.default is not None)

    @pytest.mark.parametrize("name", ALL)
    def test_submit_dryrun_contract(self, name, tmp_path):
        """submit_dryrun materializes the full request without touching any
        backend, and stamps the dryrun info (the core testability design)."""
        sched = make_scheduler(name)
        cfg = dict(MINIMAL_CFG[name])
        if name == "local":
            cfg["log_dir"] = str(tmp_path)
        info = sched.submit_dryrun(sample_app(name), cfg)
        assert isinstance(info, AppDryRunInfo)
        assert info._scheduler == name
        assert info._app is not None and info._app.name == "conf-test"
        assert info._cfg is not None
        assert str(info)  # every request pretty-prints

    @pytest.mark.parametrize("name", ALL)
    def test_pre_proc_hook_applies(self, name, tmp_path):
        marker = {}

        def pre_proc(backend, dryrun_info):  # noqa: ANN001
            marker["backend"] = backend
            return dryrun_info

        app = sample_app(name)
        app.roles[0].pre_proc = pre_proc
        cfg = dict(MINIMAL_CFG[name])
        if name == "local":
            cfg["log_dir"] = str(tmp_path)
        make_scheduler(name).submit_dryrun(app, cfg)
        assert marker["backend"] == name

    @pytest.mark.parametrize("name", ["local"])
    def test_cancel_nonexistent_is_noop(self, name):
        make_scheduler(name).cancel("ghost-app-id")  # must not raise

    def test_default_scheduler_is_first(self):
        assert get_default_scheduler_name() == next(iter(DEFAULT_SCHEDULER_MODULES))
        assert get_default_scheduler_name() == "local"

    @pytest.mark.parametrize("name", ALL)
    def test_classify_failure_contract(self, name):
        """Every backend honors the supervisor's classification contract:
        PREEMPTED -> PREEMPTION, bare FAILED -> APP (conservative), a
        describe-attached class wins, non-failures -> None."""
        sched = make_scheduler(name)

        def resp(state, fclass=None):
            return DescribeAppResponse(
                app_id="x", state=state, failure_class=fclass
            )

        assert (
            sched.classify_failure(resp(AppState.PREEMPTED))
            == FailureClass.PREEMPTION
        )
        assert sched.classify_failure(resp(AppState.FAILED)) == FailureClass.APP
        assert (
            sched.classify_failure(resp(AppState.FAILED, FailureClass.INFRA))
            == FailureClass.INFRA
        )
        for state in (
            AppState.RUNNING,
            AppState.PENDING,
            AppState.SUCCEEDED,
            AppState.CANCELLED,
        ):
            assert sched.classify_failure(resp(state)) is None


def _run_local_echo(sched, tmp_path, timeout: float = 20.0) -> str:
    """Submit a trivial echo app on the local scheduler and wait for a
    terminal state; returns the app id."""
    import time

    role = Role(
        name="echo",
        image="",
        entrypoint="echo",
        args=["conformance"],
        resource=Resource(cpu=1, memMB=64),
    )
    info = sched.submit_dryrun(
        AppDef(name="conf-lifecycle", roles=[role]), {"log_dir": str(tmp_path)}
    )
    app_id = sched.schedule(info)
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        desc = sched.describe(app_id)
        if desc is not None and is_terminal(desc.state):
            return app_id
        time.sleep(0.05)
    raise AssertionError(f"app {app_id} never reached a terminal state")


class TestLocalSchedulerLifecycle:
    """Lifecycle contract checked end-to-end on the one backend that can
    actually run jobs in CI."""

    def test_terminal_state_stays_terminal(self, tmp_path):
        sched = make_scheduler("local")
        try:
            app_id = _run_local_echo(sched, tmp_path)
            first = sched.describe(app_id).state
            assert is_terminal(first)
            # repeated describes (and a cancel) must never un-terminal it
            sched.cancel(app_id)
            for _ in range(3):
                assert sched.describe(app_id).state == first
        finally:
            sched.close()

    def test_exists_false_after_delete(self, tmp_path):
        sched = make_scheduler("local")
        try:
            app_id = _run_local_echo(sched, tmp_path)
            assert sched.exists(app_id)
            sched.delete(app_id)
            assert not sched.exists(app_id)
            assert sched.describe(app_id) is None
            assert app_id not in [a.app_id for a in sched.list()]
            sched.delete(app_id)  # idempotent
        finally:
            sched.close()
