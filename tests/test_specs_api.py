"""Unit tests for the specs core data model."""

import json
import warnings

import pytest

from torchx_tpu.specs import (
    AppDef,
    AppState,
    AppStatus,
    BindMount,
    DeviceMount,
    InvalidRunConfigException,
    MalformedAppHandleException,
    Resource,
    Role,
    TpuSlice,
    VolumeMount,
    Workspace,
    is_started,
    is_terminal,
    macros,
    make_app_handle,
    make_structured_error,
    named_resources,
    parse_app_handle,
    parse_mounts,
    resource,
    runopts,
)


class TestTpuSlice:
    def test_v5p_naming_counts_cores(self):
        s = TpuSlice.from_type("v5p-32")
        assert s.chips == 16
        assert s.cores == 32
        assert s.accelerator_type == "v5p-32"
        assert s.hosts == 4  # 4 chips per host

    def test_v5e_naming_counts_chips(self):
        s = TpuSlice.from_type("v5litepod-8")
        assert s.accelerator == "v5e"
        assert s.chips == 8
        assert s.hosts == 1
        assert s.accelerator_type == "v5litepod-8"

    def test_v6e(self):
        s = TpuSlice.from_type("v6e-16")
        assert s.chips == 16
        # multi-host v6e is built from 4-chip VMs (ct6e-standard-4t)
        assert s.hosts == 4

    # Multi-host v5e/v6e slices use 4-chip VMs exclusively; only slices that
    # fit on a single host come as 8-chip (or 1-chip) VMs. A wrong host count
    # here makes every GKE/Vertex/Batch request unschedulable.
    @pytest.mark.parametrize(
        "acc_type, chips_per_host, hosts, topology",
        [
            ("v5litepod-1", 1, 1, "1x1"),
            ("v5litepod-4", 4, 1, "2x2"),
            ("v5litepod-8", 8, 1, "2x4"),
            ("v5litepod-16", 4, 4, "4x4"),
            ("v5litepod-32", 4, 8, "4x8"),
            ("v5litepod-64", 4, 16, "8x8"),
            ("v5litepod-128", 4, 32, "8x16"),
            ("v5litepod-256", 4, 64, "16x16"),
            ("v6e-8", 8, 1, "2x4"),
            ("v6e-16", 4, 4, "4x4"),
            ("v6e-32", 4, 8, "4x8"),
            ("v6e-64", 4, 16, "8x8"),
        ],
    )
    def test_v5e_v6e_host_geometry(self, acc_type, chips_per_host, hosts, topology):
        s = TpuSlice.from_type(acc_type)
        assert s.chips_per_host == chips_per_host
        assert s.hosts == hosts
        assert s.default_topology() == topology

    @pytest.mark.parametrize(
        "acc_type, chips_per_host, hosts",
        [
            ("v4-8", 4, 1),
            ("v4-32", 4, 4),
            ("v5p-8", 4, 1),
            ("v5p-32", 4, 4),
            ("v5p-128", 4, 16),
        ],
    )
    def test_v4_v5p_host_geometry(self, acc_type, chips_per_host, hosts):
        s = TpuSlice.from_type(acc_type)
        assert s.chips_per_host == chips_per_host
        assert s.hosts == hosts

    def test_v4_single_host(self):
        s = TpuSlice.from_type("v4-8")
        assert s.chips == 4
        assert s.hosts == 1

    def test_topology_validation(self):
        TpuSlice(accelerator="v5p", chips=16, topology="2x2x4")
        with pytest.raises(ValueError):
            TpuSlice(accelerator="v5p", chips=16, topology="2x2x2")

    def test_default_topology_product(self):
        for n in (4, 8, 16, 32, 64, 128):
            s = TpuSlice(accelerator="v5p", chips=n)
            dims = [int(d) for d in s.default_topology().split("x")]
            assert len(dims) == 3
            assert dims[0] * dims[1] * dims[2] == n
        s = TpuSlice(accelerator="v5e", chips=16)
        a, b = (int(d) for d in s.default_topology().split("x"))
        assert a * b == 16

    def test_unknown_generation(self):
        with pytest.raises(ValueError):
            TpuSlice(accelerator="v99", chips=4)
        with pytest.raises(ValueError):
            TpuSlice.from_type("h100-8")

    def test_malformed_type(self):
        with pytest.raises(ValueError):
            TpuSlice.from_type("v5p")


class TestNamedResources:
    def test_catalog_lookup(self):
        r = named_resources["tpu_v5p_16"]
        assert r.tpu is not None and r.tpu.chips == 16
        assert r.cpu == 208

    def test_cloud_name_lookup(self):
        r = named_resources["v5p-32"]
        assert r.tpu.chips == 16

    def test_uncataloged_size_fallback(self):
        r = named_resources["v5e-12"]
        assert r.tpu.chips == 12

    def test_generic(self):
        r = named_resources["cpu_small"]
        assert r.cpu == 2 and r.tpu is None

    def test_contains(self):
        assert "v5p-32" in named_resources
        assert "nonsense" not in named_resources

    def test_unknown_raises(self):
        with pytest.raises(KeyError):
            named_resources["gpu_a100"]

    def test_resource_factory_h_wins(self):
        r = resource(cpu=1, memMB=1, h="v5litepod-4")
        assert r.tpu.chips == 4 and r.cpu != 1

    def test_resource_factory_tpu_str(self):
        r = resource(tpu="v4-16")
        assert r.tpu.chips == 8


class TestMounts:
    def test_parse_bind(self):
        (m,) = parse_mounts(["type=bind,src=/host,dst=/job,readonly"])
        assert isinstance(m, BindMount)
        assert m.src_path == "/host" and m.dst_path == "/job" and m.read_only

    def test_parse_multiple_groups(self):
        ms = parse_mounts(
            ["type=bind,src=/a,dst=/b", "type=volume,src=models,dst=/models"]
        )
        assert isinstance(ms[0], BindMount) and isinstance(ms[1], VolumeMount)

    def test_parse_device(self):
        (m,) = parse_mounts(["type=device,src=/dev/accel0"])
        assert isinstance(m, DeviceMount) and m.dst_path == "/dev/accel0"

    def test_parse_errors(self):
        with pytest.raises(ValueError):
            parse_mounts(["src=/a,dst=/b"])
        with pytest.raises(ValueError):
            parse_mounts(["type=bind,src=/a"])
        with pytest.raises(ValueError):
            parse_mounts(["type=nope,src=/a,dst=/b"])


class TestMacros:
    def test_apply_substitutes_args_env_entrypoint(self):
        role = Role(
            name="trainer",
            image="img",
            entrypoint="bash",
            args=["-c", f"run --id {macros.app_id} --replica {macros.replica_id}"],
            env={"LOGROOT": f"{macros.img_root}/logs"},
            mounts=[BindMount(src_path=f"{macros.img_root}/d", dst_path="/d")],
        )
        v = macros.Values(
            img_root="/img", app_id="app_1", replica_id="3", num_replicas="4"
        )
        out = v.apply(role)
        assert out.args == ["-c", "run --id app_1 --replica 3"]
        assert out.env["LOGROOT"] == "/img/logs"
        assert out.mounts[0].src_path == "/img/d"
        # original untouched
        assert macros.app_id in role.args[1]

    def test_coordinator_env_substitution(self):
        role = Role(
            name="t",
            image="i",
            entrypoint="sh",
            args=["-c", f"echo $${macros.coordinator_env}"],
        )
        out = macros.Values(coordinator_env="MY_COORD_HOST").apply(role)
        # one $ remains for the runtime shell to expand
        assert out.args[1] == "echo $MY_COORD_HOST"


class TestStatus:
    def test_terminal_and_started(self):
        assert is_terminal(AppState.SUCCEEDED)
        assert is_terminal(AppState.FAILED)
        assert not is_terminal(AppState.RUNNING)
        assert is_started(AppState.RUNNING)
        assert not is_started(AppState.PENDING)

    def test_raise_for_status(self):
        AppStatus(state=AppState.SUCCEEDED).raise_for_status()
        from torchx_tpu.specs import AppStatusError

        with pytest.raises(AppStatusError):
            AppStatus(state=AppState.FAILED).raise_for_status()

    def test_structured_error_format(self):
        err = make_structured_error("boom", exitcode=2, hostname="worker-0")
        st = AppStatus(state=AppState.FAILED, structured_error_msg=err)
        text = st.format()
        assert "boom" in text and "exitcode: 2" in text and "worker-0" in text

    def test_format_plain(self):
        st = AppStatus(state=AppState.RUNNING, msg="ok")
        assert "RUNNING" in st.format()


class TestRunopts:
    def make(self) -> runopts:
        opts = runopts()
        opts.add("log_dir", type_=str, help="log dir", default="/tmp/logs")
        opts.add("replicas", type_=int, help="n", default=1)
        opts.add("mounts", type_=list, help="mounts", default=None)
        opts.add("labels", type_=dict, help="labels", default=None)
        opts.add("detach", type_=bool, help="detach", default=False)
        opts.add("project", type_=str, help="gcp project", required=True)
        return opts

    def test_resolve_defaults_and_required(self):
        opts = self.make()
        cfg = opts.resolve({"project": "p1"})
        assert cfg["log_dir"] == "/tmp/logs" and cfg["replicas"] == 1
        with pytest.raises(InvalidRunConfigException):
            opts.resolve({})

    def test_resolve_type_error(self):
        with pytest.raises(InvalidRunConfigException):
            self.make().resolve({"project": "p", "replicas": "abc"})

    def test_str_coercion_and_camel_alias(self):
        cfg = self.make().resolve({"project": "p", "replicas": "3", "Detach": "true"})
        assert cfg["replicas"] == 3 and cfg["detach"] is True

    def test_cfg_from_str(self):
        opts = self.make()
        cfg = opts.cfg_from_str("project=p,replicas=2;detach=yes")
        assert cfg == {"project": "p", "replicas": 2, "detach": True}

    def test_cfg_from_str_list_continuation(self):
        opts = self.make()
        cfg = opts.cfg_from_str("mounts=a,b,c;project=p")
        assert cfg["mounts"] == ["a", "b", "c"]

    def test_cfg_from_str_dict(self):
        cfg = self.make().cfg_from_str("labels=team:ml")
        assert cfg["labels"] == {"team": "ml"}

    def test_cfg_from_str_dict_multi_entry(self):
        cfg = self.make().cfg_from_str("labels=a:1,b:2;project=p")
        assert cfg["labels"] == {"a": "1", "b": "2"}
        assert cfg["project"] == "p"

    def test_error_details_non_dict_json(self):
        from torchx_tpu.specs import AppState, AppStatus

        st = AppStatus(state=AppState.FAILED, structured_error_msg='"oom killed"')
        assert "oom killed" in st.format()

    def test_unknown_passthrough(self):
        cfg = self.make().resolve({"project": "p", "plugin_knob": "x"})
        assert cfg["plugin_knob"] == "x"

    def test_unknown_warns_once_per_key_per_schema(self):
        """Same schema: one warning however often it resolves — including
        across FRESH instances (run_opts() builds a new runopts per
        submit; per-submit spam is the thing warn-once prevents). A
        DIFFERENT schema (another scheduler) must still warn for its own
        unknown key of the same name (advisor r4)."""
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            self.make().resolve({"project": "p", "plugin_knob2": "x"})
            self.make().resolve({"project": "p", "plugin_knob2": "y"})
        hits = [x for x in w if "plugin_knob2" in str(x.message)]
        assert len(hits) == 1

        other_schema = runopts()
        other_schema.add("unrelated", type_=str, help="")
        with warnings.catch_warnings(record=True) as w2:
            warnings.simplefilter("always")
            other_schema.resolve({"plugin_knob2": "z"})
        hits_b = [x for x in w2 if "plugin_knob2" in str(x.message)]
        assert len(hits_b) == 1

    def test_merge(self):
        a = runopts()
        a.add("x", type_=int, help="", default=1)
        b = runopts()
        b.add("y", type_=int, help="", default=2)
        merged = a | b
        assert {k for k, _ in merged} == {"x", "y"}

    def test_json_repr(self):
        cfg = self.make().cfg_from_json_repr(json.dumps({"project": "p"}))
        assert cfg == {"project": "p"}


class TestHandles:
    def test_roundtrip(self):
        h = make_app_handle("gke", "sess", "app_abc123")
        assert parse_app_handle(h) == ("gke", "sess", "app_abc123")

    def test_empty_session(self):
        assert parse_app_handle("local://" + "/app1") == ("local", "", "app1")

    def test_malformed(self):
        with pytest.raises(MalformedAppHandleException):
            parse_app_handle("not-a-handle")


class TestWorkspaceSpec:
    def test_from_str_single(self):
        assert Workspace.from_str(".").projects == {".": ""}

    def test_from_str_mapping(self):
        ws = Workspace.from_str("./src=app/src,./conf=conf")
        assert ws.projects == {"./src": "app/src", "./conf": "conf"}

    def test_merge(self):
        a = Workspace(projects={"x": "1"})
        b = Workspace(projects={"x": "0", "y": "2"})
        assert a.merge_into(b).projects == {"x": "1", "y": "2"}


class TestRoleAppDef:
    def test_defaults(self):
        role = Role(name="r", image="i")
        assert role.num_replicas == 1
        app = AppDef(name="a", roles=[role])
        assert app.roles[0].name == "r"

    def test_resource_copy(self):
        r = Resource(cpu=1, memMB=2, capabilities={"a": 1})
        r2 = Resource.copy(r, b=2)
        assert r2.capabilities == {"a": 1, "b": 2}
        assert r.capabilities == {"a": 1}
