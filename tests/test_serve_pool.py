"""Serve-pool controller tests: the pure autoscaler decision, least-loaded
routing, and the deterministic resize e2e against the real local
scheduler (fake clock + synthetic probes; no real HTTP, no jax)."""

import time

import pytest

from torchx_tpu.obs import metrics as obs_metrics
from torchx_tpu.obs import sinks, timeline
from torchx_tpu.runner.api import Runner
from torchx_tpu.schedulers.local_scheduler import LocalScheduler
from torchx_tpu.serve.pool import (
    AutoscalePolicy,
    Autoscaler,
    LeastLoadedRouter,
    ReplicaStatus,
    ServePool,
)
from torchx_tpu.specs.api import AppDef, Role


class FakeClock:
    def __init__(self) -> None:
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# -- Autoscaler: pure decision --------------------------------------------


class TestAutoscaler:
    def policy(self, **kw):
        defaults = dict(
            min_replicas=1,
            max_replicas=4,
            target_queue_depth=4.0,
            up_streak=2,
            down_streak=3,
            cooldown_s=60.0,
        )
        defaults.update(kw)
        return AutoscalePolicy(**defaults)

    def test_scale_up_needs_consecutive_breaches(self):
        a = Autoscaler(self.policy(), clock=FakeClock())
        assert a.observe(1, 10.0) == 1  # first breach: streak building
        assert a.observe(1, 10.0) == 2  # second: scale up

    def test_streak_resets_on_recovery(self):
        a = Autoscaler(self.policy(), clock=FakeClock())
        assert a.observe(1, 10.0) == 1
        assert a.observe(1, 1.5) == 1  # recovered: streak resets
        assert a.observe(1, 10.0) == 1  # back to one breach, still holding

    def test_cooldown_gates_consecutive_scales(self):
        clock = FakeClock()
        a = Autoscaler(self.policy(), clock=clock)
        a.observe(1, 10.0)
        assert a.observe(1, 10.0) == 2
        a.notify_scaled()
        # still hot, but inside cooldown: hold
        assert a.observe(2, 10.0) == 2
        assert a.observe(2, 10.0) == 2
        clock.advance(61.0)
        # cooldown over and the streak re-built during it
        assert a.observe(2, 10.0) == 3

    def test_p99_breach_scales_up_even_with_shallow_queue(self):
        a = Autoscaler(
            self.policy(target_p99_s=0.5), clock=FakeClock()
        )
        assert a.observe(1, 0.0, p99_s=2.0) == 1
        assert a.observe(1, 0.0, p99_s=2.0) == 2

    def test_scale_down_after_streak_and_not_during_p99_breach(self):
        clock = FakeClock()
        a = Autoscaler(
            self.policy(target_p99_s=0.5, down_streak=2), clock=clock
        )
        # idle queue but p99 still over SLO: never scale down
        assert a.observe(3, 0.0, p99_s=2.0) == 3
        assert a.observe(3, 0.0, p99_s=2.0) == 4  # that's a breach: UP
        a.notify_scaled()
        clock.advance(61.0)
        assert a.observe(4, 0.0, p99_s=0.1) == 4
        assert a.observe(4, 0.0, p99_s=0.1) == 3  # idle + healthy: down

    def test_bounds_respected(self):
        clock = FakeClock()
        a = Autoscaler(
            self.policy(max_replicas=2, down_streak=1), clock=clock
        )
        a.observe(2, 10.0)
        assert a.observe(2, 10.0) == 2  # at ceiling: hold
        assert a.observe(1, 0.0) == 1  # at floor: hold

    def test_policy_validation(self):
        with pytest.raises(ValueError, match="min_replicas"):
            AutoscalePolicy(min_replicas=3, max_replicas=2)
        with pytest.raises(ValueError, match="target_queue_depth"):
            AutoscalePolicy(target_queue_depth=0)
        with pytest.raises(ValueError, match="streak"):
            AutoscalePolicy(up_streak=0)


# -- LeastLoadedRouter -----------------------------------------------------


class TestRouter:
    def statuses(self, depths, healthy=None):
        healthy = healthy or [True] * len(depths)
        return [
            ReplicaStatus(
                replica_id=i, url=f"http://r{i}", healthy=h, queue_depth=d
            )
            for i, (d, h) in enumerate(zip(depths, healthy))
        ]

    def test_pick_least_loaded(self):
        r = LeastLoadedRouter()
        r.update(self.statuses([5.0, 1.0, 3.0]))
        assert r.pick().replica_id == 1

    def test_pick_skips_unhealthy(self):
        r = LeastLoadedRouter()
        r.update(self.statuses([5.0, 1.0], healthy=[True, False]))
        assert r.pick().replica_id == 0

    def test_pick_none_when_all_down(self):
        r = LeastLoadedRouter()
        r.update(self.statuses([1.0], healthy=[False]))
        assert r.pick() is None

    def test_inflight_spreads_before_probe_catches_up(self):
        # equal probed depth: our own un-acked sends must round-robin
        r = LeastLoadedRouter()
        r.update(self.statuses([0.0, 0.0]))
        first = r.pick().replica_id
        second = r.pick().replica_id
        assert {first, second} == {0, 1}
        r.record(first, 0.01)
        assert r.pick().replica_id == first  # freed slot goes first again

    def test_p99_window(self):
        r = LeastLoadedRouter(window=100)
        assert r.p99_s() is None
        for _ in range(99):
            r.record(0, 0.010)
        r.record(0, 5.0)
        assert r.p99_s() == 5.0

    def test_queue_depth_mean_over_healthy(self):
        r = LeastLoadedRouter()
        r.update(self.statuses([2.0, 4.0, 100.0], healthy=[True, True, False]))
        assert r.queue_depth() == 3.0


# -- ServePool e2e: real local scheduler, synthetic load, fake clock -------


def sleeper_app(replicas: int = 1) -> AppDef:
    return AppDef(
        name="fake-serve",
        roles=[
            Role(
                name="server",
                image="",
                entrypoint="sh",
                args=["-c", "sleep 300"],
                num_replicas=replicas,
                port_map={"http": 8000},
            )
        ],
    )


class SyntheticLoad:
    """Injectable probe: every replica healthy at the scripted depth."""

    def __init__(self) -> None:
        self.depth = 0.0

    def __call__(self, replica_id: int, url: str) -> ReplicaStatus:
        return ReplicaStatus(
            replica_id=replica_id, url=url, healthy=True, queue_depth=self.depth
        )


class TestServePoolE2E:
    @pytest.fixture
    def runner(self):
        sched = LocalScheduler(session_name="pool-test", cache_size=10)
        r = Runner("pool-test", {"local": lambda session_name, **kw: sched})
        yield r, sched
        r.close()

    def pool(self, runner, sched, clock, load, **pol):
        defaults = dict(
            min_replicas=1,
            max_replicas=3,
            target_queue_depth=4.0,
            up_streak=2,
            down_streak=2,
            cooldown_s=30.0,
        )
        defaults.update(pol)
        return ServePool(
            runner,
            sleeper_app(),
            scheduler="local",
            policy=AutoscalePolicy(**defaults),
            probe=load,
            clock=clock,
            sleep=lambda s: None,
        )

    def live_replicas(self, sched, app_id):
        return len(sched._apps[app_id].roles.get("server", []))

    def test_load_scales_up_through_ledgered_resize(self, runner):
        r, sched = runner
        clock, load = FakeClock(), SyntheticLoad()
        pool = self.pool(r, sched, clock, load)
        handle = pool.start()
        app_id = handle.rsplit("/", 1)[-1]
        try:
            before = obs_metrics.SERVE_REPLICAS.value()
            assert before == 1
            load.depth = 10.0  # queue builds
            assert pool.step() is None  # hysteresis: one breach holds
            assert pool.step() == 2  # second breach scales up
            assert pool.replicas == 2
            assert self.live_replicas(sched, app_id) == 2  # gang resized
            assert obs_metrics.SERVE_REPLICAS.value() == 2
            # the scale rode the ordinary Runner.resize ledger
            records = timeline.load_records(sinks.trace_path())
            resizes = [r_ for r_ in records if r_.get("api") == "resize"]
            assert resizes and resizes[-1]["app_id"] == app_id
            scale_spans = [
                r_
                for r_ in records
                if timeline.is_span(r_) and r_.get("name") == "serve.scale"
            ]
            assert scale_spans and scale_spans[-1]["attrs"]["direction"] == "up"
        finally:
            pool.stop()

    def test_idle_scales_down_only_after_cooldown(self, runner):
        r, sched = runner
        clock, load = FakeClock(), SyntheticLoad()
        pool = self.pool(r, sched, clock, load)
        handle = pool.start()
        app_id = handle.rsplit("/", 1)[-1]
        try:
            load.depth = 10.0
            pool.step()
            assert pool.step() == 2
            load.depth = 0.0  # load stops
            # inside cooldown: idle observations accumulate but hold
            assert pool.step() is None
            assert pool.step() is None
            assert pool.replicas == 2
            clock.advance(31.0)
            assert pool.step() == 1  # cooldown over, streak satisfied
            assert self.live_replicas(sched, app_id) == 1
            assert pool.scale_events == [(1, 2), (2, 1)]
            assert obs_metrics.SERVE_SCALE_EVENTS.value(direction="down") >= 1
        finally:
            pool.stop()

    def test_resize_error_surfaces(self, runner):
        r, sched = runner
        clock, load = FakeClock(), SyntheticLoad()
        pool = self.pool(r, sched, clock, load)
        handle = pool.start()
        r.cancel(handle)
        # wait for the gang to actually die so resize sees terminal state
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            st = r.status(handle, fresh=True)
            if st is not None and st.state.name in ("CANCELLED", "FAILED"):
                break
            time.sleep(0.05)
        load.depth = 10.0
        pool.step()
        with pytest.raises(ValueError, match="terminal"):
            pool.step()

    def test_run_loop_exits_on_terminal_app(self, runner):
        r, sched = runner
        clock, load = FakeClock(), SyntheticLoad()
        pool = self.pool(r, sched, clock, load)
        pool.start()
        pool.stop()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            st = r.status(pool.handle, fresh=True)
            if st is not None and st.state.name in ("CANCELLED", "FAILED"):
                break
            time.sleep(0.05)
        pool.run(interval_s=0.0, iterations=50)  # returns, does not spin


class TestServePoolCli:
    def test_cli_registered_and_help(self, capsys):
        from torchx_tpu.cli.main import get_sub_cmds

        assert "serve-pool" in get_sub_cmds()

    def test_replica_url_stride(self):
        pool = ServePool(
            runner=None,
            app=sleeper_app(),
            base_port=8000,
            port_stride=2,
            probe=SyntheticLoad(),
        )
        assert pool.replica_url(0) == "http://127.0.0.1:8000"
        assert pool.replica_url(3) == "http://127.0.0.1:8006"
