"""Slurm scheduler tests with canned CLI output (reference analog:
slurm_scheduler_test.py + slurm-squeue-output.json fixtures)."""

import json
import subprocess
from unittest import mock

import pytest

from torchx_tpu.schedulers.slurm_scheduler import (
    SlurmScheduler,
    slurm_state,
)
from torchx_tpu.specs.api import (
    AppDef,
    AppState,
    Resource,
    Role,
    TpuSlice,
    macros,
)


def tpu_role(**kwargs) -> Role:
    defaults = dict(
        name="trainer",
        image="/shared/job",
        entrypoint="python",
        args=["-m", "train", f"--id={macros.app_id}"],
        resource=Resource(cpu=208, memMB=1000, tpu=TpuSlice("v5p", 8)),
    )
    defaults.update(kwargs)
    return Role(**defaults)


def completed(stdout="", rc=0, stderr=""):
    return subprocess.CompletedProcess([], returncode=rc, stdout=stdout, stderr=stderr)


@pytest.fixture
def sched():
    return SlurmScheduler("test")


class TestSbatchMaterialization:
    def test_tpu_role_het_groups(self, sched):
        app = AppDef(name="t", roles=[tpu_role()])
        info = sched.submit_dryrun(app, {})
        script = info.request.script()
        # v5p-16: 8 chips -> 2 hosts -> 2 het groups
        assert script.count("#SBATCH hetjob") == 1
        assert script.count("--het-group=") == 2
        assert "--cpus-per-task=208" in script
        assert "TPX_COORDINATOR_HOST=$(scontrol show hostnames" in script
        assert 'TPX_REPLICA_ID="0"' in script and 'TPX_REPLICA_ID="1"' in script
        assert "--kill-on-bad-exit=1" in script

    def test_het_groups_stamped_via_wrapper(self, sched):
        app = AppDef(name="t", roles=[tpu_role()])
        script = sched.submit_dryrun(app, {}).request.script()
        # every task's stdout/stderr rides through the epoch stamper so
        # log_iter can window; argv stays batch-shell-expanded positionals,
        # and pipelines (not procsubs) guarantee the stampers are drained
        # before slurmstepd reaps the task
        assert "export TPX_STAMP=" in script
        assert script.count("bash -c 'set -o pipefail;") == 2
        assert script.count('{ ("$@") 2>&1 1>&3') == 2
        assert '| python3 -u -c "$TPX_STAMP" >&2; } 3>&1' in script

    def test_elastic_script_stamped(self, sched):
        app = AppDef(name="t", roles=[tpu_role(min_replicas=1, num_replicas=2)])
        script = sched.submit_dryrun(app, {}).request.script()
        assert "export TPX_STAMP=" in script
        assert '$TPX_STAMP' in script

    def test_macro_substitution_defers_job_id(self, sched):
        app = AppDef(name="t", roles=[tpu_role()])
        script = sched.submit_dryrun(app, {}).request.script()
        # double-quoted, not single-quoted: the macro must expand at runtime
        assert '"--id=${SLURM_JOB_ID}"' in script
        assert "'--id=${SLURM_JOB_ID}'" not in script

    def test_per_group_job_names(self, sched):
        app = AppDef(name="t", roles=[tpu_role()])
        script = sched.submit_dryrun(app, {}).request.script()
        assert "#SBATCH --job-name=trainer-0" in script
        assert "#SBATCH --job-name=trainer-1" in script

    def test_log_files_use_leader_job_id(self, sched):
        app = AppDef(name="t", roles=[tpu_role()])
        script = sched.submit_dryrun(app, {}).request.script()
        assert "--output=slurm-${SLURM_JOB_ID}-trainer-0.out" in script
        assert "%j" not in script

    def test_requeue_on_retries(self, sched):
        app = AppDef(name="t", roles=[tpu_role(max_retries=2)])
        script = sched.submit_dryrun(app, {}).request.script()
        assert "scontrol requeue" in script
        assert "TPX_MAX_RETRIES=2" in script
        assert "trap tpx_requeue ERR" in script

    def test_no_requeue_without_retries(self, sched):
        app = AppDef(name="t", roles=[tpu_role()])
        assert "requeue" not in sched.submit_dryrun(app, {}).request.script()

    def test_partition_time_nomem(self, sched):
        app = AppDef(name="t", roles=[tpu_role()])
        script = sched.submit_dryrun(
            app, {"partition": "tpu", "time": "2:00:00", "nomem": True}
        ).request.script()
        assert "--partition=tpu" in script
        assert "--time=2:00:00" in script
        assert "--mem=" not in script

    def test_schedule_parses_job_id(self, sched, tmp_path, monkeypatch):
        monkeypatch.setattr(
            sched, "_run_cmd", lambda cmd, **kw: completed(stdout="1234\n")
        )
        monkeypatch.setattr(
            "torchx_tpu.schedulers.slurm_scheduler._registry_path",
            lambda: str(tmp_path / "jobdirs"),
        )
        app = AppDef(name="t", roles=[tpu_role()])
        info = sched.submit_dryrun(app, {"job_dir": str(tmp_path)})
        app_id = sched.schedule(info)
        assert app_id == "1234"
        assert (tmp_path / "tpx_sbatch.sh").exists()
        assert "1234 = " in (tmp_path / "jobdirs").read_text()

    def test_schedule_sbatch_failure(self, sched, tmp_path, monkeypatch):
        monkeypatch.setattr(
            sched, "_run_cmd", lambda cmd, **kw: completed(rc=1, stderr="bad partition")
        )
        app = AppDef(name="t", roles=[tpu_role()])
        info = sched.submit_dryrun(app, {"job_dir": str(tmp_path)})
        with pytest.raises(RuntimeError, match="bad partition"):
            sched.schedule(info)


class TestSlurmDescribe:
    def test_describe_squeue(self, sched, monkeypatch):
        payload = {
            "jobs": [
                {
                    "job_id": 1234,
                    "name": "trainer-0",
                    "job_state": ["RUNNING"],
                    "job_resources": {"nodes": "node01"},
                },
                {
                    "job_id": 1235,
                    "name": "trainer-1",
                    "job_state": "RUNNING",
                },
            ]
        }
        monkeypatch.setattr(
            sched, "_run_cmd", lambda cmd, **kw: completed(stdout=json.dumps(payload))
        )
        resp = sched.describe("1234")
        assert resp.state == AppState.RUNNING
        (rs,) = resp.roles_statuses
        assert rs.role == "trainer" and len(rs.replicas) == 2
        assert rs.replicas[0].hostname == "node01"

    def test_describe_falls_back_to_sacct(self, sched, monkeypatch):
        sacct_out = (
            "JobID|JobName|State\n"
            "1234+0|trainer-0|COMPLETED\n"
            "1234+0.batch|batch|COMPLETED\n"
            "1234+1|trainer-1|COMPLETED\n"
        )
        def run_cmd(cmd, **kw):
            if cmd[0] == "squeue":
                return completed(rc=1, stderr="Invalid job id")
            return completed(stdout=sacct_out)

        monkeypatch.setattr(sched, "_run_cmd", run_cmd)
        resp = sched.describe("1234")
        assert resp.state == AppState.SUCCEEDED
        (rs,) = resp.roles_statuses
        assert len(rs.replicas) == 2

    def test_describe_failed_dominates(self, sched, monkeypatch):
        sacct_out = (
            "JobID|JobName|State\n"
            "1234+0|trainer-0|COMPLETED\n"
            "1234+1|trainer-1|FAILED\n"
        )
        def run_cmd(cmd, **kw):
            if cmd[0] == "squeue":
                return completed(rc=1)
            return completed(stdout=sacct_out)

        monkeypatch.setattr(sched, "_run_cmd", run_cmd)
        assert sched.describe("1234").state == AppState.FAILED

    def test_describe_missing(self, sched, monkeypatch):
        monkeypatch.setattr(sched, "_run_cmd", lambda cmd, **kw: completed(rc=1))
        assert sched.describe("9999") is None

    def test_cancel(self, sched, monkeypatch):
        calls = []

        def run_cmd(cmd, **kw):
            calls.append(cmd)
            if cmd[0] == "squeue":
                return completed(stdout=json.dumps({"jobs": [{"job_id": 1, "name": "x", "job_state": "RUNNING"}]}))
            return completed()

        monkeypatch.setattr(sched, "_run_cmd", run_cmd)
        sched.cancel("1")
        assert ["scancel", "1"] in calls

    def test_log_iter(self, sched, tmp_path, monkeypatch):
        monkeypatch.setattr(
            "torchx_tpu.schedulers.slurm_scheduler._registry_path",
            lambda: str(tmp_path / "jobdirs"),
        )
        (tmp_path / "jobdirs").write_text(f"77 = {tmp_path}\n")
        (tmp_path / "slurm-77-trainer-0.out").write_text("line1\nline2\n")
        lines = list(sched.log_iter("77", "trainer", 0))
        assert lines == ["line1", "line2"]


class TestStateMap:
    def test_states(self):
        assert slurm_state("COMPLETED") == AppState.SUCCEEDED
        assert slurm_state("CANCELLED by 1000") == AppState.CANCELLED
        assert slurm_state("NODE_FAIL") == AppState.FAILED
        assert slurm_state("WEIRD") == AppState.UNKNOWN


# =========================================================================
# Recorded-fixture tests: format generations the parsers must survive
# (reference analog: slurm-squeue-output.json, slurm_scheduler.py:661-810)
# =========================================================================

import os

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


def fixture(name: str) -> str:
    with open(os.path.join(FIXTURES, name)) as f:
        return f.read()


class TestSqueueFormatGenerations:
    def test_v24_object_nodes(self, sched, monkeypatch):
        """24.05 squeue: job_state is a list, job_resources.nodes an object."""
        monkeypatch.setattr(
            sched, "_run_cmd",
            lambda cmd, **kw: completed(stdout=fixture("squeue_v24.json")),
        )
        resp = sched.describe("4001")
        assert resp.state == AppState.RUNNING
        (rs,) = resp.roles_statuses
        by_id = {r.id: r for r in rs.replicas}
        assert by_id[0].hostname == "tpu-node-3"
        assert by_id[1].state == AppState.PENDING
        assert by_id[1].hostname == ""  # job_resources: null (pending)

    def test_v22_string_nodes_and_allocated_nodes(self, sched, monkeypatch):
        """pre-23.02: job_state is a string; nodes is a string or
        allocated_nodes a list of {nodename}."""
        monkeypatch.setattr(
            sched, "_run_cmd",
            lambda cmd, **kw: completed(stdout=fixture("squeue_v22.json")),
        )
        resp = sched.describe("1234")
        (rs,) = resp.roles_statuses
        by_id = {r.id: r for r in rs.replicas}
        assert by_id[0].hostname == "gpu-compute-[01-02]"
        assert by_id[1].hostname == "gpu-compute-03"

    def test_truncated_payload_falls_through(self, sched, monkeypatch):
        """A half-written/truncated squeue JSON must not crash describe —
        it falls through to sacct (which here has nothing)."""

        def run_cmd(cmd, **kw):
            if cmd[0] == "squeue":
                return completed(stdout='{"jobs": [{"job_id": 1, "na')
            return completed(stdout="")

        monkeypatch.setattr(sched, "_run_cmd", run_cmd)
        assert sched.describe("1") is None


class TestSacctFormatVariants:
    def test_het_offsets_steps_and_blank_state(self, sched, monkeypatch):
        """sacct rows: het-job `+N` ids, `.batch`/`.0` step rows (skipped),
        'CANCELLED by uid' states, and a blank state column."""

        def run_cmd(cmd, **kw):
            if cmd[0] == "squeue":
                return completed(rc=1)  # job left the queue
            return completed(stdout=fixture("sacct_variants.txt"))

        monkeypatch.setattr(sched, "_run_cmd", run_cmd)
        resp = sched.describe("777")
        assert resp is not None
        assert resp.state == AppState.CANCELLED
        (rs,) = [r for r in resp.roles_statuses if r.role == "spmd"]
        assert {r.id: r.state for r in rs.replicas} == {
            0: AppState.CANCELLED,
            1: AppState.SUCCEEDED,
        }

    def test_sacct_header_only(self, sched, monkeypatch):
        def run_cmd(cmd, **kw):
            if cmd[0] == "squeue":
                return completed(rc=1)
            return completed(stdout="JobID|JobName|State\n")

        monkeypatch.setattr(sched, "_run_cmd", run_cmd)
        assert sched.describe("777") is None

    def test_multi_role_rows_grouped_and_worst_state_wins(self, sched, monkeypatch):
        """sacct rows for a two-role hetjob (trainer-0/1, tb-0): replicas
        group under their role and one FAILED row fails the app even when
        later rows completed."""
        sacct_out = (
            "JobID|JobName|State\n"
            "900+0|trainer-0|FAILED\n"
            "900+1|trainer-1|COMPLETED\n"
            "900+2|tb-0|COMPLETED\n"
        )

        def run_cmd(cmd, **kw):
            if cmd[0] == "squeue":
                return completed(rc=1)
            return completed(stdout=sacct_out)

        monkeypatch.setattr(sched, "_run_cmd", run_cmd)
        resp = sched.describe("900")
        assert resp.state == AppState.FAILED
        roles = {r.role: r for r in resp.roles_statuses}
        assert set(roles) == {"trainer", "tb"}
        assert len(roles["trainer"].replicas) == 2
        assert len(roles["tb"].replicas) == 1

    def test_preempted_and_timeout_map_to_failed(self):
        # requeue-able terminal states must read as failures (retry machinery
        # keys off FAILED), not unknowns
        assert slurm_state("PREEMPTED") == AppState.FAILED
        assert slurm_state("TIMEOUT") == AppState.FAILED
        assert slurm_state("COMPLETING") == AppState.RUNNING
        assert slurm_state("REQUEUED") == AppState.PENDING
        assert slurm_state("CANCELLED+") == AppState.CANCELLED  # federation '+'
        assert slurm_state("") == AppState.UNKNOWN


class TestSlurmList:
    def test_list_me(self, sched, monkeypatch):
        payload = {
            "jobs": [
                {"job_id": 11, "name": "a-x1", "job_state": ["RUNNING"]},
                {"job_id": 12, "name": "b-x2", "job_state": "PENDING"},
            ]
        }
        calls = []

        def run_cmd(cmd, **kw):
            calls.append(cmd)
            return completed(stdout=json.dumps(payload))

        monkeypatch.setattr(sched, "_run_cmd", run_cmd)
        apps = sched.list()
        assert [a.app_id for a in apps] == ["11", "12"]
        assert apps[0].state == AppState.RUNNING
        assert apps[1].state == AppState.PENDING
        assert ["squeue", "--json", "--me"] in calls

    def test_list_squeue_failure_raises(self, sched, monkeypatch):
        monkeypatch.setattr(
            sched, "_run_cmd", lambda cmd, **kw: completed(rc=1, stderr="down")
        )
        with pytest.raises(RuntimeError, match="squeue failed"):
            sched.list()


class TestSlurmLogIter:
    @pytest.fixture
    def job_dir(self, tmp_path, monkeypatch):
        monkeypatch.setattr(
            "torchx_tpu.schedulers.slurm_scheduler._registry_path",
            lambda: str(tmp_path / "jobdirs"),
        )
        (tmp_path / "jobdirs").write_text(f"55 = {tmp_path}\n")
        return tmp_path

    def test_stderr_stream(self, sched, job_dir):
        from torchx_tpu.schedulers.api import Stream

        (job_dir / "slurm-55-trainer-0.err").write_text("E1\nE2\n")
        lines = list(sched.log_iter("55", "trainer", 0, streams=Stream.STDERR))
        assert lines == ["E1", "E2"]

    def test_non_het_fallback_filename(self, sched, job_dir):
        # single-replica jobs write slurm-{id}.out without role/replica parts
        (job_dir / "slurm-55.out").write_text("solo\n")
        assert list(sched.log_iter("55", "trainer", 0)) == ["solo"]

    def test_regex_filter(self, sched, job_dir):
        (job_dir / "slurm-55-trainer-0.out").write_text("keep 1\ndrop\nkeep 2\n")
        assert list(sched.log_iter("55", "trainer", 0, regex="keep")) == [
            "keep 1",
            "keep 2",
        ]

    def test_window_on_stamped_lines(self, sched, job_dir):
        # the batch-script wrapper stamps epoch millis; log_iter windows
        # on them and strips the stamp (7/7 backends honor windows)
        (job_dir / "slurm-55-trainer-0.out").write_text(
            "1700000000.000 early\n1700000100.000 mid\n1700000200.000 late\n"
        )
        assert list(
            sched.log_iter("55", "trainer", 0, since=1700000050.0)
        ) == ["mid", "late"]
        assert list(
            sched.log_iter(
                "55", "trainer", 0, since=1700000050.0, until=1700000150.0
            )
        ) == ["mid"]

    def test_stamps_stripped_without_window(self, sched, job_dir):
        (job_dir / "slurm-55-trainer-0.out").write_text(
            "1700000000.000 stamped\nlegacy unstamped\n"
        )
        assert list(sched.log_iter("55", "trainer", 0)) == [
            "stamped",
            "legacy unstamped",
        ]

    def test_legacy_unstamped_passes_window(self, sched, job_dir):
        # pre-stamping log files carry no timestamps: windows can't apply,
        # lines pass through whole rather than vanishing
        (job_dir / "slurm-55-trainer-0.out").write_text("legacy line\n")
        assert list(
            sched.log_iter("55", "trainer", 0, since=1700000050.0)
        ) == ["legacy line"]

    def test_supports_log_windows_flag(self, sched):
        assert type(sched).supports_log_windows is True

    def test_unknown_job_dir_raises(self, sched, job_dir):
        with pytest.raises(RuntimeError, match="no job dir recorded"):
            sched.log_iter("66", "trainer", 0)

    def test_missing_file_yields_nothing(self, sched, job_dir):
        assert list(sched.log_iter("55", "trainer", 3)) == []


class TestSqueueNodeFormats:
    """_squeue_job_nodes across the format generations the parsers must
    survive (reference parses 3 SLURM JSON formats, :661-810)."""

    def test_object_with_list(self):
        from torchx_tpu.schedulers.slurm_scheduler import _squeue_job_nodes

        job = {"job_resources": {"nodes": {"count": 2, "list": ["n1", "n2"]}}}
        assert _squeue_job_nodes(job) == "n1,n2"

    def test_object_with_nodes_string(self):
        from torchx_tpu.schedulers.slurm_scheduler import _squeue_job_nodes

        job = {"job_resources": {"nodes": {"nodes": "n[01-04]"}}}
        assert _squeue_job_nodes(job) == "n[01-04]"

    def test_allocated_nodes_dicts(self):
        from torchx_tpu.schedulers.slurm_scheduler import _squeue_job_nodes

        job = {
            "job_resources": {
                "allocated_nodes": [{"nodename": "a"}, {"nodename": "b"}]
            }
        }
        assert _squeue_job_nodes(job) == "a,b"

    def test_null_and_garbage(self):
        from torchx_tpu.schedulers.slurm_scheduler import _squeue_job_nodes

        assert _squeue_job_nodes({}) == ""
        assert _squeue_job_nodes({"job_resources": None}) == ""
        assert _squeue_job_nodes({"job_resources": "weird"}) == ""


class TestCancelFailure:
    def test_scancel_error_raises(self, sched, monkeypatch):
        def run_cmd(cmd, **kw):
            if cmd[0] == "squeue":
                return completed(
                    stdout=json.dumps(
                        {"jobs": [{"job_id": 1, "name": "x", "job_state": "RUNNING"}]}
                    )
                )
            return completed(rc=1, stderr="Access denied")

        monkeypatch.setattr(sched, "_run_cmd", run_cmd)
        with pytest.raises(RuntimeError, match="scancel failed"):
            sched.cancel("1")


class TestElasticGang:
    """min_replicas -> one RANGED --nodes group; slurm restarts a requeued
    job with whatever node count survives (>= the floor)."""

    def _dryrun(self, sched, **role_kwargs):
        role_kwargs.setdefault("min_replicas", 1)
        role_kwargs.setdefault("max_retries", 2)
        app = AppDef(name="t", roles=[tpu_role(**role_kwargs)])
        return sched.submit_dryrun(app, {})

    def test_ranged_nodes_no_hetjob(self, sched):
        # v5p-16 slice = 2 hosts; min 1 slice -> 2-2 ... use 2 slices
        info = self._dryrun(sched, num_replicas=2, min_replicas=1)
        script = info.request.script()
        # 2 slices x 2 hosts max, floor 1 slice x 2 hosts
        assert "#SBATCH --nodes=2-4" in script
        assert "hetjob" not in script
        assert "--ntasks-per-node=1" in script
        assert info.request.elastic_range == (2, 4)

    def test_runtime_identity_derivation(self, sched):
        script = self._dryrun(sched, num_replicas=2, min_replicas=1).request.script()
        # identity comes from slurm at RUN time (size known only then)
        assert 'TPX_REPLICA_ID="$SLURM_PROCID"' in script
        assert 'TPX_NUM_REPLICAS="$SLURM_NTASKS"' in script
        # AppDef units (1 slice), matching GKE's TPX_MIN_REPLICAS injection
        assert "export TPX_MIN_REPLICAS=1" in script
        assert "export TPX_HOSTS_PER_UNIT=2" in script
        # whole-slice rounding: the srun step is clamped so a requeue that
        # lands on 3 surviving nodes runs a 2-host (1-slice) gang
        assert "TPX_USABLE_NODES=$(( SLURM_JOB_NUM_NODES / 2 * 2 ))" in script
        assert '--nodes="$TPX_USABLE_NODES" --ntasks="$TPX_USABLE_NODES"' in script
        # the macro-substituted arg defers to the task-derived env
        assert "--id=${SLURM_JOB_ID}" in script

    def test_requeue_trap_present(self, sched):
        script = self._dryrun(sched, num_replicas=2, min_replicas=1).request.script()
        assert "scontrol requeue" in script
        assert "trap tpx_requeue ERR" in script

    def test_per_task_log_files(self, sched):
        script = self._dryrun(sched, num_replicas=2, min_replicas=1).request.script()
        # %t = task id, matching log_iter's slurm-{id}-{role}-{k}.{out}
        assert "--output=slurm-${SLURM_JOB_ID}-trainer-%t.out" in script

    def test_multi_role_elastic_rejected(self, sched):
        cpu = Role(
            name="reader", image="/x", entrypoint="python",
            resource=Resource(cpu=2, memMB=100),
        )
        app = AppDef(
            name="t", roles=[tpu_role(min_replicas=1), cpu]
        )
        with pytest.raises(ValueError, match="single-role"):
            sched.submit_dryrun(app, {})

    def test_elastic_lifecycle_requeued_then_resized(self, sched, monkeypatch):
        """Canned lifecycle: sbatch -> squeue shows RUNNING on 4 nodes ->
        node failure requeues -> squeue shows REQUEUED then RUNNING on 2
        nodes -> sacct shows COMPLETED. The launcher's view stays coherent
        through the shrink."""
        phases = iter(
            [
                ("sinfo", completed(stdout="128000\n")),  # mem probe
                ("sbatch", completed(stdout="999\n")),
                ("squeue", completed(stdout=json.dumps({"jobs": [
                    {"job_id": 999, "name": "trainer-0",
                     "job_state": ["RUNNING"],
                     "job_resources": {"nodes": "n[0-3]"}}]}))),
                ("squeue", completed(stdout=json.dumps({"jobs": [
                    {"job_id": 999, "name": "trainer-0",
                     "job_state": ["REQUEUED"]}]}))),
                ("squeue", completed(stdout=json.dumps({"jobs": [
                    {"job_id": 999, "name": "trainer-0",
                     "job_state": ["RUNNING"],
                     "job_resources": {"nodes": "n[0-1]"}}]}))),
                ("squeue", completed(rc=1)),  # left the queue
                ("sacct", completed(stdout=(
                    "JobID|JobName|State\n"
                    "999|trainer-0|COMPLETED\n"
                    "999.batch|batch|COMPLETED\n"
                ))),
            ]
        )

        def run_cmd(cmd, **kw):
            expect, out = next(phases)
            assert cmd[0] == expect, (cmd, expect)
            return out

        monkeypatch.setattr(sched, "_run_cmd", run_cmd)
        app = AppDef(
            name="t", roles=[tpu_role(num_replicas=2, min_replicas=1,
                                      max_retries=2)]
        )
        app_id = sched.schedule(sched.submit_dryrun(app, {}))
        assert app_id == "999"
        assert sched.describe(app_id).state == AppState.RUNNING
        assert sched.describe(app_id).state == AppState.PENDING  # requeued
        assert sched.describe(app_id).state == AppState.RUNNING  # shrunk
        final = sched.describe(app_id)
        assert final.state == AppState.SUCCEEDED


class TestMemProbe:
    def _probe(self, sched, monkeypatch, sinfo_out, rc=0):
        calls = []

        def run_cmd(cmd, **kw):
            calls.append(cmd)
            if cmd[0] == "sinfo":
                return completed(stdout=sinfo_out, rc=rc)
            return completed(stdout="1\n")

        monkeypatch.setattr(sched, "_run_cmd", run_cmd)
        return calls

    def test_unset_realmemory_drops_mem(self, sched, monkeypatch):
        self._probe(sched, monkeypatch, "1\n1\n")
        script = sched.submit_dryrun(
            AppDef(name="t", roles=[tpu_role()]), {"partition": "tpu"}
        ).request.script()
        assert "--mem=" not in script

    def test_real_memory_keeps_mem(self, sched, monkeypatch):
        self._probe(sched, monkeypatch, "128000+\n")
        script = sched.submit_dryrun(
            AppDef(name="t", roles=[tpu_role()]), {"partition": "tpu"}
        ).request.script()
        assert "--mem=1000" in script

    def test_probe_failure_keeps_mem(self, sched, monkeypatch):
        self._probe(sched, monkeypatch, "", rc=1)
        script = sched.submit_dryrun(
            AppDef(name="t", roles=[tpu_role()]), {"partition": "x"}
        ).request.script()
        assert "--mem=1000" in script

    def test_probe_cached_per_partition(self, sched, monkeypatch):
        calls = self._probe(sched, monkeypatch, "128000\n")
        app = AppDef(name="t", roles=[tpu_role()])
        sched.submit_dryrun(app, {"partition": "tpu"})
        sched.submit_dryrun(app, {"partition": "tpu"})
        assert sum(1 for c in calls if c[0] == "sinfo") == 1


class TestSacctRequeueVariant:
    def test_requeued_job_with_extern_steps(self, sched, monkeypatch):
        """Third sacct variant: a requeued job mid-restart — REQUEUED top
        row maps to PENDING, `.extern`/`.batch`/`.0` step rows (including
        truncated `CANCELLED+` states) are skipped, and the launcher keeps
        polling rather than declaring the app dead."""

        def run_cmd(cmd, **kw):
            if cmd[0] == "squeue":
                return completed(rc=1)
            with open("tests/fixtures/sacct_requeue.txt") as f:
                return completed(stdout=f.read())

        monkeypatch.setattr(sched, "_run_cmd", run_cmd)
        resp = sched.describe("888")
        assert resp is not None
        assert resp.state == AppState.PENDING  # requeued, not failed
        (rs,) = [r for r in resp.roles_statuses if r.role == "spmd"]
        assert {r.id: r.state for r in rs.replicas} == {0: AppState.PENDING}
