"""Vertex AI scheduler tests: assert on the materialized CustomJob dict
(reference analog: aws_sagemaker_scheduler_test.py — dryrun request checks
with no cloud project)."""

from unittest import mock

import pytest

from torchx_tpu.schedulers.vertex_scheduler import (
    VertexScheduler,
    app_to_custom_job,
    cpu_machine_spec,
    describe_custom_job,
    tpu_machine_spec,
)
from torchx_tpu.specs.api import (
    AppDef,
    AppState,
    Resource,
    Role,
    TpuSlice,
    macros,
)


def tpu_role(chips=16, accelerator="v5p", **kwargs) -> Role:
    defaults = dict(
        name="trainer",
        image="gcr.io/proj/img:1",
        entrypoint="python",
        args=["-m", "train", f"--app={macros.app_id}"],
        resource=Resource(cpu=208, memMB=448 * 1024, tpu=TpuSlice(accelerator, chips)),
    )
    defaults.update(kwargs)
    return Role(**defaults)


@pytest.fixture
def sched():
    return VertexScheduler("test", client=mock.MagicMock())


class TestCustomJobMaterialization:
    def test_tpu_machine_spec_multihost(self):
        spec = tpu_machine_spec(tpu_role())  # v5p-32: 16 chips, 4 hosts
        assert spec["machineType"] == "ct5p-hightpu-4t"
        assert spec["tpuTopology"] == "2x2x4"

    def test_tpu_machine_spec_single_host(self):
        spec = tpu_machine_spec(tpu_role(chips=8, accelerator="v5e"))
        assert spec["machineType"] == "ct5lp-hightpu-8t"
        assert "tpuTopology" not in spec  # single host: no topology field

    # Multi-host v5e/v6e rides 4-chip VMs: ct5lp-hightpu-8t + tpuTopology 4x4
    # is an invalid machine spec Vertex rejects at admission.
    @pytest.mark.parametrize(
        "accelerator, chips, machine_type, topology",
        [
            ("v5e", 16, "ct5lp-hightpu-4t", "4x4"),
            ("v5e", 32, "ct5lp-hightpu-4t", "4x8"),
            ("v5e", 64, "ct5lp-hightpu-4t", "8x8"),
            ("v6e", 16, "ct6e-standard-4t", "4x4"),
            ("v6e", 32, "ct6e-standard-4t", "4x8"),
        ],
    )
    def test_tpu_machine_spec_multihost_v5e_v6e(
        self, accelerator, chips, machine_type, topology
    ):
        spec = tpu_machine_spec(tpu_role(chips=chips, accelerator=accelerator))
        assert spec["machineType"] == machine_type
        assert spec["tpuTopology"] == topology

    def test_unknown_generation_raises(self):
        with pytest.raises(ValueError, match="no Vertex AI machine type"):
            tpu_machine_spec(tpu_role(accelerator="v2", chips=8))

    def test_gpu_machine_spec_from_catalog(self):
        from torchx_tpu.specs import named_resources

        role = Role(
            name="scorer", image="i", entrypoint="python",
            resource=named_resources["gpu_a100_4"],
        )
        spec = cpu_machine_spec(role)
        assert spec == {
            "machineType": "a2-highgpu-4g",
            "acceleratorType": "NVIDIA_TESLA_A100",
            "acceleratorCount": 4,
        }

    def test_machine_type_capability_wins(self):
        role = Role(
            name="r", image="i", entrypoint="python",
            resource=Resource(
                cpu=6, memMB=40 * 1024,
                capabilities={"gce.machine_type": "c3-standard-22"},
            ),
        )
        assert cpu_machine_spec(role) == {"machineType": "c3-standard-22"}

    def test_cpu_machine_spec_covers_ask(self):
        role = Role(
            name="r", image="i", entrypoint="python",
            resource=Resource(cpu=6, memMB=40 * 1024),
        )
        assert cpu_machine_spec(role) == {"machineType": "n2-standard-16"}

    def test_worker_pools_and_env(self):
        app = AppDef(name="train", roles=[tpu_role()])
        job = app_to_custom_job(app, "train-abc12", "sess")
        assert job["displayName"] == "train-abc12"
        (pool,) = job["jobSpec"]["workerPoolSpecs"]
        assert pool["replicaCount"] == 1  # one slice = one logical replica
        cs = pool["containerSpec"]
        assert cs["imageUri"] == "gcr.io/proj/img:1"
        assert "--app=train-abc12" in cs["args"]  # macro substituted
        env = {e["name"]: e["value"] for e in cs["env"]}
        assert env["TPX_APP_ID"] == "train-abc12"
        assert env["TPX_NUM_REPLICAS"] == "4"  # per-host procs in the slice
        assert job["labels"]["tpx-session"] == "sess"

    def test_retries_enable_restart_scheduling(self):
        app = AppDef(name="t", roles=[tpu_role(max_retries=2)])
        job = app_to_custom_job(app, "t-x", "s")
        assert job["jobSpec"]["scheduling"] == {"restartJobOnWorkerRestart": True}

    def test_replica_retry_policy_never_restarts_the_job(self):
        from torchx_tpu.specs.api import RetryPolicy

        app = AppDef(
            name="t",
            roles=[tpu_role(max_retries=2, retry_policy=RetryPolicy.REPLICA)],
        )
        job = app_to_custom_job(app, "t-x", "s")
        assert "scheduling" not in job["jobSpec"]

    def test_multislice_rejected_on_submit_path(self, sched):
        # Scheduler.submit()/submit_dryrun() must hit the validation too,
        # not just the Runner path
        app = AppDef(name="t", roles=[tpu_role(num_replicas=2)])
        with pytest.raises(ValueError, match="multi-slice"):
            sched.submit_dryrun(app, {"project": "p"})

    def test_optional_infra_fields(self):
        app = AppDef(name="t", roles=[tpu_role()])
        job = app_to_custom_job(
            app, "t-x", "s",
            service_account="sa@proj.iam.gserviceaccount.com",
            network="projects/1/global/networks/vpc",
            staging_bucket="gs://bucket/out",
        )
        js = job["jobSpec"]
        assert js["serviceAccount"].startswith("sa@")
        assert js["network"].endswith("/vpc")
        assert js["baseOutputDirectory"] == {"outputUriPrefix": "gs://bucket/out"}

    def test_dryrun_materializes_full_request(self, sched):
        app = AppDef(name="t", roles=[tpu_role()])
        info = sched.submit_dryrun(app, {"project": "my-proj", "region": "us-east5"})
        req = info.request
        assert req.parent == "projects/my-proj/locations/us-east5"
        assert req.custom_job["jobSpec"]["workerPoolSpecs"]

    def test_multislice_rejected(self, sched):
        app = AppDef(name="t", roles=[tpu_role(num_replicas=2)])
        with pytest.raises(ValueError, match="multi-slice"):
            sched._validate(app, {})


class TestVertexLifecycle:
    def make_sched(self, tmp_path, monkeypatch, state="JOB_STATE_RUNNING"):
        monkeypatch.setattr(
            "torchx_tpu.schedulers.vertex_scheduler._registry_path",
            lambda: str(tmp_path / "jobs"),
        )
        client = mock.MagicMock()
        created = mock.MagicMock()
        created.name = "projects/p/locations/r/customJobs/123"
        client.create_custom_job.return_value = created
        got = mock.MagicMock()
        got.state.name = state
        got.error = None
        client.get_custom_job.return_value = got
        return VertexScheduler("test", client=client), client

    def test_schedule_describe_cancel(self, tmp_path, monkeypatch):
        sched, client = self.make_sched(tmp_path, monkeypatch)
        app = AppDef(name="t", roles=[tpu_role()])
        app_id = sched.submit(app, {"project": "p", "region": "r"})
        assert app_id.startswith("t-")
        kwargs = client.create_custom_job.call_args.kwargs
        assert kwargs["parent"] == "projects/p/locations/r"
        resp = sched.describe(app_id)
        assert resp.state == AppState.RUNNING
        sched.cancel(app_id)
        client.cancel_custom_job.assert_called_once_with(
            name="projects/p/locations/r/customJobs/123"
        )

    def test_describe_unknown_app(self, tmp_path, monkeypatch):
        sched, _ = self.make_sched(tmp_path, monkeypatch)
        assert sched.describe("nope") is None

    def test_log_iter_window_filters(self, tmp_path, monkeypatch):
        sched, client = self.make_sched(tmp_path, monkeypatch)
        app_id = sched.submit(
            AppDef(name="t", roles=[tpu_role()]), {"project": "p", "region": "r"}
        )
        calls = []

        def fake_run(cmd, **kwargs):
            calls.append(cmd)
            return mock.MagicMock(returncode=0, stdout="a\nb\n", stderr="")

        monkeypatch.setattr("subprocess.run", fake_run)
        lines = list(
            sched.log_iter(app_id, "w", 0, since=1785283200.0, until=1785286800.0)
        )
        assert lines == ["a", "b"]
        filt = calls[-1][3]
        assert 'timestamp>="2026-07-29T00:00:00Z"' in filt
        assert 'timestamp<="2026-07-29T01:00:00Z"' in filt

    def test_log_iter_rejects_stream_selection(self, tmp_path, monkeypatch):
        from torchx_tpu.schedulers.api import Stream

        sched, _ = self.make_sched(tmp_path, monkeypatch)
        app_id = sched.submit(
            AppDef(name="t", roles=[tpu_role()]), {"project": "p", "region": "r"}
        )
        with pytest.raises(ValueError, match="combined"):
            sched.log_iter(app_id, "w", 0, streams=Stream.STDERR)

    def test_state_map_and_error_surface(self):
        resp = describe_custom_job(
            "a",
            {"state": "JOB_STATE_FAILED", "error": {"message": "OOM on host 2"}},
        )
        assert resp.state == AppState.FAILED
        assert "OOM" in resp.structured_error_msg
        assert describe_custom_job("a", {"state": "JOB_STATE_WEIRD"}).state == (
            AppState.UNKNOWN
        )
