"""tpu_vm scheduler (canned gcloud output) + pipeline DAG tests."""

import json
import subprocess

import pytest

from torchx_tpu.pipelines import Pipeline, topo_order
from torchx_tpu.pipelines.kfp import pipeline_to_workflow
from torchx_tpu.pipelines.local_runner import run_pipeline
from torchx_tpu.runner.api import get_runner
from torchx_tpu.schedulers.tpu_vm_scheduler import TpuVmScheduler
from torchx_tpu.specs.api import (
    AppDef,
    AppState,
    Resource,
    Role,
    TpuSlice,
)


def completed(stdout="", rc=0, stderr=""):
    return subprocess.CompletedProcess([], returncode=rc, stdout=stdout, stderr=stderr)


def tpu_app(**role_kwargs) -> AppDef:
    defaults = dict(
        name="train",
        image="",
        entrypoint="python",
        args=["-m", "train"],
        env={"A": "1"},
        resource=Resource(cpu=208, memMB=1000, tpu=TpuSlice("v5p", 16)),
    )
    defaults.update(role_kwargs)
    return AppDef(name="train", roles=[Role(**defaults)])


@pytest.fixture
def sched():
    return TpuVmScheduler("test")


class TestTpuVmScheduler:
    def test_dryrun_materializes_gcloud_cmd(self, sched):
        info = sched.submit_dryrun(tpu_app(), {"zone": "us-east5-a"})
        req = info.request
        cmd = req.create_cmd()
        assert "--accelerator-type=v5p-32" in cmd
        assert "--zone=us-east5-a" in cmd
        assert req.runtime_version == "v2-alpha-tpuv5"
        script = req.startup_script
        assert "TPX_NUM_REPLICAS=4" in script
        assert "TPX_COORDINATOR_HOST" in script
        assert 'export A="1"' in script
        # double-quoted (not single): $WORKER_ID-style macros must expand
        assert "'" not in script.split("(")[1].split(")")[0]

    def test_spot_flag(self, sched):
        info = sched.submit_dryrun(tpu_app(), {"zone": "z", "spot": True})
        assert "--spot" in info.request.create_cmd()

    def test_rejects_multi_role(self, sched):
        app = tpu_app()
        app.roles.append(Role(name="extra", image="i", entrypoint="e"))
        with pytest.raises(ValueError, match="one role"):
            sched.submit_dryrun(app, {"zone": "z"})

    def test_rejects_cpu_role(self, sched):
        app = AppDef(
            name="x", roles=[Role(name="r", image="i", entrypoint="e")]
        )
        with pytest.raises(ValueError, match="TPU resource"):
            sched.submit_dryrun(app, {"zone": "z"})

    def test_requires_zone(self, sched):
        from torchx_tpu.specs.api import InvalidRunConfigException

        with pytest.raises(InvalidRunConfigException):
            sched.submit_dryrun(tpu_app(), {})

    def test_schedule_and_describe(self, sched, monkeypatch):
        calls = []

        def run_cmd(cmd, **kw):
            calls.append(cmd)
            if "create" in cmd:
                return completed(stdout="{}")
            if "describe" in cmd:
                return completed(
                    stdout=json.dumps(
                        {"state": {"state": "ACTIVE"}, "tpu": {"nodeSpec": [{}]}}
                    )
                )
            return completed()

        monkeypatch.setattr(sched, "_run_cmd", run_cmd)
        info = sched.submit_dryrun(tpu_app(), {"zone": "us-east5-a"})
        app_id = sched.schedule(info)
        assert app_id.startswith("us-east5-a:train-")
        resp = sched.describe(app_id)
        assert resp.state == AppState.RUNNING

    def test_describe_waiting(self, sched, monkeypatch):
        monkeypatch.setattr(
            sched,
            "_run_cmd",
            lambda cmd, **kw: completed(
                stdout=json.dumps({"state": {"state": "WAITING_FOR_RESOURCES"}})
            ),
        )
        assert sched.describe("z:n").state == AppState.PENDING

    def test_describe_missing(self, sched, monkeypatch):
        monkeypatch.setattr(sched, "_run_cmd", lambda cmd, **kw: completed(rc=1))
        assert sched.describe("z:nope") is None

    def test_cancel(self, sched, monkeypatch):
        calls = []

        def run_cmd(cmd, **kw):
            calls.append(cmd)
            if "describe" in cmd:
                return completed(stdout=json.dumps({"state": {"state": "ACTIVE"}}))
            return completed()

        monkeypatch.setattr(sched, "_run_cmd", run_cmd)
        sched.cancel("z:n")
        assert any("delete" in c for c in calls)


class TestTpuVmLogs:
    def fake_ssh(self, sched, monkeypatch, file_contents, exitcode="0"):
        """Fake the batched remote reader: serves per-file windows from
        canned contents, honoring offsets, one 'ssh' per poll."""
        calls = []

        def fetch(app_id, worker, offsets):
            calls.append((app_id, worker, dict(offsets)))
            chunks = {
                p: file_contents.get(p, "")[off - 1:]
                for p, off in offsets.items()
            }
            return {p: c for p, c in chunks.items() if c}, exitcode

        monkeypatch.setattr(sched, "_fetch_log_windows", fetch)
        return calls

    def test_parse_log_frames_roundtrip(self):
        from torchx_tpu.schedulers.tpu_vm_scheduler import _parse_log_frames

        payload = (
            "Warning: Permanently added 'host' to known hosts.\n"  # ssh noise
            "/tmp/tpx/stdout.log 21\n"
            "1722000100.000 hello\n"
            "/tmp/tpx/stderr.log 0\n"
            "__exitcode__ 0\n"
        )
        chunks, ec = _parse_log_frames(
            payload, ["/tmp/tpx/stdout.log", "/tmp/tpx/stderr.log"]
        )
        assert chunks == {"/tmp/tpx/stdout.log": "1722000100.000 hello\n"}
        assert ec == "0"

    def test_parse_log_frames_running_job(self):
        from torchx_tpu.schedulers.tpu_vm_scheduler import _parse_log_frames

        chunks, ec = _parse_log_frames(
            "/tmp/tpx/stdout.log 2\nhi__exitcode__ \n", ["/tmp/tpx/stdout.log"]
        )
        assert chunks == {"/tmp/tpx/stdout.log": "hi"}
        assert ec is None  # no exitcode file yet: job still running

    def test_fetch_builds_one_ssh_command(self, sched, monkeypatch):
        """The whole multi-file window fetch is ONE ssh invocation."""
        calls = []

        def run_cmd(cmd, **kw):
            calls.append(cmd)
            return completed(stdout="__exitcode__ \n")

        monkeypatch.setattr(sched, "_run_cmd", run_cmd)
        chunks, ec = sched._fetch_log_windows(
            "us-east5-a:n1", 1, {"/tmp/tpx/stdout.log": 1, "/tmp/tpx/stderr.log": 5}
        )
        (cmd,) = calls
        assert "ssh" in cmd and "--worker=1" in cmd and "--zone=us-east5-a" in cmd
        assert chunks == {} and ec is None

    def test_stamp_parsing_is_strict(self):
        from torchx_tpu.schedulers.tpu_vm_scheduler import _parse_stamp

        assert _parse_stamp("1722333444.123 payload") == (1722333444.123, "payload")
        # numeric-leading content lines are NOT stamps
        assert _parse_stamp("3 retries left") == (None, "3 retries left")
        assert _parse_stamp("42.5 degrees") == (None, "42.5 degrees")
        assert _parse_stamp("plain line") == (None, "plain line")

    def test_stream_selection_and_stamp_stripping(self, sched, monkeypatch):
        from torchx_tpu.schedulers.tpu_vm_scheduler import REMOTE_STDOUT
        from torchx_tpu.schedulers.api import Stream

        calls = self.fake_ssh(
            sched, monkeypatch,
            {REMOTE_STDOUT: "1722000100.000 line-a\n1722000101.000 line-b\n"},
        )
        lines = list(
            sched.log_iter("us-east5-a:node1", "tpu", k=1, streams=Stream.STDOUT)
        )
        assert lines == ["line-a", "line-b"]
        ((app_id, worker, offsets),) = calls
        assert app_id == "us-east5-a:node1" and worker == 1
        assert list(offsets) == [REMOTE_STDOUT]

    def test_combined_merges_streams_chronologically(self, sched, monkeypatch):
        from torchx_tpu.schedulers.tpu_vm_scheduler import (
            REMOTE_STDERR,
            REMOTE_STDOUT,
        )

        self.fake_ssh(
            sched, monkeypatch,
            {
                REMOTE_STDOUT: "1722000100.000 out-1\n1722000102.000 out-2\n",
                REMOTE_STDERR: "1722000101.000 err-1\n",
            },
        )
        lines = list(sched.log_iter("z:n", "tpu", 0))
        assert lines == ["out-1", "err-1", "out-2"]

    def test_since_until_window(self, sched, monkeypatch):
        from torchx_tpu.schedulers.tpu_vm_scheduler import REMOTE_STDOUT
        from torchx_tpu.schedulers.api import Stream

        self.fake_ssh(
            sched, monkeypatch,
            {REMOTE_STDOUT: "1722000100.000 early\n1722000200.000 mid\n1722000300.000 late\n"},
        )
        lines = list(
            sched.log_iter(
                "z:n", "tpu", 0, since=1722000150.0, until=1722000250.0, streams=Stream.STDOUT
            )
        )
        assert lines == ["mid"]

    def test_legacy_unstamped_lines_pass_through(self, sched, monkeypatch):
        from torchx_tpu.schedulers.tpu_vm_scheduler import REMOTE_LOG

        self.fake_ssh(
            sched, monkeypatch, {REMOTE_LOG: "raw-line-1\nraw-line-2\n"}
        )
        lines = list(sched.log_iter("z:n", "tpu", 0))
        assert lines == ["raw-line-1", "raw-line-2"]

    def test_tail_advances_offset_and_stops_on_exitcode(self, sched, monkeypatch):
        """Tailing fetches only NEW bytes each poll and stops after a
        final drain once the remote exitcode file appears — even though
        the queued resource itself stays ACTIVE after the job exits."""
        from torchx_tpu.schedulers.api import DescribeAppResponse, Stream
        from torchx_tpu.schedulers.tpu_vm_scheduler import REMOTE_STDOUT
        from torchx_tpu.specs.api import AppState

        content = {REMOTE_STDOUT: "1722000100.000 first\n"}
        state = {"polls": 0}
        offsets_seen = []

        def fetch(app_id, worker, offsets):
            state["polls"] += 1
            off = offsets[REMOTE_STDOUT]
            offsets_seen.append(off)
            chunk = content[REMOTE_STDOUT][off - 1:]
            # the job "finishes" (writes exitcode) on the second poll
            ec = "0" if state["polls"] >= 2 else None
            if state["polls"] == 1:
                content[REMOTE_STDOUT] += "1722000101.000 second\n"
            return ({REMOTE_STDOUT: chunk} if chunk else {}), ec

        monkeypatch.setattr(sched, "_fetch_log_windows", fetch)
        # queued resource stays ACTIVE (RUNNING) forever — must NOT hang
        monkeypatch.setattr(
            sched,
            "describe",
            lambda a: DescribeAppResponse(app_id=a, state=AppState.RUNNING),
        )
        monkeypatch.setattr("time.sleep", lambda s: None)
        lines = list(
            sched.log_iter(
                "z:n", "tpu", 0, should_tail=True, streams=Stream.STDOUT
            )
        )
        assert lines[0] == "first" and "second" in lines
        assert offsets_seen[0] == 1 and offsets_seen[-1] > 1

    def test_tail_survives_transient_describe_failures(self, sched, monkeypatch):
        """One flaky gcloud describe must not end a live tail; repeated
        failures eventually do (no infinite loop on a deleted resource)."""
        from torchx_tpu.schedulers.api import Stream
        from torchx_tpu.schedulers.tpu_vm_scheduler import REMOTE_STDOUT

        state = {"polls": 0}

        def fetch(app_id, worker, offsets):
            state["polls"] += 1
            if state["polls"] == 1:
                return {REMOTE_STDOUT: "1722000100.000 only-line\n"}, None
            return {}, None

        monkeypatch.setattr(sched, "_fetch_log_windows", fetch)
        monkeypatch.setattr(sched, "describe", lambda a: None)  # always fails
        monkeypatch.setattr("time.sleep", lambda s: None)
        lines = list(
            sched.log_iter(
                "z:n", "tpu", 0, should_tail=True, streams=Stream.STDOUT
            )
        )
        assert lines == ["only-line"]
        # tolerated 3 describe failures (4 polls: initial + 3 retries)
        assert state["polls"] >= 4

    def test_log_fetch_failure(self, sched, monkeypatch):
        monkeypatch.setattr(
            sched, "_run_cmd", lambda cmd, **kw: completed(rc=255, stderr="no ssh")
        )
        with pytest.raises(RuntimeError, match="log fetch"):
            list(sched.log_iter("z:n", "tpu", 0))


class TestPipelineModel:
    def app(self, name="a"):
        return AppDef(
            name=name, roles=[Role(name="r", image="", entrypoint="true")]
        )

    def test_topo_generations(self):
        p = (
            Pipeline("p")
            .stage("a", self.app())
            .stage("b", self.app(), depends_on=["a"])
            .stage("c", self.app(), depends_on=["a"])
            .stage("d", self.app(), depends_on=["b", "c"])
        )
        gens = topo_order(p)
        names = [[s.name for s in g] for g in gens]
        assert names[0] == ["a"]
        assert sorted(names[1]) == ["b", "c"]
        assert names[2] == ["d"]

    def test_cycle_detected(self):
        p = (
            Pipeline("p")
            .stage("a", self.app(), depends_on=["b"])
            .stage("b", self.app(), depends_on=["a"])
        )
        with pytest.raises(ValueError, match="cycle"):
            topo_order(p)

    def test_unknown_dep(self):
        p = Pipeline("p").stage("a", self.app(), depends_on=["ghost"])
        with pytest.raises(ValueError, match="unknown"):
            topo_order(p)

    def test_duplicate_names(self):
        p = Pipeline("p").stage("a", self.app()).stage("a", self.app())
        with pytest.raises(ValueError, match="duplicate"):
            topo_order(p)


class TestLocalPipelineRun:
    def sh_app(self, name, script):
        return AppDef(
            name=name,
            roles=[Role(name=name, image="", entrypoint="sh", args=["-c", script])],
        )

    def test_three_stage_success(self, tmp_path):
        p = (
            Pipeline("p")
            .stage("data", self.sh_app("data", f"echo d > {tmp_path}/data"))
            .stage(
                "train",
                self.sh_app("train", f"test -f {tmp_path}/data && echo t > {tmp_path}/model"),
                depends_on=["data"],
            )
            .stage(
                "eval",
                self.sh_app("eval", f"test -f {tmp_path}/model"),
                depends_on=["train"],
            )
        )
        with get_runner("pipe-test") as runner:
            run = run_pipeline(
                runner, p, "local", {"log_dir": str(tmp_path / "logs")}, wait_interval=0.1
            )
        assert run.state == AppState.SUCCEEDED
        assert set(run.statuses) == {"data", "train", "eval"}

    def test_fail_fast_cancels_sibling(self, tmp_path):
        p = (
            Pipeline("p")
            .stage("fast-fail", self.sh_app("fastfail", "sleep 0.3; exit 1"))
            .stage("slow", self.sh_app("slow", "sleep 60"))
        )
        import time as _time

        t0 = _time.monotonic()
        with get_runner("pipe-ff") as runner:
            run = run_pipeline(
                runner, p, "local", {"log_dir": str(tmp_path)}, wait_interval=0.1
            )
        assert run.state == AppState.FAILED
        # the 60s sibling must have been cancelled promptly
        assert _time.monotonic() - t0 < 30
        assert run.statuses["slow"].state in (AppState.CANCELLED, AppState.FAILED)

    def test_failure_skips_downstream(self, tmp_path):
        p = (
            Pipeline("p")
            .stage("bad", self.sh_app("bad", "exit 1"))
            .stage("after", self.sh_app("after", "true"), depends_on=["bad"])
        )
        with get_runner("pipe-fail") as runner:
            run = run_pipeline(
                runner, p, "local", {"log_dir": str(tmp_path)}, wait_interval=0.1
            )
        assert run.state == AppState.FAILED
        assert "after" not in run.handles  # never submitted


class TestKfpAdapter:
    def test_workflow_emission(self):
        from torchx_tpu.examples.pipeline_data_train_eval import build_pipeline

        p = build_pipeline("/tmp/w", tpu="v5p-32")
        wf = pipeline_to_workflow(p)
        assert wf["kind"] == "Workflow"
        templates = {t["name"]: t for t in wf["spec"]["templates"]}
        dag_tasks = {t["name"]: t for t in templates["dag"]["dag"]["tasks"]}
        assert dag_tasks["train"]["dependencies"] == ["data"]
        assert dag_tasks["eval"]["dependencies"] == ["train"]
        # TPU multi-host train stage becomes a JobSet resource template;
        # the manifest must be a string (Argo CRD contract)
        assert "resource" in templates["train"]
        manifest = templates["train"]["resource"]["manifest"]
        assert isinstance(manifest, str)
        assert json.loads(manifest)["kind"] == "JobSet"
        # single-pod stages are plain container templates
        assert "container" in templates["data"]
