"""Flagship end-to-end: dist.spmd forms a real multi-process JAX mesh.

The TPU analog of the reference's compute_world_size e2e
(torchx/examples/apps/compute_world_size, driven by DistributedTestCase at
test/fixtures.py:253-305): 2 processes x 2 simulated devices rendezvous via
jax.distributed and psum across the global mesh.
"""

import os

import pytest

import torchx_tpu
from torchx_tpu.runner.api import get_runner
from torchx_tpu.specs.api import AppState

EXAMPLE = os.path.join(
    os.path.dirname(torchx_tpu.__file__), "examples", "compute_mesh_size.py"
)


@pytest.mark.e2e
def test_spmd_mesh_formation(tmp_path):
    with get_runner("spmd-e2e") as runner:
        handle = runner.run_component(
            "dist.spmd",
            ["-j", "2x2", "--script", EXAMPLE],
            "local",
            {"log_dir": str(tmp_path)},
        )
        status = runner.wait(handle, wait_interval=0.5)
        assert status is not None and status.state == AppState.SUCCEEDED, (
            status and status.format()
        )
        for replica in (0, 1):
            lines = list(runner.log_lines(handle, "spmd", replica))
            assert any("computed_mesh_size=4" in ln for ln in lines), lines


@pytest.mark.e2e
def test_ddp_torchrun_world_size(tmp_path):
    """The compat dist.ddp path: torchrun + c10d rendezvous + gloo
    allreduce (the reference's canonical e2e, compute_world_size)."""
    script = os.path.join(
        os.path.dirname(torchx_tpu.__file__),
        "examples",
        "compute_world_size_torch.py",
    )
    with get_runner("ddp-e2e") as runner:
        handle = runner.run_component(
            "dist.ddp",
            ["-j", "1x2", "--script", script],
            "local",
            {"log_dir": str(tmp_path)},
        )
        status = runner.wait(handle, wait_interval=0.5)
        assert status is not None and status.state == AppState.SUCCEEDED, (
            status and status.format()
        )
        lines = list(runner.log_lines(handle, "ddp", 0))
        assert any("computed_world_size=2" in ln for ln in lines), lines


@pytest.mark.e2e
def test_ddp_multinode_deferred_endpoint(tmp_path):
    """2 separate torchrun agents rendezvous through the shell-deferred
    ${TPX_COORDINATOR_HOST:=localhost} endpoint (SURVEY hard-part (a))."""
    script = os.path.join(
        os.path.dirname(torchx_tpu.__file__),
        "examples",
        "compute_world_size_torch.py",
    )
    with get_runner("ddp-mn") as runner:
        handle = runner.run_component(
            "dist.ddp",
            ["-j", "2x1", "--script", script],
            "local",
            {"log_dir": str(tmp_path)},
        )
        status = runner.wait(handle, wait_interval=0.5)
        assert status.state == AppState.SUCCEEDED, status.format()
        lines = list(runner.log_lines(handle, "ddp", 0))
        assert any("computed_world_size=2" in ln for ln in lines), lines


@pytest.mark.e2e
def test_spmd_failure_surfaces_structured_error(tmp_path):
    with get_runner("spmd-e2e-fail") as runner:
        handle = runner.run_component(
            "dist.spmd",
            [
                "-j",
                "1x1",
                "--script",
                EXAMPLE,
                "--env",
                "TPX_EXAMPLE_THROWS=1",
            ],
            "local",
            {"log_dir": str(tmp_path)},
        )
        status = runner.wait(handle, wait_interval=0.5)
        assert status.state == AppState.FAILED
        assert "injected failure" in status.structured_error_msg


@pytest.mark.e2e
def test_spmd_retry_restarts_failed_gang(tmp_path):
    """Fault-injected replica death + max_retries: the gang restarts and
    the SECOND attempt forms the full mesh (VERDICT/BASELINE: retry
    policies actually restart a failed gang, proven end-to-end)."""
    marker = tmp_path / "fault-fired"
    with get_runner("spmd-e2e-retry") as runner:
        handle = runner.run_component(
            "dist.spmd",
            [
                "-j",
                "2x2",
                "--script",
                EXAMPLE,
                "--max_retries",
                "1",
                "--env",
                f"TPX_EXAMPLE_THROWS=once:{marker},TPX_EXAMPLE_THROWS_REPLICA=1",
            ],
            "local",
            {"log_dir": str(tmp_path)},
        )
        status = runner.wait(handle, wait_interval=0.5)
        assert status is not None and status.state == AppState.SUCCEEDED, (
            status and status.format()
        )
        assert marker.exists()  # the fault really fired on attempt 0
        for replica in (0, 1):
            lines = list(runner.log_lines(handle, "spmd", replica))
            assert any("computed_mesh_size=4" in ln for ln in lines), lines


@pytest.mark.e2e
def test_resize_resumes_training_from_checkpoint(tmp_path):
    """BASELINE config 4, operator-driven: `resize` a live 2-process SPMD
    training gang down to 1; the restarted world re-forms jax.distributed,
    resumes from the checkpoint, and finishes."""
    import time

    ckpt = tmp_path / "ckpt"
    with get_runner("resize-e2e") as runner:
        handle = runner.run_component(
            "dist.spmd",
            [
                "-j", "2x1",
                "-m", "torchx_tpu.examples.train_llama",
                "--",
                "--config", "tiny",
                "--mesh", "dp=-1,fsdp=1",
                "--batch", "4",
                "--seq", "32",
                "--steps", "300",
                "--ckpt-dir", str(ckpt),
                "--ckpt-every", "20",
            ],
            "local",
            {"log_dir": str(tmp_path)},
        )
        def finalized_step() -> bool:
            # orbax writes async saves into *.orbax-checkpoint-tmp-* staging
            # dirs first; only a committed digit-named step dir (or pickle
            # step file) counts as a durable checkpoint
            if not ckpt.exists():
                return False
            return any(
                p.name.isdigit() or p.name.startswith("step_")
                for p in ckpt.iterdir()
            )

        # wait until training is underway and a checkpoint landed
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if finalized_step():
                break
            status = runner.status(handle)
            assert status is not None and not status.is_terminal(), (
                status and status.format()
            )
            time.sleep(0.5)
        else:
            raise TimeoutError("no checkpoint appeared")
        runner.resize(handle, "spmd", 1)
        status = runner.wait(handle, wait_interval=0.5)
        assert status is not None and status.state == AppState.SUCCEEDED, (
            status and status.format()
        )
        lines = list(runner.log_lines(handle, "spmd", 0))
        assert any("resumed from checkpoint step" in ln for ln in lines), lines
        # exactly one replica in the resized terminal gang
        (rs,) = status.roles
        assert len(rs.replicas) == 1
