"""Tests for auxiliary components, structured args, process_monitor,
notebook workspace, deprecations."""

import subprocess
import sys
import time
import warnings
from pathlib import Path

import pytest

from torchx_tpu.components import metrics, serve, utils
from torchx_tpu.components.component_test_base import ComponentTestCase
from torchx_tpu.components.structured_arg import (
    StructuredJArgument,
    StructuredNameArgument,
)
from torchx_tpu.specs.builders import materialize_appdef


class TestStructuredArgs:
    def test_name_parse(self):
        a = StructuredNameArgument.parse_from("exp/run")
        assert (a.app_name, a.role_name) == ("exp", "run")
        a = StructuredNameArgument.parse_from("justapp")
        assert a.app_name == "justapp" and a.role_name == "role"
        a = StructuredNameArgument.parse_from("/justrole")
        assert a.app_name == "app" and a.role_name == "justrole"

    def test_j_parse_explicit(self):
        a = StructuredJArgument.parse_from("1:2x4")
        assert (a.min_replicas, a.replicas, a.nproc) == (1, 2, 4)
        assert str(a) == "1:2x4"

    def test_j_nproc_inferred_from_named_resource(self):
        a = StructuredJArgument.parse_from("2", h="v5litepod-8")
        assert a.nproc == 8
        a = StructuredJArgument.parse_from("2", h="cpu_small")
        assert a.nproc == 1


class TestAuxComponents(ComponentTestCase):
    def test_tensorboard_lints(self):
        self.validate(metrics, "tensorboard")

    def test_model_server_lints(self):
        self.validate(serve, "model_server")

    def test_tensorboard_materializes(self):
        app = materialize_appdef(
            metrics.tensorboard,
            ["--logdir", "/mnt/logs", "--exit_on_file", "/mnt/logs/DONE"],
        )
        args = " ".join(app.roles[0].args)
        assert "process_monitor" in args
        assert "--logdir /mnt/logs" in args
        assert "--exit_on_file /mnt/logs/DONE" in args
        assert app.roles[0].port_map["http"] == 6006

    def test_model_server_materializes(self):
        app = materialize_appdef(
            serve.model_server,
            [
                "--model_path",
                "gs://b/m",
                "--management_api",
                "http://srv:8081",
            ],
        )
        args = app.roles[0].args
        assert "gs://b/m" in args and "http://srv:8081" in args

    def test_run_component_helper(self, tmp_path=None):
        handle = self.run_component(
            utils.echo, ["--msg", "from-component-test"], scheduler="local"
        )
        assert handle.startswith("local://")


class TestProcessMonitor:
    def test_exit_on_file(self, tmp_path):
        marker = tmp_path / "DONE"
        proc = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "torchx_tpu.apps.process_monitor",
                "--poll_interval",
                "0.1",
                "--",
                "sleep",
                "30",
            ],
        )
        time.sleep(1.0)
        assert proc.poll() is None
        marker.write_text("")
        # no exit_on_file passed -> still running; now test with the flag
        proc.terminate()
        proc.wait()

        proc = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "torchx_tpu.apps.process_monitor",
                "--poll_interval",
                "0.1",
                "--exit_on_file",
                str(marker),
                "--",
                "sleep",
                "30",
            ],
        )
        assert proc.wait(timeout=15) == 0

    def test_timeout(self):
        t0 = time.monotonic()
        rc = subprocess.run(
            [
                sys.executable,
                "-m",
                "torchx_tpu.apps.process_monitor",
                "--timeout",
                "1",
                "--poll_interval",
                "0.1",
                "--",
                "sleep",
                "30",
            ],
            timeout=20,
        ).returncode
        assert rc == 0
        assert time.monotonic() - t0 < 15

    def test_propagates_exit_code(self):
        rc = subprocess.run(
            [
                sys.executable,
                "-m",
                "torchx_tpu.apps.process_monitor",
                "--poll_interval",
                "0.1",
                "--",
                "sh",
                "-c",
                "exit 3",
            ],
            timeout=20,
        ).returncode
        assert rc == 3


class TestNotebook:
    def test_workspacefile(self, monkeypatch, tmp_path):
        import torchx_tpu.notebook as nb

        monkeypatch.setattr(nb, "_workspace_dir", str(tmp_path))
        nb.workspacefile("sub/main.py", "print('hi')\n")
        assert (tmp_path / "sub" / "main.py").read_text() == "print('hi')\n"

    def test_empty_line_rejected(self):
        import torchx_tpu.notebook as nb

        with pytest.raises(ValueError):
            nb.workspacefile("", "x")


class TestDeprecations:
    def test_deprecated_warns(self):
        from torchx_tpu.deprecations import deprecated

        @deprecated(replacement="new_fn", since="0.2")
        def old_fn():
            return 42

        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            assert old_fn() == 42
        assert any("new_fn" in str(x.message) for x in w)
