"""Model/ops/parallel stack tests on the 8-device CPU mesh."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchx_tpu.models import llama
from torchx_tpu.ops.attention import xla_attention
from torchx_tpu.ops.norms import rms_norm
from torchx_tpu.ops.ring_attention import ring_attention
from torchx_tpu.ops.rope import apply_rope, rope_frequencies
from torchx_tpu.parallel.mesh import MeshConfig, make_mesh


class TestMeshConfig:
    def test_resolve_wildcard(self):
        assert MeshConfig(dp=2, fsdp=-1, tp=2).resolve(8) == {
            "pp": 1,
            "dp": 2,
            "fsdp": 2,
            "ep": 1,
            "tp": 2,
            "sp": 1,
        }

    def test_resolve_exact(self):
        assert MeshConfig(dp=1, fsdp=8, tp=1, sp=1).resolve(8)["fsdp"] == 8

    def test_resolve_errors(self):
        with pytest.raises(ValueError):
            MeshConfig(dp=3, fsdp=-1).resolve(8)
        with pytest.raises(ValueError):
            MeshConfig(dp=2, fsdp=2).resolve(8)
        with pytest.raises(ValueError):
            MeshConfig(dp=-1, fsdp=-1).resolve(8)

    def test_make_mesh(self):
        mesh = make_mesh(MeshConfig(dp=2, fsdp=2, tp=2, sp=1))
        assert dict(mesh.shape) == {
            "pp": 1, "dp": 2, "fsdp": 2, "ep": 1, "tp": 2, "sp": 1,
        }


class TestOps:
    def test_rms_norm_matches_reference(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 16))
        w = jax.random.normal(jax.random.PRNGKey(1), (16,))
        out = rms_norm(x, w)
        ref = x / np.sqrt((np.asarray(x) ** 2).mean(-1, keepdims=True) + 1e-5) * w
        np.testing.assert_allclose(out, ref, rtol=1e-5)

    def test_rms_norm_fused_bwd_matches_xla(self):
        """The fused Pallas backward (interpret mode on CPU) produces the
        same dx/dw as autodiff of the plain XLA forward."""
        x = jax.random.normal(
            jax.random.PRNGKey(0), (2, 16, 128), dtype=jnp.float32
        )
        w = 1.0 + 0.1 * jax.random.normal(jax.random.PRNGKey(1), (128,))
        dy = jax.random.normal(jax.random.PRNGKey(2), x.shape)

        def loss(fused):
            def f(x, w):
                return jnp.sum(rms_norm(x, w, fused=fused) * dy)

            return jax.grad(f, argnums=(0, 1))(x, w)

        dx_ref, dw_ref = loss("never")
        dx_fused, dw_fused = loss("interpret")
        np.testing.assert_allclose(dx_fused, dx_ref, rtol=2e-5, atol=2e-6)
        np.testing.assert_allclose(dw_fused, dw_ref, rtol=2e-5, atol=2e-6)

    def test_rms_norm_fused_bwd_bf16(self):
        x = jax.random.normal(
            jax.random.PRNGKey(0), (4, 8, 256), dtype=jnp.bfloat16
        )
        w = jnp.ones((256,), dtype=jnp.bfloat16)
        dy = jax.random.normal(jax.random.PRNGKey(2), x.shape, jnp.bfloat16)

        def grads(fused):
            def f(x, w):
                return jnp.sum(
                    rms_norm(x, w, fused=fused).astype(jnp.float32)
                    * dy.astype(jnp.float32)
                )

            return jax.grad(f, argnums=(0, 1))(x, w)

        dx_ref, dw_ref = grads("never")
        dx_fused, dw_fused = grads("interpret")
        np.testing.assert_allclose(
            np.asarray(dx_fused, np.float32),
            np.asarray(dx_ref, np.float32),
            rtol=0.05,
            atol=0.02,
        )
        np.testing.assert_allclose(
            np.asarray(dw_fused, np.float32),
            np.asarray(dw_ref, np.float32),
            rtol=0.05,
            atol=0.02,
        )

    @pytest.mark.parametrize(
        "axes",
        [
            dict(dp=2, fsdp=2, tp=1, sp=2),
            dict(dp=1, fsdp=2, tp=2, sp=2),
            # pp > 1: the norm runs inside one stage; the wrap must not
            # touch the pp axis
            dict(pp=2, fsdp=2, tp=1, sp=2),
            # ep > 1: expert axis present but dense layers ignore it
            dict(fsdp=2, ep=2, tp=2, sp=1),
        ],
    )
    def test_rms_norm_fused_sharded_mesh(self, axes):
        """The full-manual shard_map wrap: grads (incl. the weight grad,
        summed over row shards and de-duplicated over tp) match the
        unsharded reference."""
        mesh = make_mesh(MeshConfig(**axes))
        x = jax.random.normal(jax.random.PRNGKey(0), (4, 16, 128))
        w = 1.0 + 0.1 * jax.random.normal(jax.random.PRNGKey(1), (128,))
        dy = jax.random.normal(jax.random.PRNGKey(2), x.shape)

        def f(x, w):
            return jnp.sum(rms_norm(x, w, fused="interpret", mesh=mesh) * dy)

        def ref(x, w):
            return jnp.sum(rms_norm(x, w, fused="never") * dy)

        dx, dw = jax.jit(jax.grad(f, argnums=(0, 1)))(x, w)
        dx_ref, dw_ref = jax.grad(ref, argnums=(0, 1))(x, w)
        np.testing.assert_allclose(dx, dx_ref, rtol=2e-5, atol=2e-6)
        np.testing.assert_allclose(dw, dw_ref, rtol=2e-5, atol=2e-6)

    def test_attention_shard_wrap_matches_xla(self):
        """The fully-manual shard_map wrap Mosaic kernels need on sharded
        meshes (ops/attention._shard_wrap): splash (interpret mode) under
        the wrap on a dp x fsdp x tp mesh matches plain xla attention."""
        import importlib

        # torchx_tpu.ops re-exports the attention FUNCTION under the
        # submodule's name, so plain `import ... as` resolves to the
        # function; go through importlib for the module itself
        attn_mod = importlib.import_module("torchx_tpu.ops.attention")
        from torchx_tpu.parallel.mesh import MeshConfig, make_mesh

        mesh = make_mesh(MeshConfig(dp=2, fsdp=2, tp=2, sp=1))
        q = jax.random.normal(jax.random.PRNGKey(0), (4, 512, 8, 64))
        k = jax.random.normal(jax.random.PRNGKey(1), (4, 512, 4, 64))
        v = jax.random.normal(jax.random.PRNGKey(2), (4, 512, 4, 64))

        def kernel(q, k, v, seg):  # noqa: ANN001
            return attn_mod.splash_attention(
                q, k, v, causal=True, interpret=True, segment_ids=seg
            )
        out = jax.jit(
            lambda q, k, v: attn_mod._shard_wrap(
                kernel, q, k, v, None, mesh, ("dp", "fsdp"), "tp"
            )
        )(q, k, v)
        ref = attn_mod.xla_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=5e-3, rtol=5e-3
        )

    def test_rope_rotation_preserves_norm(self):
        cos, sin = rope_frequencies(16, 32)
        x = jax.random.normal(jax.random.PRNGKey(0), (1, 32, 2, 16))
        out = apply_rope(x, cos, sin)
        np.testing.assert_allclose(
            jnp.linalg.norm(out, axis=-1), jnp.linalg.norm(x, axis=-1), rtol=1e-5
        )

    def test_rope_position_zero_identity(self):
        cos, sin = rope_frequencies(8, 4)
        x = jax.random.normal(jax.random.PRNGKey(0), (1, 4, 1, 8))
        out = apply_rope(x, cos, sin)
        np.testing.assert_allclose(out[0, 0], x[0, 0], rtol=1e-6)

    def test_attention_causality(self):
        # perturbing a future token must not change earlier outputs
        q = jax.random.normal(jax.random.PRNGKey(0), (1, 8, 2, 16))
        k = jax.random.normal(jax.random.PRNGKey(1), (1, 8, 2, 16))
        v = jax.random.normal(jax.random.PRNGKey(2), (1, 8, 2, 16))
        out1 = xla_attention(q, k, v, causal=True)
        k2 = k.at[:, -1].set(99.0)
        v2 = v.at[:, -1].set(99.0)
        out2 = xla_attention(q, k2, v2, causal=True)
        np.testing.assert_allclose(out1[:, :-1], out2[:, :-1], rtol=1e-5)
        assert not np.allclose(out1[:, -1], out2[:, -1])

    def test_gqa_equals_repeated_mha(self):
        q = jax.random.normal(jax.random.PRNGKey(0), (1, 8, 4, 16))
        k = jax.random.normal(jax.random.PRNGKey(1), (1, 8, 2, 16))
        v = jax.random.normal(jax.random.PRNGKey(2), (1, 8, 2, 16))
        gqa = xla_attention(q, k, v)
        k_rep = jnp.repeat(k, 2, axis=2)
        v_rep = jnp.repeat(v, 2, axis=2)
        mha = xla_attention(q, k_rep, v_rep)
        np.testing.assert_allclose(gqa, mha, rtol=1e-5)

    def test_segment_ids_block_cross_attention(self):
        q = k = v = jax.random.normal(jax.random.PRNGKey(0), (1, 8, 1, 8))
        seg = jnp.array([[0, 0, 0, 0, 1, 1, 1, 1]])
        out = xla_attention(q, k, v, causal=True, segment_ids=seg)
        # first token of segment 1 attends only to itself -> output == its v
        np.testing.assert_allclose(out[0, 4, 0], v[0, 4, 0], rtol=1e-5)


class TestSplashAttention:
    def test_matches_reference_fwd(self):
        # pallas interpreter on CPU: GQA shapes (4 q-heads over 2 kv)
        from torchx_tpu.ops.attention import splash_attention

        b, s, h, kvh, d = 1, 256, 4, 2, 64
        q = jax.random.normal(jax.random.PRNGKey(0), (b, s, h, d), jnp.float32)
        k = jax.random.normal(jax.random.PRNGKey(1), (b, s, kvh, d), jnp.float32)
        v = jax.random.normal(jax.random.PRNGKey(2), (b, s, kvh, d), jnp.float32)
        ref = xla_attention(q, k, v, causal=True)
        out = splash_attention(q, k, v, causal=True, interpret=True)
        np.testing.assert_allclose(out, ref, rtol=5e-3, atol=5e-3)

    def test_segment_ids(self):
        from torchx_tpu.ops.attention import splash_attention

        b, s, h, d = 1, 256, 2, 64
        q = jax.random.normal(jax.random.PRNGKey(0), (b, s, h, d), jnp.float32)
        k = jax.random.normal(jax.random.PRNGKey(1), (b, s, h, d), jnp.float32)
        v = jax.random.normal(jax.random.PRNGKey(2), (b, s, h, d), jnp.float32)
        seg = jnp.concatenate(
            [jnp.zeros((b, s // 2), jnp.int32), jnp.ones((b, s // 2), jnp.int32)],
            axis=1,
        )
        ref = xla_attention(q, k, v, causal=True, segment_ids=seg)
        out = splash_attention(
            q, k, v, causal=True, segment_ids=seg, interpret=True
        )
        np.testing.assert_allclose(out, ref, rtol=5e-3, atol=5e-3)


class TestAttentionBlockSanitize:
    def test_fit_block(self):
        # shared by the pallas and splash paths: divide-seq + lane rules
        from torchx_tpu.ops.attention import _fit_block

        assert _fit_block(256, 2048) == 256
        assert _fit_block(256, 1920) == 128  # must divide seq
        assert _fit_block(192, 2048) == 128  # lane multiple
        assert _fit_block(64, 2048) == 128  # clamped up to the lane minimum
        assert _fit_block(1024, 1536) == 768  # largest divisor <= requested
        assert _fit_block(512, 640) == 128
        assert _fit_block(256, 320) == 0  # seq not a multiple of 128
        assert _fit_block(128, 64) == 0  # seq below one lane tile


class TestRingAttention:
    def test_matches_reference_fwd_bwd(self):
        mesh = make_mesh(MeshConfig(dp=1, fsdp=2, tp=1, sp=4))
        b, s, h, kvh, d = 4, 32, 8, 4, 16
        q = jax.random.normal(jax.random.PRNGKey(0), (b, s, h, d))
        k = jax.random.normal(jax.random.PRNGKey(1), (b, s, kvh, d))
        v = jax.random.normal(jax.random.PRNGKey(2), (b, s, kvh, d))
        ref = xla_attention(q, k, v, causal=True)
        out = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh))(q, k, v)
        np.testing.assert_allclose(out, ref, atol=2e-5)

        g_ring = jax.grad(lambda q: jnp.sum(ring_attention(q, k, v, mesh) ** 2))(q)
        g_ref = jax.grad(lambda q: jnp.sum(xla_attention(q, k, v, True) ** 2))(q)
        np.testing.assert_allclose(g_ring, g_ref, atol=1e-4)


class TestUlyssesAttention:
    def test_matches_reference_fwd_bwd(self):
        from torchx_tpu.ops.ulysses import ulysses_attention

        mesh = make_mesh(MeshConfig(dp=1, fsdp=2, tp=1, sp=4))
        b, s, h, kvh, d = 4, 32, 8, 4, 16
        q = jax.random.normal(jax.random.PRNGKey(0), (b, s, h, d))
        k = jax.random.normal(jax.random.PRNGKey(1), (b, s, kvh, d))
        v = jax.random.normal(jax.random.PRNGKey(2), (b, s, kvh, d))
        ref = xla_attention(q, k, v, causal=True)
        out = jax.jit(lambda q, k, v: ulysses_attention(q, k, v, mesh))(q, k, v)
        np.testing.assert_allclose(out, ref, atol=1e-6)
        g1 = jax.grad(lambda q: jnp.sum(ulysses_attention(q, k, v, mesh) ** 2))(q)
        g2 = jax.grad(lambda q: jnp.sum(xla_attention(q, k, v, True) ** 2))(q)
        np.testing.assert_allclose(g1, g2, atol=1e-5)

    def test_heads_not_divisible_raises(self):
        from torchx_tpu.ops.ulysses import ulysses_attention

        mesh = make_mesh(MeshConfig(dp=1, fsdp=2, tp=1, sp=4))
        q = jnp.zeros((2, 32, 6, 8))  # 6 heads % 4 != 0
        with pytest.raises(ValueError, match="divisible"):
            ulysses_attention(q, q, q, mesh)


class TestLlama:
    def test_forward_shapes_and_dtype(self):
        cfg = llama.llama_tiny()
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jnp.zeros((2, 16), dtype=jnp.int32)
        logits = llama.forward(params, tokens, cfg)
        assert logits.shape == (2, 16, cfg.vocab_size)
        assert logits.dtype == jnp.float32

    def test_param_count_matches_tree(self):
        cfg = llama.llama_tiny()
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        n = sum(x.size for x in jax.tree.leaves(params))
        assert n == cfg.param_count()

    def test_llama3_8b_param_count(self):
        assert llama.llama3_8b().param_count() == pytest.approx(8.03e9, rel=0.01)

    def test_param_specs_cover_tree(self):
        cfg = llama.llama_tiny()
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        specs = llama.param_specs(cfg)
        jax.tree.map(lambda p, s: None, params, specs)  # same structure

    def test_causal_lm_property(self):
        # changing token t must not affect logits before t
        cfg = llama.llama_tiny()
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0, 100)
        l1 = llama.forward(params, tokens, cfg)
        l2 = llama.forward(params, tokens.at[0, 8].set(101), cfg)
        np.testing.assert_allclose(l1[0, :8], l2[0, :8], atol=1e-5)
        assert not np.allclose(l1[0, 8], l2[0, 8])

    def test_sharded_matches_unsharded(self):
        cfg = llama.llama_tiny()
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, 100)
        ref = llama.forward(params, tokens, cfg)
        mesh = make_mesh(MeshConfig(dp=2, fsdp=2, tp=2, sp=1))
        sharded = llama.shard_params(params, cfg, mesh)
        out = jax.jit(lambda p, t: llama.forward(p, t, cfg, mesh))(sharded, tokens)
        np.testing.assert_allclose(out, ref, atol=1e-4)

    def test_ring_attention_model_matches(self):
        cfg = llama.llama_tiny()
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, 100)
        ref = llama.forward(params, tokens, cfg)
        mesh = make_mesh(MeshConfig(dp=1, fsdp=2, tp=1, sp=4))
        cfg_ring = dataclasses.replace(cfg, use_ring_attention=True)
        sharded = llama.shard_params(params, cfg_ring, mesh)
        out = jax.jit(lambda p, t: llama.forward(p, t, cfg_ring, mesh))(
            sharded, tokens
        )
        np.testing.assert_allclose(out, ref, atol=1e-3)

    def test_chunked_loss_matches_unchunked(self):
        cfg = llama.llama_tiny(max_seq=64, loss_chunk=16)
        cfg_full = dataclasses.replace(cfg, loss_chunk=0)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 65), 0, 512)
        batch = {"tokens": tokens}
        np.testing.assert_allclose(
            llama.loss_fn(params, batch, cfg),
            llama.loss_fn(params, batch, cfg_full),
            rtol=1e-5,
        )
        g1 = jax.grad(llama.loss_fn)(params, batch, cfg)
        g2 = jax.grad(llama.loss_fn)(params, batch, cfg_full)
        for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
            np.testing.assert_allclose(a, b, atol=2e-5)

    def test_chunked_loss_with_mask(self):
        cfg = llama.llama_tiny(max_seq=64, loss_chunk=16)
        cfg_full = dataclasses.replace(cfg, loss_chunk=0)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 65), 0, 512)
        mask = jax.random.uniform(jax.random.PRNGKey(2), (2, 65)) > 0.5
        batch = {"tokens": tokens, "loss_mask": mask}
        np.testing.assert_allclose(
            llama.loss_fn(params, batch, cfg),
            llama.loss_fn(params, batch, cfg_full),
            rtol=1e-5,
        )

    def test_loss_decreases(self):
        from torchx_tpu.examples.train_llama import train
        from torchx_tpu.parallel.mesh import MeshConfig as MC

        metrics = train(
            llama.llama_tiny(),
            MC(dp=1, fsdp=-1, tp=1, sp=1),
            batch=8,
            seq=32,
            steps=10,
            lr=1e-2,
            warmup=2,
        )
        assert metrics["loss"] < 5.5  # from ~6.2 (ln 512) at init

    def test_remat_policies_agree(self):
        # all remat policies compute identical grads (they only change
        # what is saved vs recomputed), including the named-attn policy
        import jax
        import jax.numpy as jnp

        tokens = jnp.arange(2 * 64, dtype=jnp.int32).reshape(2, 64) % 512
        grads = {}
        for policy in ["full", "dots", "dots_attn"]:
            cfg = llama.llama_tiny(remat_policy=policy)
            params = llama.init_params(cfg, jax.random.PRNGKey(0))

            def loss(p, cfg=cfg):
                return llama.forward(p, tokens, cfg).astype(jnp.float32).mean()

            grads[policy] = jax.grad(loss)(params)
        flat_a = jax.tree_util.tree_leaves(grads["full"])
        for other in ["dots", "dots_attn"]:
            flat_b = jax.tree_util.tree_leaves(grads[other])
            for a, b in zip(flat_a, flat_b):
                assert jnp.allclose(a, b, atol=2e-2), other

    def test_ring_attention_with_remat(self):
        # the 8B long-context path: remat + ring attention compose
        cfg = llama.llama_tiny(use_ring_attention=True, remat=True)
        mesh = make_mesh(MeshConfig(dp=1, fsdp=2, tp=1, sp=4))
        params = llama.shard_params(
            llama.init_params(cfg, jax.random.PRNGKey(0)), cfg, mesh
        )
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 65), 0, 100)
        loss, grads = jax.jit(
            lambda p, b: jax.value_and_grad(llama.loss_fn)(p, b, cfg, mesh)
        )(params, {"tokens": tokens})
        assert jnp.isfinite(loss)
        assert all(jnp.isfinite(g).all() for g in jax.tree.leaves(grads))

    def test_llama8b_shardings_trace(self):
        """AOT-validate the full-scale 8B shardings: abstract trace of the
        train step over a 4x2 mesh — no weights materialize."""
        from torchx_tpu.examples.train_llama import TrainState, make_optimizer

        import optax

        cfg = llama.llama3_8b(max_seq=256)
        mesh = make_mesh(MeshConfig(dp=1, fsdp=4, tp=2, sp=1))
        opt = make_optimizer()
        specs = llama.param_specs(cfg)
        from jax.sharding import NamedSharding

        param_shapes = jax.eval_shape(
            lambda k: llama.init_params(cfg, k), jax.random.PRNGKey(0)
        )
        param_abstract = jax.tree.map(
            lambda shp, spec: jax.ShapeDtypeStruct(
                shp.shape, shp.dtype, sharding=NamedSharding(mesh, spec)
            ),
            param_shapes,
            specs,
        )
        opt_abstract = jax.eval_shape(opt.init, param_abstract)
        state = TrainState(
            params=param_abstract,
            opt_state=opt_abstract,
            step=jax.ShapeDtypeStruct((), jnp.int32),
        )
        batch = {"tokens": jax.ShapeDtypeStruct((8, 257), jnp.int32)}

        def step(state, batch):
            loss, grads = jax.value_and_grad(llama.loss_fn)(
                state.params, batch, cfg, mesh
            )
            updates, opt_state = opt.update(grads, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            return TrainState(params, opt_state, state.step + 1), loss

        lowered = jax.jit(step).lower(state, batch)  # one trace, no compile
        assert lowered.out_info[1].shape == ()  # loss is a scalar

    def test_tied_embeddings(self):
        cfg = llama.llama_tiny(tie_embeddings=True)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        assert "lm_head" not in params
        logits = llama.forward(params, jnp.zeros((1, 8), jnp.int32), cfg)
        assert logits.shape[-1] == cfg.vocab_size


class TestGraftEntry:
    def test_entry_jits(self):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "graft_entry", "__graft_entry__.py"
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        fn, args = mod.entry()
        out = jax.jit(fn)(*args)
        assert out.shape[0] == 1 and out.ndim == 3

    def test_dryrun_multichip_8(self):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "graft_entry2", "__graft_entry__.py"
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        mod.dryrun_multichip(8)


class TestInterpretability:
    def test_forward_from_embeddings_matches_forward(self):
        import jax
        import jax.numpy as jnp

        cfg = llama.llama_tiny()
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jnp.array([[1, 2, 3, 4]], dtype=jnp.int32)
        direct = llama.forward(params, tokens, cfg)
        via_embeds = llama.forward_from_embeddings(
            params, params["embed"][tokens[0]][None], cfg
        )
        assert jnp.allclose(direct, via_embeds, atol=1e-5)

    def test_token_attributions_shapes_and_grads_flow(self):
        import jax
        import jax.numpy as jnp

        from torchx_tpu.examples.interpret_llama import token_attributions

        cfg = llama.llama_tiny()
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jnp.array([[5, 6, 7, 8, 9]], dtype=jnp.int32)
        sal, ig = token_attributions(params, tokens, cfg, steps=4)
        assert sal.shape == (5,) and ig.shape == (5,)
        # gradients actually flow: saliency is strictly positive somewhere
        assert float(jnp.max(sal)) > 0
        assert not jnp.isnan(ig).any()


class TestTrainStepTimeKnobs:
    """The --grad-bucket-mb / --kernels / launch-anchor trainer wiring."""

    def _train(self, **kw):
        from torchx_tpu.examples.train_llama import train
        from torchx_tpu.parallel.mesh import MeshConfig as MC

        return train(
            llama.llama_tiny(),
            MC(dp=1, fsdp=-1, tp=1, sp=1),
            batch=8,
            seq=32,
            steps=4,
            warmup=2,
            **kw,
        )

    def test_bucketed_loss_bitwise_equals_single_sync(self):
        ref = self._train(grad_bucket_mb=0)
        bucketed = self._train(grad_bucket_mb="auto")
        assert bucketed["grad_buckets"] >= 1
        assert bucketed["grad_bucket_mb"] > 0
        assert any(t["chosen"] for t in bucketed["grad_bucket_trials"])
        # barriers are value identities: losses agree to the last bit
        assert bucketed["loss"] == ref["loss"]

    def test_explicit_bucket_mb_plumbs_through(self):
        out = self._train(grad_bucket_mb="16")
        assert out["grad_bucket_mb"] == 16
        assert out["grad_bucket_trials"][0]["reason"] == "explicit --grad-bucket-mb"

    def test_launch_anchor_reanchors_first_step(self):
        # a later in-process train() re-anchored at its own call must
        # report seconds for ITS launch, not the age of the process
        # (the bench int8-leg drift this seam exists to fix)
        import time

        self._train()  # consume any first-train process-start anchoring
        t0 = time.monotonic()
        out = self._train(launch_anchor=t0)
        own = time.monotonic() - t0
        assert 0 < out["launch_to_first_step_s"] <= own
        process_age = time.monotonic() - 0  # sanity: anchor is not epoch
        assert out["launch_to_first_step_s"] < process_age

    def test_kernels_flag_reported(self):
        out = self._train(kernels="pallas")  # degrades to reference on CPU
        assert out["kernels"] == "reference"
