"""Elastic gang supervision: hang detection, mesh reshape, verified resume.

The ISSUE acceptance scenarios, all on CPU with no sleeps longer than the
monitor deadline:

(a) a hung replica (heartbeats stop while scheduler status stays RUNNING)
    is detected within the hang deadline, classified ``FailureClass.HANG``,
    killed, and resubmitted;
(b) a checkpoint saved on an 8-device mesh restores onto a 4-device mesh
    and training continues from the resumed step;
(c) a corrupt checkpoint step is quarantined on restore (content digest
    mismatch) and the run falls back to the previous verified step.

Plus unit coverage for :class:`GangMonitor` verdicts, liveness leases, the
jax-free mesh-shrink arithmetic, and the supervisor's reshape-on-resubmit
flow against a scripted scheduler.
"""

import json
import logging
import os
import random
import time
from typing import Mapping, Optional

import pytest

from torchx_tpu.parallel.mesh_config import (
    AXES,
    MeshConfig,
    mesh_sizes_spec,
    parse_mesh_spec,
    shrink_data_axes,
)
from torchx_tpu.runner.api import Runner
from torchx_tpu.runner.events import get_events_logger
from torchx_tpu.runner.events.api import TpxEvent
from torchx_tpu.schedulers.api import DescribeAppResponse, Scheduler
from torchx_tpu.settings import CHECKPOINT_MANIFEST, ENV_TPX_MESH
from torchx_tpu.specs.api import (
    AppDef,
    AppDryRunInfo,
    AppState,
    CfgVal,
    FailureClass,
    Role,
    runopts,
)
from torchx_tpu.supervisor import Supervisor, SupervisorPolicy
from torchx_tpu.supervisor.gang import (
    GangMonitor,
    GangState,
    GangVerdict,
    read_leases,
    renew_lease,
)

# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

#: fixed "now" for deterministic monitor verdicts (epoch seconds).
NOW = 1_700_000_000.0


def heartbeat(path, replica, ts, step=-1, name="step.window"):
    """Append one heartbeat span line the way train_llama emits them."""
    rec = {
        "kind": "span",
        "name": name,
        "start_epoch_usec": int(ts * 1e6),
        "attrs": {"replica": replica, "step": step},
    }
    with open(path, "a") as f:
        f.write(json.dumps(rec) + "\n")


def monitor(trace_file, replicas=2, deadline=5.0, clock=None, **kw):
    return GangMonitor(
        expected_replicas=replicas,
        hang_deadline_s=deadline,
        trace_file=str(trace_file),
        clock=clock or (lambda: NOW),
        **kw,
    )


class ScriptedScheduler(Scheduler[dict]):
    """Each ``schedule()`` consumes the next scripted terminal outcome;
    ``describe()`` then reports that attempt as immediately terminal."""

    def __init__(self, session_name: str, script=None, **kwargs):
        super().__init__("scripted", session_name)
        self.script = list(script or [])
        self.apps: dict[str, tuple[AppState, Optional[FailureClass]]] = {}
        self.submitted_envs: list[dict[str, str]] = []
        self.cancelled: list[str] = []
        self._counter = 0

    def run_opts(self) -> runopts:
        return runopts()

    def _submit_dryrun(self, app: AppDef, cfg: Mapping[str, CfgVal]):
        return AppDryRunInfo({"app": app})

    def schedule(self, dryrun_info) -> str:
        self._counter += 1
        app_id = f"job_{self._counter}"
        outcome = (
            self.script.pop(0) if self.script else (AppState.SUCCEEDED, None)
        )
        self.apps[app_id] = outcome
        self.submitted_envs.append(dict(dryrun_info._app.roles[0].env))
        return app_id

    def describe(self, app_id: str) -> Optional[DescribeAppResponse]:
        if app_id not in self.apps:
            return None
        state, fclass = self.apps[app_id]
        return DescribeAppResponse(
            app_id=app_id, state=state, failure_class=fclass
        )

    def _cancel_existing(self, app_id: str) -> None:
        self.apps[app_id] = (AppState.CANCELLED, None)
        self.cancelled.append(app_id)


class WarmupScheduler(ScriptedScheduler):
    """Reports RUNNING for the first ``warmup_polls`` describes of each
    app before revealing its scripted outcome — models the compile/warmup
    window between submission and the first heartbeat, during which gang
    checks already run."""

    def __init__(self, session_name, script=None, warmup_polls=2, **kwargs):
        super().__init__(session_name, script=script, **kwargs)
        self.warmup_polls = warmup_polls
        self._polls: dict[str, int] = {}

    def describe(self, app_id: str) -> Optional[DescribeAppResponse]:
        resp = super().describe(app_id)
        if resp is None:
            return resp
        n = self._polls.get(app_id, 0)
        self._polls[app_id] = n + 1
        if n < self.warmup_polls and app_id not in self.cancelled:
            return DescribeAppResponse(app_id=app_id, state=AppState.RUNNING)
        return resp


def make_warmup_runner(script, warmup_polls=2):
    sched = WarmupScheduler("gang", script=script, warmup_polls=warmup_polls)
    runner = Runner("gang", {"scripted": lambda session_name, **kw: sched})
    return runner, sched


RUNNING = (AppState.RUNNING, None)
PREEMPT = (AppState.PREEMPTED, FailureClass.PREEMPTION)
APP_FAIL = (AppState.FAILED, FailureClass.APP)
OK = (AppState.SUCCEEDED, None)


class _CaptureEvents(logging.Handler):
    def __init__(self):
        super().__init__()
        self.events: list[TpxEvent] = []

    def emit(self, record: logging.LogRecord) -> None:
        msg = record.getMessage()
        if json.loads(msg).get("kind") == "span":
            return
        self.events.append(TpxEvent.deserialize(msg))


@pytest.fixture
def capture_events():
    handler = _CaptureEvents()
    logger = get_events_logger()
    logger.addHandler(handler)
    yield handler.events
    logger.removeHandler(handler)


def make_runner(script):
    sched = ScriptedScheduler("gang", script=script)
    runner = Runner("gang", {"scripted": lambda session_name, **kw: sched})
    return runner, sched


def dryrun(runner):
    app = AppDef(
        name="train",
        roles=[Role(name="trainer", image="i", entrypoint="python")],
    )
    return runner.dryrun(app, "scripted")


def gang_policy(**kwargs) -> SupervisorPolicy:
    defaults = dict(
        backoff_seconds=0.01,
        jitter=0.0,
        poll_interval=0.01,
    )
    defaults.update(kwargs)
    return SupervisorPolicy(**defaults)


def run_supervised(script, policy):
    runner, sched = make_runner(script)
    sleeps: list[float] = []
    with runner:
        result = Supervisor(
            runner,
            dryrun(runner),
            policy,
            sleep=sleeps.append,
            rng=random.Random(0),
        ).run()
    return result, sched, sleeps


# ---------------------------------------------------------------------------
# GangMonitor verdicts
# ---------------------------------------------------------------------------


class TestGangMonitor:
    def test_waiting_before_any_evidence(self, tmp_path):
        m = monitor(tmp_path / "trace.jsonl")  # file does not exist yet
        v = m.check()
        assert v.state == GangState.WAITING
        assert not v.unhealthy
        assert v.survivors == 0

    def test_healthy_with_fresh_heartbeats(self, tmp_path):
        tf = tmp_path / "trace.jsonl"
        heartbeat(tf, 0, NOW - 1.0, step=10)
        heartbeat(tf, 1, NOW - 2.0, step=10, name="job.first_step")
        v = monitor(tf).check()
        assert v.state == GangState.HEALTHY
        assert v.survivors == 2 and v.live == (0, 1) and v.lost == ()

    def test_hang_when_all_replicas_stale(self, tmp_path):
        tf = tmp_path / "trace.jsonl"
        heartbeat(tf, 0, NOW - 60.0)
        heartbeat(tf, 1, NOW - 45.0)
        v = monitor(tf).check()
        assert v.state == GangState.HANG
        assert v.unhealthy
        assert v.survivors == 0 and v.lost == (0, 1)
        assert "stale" in v.detail

    def test_partial_loss_counts_survivors(self, tmp_path):
        tf = tmp_path / "trace.jsonl"
        heartbeat(tf, 0, NOW - 1.0, step=20)
        heartbeat(tf, 1, NOW - 60.0, step=18)
        v = monitor(tf).check()
        assert v.state == GangState.PARTIAL_LOSS
        assert v.unhealthy
        assert v.live == (0,) and v.lost == (1,) and v.survivors == 1

    def test_never_seen_replica_grace_then_lost(self, tmp_path):
        """Replica 1 never produced evidence. Ordinary startup skew puts
        replicas' first flushes seconds apart, so right after arming the
        silent replica gets the hang deadline as grace (WAITING, not a
        gang-killing PARTIAL_LOSS); once the deadline passes since arming
        it counts as lost."""
        tf = tmp_path / "trace.jsonl"
        heartbeat(tf, 0, NOW - 1.0)
        clock = {"now": NOW}
        m = monitor(tf, clock=lambda: clock["now"])  # deadline 5.0
        v = m.check()
        assert v.state == GangState.WAITING
        assert not v.unhealthy
        assert v.live == (0,)
        assert "waiting for first evidence" in v.detail
        # replica 0 stays fresh; replica 1 still silent past the deadline
        clock["now"] = NOW + 6.0
        heartbeat(tf, 0, NOW + 5.5)
        v = m.check()
        assert v.state == GangState.PARTIAL_LOSS
        assert v.unhealthy
        assert v.lost == (1,) and v.live == (0,)

    def test_stale_evidence_before_floor_is_ignored(self, tmp_path):
        """A resubmitted attempt's monitor gets an evidence floor: the
        dead predecessor's heartbeats and lease files must read as "no
        evidence yet" (WAITING), not as an instant all-stale HANG while
        the new gang is still compiling."""
        tf = tmp_path / "trace.jsonl"
        heartbeat(tf, 0, NOW - 60.0, step=12)
        heartbeat(tf, 1, NOW - 45.0, step=12)
        # a leftover lease file from the dead attempt (backdate the stamp:
        # renew_lease always writes the real wall clock)
        path = renew_lease(0, step=12, session="gang-floor-test")
        rec = json.loads(open(path).read())
        rec["epoch_usec"] = int((NOW - 40.0) * 1e6)
        with open(path, "w") as f:
            f.write(json.dumps(rec))
        m = monitor(
            tf,
            session="gang-floor-test",
            ignore_evidence_before=NOW - 30.0,
        )
        v = m.check()
        assert v.state == GangState.WAITING
        assert not v.unhealthy
        assert m.replicas == {}
        # evidence stamped after the floor arms the monitor normally
        heartbeat(tf, 0, NOW - 1.0, step=13)
        heartbeat(tf, 1, NOW - 1.0, step=13)
        assert m.check().state == GangState.HEALTHY

    def test_straggler_is_warn_only(self, tmp_path):
        tf = tmp_path / "trace.jsonl"
        heartbeat(tf, 0, NOW - 1.0, step=50)
        heartbeat(tf, 1, NOW - 1.0, step=40)
        v = monitor(tf, straggler_step_lag=5).check()
        assert v.state == GangState.STRAGGLER
        assert not v.unhealthy
        assert "spread" in v.detail
        # within the lag: healthy
        heartbeat(tf, 1, NOW - 0.5, step=46)
        assert monitor(tf, straggler_step_lag=5).check().state == GangState.HEALTHY

    def test_lease_keeps_replica_alive_when_trace_stalls(self, tmp_path):
        """A renewed lease is proof of life even with stale heartbeats —
        the sidecar path for trainers that cannot emit spans."""
        tf = tmp_path / "trace.jsonl"
        now = time.time()
        heartbeat(tf, 0, now - 3600)
        renew_lease(0, step=7, session="gang-lease-test")
        m = monitor(
            tf,
            replicas=1,
            deadline=0.5,
            clock=time.time,
            lease_ttl_s=60.0,
            session="gang-lease-test",
        )
        v = m.check()
        assert v.state == GangState.HEALTHY
        assert read_leases("gang-lease-test")[0]["step"] == 7

    def test_torn_final_line_held_back_then_reread(self, tmp_path):
        tf = tmp_path / "trace.jsonl"
        heartbeat(tf, 0, NOW - 1.0)
        # writer dies (or is mid-write) after half a line
        partial = json.dumps(
            {
                "kind": "span",
                "name": "step.window",
                "start_epoch_usec": int((NOW - 1.0) * 1e6),
                "attrs": {"replica": 1},
            }
        )
        with open(tf, "a") as f:
            f.write(partial[: len(partial) // 2])
        m = monitor(tf)
        m.observe()
        assert set(m.replicas) == {0}
        # the writer finishes the line; the next observe picks it up
        with open(tf, "a") as f:
            f.write(partial[len(partial) // 2 :] + "\n")
        m.observe()
        assert set(m.replicas) == {0, 1}
        assert m.check().state == GangState.HEALTHY

    def test_validation(self, tmp_path):
        with pytest.raises(ValueError):
            GangMonitor(expected_replicas=0, hang_deadline_s=1.0)
        with pytest.raises(ValueError):
            GangMonitor(expected_replicas=1, hang_deadline_s=0.0)


# ---------------------------------------------------------------------------
# mesh-shrink arithmetic (jax-free)
# ---------------------------------------------------------------------------


class TestShrinkDataAxes:
    def _sizes(self, **kw):
        base = {a: 1 for a in AXES}
        base.update(kw)
        return base

    def test_binary_step_halves_dp_first(self):
        assert shrink_data_axes(self._sizes(dp=4, fsdp=2))["dp"] == 2
        shrunk = shrink_data_axes(self._sizes(fsdp=8))
        assert shrunk["fsdp"] == 4 and shrunk["dp"] == 1

    def test_target_preserves_fsdp_extent_when_divisible(self):
        """8 -> 4 surviving devices with fsdp=4: parameter shards keep
        their size, the loss folds into dp."""
        shrunk = shrink_data_axes(self._sizes(dp=2, fsdp=4), 4)
        assert shrunk == self._sizes(dp=1, fsdp=4)

    def test_target_collapses_into_fsdp_otherwise(self):
        shrunk = shrink_data_axes(self._sizes(fsdp=8), 4)
        assert shrunk == self._sizes(dp=1, fsdp=4)
        shrunk = shrink_data_axes(self._sizes(dp=1, fsdp=8), 2)
        assert shrunk == self._sizes(dp=1, fsdp=2)

    def test_model_axes_never_shrink(self):
        sizes = self._sizes(tp=2, fsdp=4)
        shrunk = shrink_data_axes(sizes, 4)  # 8 devices -> 4
        assert shrunk["tp"] == 2 and shrunk["fsdp"] == 2
        with pytest.raises(ValueError, match="model"):
            shrink_data_axes(sizes, 1)  # cannot fit tp=2 in 1 device

    def test_unshrinkable_and_non_shrink_targets_raise(self):
        with pytest.raises(ValueError, match="no data parallelism"):
            shrink_data_axes(self._sizes())
        with pytest.raises(ValueError, match="not a shrink"):
            shrink_data_axes(self._sizes(fsdp=4), 8)

    def test_spec_round_trip(self):
        sizes = MeshConfig(fsdp=-1).resolve(8)
        spec = mesh_sizes_spec(sizes)
        assert spec == "pp=1,dp=1,fsdp=8,ep=1,tp=1,sp=1"
        assert parse_mesh_spec(spec).resolve(8) == sizes
        with pytest.raises(ValueError, match="unknown mesh axis"):
            parse_mesh_spec("dpp=2")


# ---------------------------------------------------------------------------
# acceptance (a): hang detected -> killed -> classified HANG -> resubmitted
# ---------------------------------------------------------------------------


class TestHangDetection:
    def test_hung_gang_killed_and_resubmitted(self, tmp_path, capture_events):
        """Scheduler status stays RUNNING while heartbeats are long stale:
        the monitor must flag HANG within the deadline, the supervisor
        kills the attempt, classifies it HANG, and the resubmission
        succeeds — all in well under a second of wall time."""
        tf = tmp_path / "trace.jsonl"
        heartbeat(tf, 0, time.time() - 60.0, step=12)

        runner, sched = make_runner([RUNNING, OK])
        deadline = 1.0
        policy = gang_policy(
            hang_deadline_seconds=deadline,
            gang_check_interval=0.05,
            poll_interval=0.05,
            max_hang_retries=1,
        )
        with runner:
            sup = Supervisor(
                runner,
                dryrun(runner),
                policy,
                sleep=time.sleep,  # Runner.wait timeouts use real time
                rng=random.Random(0),
            )
            sup.monitor_factory = lambda **kw: GangMonitor(
                trace_file=str(tf), **kw
            )
            t0 = time.monotonic()
            result = sup.run()
            elapsed = time.monotonic() - t0

        assert result.succeeded
        assert result.attempts == 2
        assert result.retries[FailureClass.HANG] == 1
        assert result.budget_exhausted is None
        # the supervisor itself killed the wedged attempt
        assert sched.cancelled == ["job_1"]
        # detected within the configured deadline (not via a long sleep)
        assert elapsed < deadline
        sup_events = [e for e in capture_events if e.api == "supervise"]
        by_transition = {
            e.app_metadata["transition"]: e.app_metadata for e in sup_events
        }
        assert by_transition["gang_hang"]["survivors"] == 0
        assert by_transition["gang_hang"]["expected"] == 1
        assert by_transition["gang_hang"]["lost"] == [0]
        assert by_transition["resubmitting"]["failure_class"] == "HANG"

    def test_hang_budget_exhaustion(self, tmp_path):
        tf = tmp_path / "trace.jsonl"
        heartbeat(tf, 0, time.time() - 60.0)
        runner, sched = make_runner([RUNNING, RUNNING])
        policy = gang_policy(
            hang_deadline_seconds=0.5,
            gang_check_interval=0.05,
            poll_interval=0.05,
            max_hang_retries=1,
        )

        def factory(**kw):
            # every attempt hangs for real: the resubmitted gang emits one
            # heartbeat (past the attempt's evidence floor) and then
            # wedges, going stale within the deadline
            if kw.get("ignore_evidence_before"):
                heartbeat(tf, 0, time.time())
            return GangMonitor(trace_file=str(tf), **kw)

        with runner:
            sup = Supervisor(
                runner, dryrun(runner), policy,
                sleep=time.sleep, rng=random.Random(0),
            )
            sup.monitor_factory = factory
            result = sup.run()
        assert not result.succeeded
        assert result.budget_exhausted == FailureClass.HANG
        assert result.retries[FailureClass.HANG] == 1
        assert sched.cancelled == ["job_1", "job_2"]
        assert result.status.failure_class == FailureClass.HANG
        assert "gang HANG" in result.status.msg

    def test_resubmitted_attempt_survives_stale_evidence(self, tmp_path):
        """Regression: the resubmitted attempt's fresh monitor tails the
        SAME session trace and lease files. Attempt 1's stale heartbeats
        must not arm attempt 2's monitor (instant HANG during warmup,
        before attempt 2's first heartbeat) — the evidence floor set at
        resubmission filters them, so attempt 2 warms up under WAITING
        and runs to completion."""
        tf = tmp_path / "trace.jsonl"
        heartbeat(tf, 0, time.time() - 60.0, step=12)

        # attempt 1 hangs; attempt 2 spends several polls "warming up"
        # (RUNNING, no heartbeat yet) before succeeding — exactly the
        # window where stale evidence used to kill it
        runner, sched = make_warmup_runner([RUNNING, OK], warmup_polls=3)
        policy = gang_policy(
            hang_deadline_seconds=1.0,
            gang_check_interval=0.05,
            poll_interval=0.05,
            max_hang_retries=1,
        )
        with runner:
            sup = Supervisor(
                runner, dryrun(runner), policy,
                sleep=time.sleep, rng=random.Random(0),
            )
            sup.monitor_factory = lambda **kw: GangMonitor(
                trace_file=str(tf), **kw
            )
            result = sup.run()
        assert result.succeeded
        assert result.attempts == 2
        assert result.budget_exhausted is None
        # only the genuinely hung first attempt was killed
        assert sched.cancelled == ["job_1"]

    def test_healthy_gang_runs_to_completion(self, tmp_path):
        """Fresh heartbeats must never trip the monitor: an attempt that
        finishes normally under gang watch stays a single attempt."""
        tf = tmp_path / "trace.jsonl"
        heartbeat(tf, 0, time.time(), step=1)
        runner, sched = make_runner([OK])
        policy = gang_policy(
            hang_deadline_seconds=30.0,
            gang_check_interval=0.05,
            poll_interval=0.05,
        )
        with runner:
            sup = Supervisor(
                runner, dryrun(runner), policy,
                sleep=time.sleep, rng=random.Random(0),
            )
            sup.monitor_factory = lambda **kw: GangMonitor(
                trace_file=str(tf), **kw
            )
            result = sup.run()
        assert result.succeeded
        assert result.attempts == 1
        assert sched.cancelled == []


# ---------------------------------------------------------------------------
# elastic reshape on resubmit (scripted scheduler)
# ---------------------------------------------------------------------------


class TestElasticReshape:
    def test_preemption_resubmits_on_shrunken_mesh(self, tmp_path):
        result, sched, _ = run_supervised(
            [PREEMPT, OK],
            gang_policy(
                max_preemptions=2,
                elastic_reshape=True,
                mesh="fsdp=-1",
                devices_per_replica=8,
            ),
        )
        assert result.succeeded and result.attempts == 2
        # launch attempt runs the flag-given mesh; the resubmit overrides
        assert ENV_TPX_MESH not in sched.submitted_envs[0]
        assert (
            sched.submitted_envs[1][ENV_TPX_MESH]
            == "pp=1,dp=1,fsdp=4,ep=1,tp=1,sp=1"
        )

    def test_repeated_preemptions_keep_degrading(self):
        result, sched, _ = run_supervised(
            [PREEMPT, PREEMPT, OK],
            gang_policy(
                max_preemptions=3,
                elastic_reshape=True,
                mesh="fsdp=-1",
                devices_per_replica=8,
            ),
        )
        assert result.succeeded and result.attempts == 3
        assert sched.submitted_envs[1][ENV_TPX_MESH].endswith("fsdp=4,ep=1,tp=1,sp=1")
        assert sched.submitted_envs[2][ENV_TPX_MESH].endswith("fsdp=2,ep=1,tp=1,sp=1")

    def test_unshrinkable_mesh_resubmits_at_same_shape(self):
        result, sched, _ = run_supervised(
            [PREEMPT, OK],
            gang_policy(
                max_preemptions=2,
                elastic_reshape=True,
                mesh="fsdp=-1",
                devices_per_replica=1,
            ),
        )
        assert result.succeeded
        assert (
            sched.submitted_envs[1][ENV_TPX_MESH]
            == "pp=1,dp=1,fsdp=1,ep=1,tp=1,sp=1"
        )

    def test_app_failures_never_reshape(self):
        result, sched, _ = run_supervised(
            [APP_FAIL, OK],
            gang_policy(
                max_app_retries=1,
                elastic_reshape=True,
                mesh="fsdp=-1",
                devices_per_replica=8,
            ),
        )
        assert result.succeeded
        assert ENV_TPX_MESH not in sched.submitted_envs[1]

    def test_gang_verdict_targets_surviving_capacity(self):
        """With a verdict the shrink is a refit to survivors x devices,
        not a blind halving."""
        runner, _ = make_runner([])
        with runner:
            sup = Supervisor(
                runner,
                dryrun(runner),
                gang_policy(
                    elastic_reshape=True, mesh="fsdp=8", devices_per_replica=2
                ),
                sleep=lambda s: None,
            )
            sup._last_verdict = GangVerdict(
                state=GangState.PARTIAL_LOSS,
                detail="3 lost",
                expected=4,
                live=(0,),
                lost=(1, 2, 3),
            )
            sup._maybe_reshape(FailureClass.HANG)
        assert sup._mesh_spec == "pp=1,dp=1,fsdp=2,ep=1,tp=1,sp=1"
        # the verdict is consumed: a later plain preemption halves instead
        assert sup._last_verdict is None

    def test_full_healthy_gang_grows_back_to_launch_mesh(self):
        """Blind preemption halving must not ratchet a healthy job toward
        dp=1: once the monitor saw the full gang live on the degraded
        shape, a verdict-less preemption restores the launch mesh (a
        reschedule is a fresh allocation at the requested size)."""
        runner, _ = make_runner([])
        with runner:
            sup = Supervisor(
                runner,
                dryrun(runner),
                gang_policy(
                    elastic_reshape=True, mesh="fsdp=-1", devices_per_replica=8
                ),
                sleep=lambda s: None,
            )
            degraded = parse_mesh_spec("dp=1,fsdp=4,pp=1,ep=1,tp=1,sp=1")
            sup._current_mesh = {a: getattr(degraded, a) for a in AXES}
            sup._mesh_spec = mesh_sizes_spec(sup._current_mesh)
            sup._gang_was_full = True
            sup._maybe_reshape(FailureClass.PREEMPTION)
        assert sup._mesh_spec == "pp=1,dp=1,fsdp=8,ep=1,tp=1,sp=1"

    def test_preemption_after_healthy_gang_keeps_launch_mesh(self, tmp_path):
        """At the launch shape with a demonstrably whole gang, a plain
        preemption resubmits unchanged — no TPX_MESH override, no blind
        shrink (end to end: healthy verdict observed by the monitor during
        attempt 1, preemption, resubmit)."""
        tf = tmp_path / "trace.jsonl"
        heartbeat(tf, 0, time.time(), step=5)
        runner, sched = make_warmup_runner([PREEMPT, OK], warmup_polls=2)
        policy = gang_policy(
            max_preemptions=2,
            elastic_reshape=True,
            mesh="fsdp=-1",
            devices_per_replica=8,
            hang_deadline_seconds=30.0,
            gang_check_interval=0.05,
            poll_interval=0.05,
        )
        with runner:
            sup = Supervisor(
                runner, dryrun(runner), policy,
                sleep=time.sleep, rng=random.Random(0),
            )
            sup.monitor_factory = lambda **kw: GangMonitor(
                trace_file=str(tf), **kw
            )
            result = sup.run()
        assert result.succeeded and result.attempts == 2
        assert sched.cancelled == []
        assert ENV_TPX_MESH not in sched.submitted_envs[1]

    def test_elastic_reshape_requires_mesh(self):
        with pytest.raises(ValueError, match="mesh"):
            SupervisorPolicy(elastic_reshape=True)

    def test_resume_replays_reshaped_mesh(self):
        """A supervise client that crashes after a reshape must resume onto
        the degraded shape, not the launch one (replayed from the attempt
        ledger's ``submitted`` entries)."""
        runner, sched = make_runner([PREEMPT, OK])
        policy = gang_policy(
            max_preemptions=2,
            elastic_reshape=True,
            mesh="fsdp=-1",
            devices_per_replica=8,
        )
        with runner:
            result = Supervisor(
                runner,
                dryrun(runner),
                policy,
                sleep=lambda s: None,
                rng=random.Random(0),
                session="gang-resume",
            ).run()
            assert result.succeeded
            sup2 = Supervisor.resume(runner, "gang-resume")
        assert sup2._mesh_spec == "pp=1,dp=1,fsdp=4,ep=1,tp=1,sp=1"
        assert sup2._current_mesh["fsdp"] == 4
        assert sup2._policy.elastic_reshape  # policy round-tripped via meta
        # the reattached monitor must not ingest earlier attempts' stale
        # evidence: the floor is the reattached attempt's submission time
        assert sup2._evidence_floor > 0


# ---------------------------------------------------------------------------
# in-job liveness lease helper (train_llama)
# ---------------------------------------------------------------------------


class TestLivenessLeaseHelper:
    def test_first_step_lease_written_when_step_unknown(self):
        """Regression: ``_renew_liveness_lease(None)`` used to die on
        ``int(None)`` inside its broad except — silently skipping the
        first-step lease exactly when lease evidence matters most (before
        ``step.window`` heartbeats start). None must degrade to the
        'step unknown' sentinel, not to no lease at all."""
        from torchx_tpu.examples.train_llama import _renew_liveness_lease

        _renew_liveness_lease(None)
        leases = read_leases()
        assert leases, "lease must be written even with no step known"
        assert all(rec["step"] == -1 for rec in leases.values())


# ---------------------------------------------------------------------------
# acceptance (b): 8-device save -> 4-device restore
# ---------------------------------------------------------------------------


class TestCrossMeshRestore:
    def test_8_device_save_restores_onto_4_device_mesh(self, tmp_path):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from torchx_tpu.parallel.checkpoint import Checkpointer
        from torchx_tpu.parallel.mesh import make_mesh

        devs = jax.devices()
        assert len(devs) == 8, "conftest guarantees 8 virtual CPU devices"
        mesh8 = make_mesh(MeshConfig(fsdp=-1), devices=devs)
        w = jax.device_put(
            jnp.arange(64.0).reshape(8, 8), NamedSharding(mesh8, P("fsdp"))
        )
        ckpt = Checkpointer(str(tmp_path))
        try:
            assert ckpt.save(3, {"w": w, "step": jnp.int32(3)}, force=True)
            ckpt.wait()
        finally:
            ckpt.close()

        # the degraded shape the supervisor would compute for 8 -> 4
        shrunk = shrink_data_axes(MeshConfig(fsdp=-1).resolve(8), 4)
        mesh4 = make_mesh(
            parse_mesh_spec(mesh_sizes_spec(shrunk)), devices=devs[:4]
        )
        target = {
            "w": jax.ShapeDtypeStruct(
                (8, 8), jnp.float32, sharding=NamedSharding(mesh4, P("fsdp"))
            ),
            "step": jax.ShapeDtypeStruct(
                (), jnp.int32, sharding=NamedSharding(mesh4, P())
            ),
        }
        ckpt2 = Checkpointer(str(tmp_path))
        try:
            step, restored = ckpt2.restore_latest(target)
        finally:
            ckpt2.close()
        assert step == 3
        np.testing.assert_array_equal(
            np.asarray(restored["w"]), np.arange(64.0).reshape(8, 8)
        )
        # the state now lives on the 4-device mesh...
        assert set(restored["w"].sharding.mesh.devices.flat) == set(devs[:4])
        # ...and training continues: a jitted update step runs on it
        stepped = jax.jit(lambda s: {**s, "w": s["w"] * 0.5, "step": s["step"] + 1})(
            restored
        )
        assert int(stepped["step"]) == 4
        assert float(stepped["w"][0, 2]) == 1.0


# ---------------------------------------------------------------------------
# acceptance (c): digest-verified restore quarantines corrupt steps
# ---------------------------------------------------------------------------


class TestDigestVerification:
    def test_corrupt_step_quarantined_and_fallback(self, tmp_path):
        import jax.numpy as jnp

        from torchx_tpu.parallel.checkpoint import Checkpointer

        ckpt = Checkpointer(str(tmp_path), async_save=False)
        ckpt.save(1, {"w": jnp.full(4, 1.0)})
        ckpt.save(2, {"w": jnp.full(4, 2.0)})
        ckpt.wait()
        ckpt.close()
        manifest = json.loads((tmp_path / CHECKPOINT_MANIFEST).read_text())
        assert manifest["latest_step"] == 2
        assert set(manifest["steps"]) == {"1", "2"}

        # silent corruption: APPEND junk — the payload may still
        # deserialize without an exception, so only the digest catches it
        step2 = tmp_path / "2"
        victim = (
            next(p for p in sorted(step2.rglob("*")) if p.is_file())
            if step2.is_dir()
            else tmp_path / "step_2.pkl"
        )
        victim.write_bytes(victim.read_bytes() + b"\x00 corrupted")

        ckpt2 = Checkpointer(str(tmp_path))
        try:
            assert ckpt2.verify_step(2) is False
            assert ckpt2.verify_step(1) is True
            step, restored = ckpt2.restore_latest({"w": jnp.zeros(4)})
            assert step == 1
            assert float(restored["w"][0]) == 1.0
            # quarantined aside as evidence, never deleted
            assert any(".corrupt" in p.name for p in tmp_path.iterdir())
            # manifest repaired: the client-side supervisor must not inject
            # the quarantined step as the next TPX_RESUME_STEP
            manifest = json.loads((tmp_path / CHECKPOINT_MANIFEST).read_text())
            assert manifest["latest_step"] == 1
            assert "2" not in manifest["steps"]
        finally:
            ckpt2.close()

    def test_undigested_steps_restore_as_before(self, tmp_path):
        """Checkpoints from before the digest table (manifest has no steps
        entry) must restore unverified rather than be treated as corrupt."""
        import jax.numpy as jnp

        from torchx_tpu.parallel.checkpoint import Checkpointer

        ckpt = Checkpointer(str(tmp_path), async_save=False)
        ckpt.save(5, {"w": jnp.full(4, 5.0)})
        ckpt.wait()
        ckpt.close()
        # simulate a pre-digest manifest
        (tmp_path / CHECKPOINT_MANIFEST).write_text(
            json.dumps({"latest_step": 5})
        )
        ckpt2 = Checkpointer(str(tmp_path))
        try:
            assert ckpt2.verify_step(5) is None
            step, restored = ckpt2.restore_latest({"w": jnp.zeros(4)})
        finally:
            ckpt2.close()
        assert step == 5
        assert float(restored["w"][0]) == 5.0
