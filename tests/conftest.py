"""Test configuration: force CPU JAX with 8 virtual devices.

Mirrors the reference's distributed-without-a-cluster strategy
(torchx/test/fixtures.py:253-305) using XLA's host-platform device-count
flag so mesh/sharding tests run anywhere — including sandboxes whose
sitecustomize force-registers a vendor TPU platform (hence the explicit
jax.config.update, which wins over site hooks as long as no backend has
initialized yet).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("TPX_EVENT_DESTINATION", "null")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _isolated_registries(tmp_path, monkeypatch):
    """Keep per-user registry files (~/.tpx_local_apps, ~/.tpxslurmjobdirs),
    supervisor ledgers, and the obs trace/metrics sinks out of the real
    home during tests. Control-plane breakers are process-global state and
    must not leak trips between tests."""
    monkeypatch.setenv("TPX_OBS_DIR", str(tmp_path / "obs"))
    monkeypatch.setenv("TPX_SUPERVISOR_DIR", str(tmp_path / "supervisor"))
    from torchx_tpu.resilience import call as resilience_call
    from torchx_tpu.resilience import faults as resilience_faults

    resilience_call.reset_breakers()
    resilience_faults.reset()
    monkeypatch.setattr(
        "torchx_tpu.schedulers.local_scheduler._registry_path",
        lambda: str(tmp_path / "tpx_local_apps"),
        raising=False,
    )
    monkeypatch.setattr(
        "torchx_tpu.schedulers.slurm_scheduler._registry_path",
        lambda: str(tmp_path / "tpx_slurm_dirs"),
        raising=False,
    )
