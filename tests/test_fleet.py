"""Fleet-scheduler tests: the modeled fleet + gang requests, queue
ordering (class / fair share / FIFO) and quotas, the durable decision
journal, topology-aware placement with the deep-preflight HBM oracle,
the preemption market's shrink/preempt planning, the FleetScheduler
facade (shrink -> grow-back through the attempt ledger, rehydration),
the TPX602 analyze rule, and the daemon e2e paths (fleet submits on the
real LocalScheduler, queue ordering over HTTP, the legacy 429 contract,
restart rehydration)."""

import json
import time
import types
import urllib.error
import urllib.request

import pytest

from torchx_tpu.analyze import Severity, analyze
from torchx_tpu.control.client import ControlClient, ControlClientError
from torchx_tpu.control.daemon import ControlDaemon
from torchx_tpu.fleet import (
    FleetJournal,
    FleetModel,
    FleetQueue,
    FleetScheduler,
    GangRequest,
    PlacementDecision,
    Preempt,
    Shrink,
    SlicePool,
    Victim,
    over_quota,
    parse_quotas,
    plan_market,
    plan_placement,
    priority_index,
)
from torchx_tpu.runner.api import get_runner
from torchx_tpu.specs.api import AppDef, Role, TpuSlice
from torchx_tpu.specs.serialize import appdef_to_dict
from torchx_tpu.supervisor.policy import SupervisorPolicy


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


class FakeExec:
    """A FleetExecutor double: mints handles, records every call."""

    def __init__(self) -> None:
        self.n = 0
        self.calls: list = []
        self.fail_next = False

    def schedule(self, job, mesh_spec):
        if self.fail_next:
            self.fail_next = False
            raise RuntimeError("backend said no")
        self.n += 1
        self.calls.append((job.req.job, job.cur_replicas, mesh_spec))
        return f"local://fake/app-{self.n}"

    def cancel(self, handle):
        self.calls.append(("cancel", handle))


def terminal_event(app_id: str, state: str = "SUCCEEDED"):
    return types.SimpleNamespace(
        scheduler="local",
        app_id=app_id,
        terminal=True,
        state=types.SimpleNamespace(name=state),
    )


def make_fs(tmp_path, spec: str, quotas=None) -> tuple:
    clock = [0.0]
    fs = FleetScheduler(
        FleetModel.from_spec(spec),
        state_dir=str(tmp_path),
        quotas=quotas,
        clock=lambda: clock[0],
    )
    ex = FakeExec()
    fs.bind(ex)
    return fs, ex, clock


def gang(job="", tenant="t", klass="batch", replicas=1, chips=1, **kw):
    return GangRequest(
        job=job,
        tenant=tenant,
        klass=klass,
        replicas=replicas,
        chips_per_replica=chips,
        **kw,
    )


def llama_role() -> Role:
    from torchx_tpu.components import dist

    app = dist.spmd(
        "--config",
        "llama3_8b",
        "--mesh",
        "fsdp=-1",
        m="my.custom_trainer",
        j="1x8",
    )
    return app.roles[0]


# ---------------------------------------------------------------------------
# the model
# ---------------------------------------------------------------------------


class TestFleetModel:
    def test_from_spec(self):
        m = FleetModel.from_spec("default:v5e-4x8,big:v5p-8x2")
        assert m.total_chips == 4 * 8 + 8 * 2
        assert len(m.units()) == 10
        assert m.unit("big/1").shape.accelerator == "v5p"

    def test_bare_spec_gets_default_pool_name(self):
        m = FleetModel.from_spec("v5e-4x2")
        assert [p.name for p in m.pools] == ["default"]

    def test_bad_spec_raises(self):
        with pytest.raises(ValueError, match="bad fleet pool spec"):
            FleetModel.from_spec("v5e-fourxtwo")
        with pytest.raises(ValueError, match="at least one pool"):
            FleetModel.from_spec("")
        with pytest.raises(ValueError, match="duplicate pool"):
            FleetModel(
                [
                    SlicePool("a", TpuSlice("v5e", 4), 1),
                    SlicePool("a", TpuSlice("v5e", 4), 1),
                ]
            )

    def test_assign_release_accounting(self):
        m = FleetModel.from_spec("p:v5e-4x2")
        m.assign(["p/0"], "j1")
        assert m.owner_of("p/0") == "j1"
        assert m.free_chips == 4
        with pytest.raises(ValueError, match="already owned"):
            m.assign(["p/0"], "j2")
        assert m.release_job("j1") == ["p/0"]
        assert m.free_chips == 8

    def test_gang_request_validation(self):
        with pytest.raises(ValueError, match="unknown priority class"):
            gang(klass="gold")
        with pytest.raises(ValueError, match="min_replicas"):
            gang(replicas=2, min_replicas=3)
        g = gang(klass="serve", replicas=2, chips=4)
        assert g.chips == 8
        assert g.priority == priority_index("serve") == 0
        assert priority_index("preemptible") == 3


# ---------------------------------------------------------------------------
# queue + quota + journal
# ---------------------------------------------------------------------------


class TestQueueOrdering:
    def test_class_then_fairshare_then_fifo(self):
        q = FleetQueue()
        q.push(gang(job="b1", tenant="big", klass="batch"), 0.0)
        q.push(gang(job="b2", tenant="small", klass="batch"), 0.0)
        q.push(gang(job="s1", tenant="big", klass="serve"), 0.0)
        # serve beats batch regardless of arrival; within batch the
        # tenant with fewer placed chips goes first
        order = [e.req.job for e in q.ordered({"big": 100, "small": 2})]
        assert order == ["s1", "b2", "b1"]
        assert q.position("b1", {"big": 100, "small": 2}) == 3
        # equal placed chips -> FIFO
        assert [e.req.job for e in q.ordered()] == ["s1", "b1", "b2"]

    def test_requeue_keeps_original_seq(self):
        q = FleetQueue()
        first = q.push(gang(job="old", klass="batch"), 0.0)
        q.remove("old")
        q.push(gang(job="new", klass="batch"), 1.0)
        q.push(gang(job="old", klass="batch"), 2.0, seq=first.seq)
        assert [e.req.job for e in q.ordered()] == ["old", "new"]

    def test_over_quota(self):
        quotas = parse_quotas(["capped=8"])
        assert not over_quota(gang(tenant="free", chips=999), {}, quotas)
        assert not over_quota(
            gang(tenant="capped", replicas=2, chips=4), {}, quotas
        )
        assert over_quota(
            gang(tenant="capped", chips=1), {"capped": 8}, quotas
        )
        with pytest.raises(ValueError, match="expected tenant=chips"):
            parse_quotas(["nope"])


class TestFleetJournal:
    def test_roundtrip_and_torn_line(self, tmp_path):
        j = FleetJournal(str(tmp_path / "j.jsonl"))
        j.append("submit", job="a", seq=1)
        j.append("place", job="a", units=["p/0"])
        with open(j.path, "a") as f:
            f.write('{"kind": "torn')  # crash mid-append
        kinds = [e["kind"] for e in j.entries()]
        assert kinds == ["submit", "place"]

    def test_missing_file_is_empty(self, tmp_path):
        assert list(FleetJournal(str(tmp_path / "none.jsonl")).entries()) == []


# ---------------------------------------------------------------------------
# the placer (+ the HBM oracle)
# ---------------------------------------------------------------------------


class TestPlacer:
    def test_single_pool_contiguity_preferred(self):
        m = FleetModel.from_spec("a:v5e-4x2,b:v5e-4x4")
        d = plan_placement(gang(replicas=3, chips=4), m)
        # only pool b can host the whole gang; lowest indices first
        assert [u.uid for u in d.units] == ["b/0", "b/1", "b/2"]

    def test_exact_fit_beats_fragmenting_big_slices(self):
        m = FleetModel.from_spec("small:v5e-4x2,big:v5p-8x2")
        d = plan_placement(gang(replicas=2, chips=4), m)
        assert [u.uid for u in d.units] == ["small/0", "small/1"]

    def test_spill_across_pools_when_no_pool_fits_alone(self):
        m = FleetModel.from_spec("a:v5e-4x1,b:v5e-4x1")
        d = plan_placement(gang(replicas=2, chips=4), m)
        assert sorted(u.uid for u in d.units) == ["a/0", "b/0"]

    def test_insufficient_capacity_queues_not_infeasible(self):
        m = FleetModel.from_spec("a:v5e-4x1")
        m.assign(["a/0"], "other")
        d = plan_placement(gang(replicas=1, chips=4), m)
        assert not d.placed and not d.infeasible

    def test_gang_admission_is_all_or_nothing(self):
        m = FleetModel.from_spec("a:v5e-4x2")
        d = plan_placement(gang(replicas=3, chips=4), m)
        assert d.units == []  # 2 free, 3 needed: nothing placed

    def test_no_capable_pool_is_infeasible(self):
        m = FleetModel.from_spec("a:v5e-4x2")
        d = plan_placement(gang(replicas=1, chips=8), m)
        assert "no pool has 8-chip slices" in d.infeasible

    def test_oracle_refuses_hbm_infeasible_generation(self):
        role = llama_role()
        # 8B params cannot fit one v5e chip (16 GiB): every pool refuses
        m = FleetModel.from_spec("edge:v5e-1x2")
        d = plan_placement(gang(replicas=1, chips=1), m, role=role)
        assert "TPX701" in d.infeasible
        assert "edge" in d.refusals

    def test_oracle_prunes_to_a_capable_generation(self):
        role = llama_role()
        m = FleetModel.from_spec("edge:v5e-1x2,big:v5p-8x2")
        d = plan_placement(gang(replicas=1, chips=8), m, role=role)
        assert d.placed and d.units[0].pool == "big"


# ---------------------------------------------------------------------------
# the market
# ---------------------------------------------------------------------------


def victim(job, klass, seq, elastic=True, replicas=4, min_replicas=1, ok=True):
    return Victim(
        job=job,
        priority=priority_index(klass),
        elastic=elastic,
        replicas=replicas,
        min_replicas=min_replicas,
        seq=seq,
        suitable=ok,
    )


class TestMarket:
    def test_elastic_victim_is_shrunk_not_killed(self):
        plan = plan_market(2, 0, [victim("v", "batch", 1)])
        assert plan == [Shrink(job="v", to_replicas=2, freed=2)]

    def test_shrink_respects_min_replicas(self):
        plan = plan_market(2, 0, [victim("v", "batch", 1, min_replicas=3)])
        # only 1 replica of headroom: not enough alone -> no plan
        assert plan == []

    def test_lowest_class_youngest_pays_first(self):
        plan = plan_market(
            2,
            0,
            [
                victim("old-preempt", "preemptible", 1, replicas=2),
                victim("young-preempt", "preemptible", 5, replicas=2),
                victim("batch", "batch", 2, replicas=4),
            ],
        )
        assert [a.job for a in plan] == ["young-preempt", "old-preempt"]

    def test_non_elastic_falls_back_to_preempt(self):
        plan = plan_market(
            2, 0, [victim("v", "batch", 1, elastic=False, replicas=2)]
        )
        assert plan == [Preempt(job="v", freed=2)]

    def test_equal_or_higher_class_is_never_victimized(self):
        assert plan_market(1, 1, [victim("peer", "interactive", 1)]) == []
        assert plan_market(1, 1, [victim("above", "serve", 1)]) == []

    def test_all_or_nothing(self):
        # one elastic victim with 1 headroom cannot cover a need of 3
        plan = plan_market(3, 0, [victim("v", "batch", 1, replicas=2)])
        assert plan == []

    def test_unsuitable_victims_are_skipped(self):
        assert plan_market(1, 0, [victim("v", "batch", 1, ok=False)]) == []


# ---------------------------------------------------------------------------
# the scheduler facade
# ---------------------------------------------------------------------------


class TestFleetScheduler:
    def test_place_queue_and_gang_admission(self, tmp_path):
        fs, ex, _ = make_fs(tmp_path, "sim:v5e-1x4")
        r1 = fs.submit(gang(replicas=3), {"scheduler": "local"})
        assert r1["status"] == "placed"
        # 1 free slice, gang of 3: queued whole, nothing partially placed
        r2 = fs.submit(gang(tenant="u", replicas=3), {"scheduler": "local"})
        assert r2["status"] == "queued" and r2["position"] == 1
        assert fs.model.free_chips == 1
        assert ex.n == 1

    def test_quota_blocks_placement_not_admission(self, tmp_path):
        fs, ex, _ = make_fs(tmp_path, "sim:v5e-1x4", quotas={"capped": 2})
        r1 = fs.submit(
            gang(tenant="capped", replicas=3), {"scheduler": "local"}
        )
        assert r1["status"] == "queued"  # 3 chips > quota of 2
        snap = fs.queue_snapshot()
        assert snap["queue"][0]["quota_blocked"] is True
        # an unlimited tenant sails past the quota-blocked gang
        r2 = fs.submit(gang(tenant="free", replicas=4), {"scheduler": "local"})
        assert r2["status"] == "placed"

    def test_shrink_then_growback_through_the_ledger(self, tmp_path):
        fs, ex, _ = make_fs(tmp_path, "sim:v5e-1x4")
        low = fs.submit(
            gang(
                klass="batch",
                tenant="research",
                replicas=4,
                elastic=True,
                mesh="fsdp=-1",
                min_replicas=1,
            ),
            {"scheduler": "local"},
        )
        high = fs.submit(
            gang(klass="serve", tenant="prod", replicas=2),
            {"scheduler": "local"},
        )
        assert high["status"] == "placed"
        assert fs.reshapes == 1 and fs.kills == 0
        low_job = fs.job(low["job"])
        assert low_job.cur_replicas == 2 and low_job.shrunk
        # serve completes -> the debt is repaid at the full launch mesh
        fs.on_event(terminal_event("app-3"))
        assert fs.grows == 1
        assert low_job.cur_replicas == 4 and not low_job.shrunk
        meshes = [
            e.get("mesh") for e in fs.ledger(low["job"]).entries()
        ]
        assert meshes == [
            None,
            "pp=1,dp=1,fsdp=2,ep=1,tp=1,sp=1",
            "pp=1,dp=1,fsdp=4,ep=1,tp=1,sp=1",
        ]

    def test_non_elastic_victim_requeued_then_replaced(self, tmp_path):
        fs, ex, _ = make_fs(tmp_path, "sim:v5e-1x2")
        low = fs.submit(
            gang(klass="preemptible", tenant="spot", replicas=2),
            {"scheduler": "local"},
        )
        high = fs.submit(
            gang(klass="interactive", tenant="dev", replicas=2),
            {"scheduler": "local"},
        )
        assert high["status"] == "placed"
        assert fs.kills == 1 and fs.reshapes == 0
        assert fs.job(low["job"]).state == "queued"
        fs.on_event(terminal_event("app-2"))  # interactive finishes
        assert fs.job(low["job"]).state == "running"
        assert ("cancel", "local://fake/app-1") in ex.calls

    def test_oracle_infeasible_at_submit(self, tmp_path):
        fs, ex, _ = make_fs(tmp_path, "edge:v5e-1x2")
        app = AppDef(name="llama", roles=[llama_role()])
        r = fs.submit(
            gang(replicas=1),
            {"appdef": appdef_to_dict(app), "scheduler": "local"},
        )
        assert r["status"] == "infeasible"
        assert "TPX701" in r["reason"]
        assert ex.n == 0

    def test_executor_failure_requeues_without_leaking_slices(self, tmp_path):
        fs, ex, _ = make_fs(tmp_path, "sim:v5e-1x2")
        ex.fail_next = True
        r = fs.submit(gang(replicas=2), {"scheduler": "local"})
        assert r["status"] == "queued"
        assert fs.model.free_chips == 2
        # next loop trigger retries it
        fs.on_event(terminal_event("no-such-app"))  # unknown handle: no-op
        r2 = fs.submit(gang(tenant="u", replicas=2), {"scheduler": "local"})
        assert r2["status"] == "queued"  # first gang placed on its retry
        assert fs.job(r["job"]).state == "running"

    def test_journal_rehydration(self, tmp_path):
        fs, ex, _ = make_fs(tmp_path, "sim:v5e-1x4")
        running = fs.submit(
            gang(
                klass="batch",
                replicas=4,
                elastic=True,
                mesh="fsdp=-1",
            ),
            {"scheduler": "local"},
        )
        fs.submit(gang(tenant="u", klass="serve", replicas=2), {"scheduler": "local"})
        # serve shrank batch to 2; now replay the journal from scratch
        fs2, _, _ = make_fs(tmp_path, "sim:v5e-1x4")
        assert fs2.rehydrate() == 2
        j = fs2.job(running["job"])
        assert j.state == "running" and j.cur_replicas == 2 and j.shrunk
        assert fs2.model.free_chips == 0
        # new submits keep queueing behind the rehydrated state
        r3 = fs2.submit(gang(tenant="w", replicas=1), {"scheduler": "local"})
        assert r3["status"] == "queued"

    def test_cancel_queued_job(self, tmp_path):
        fs, ex, _ = make_fs(tmp_path, "sim:v5e-1x1")
        fs.submit(gang(replicas=1), {"scheduler": "local"})
        queued = fs.submit(gang(tenant="u", replicas=1), {"scheduler": "local"})
        assert fs.cancel_job(queued["job"]) is True
        assert fs.job(queued["job"]).state == "done"
        assert fs.cancel_job("fj-9999") is False


# ---------------------------------------------------------------------------
# TPX602
# ---------------------------------------------------------------------------


def fleet_role(klass=None, env_klass=None, args=()):
    role = Role(
        name="w", image="img", entrypoint="python", args=list(args)
    )
    if klass:
        role.metadata["fleet/class"] = klass
    if env_klass:
        role.env["TPX_FLEET_CLASS"] = env_klass
    return AppDef(name="app", roles=[role])


class TestFleetClassRule:
    def codes(self, report):
        return [d.code for d in report.diagnostics]

    def test_victim_class_without_recovery_warns(self):
        report = analyze(fleet_role(klass="preemptible"))
        assert "TPX602" in self.codes(report)
        d = next(d for d in report.diagnostics if d.code == "TPX602")
        assert d.severity is Severity.WARNING
        assert "full progress" in d.message

    def test_env_spelling_counts(self):
        assert "TPX602" in self.codes(analyze(fleet_role(env_klass="batch")))

    def test_checkpoint_flag_silences(self):
        report = analyze(
            fleet_role(klass="batch", args=["--ckpt-dir", "/ckpt"])
        )
        assert "TPX602" not in self.codes(report)

    def test_elastic_reshape_policy_silences(self):
        policy = SupervisorPolicy(elastic_reshape=True, mesh="fsdp=-1")
        report = analyze(fleet_role(klass="preemptible"), policy=policy)
        assert "TPX602" not in self.codes(report)

    def test_protected_classes_are_silent(self):
        assert "TPX602" not in self.codes(analyze(fleet_role(klass="serve")))
        assert "TPX602" not in self.codes(analyze(fleet_role()))


# ---------------------------------------------------------------------------
# daemon e2e (real LocalScheduler)
# ---------------------------------------------------------------------------


def make_daemon(tmp_path, monkeypatch, fleet_spec=None, quotas=None, **kw):
    monkeypatch.setenv("TPX_WATCH_INTERVAL", "0.05")
    state_dir = str(tmp_path / "control")
    fleet = None
    if fleet_spec:
        fleet = FleetScheduler(
            FleetModel.from_spec(fleet_spec),
            state_dir=state_dir,
            quotas=quotas,
        )
    return ControlDaemon(
        runner=get_runner("fleet-test"),
        state_dir=state_dir,
        fleet=fleet,
        **kw,
    ).start()


def wait_until(predicate, timeout=60.0, interval=0.1):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


class TestFleetDaemon:
    def test_shrink_and_growback_e2e(self, tmp_path, monkeypatch):
        d = make_daemon(tmp_path, monkeypatch, fleet_spec="sim:v5e-1x4")
        try:
            client = ControlClient(d.addr, d.root_token)
            low = client.submit_job(
                "utils.sh",
                ["sleep", "30"],
                "local",
                cfg={"log_dir": str(tmp_path / "low")},
                priority="batch",
                elastic=True,
                mesh="fsdp=-1",
                replicas=4,
                min_replicas=1,
            )
            assert low.get("handle", "").startswith("local://")
            # high-priority gang forces the elastic shrink, placing NOW
            high = client.submit_job(
                "utils.sh",
                ["sleep", "1"],
                "local",
                cfg={"log_dir": str(tmp_path / "high")},
                priority="serve",
                replicas=2,
            )
            assert high.get("handle", "").startswith("local://")
            entries = list(d.fleet.ledger(low["fleet_job"]).entries())
            assert [e.get("mesh") for e in entries] == [
                None,
                "pp=1,dp=1,fsdp=2,ep=1,tp=1,sp=1",
            ]
            assert [e.get("replicas") for e in entries] == [4, 2]
            # the shrunk attempt really runs on 2 replicas with the env
            snap = client.queue()
            mine = next(
                r for r in snap["running"] if r["job"] == low["fleet_job"]
            )
            assert mine["shrunk"] and mine["replicas"] == 2
            assert snap["market"]["reshapes"] == 1
            assert snap["market"]["kills"] == 0
            # serve finishes (~1s): the watch stream triggers the grow-back
            assert wait_until(
                lambda: client.queue()["market"]["growbacks"] == 1
            ), "grow-back never happened"
            entries = list(d.fleet.ledger(low["fleet_job"]).entries())
            assert entries[-1].get("mesh") == "pp=1,dp=1,fsdp=4,ep=1,tp=1,sp=1"
            assert entries[-1].get("replicas") == 4
            mine = next(
                r
                for r in client.queue()["running"]
                if r["job"] == low["fleet_job"]
            )
            assert not mine["shrunk"] and mine["replicas"] == 4
        finally:
            d.close()
            d.runner.close()

    def test_queue_ordering_metrics_and_202(self, tmp_path, monkeypatch):
        d = make_daemon(tmp_path, monkeypatch, fleet_spec="sim:v5e-1x4")
        try:
            client = ControlClient(d.addr, d.root_token)
            filler = client.submit_job(
                "utils.sh",
                ["sleep", "30"],
                "local",
                cfg={"log_dir": str(tmp_path / "filler")},
                priority="serve",
                replicas=4,
            )
            assert filler.get("handle")
            batch = client.submit_job(
                "utils.sh",
                ["sleep", "1"],
                "local",
                cfg={"log_dir": str(tmp_path / "b")},
                priority="batch",
            )
            inter = client.submit_job(
                "utils.sh",
                ["sleep", "1"],
                "local",
                cfg={"log_dir": str(tmp_path / "i")},
                priority="interactive",
            )
            assert batch["queued"] and inter["queued"]
            # interactive outranks batch despite arriving later
            snap = client.queue()
            assert [q["class"] for q in snap["queue"]] == [
                "interactive",
                "batch",
            ]
            assert snap["queue"][0]["job"] == inter["fleet_job"]
            # the legacy handle-now verb surfaces queueing as a 202
            with pytest.raises(ControlClientError) as ei:
                client.submit(
                    "utils.sh",
                    ["sleep", "1"],
                    "local",
                    cfg={"log_dir": str(tmp_path / "x")},
                )
            assert ei.value.code == 202 and "tpx queue" in ei.value.message
            # fleet gauges are on /metricz
            with urllib.request.urlopen(d.addr + "/metricz") as resp:
                text = resp.read().decode()
            assert 'tpx_fleet_queue_depth{klass="interactive"} 1' in text
            assert 'tpx_fleet_chips{state="free"} 0' in text
            assert "tpx_fleet_placements_total" in text
            # a queued gang can be cancelled by fleet job id
            client._request("/v1/cancel", {"job": batch["fleet_job"]})
            assert all(
                q["job"] != batch["fleet_job"]
                for q in client.queue()["queue"]
            )
        finally:
            d.close()
            d.runner.close()

    def test_legacy_429_retry_after_contract(self, tmp_path, monkeypatch):
        d = make_daemon(tmp_path, monkeypatch, tenant_cap=1)
        try:
            client = ControlClient(d.addr, d.root_token)
            client.submit(
                "utils.sh",
                ["sleep", "30"],
                "local",
                cfg={"log_dir": str(tmp_path / "one")},
            )
            req = urllib.request.Request(
                d.addr + "/v1/submit",
                data=json.dumps(
                    {
                        "component": "utils.sh",
                        "args": ["sleep", "1"],
                        "scheduler": "local",
                        "cfg": {"log_dir": str(tmp_path / "two")},
                    }
                ).encode(),
                headers={
                    "Authorization": f"Bearer {d.root_token}",
                    "Content-Type": "application/json",
                },
                method="POST",
            )
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req)
            err = ei.value
            assert err.code == 429
            assert err.headers["Retry-After"] == "5"
            body = json.loads(err.read())
            assert body["code"] == "tenant_cap_exceeded"
            assert body["tenant"] == "root"
            assert body["active"] == 1 and body["cap"] == 1
            assert body["retry_after_seconds"] == 5
        finally:
            d.close()
            d.runner.close()

    def test_daemon_restart_rehydrates_the_queue(self, tmp_path, monkeypatch):
        # one 4-chip slice: a 2-replica x 4-chip gang can NEVER place now
        # but is not infeasible (the pool shape fits) -> it queues durably
        d = make_daemon(tmp_path, monkeypatch, fleet_spec="sim:v5e-4x1")
        batch = inter = None
        try:
            client = ControlClient(d.addr, d.root_token)
            batch = client.submit_job(
                "utils.sh",
                ["sleep", "1"],
                "local",
                cfg={"log_dir": str(tmp_path / "b")},
                priority="batch",
                replicas=2,
                chips=4,
            )
            inter = client.submit_job(
                "utils.sh",
                ["sleep", "1"],
                "local",
                cfg={"log_dir": str(tmp_path / "i")},
                priority="interactive",
                replicas=2,
                chips=4,
            )
            assert batch["queued"] and inter["queued"]
        finally:
            d.close()
            d.runner.close()
        d2 = make_daemon(tmp_path, monkeypatch, fleet_spec="sim:v5e-4x1")
        try:
            client = ControlClient(d2.addr, d2.root_token)
            snap = client.queue()
            assert [q["job"] for q in snap["queue"]] == [
                inter["fleet_job"],
                batch["fleet_job"],
            ]
            assert snap["fleet"]["chips_free"] == 4
        finally:
            d2.close()
            d2.runner.close()

    def test_infeasible_submit_is_409(self, tmp_path, monkeypatch):
        d = make_daemon(tmp_path, monkeypatch, fleet_spec="sim:v5e-4x1")
        try:
            client = ControlClient(d.addr, d.root_token)
            with pytest.raises(ControlClientError) as ei:
                client.submit_job(
                    "utils.sh",
                    ["sleep", "1"],
                    "local",
                    cfg={"log_dir": str(tmp_path / "big")},
                    chips=8,  # no pool has 8-chip slices
                )
            assert ei.value.code == 409
            assert "cannot fit this fleet" in ei.value.message
        finally:
            d.close()
            d.runner.close()

    def test_queue_endpoint_without_fleet(self, tmp_path, monkeypatch):
        d = make_daemon(tmp_path, monkeypatch)
        try:
            client = ControlClient(d.addr, d.root_token)
            assert client.queue() == {"enabled": False}
        finally:
            d.close()
            d.runner.close()
