"""GCP Batch scheduler tests: assert on the materialized Batch job config
and drive the lifecycle with canned gcloud output (reference analog:
aws_batch_scheduler_test.py — mock-client node-group assertions)."""

import json
import subprocess
from unittest import mock

import pytest

from torchx_tpu.schedulers.gcp_batch_scheduler import (
    GCPBatchOpts,
    GCPBatchScheduler,
    app_to_batch_job,
    describe_batch_job,
    role_to_task_group,
)
from torchx_tpu.specs.api import (
    AppDef,
    AppState,
    Resource,
    Role,
    TpuSlice,
    macros,
)


@pytest.fixture(autouse=True)
def _isolated_scopes(tmp_path, monkeypatch):
    """Point the durable scope registry at tmp so tests never touch ~."""
    from torchx_tpu.schedulers import gcp_batch_scheduler as mod

    monkeypatch.setattr(
        mod, "_scopes_path", lambda: str(tmp_path / "scopes")
    )
    monkeypatch.setattr(
        mod, "_fails_path", lambda: str(tmp_path / "scope_fails")
    )


def tpu_role(chips=16, accelerator="v5p", num_replicas=1, **kwargs) -> Role:
    return Role(
        name="trainer",
        image="gcr.io/proj/img:1",
        entrypoint="python",
        args=["-m", "train", f"--replica={macros.replica_id}"],
        num_replicas=num_replicas,
        resource=Resource(
            cpu=208, memMB=448 * 1024, tpu=TpuSlice(accelerator, chips)
        ),
        **kwargs,
    )


def cpu_role(**kwargs) -> Role:
    defaults = dict(
        name="reader",
        image="",
        entrypoint="sh",
        args=["-c", "echo hi"],
        num_replicas=2,
        resource=Resource(cpu=2, memMB=4096),
    )
    defaults.update(kwargs)
    return Role(**defaults)


class TestMaterialization:
    def test_tpu_role_task_group(self):
        group = role_to_task_group(tpu_role(), "app-1")
        # v5p-16 = 4 hosts, one task per VM, gang parallelism
        assert group["taskCount"] == 4
        assert group["parallelism"] == 4
        assert group["taskCountPerNode"] == 1
        assert group["requireHostsFile"] is True
        (runnable,) = group["taskSpec"]["runnables"]
        script = runnable["container"]["commands"][1]
        assert "export TPX_NUM_REPLICAS=4" in script
        assert 'TPX_REPLICA_ID="${BATCH_TASK_INDEX:-0}"' in script
        assert "cloudbatch-taskgroup-hosts" in script  # coordinator source
        # the replica-id macro rides the exported env var, double-quoted so
        # the shell expands it at runtime
        assert '"--replica=$TPX_REPLICA_ID"' in script

    def test_container_runnable_mounts_hosts_file(self):
        group = role_to_task_group(tpu_role(), "app-1")
        (runnable,) = group["taskSpec"]["runnables"]
        assert runnable["container"]["imageUri"] == "gcr.io/proj/img:1"
        assert (
            "/etc/cloudbatch-taskgroup-hosts:/etc/cloudbatch-taskgroup-hosts:ro"
            in runnable["container"]["volumes"]
        )

    def test_imageless_role_uses_script_runnable(self):
        group = role_to_task_group(cpu_role(), "app-1")
        (runnable,) = group["taskSpec"]["runnables"]
        assert "script" in runnable
        assert "echo hi" in runnable["script"]["text"]

    def test_cpu_role_compute_resource(self):
        group = role_to_task_group(cpu_role(), "app-1")
        assert group["taskSpec"]["computeResource"] == {
            "cpuMilli": 2000,
            "memoryMib": 4096,
        }
        assert group["taskCount"] == 2

    def test_retries(self):
        group = role_to_task_group(cpu_role(max_retries=3), "app-1")
        assert group["taskSpec"]["maxRetryCount"] == 3

    def test_multislice_hosts(self):
        group = role_to_task_group(tpu_role(num_replicas=2), "app-1")
        assert group["taskCount"] == 8  # 2 slices x 4 hosts

    def test_tpu_machine_type_single_host(self):
        # v5litepod-8 fits on one host: the 8-chip VM family
        cfg = app_to_batch_job(
            AppDef(name="a", roles=[tpu_role(accelerator="v5e", chips=8)]),
            "app-1",
            GCPBatchOpts(),
        )
        (inst,) = cfg["allocationPolicy"]["instances"]
        assert inst["policy"]["machineType"] == "ct5lp-hightpu-8t"

    @pytest.mark.parametrize(
        "accelerator, chips, machine_type",
        [
            ("v5e", 16, "ct5lp-hightpu-4t"),  # multi-host v5e = 4-chip VMs
            ("v5e", 64, "ct5lp-hightpu-4t"),
            ("v6e", 16, "ct6e-standard-4t"),
            ("v6e", 8, "ct6e-standard-8t"),  # single host keeps the 8t VM
            ("v4", 16, "ct4p-hightpu-4t"),
        ],
    )
    def test_tpu_machine_type_geometry(self, accelerator, chips, machine_type):
        cfg = app_to_batch_job(
            AppDef(name="a", roles=[tpu_role(accelerator=accelerator, chips=chips)]),
            "app-1",
            GCPBatchOpts(),
        )
        (inst,) = cfg["allocationPolicy"]["instances"]
        assert inst["policy"]["machineType"] == machine_type

    def test_unknown_accelerator_raises(self):
        # v7x is a valid slice generation but has no Batch machine family
        with pytest.raises(ValueError, match="no Batch TPU-VM machine family"):
            app_to_batch_job(
                AppDef(name="a", roles=[tpu_role(accelerator="v7x")]),
                "app-1",
                GCPBatchOpts(),
            )

    def test_cpu_machine_type_from_opts(self):
        cfg = app_to_batch_job(
            AppDef(name="a", roles=[cpu_role()]),
            "app-1",
            GCPBatchOpts(machine_type="n2-standard-8"),
        )
        (inst,) = cfg["allocationPolicy"]["instances"]
        assert inst["policy"]["machineType"] == "n2-standard-8"

    def test_labels_and_logging(self):
        cfg = app_to_batch_job(
            AppDef(name="a", roles=[cpu_role()]), "app-1", GCPBatchOpts()
        )
        assert cfg["labels"]["tpx-app-name"] == "app-1"
        assert cfg["labels"]["tpx-role-name"] == "reader"
        assert cfg["logsPolicy"]["destination"] == "CLOUD_LOGGING"

    def test_multi_role_rejected(self):
        # the Batch API takes exactly one taskGroup per job
        with pytest.raises(ValueError, match="single-role"):
            app_to_batch_job(
                AppDef(name="a", roles=[tpu_role(), cpu_role()]),
                "app-1",
                GCPBatchOpts(),
            )


class TestDescribeMapping:
    def test_running_with_counts(self):
        payload = {
            "status": {
                "state": "RUNNING",
                "taskGroups": {
                    "group0": {"counts": {"RUNNING": 3, "SUCCEEDED": 1}}
                },
            }
        }
        resp = describe_batch_job("loc:app", payload, ["trainer"])
        assert resp.state == AppState.RUNNING
        (rs,) = resp.roles_statuses
        states = sorted(r.state.name for r in rs.replicas)
        assert states == ["RUNNING", "RUNNING", "RUNNING", "SUCCEEDED"]

    def test_malformed_payload_never_crashes(self):
        resp = describe_batch_job(
            "loc:app",
            {"status": {"state": "FAILED", "taskGroups": {"group0": {"counts": {"FAILED": "x"}}}}},
            ["w"],
        )
        assert resp.state == AppState.FAILED
        (rs,) = resp.roles_statuses
        assert rs.replicas == []

    def test_empty_payload(self):
        resp = describe_batch_job("loc:app", {}, ["w"])
        assert resp.state == AppState.UNKNOWN


def proc(rc=0, stdout="", stderr=""):
    return subprocess.CompletedProcess([], rc, stdout=stdout, stderr=stderr)


class TestLifecycle:
    def _sched(self, run_cmd):
        sched = GCPBatchScheduler("test")
        sched._run_cmd = run_cmd
        return sched

    def test_schedule_submits_config_on_stdin(self):
        calls = []

        def run_cmd(cmd, **kwargs):
            calls.append((cmd, kwargs))
            return proc()

        sched = self._sched(run_cmd)
        app = AppDef(name="train", roles=[cpu_role()])
        info = sched.submit_dryrun(app, {"location": "us-east1"})
        app_id = sched.schedule(info)
        assert app_id.startswith("us-east1:train-")
        (cmd, kwargs) = calls[0]
        assert cmd[:4] == ["gcloud", "batch", "jobs", "submit"]
        assert "--location" in cmd and "us-east1" in cmd
        config = json.loads(kwargs["input"])
        assert config["taskGroups"][0]["taskCount"] == 2

    def test_schedule_failure_raises(self):
        sched = self._sched(lambda cmd, **kw: proc(rc=1, stderr="quota"))
        info = sched.submit_dryrun(AppDef(name="t", roles=[cpu_role()]), {})
        with pytest.raises(RuntimeError, match="quota"):
            sched.schedule(info)

    def test_describe_parses_state(self):
        payload = json.dumps(
            {
                "taskGroups": [{}],
                "labels": {"tpx-role-name": "trainer"},
                "status": {
                    "state": "SUCCEEDED",
                    "taskGroups": {"group0": {"counts": {"SUCCEEDED": 2}}},
                },
            }
        )
        sched = self._sched(lambda cmd, **kw: proc(stdout=payload))
        resp = sched.describe("us-central1:app-1")
        assert resp.state == AppState.SUCCEEDED
        # the real role name is recovered from the job label
        (rs,) = resp.roles_statuses
        assert rs.role == "trainer"

    def test_project_qualified_app_id_routes_project(self):
        calls = []

        def run_cmd(cmd, **kwargs):
            calls.append(cmd)
            return proc()

        sched = self._sched(run_cmd)
        app = AppDef(name="train", roles=[cpu_role()])
        info = sched.submit_dryrun(
            app, {"location": "us-east1", "project": "my-proj"}
        )
        app_id = sched.schedule(info)
        assert app_id.startswith("my-proj:us-east1:train-")
        sched.delete(app_id)
        delete_cmd = calls[-1]
        assert "--project" in delete_cmd and "my-proj" in delete_cmd

    def test_describe_missing_returns_none(self):
        sched = self._sched(lambda cmd, **kw: proc(rc=1, stderr="NOT_FOUND"))
        assert sched.describe("us-central1:gone") is None

    def test_list(self):
        payload = json.dumps(
            [
                {
                    "name": "projects/p/locations/l/jobs/app-1",
                    "status": {"state": "RUNNING"},
                }
            ]
        )
        sched = self._sched(
            lambda cmd, **kw: proc(
                stdout="(unset)" if "config" in cmd else payload
            )
        )
        (item,) = sched.list()
        assert item.name == "app-1"
        assert item.state == AppState.RUNNING

    def test_list_scoped_to_session_cfg(self):
        # jobs submitted with an explicit project/location must stay visible
        # to list(), and listed ids must carry the project prefix so later
        # describe/cancel target the same project
        payload = json.dumps(
            [
                {
                    "name": "projects/my-proj/locations/eu-west4/jobs/app-1",
                    "status": {"state": "RUNNING"},
                }
            ]
        )
        calls = []

        def run_cmd(cmd, **kwargs):
            calls.append(cmd)
            if "config" in cmd:
                return proc(stdout="(unset)")
            return proc(stdout=payload if "list" in cmd else "{}")

        sched = self._sched(run_cmd)
        info = sched.submit_dryrun(
            AppDef(name="t", roles=[cpu_role()]),
            {"location": "eu-west4", "project": "my-proj"},
        )
        sched.schedule(info)  # list() scopes to SUBMITTED cfg, not dryruns
        (item,) = sched.list()
        assert item.app_id == "my-proj:eu-west4:app-1"
        list_cmd = calls[-1]
        assert "--project" in list_cmd and "my-proj" in list_cmd
        assert "--location" in list_cmd and "eu-west4" in list_cmd

    def test_list_scope_survives_fresh_process(self):
        # the scope registry is durable: a NEW scheduler instance (fresh
        # CLI process) must still query the explicit project a job was
        # submitted to, instead of the gcloud default
        payload = json.dumps(
            [
                {
                    "name": "projects/my-proj/locations/eu-west4/jobs/app-1",
                    "status": {"state": "RUNNING"},
                }
            ]
        )
        submitter = self._sched(lambda cmd, **kw: proc(stdout="{}"))
        info = submitter.submit_dryrun(
            AppDef(name="t", roles=[cpu_role()]),
            {"location": "eu-west4", "project": "my-proj"},
        )
        submitter.schedule(info)

        calls = []

        def run_cmd(cmd, **kwargs):
            calls.append(cmd)
            return proc(stdout=payload if "list" in cmd else "")

        fresh = self._sched(run_cmd)  # no _session_opts
        (item,) = fresh.list()
        assert item.app_id == "my-proj:eu-west4:app-1"
        list_cmd = calls[-1]
        assert "--project" in list_cmd and "my-proj" in list_cmd
        assert "--location" in list_cmd and "eu-west4" in list_cmd

    def test_list_evicts_scope_after_repeated_failures(self):
        """A registered scope whose gcloud calls keep failing (revoked /
        deleted project) must stop adding a failing subprocess to every
        list() — evicted after 3 unbroken failures (advisor r4)."""
        submitter = self._sched(lambda cmd, **kw: proc(stdout="{}"))
        info = submitter.submit_dryrun(
            AppDef(name="t", roles=[cpu_role()]),
            {"location": "eu-west4", "project": "dead-proj"},
        )
        submitter.schedule(info)

        calls = []

        def failing(cmd, **kwargs):
            calls.append(cmd)
            if "config" in cmd:
                return proc(stdout="(unset)")
            return proc(rc=1, stderr="PERMISSION_DENIED")

        fresh = self._sched(failing)
        for _ in range(3):
            fresh.list()
        dead_before = sum(
            1 for c in calls if "list" in c and "dead-proj" in c
        )
        assert dead_before == 3
        fresh.list()  # 4th: evicted — the dead scope is never queried
        # (list() may still fall back to the DEFAULT scope, which is fine:
        # the advisor's complaint was the dead scope's eternal failure)
        assert (
            sum(1 for c in calls if "list" in c and "dead-proj" in c)
            == dead_before
        )

    def test_successful_submit_unevicts_scope(self):
        from torchx_tpu.schedulers import gcp_batch_scheduler as mod

        for _ in range(mod.SCOPE_EVICT_FAILURES):
            mod._note_scope_result("dead-proj", "eu-west4", ok=False)
        assert ("dead-proj", "eu-west4") in mod._evicted_scopes()
        sched = self._sched(lambda cmd, **kw: proc(stdout="{}"))
        info = sched.submit_dryrun(
            AppDef(name="t", roles=[cpu_role()]),
            {"location": "eu-west4", "project": "dead-proj"},
        )
        sched.schedule(info)
        assert ("dead-proj", "eu-west4") not in mod._evicted_scopes()

    def test_list_unions_scopes_dedup(self):
        # session scope == registered scope: one gcloud call, no dup rows
        payload = json.dumps(
            [
                {
                    "name": "projects/my-proj/locations/eu-west4/jobs/app-1",
                    "status": {"state": "RUNNING"},
                }
            ]
        )
        calls = []

        def run_cmd(cmd, **kwargs):
            calls.append(cmd)
            if "config" in cmd:
                return proc(stdout="(unset)")
            return proc(stdout=payload if "list" in cmd else "{}")

        sched = self._sched(run_cmd)
        info = sched.submit_dryrun(
            AppDef(name="t", roles=[cpu_role()]),
            {"location": "eu-west4", "project": "my-proj"},
        )
        sched.schedule(info)
        items = sched.list()
        assert [i.app_id for i in items] == ["my-proj:eu-west4:app-1"]
        assert sum(1 for c in calls if "list" in c) == 1

    def test_list_keeps_default_project_jobs_with_explicit_scope(self):
        # a default-project job (submitted via raw gcloud) must not vanish
        # from list() once an explicit-project scope is registered
        explicit = json.dumps(
            [
                {
                    "name": "projects/my-proj/locations/eu-west4/jobs/app-1",
                    "status": {"state": "RUNNING"},
                }
            ]
        )
        default = json.dumps(
            [
                {
                    "name": "projects/dflt/locations/us-central1/jobs/raw-1",
                    "status": {"state": "RUNNING"},
                }
            ]
        )

        def run_cmd(cmd, **kwargs):
            if "config" in cmd:
                return proc(stdout="dflt\n")
            if "list" in cmd:
                return proc(
                    stdout=explicit if "my-proj" in cmd else default
                )
            return proc(stdout="{}")

        sched = self._sched(run_cmd)
        info = sched.submit_dryrun(
            AppDef(name="t", roles=[cpu_role()]),
            {"location": "eu-west4", "project": "my-proj"},
        )
        sched.schedule(info)
        ids = {i.app_id for i in sched.list()}
        assert ids == {"my-proj:eu-west4:app-1", "dflt:us-central1:raw-1"}

    def test_list_no_duplicates_when_default_equals_explicit(self):
        # scope recorded as resolved default + session None-project scope
        # must collapse to ONE query/row, not duplicate prefixless ids
        payload = json.dumps(
            [
                {
                    "name": "projects/dflt/locations/us-central1/jobs/j-1",
                    "status": {"state": "RUNNING"},
                }
            ]
        )
        calls = []

        def run_cmd(cmd, **kwargs):
            calls.append(cmd)
            if "config" in cmd:
                return proc(stdout="dflt\n")
            return proc(stdout=payload if "list" in cmd else "{}")

        sched = self._sched(run_cmd)
        info = sched.submit_dryrun(
            AppDef(name="t", roles=[cpu_role()]), {}
        )  # no explicit project: scope records the RESOLVED default
        sched.schedule(info)
        items = sched.list()
        assert [i.app_id for i in items] == ["dflt:us-central1:j-1"]
        assert sum(1 for c in calls if "list" in c) == 1

    def test_list_falls_back_to_gcloud_project(self):
        # no session cfg: list() asks gcloud for the configured project
        jobs = json.dumps(
            [{"name": "projects/p/locations/l/jobs/j-1", "status": {"state": "QUEUED"}}]
        )

        def run_cmd(cmd, **kwargs):
            if "config" in cmd:
                return proc(stdout="cfg-proj\n")
            return proc(stdout=jobs)

        sched = self._sched(run_cmd)
        (item,) = sched.list()
        assert item.app_id == "cfg-proj:us-central1:j-1"

    def test_cancel_falls_back_to_delete(self):
        calls = []

        def run_cmd(cmd, **kwargs):
            calls.append(cmd)
            # `cancel` unsupported on this gcloud -> rc 2, then delete ok
            return proc(rc=2 if "cancel" in cmd else 0, stdout="{}")

        sched = self._sched(run_cmd)
        # exists() check hits describe first; feed it a running job
        sched.describe = lambda app_id: describe_batch_job(
            app_id, {"status": {"state": "RUNNING"}}, ["w"]
        )
        sched.cancel("us-central1:app-1")
        assert any("cancel" in c for c in calls)
        assert any("delete" in c for c in calls)

    def test_invalid_app_id(self):
        sched = self._sched(lambda cmd, **kw: proc())
        with pytest.raises(ValueError, match="location:name"):
            sched.describe("nocolon")
        with pytest.raises(ValueError, match="location:name"):
            sched.describe("a:b:c:d")

    def test_log_iter_filters_on_server_uid(self):
        entries = json.dumps(
            [{"textPayload": "step 1\n"}, {"textPayload": "step 2 done\n"}]
        )
        calls = []

        def run_cmd(cmd, **kwargs):
            calls.append(cmd)
            if "describe" in cmd:
                # Batch stamps logs with the server-generated UID
                return proc(stdout=json.dumps({"uid": "app-1-7f3e0d"}))
            return proc(stdout=entries)

        sched = self._sched(run_cmd)
        lines = list(sched.log_iter("us-central1:app-1", "w", 1, regex="done"))
        assert lines == ["step 2 done"]
        read_cmd = calls[-1]
        assert read_cmd[:3] == ["gcloud", "logging", "read"]
        assert 'labels.job_uid="app-1-7f3e0d"' in read_cmd[3]
        assert 'labels.task_index="1"' in read_cmd[3]

    def test_log_iter_uid_fallback_when_describe_fails(self):
        calls = []

        def run_cmd(cmd, **kwargs):
            calls.append(cmd)
            if "describe" in cmd:
                return proc(rc=1, stderr="gone")
            return proc(stdout="[]")

        sched = self._sched(run_cmd)
        list(sched.log_iter("us-central1:app-1", "w", 0))
        assert 'labels.job_uid="app-1"' in calls[-1][3]

    def test_log_iter_window_filters(self):
        calls = []

        def run_cmd(cmd, **kwargs):
            calls.append(cmd)
            if "describe" in cmd:
                return proc(stdout=json.dumps({"uid": "u1"}))
            return proc(stdout="[]")

        sched = self._sched(run_cmd)
        # 2026-07-29T00:00:00Z .. +1h
        list(sched.log_iter("us-central1:app-1", "w", 0, since=1785283200.0,
                            until=1785286800.0))
        filt = calls[-1][3]
        assert 'timestamp>="2026-07-29T00:00:00Z"' in filt
        assert 'timestamp<="2026-07-29T01:00:00Z"' in filt

    def test_log_iter_rejects_stream_selection(self):
        from torchx_tpu.schedulers.api import Stream

        sched = self._sched(lambda cmd, **kw: proc())
        with pytest.raises(ValueError, match="combined"):
            sched.log_iter("us-central1:app-1", "w", 0, streams=Stream.STDOUT)

    def test_long_app_name_capped_to_63(self):
        sched = self._sched(lambda cmd, **kw: proc())
        app = AppDef(name="x" * 80, roles=[cpu_role()])
        info = sched.submit_dryrun(app, {})
        assert len(info.request.name) <= 60
        labels = info.request.config["labels"]
        assert all(len(v) <= 63 for v in labels.values())


class TestRegistry:
    def test_gcp_batch_registered(self):
        from torchx_tpu.schedulers import get_scheduler_factories

        assert "gcp_batch" in get_scheduler_factories()
