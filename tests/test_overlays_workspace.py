"""Overlays, StructuredOpts, and workspace-layer tests."""

import io
import os
import tarfile
from dataclasses import dataclass, field
from typing import Optional

import pytest

from torchx_tpu.schedulers.structured_opts import StructuredOpts
from torchx_tpu.specs.api import Role, Workspace
from torchx_tpu.specs.overlays import (
    DEL,
    JOIN,
    PUT,
    apply_overlay,
    get_overlay,
    set_overlay,
    validate_overlay,
)
from torchx_tpu.workspace.api import walk_workspace
from torchx_tpu.workspace.dir_workspace import DirWorkspaceMixin, copy_workspace
from torchx_tpu.workspace.docker_workspace import build_context


class TestOverlays:
    def test_strategic_merge(self):
        target = {"a": {"b": 1, "c": 2}, "keep": True}
        out = apply_overlay(target, {"a": {"b": 9}})
        assert out == {"a": {"b": 9, "c": 2}, "keep": True}
        assert target["a"]["b"] == 1  # original untouched

    def test_put_replaces(self):
        out = apply_overlay({"a": {"b": 1}}, {PUT("a"): {"x": 1}})
        assert out["a"] == {"x": 1}

    def test_del(self):
        out = apply_overlay({"a": 1, "b": 2}, {DEL("a"): None})
        assert out == {"b": 2}

    def test_join_by_name(self):
        target = {"containers": [{"name": "main", "image": "a"}, {"name": "side"}]}
        out = apply_overlay(
            target,
            {
                JOIN("containers"): [
                    {"name": "main", "image": "b"},
                    {"name": "new"},
                ]
            },
        )
        names = [c["name"] for c in out["containers"]]
        assert names == ["main", "side", "new"]
        assert out["containers"][0]["image"] == "b"

    def test_join_custom_key(self):
        target = {"env": [{"key": "A", "v": 1}]}
        out = apply_overlay(target, {JOIN("env", "key"): [{"key": "A", "v": 2}]})
        assert out["env"] == [{"key": "A", "v": 2}]

    def test_validate(self):
        assert validate_overlay({"a": 1}) == []
        assert validate_overlay({DEL("a"): "not-empty"})
        assert validate_overlay("nope")
        assert validate_overlay({PUT(""): 1})

    def test_role_attachment(self):
        role = Role(name="r", image="i")
        set_overlay(role, "gke", {"a": 1})
        assert get_overlay(role, "gke") == {"a": 1}
        assert get_overlay(role, "slurm") is None
        with pytest.raises(ValueError):
            set_overlay(role, "gke", {DEL("x"): "bad"})


@dataclass
class _Nested(StructuredOpts):
    context: str = "default-ctx"
    """kube context to use."""


@dataclass
class _MyOpts(StructuredOpts):
    namespace: str = "default"
    """namespace to submit into."""
    replicas: int = 1
    """number of replicas."""
    queue: Optional[str] = None
    """queue name."""
    k8s: _Nested = field(default_factory=_Nested)


class TestStructuredOpts:
    def test_to_runopts_docs_and_defaults(self):
        opts = _MyOpts.to_runopts()
        d = dict(opts)
        assert d["namespace"].default == "default"
        assert d["namespace"].help == "namespace to submit into."
        assert d["replicas"].opt_type is int
        assert "k8s.context" in d  # nested group flattened

    def test_from_cfg(self):
        cfg = _MyOpts.to_runopts().resolve(
            {"namespace": "ml", "replicas": "3", "k8s.context": "prod"}
        )
        typed = _MyOpts.from_cfg(cfg)
        assert typed.namespace == "ml"
        assert typed.replicas == 3
        assert typed.k8s.context == "prod"
        assert typed["namespace"] == "ml"  # mapping protocol
        assert typed.get("nope", "dflt") == "dflt"


class TestWorkspaceWalk:
    def make_tree(self, tmp_path):
        (tmp_path / "src").mkdir()
        (tmp_path / "src" / "main.py").write_text("print()")
        (tmp_path / "data").mkdir()
        (tmp_path / "data" / "big.bin").write_text("x" * 10)
        (tmp_path / "keep.bin").write_text("k")
        (tmp_path / ".git").mkdir()
        (tmp_path / ".git" / "obj").write_text("g")
        (tmp_path / ".tpxignore").write_text("*.bin\n!keep.bin\n.git\ndata\n")
        return tmp_path

    def test_ignore_with_negation(self, tmp_path):
        root = self.make_tree(tmp_path)
        rels = {rel for _, rel in walk_workspace(str(root))}
        assert rels == {"src/main.py", "keep.bin"}

    def test_copy_workspace(self, tmp_path):
        root = self.make_tree(tmp_path)
        dst = tmp_path / "out"
        n = copy_workspace(Workspace(projects={str(root): "app"}), str(dst))
        assert n == 2
        assert (dst / "app" / "src" / "main.py").exists()

    def test_dir_mixin_points_image(self, tmp_path):
        root = self.make_tree(tmp_path)

        class S(DirWorkspaceMixin):
            pass

        role = Role(name="r", image="orig")
        S().build_workspace_and_update_role(
            role, Workspace(projects={str(root): ""}), {"job_dir": str(tmp_path / "jd")}
        )
        assert role.image == str(tmp_path / "jd" / "workspace")

    def test_build_context_generates_dockerfile(self, tmp_path):
        root = self.make_tree(tmp_path)
        buf = build_context("base:1", Workspace(projects={str(root): ""}))
        with tarfile.open(fileobj=buf) as tar:
            names = tar.getnames()
            assert "Dockerfile" in names
            assert "src/main.py" in names
            df = tar.extractfile("Dockerfile").read().decode()
            assert "COPY . ." in df

    def test_build_context_custom_dockerfile(self, tmp_path):
        root = self.make_tree(tmp_path)
        (root / "Dockerfile.tpx").write_text("FROM custom\n")
        buf = build_context("base:1", Workspace(projects={str(root): ""}))
        with tarfile.open(fileobj=buf) as tar:
            df = tar.extractfile("Dockerfile").read().decode()
            assert df == "FROM custom\n"


class TestDockerBuildCache:
    """Skip-if-unchanged: the second build of an identical workspace reuses
    the labeled image with ZERO docker build calls (reference analog:
    torchx/workspace/api.py:97-154)."""

    def make_tree(self, tmp_path):
        (tmp_path / "src").mkdir()
        (tmp_path / "src" / "main.py").write_text("print('v1')")
        return tmp_path

    def make_mixin(self):
        from unittest import mock

        from torchx_tpu.workspace.docker_workspace import (
            DockerWorkspaceMixin,
            LABEL_CONTENT_HASH,
        )

        client = mock.MagicMock()
        built = mock.MagicMock()
        built.id = "sha256:" + "a" * 64
        client.images.build.return_value = (built, iter(()))
        # image store: return cached images only for digests seen by build
        store: dict[str, object] = {}

        def record_build(**kwargs):
            digest = kwargs["labels"][LABEL_CONTENT_HASH]
            store[digest] = built
            return (built, iter(()))

        def list_images(filters):
            label = filters["label"]
            digest = label.split("=", 1)[1]
            return [store[digest]] if digest in store else []

        client.images.build.side_effect = record_build
        client.images.list.side_effect = list_images

        class WS(DockerWorkspaceMixin):
            pass

        return WS(docker_client=client), client

    def test_digest_stable_and_content_sensitive(self, tmp_path):
        from torchx_tpu.workspace.docker_workspace import workspace_digest

        root = self.make_tree(tmp_path)
        ws = Workspace(projects={str(root): ""})
        d1 = workspace_digest("base:1", ws)
        assert d1 == workspace_digest("base:1", ws)  # deterministic
        assert d1 != workspace_digest("base:2", ws)  # base image matters
        (root / "src" / "main.py").write_text("print('v2')")
        assert d1 != workspace_digest("base:1", ws)  # content matters

    def test_second_build_skipped_when_unchanged(self, tmp_path):
        from torchx_tpu.specs.api import Resource, Role

        root = self.make_tree(tmp_path)
        ws = Workspace(projects={str(root): ""})
        mixin, client = self.make_mixin()

        def fresh_role():
            return Role(
                name="r", image="base:1", entrypoint="python",
                resource=Resource(cpu=1, memMB=1024),
            )

        r1 = fresh_role()
        mixin.build_workspace_and_update_role(r1, ws, {})
        assert client.images.build.call_count == 1
        assert r1.image.startswith("sha256:")

        r2 = fresh_role()
        mixin.build_workspace_and_update_role(r2, ws, {})
        assert client.images.build.call_count == 1  # no second build
        assert r2.image == r1.image

        # an edit invalidates the cache and rebuilds
        (root / "src" / "main.py").write_text("print('v2')")
        r3 = fresh_role()
        mixin.build_workspace_and_update_role(r3, ws, {})
        assert client.images.build.call_count == 2

    def test_digest_tolerates_symlinks_and_fifos(self, tmp_path):
        """Dangling symlinks and FIFOs must neither crash nor hang the
        digest (they are archived as entries, never opened)."""
        from torchx_tpu.workspace.docker_workspace import workspace_digest

        root = self.make_tree(tmp_path)
        os.symlink("/nonexistent/target", root / "dangling")
        os.mkfifo(root / "pipe")
        ws = Workspace(projects={str(root): ""})
        d1 = workspace_digest("base:1", ws)
        # the symlink target participates in the digest
        os.remove(root / "dangling")
        os.symlink("/other/target", root / "dangling")
        assert workspace_digest("base:1", ws) != d1

    def test_cache_probe_failure_falls_back_to_build(self, tmp_path):
        from torchx_tpu.specs.api import Resource, Role

        root = self.make_tree(tmp_path)
        ws = Workspace(projects={str(root): ""})
        mixin, client = self.make_mixin()
        client.images.list.side_effect = RuntimeError("daemon unreachable")
        role = Role(
            name="r", image="base:1", entrypoint="python",
            resource=Resource(cpu=1, memMB=1024),
        )
        mixin.build_workspace_and_update_role(role, ws, {})
        assert client.images.build.call_count == 1
