"""Parity suite for the fused Pallas kernels (ops/fused.py).

Every test runs the kernels in the Pallas interpreter (CPU), comparing
against the reference ops in ops/attention.py / ops/norms.py — forward
AND backward, f32/bf16/int8-adjacent legs, and under a sharded 8-device
mesh. The flash comparisons are tight-allclose (tiled online softmax
cannot be bitwise against a monolithic softmax); the fused-norm forward
is checked bitwise (identical op sequence).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchx_tpu.ops import fused
from torchx_tpu.ops.attention import xla_attention
from torchx_tpu.ops.norms import _rms_norm_fwd_math
from torchx_tpu.parallel.mesh import MeshConfig, make_mesh


def _qkv(key, b, s, h, kv_h, d, dtype):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, h, d), dtype=dtype)
    k = jax.random.normal(kk, (b, s, kv_h, d), dtype=dtype)
    v = jax.random.normal(kv, (b, s, kv_h, d), dtype=dtype)
    return q, k, v


class TestFlashForward:
    @pytest.mark.parametrize("dtype,tol", [(jnp.float32, 2e-5), (jnp.bfloat16, 2e-2)])
    def test_matches_xla(self, dtype, tol):
        q, k, v = _qkv(jax.random.PRNGKey(0), 2, 256, 2, 2, 64, dtype)
        out = fused.flash_attention(q, k, v, causal=True, kernels="interpret")
        assert out is not None and out.dtype == dtype
        ref = xla_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            out.astype(jnp.float32), ref.astype(jnp.float32), rtol=tol, atol=tol
        )

    def test_non_causal(self):
        q, k, v = _qkv(jax.random.PRNGKey(1), 1, 128, 2, 2, 64, jnp.float32)
        out = fused.flash_attention(q, k, v, causal=False, kernels="interpret")
        ref = xla_attention(q, k, v, causal=False)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_gqa_kv_repeat(self):
        q, k, v = _qkv(jax.random.PRNGKey(2), 2, 256, 4, 2, 64, jnp.float32)
        out = fused.flash_attention(q, k, v, causal=True, kernels="interpret")
        ref = xla_attention(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_multiple_kv_blocks(self):
        """seq > block: the online-softmax recurrence actually iterates."""
        q, k, v = _qkv(jax.random.PRNGKey(3), 1, 512, 2, 2, 64, jnp.float32)
        out = fused.flash_attention(
            q, k, v, causal=True, kernels="interpret", block_q=128, block_kv=128
        )
        ref = xla_attention(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_gating_returns_none(self):
        # head_dim 16 is not lane-tileable
        q, k, v = _qkv(jax.random.PRNGKey(4), 1, 128, 2, 2, 16, jnp.float32)
        assert fused.flash_attention(q, k, v, kernels="interpret") is None
        # ragged sequence
        q, k, v = _qkv(jax.random.PRNGKey(5), 1, 100, 2, 2, 64, jnp.float32)
        assert fused.flash_attention(q, k, v, kernels="interpret") is None
        # reference never enters the module
        q, k, v = _qkv(jax.random.PRNGKey(6), 1, 128, 2, 2, 64, jnp.float32)
        assert fused.flash_attention(q, k, v, kernels="reference") is None
        # pallas off-TPU resolves to reference
        assert fused.flash_attention(q, k, v, kernels="pallas") is None
        assert fused.resolve_kernels("pallas") == "reference"
        assert fused.resolve_kernels("interpret") == "interpret"
        assert fused.resolve_kernels("reference") == "reference"


class TestFlashBackward:
    @pytest.mark.parametrize(
        "dtype,tol", [(jnp.float32, 5e-4), (jnp.bfloat16, 5e-2)]
    )
    def test_grads_match_xla(self, dtype, tol):
        q, k, v = _qkv(jax.random.PRNGKey(7), 2, 256, 2, 2, 64, dtype)
        dy = jax.random.normal(jax.random.PRNGKey(8), q.shape, dtype)

        def loss(fn, q, k, v):
            return jnp.sum(fn(q, k, v).astype(jnp.float32) * dy.astype(jnp.float32))

        flash = functools.partial(
            fused.flash_attention, causal=True, kernels="interpret",
            block_q=128, block_kv=128,
        )
        ref = functools.partial(xla_attention, causal=True)
        g_flash = jax.grad(functools.partial(loss, flash), argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(functools.partial(loss, ref), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_flash, g_ref):
            np.testing.assert_allclose(
                a.astype(jnp.float32), b.astype(jnp.float32), rtol=tol, atol=tol
            )

    def test_gqa_grads_sum_over_repeats(self):
        """kv-head cotangents fold the query-group contributions back."""
        q, k, v = _qkv(jax.random.PRNGKey(9), 1, 128, 4, 1, 64, jnp.float32)
        dy = jax.random.normal(jax.random.PRNGKey(10), q.shape)

        def loss(fn, q, k, v):
            return jnp.sum(fn(q, k, v) * dy)

        flash = functools.partial(
            fused.flash_attention, causal=True, kernels="interpret"
        )
        ref = functools.partial(xla_attention, causal=True)
        g_flash = jax.grad(functools.partial(loss, flash), argnums=(1, 2))(q, k, v)
        g_ref = jax.grad(functools.partial(loss, ref), argnums=(1, 2))(q, k, v)
        for a, b in zip(g_flash, g_ref):
            assert a.shape == (1, 128, 1, 64)
            np.testing.assert_allclose(a, b, rtol=5e-4, atol=5e-4)


class TestFlashSharded:
    def test_sharded_mesh_matches_unsharded(self):
        """Full-manual shard_map over the 8-device mesh: dp*fsdp on batch,
        tp on heads — same values as the single-device kernel."""
        mesh = make_mesh(MeshConfig(dp=2, fsdp=2, tp=2, sp=1))
        q, k, v = _qkv(jax.random.PRNGKey(11), 4, 128, 4, 2, 64, jnp.float32)
        out = fused.flash_attention(
            q, k, v, causal=True, kernels="interpret", mesh=mesh
        )
        assert out is not None
        ref = xla_attention(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_sharded_grads(self):
        mesh = make_mesh(MeshConfig(dp=2, fsdp=2, tp=2, sp=1))
        q, k, v = _qkv(jax.random.PRNGKey(12), 4, 128, 2, 2, 64, jnp.float32)
        dy = jax.random.normal(jax.random.PRNGKey(13), q.shape)

        def loss(fn, q, k, v):
            return jnp.sum(fn(q, k, v) * dy)

        flash = functools.partial(
            fused.flash_attention, causal=True, kernels="interpret", mesh=mesh
        )
        ref = functools.partial(xla_attention, causal=True)
        g_flash = jax.grad(functools.partial(loss, flash), argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(functools.partial(loss, ref), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_flash, g_ref):
            np.testing.assert_allclose(a, b, rtol=5e-4, atol=5e-4)

    def test_undividable_mesh_returns_none(self):
        mesh = make_mesh(MeshConfig(dp=2, fsdp=2, tp=2, sp=1))
        # 3 heads do not divide tp=2
        q, k, v = _qkv(jax.random.PRNGKey(14), 4, 128, 3, 3, 64, jnp.float32)
        assert (
            fused.flash_attention(q, k, v, kernels="interpret", mesh=mesh)
            is None
        )


class TestRmsNormResidual:
    def test_forward_bitwise(self):
        """The fused forward is the same op sequence as the reference —
        bitwise, not just close."""
        x = jax.random.normal(jax.random.PRNGKey(20), (2, 16, 128))
        r = jax.random.normal(jax.random.PRNGKey(21), (2, 16, 128))
        w = 1.0 + 0.1 * jax.random.normal(jax.random.PRNGKey(22), (128,))
        y, s = fused.rms_norm_residual(x, r, w, kernels="interpret")
        y_ref = _rms_norm_fwd_math(x + r, w, 1e-5)
        assert np.array_equal(np.asarray(s), np.asarray(x + r))
        assert np.array_equal(np.asarray(y), np.asarray(y_ref))

    def test_forward_bitwise_bf16(self):
        x = jax.random.normal(jax.random.PRNGKey(23), (4, 8, 256), jnp.bfloat16)
        r = jax.random.normal(jax.random.PRNGKey(24), (4, 8, 256), jnp.bfloat16)
        w = jnp.ones((256,), jnp.bfloat16)
        y, s = fused.rms_norm_residual(x, r, w, kernels="interpret")
        y_ref = _rms_norm_fwd_math(x + r, w, 1e-5)
        assert np.array_equal(
            np.asarray(y, dtype=np.float32), np.asarray(y_ref, dtype=np.float32)
        )

    def test_reference_mode_identical(self):
        x = jax.random.normal(jax.random.PRNGKey(25), (2, 8, 128))
        r = jax.random.normal(jax.random.PRNGKey(26), (2, 8, 128))
        w = jnp.ones((128,))
        y_f, s_f = fused.rms_norm_residual(x, r, w, kernels="interpret")
        y_r, s_r = fused.rms_norm_residual(x, r, w, kernels="reference")
        assert np.array_equal(np.asarray(y_f), np.asarray(y_r))
        assert np.array_equal(np.asarray(s_f), np.asarray(s_r))

    def test_grads_match_reference(self):
        x = jax.random.normal(jax.random.PRNGKey(27), (2, 16, 128))
        r = jax.random.normal(jax.random.PRNGKey(28), (2, 16, 128))
        w = 1.0 + 0.1 * jax.random.normal(jax.random.PRNGKey(29), (128,))
        dy = jax.random.normal(jax.random.PRNGKey(30), x.shape)

        def loss(kernels, x, r, w):
            y, s = fused.rms_norm_residual(x, r, w, kernels=kernels)
            # use both outputs so the s-cotangent path is exercised
            return jnp.sum(y * dy) + jnp.sum(s)

        g_f = jax.grad(functools.partial(loss, "interpret"), argnums=(0, 1, 2))(x, r, w)
        g_r = jax.grad(functools.partial(loss, "reference"), argnums=(0, 1, 2))(x, r, w)
        for a, b in zip(g_f, g_r):
            np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6)

    def test_sharded_mesh(self):
        mesh = make_mesh(MeshConfig(dp=2, fsdp=2, tp=2, sp=1))
        x = jax.random.normal(jax.random.PRNGKey(31), (8, 16, 128))
        r = jax.random.normal(jax.random.PRNGKey(32), (8, 16, 128))
        w = 1.0 + 0.1 * jax.random.normal(jax.random.PRNGKey(33), (128,))
        y, s = fused.rms_norm_residual(x, r, w, kernels="interpret", mesh=mesh)
        y_ref = _rms_norm_fwd_math(x + r, w, 1e-5)
        np.testing.assert_allclose(y, y_ref, rtol=1e-6, atol=1e-6)
        np.testing.assert_allclose(s, x + r, rtol=0, atol=0)

    def test_sharded_grads(self):
        mesh = make_mesh(MeshConfig(dp=2, fsdp=4, tp=1, sp=1))
        x = jax.random.normal(jax.random.PRNGKey(34), (8, 16, 128))
        r = jax.random.normal(jax.random.PRNGKey(35), (8, 16, 128))
        w = 1.0 + 0.1 * jax.random.normal(jax.random.PRNGKey(36), (128,))
        dy = jax.random.normal(jax.random.PRNGKey(37), x.shape)

        def loss(kernels, m, x, r, w):
            y, s = fused.rms_norm_residual(x, r, w, kernels=kernels, mesh=m)
            return jnp.sum(y * dy) + 0.5 * jnp.sum(s)

        g_f = jax.grad(
            functools.partial(loss, "interpret", mesh), argnums=(0, 1, 2)
        )(x, r, w)
        g_r = jax.grad(
            functools.partial(loss, "reference", None), argnums=(0, 1, 2)
        )(x, r, w)
        for a, b in zip(g_f, g_r):
            np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6)

    def test_untileable_falls_back(self):
        # d=64 is not lane-aligned: reference math, same result shape
        x = jax.random.normal(jax.random.PRNGKey(38), (2, 8, 64))
        r = jax.random.normal(jax.random.PRNGKey(39), (2, 8, 64))
        w = jnp.ones((64,))
        y, s = fused.rms_norm_residual(x, r, w, kernels="interpret")
        y_ref = _rms_norm_fwd_math(x + r, w, 1e-5)
        assert np.array_equal(np.asarray(y), np.asarray(y_ref))


class TestInt8Leg:
    def test_flash_with_int8_model_dtypes(self):
        """int8 training keeps activations bf16 at the attention boundary
        (quantization lives in the matmuls); the kernel must stay exact
        on the bf16 leg it actually sees under --int8."""
        q, k, v = _qkv(jax.random.PRNGKey(40), 2, 128, 2, 2, 64, jnp.bfloat16)
        out = fused.flash_attention(q, k, v, causal=True, kernels="interpret")
        ref = xla_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            out.astype(jnp.float32), ref.astype(jnp.float32), rtol=2e-2, atol=2e-2
        )


class TestModelRouting:
    """cfg.kernels routes the llama layer through the fused kernels."""

    def _cfg(self, kernels):
        from torchx_tpu.models import llama

        # dim=128 (lane-aligned norm), head_dim=64 (flash-tileable)
        return llama.llama_tiny(
            dim=128, n_heads=2, n_kv_heads=1, ffn_dim=256, kernels=kernels
        )

    def test_interpret_matches_reference_loss_and_grads(self):
        from torchx_tpu.models import llama

        tokens = jax.random.randint(jax.random.PRNGKey(50), (2, 129), 0, 512)
        batch = {"tokens": tokens}
        cfg_ref = self._cfg("reference")
        cfg_fused = self._cfg("interpret")
        params = llama.init_params(cfg_ref, jax.random.PRNGKey(51))
        l_ref, g_ref = jax.value_and_grad(llama.loss_fn)(params, batch, cfg_ref)
        l_fused, g_fused = jax.value_and_grad(llama.loss_fn)(
            params, batch, cfg_fused
        )
        np.testing.assert_allclose(l_fused, l_ref, rtol=1e-5, atol=1e-5)
        for a, b in zip(jax.tree.leaves(g_fused), jax.tree.leaves(g_ref)):
            np.testing.assert_allclose(a, b, rtol=5e-4, atol=5e-5)

    def test_pallas_request_off_tpu_matches_reference_bitwise(self):
        # "pallas" on a CPU backend must take the reference path exactly
        from torchx_tpu.models import llama

        tokens = jax.random.randint(jax.random.PRNGKey(52), (1, 129), 0, 512)
        batch = {"tokens": tokens}
        params = llama.init_params(self._cfg("reference"), jax.random.PRNGKey(53))
        l_ref = llama.loss_fn(params, batch, self._cfg("reference"))
        l_pal = llama.loss_fn(params, batch, self._cfg("pallas"))
        assert np.asarray(l_pal).tobytes() == np.asarray(l_ref).tobytes()

    def test_invalid_kernels_rejected(self):
        with pytest.raises(ValueError):
            self._cfg("mosaic")
